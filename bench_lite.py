"""Lite-client chain certification bench (BASELINE.json config 5).

The reference's light client certifies headers one at a time — one
`ValidatorSet.VerifyCommit` (V scalar Ed25519 verifies) per header
(lite/static_certifier.go:57; lite/performance_test.go:10-105 measures
exactly this loop). Here a whole run of consecutive headers goes through
`lite.certify_chain`, which pools EVERY commit signature across the
chain into batched device dispatches.

Workload: N synthetic headers, each signed by V validators — N·V
signatures certified end-to-end (structural checks + quorum math on
host, signatures on device). Reported as headers/sec with the
scalar-OpenSSL baseline measured over the same per-header verify loop.

Standalone: `python bench_lite.py [n_headers] [n_vals]` prints one JSON
line. bench.py folds `run()` into its `extra` field for the driver.
"""

from __future__ import annotations

import json
import os
import sys
import time

from bench_util import enable_tpu_compilation_cache

enable_tpu_compilation_cache()  # must precede any jax import


from bench_util import fast_signer


def _signers(keys):
    return {k.pubkey.address: fast_signer(k.seed) for k in keys}


def build_chain(n_headers: int, n_vals: int, chain_id: str = "bench-lite"):
    """[FullCommit] for heights 1..n_headers, one constant valset."""
    from tendermint_tpu.lite.types import FullCommit, SignedHeader
    from tendermint_tpu.types import PrivKey
    from tendermint_tpu.types.block import (BlockID, Commit, Header,
                                            PartSetHeader)
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote, VoteType

    keys = [PrivKey.generate((i + 1).to_bytes(32, "little"))
            for i in range(n_vals)]
    valset = ValidatorSet([Validator(k.pubkey.ed25519, 10) for k in keys])
    sign = _signers(keys)
    by_addr = {v.address: i for i, v in enumerate(valset.validators)}

    fcs = []
    for height in range(1, n_headers + 1):
        header = Header(chain_id=chain_id, height=height, time_ns=height,
                        validators_hash=valset.hash(),
                        app_hash=height.to_bytes(32, "big"))
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x22" * 32))
        precommits = [None] * n_vals
        for k in keys:
            idx = by_addr[k.pubkey.address]
            v = Vote(k.pubkey.address, idx, height, 0, height,
                     VoteType.PRECOMMIT, bid)
            v.signature = sign[k.pubkey.address](v.sign_bytes(chain_id))
            precommits[idx] = v
        fcs.append(FullCommit(
            SignedHeader(header, Commit(bid, precommits), bid), valset))
    return fcs, valset


def scalar_baseline_rate(fcs, chain_id: str, budget_s: float = 3.0):
    """Headers/sec for the reference execution model: one scalar Ed25519
    verify per precommit per header (lite/performance_test.go's loop),
    on the FASTEST scalar backend available (OpenSSL beats Go's
    x/crypto, so this is a conservative baseline)."""
    from bench_util import scalar_verify_one
    _v = scalar_verify_one()

    def verify(pub, sig, msg):
        assert _v(pub, msg, sig)

    n_done = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        fc = fcs[n_done % len(fcs)]
        pubs = {v.address: v.pubkey for v in fc.validators.validators}
        for pc in fc.signed_header.commit.precommits:
            if pc is not None:
                verify(pubs[pc.validator_address], pc.signature,
                       pc.sign_bytes(chain_id))
        n_done += 1
    return n_done / (time.perf_counter() - t0)


def run(n_headers: int = 2000, n_vals: int = 64,
        with_baseline: bool = True) -> dict:
    from tendermint_tpu.lite.certifier import certify_chain

    chain_id = "bench-lite"
    t0 = time.perf_counter()
    fcs, valset = build_chain(n_headers, n_vals)
    build_s = time.perf_counter() - t0

    # compile every kernel shape the measured certify will dispatch
    # (full chunks + padded tail) BEFORE the timed region
    from tendermint_tpu.models.verifier import default_verifier
    default_verifier().warmup(n_headers * n_vals)

    # best-of-3: shared-tunnel load varies minute to minute (same
    # policy as the headline and fast-sync arms)
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        certify_chain(chain_id, fcs, trusted=valset)
        dt = min(dt, time.perf_counter() - t0)
    rate = n_headers / dt

    out = {
        "headers_per_sec": round(rate, 1),
        "headers": n_headers, "vals_per_header": n_vals,
        "sig_verifies_per_sec": round(rate * n_vals, 1),
        "certify_s": round(dt, 3), "build_s": round(build_s, 1),
    }
    if with_baseline:
        base = scalar_baseline_rate(fcs, chain_id)
        out["scalar_headers_per_sec"] = round(base, 1)
        out["vs_baseline"] = round(rate / base, 2)
    return out


def run_streamed(n_headers: int = 1_000_000, n_vals: int = 64,
                 wave: int = 16384, deadline: float = None) -> dict:
    """Config 5 at FULL scale: 1M headers x 64 validators, streamed —
    build a wave (untimed: TPU batch signing via ops/ed25519.sign_batch,
    ~5-6us/signature end-to-end), certify it (timed), alternate. Memory
    stays bounded at one wave; sustained headers/s across all timed
    waves is the headline, per VERDICT r3 item 4.

    `deadline` (time.monotonic() timestamp): stop cleanly after the
    current wave once passed — the artifact then reports the achieved
    header count with scaled_to_budget=True instead of the driver
    SIGTERM-ing mid-arm and losing the whole result (VERDICT r4
    weak #1)."""
    from tendermint_tpu.lite.certifier import certify_chain
    from tendermint_tpu.lite.types import FullCommit, SignedHeader
    from tendermint_tpu.models.verifier import default_verifier
    from tendermint_tpu.ops import ed25519 as ed
    from tendermint_tpu.types import PrivKey
    from tendermint_tpu.types.block import (BlockID, Commit, Header,
                                            PartSetHeader)
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote, VoteType

    chain_id = "bench-lite"
    # Signature disk cache: the wave build is UNTIMED setup (the metric
    # is certify headers/s), but 64M device signatures cost ~6 min of
    # wall clock the driver budget can't spare — so waves persist their
    # signatures once per box, keyed by every parameter that shapes
    # them. certify_chain re-verifies every cached signature, so a
    # corrupt cache fails the arm loudly rather than passing silently.
    # TM_BENCH_NO_SIGCACHE=1 disables (fields report cache use either
    # way).
    cache_dir = None
    if not os.environ.get("TM_BENCH_NO_SIGCACHE"):
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), ".bench_sigcache")
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError:
            cache_dir = None
    cache_hits = 0
    seeds = [(i + 1).to_bytes(32, "little") for i in range(n_vals)]
    keys = [PrivKey.generate(s) for s in seeds]
    valset = ValidatorSet([Validator(k.pubkey.ed25519, 10) for k in keys])
    order = {k.pubkey.address: i for i, k in enumerate(keys)}
    idx_of = [order[v.address] for v in valset.validators]
    vals = valset.validators
    vhash = valset.hash()

    default_verifier().warmup(wave * n_vals)
    # the final PARTIAL wave ends with a short certify window whose
    # batch shape nothing above compiles — warm it too, or its JIT
    # compile lands inside the last timed wave
    from tendermint_tpu.lite.certifier import default_window
    tail_h = (n_headers % wave) % default_window(n_vals)
    if tail_h:
        default_verifier().warmup(tail_h * n_vals)
    t_all = time.perf_counter()
    build_s = 0.0
    warm_s = 0.0
    timed_s = 0.0
    best_wave = 0.0
    wave_rates = []

    def build_wave(b_done: int):
        """Build one wave starting at height b_done+1; returns
        (fcs, seconds, cache_hit). Pure host work on the cached-sig
        path, so it runs on a helper thread UNDER the next wave's
        certify — certify's device fetches release the GIL, and the
        build fills those gaps (1-core pipelining; with ~40%% host
        occupancy during certify the build is nearly free)."""
        tb = time.perf_counter()
        n_w = min(wave, n_headers - b_done)
        heights = range(b_done + 1, b_done + n_w + 1)
        headers, bids = [], []
        for h in heights:
            header = Header(chain_id=chain_id, height=h, time_ns=h,
                            validators_hash=vhash,
                            app_hash=h.to_bytes(32, "big"))
            bid = BlockID(header.hash(), PartSetHeader(1, b"\x22" * 32))
            headers.append(header)
            bids.append(bid)
        wave_idx = b_done // wave
        cpath = None
        blob = None
        if cache_dir is not None:
            cpath = os.path.join(
                cache_dir, f"{chain_id}-v{n_vals}-w{wave}"
                           f"-i{wave_idx}-n{n_w}.sig")
            try:
                if os.path.getsize(cpath) == n_w * n_vals * 64:
                    with open(cpath, "rb") as f:
                        blob = f.read()
            except OSError:
                pass
        resolver = None
        if blob is None:
            # sign-bytes only exist on the signing path — every
            # validator signs the SAME canonical bytes per header
            # (v0.16 sign bytes carry no validator identity; one
            # timestamp); a cache hit skips the n_w encodes entirely
            msgs = [Vote(vals[0].address, 0, h, 0, h,
                         VoteType.PRECOMMIT,
                         bids[h - (b_done + 1)]).sign_bytes(chain_id)
                    for h in heights]
            sig_seeds = [seeds[idx_of[j]]
                         for _ in range(n_w) for j in range(n_vals)]
            sig_msgs = [m for m in msgs for _ in range(n_vals)]
            # dispatch signing, then build the vote/commit objects
            # WHILE the device computes R = r*B — signatures attach at
            # resolve
            resolver = ed.sign_batch_async(sig_seeds, sig_msgs)
        fcs = []
        all_votes = []
        vote_new = Vote.__new__
        addrs = [v.address for v in vals]
        for i, h in enumerate(heights):
            bid = bids[i]
            # slim construction: 1M dataclass __init__ calls per wave
            # cost more than the certify host plane; a prototype dict
            # + __dict__.update builds identical instances
            proto = {"height": h, "round": 0, "timestamp_ns": h,
                     "type": VoteType.PRECOMMIT, "block_id": bid,
                     "signature": b"", "validator_index": 0,
                     "validator_address": b""}
            precommits = [None] * n_vals
            for j in range(n_vals):
                v = vote_new(Vote)
                d = v.__dict__
                d.update(proto)
                d["validator_address"] = addrs[j]
                d["validator_index"] = j
                precommits[j] = v
                all_votes.append(v)
            fcs.append(FullCommit(
                SignedHeader(headers[i], Commit(bid, precommits), bid),
                valset))
        if blob is not None:
            for i, v in enumerate(all_votes):
                v.signature = blob[64 * i:64 * (i + 1)]
        else:
            sigs = resolver()
            for v, sig in zip(all_votes, sigs):
                v.signature = sig
            if cpath is not None:
                try:  # atomic publish; a failed write just skips cache
                    tmp = cpath + f".{os.getpid()}.tmp"
                    with open(tmp, "wb") as f:
                        f.write(b"".join(sigs))
                    os.replace(tmp, cpath)
                except OSError:
                    pass
        return fcs, time.perf_counter() - tb, blob is not None

    def wave_cached(b_done: int) -> bool:
        if cache_dir is None:
            return False
        n_w = min(wave, n_headers - b_done)
        cpath = os.path.join(
            cache_dir, f"{chain_id}-v{n_vals}-w{wave}"
                       f"-i{b_done // wave}-n{n_w}.sig")
        try:
            return os.path.getsize(cpath) == n_w * n_vals * 64
        except OSError:
            return False

    from concurrent.futures import ThreadPoolExecutor
    pool = ThreadPoolExecutor(max_workers=1,
                              thread_name_prefix="lite-build")
    done = 0
    fut = pool.submit(build_wave, 0)
    try:
        while done < n_headers:
            fcs, b_s, hit = fut.result()
            fut = None
            build_s += b_s
            cache_hits += int(hit)
            n_w = len(fcs)
            if deadline is not None and done > 0 and \
                    time.monotonic() >= deadline:
                break  # past deadline: don't certify the prebuilt wave
            if done + n_w < n_headers and wave_cached(done + n_w):
                # pipeline ONLY cache-hit builds (pure host work that
                # fills certify's GIL-free device waits); a cache-miss
                # build dispatches TPU signing, which must not compete
                # with the timed certify — it runs sequentially below
                fut = pool.submit(build_wave, done + n_w)
            if done == 0:
                # one untimed mini-certify first: the verifier's
                # warmup() compiles the FULL kernel shapes, but
                # certify's steady state runs the predecompressed
                # variant (engages on the 2nd sighting of this
                # valset's padded pubkey batch) — its ~40s Mosaic
                # compile must not land in wave 1's timed run
                tw = time.perf_counter()
                certify_chain(chain_id, fcs[:1024], trusted=valset)
                warm_s = time.perf_counter() - tw
            tw = time.perf_counter()
            certify_chain(chain_id, fcs, trusted=valset)
            dt = time.perf_counter() - tw
            timed_s += dt
            best_wave = max(best_wave, n_w / dt)
            wave_rates.append(n_w / dt)
            done += n_w
            if fut is None and done < n_headers:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                fut = pool.submit(build_wave, done)  # sequential: wait
                # (miss path; certify of this wave already finished)
    finally:
        pool.shutdown(wait=True)
    wave_rates.sort()
    return {
        "headers_per_sec": round(done / timed_s, 1),
        "best_wave_headers_per_sec": round(best_wave, 1),
        # a 1M-header run spans ~25 min of shared-tunnel load swings;
        # the median wave separates capability from transient load
        "median_wave_headers_per_sec": round(
            wave_rates[len(wave_rates) // 2], 1),
        "headers": done, "target_headers": n_headers,
        "scaled_to_budget": done < n_headers,
        "vals_per_header": n_vals,
        "waves": (done + wave - 1) // wave, "wave_headers": wave,
        "sig_verifies_per_sec": round(done * n_vals / timed_s, 1),
        "sig_cache_waves": cache_hits,
        "certify_s": round(timed_s, 3), "build_s": round(build_s, 1),
        "warm_s": round(warm_s, 1),
        "total_wall_s": round(time.perf_counter() - t_all, 1),
    }


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--streamed":
        args = [int(a) for a in sys.argv[2:]]
        r = run_streamed(*args)
        print(json.dumps({
            "metric": "lite_chain_certify_1m",
            "value": r["headers_per_sec"],
            "unit": "headers/sec", "vs_baseline": 0.0, "extra": r,
        }))
        return 0
    n_headers = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    n_vals = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    r = run(n_headers, n_vals)
    print(json.dumps({
        "metric": "lite_chain_certify",
        "value": r["headers_per_sec"],
        "unit": "headers/sec",
        "vs_baseline": r.get("vs_baseline", 0.0),
        "extra": r,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
