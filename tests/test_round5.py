"""Round-5 feature coverage: read-only WAL opens, the replay
later-ENDHEIGHT guard, batched mempool gossip, one-pass merkle tree
proofs, lazy uniform deliver results, and the bucket warmup contract."""

import os

import numpy as np
import pytest

from tendermint_tpu.storage.wal import WAL, EndHeightMessage


# ---------------------------------------------------------------- WAL

def test_readonly_wal_never_mutates_a_torn_log(tmp_path):
    """A writable open trims the torn tail; a readonly open (the replay
    CLI on a possibly-live dir) must leave the file byte-identical and
    turn save()/flush() into no-ops."""
    path = str(tmp_path / "wal")
    w = WAL(path)
    w.save({"type": "vote", "h": 1})
    w.save_end_height(1)
    w.close()
    # append a torn frame: header promising 100 payload bytes, cut
    # short mid-write (EOF truncation — the only class trim handles)
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03\x04" + (100).to_bytes(4, "big")
                + b"partial")
    before = open(path, "rb").read()

    ro = WAL(path, readonly=True)
    ro.save({"type": "vote", "h": 2})   # no-op
    ro.save_end_height(2)               # no-op
    ro.flush()
    ro.close()
    assert open(path, "rb").read() == before  # byte-identical
    # the readers still tolerate the torn head tail
    msgs = ro.all_messages()
    assert [m.msg.get("type") for m in msgs] == ["endheight", "vote",
                                                 "endheight"]

    # a writable reopen trims it (existing behavior, still intact)
    W2 = WAL(path)
    W2.close()
    assert len(open(path, "rb").read()) < len(before)


def test_replay_rejects_endheight_past_state_height(tmp_path):
    """wal_tail_for must refuse a tail that spans FURTHER committed
    heights (state store behind WAL) instead of double-replaying them
    — the reference's catchupReplay errors the same way."""
    from tendermint_tpu.consensus.replay import wal_tail_for

    path = str(tmp_path / "wal")
    w = WAL(path)
    w.save_end_height(3)
    w.save({"type": "vote", "h": 4})
    w.save_end_height(4)          # state store lost height 4
    w.close()
    with pytest.raises(ValueError, match="ENDHEIGHT 4"):
        wal_tail_for(w, 3)
    # a clean tail (no later markers) still replays
    assert wal_tail_for(w, 4) == []


# ------------------------------------------------------- mempool gossip

class _FakePeer:
    def __init__(self):
        self.id = "fake-peer"
        self.running = True
        self.sent = []

    def send(self, ch, payload):
        self.sent.append(payload)
        return True

    def get(self, key):
        return None


def test_batched_tx_gossip_message_roundtrip():
    """A 'txs' batch message admits every tx; a malformed batch stops
    the peer like any protocol violation."""
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.types import encoding

    conns = AppConns(local_client_creator(KVStoreApp()))
    mp = Mempool(conns.mempool)
    r = MempoolReactor(mp, broadcast=False)
    peer = _FakePeer()
    r.receive(0x30, peer, encoding.cdumps(
        {"type": "txs", "txs": [b"a=1".hex(), b"b=2".hex()]}))
    assert mp.size() == 2
    # single-tx form still works
    r.receive(0x30, peer, encoding.cdumps(
        {"type": "tx", "tx": b"c=3".hex()}))
    assert mp.size() == 3

    stopped = []

    class _Switch:
        def stop_peer_for_error(self, p, e):
            stopped.append((p.id, str(e)))

    r.switch = _Switch()
    r.receive(0x30, peer, encoding.cdumps(
        {"type": "txs", "txs": "deadbeef"}))  # not a list
    assert stopped and "batch" in stopped[0][1]
    assert mp.size() == 3


def test_broadcast_routine_batches_backlog():
    """With a backlog in the clist, one send carries many txs."""
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.mempool.reactor import MempoolReactor
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.types import encoding
    import threading

    conns = AppConns(local_client_creator(KVStoreApp()))
    mp = Mempool(conns.mempool)
    for i in range(40):
        mp.check_tx(b"k%d=v" % i)
    r = MempoolReactor(mp, broadcast=False)
    peer = _FakePeer()
    t = threading.Thread(target=r._broadcast_tx_routine, args=(peer,),
                         daemon=True)
    t.start()
    deadline = 5.0
    import time
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        got = sum(
            len(m.get("txs", [m.get("tx")]))
            for m in (encoding.cloads(p) for p in list(peer.sent)))
        if got >= 40:
            break
        time.sleep(0.05)
    r.stop()
    peer.running = False
    t.join(timeout=2)
    msgs = [encoding.cloads(p) for p in peer.sent]
    total = sum(len(m.get("txs", [m.get("tx")])) for m in msgs)
    assert total == 40
    # the backlog must have coalesced: far fewer messages than txs
    assert len(msgs) <= 4, f"{len(msgs)} messages for 40 txs"


# ------------------------------------------------------------- merkle

def test_tree_proofs_host_matches_per_item_proofs():
    from tendermint_tpu.ops import merkle
    rng = np.random.RandomState(9)
    for n in (1, 2, 5, 33, 400):
        items = [rng.bytes(rng.randint(0, 80)) for _ in range(n)]
        root, proofs = merkle.tree_proofs_host(items)
        assert len(proofs) == n
        for i in range(n):
            r2, aunts = merkle.proof_host(items, i)
            assert r2 == root
            assert aunts == proofs[i]
            assert merkle.verify_proof_host(root, n, i, items[i],
                                            proofs[i])
        # tamper: a wrong item fails against its own proof
        if n > 1:
            assert not merkle.verify_proof_host(root, n, 0, b"evil",
                                                proofs[0])


# ------------------------------------------- lazy uniform results

def test_uniform_results_lazy_keys_roundtrip():
    from tendermint_tpu.abci.types import UniformDeliverResults

    packed = b"".join(len(k).to_bytes(4, "little") + k
                      for k in (b"k1", b"key2", b""))
    r = UniformDeliverResults(None, packed=packed, n=3)
    assert len(r) == 3
    assert r._keys is None           # nothing materialized yet
    o = r.to_compact_obj()           # persists from the blob
    assert r._keys is None
    r2 = UniformDeliverResults.from_compact_obj(o)
    assert r2._keys is None          # load path stays lazy too
    assert r2[1].tags["app.key"] == "key2"
    assert r2.keys == [b"k1", b"key2", b""]


# -------------------------------------------------- verifier warmup

def test_warmup_buckets_covers_every_tail_bucket():
    """Every power-of-two bucket from 512 to BATCH_CHUNK must verify
    without a fresh jit entry afterwards (the compile-set is closed)."""
    from tendermint_tpu.models.verifier import BATCH_CHUNK, BatchVerifier
    b, buckets = 512, []
    while b <= BATCH_CHUNK:
        buckets.append(b)
        b *= 2
    assert buckets[0] == 512 and buckets[-1] == BATCH_CHUNK
    # python backend: warmup must be a no-op (no jax import storm)
    BatchVerifier("python").warmup_buckets()
