"""Crash-recovery tests: WAL catchup replay + ABCI handshake matrix
(models consensus/replay_test.go TestHandshakeReplay* + crashingWAL)."""

import os

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState, MockTicker
from tendermint_tpu.consensus.replay import Handshaker, catchup_replay
from tendermint_tpu.node import Node
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import WAL, BlockStore, MemDB, StateStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def _gen(chain_id="replay-chain"):
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    return gen, key


def _run_node(tmp_path, gen, key, heights, in_memory=False,
              reuse_home=True):
    """Run an in-process single-validator node to `heights` using the real
    Node assembly (handshake + WAL) but a mock ticker for determinism."""
    cfg = make_test_config(str(tmp_path))
    node = Node(cfg, gen,
                priv_validator=PrivValidator(LocalSigner(key)),
                app=KVStoreApp(), in_memory=in_memory)
    # swap in a deterministic ticker before starting
    node.consensus.ticker.stop()
    node.consensus.ticker = MockTicker(node.consensus._on_timeout_fire)
    node.start()
    for _ in range(40 * heights):
        if node.height >= heights:
            break
        node.consensus.ticker.fire_next()
    assert node.height >= heights, f"stuck at {node.height}"
    return node


def test_node_restarts_and_continues(tmp_path):
    gen, key = _gen()
    node = _run_node(tmp_path, gen, key, 3)
    h1 = node.height
    app_hash = node.consensus.state.app_hash
    node.stop()

    # restart from disk: handshake replays the app (fresh KVStoreApp!)
    node2 = _run_node(tmp_path, gen, key, h1 + 2)
    assert node2.height >= h1 + 2
    # state survived: the chain continued, not restarted
    assert node2.consensus.state.last_block_height > h1
    # the fresh app was replayed up to the persisted chain height
    assert node2.app.height >= h1
    node2.stop()


def test_handshake_replays_all_blocks_into_fresh_app(tmp_path):
    gen, key = _gen()
    node = _run_node(tmp_path, gen, key, 3)
    stored_hash = node.consensus.state.app_hash
    state_store, block_store = node.state_store, node.block_store
    node.stop()

    fresh_app = KVStoreApp()
    conns = AppConns(local_client_creator(fresh_app))
    hs = Handshaker(state_store, block_store, gen)
    state = hs.handshake(conns)
    assert hs.n_blocks >= 3
    assert fresh_app.height == block_store.height()
    assert state.app_hash == stored_hash


def test_handshake_rejects_app_ahead_of_store():
    gen, key = _gen()
    app = KVStoreApp()
    app.height = 42  # pretend the app ran ahead
    conns = AppConns(local_client_creator(app))
    hs = Handshaker(StateStore(MemDB()), BlockStore(MemDB()), gen)
    from tendermint_tpu.consensus.replay import HandshakeError
    with pytest.raises(HandshakeError, match="ahead of store"):
        hs.handshake(conns)


def test_wal_catchup_replay_is_idempotent(tmp_path):
    """Messages in the WAL tail re-fed after restart must not double-apply:
    the vote sets dedup, the priv validator refuses double-signs."""
    gen, key = _gen()
    node = _run_node(tmp_path, gen, key, 2)
    node.stop()

    # restart; catchup_replay runs inside start()
    cfg = make_test_config(str(tmp_path))
    node2 = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                 app=KVStoreApp())
    node2.consensus.ticker.stop()
    node2.consensus.ticker = MockTicker(node2.consensus._on_timeout_fire)
    h_before = node2.height
    node2.start()  # replays tail; must not crash or regress
    assert node2.height >= h_before
    # chain continues after replay
    for _ in range(80):
        if node2.height >= h_before + 1:
            break
        node2.consensus.ticker.fire_next()
    assert node2.height >= h_before + 1
    node2.stop()


# --------------------------------------------------------- WAL generator --

def test_wal_generator_produces_replayable_wal(tmp_path):
    """consensus/wal_generator.go:31 parity: a generated WAL covers N
    heights with ENDHEIGHT markers and replays cleanly."""
    from tendermint_tpu.consensus.wal_generator import wal_with_n_blocks
    from tendermint_tpu.storage.wal import WAL

    path = str(tmp_path / "gen.wal")
    gen, state, block_store = wal_with_n_blocks(3, path)
    assert state.last_block_height >= 3
    assert block_store.height() >= 3

    wal = WAL(path)
    msgs = wal.messages_after_end_height(2)
    assert msgs, "no messages after ENDHEIGHT(2)"
    types = {m.msg.get("type") for m in msgs}
    assert "vote" in types and "proposal" in types


# ------------------------------------------------- genesis tail fallback --

def test_wal_tail_for_legacy_genesis_log(tmp_path):
    """A pre-marker-era WAL (height-1 messages, no #ENDHEIGHT at all)
    must still yield its whole log as height 1's tail at state-height 0
    — but a log whose markers prove committed heights over a wiped
    state store must refuse loudly instead of replaying into genesis."""
    from tendermint_tpu.consensus.replay import wal_tail_for
    from tendermint_tpu.storage.wal import WAL, encode_frame, WALMessage

    # legacy log: write raw frames (no creation marker)
    legacy = str(tmp_path / "legacy.wal")
    with open(legacy, "wb") as f:
        f.write(encode_frame(WALMessage(0, {"type": "proposal", "h": 1})))
        f.write(encode_frame(WALMessage(0, {"type": "vote", "h": 1})))
    tail = wal_tail_for(WAL(legacy), 0)
    assert [m.msg["type"] for m in tail] == ["proposal", "vote"]

    # multi-height log over genesis state: must raise, not replay
    multi = str(tmp_path / "multi.wal")
    with open(multi, "wb") as f:
        f.write(encode_frame(WALMessage(0, {"type": "vote", "h": 1})))
        f.write(encode_frame(WALMessage(
            0, {"type": "endheight", "height": 1})))
        f.write(encode_frame(WALMessage(0, {"type": "vote", "h": 2})))
    with pytest.raises(ValueError, match="state store wiped"):
        wal_tail_for(WAL(multi), 0)


# ------------------------------------------------ crashing-WAL sweep --

class _WALCrash(BaseException):
    """Simulated process death at a programmed WAL write (BaseException
    so nothing between submit() and the test accidentally swallows it)."""


def test_crashing_wal_sweep(tmp_path):
    """consensus/replay_test.go crashingWAL parity: kill the node at
    the k-th WAL write, for a sweep of k across the first two heights'
    message sequence, and require the restart to recover from whatever
    prefix reached disk and keep committing. Exercises the marker/
    catchup/double-sign-protection interplay at EVERY boundary, not
    just the curated fail-point indices."""
    crashed_any = False
    for k in (*range(1, 13), 14, 17, 20, 24, 28):
        home = tmp_path / f"k{k}"
        gen, key = _gen(f"crashwal-{k}")
        cfg = make_test_config(str(home))
        node = Node(cfg, gen,
                    priv_validator=PrivValidator(LocalSigner(key)),
                    app=KVStoreApp())
        # arm the crash on the node's own WAL (same file, same state)
        wal = node.wal
        orig_save = wal.save
        writes = [0]

        def crashing_save(msg, time_ns=0, _orig=orig_save, _k=k):
            if writes[0] >= _k:
                raise _WALCrash(f"write {writes[0]}")
            writes[0] += 1
            _orig(msg, time_ns)
        wal.save = crashing_save

        node.consensus.ticker.stop()
        node.consensus.ticker = MockTicker(node.consensus._on_timeout_fire)
        crashed = False
        try:
            node.start()
            for _ in range(80):
                if node.height >= 2:
                    break
                node.consensus.ticker.fire_next()
        except _WALCrash:
            crashed = True
        h_before = node.height
        # the "process" is dead: writes are lost from here on, and the
        # teardown below is the test's hygiene, not the node's doing
        wal.save = lambda msg, time_ns=0: None
        try:
            node.stop()
        except Exception:
            pass
        if not crashed:
            assert h_before >= 2
            continue  # k beyond this run's write count: nothing to test
        crashed_any = True

        # restart from disk; must make progress past the crash height
        try:
            node2 = _run_node(home, gen, key, max(h_before + 1, 2))
        except AssertionError as e:
            raise AssertionError(
                f"k={k}: recovery failed after crash at "
                f"h={h_before}: {e}") from e
        node2.stop()
    assert crashed_any, "sweep never crashed: widen the k range"
