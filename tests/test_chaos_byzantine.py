"""Byzantine harness tests: evidence end-to-end through the chaos
monitor (injected double-sign -> pool admission -> committed in a later
block), and the non-equivocation behaviors (withheld / invalid
proposals, amnesia) recovering via round advance."""

import pytest


def test_equivocation_evidence_committed_end_to_end():
    """ISSUE 4 satellite: an equivocating validator's double-signs must
    surface as DuplicateVoteEvidence in honest pools AND be committed
    in a later block — asserted via the chaos monitor, which tracks
    every injected double-sign until it appears in committed block
    evidence."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"byzantine": [{"node": 1, "behavior": "equivocate",
                           "start": 2, "stop": 40}]}
    r = run_chaos(spec=spec, seed=9, target_height=6, max_steps=500)
    assert r["violations"] == []
    ev = r["evidence"]
    assert ev["injected_double_signs"] >= 1
    assert ev["committed"] == ev["injected_double_signs"]
    assert r["faults_injected"].get("equivocation", 0) >= 1
    # the net kept committing THROUGH the attack window, not only after
    assert r["max_height"] >= 6


@pytest.mark.slow
def test_withheld_proposal_round_advances():
    """A proposer that swallows its own proposals must not stall the
    chain: honest nodes prevote nil on the propose timeout and the
    next round's proposer carries the height."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"byzantine": [{"node": 0, "behavior": "withhold_proposal",
                           "start": 1, "stop": 60}]}
    r = run_chaos(spec=spec, seed=4, target_height=5, max_steps=700)
    assert r["violations"] == []
    assert r["max_height"] >= 5
    assert r["faults_injected"].get("withheld_proposal", 0) >= 1


@pytest.mark.slow
def test_invalid_proposal_rejected_and_recovers():
    """A corrupted proposal signature must be rejected by every honest
    node (verify_one at the proposal boundary) and cost at most the
    round — never a commit of the bad proposal."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"byzantine": [{"node": 0, "behavior": "invalid_proposal",
                           "start": 1, "stop": 60}]}
    r = run_chaos(spec=spec, seed=6, target_height=5, max_steps=700)
    assert r["violations"] == []
    assert r["max_height"] >= 5
    assert r["faults_injected"].get("invalid_proposal", 0) >= 1


@pytest.mark.slow
def test_amnesia_single_node_cannot_break_agreement():
    """One amnesiac (forgets its locks every step) holds <1/3 power:
    agreement must hold and the chain must keep committing."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"byzantine": [{"node": 2, "behavior": "amnesia",
                           "start": 1, "stop": 80}],
            "delay": 0.1, "delay_steps": [1, 2]}
    r = run_chaos(spec=spec, seed=13, target_height=6, max_steps=700)
    assert r["violations"] == []
    assert r["max_height"] >= 6


def test_agent_forges_conflicting_vote_with_valid_signature():
    """Unit: the equivocation twin signs a verifiable conflicting vote
    for the same (H, R, type) and records the double-sign key."""
    from tendermint_tpu.chaos.byzantine import (ByzantineAgent,
                                                double_sign_key)
    from tendermint_tpu.chaos.schedule import FaultSchedule
    from tendermint_tpu.types import PrivKey
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType

    key = PrivKey.generate(b"\x07" * 32)
    sched = FaultSchedule()
    expected = []
    mon = type("M", (), {"expect_double_sign":
                         staticmethod(expected.append)})()
    agent = ByzantineAgent(0, key, "byz-chain", sched, mon)

    vote = Vote(key.pubkey.address, 0, 5, 1, 1234, VoteType.PRECOMMIT,
                BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32)))
    vote.signature = key.sign(vote.sign_bytes("byz-chain"))
    out = agent.transform(3, "equivocate", {"type": "vote",
                                            "vote": vote.to_obj()})
    assert len(out) == 2
    evil = Vote.from_obj(out[1]["vote"])
    assert (evil.height, evil.round, evil.type) == (5, 1,
                                                    VoteType.PRECOMMIT)
    assert evil.block_id != vote.block_id
    assert key.pubkey.verify(evil.sign_bytes("byz-chain"),
                             evil.signature)
    assert expected == [double_sign_key(vote)]
    assert sched.counts.get("equivocation") == 1

    # nil votes pass through untouched — nothing to conflict with
    nil = Vote(key.pubkey.address, 0, 5, 1, 1234, VoteType.PREVOTE,
               BlockID())
    nil.signature = key.sign(nil.sign_bytes("byz-chain"))
    assert agent.transform(3, "equivocate",
                           {"type": "vote", "vote": nil.to_obj()}) \
        == [{"type": "vote", "vote": nil.to_obj()}]
