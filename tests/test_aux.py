"""Aux subsystems: trust metric, fail-point crash/recovery matrix,
byzantine double-signing evidence flow, WAL fuzzing
(SURVEY.md §5 capability parity)."""

import os
import random
import subprocess
import sys
import time

import pytest

from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore
from tendermint_tpu.storage import MemDB


# ----------------------------------------------------------------- trust

def test_trust_metric_scores():
    m = TrustMetric(interval_s=1000)
    assert m.trust_score() == 100  # no evidence: full trust
    m.good_events(10)
    assert m.trust_score() == 100
    m.bad_events(30)
    assert m.trust_score() < 75
    only_bad = TrustMetric(interval_s=1000)
    only_bad.bad_events(5)
    assert only_bad.trust_score() < only_bad_floor()


def only_bad_floor():
    # integral (empty history) = 1.0 weighted 0.6; proportional 0 -> ~48
    return 70


def test_trust_metric_history_fades():
    m = TrustMetric(interval_s=0.02)
    m.bad_events(10)
    time.sleep(0.05)
    m.good_events(1)  # rolls the bad interval into history
    score_after_bad = m.trust_score()
    for _ in range(10):
        time.sleep(0.025)
        m.good_events(5)
    assert m.trust_score() > score_after_bad  # good behaviour recovers


def test_trust_store_persists():
    db = MemDB()
    store = TrustMetricStore(db, interval_s=1000)
    store.get_metric("peerA").bad_events(7)
    store.get_metric("peerA").good_events(1)
    store.save()
    store2 = TrustMetricStore(db, interval_s=1000)
    assert store2.get_metric("peerA").trust_score() < 100
    assert store2.get_metric("unknown").trust_score() == 100


# ------------------------------------------------------------ fail points

FAIL_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
home = sys.argv[1]
from tendermint_tpu.cli import main as cli_main
if not os.path.exists(os.path.join(home, "config", "genesis.json")):
    cli_main(["--home", home, "init", "--chain-id", "failnet"])
# test-speed consensus timeouts: the matrix boots 14 single-node nets,
# and default timeouts (propose 3000ms, commit 1000ms) would spend
# ~5s/run idling between its blocks
import json
cfgp = os.path.join(home, "config", "config.json")
cfg = json.load(open(cfgp)) if os.path.exists(cfgp) else {{}}
cfg.setdefault("consensus", {{}}).update({{
    "timeout_propose": 300, "timeout_propose_delta": 100,
    "timeout_prevote": 100, "timeout_prevote_delta": 50,
    "timeout_precommit": 100, "timeout_precommit_delta": 50,
    "timeout_commit": 50}})
json.dump(cfg, open(cfgp, "w"))
cli_main(["--home", home, "node", "--max-height", "2",
          "--max-seconds", "60"])
h = 0
from tendermint_tpu.node import default_node
print("OK", flush=True)
"""


def test_fail_point_matrix_crash_and_recover(tmp_path):
    """Kill the node at each commit-critical fail point, then restart
    WITHOUT the fail index and require it to recover and keep committing
    (test/persist/test_failure_indices.sh)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = FAIL_SCRIPT.format(repo=repo)
    for index in (1, 2, 3, 4, 5, 6, 7):
        home = str(tmp_path / f"failhome{index}")
        env = dict(os.environ, FAIL_TEST_INDEX=str(index),
                   JAX_PLATFORMS="cpu")
        p = subprocess.run([sys.executable, "-c", script, home],
                           env=env, capture_output=True, timeout=120,
                           text=True)
        assert p.returncode == 99, (
            f"index {index}: expected fail-point exit, got "
            f"{p.returncode}: {p.stderr[-500:]}")
        # recovery run: no fail index
        env.pop("FAIL_TEST_INDEX")
        p = subprocess.run([sys.executable, "-c", script, home],
                           env=env, capture_output=True, timeout=120,
                           text=True)
        assert p.returncode == 0, (
            f"recovery after index {index} failed: {p.stderr[-800:]}")


# -------------------------------------------------------------- byzantine

def test_byzantine_double_signer_produces_evidence():
    """A validator that double-signs prevotes gets DuplicateVoteEvidence
    into the honest nodes' evidence pools, and the net keeps committing
    (consensus/byzantine_test.go's capability)."""
    from tests.test_consensus import make_net, run_until_height
    from tendermint_tpu.types.vote import Vote

    nodes, keys = make_net(4, chain_id="byz-test")

    # wrap node0's broadcast: every vote it signs is re-signed for a
    # second, conflicting block and sent too (a true equivocator)
    byz = nodes[0]
    orig_hooks = list(byz.broadcast_hooks)

    evidence_seen = []
    for n in nodes[1:]:
        pool = n.evidence_pool

        class RecordingPool:
            def __init__(self, inner):
                self.inner = inner

            def add_evidence(self, ev):
                evidence_seen.append(ev)

            def pending_evidence(self):
                return []

            def update(self, block, state=None):
                pass
        n.evidence_pool = RecordingPool(pool)

    def double_sign(msg):
        if msg.get("type") != "vote":
            return
        v = Vote.from_obj(msg["vote"])
        if v.block_id.is_zero():
            return
        evil = Vote(v.validator_address, v.validator_index, v.height,
                    v.round, v.timestamp_ns + 1, v.type,
                    type(v.block_id)(b"\xee" * 32, v.block_id.parts))
        # sign with the raw key, bypassing double-sign protection
        evil.signature = keys[0].sign(
            evil.sign_bytes("byz-test"))
        for n in nodes[1:]:
            n.submit({"type": "vote", "vote": evil.to_obj()},
                     peer_id="byzantine")
    byz.broadcast_hooks.append(double_sign)

    for n in nodes:
        n.start()
    # An honest MAJORITY must keep committing. One honest node may
    # legitimately stall a height: if it processes the equivocator's
    # conflicting precommit before the real one, it holds only 2-of-4
    # for the block at that round, and the healing path (peers
    # re-gossiping old-round precommits to a lagging peer) belongs to
    # the consensus REACTOR, which this minimal broadcast-relay harness
    # does not run — reactor catch-up is pinned by
    # test_late_joiner_catches_up_via_gossip and the e2e fast-sync
    # tests instead.
    from tests.test_consensus import fire_all
    honest = nodes[1:]
    for _ in range(200):
        if sum(n.state.last_block_height >= 2 for n in honest) >= 2:
            break
        fire_all(nodes)
    assert sum(n.state.last_block_height >= 2 for n in honest) >= 2, (
        f"honest majority stalled: "
        f"{[n.state.last_block_height for n in honest]}")
    assert evidence_seen, "honest nodes never detected the equivocation"
    ev = evidence_seen[0]
    assert ev.vote_a.block_id != ev.vote_b.block_id
    # evidence is genuinely verifiable
    ev.verify("byz-test", keys[0].pubkey.ed25519)


# ---------------------------------------------------------------- WAL fuzz

def test_wal_decoder_fuzz():
    """Random corruptions must yield clean truncation or
    WALCorruptionError — never a crash or phantom message
    (consensus/wal_fuzz.go's property)."""
    from tendermint_tpu.storage.wal import (
        WALCorruptionError, WALMessage, decode_frames, encode_frame)

    msgs = [{"type": "vote", "i": i, "payload": "x" * (i % 50)}
            for i in range(20)]
    good = b"".join(encode_frame(WALMessage(1000 + i, m))
                    for i, m in enumerate(msgs))
    decoded = decode_frames(good)
    assert [m.msg["i"] for m in decoded] == list(range(20))

    rng = random.Random(42)
    for trial in range(200):
        data = bytearray(good)
        mode = rng.randrange(3)
        if mode == 0:      # flip a byte
            data[rng.randrange(len(data))] ^= rng.randrange(1, 256)
        elif mode == 1:    # truncate
            del data[rng.randrange(len(data)):]
        else:              # splice garbage
            pos = rng.randrange(len(data))
            data[pos:pos] = os.urandom(rng.randrange(1, 20))
        try:
            out = list(decode_frames(bytes(data)))
        except WALCorruptionError:
            continue
        # tolerated output MUST be an exact prefix of the original
        # stream — any divergent message is a phantom the decoder let
        # through (CRC framing makes collisions vanishingly unlikely)
        assert len(out) <= len(msgs)
        for got, want in zip(out, msgs):
            assert got.msg == want, (trial, got.msg, want)
