"""Wire-level chaos plane (ISSUE 13): WireSchedule determinism, the
TCP fault proxy against real switches, graceful degradation of the
codec + loop plane under corruption at every codec state, and the
RPC-polling SocketInvariantMonitor's verdict logic."""

import socket
import struct
import threading
import time

import pytest

from tendermint_tpu.chaos.wire import (
    SocketInvariantMonitor,
    WireProxy,
    WireSchedule,
)
from tendermint_tpu.p2p import NetAddress
from tendermint_tpu.p2p.test_util import make_switch


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


SPEC = {
    "drop": 0.01, "delay": 0.2, "delay_steps": [1, 4],
    "corrupt": 0.005,
    "partitions": [{"start": 10, "stop": 30, "groups": [[0], [1, 2, 3]]}],
    "stalls": [{"start": 40, "stop": 50, "links": [[0, 1]]}],
    "resets": [{"at": 60, "links": [[1, 2]]}],
    "reset_every_steps": 100,
    "geo": {"profile": "wan2"},
    "step_ms": 20,
}


# ----------------------------------------------------------- determinism


def test_same_spec_seed_gives_byte_identical_plan_and_streams():
    a = WireSchedule(SPEC, seed=42, n_nodes=4)
    b = WireSchedule(SPEC, seed=42, n_nodes=4)
    assert a.plan == b.plan
    assert a.plan_digest() == b.plan_digest()
    for i in range(4):
        for j in range(4):
            if i == j:
                continue
            assert a.link_stream(i, j, 0).digest(300) == \
                b.link_stream(i, j, 0).digest(300)


def test_seed_link_and_conn_change_the_streams():
    a = WireSchedule(SPEC, seed=42, n_nodes=4)
    other_seed = WireSchedule(SPEC, seed=43, n_nodes=4)
    assert a.plan_digest() != other_seed.plan_digest()
    base = a.link_stream(0, 1, 0).digest(300)
    assert a.link_stream(1, 0, 0).digest(300) != base   # direction
    assert a.link_stream(0, 2, 0).digest(300) != base   # link
    assert a.link_stream(0, 1, 1).digest(300) != base   # conn index
    assert other_seed.link_stream(0, 1, 0).digest(300) != base


def test_decision_stream_is_frame_indexed_and_aligned():
    """Every frame draws the same number of RNG values regardless of
    outcome, so decision k is a pure function of (seed, link, conn, k)
    — the alignment the byte-identical-log contract rests on."""
    a = WireSchedule(SPEC, seed=7, n_nodes=4).link_stream(0, 1, 0)
    b = WireSchedule(SPEC, seed=7, n_nodes=4).link_stream(0, 1, 0)
    decs_a = [a.decide() for _ in range(200)]
    decs_b = [b.decide() for _ in range(200)]
    assert decs_a == decs_b
    assert [d["frame"] for d in decs_a] == list(range(200))


def test_spec_validation_is_loud():
    with pytest.raises(ValueError, match="unknown wire spec key"):
        WireSchedule({"dorp": 0.1})
    with pytest.raises(ValueError, match="unknown geo profile"):
        WireSchedule({"geo": {"profile": "wan9"}})


def test_geo_latency_rides_every_frame():
    sched = WireSchedule({"geo": {"profile": "wan2"}, "step_ms": 100},
                         seed=1, n_nodes=2)
    # wan2 cross-region latency is 4 steps; nodes 0/1 round-robin into
    # regions 0/1, so every 0->1 frame carries >= 0.4s
    st = sched.link_stream(0, 1, 0)
    for _ in range(50):
        assert st.decide()["delay_s"] >= 0.4
    # no geo => no added latency
    flat = WireSchedule({}, seed=1, n_nodes=2).link_stream(0, 1, 0)
    assert all(flat.decide()["delay_s"] == 0.0 for _ in range(50))


def test_blocked_windows_follow_the_plan():
    sched = WireSchedule(SPEC, seed=3, n_nodes=4)
    assert sched.blocked(15, 0, 1) == "partition"
    assert sched.blocked(15, 1, 2) is None      # same group
    assert sched.blocked(35, 0, 1) is None      # healed
    assert sched.blocked(45, 0, 1) == "stall"
    assert sched.blocked(45, 1, 0) is None      # stall is directed
    assert (60, (1, 2)) in sched.resets()


# ------------------------------------------- corruption: every codec state


def _secret_pair():
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.conn import SecretConnection
    from tendermint_tpu.types.keys import PrivKey
    s1, s2 = socket.socketpair()
    out = {}
    t = threading.Thread(target=lambda: out.setdefault(
        "a", SecretConnection.make(s1, NodeKey(PrivKey.generate(
            b"\x01" * 32)))))
    t.start()
    out["b"] = SecretConnection.make(
        s2, NodeKey(PrivKey.generate(b"\x02" * 32)))
    t.join(10)
    return out["a"], out["b"], s1, s2


def _protocol_errors():
    from tendermint_tpu.native import AeadTagError
    from tendermint_tpu.p2p.conn import purecrypto
    kinds = [ValueError, AeadTagError, purecrypto.InvalidTag]
    try:
        from cryptography.exceptions import InvalidTag
        kinds.append(InvalidTag)
    except ImportError:
        pass
    return tuple(kinds)


def test_feed_wire_corruption_sweep_every_byte_class_raises_cleanly():
    """A corrupted or unparseable frame must raise a classifiable
    protocol error from feed_wire — at EVERY codec state: length
    prefix (oversize immediately; an underflowing prefix once the
    bytes that follow complete the bogus frame, as on a live stream),
    frame header, payload and tag bytes, and a flip landing in the
    second frame of a burst. Never a hang, never a non-exception
    crash."""
    kinds = _protocol_errors()
    a, b, s1, s2 = _secret_pair()
    wire = a.seal_frames([b"frame-one-payload", b"frame-two"])
    # byte classes: 0-3 length prefix, 4-6 sealed header region, mid
    # payload, last byte (tag), and a flip inside the SECOND frame
    (l1,) = struct.unpack(">I", wire[:4])
    for pos in (0, 1, 3, 4, 6, 4 + l1 // 2, 4 + l1 - 1, 4 + l1 + 2):
        fresh_a, fresh_b, fs1, fs2 = _secret_pair()
        clean = fresh_a.seal_frames([b"frame-one-payload",
                                     b"frame-two"])
        corrupted = bytearray(clean)
        corrupted[pos] ^= 0xFF
        with pytest.raises(kinds):
            frames = fresh_b.feed_wire(bytes(corrupted))
            # a prefix that decoded SMALLER than the real frame parses
            # nothing yet; the stream bytes that keep arriving complete
            # the bogus frame and the tag check kills it
            assert frames == []
            fresh_b.feed_wire(b"\xff" * 4096)
        for s in (fs1, fs2):
            s.close()
    # partial feed then corruption: state machine mid-frame
    fresh_a, fresh_b, fs1, fs2 = _secret_pair()
    clean = fresh_a.seal_frames([b"x" * 600])
    assert fresh_b.feed_wire(clean[:5]) == []   # partial: buffered
    corrupted = bytearray(clean[5:])
    corrupted[-1] ^= 0x01
    with pytest.raises(kinds):
        fresh_b.feed_wire(bytes(corrupted))
    for s in (fs1, fs2, s1, s2):
        s.close()


def test_loop_conn_survives_corrupt_frame_with_disconnect_not_crash():
    """Graceful degradation on the loop plane: garbage on a live conn
    fires on_error (disconnect) and the LOOP stays alive — other conns
    and timers keep running."""
    from tendermint_tpu.p2p.conn.loop import LoopMConnection, ReactorLoop
    from tendermint_tpu.p2p.conn import ChannelDescriptor
    from tendermint_tpu.p2p.conn.mconn import PlainFramedConn

    loop = ReactorLoop(name="test-wire-loop")
    loop.start()
    try:
        s1, s2 = socket.socketpair()
        errors = []
        conn = LoopMConnection(
            loop, PlainFramedConn(s1), [ChannelDescriptor(0x10)],
            on_receive=lambda ch, m: None,
            on_error=lambda e: errors.append(e))
        conn.start()
        # an impossible frame: length prefix far beyond the 1042B cap
        s2.sendall(struct.pack(">I", 1 << 30) + b"\xff" * 64)
        assert wait_for(lambda: errors)
        assert isinstance(errors[0], ValueError)
        assert wait_for(lambda: not conn.running)
        # the loop itself is intact: timers still fire
        fired = threading.Event()
        loop.call_later(0.01, fired.set)
        assert fired.wait(2.0)
        s2.close()
    finally:
        loop.stop()


# ------------------------------------------------------------- proxy e2e


def _proxied_switch_pair(spec, seed=1, ban_score=0):
    """Two encrypted switches connected THROUGH a WireProxy (node 0
    dials node 1), persistent so the redial path is live."""
    sw0 = make_switch(network="wire-net", seed=b"\x21" * 32,
                      encrypt=True)
    sw1 = make_switch(network="wire-net", seed=b"\x22" * 32,
                      encrypt=True)
    sw0._ban_score = ban_score  # keep trust enforcement out of the way
    sw1._ban_score = ban_score
    a1 = sw1.listen("127.0.0.1", 0)
    sched = WireSchedule(spec, seed=seed, n_nodes=2)
    proxy = WireProxy(sched, {(0, 1): ("127.0.0.1", a1.port)})
    ports = proxy.listen()
    proxy.start()
    sw0.start()
    sw1.start()
    sw0.dial_peer(NetAddress("127.0.0.1", ports[(0, 1)], sw1.node_info.id),
                  persistent=True)
    return sw0, sw1, proxy, sched


def test_proxy_reset_disconnects_and_persistent_peer_redials():
    spec = {"resets": [{"at": 0, "links": [[0, 1]]}], "step_ms": 20}
    sw0, sw1, proxy, sched = _proxied_switch_pair(spec)
    try:
        # BOTH ends registered: sw1's inbound add_peer runs async
        assert wait_for(lambda: sw0.peers.size() == 1 and
                        sw1.peers.size() == 1)
        first = sw0.peers.list()[0]
        proxy.arm()
        # the reset kills the live conn...
        assert wait_for(lambda: sw0.peers.get(first.id) is not first or
                        not first.running, timeout=15.0)
        # the victim can observe the RST a GIL slice before the proxy
        # thread books the fault — the count must be waited for too
        assert wait_for(
            lambda: sched.applied_counts().get("reset", 0) >= 1)
        # ...and the persistent dialer re-establishes THROUGH the proxy
        assert wait_for(
            lambda: sw0.peers.size() == 1 and
            sw0.peers.list()[0].running and
            sw0.peers.list()[0] is not first, timeout=20.0)
    finally:
        sw0.stop()
        sw1.stop()
        proxy.stop()


def test_proxy_corruption_causes_disconnect_not_crash():
    """corrupt=1.0: the first faulted frame poisons the AEAD stream;
    the victim must classify + disconnect, and BOTH switches stay
    functional (the wedge/crash regression the tentpole demands)."""
    spec = {"corrupt": 1.0, "step_ms": 20}
    sw0, sw1, proxy, sched = _proxied_switch_pair(spec)
    try:
        assert wait_for(lambda: sw0.peers.size() == 1 and
                        sw1.peers.size() == 1)
        peer0 = sw0.peers.list()[0]
        proxy.arm()
        # force traffic through the armed proxy
        peer0.try_send(0x01, b"\x01")  # ping channel id unused; raw msg
        # the corrupted frame must be BOOKED (waited: the victim's
        # disconnect can outrun the proxy's bookkeeping) and the conn
        # must die on it
        assert wait_for(
            lambda: sched.applied_counts().get("corrupt", 0) >= 1,
            timeout=15.0)
        assert wait_for(lambda: sw0.peers.size() == 0 or
                        sw1.peers.size() == 0, timeout=15.0)
        # both switches alive: they can still accept fresh work
        assert sw0.listen_address is None  # never listened — still sane
        assert sw1.listen_address is not None
    finally:
        sw0.stop()
        sw1.stop()
        proxy.stop()


# ---------------------------------------------------------------- monitor


class _FakeClient:
    """Scripted RPC client: status + blockchain from canned chains."""

    def __init__(self, chain):
        # chain: height -> (block_hash_hex, app_hash_hex)
        self.chain = chain

    def call(self, method, **kw):
        if method == "status":
            return {"latest_block_height": max(self.chain, default=0)}
        if method == "blockchain":
            lo, hi = kw["min_height"], kw["max_height"]
            return {"block_metas": [
                {"header": {"height": h, "app_hash": self.chain[h][1]},
                 "block_id": {"hash": self.chain[h][0]}}
                for h in range(hi, lo - 1, -1) if h in self.chain]}
        raise AssertionError(method)


def _monitor_for(chains):
    mon = SocketInvariantMonitor.__new__(SocketInvariantMonitor)
    mon.clients = [_FakeClient(c) for c in chains]
    mon.poll_s = 0.01
    mon.violations = []
    mon.checks = {}
    mon.heights = {}
    mon.per_height = {}
    mon.progress = []
    mon._audited = {}
    mon._stop = threading.Event()
    mon._thread = None
    return mon


def test_monitor_accepts_identical_chains():
    chain = {1: ("aa", "11"), 2: ("bb", "22")}
    mon = _monitor_for([dict(chain), dict(chain)])
    for i, c in enumerate(mon.clients):
        mon._poll_node(i, c)
    assert mon.violations == []
    assert mon.checks["agreement"] == 2
    assert mon.checks["apphash"] == 2


def test_monitor_flags_agreement_and_apphash_divergence():
    mon = _monitor_for([{1: ("aa", "11")}, {1: ("aa", "99")},
                        {1: ("cc", "11")}])
    for i, c in enumerate(mon.clients):
        mon._poll_node(i, c)
    kinds = sorted(v["invariant"] for v in mon.violations)
    assert "apphash" in kinds and "agreement" in kinds


def test_monitor_flags_height_regression():
    mon = _monitor_for([{3: ("aa", "11")}])
    mon._poll_node(0, mon.clients[0])
    mon.clients[0].chain = {2: ("bb", "22")}
    mon._audited[0] = 3  # already audited past it
    mon._poll_node(0, mon.clients[0])
    assert any(v["invariant"] == "validity" for v in mon.violations)


def test_monitor_recovery_and_liveness_verdicts():
    mon = _monitor_for([{1: ("aa", "11")}])
    t = time.monotonic()
    mon.progress = [(t + 1.0, 5), (t + 8.0, 6)]
    report = mon.finalize(
        [("partition", t), ("reset", t + 5.0), ("stall", t + 100.0)],
        liveness_bound_s=4.0)
    eps = {e["kind"]: e["recovery_s"] for e in
           report["recovery"]["episodes"]}
    assert eps["partition"] == 1.0
    assert eps["reset"] == 3.0
    assert eps["stall"] is None     # never recovered => liveness trip
    assert [v["invariant"] for v in report["violations"]] == ["liveness"]
    assert report["recovery"]["latency_seconds"]["n"] == 2
