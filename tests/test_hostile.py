"""Hostile-peer hardening (ISSUE 13): total handshake deadline,
weighted trust scoring + ban enforcement with decaying unban, clean-
traffic scoring (the trust asymmetry fix), fd-headroom admission
shedding, and deterministic redial jitter."""

import time

from tendermint_tpu.chaos import hostile
from tendermint_tpu.p2p.switch import (
    CLEAN_MSGS_PER_GOOD,
    PROTOCOL_BAD_WEIGHT,
    _protocol_error,
    _redial_jitter,
)
from tendermint_tpu.p2p.test_util import connect_switches, make_switch
from tendermint_tpu.p2p.trust import TrustMetric, TrustMetricStore
from tendermint_tpu.storage import MemDB

from tests.test_p2p import EchoReactor


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def make_victim(ban_base_s=1.0, handshake_timeout_s=1.5):
    sw = make_switch(network="hostile-net", seed=b"\x31" * 32,
                     encrypt=True)
    sw.trust_store = TrustMetricStore(MemDB())
    sw._ban_base_s = ban_base_s
    sw.config.handshake_timeout_s = handshake_timeout_s
    return sw


# ------------------------------------------------------ handshake deadline


def test_handshake_stall_killed_by_total_deadline():
    sw = make_victim()
    addr = sw.listen("127.0.0.1", 0)
    try:
        r = hostile.run_hostile("handshake_stall", "127.0.0.1",
                                addr.port, budget_s=6.0)
        assert r["defense_fired"], r
        assert r["closed_by_victim_s"] < 4.0
    finally:
        sw.stop()


def test_slow_loris_handshake_killed_despite_per_read_progress():
    """One byte per 0.3s never trips a per-read timeout — only the
    TOTAL deadline disconnects this peer."""
    sw = make_victim(handshake_timeout_s=1.2)
    addr = sw.listen("127.0.0.1", 0)
    try:
        r = hostile.run_hostile("slow_handshake", "127.0.0.1",
                                addr.port, byte_interval_s=0.3,
                                budget_s=8.0)
        assert r["defense_fired"], r
        assert 2 <= r["bytes_sent"] < 32  # progressing, yet killed
    finally:
        sw.stop()


# ------------------------------------------------------------- ban plane


def test_garbage_peer_banned_then_readmitted_after_decay():
    """The full lifecycle from one hostile identity: authed -> garbage
    -> weighted bad score -> BAN (handshake refused) -> ban expiry ->
    re-admission. The trust plane now enforces, not just records."""
    sw = make_victim(ban_base_s=1.0)
    addr = sw.listen("127.0.0.1", 0)
    try:
        r = hostile.run_hostile(
            "garbage_after_auth", "127.0.0.1", addr.port,
            network="hostile-net", channels=[], rounds=9,
            retry_gap_s=0.25, budget_s=20.0)
        kinds = [o["outcome"] for o in r["rounds"]]
        assert r["saw_ban"], kinds
        assert r["readmitted_after_ban"], kinds
        # the ban plane recorded the offender
        assert sw.trust_store.get_metric(r["peer_id"]).trust_score() < 30
        with sw._lock:
            assert r["peer_id"] in sw.banned
    finally:
        sw.stop()


def test_oversize_frame_killed_and_scored():
    sw = make_victim()
    addr = sw.listen("127.0.0.1", 0)
    try:
        r = hostile.run_hostile("oversize_frame", "127.0.0.1",
                                addr.port, network="hostile-net",
                                channels=[])
        assert r["outcome"] == "authed"
        assert r["defense_fired"], r
    finally:
        sw.stop()


def test_ban_duration_doubles_and_strikes_decay():
    sw = make_victim(ban_base_s=0.2)
    try:
        sw.ban_peer("p1")
        with sw._lock:
            first = dict(sw.banned["p1"])
        assert first["strikes"] == 1
        sw.ban_peer("p1")          # immediate repeat: escalation
        with sw._lock:
            second = dict(sw.banned["p1"])
        assert second["strikes"] == 2
        assert second["until"] - second["last"] > \
            (first["until"] - first["last"]) * 1.5
        time.sleep(1.7)            # > 2 decay steps (0.8s each)
        sw.ban_peer("p1")
        with sw._lock:
            third = dict(sw.banned["p1"])
        assert third["strikes"] == 1  # clean time earned decay back
    finally:
        sw.stop()


def test_is_banned_lazy_expiry_keeps_strike_history():
    sw = make_victim(ban_base_s=0.1)
    try:
        sw.ban_peer("p2")
        assert sw.is_banned("p2")
        assert wait_for(lambda: not sw.is_banned("p2"), timeout=3.0)
        with sw._lock:
            assert sw.banned["p2"]["strikes"] == 1  # history survives
            assert not sw.banned["p2"]["active"]
    finally:
        sw.stop()


def test_ban_disabled_at_zero_score_threshold():
    sw = make_victim()
    sw._ban_score = 0
    try:
        sw.trust_store.get_metric("p3").bad_events(1000)
        sw._maybe_ban("p3")
        with sw._lock:
            assert "p3" not in sw.banned
    finally:
        sw.stop()


# ------------------------------------------------ trust scoring asymmetry


def test_protocol_errors_classified_and_weighted():
    from tendermint_tpu.native import AeadTagError
    from tendermint_tpu.p2p.conn import purecrypto
    assert _protocol_error(ValueError("oversized secret frame"))
    assert _protocol_error(AeadTagError("tag"))
    assert _protocol_error(purecrypto.InvalidTag("tag"))
    assert not _protocol_error(ConnectionError("reset"))
    assert not _protocol_error(OSError(104, "reset"))


def test_long_lived_honest_peer_survives_one_bad_burst():
    """The satellite fix in numbers: with steady-state good scoring, a
    peer that routed ~1000 clean messages keeps its score ABOVE the
    ban threshold through one protocol-weighted bad event. Without it
    (good = the single add_peer credit) the same burst bans it."""
    with_traffic = TrustMetric()
    with_traffic.good_events(1 + 1000 / CLEAN_MSGS_PER_GOOD)
    with_traffic.bad_events(PROTOCOL_BAD_WEIGHT)
    assert with_traffic.trust_score() >= 30

    pre_fix = TrustMetric()
    pre_fix.good_events(1)            # add_peer only — the old plane
    pre_fix.bad_events(PROTOCOL_BAD_WEIGHT)
    assert pre_fix.trust_score() < 30


def test_clean_traffic_scores_good_events_through_route():
    r1 = EchoReactor("echo", 0x10, echo=False)
    r2 = EchoReactor("echo", 0x10, echo=False)
    sw1 = make_switch(seed=b"\x33" * 32)
    sw2 = make_switch(seed=b"\x34" * 32)
    sw2.trust_store = TrustMetricStore(MemDB())
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start()
    sw2.start()
    try:
        p1, _ = connect_switches(sw1, sw2)
        metric = sw2.trust_store.get_metric(sw1.node_info.id)
        base = metric.good
        for i in range(CLEAN_MSGS_PER_GOOD * 2):
            assert p1.send(0x10, b"m%d" % i)
        assert wait_for(
            lambda: len(r2.received) >= CLEAN_MSGS_PER_GOOD * 2)
        assert wait_for(lambda: metric.good >= base + 2)
    finally:
        sw1.stop()
        sw2.stop()


# --------------------------------------------------- admission + redial


def test_fd_headroom_sheds_inbound_accepts():
    sw = make_victim()
    # simulate scarcity: 90 of 100 fds in use, headroom demands 64
    sw._fd_budget = lambda: (100, 90)
    addr = sw.listen("127.0.0.1", 0)
    try:
        import socket as _socket
        c = _socket.create_connection(("127.0.0.1", addr.port),
                                      timeout=3.0)
        c.settimeout(3.0)
        assert c.recv(1) == b""   # shed: closed without a handshake
        c.close()
        assert sw.peers.size() == 0
    finally:
        sw.stop()


def test_fd_headroom_unknowable_passes():
    sw = make_victim()
    sw._fd_budget = lambda: (0, 0)
    assert sw._fd_headroom_ok()
    sw.stop()


def test_redial_jitter_is_deterministic_and_bounded():
    vals = set()
    for attempt in range(12):
        j = _redial_jitter("id@127.0.0.1:1234", attempt)
        assert j == _redial_jitter("id@127.0.0.1:1234", attempt)
        assert 0.5 <= j < 1.0
        vals.add(j)
    assert len(vals) > 6            # attempts actually spread
    assert _redial_jitter("a", 0) != _redial_jitter("b", 0)
