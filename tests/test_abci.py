"""ABCI boundary tests: local/socket clients, server, example apps.

Models the reference's ABCI conformance tests (test/app/counter_test.sh,
dummy_test.sh) in-process: the same app driven over both transports must
behave identically.
"""

import threading

import pytest

from tendermint_tpu.abci import (
    ABCIServer, AppConns, LocalClient, SocketClient, local_client_creator,
    socket_client_creator,
)
from tendermint_tpu.abci.apps import CounterApp, KVStoreApp
from tendermint_tpu.abci.client import ABCIClientError
from tendermint_tpu.abci.types import ValidatorUpdate


@pytest.fixture
def socket_kvstore():
    app = KVStoreApp()
    server = ABCIServer(app, "127.0.0.1:0")
    server.start()
    yield app, f"127.0.0.1:{server.bound_port}"
    server.stop()


def _drive_kvstore(conn):
    assert conn.echo("hello") == "hello"
    info = conn.info()
    assert info.last_block_height == 0

    assert conn.check_tx(b"a=1").ok
    assert not conn.check_tx(b"").ok

    conn.init_chain([ValidatorUpdate(b"\x01" * 32, 10)], "chain")
    conn.begin_block(b"\xaa" * 32, {"height": 1})
    r = conn.deliver_tx(b"name=satoshi")
    assert r.ok and r.tags["app.key"] == "name"
    conn.end_block(1)
    h1 = conn.commit()
    assert len(h1) == 32

    q = conn.query("/store", b"name", 0, False)
    assert q.value == b"satoshi"

    # second block changes the app hash
    conn.begin_block(b"\xbb" * 32, {"height": 2})
    batch = conn.deliver_tx_batch([b"k%d=v%d" % (i, i) for i in range(10)])
    assert all(r.ok for r in batch)
    conn.end_block(2)
    h2 = conn.commit()
    assert h2 != h1
    assert conn.info().last_block_height == 2


def test_kvstore_local():
    _drive_kvstore(LocalClient(KVStoreApp()))


def test_kvstore_socket(socket_kvstore):
    _, addr = socket_kvstore
    conn = SocketClient(addr)
    _drive_kvstore(conn)
    conn.close()


def test_counter_serial_semantics():
    conn = LocalClient(CounterApp(serial=True))
    assert conn.deliver_tx((0).to_bytes(8, "big")).ok
    assert conn.deliver_tx((1).to_bytes(8, "big")).ok
    r = conn.deliver_tx((5).to_bytes(8, "big"))
    assert not r.ok and "expected 2" in r.log
    # check_tx rejects stale values only
    assert not conn.check_tx((0).to_bytes(8, "big")).ok
    assert conn.check_tx((2).to_bytes(8, "big")).ok
    assert conn.query("tx", b"", 0, False).value == b"2"


def test_app_conns_three_connections_share_app():
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    conns.consensus.deliver_tx(b"x=1")
    conns.consensus.commit()
    assert conns.query.query("/store", b"x", 0, False).value == b"1"
    assert conns.mempool.check_tx(b"y=2").ok
    conns.close()


def test_socket_server_error_propagation(socket_kvstore):
    _, addr = socket_kvstore
    conn = SocketClient(addr)
    with pytest.raises(ABCIClientError, match="unknown ABCI method"):
        conn._call("bogus_method")
    # connection still usable afterwards
    assert conn.echo("still-alive") == "still-alive"
    conn.close()


def test_socket_concurrent_connections(socket_kvstore):
    """Three logical conns hammering one app server stay consistent."""
    _, addr = socket_kvstore
    conns = AppConns(socket_client_creator(addr))
    errs = []

    def spam_checks():
        try:
            for _ in range(50):
                assert conns.mempool.check_tx(b"t=1").ok
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=spam_checks)
    t.start()
    for i in range(20):
        conns.consensus.deliver_tx(b"c%d=1" % i)
    conns.consensus.commit()
    t.join()
    assert not errs
    assert conns.query.info().last_block_height == 1
    conns.close()


def test_kvstore_validator_update_guard():
    """persistent_dummy's updateValidator guard: removals of unknown
    validators and set-emptying batches are rejected at DeliverTx so an
    invalid update never reaches EndBlock (where the core would treat it
    as a consensus failure and halt)."""
    from tendermint_tpu.abci.types import ValidatorUpdate

    def val_tx(pk: bytes, power: int) -> bytes:
        return b"val:" + pk.hex().encode() + b"/%d" % power

    a, b, c = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
    app = KVStoreApp()
    app.init_chain([ValidatorUpdate(a, 10), ValidatorUpdate(b, 10)], "t")

    # unknown removal -> rejected, nothing queued
    assert app.deliver_tx(val_tx(c, 0)).code == 2
    assert app.end_block(1).validator_updates == []

    # legit add + power change + removal all pass
    assert app.deliver_tx(val_tx(c, 5)).code == 0
    assert app.deliver_tx(val_tx(a, 30)).code == 0
    assert app.deliver_tx(val_tx(b, 0)).code == 0
    ups = app.end_block(1).validator_updates
    assert [(u.pubkey, u.power) for u in ups] == [(c, 5), (a, 30), (b, 0)]

    # same-block visibility: add X then remove X is coherent
    x = b"\x04" * 32
    assert app.deliver_tx(val_tx(x, 7)).code == 0
    assert app.deliver_tx(val_tx(x, 0)).code == 0
    app.end_block(2)

    # draining the set to empty is refused on the last member
    assert app.deliver_tx(val_tx(a, 0)).code == 0
    assert app.deliver_tx(val_tx(c, 0)).code == 3  # last one standing
    ups = app.end_block(3).validator_updates
    assert [(u.pubkey, u.power) for u in ups] == [(a, 0)]

    # an UNSEEDED app (no InitChain) still blocks unknown removals but
    # cannot judge emptiness -> allows removing the last tx-added one
    app2 = KVStoreApp()
    assert app2.deliver_tx(val_tx(a, 0)).code == 2
    assert app2.deliver_tx(val_tx(a, 9)).code == 0
    assert app2.deliver_tx(val_tx(a, 0)).code == 0
