"""Consensus state machine tests (models consensus/state_test.go +
reactor_test.go behaviors, in-process, deterministic via MockTicker).

The net harness wires N ConsensusStates directly through their broadcast
hooks — the reference's randConsensusNet over in-memory connections
(consensus/common_test.go:343)."""

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState, MockTicker, Step
from tendermint_tpu.consensus.ticker import TimeoutInfo
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.storage import BlockStore, MemDB, StateStore
from tendermint_tpu.types import (
    GenesisDoc, GenesisValidator, PrivKey,
)
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def make_node(gen_doc, key=None, app=None):
    """One in-process validator node around a KVStore app."""
    app = app or KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen_doc)
    # InitChain equivalent at genesis
    from tendermint_tpu.abci.types import ValidatorUpdate
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen_doc.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, block_store,
        priv_validator=PrivValidator(LocalSigner(key)) if key else None,
        ticker_factory=MockTicker)
    return cs


def make_net(n, chain_id="cs-test"):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    nodes = [make_node(gen, k) for k in keys]
    # full-mesh wiring: every broadcast goes to every OTHER node
    for i, src in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part", "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src.broadcast_hooks.append(relay)
    return nodes, keys


def fire_all(nodes):
    """Deliver every pending mock timeout once; returns #fired."""
    n = 0
    for node in nodes:
        if node.ticker.fire_next() is not None:
            n += 1
    return n


def run_until_height(nodes, height, max_ticks=200):
    for _ in range(max_ticks):
        if all(n.state.last_block_height >= height for n in nodes):
            return
        if fire_all(nodes) == 0 and \
                all(n.state.last_block_height >= height for n in nodes):
            return
    raise AssertionError(
        f"net did not reach height {height}; at "
        f"{[n.state.last_block_height for n in nodes]}, steps "
        f"{[(n.rs.height, n.rs.round, int(n.rs.step)) for n in nodes]}")


def test_single_validator_commits_blocks():
    nodes, _ = make_net(1)
    cs = nodes[0]
    committed = []
    cs.decided_hook = committed.append
    cs.start()
    run_until_height(nodes, 3)
    assert cs.state.last_block_height >= 3
    assert [b.header.height for b in committed][:3] == [1, 2, 3]
    # app hash advances into the next header
    assert committed[1].header.app_hash != b""


def test_four_validators_commit_and_agree():
    nodes, _ = make_net(4)
    for n in nodes:
        n.start()
    run_until_height(nodes, 3)
    hashes = {n.state.last_block_id.key() for n in nodes
              if n.state.last_block_height == nodes[0].state.last_block_height}
    assert len(hashes) == 1  # all agree on the chain tip
    assert all(n.state.last_block_height >= 3 for n in nodes)


def test_net_with_txs_delivers_to_all_apps():
    nodes, keys = make_net(4)
    apps = []
    # rebuild with recorded apps + a simple list mempool on the proposer
    gen = GenesisDoc(chain_id="tx-test", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])

    class ListMempool:
        def __init__(self):
            self.txs = []
        def lock(self): pass
        def unlock(self): pass
        def size(self): return len(self.txs)
        def reap(self, mx): return self.txs[:mx]
        def update(self, height, txs):
            self.txs = [t for t in self.txs if t not in txs]
        def flush(self): pass

    nodes = []
    mempools = []
    for k in keys:
        app = KVStoreApp()
        apps.append(app)
        node = make_node(gen, k, app=app)
        mp = ListMempool()
        node.mempool = mp
        mempools.append(mp)
        nodes.append(node)
    for i, src in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part", "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src.broadcast_hooks.append(relay)

    for mp in mempools:
        mp.txs = [b"alpha=1", b"beta=2"]
    for n in nodes:
        n.start()
    run_until_height(nodes, 2)
    for app in apps:
        assert app.store.get(b"alpha") == b"1"
        assert app.store.get(b"beta") == b"2"
    # all apps computed the same state hash
    assert len({app.app_hash for app in apps}) == 1


def test_validator_absent_still_commits():
    """3 of 4 validators (75% > 2/3) should still make progress."""
    nodes, _ = make_net(4)
    live = nodes[:3]
    # node 3 never starts and drops everything (its submit is disabled)
    nodes[3].submit = lambda *a, **k: None
    for n in live:
        n.start()
    run_until_height(live, 2, max_ticks=400)
    assert all(n.state.last_block_height >= 2 for n in live)


def test_round_advances_without_proposer():
    """If the round-0 proposer is down, others must advance to round 1 and
    commit with the next proposer."""
    nodes, _ = make_net(4)
    # find round-0 proposer of height 1 and kill it
    proposer_addr = nodes[0].rs.validators.proposer().address
    dead = [n for n in nodes
            if n.priv_validator.address == proposer_addr][0]
    live = [n for n in nodes if n is not dead]
    dead.submit = lambda *a, **k: None
    for n in live:
        n.start()
    run_until_height(live, 1, max_ticks=600)
    assert all(n.state.last_block_height >= 1 for n in live)
