"""Consensus state machine tests (models consensus/state_test.go +
reactor_test.go behaviors, in-process, deterministic via MockTicker).

The net harness wires N ConsensusStates directly through their broadcast
hooks — the reference's randConsensusNet over in-memory connections
(consensus/common_test.go:343)."""

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState, MockTicker, Step
from tendermint_tpu.consensus.ticker import TimeoutInfo
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.storage import BlockStore, MemDB, StateStore
from tendermint_tpu.types import (
    GenesisDoc, GenesisValidator, PrivKey,
)
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def make_node(gen_doc, key=None, app=None):
    """One in-process validator node around a KVStore app."""
    app = app or KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen_doc)
    # InitChain equivalent at genesis
    from tendermint_tpu.abci.types import ValidatorUpdate
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen_doc.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, block_store,
        priv_validator=PrivValidator(LocalSigner(key)) if key else None,
        ticker_factory=MockTicker)
    return cs


class ListMempool:
    """Minimal reap/update mempool for proposer-side tx injection."""

    def __init__(self):
        self.txs = []

    def lock(self): pass

    def unlock(self): pass

    def size(self): return len(self.txs)

    def reap(self, mx): return self.txs[:mx]

    def update(self, height, txs):
        self.txs = [t for t in self.txs if t not in txs]

    def flush(self): pass


def wire_full_mesh(nodes):
    """Relay proposal/part/vote broadcasts to every other node."""
    for i, src_node in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part",
                                              "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src_node.broadcast_hooks.append(relay)


def make_net(n, chain_id="cs-test"):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    nodes = [make_node(gen, k) for k in keys]
    # full-mesh wiring: every broadcast goes to every OTHER node
    for i, src in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part", "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src.broadcast_hooks.append(relay)
    return nodes, keys


def fire_all(nodes):
    """Deliver every pending mock timeout once; returns #fired."""
    n = 0
    for node in nodes:
        if node.ticker.fire_next() is not None:
            n += 1
    return n


def run_until_height(nodes, height, max_ticks=200):
    for _ in range(max_ticks):
        if all(n.state.last_block_height >= height for n in nodes):
            return
        if fire_all(nodes) == 0 and \
                all(n.state.last_block_height >= height for n in nodes):
            return
    raise AssertionError(
        f"net did not reach height {height}; at "
        f"{[n.state.last_block_height for n in nodes]}, steps "
        f"{[(n.rs.height, n.rs.round, int(n.rs.step)) for n in nodes]}")


def test_single_validator_commits_blocks():
    nodes, _ = make_net(1)
    cs = nodes[0]
    committed = []
    cs.decided_hook = committed.append
    cs.start()
    run_until_height(nodes, 3)
    assert cs.state.last_block_height >= 3
    assert [b.header.height for b in committed][:3] == [1, 2, 3]
    # app hash advances into the next header
    assert committed[1].header.app_hash != b""


def test_four_validators_commit_and_agree():
    nodes, _ = make_net(4)
    for n in nodes:
        n.start()
    run_until_height(nodes, 3)
    hashes = {n.state.last_block_id.key() for n in nodes
              if n.state.last_block_height == nodes[0].state.last_block_height}
    assert len(hashes) == 1  # all agree on the chain tip
    assert all(n.state.last_block_height >= 3 for n in nodes)


def test_net_with_txs_delivers_to_all_apps():
    nodes, keys = make_net(4)
    apps = []
    # rebuild with recorded apps + a simple list mempool on the proposer
    gen = GenesisDoc(chain_id="tx-test", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])

    nodes = []
    mempools = []
    for k in keys:
        app = KVStoreApp()
        apps.append(app)
        node = make_node(gen, k, app=app)
        mp = ListMempool()
        node.mempool = mp
        mempools.append(mp)
        nodes.append(node)
    wire_full_mesh(nodes)

    for mp in mempools:
        mp.txs = [b"alpha=1", b"beta=2"]
    for n in nodes:
        n.start()
    run_until_height(nodes, 2)
    for app in apps:
        assert app.store.get(b"alpha") == b"1"
        assert app.store.get(b"beta") == b"2"
    # all apps computed the same state hash
    assert len({app.app_hash for app in apps}) == 1


def test_validator_absent_still_commits():
    """3 of 4 validators (75% > 2/3) should still make progress."""
    nodes, _ = make_net(4)
    live = nodes[:3]
    # node 3 never starts and drops everything (its submit is disabled)
    nodes[3].submit = lambda *a, **k: None
    for n in live:
        n.start()
    run_until_height(live, 2, max_ticks=400)
    assert all(n.state.last_block_height >= 2 for n in live)


def test_round_advances_without_proposer():
    """If the round-0 proposer is down, others must advance to round 1 and
    commit with the next proposer."""
    nodes, _ = make_net(4)
    # find round-0 proposer of height 1 and kill it
    proposer_addr = nodes[0].rs.validators.proposer().address
    dead = [n for n in nodes
            if n.priv_validator.address == proposer_addr][0]
    live = [n for n in nodes if n is not dead]
    dead.submit = lambda *a, **k: None
    for n in live:
        n.start()
    run_until_height(live, 1, max_ticks=600)
    assert all(n.state.last_block_height >= 1 for n in live)


def test_validator_set_changes_through_end_block():
    """The reference's TestReactorValidatorSetChanges core: a `val:` tx
    committed through consensus changes the validator set via EndBlock —
    a power change lands in state.validators at the NEXT height, a
    power-0 update removes the validator, and the net keeps committing
    with the new set throughout."""
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    gen = GenesisDoc(chain_id="valchange-test", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])

    nodes, mempools = [], []
    for k in keys:
        node = make_node(gen, k)
        mp = ListMempool()
        node.mempool = mp
        node.block_exec.mempool = mp  # so committed txs leave the pool
        mempools.append(mp)
        nodes.append(node)
    wire_full_mesh(nodes)

    # raise validator 0's power 10 -> 30
    target = keys[0].pubkey
    bump = b"val:" + target.ed25519.hex().encode() + b"/30"
    for mp in mempools:
        mp.txs = [bump]
    for n in nodes:
        n.start()
    run_until_height(nodes, 3)

    for n in nodes:
        _, val = n.state.validators.get_by_address(target.address)
        assert val is not None and val.voting_power == 30, \
            (n.state.last_block_height, val)
    assert all(n.state.validators.total_voting_power() == 60 for n in nodes)

    # now remove validator 3 entirely (power 0); remaining power 50/60
    # of the CURRENT set still commits, and the set shrinks to 3
    gone = keys[3].pubkey
    drop = b"val:" + gone.ed25519.hex().encode() + b"/0"
    for mp in mempools:
        mp.txs = [drop]
    h = nodes[0].state.last_block_height
    run_until_height(nodes, h + 2)
    for n in nodes:
        assert len(n.state.validators) == 3
        assert not n.state.validators.has_address(gone.address)
    # ...and the 3-validator set keeps committing (incl. node3, now a
    # non-validator full node)
    h = nodes[0].state.last_block_height
    run_until_height(nodes, h + 1)


def test_invalid_app_validator_update_fails_loudly():
    """An app emitting an invalid update (removing an unknown validator)
    must raise ApplyBlockError — NOT a ValueError that vote handlers
    would swallow while the node stalls silently in COMMIT (the
    reference panics on ApplyBlock errors)."""
    from tendermint_tpu.state.execution import ApplyBlockError

    from tendermint_tpu.abci.types import ValidatorUpdate

    key = PrivKey.generate(b"\x01" * 32)
    gen = GenesisDoc(chain_id="loud-fail", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    app = KVStoreApp()
    cs = make_node(gen, key, app=app)
    nodes = [cs]
    # the app drops an unknown validator at height 1
    ghost = PrivKey.generate(b"\x77" * 32).pubkey
    app._val_updates.append(ValidatorUpdate(ghost.ed25519, 0))
    cs.start()
    with pytest.raises(ApplyBlockError):
        run_until_height(nodes, 1, max_ticks=30)


def test_heartbeat_sent_while_waiting_for_txs():
    """With create_empty_blocks=False a validator entering the wait
    broadcasts a SIGNED proposal heartbeat (consensus/state.go:696
    proposalHeartbeat) instead of proposing, and proposes only when
    txs_available fires."""
    key = PrivKey.generate(b"\x05" * 32)
    gen = GenesisDoc(chain_id="hb-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cs = make_node(gen, key)
    cs.config.create_empty_blocks = False
    mp = ListMempool()
    cs.mempool = mp
    cs.block_exec.mempool = mp
    sent = []
    cs.broadcast_hooks.append(
        lambda m: sent.append(m) if m.get("type") == "heartbeat" else None)
    cs.start()
    # heights 1-2 are proof blocks (the app hash settles after the
    # first commit), so the wait starts at height 3
    run_until_height([cs], 2)
    for _ in range(5):
        cs.ticker.fire_next()
    assert cs.state.last_block_height == 2, "must WAIT with no txs"
    assert sent, "no heartbeat broadcast while waiting for txs"
    from tendermint_tpu.types.proposal import Heartbeat
    hb = Heartbeat.from_obj(sent[-1]["heartbeat"])
    assert hb.height == 3
    assert key.pubkey.verify(hb.sign_bytes("hb-test"), hb.signature)
    # txs arrive -> propose + commit height 3
    mp.txs = [b"wake=up"]
    cs.submit({"type": "txs_available"})
    run_until_height([cs], 3)
    assert cs.state.last_block_height >= 3


def test_bad_proposal_rejected_and_prevotes_nil():
    """TestStateBadProposal (consensus/state_test.go:182): a proposal
    with a forged signature never becomes the round's proposal, and a
    properly-signed proposal for an INVALID block (bad app_hash) makes
    the node prevote nil — never the bad block's hash."""
    from tendermint_tpu.types.block import Commit
    from tendermint_tpu.types.proposal import Proposal

    nodes, keys = make_net(2, chain_id="badprop-test")
    # identify the height-1 proposer; the OTHER node is under test,
    # ISOLATED (no relays) so only the hand-crafted messages arrive
    for n in nodes:
        n.broadcast_hooks.clear()
    prop_addr = nodes[0].rs.validators.proposer().address
    prop_idx = next(i for i, k in enumerate(keys)
                    if k.pubkey.address == prop_addr)
    victim = nodes[1 - prop_idx]
    prop_key = keys[prop_idx]
    victim.start()

    # build an invalid block: proper structure, corrupted app_hash
    bad_block = victim.state.make_block(
        1, [b"tx=1"], Commit(), time_ns=10 ** 9)
    bad_block.header.app_hash = b"\xde\xad" * 16
    parts = bad_block.make_part_set(
        victim.state.consensus_params.block_gossip.block_part_size_bytes)

    # 1) forged signature: rejected, no proposal recorded
    forged = Proposal(1, 0, parts.header(), timestamp_ns=5)
    forged.signature = keys[1 - prop_idx].sign(   # WRONG signer
        forged.sign_bytes("badprop-test"))
    victim.submit({"type": "proposal", "proposal": forged.to_obj()},
                  peer_id="peerX")
    assert victim.rs.proposal is None, "forged proposal accepted"

    # 2) properly-signed proposal for the invalid block: accepted as
    # the round's proposal, but the prevote must be NIL
    prevotes = []
    victim.broadcast_hooks.append(
        lambda m: prevotes.append(m) if m.get("type") == "vote" and
        m["vote"]["type"] == 1 else None)
    good_sig = Proposal(1, 0, parts.header(), timestamp_ns=5)
    good_sig.signature = prop_key.sign(good_sig.sign_bytes("badprop-test"))
    victim.submit({"type": "proposal", "proposal": good_sig.to_obj()},
                  peer_id="peerX")
    assert victim.rs.proposal is not None
    for i in range(parts.total):
        victim.submit({"type": "block_part", "height": 1, "round": 0,
                       "part": parts.get_part(i).to_obj()},
                      peer_id="peerX")
    # drive timeouts until the prevote goes out
    for _ in range(20):
        if prevotes:
            break
        victim.ticker.fire_next()
    assert prevotes, "no prevote broadcast"
    v = prevotes[0]["vote"]
    assert v["block_id"]["hash"] == "", \
        f"prevoted the invalid block: {v['block_id']}"


def test_conflicting_precommit_for_claimed_maj23_block_commits():
    """types/vote_set.go:219-287 + AddVote's (added, err) pair, driven
    through the full state machine: after a peer claims +2/3 for block
    B (vote-set-maj23), an equivocating validator's CONFLICTING
    precommit for B both files DuplicateVoteEvidence and — because it
    was counted — tips the quorum, so the node must enter commit
    immediately rather than sit on a formed +2/3 until a timeout."""
    from tendermint_tpu.types.block import BlockID, Commit
    from tendermint_tpu.types.proposal import Proposal
    from tendermint_tpu.types.vote import Vote, VoteType

    nodes, keys = make_net(4, chain_id="maj23-test")
    for n in nodes:
        n.broadcast_hooks.clear()
    prop_addr = nodes[0].rs.validators.proposer().address
    prop_idx = next(i for i, k in enumerate(keys)
                    if k.pubkey.address == prop_addr)
    victim_idx = next(i for i in range(4) if i != prop_idx)
    victim = nodes[victim_idx]
    sent = []
    victim.broadcast_hooks.append(
        lambda m: sent.append(m) if m.get("type") == "vote" else None)
    victim.start()

    block = victim.state.make_block(1, [b"tx=1"], Commit(), time_ns=10 ** 9)
    parts = block.make_part_set(
        victim.state.consensus_params.block_gossip.block_part_size_bytes)
    prop = Proposal(1, 0, parts.header(), timestamp_ns=5)
    prop.signature = keys[prop_idx].sign(prop.sign_bytes("maj23-test"))
    victim.submit({"type": "proposal", "proposal": prop.to_obj()},
                  peer_id="peerX")
    for i in range(parts.total):
        victim.submit({"type": "block_part", "height": 1, "round": 0,
                       "part": parts.get_part(i).to_obj()}, peer_id="peerX")
    for _ in range(20):
        if any(m["vote"]["type"] == VoteType.PREVOTE for m in sent):
            break
        victim.ticker.fire_next()
    my_prevote = next(m for m in sent
                      if m["vote"]["type"] == VoteType.PREVOTE)
    bid = BlockID.from_obj(my_prevote["vote"]["block_id"])
    assert bid.hash == block.hash(), "victim did not prevote the block"

    def vote_from(key, type_, vbid, ts):
        i, _ = victim.rs.validators.get_by_address(key.pubkey.address)
        v = Vote(key.pubkey.address, i, 1, 0, ts, type_, vbid)
        v.signature = key.sign(v.sign_bytes("maj23-test"))
        return {"type": "vote", "vote": v.to_obj()}

    others = [k for i, k in enumerate(keys) if i != victim_idx]
    honest1, honest2, equivocator = others
    nil_bid = BlockID(b"", bid.parts.__class__(0, b""))

    # polka: two honest prevotes for B -> victim precommits B
    victim.submit(vote_from(honest1, VoteType.PREVOTE, bid, 11), "p1")
    victim.submit(vote_from(honest2, VoteType.PREVOTE, bid, 12), "p2")
    for _ in range(20):
        if any(m["vote"]["type"] == VoteType.PRECOMMIT for m in sent):
            break
        victim.ticker.fire_next()
    assert any(m["vote"]["type"] == VoteType.PRECOMMIT and
               m["vote"]["block_id"]["hash"] == bid.hash.hex()
               for m in sent), "victim did not precommit the block"

    # one honest precommit for B (2 of 4 power), equivocator NIL (first vote)
    victim.submit(vote_from(honest1, VoteType.PRECOMMIT, bid, 21), "p1")
    victim.submit(vote_from(equivocator, VoteType.PRECOMMIT, nil_bid, 22),
                  "p3")
    assert victim.state.last_block_height == 0  # no quorum yet

    # record evidence (make_node wires a MockEvidencePool that drops it)
    filed = []

    class RecordingPool:
        def add_evidence(self, ev):
            filed.append(ev)

        def pending_evidence(self):
            return []

        def update(self, block, state=None):
            pass
    victim.evidence_pool = RecordingPool()

    # a peer claims +2/3 for B; then the equivocator's CONFLICTING
    # precommit for B arrives and must tip the commit
    victim.rs.votes.set_peer_maj23(0, VoteType.PRECOMMIT, "peerZ", bid)
    victim.submit(vote_from(equivocator, VoteType.PRECOMMIT, bid, 23), "p4")
    for _ in range(20):
        if victim.state.last_block_height >= 1:
            break
        victim.ticker.fire_next()
    assert victim.state.last_block_height >= 1, (
        "formed +2/3 was not acted on: conflicting-but-counted vote "
        "did not trigger commit")
    assert filed, "equivocation produced no evidence"
    assert filed[0].vote_a.block_id != filed[0].vote_b.block_id


def test_proposer_rotates_across_heights():
    """consensus/state_test.go:58 TestStateProposerSelection0: with
    equal powers the height-h round-0 proposer is validators[(h-1) % n]
    in address order — the constructor increment gives height 1 to
    position 0 and ApplyBlock's per-block increment advances it."""
    nodes, _ = make_net(4, chain_id="rot-test")
    for n in nodes:
        n.start()
    run_until_height(nodes, 3)
    for n in nodes:
        vs = n.rs.validators
        expect = vs.validators[(n.rs.height - 1) % 4].address
        assert vs.proposer().address == expect, (
            f"height {n.rs.height}: wrong proposer")


def test_proposer_rotates_per_round_on_nil_votes():
    """consensus/state_test.go:92 TestStateProposerSelection2: every
    nil round hands the proposer role to the next validator in address
    order (equal powers) — round r of height 1 belongs to position
    r % n."""
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType

    nodes, keys = make_net(4, chain_id="rot2-test")
    for n in nodes:
        n.broadcast_hooks.clear()
    victim = nodes[0]
    victim.start()
    nil_bid = BlockID(b"", PartSetHeader(0, b""))
    my_addr = victim.priv_validator.address

    for r in range(4):
        assert victim.rs.round == r
        vs = victim.rs.validators
        assert vs.proposer().address == vs.validators[r % 4].address, (
            f"round {r}: wrong proposer")
        for k in keys:
            if k.pubkey.address == my_addr:
                continue
            i, _val = vs.get_by_address(k.pubkey.address)
            for t, ts in ((VoteType.PREVOTE, 100 + r),
                          (VoteType.PRECOMMIT, 200 + r)):
                v = Vote(k.pubkey.address, i, 1, r, ts, t, nil_bid)
                v.signature = k.sign(v.sign_bytes("rot2-test"))
                victim.submit({"type": "vote", "vote": v.to_obj()},
                              peer_id="px")
        for _ in range(30):
            if victim.rs.round > r:
                break
            victim.ticker.fire_next()
        assert victim.rs.round == r + 1, f"stuck in round {r}"
