"""gRPC surface: BroadcastAPI (rpc/grpc/types.proto parity) and the
ABCI-over-gRPC transport (proxy/client.go:65 grpc ClientCreator)."""

import hashlib
import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.grpc_app import (ABCIGrpcServer, GrpcClient,
                                          grpc_client_creator)
from tendermint_tpu.abci.proxy import AppConns
from tendermint_tpu.abci.types import ValidatorUpdate
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.node import Node
from tendermint_tpu.rpc.grpc_service import (BroadcastAPIClient,
                                             BroadcastAPIServer)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


# ---------------------------------------------------------- ABCI over gRPC

def test_abci_grpc_roundtrip():
    app = KVStoreApp()
    server = ABCIGrpcServer(app, "127.0.0.1:0")
    server.start()
    try:
        c = GrpcClient(f"127.0.0.1:{server.port}")
        assert c.echo("hello") == "hello"
        info = c.info()
        assert info.last_block_height == 0

        c.init_chain([ValidatorUpdate(b"\x01" * 32, 10)], "grpc-chain")
        c.begin_block(b"\xaa" * 32, {"height": 1, "time_ns": 1},
                      absent_validators=[], byzantine_validators=[])
        res = c.deliver_tx(b"k=v")
        assert res.code == 0 and res.tags
        batch = c.deliver_tx_batch([b"a=1", b"b=2"])
        assert [r.code for r in batch] == [0, 0]
        eb = c.end_block(1)
        assert eb.validator_updates == []
        app_hash = c.commit()
        assert app_hash

        q = c.query("/key", b"k")
        assert q.value == b"v"
        chk = c.check_tx(b"x=y")
        assert chk.ok
        bad = c.check_tx(b"")   # kvstore rejects the empty tx
        assert not bad.ok and bad.code == 1
        c.close()
    finally:
        server.stop()


def test_abci_grpc_client_creator_with_appconns():
    """The node-side usage: three AppConns over three channels."""
    app = KVStoreApp()
    server = ABCIGrpcServer(app, "127.0.0.1:0")
    server.start()
    try:
        conns = AppConns(grpc_client_creator(f"127.0.0.1:{server.port}"))
        assert conns.query.info().last_block_height == 0
        assert conns.mempool.check_tx(b"k=v").ok
        conns.consensus.begin_block(b"\x01" * 32, {"height": 1})
        conns.consensus.deliver_tx(b"k=v")
        conns.consensus.end_block(1)
        assert conns.consensus.commit()
        conns.close()
    finally:
        server.stop()


# ----------------------------------------------------------- BroadcastAPI

@pytest.fixture(scope="module")
def grpc_node():
    key = PrivKey.generate(b"\x0b" * 32)
    gen = GenesisDoc(chain_id="grpc-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cfg = make_test_config("")
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
    node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True, with_rpc=True)
    node.start()
    deadline = time.monotonic() + 30
    while node.height < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert node.height >= 2
    yield node
    node.stop()


def test_broadcast_api_ping_and_tx(grpc_node):
    c = BroadcastAPIClient(f"127.0.0.1:{grpc_node.grpc_server.port}")
    c.ping()  # must not raise
    tx = b"gk=gv"
    res = c.broadcast_tx(tx)
    assert res.check_tx.code == 0
    assert res.deliver_tx.code == 0
    assert res.height >= 1
    assert res.hash == hashlib.sha256(tx).digest()
    c.close()


def test_grpc_bind_conflict_raises():
    """grpcio enables SO_REUSEPORT by default, under which two nodes
    binding the same grpc_laddr BOTH succeed and the kernel round-robins
    RPCs between them. We disable it (rpc/grpc_util.py): the second bind
    must fail loudly, like the reference's net.Listen
    (rpc/grpc/client_server.go:15)."""
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.grpc_app import ABCIGrpcServer

    first = ABCIGrpcServer(KVStoreApp(), "127.0.0.1:0")
    try:
        # grpcio raises RuntimeError at add_insecure_port on conflict;
        # OSError is our own guard for the silent-0 case
        with pytest.raises((OSError, RuntimeError)):
            ABCIGrpcServer(KVStoreApp(), f"127.0.0.1:{first.port}")
    finally:
        first.stop()
