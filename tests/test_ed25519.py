"""Differential tests: TPU batch-verify kernel vs pure-Python RFC 8032 ref."""

import hashlib
import random

import numpy as np
import jax.numpy as jnp

from tendermint_tpu.ops import curve, ed25519, field as fe
from tendermint_tpu.utils import ed25519_ref as ref

rng = random.Random(99)


def seeds(n):
    return [rng.randbytes(32) for _ in range(n)]


def test_curve_ops_match_reference():
    # batched add/double/encode vs python ints
    pts_int = [ref.point_mul(rng.randrange(1, ref.L), ref.BASE) for _ in range(4)]
    pts_aff = []
    for X, Y, Z, _ in pts_int:
        zi = pow(Z, ref.P - 2, ref.P)
        pts_aff.append((X * zi % ref.P, Y * zi % ref.P))
    batch = tuple(
        jnp.stack([comp for comp in comps])
        for comps in zip(*[curve.from_ints(x, y) for x, y in pts_aff])
    )
    # double
    d = curve.double(batch)
    enc = np.asarray(curve.encode(d))
    for i, p in enumerate(pts_int):
        expect = ref.point_compress(ref.point_add(p, p))
        assert enc[i].tobytes() == expect
    # add p[i] + p[(i+1)%4]
    rolled = tuple(jnp.roll(c, -1, axis=0) for c in batch)
    s = curve.add(batch, rolled)
    enc2 = np.asarray(curve.encode(s))
    for i, p in enumerate(pts_int):
        expect = ref.point_compress(ref.point_add(p, pts_int[(i + 1) % 4]))
        assert enc2[i].tobytes() == expect
    # adding identity is a no-op (completeness)
    ident = curve.identity((4,))
    s2 = curve.add(batch, ident)
    enc3 = np.asarray(curve.encode(s2))
    for i, (x, y) in enumerate(pts_aff):
        expect = ref.point_compress((x, y, 1, x * y % ref.P))
        assert enc3[i].tobytes() == expect


def test_decompress_valid_and_invalid():
    sds = seeds(3)
    pks = [ref.public_key(s) for s in sds]
    bad = bytearray(pks[0])
    bad[0] ^= 1  # almost surely not on curve
    candidates = pks + [bytes(bad)]
    arr = jnp.asarray(np.stack([np.frombuffer(c, np.uint8) for c in candidates]))
    pt, ok = curve.decompress(arr)
    ok = np.asarray(ok)
    expected = [ref.point_decompress(c) is not None for c in candidates]
    assert list(ok) == expected
    enc = np.asarray(curve.encode(pt))
    for i, c in enumerate(candidates):
        if expected[i]:
            assert enc[i].tobytes() == c


def test_verify_batch_good_and_bad():
    from bench_util import fast_signer, scalar_verify_one
    sds = seeds(6)
    pks = [ref.public_key(s) for s in sds]
    msgs = [rng.randbytes(rng.randrange(0, 100)) for _ in sds]
    sigs = [fast_signer(s)(m) for s, m in zip(sds, msgs)]

    # sanity: the independent scalar backend verifies its own sigs
    _sv = scalar_verify_one()
    assert all(_sv(p, m, s) for p, m, s in zip(pks, msgs, sigs))

    # corruptions
    bad_sig = bytearray(sigs[1]); bad_sig[0] ^= 1
    bad_msg = msgs[2] + b"x"
    wrong_key = pks[3]
    high_s = bytearray(sigs[4])
    s_int = int.from_bytes(bytes(high_s[32:]), "little") + ref.L
    high_s[32:] = s_int.to_bytes(32, "little")

    pubkeys = [pks[0], pks[1], pks[2], wrong_key, pks[4], pks[5]]
    messages = [msgs[0], msgs[1], bad_msg, msgs[4], msgs[4], msgs[5]]
    signatures = [sigs[0], bytes(bad_sig), sigs[2], sigs[4], bytes(high_s), sigs[5]]
    expected = [True, False, False, False, False, True]

    got = ed25519.verify_batch(pubkeys, messages, signatures)
    assert list(got) == expected
    # agreement with the python reference on every case
    pyref = [ref.verify(p, m, s) for p, m, s in zip(pubkeys, messages, signatures)]
    assert list(got) == pyref


def test_verify_batch_padding_and_empty():
    assert ed25519.verify_batch([], [], []).shape == (0,)
    sds = seeds(3)
    pks = [ref.public_key(s) for s in sds]
    msgs = [b"a", b"bb", b"ccc"]
    sigs = [ref.sign(s, m) for s, m in zip(sds, msgs)]
    got = ed25519.verify_batch(pks, msgs, sigs)
    assert got.all() and got.shape == (3,)


def test_predecompressed_cache_path_matches_full():
    """The stable-valset fast path (pre-decompressed pubkey cache,
    ops/ed25519._verify_cached_predecomp): the first occurrence of a
    pubkey batch takes the full kernel, repeats take the *_pre kernel
    with cached (-A) bytes — verdicts must be identical across calls,
    including invalid pubkeys and tampered signatures."""
    import random

    from tendermint_tpu.ops import ed25519
    from tendermint_tpu.utils import ed25519_ref as ref

    rng = random.Random(99)
    n = 8
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = rng.randbytes(32)
        m = b"pre-cache %d" % i
        pubs.append(ref.public_key(seed))
        msgs.append(m)
        from bench_util import fast_signer
        sigs.append(fast_signer(seed)(m))
    # sprinkle failures: tampered sig, wrong msg, non-point pubkey
    sigs[5] = sigs[5][:32] + bytes([sigs[5][32] ^ 1]) + sigs[5][33:]
    msgs[1] = b"wrong"
    pubs[7] = b"\xff" * 32

    expect = [i not in (5, 1, 7) for i in range(n)]
    ed25519._predecomp.clear()
    ed25519._predecomp_seen.clear()
    # run the cache at batch 8 (shapes earlier tests already compiled —
    # the production 64 gate exists to spare one-shot SMALL batches the
    # decompress dispatch, not because the cache logic differs by size)
    orig_min = ed25519._PREDECOMP_MIN_BATCH
    ed25519._PREDECOMP_MIN_BATCH = 8
    try:
        r1 = ed25519.verify_batch(pubs, msgs, sigs)  # full kernel, records
        assert r1.tolist() == expect
        r2 = ed25519.verify_batch(pubs, msgs, sigs)  # builds + uses cache
        assert r2.tolist() == expect
        # per-pubkey rows: one per distinct key (incl. the invalid one,
        # cached with ok=False so forged keys never re-pay the sqrt)
        assert len(ed25519._predecomp) == n, "cache did not engage"
        r3 = ed25519.verify_batch(pubs, msgs, sigs)  # cache hit
        assert r3.tolist() == expect
        assert ed25519._predecomp_stats["hit"] >= 1
        # the point of per-KEY rows: a REORDERED batch over the same
        # keys is still a pure cache hit (batch-content keying missed)
        hits0 = ed25519._predecomp_stats["hit"]
        perm = list(range(n))[::-1]
        r4 = ed25519.verify_batch([pubs[i] for i in perm],
                                  [msgs[i] for i in perm],
                                  [sigs[i] for i in perm])
        assert r4.tolist() == [expect[i] for i in perm]
        assert ed25519._predecomp_stats["hit"] == hits0 + 1
    finally:
        ed25519._PREDECOMP_MIN_BATCH = orig_min
        ed25519._predecomp.clear()
        ed25519._predecomp_seen.clear()


def test_predecomp_telemetry_stays_meaningful_under_churn():
    """Valset rotation vs the per-pubkey predecompression LRU (ISSUE 11
    satellite): a rotating valset must show up as full->fill->hit
    cycles per rotation, evictions must be COUNTED (they were invisible
    before — a churning valset quietly degraded every hit into a
    re-fill), and the tm_verifier_predecomp_* counters must mirror the
    host stats."""
    from tendermint_tpu import telemetry
    from tendermint_tpu.ops import ed25519
    from tendermint_tpu.utils import ed25519_ref as ref

    from bench_util import fast_signer

    def batch(tag, n=8):
        pubs, msgs, sigs = [], [], []
        for i in range(n):
            seed = bytes([tag, i]) * 16
            m = b"churn %d.%d" % (tag, i)
            pubs.append(ref.public_key(seed))
            msgs.append(m)
            sigs.append(fast_signer(seed)(m))
        return pubs, msgs, sigs

    was_enabled = telemetry.enabled()
    telemetry.set_enabled(True)
    ed25519._predecomp.clear()
    ed25519._predecomp_seen.clear()
    orig_min = ed25519._PREDECOMP_MIN_BATCH
    orig_max = ed25519._PREDECOMP_MAX_KEYS
    ed25519._PREDECOMP_MIN_BATCH = 8
    ed25519._PREDECOMP_MAX_KEYS = 8  # one valset's worth of rows
    s0 = ed25519.predecomp_stats()
    ev0 = telemetry.value("verifier_predecomp_evictions_total") or 0.0
    try:
        a = batch(1)
        for _ in range(3):  # full (first sighting) -> fill -> hit
            assert ed25519.verify_batch(*a).all()
        s1 = ed25519.predecomp_stats()
        assert s1["full"] == s0["full"] + 1
        assert s1["fill"] == s0["fill"] + 1
        assert s1["hit"] == s0["hit"] + 1
        assert s1["evict"] == s0["evict"]
        assert s1["keys"] == 8

        # rotation: a new valset's repeat traffic evicts the old rows
        # (capacity 8) and runs its own full->fill->hit cycle — the
        # hit/fill split stays meaningful instead of silently decaying
        b = batch(2)
        for _ in range(3):
            assert ed25519.verify_batch(*b).all()
        s2 = ed25519.predecomp_stats()
        assert s2["full"] == s1["full"] + 1
        assert s2["fill"] == s1["fill"] + 1
        assert s2["hit"] == s1["hit"] + 1
        assert s2["evict"] == s1["evict"] + 8  # old valset's rows
        assert s2["keys"] == 8
        assert 0.0 < s2["hit_rate"] < 1.0

        # telemetry mirrors the host stats (the new eviction counter
        # most of all — that is the one that was invisible)
        assert (telemetry.value("verifier_predecomp_evictions_total")
                - ev0) == 8.0
        assert telemetry.value("verifier_predecomp_keys") == 8.0
        assert telemetry.value("verifier_predecomp_batches_total",
                               {"outcome": "hit"}) >= 2.0
    finally:
        telemetry.set_enabled(was_enabled)
        ed25519._PREDECOMP_MIN_BATCH = orig_min
        ed25519._PREDECOMP_MAX_KEYS = orig_max
        ed25519._predecomp.clear()
        ed25519._predecomp_seen.clear()


def test_scalar_openssl_matches_pure_oracle():
    """PubKey.verify/verify_any route through OpenSSL (~170x faster);
    verdicts must agree with the pure RFC 8032 oracle on valid,
    tampered, truncated, garbage AND adversarial non-canonical
    encodings (OpenSSL's leniency gap routes back to the oracle — a
    verdict split there would be a consensus fork)."""
    import random

    import pytest as _pytest

    from tendermint_tpu.types import keys as keys_mod
    from tendermint_tpu.types.keys import PubKey, _openssl_verify
    from tendermint_tpu.utils import ed25519_ref as ref

    _pytest.importorskip("cryptography")

    p255 = (1 << 255) - 19
    rng = random.Random(4242)
    for i in range(30):
        seed = rng.randbytes(32)
        pk = ref.public_key(seed)
        msg = rng.randbytes(rng.randrange(0, 64))
        sig = ref.sign(seed, msg)
        cases = [
            (pk, msg, sig),                                   # valid
            (pk, msg + b"x", sig),                            # wrong msg
            (pk, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]),
            (pk, msg, sig[:-1]),                              # short sig
            (pk, msg, rng.randbytes(64)),                     # garbage
            (rng.randbytes(32), msg, sig),                    # wrong key
        ]
        for p, m, s in cases:
            want = ref.verify(p, m, s)
            assert PubKey(p).verify(m, s) == want, (i, p.hex())

    # adversarial non-canonical encodings: x=0 identity rows with the
    # sign bit set, and y >= p — _openssl_verify must DECLINE (None)
    # and the routed verdict must equal the oracle's
    msg = b"adversarial"
    ncid = (1).to_bytes(32, "little")
    ncid = ncid[:31] + bytes([ncid[31] | 0x80])        # y=1, sign=1
    ncid2 = (p255 - 1).to_bytes(32, "little")
    ncid2 = ncid2[:31] + bytes([ncid2[31] | 0x80])     # y=-1, sign=1
    ybig = (p255 + 2).to_bytes(32, "little")           # y >= p
    for bad in (ncid, ncid2, ybig):
        for pkey, sg in ((bad, bad + bytes(32)),
                         (ref.public_key(b"\x01" * 32), bad + bytes(32)),
                         (bad, ref.sign(b"\x01" * 32, msg))):
            assert _openssl_verify(pkey, msg, sg) is None, bad.hex()
            assert PubKey(pkey).verify(msg, sg) == \
                ref.verify(pkey, msg, sg)

    # the pure-fallback branch (no cryptography) still verifies
    orig = keys_mod._ossl_pub_cls
    try:
        keys_mod._ossl_pub_cls = False
        seed = b"\x05" * 32
        pk = ref.public_key(seed)
        sig = ref.sign(seed, msg)
        assert PubKey(pk).verify(msg, sig)
        assert not PubKey(pk).verify(msg + b"!", sig)
    finally:
        keys_mod._ossl_pub_cls = orig
