"""Recovery-plane tests: chunked snapshot store (atomic publish,
content-addressed chunks, Merkle manifest), store pruning boundaries
(snapshot / evidence / peer floors), handshake app-recovery from a
pruned store, and the crash-at-every-recovery-fail-point sweep against
a clean control's AppHash (the in-process analogue of the commit-point
sweep in test_fail_points.py)."""

import hashlib
import os

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import MockTicker
from tendermint_tpu.node import Node
from tendermint_tpu.storage import (
    BlockStore, MemDB, SnapshotManager, SnapshotStore, SQLiteDB,
    StateStore,
)
from tendermint_tpu.storage.snapshot import (
    MANIFEST_NAME, build_payload, chunk_name, light_verify_payload,
    manifest_root,
)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import PrivValidatorFile
from tendermint_tpu.utils import fail


class _Crash(BaseException):
    """Simulated process death at a fail point (BaseException: nothing
    between the fail point and the test may swallow it)."""


def _payload(n_app=5):
    return {"state": {"chain_id": "t", "app_hash": "ab" * 32},
            "commit": {}, "app": [["%02x" % i, "aa"] for i in range(n_app)]}


# ------------------------------------------------------- snapshot store --

def test_take_assemble_roundtrip_and_idempotence(tmp_path):
    store = SnapshotStore(str(tmp_path))
    m = store.take(8, _payload(), chunk_size=16)
    assert len(m["chunks"]) > 1
    assert m["root"] == manifest_root(m["chunks"])
    assert store.list_heights() == [8]
    assert store.assemble_payload(8, m["root"]) == _payload()
    # idempotent: a second take returns the SAME manifest untouched
    assert store.take(8, _payload(99), chunk_size=16) == m


def test_chunks_are_content_addressed_and_digest_checked(tmp_path):
    store = SnapshotStore(str(tmp_path))
    m = store.take(4, _payload(), chunk_size=16)
    digest = m["chunks"][1]
    data = store.read_chunk(4, 1)
    assert hashlib.sha256(data).hexdigest() == digest
    # bit-rot: a corrupted chunk file is refused, and assembly fails
    path = os.path.join(store.dir_for(4), chunk_name(digest))
    with open(path, "wb") as f:
        f.write(b"\x00" * len(data))
    assert store.read_chunk(4, 1) is None
    with pytest.raises(ValueError, match="missing or corrupt"):
        store.assemble_payload(4)


def test_tampered_manifest_root_rejected(tmp_path):
    store = SnapshotStore(str(tmp_path))
    m = store.take(4, _payload(), chunk_size=64)
    m["root"] = "00" * 32
    import tendermint_tpu.types.encoding as encoding
    with open(os.path.join(store.dir_for(4), MANIFEST_NAME), "wb") as f:
        f.write(encoding.cdumps(m))
    with pytest.raises(ValueError, match="root mismatch"):
        store.assemble_payload(4)


def test_crash_mid_write_never_publishes_half_snapshot(tmp_path):
    """A crash at snapshot.after_chunk or snapshot.before_publish
    leaves NO visible snapshot — only a temp dir the next take sweeps."""
    for point in ("snapshot.after_chunk", "snapshot.before_publish"):
        store = SnapshotStore(str(tmp_path / point.replace(".", "_")))

        def crash(name):
            raise _Crash(name)

        fail.arm(point, crash)
        with pytest.raises(_Crash):
            store.take(8, _payload(), chunk_size=16)
        fail.disarm_all()
        assert store.list_heights() == []
        assert store.load_manifest(8) is None
        # recovery: the next take republishes cleanly and sweeps tmp
        m = store.take(8, _payload(), chunk_size=16)
        assert store.assemble_payload(8, m["root"]) == _payload()
        leftover = [n for n in os.listdir(store.root_dir)
                    if n.startswith(".tmp-")]
        assert leftover == []


def test_retention_drops_oldest(tmp_path):
    store = SnapshotStore(str(tmp_path))
    for h in (2, 4, 6, 8):
        store.take(h, _payload(), chunk_size=64)
    assert store.retain(2) == [2, 4]
    assert store.list_heights() == [6, 8]


# ----------------------------------------------------------- db pruning --

@pytest.mark.parametrize("mk", [lambda tmp: MemDB(),
                                lambda tmp: SQLiteDB(str(tmp / "kv.db"))])
def test_delete_batch_and_compact(tmp_path, mk):
    db = mk(tmp_path)
    db.set_batch([(b"k%03d" % i, b"v" * 64) for i in range(100)])
    db.delete_batch([b"k%03d" % i for i in range(50)])
    assert db.get(b"k000") is None and db.get(b"k099") is not None
    assert len(list(db.iterate(b"k"))) == 50
    db.compact()  # must be callable at any quiescent point
    assert len(list(db.iterate(b"k"))) == 50
    db.close()


def test_block_store_prune_and_base(tmp_path):
    from tests.test_fast_sync import build_chain
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="prune-bs", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    _, _, store, gen = build_chain(gen, key, 8)
    assert store.base() == 1
    n = store.prune(5, window=2)
    assert n == 4
    assert store.base() == 5
    assert store.load_block(4) is None
    assert store.load_block_meta(4) is None
    assert store.load_block(5) is not None
    assert store.load_seen_commit(4) is None
    # pruning is capped at the frontier and never re-deletes
    assert store.prune(100) == store.height() - 5
    assert store.base() == store.height()


def test_block_store_prune_crash_mid_range_is_idempotent(tmp_path):
    from tests.test_fast_sync import build_chain
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="prune-crash", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    _, _, store, gen = build_chain(gen, key, 8)

    hits = []

    def crash(name):
        hits.append(name)
        if len(hits) == 1:  # die after the FIRST window's deletes
            raise _Crash(name)

    fail.arm("prune.mid_range", crash)
    with pytest.raises(_Crash):
        store.prune(7, window=2)
    fail.disarm_all()
    # the first window died before its base advance (rows 1-2 deleted,
    # row says 1): base() self-heals by scanning to the first retained
    # block, so a restarted handshake never asks for a deleted height
    assert store.base() == 3
    assert store.prune(7, window=2) == 4
    assert store.base() == 7
    assert store.load_block(7) is not None


def test_state_store_prune_keeps_indirection_targets():
    ss = StateStore(MemDB())
    k = PrivKey.generate(b"\x01" * 32)
    gen = GenesisDoc(chain_id="ssp", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)])
    state = ss.load_or_genesis(gen)
    # heights 1..9 with no valset change: every row points at 1
    for h in range(1, 10):
        state = state.copy()
        state.last_block_height = h
        ss.save(state)
        ss.save_abci_responses(h, {"deliver_txs": [], "end_block": {}})
    ss.prune(7)
    # rows below 7 swept, EXCEPT the pointer target (height 1)
    assert ss.load_abci_responses(3) is None
    vs = ss.load_validators(8)   # 8 -> last_changed 1 must still resolve
    assert vs.hash() == state.validators.hash()
    assert ss.load_consensus_params(9) is not None


def test_state_store_bootstrap_and_pins():
    ss = StateStore(MemDB())
    k = PrivKey.generate(b"\x02" * 32)
    gen = GenesisDoc(chain_id="ssb", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)])
    state = ss.load_or_genesis(gen)
    state = state.copy()
    state.last_block_height = 42
    state.last_validators = state.validators
    ss.bootstrap(state)
    assert ss.load().last_block_height == 42
    assert ss.load_validators(42).hash() == state.validators.hash()
    assert ss.load_validators(43).hash() == state.validators.hash()
    ss.pin_snapshot(42, {"root": "ab" * 32})
    assert ss.latest_snapshot_height() == 42
    assert ss.load_snapshot_pin(42)["root"] == "ab" * 32
    ss.unpin_snapshot(42)
    assert ss.load_snapshot_pin(42) is None


# -------------------------------------------------- prune floor policy --

class _FloorHarness:
    """SnapshotManager over real Mem stores with a scripted chain."""

    def __init__(self, tmp_path, retain, interval=2, max_age=100000,
                 peer_floor=None):
        from tests.test_fast_sync import build_chain
        key = PrivKey.generate(b"\x09" * 32)
        gen = GenesisDoc(
            chain_id="floor", genesis_time_ns=1,
            validators=[GenesisValidator(key.pubkey.ed25519, 10)])
        gen.consensus_params.evidence.max_age = max_age
        self.state, self.state_store, self.block_store, _ = \
            build_chain(gen, key, 10)
        self.app = KVStoreApp()
        self.mgr = SnapshotManager(
            SnapshotStore(str(tmp_path)), self.state_store,
            self.block_store, self.app, interval=interval,
            retain_heights=retain, peer_floor=peer_floor)


def test_prune_refuses_below_latest_snapshot(tmp_path):
    h = _FloorHarness(tmp_path, retain=1, interval=0)
    # retain=1 would prune to height 10 — but with NO snapshot at all
    # pruning must refuse entirely (the app could never rebuild)
    h.mgr.maybe_snapshot(h.state)
    assert h.block_store.base() == 1
    # with a snapshot pinned at 6, the floor is capped AT it
    m = h.mgr.store.take(6, _payload())
    h.state_store.pin_snapshot(6, m)
    h.mgr.maybe_snapshot(h.state)
    assert h.block_store.base() == 6
    assert h.block_store.load_block(6) is not None


def test_prune_respects_peer_catchup_frontier(tmp_path):
    h = _FloorHarness(tmp_path, retain=1, interval=2,
                      peer_floor=lambda: 4)
    h.mgr.maybe_snapshot(h.state)  # snapshots at 10, floor min(10, 4)=4
    assert h.block_store.base() == 4
    assert h.block_store.load_block(4) is not None


def test_prune_respects_evidence_horizon_in_state_store(tmp_path):
    h = _FloorHarness(tmp_path, retain=1, interval=2, max_age=3)
    h.mgr.maybe_snapshot(h.state)
    # blocks pruned to the snapshot floor (10)...
    assert h.block_store.base() == 10
    # ...but state rows within the evidence window (10-3+1 = 8) survive
    assert h.state_store.load_validators(8) is not None
    assert h.state_store.load_abci_responses(7) is None


# ------------------------------------- node-level sweep vs control run --

WAVE_A = [b"sn/a%d=v%d" % (i, i) for i in range(1, 4)]
WAVE_B = [b"sn/b%d=w%d" % (i, i) for i in range(1, 4)]

RECOVERY_SWEEP_POINTS = ("snapshot.after_chunk",
                         "snapshot.before_publish",
                         "prune.mid_range")


def _gen(chain_id):
    key = PrivKey.generate(b"\x0b" * 32)
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    gen.consensus_params.evidence.max_age = 4
    return gen, key


def _make_node(home, gen, key):
    pv_path = os.path.join(home, "priv_validator.json")
    if os.path.exists(pv_path):
        pv = PrivValidatorFile.load(pv_path)
    else:
        pv = PrivValidatorFile(pv_path, key)
        pv._persist()
    node = Node(make_test_config(home), gen, priv_validator=pv,
                app=KVStoreApp())
    node.consensus.ticker.stop()
    node.consensus.ticker = MockTicker(node.consensus._on_timeout_fire)
    return node


def _inject(node, txs):
    for tx in txs:
        try:
            node.mempool.check_tx(tx)
        except Exception:
            pass


def _commit_to(node, target_height, max_ticks=400):
    for _ in range(max_ticks):
        if node.height >= target_height:
            return
        node.consensus.ticker.fire_next()
    raise AssertionError(f"stuck at height {node.height}")


def _drain(node, max_ticks=200):
    for _ in range(max_ticks):
        if node.mempool.size() == 0:
            return
        node.consensus.ticker.fire_next()
    raise AssertionError("mempool never drained")


def test_crash_at_every_recovery_point_recovers_control_apphash(
        tmp_path, monkeypatch):
    """For EVERY snapshot/prune fail point: run a snapshotting+pruning
    node, crash it at that point mid-run, rebuild from the home dir,
    and require the recovered node to reach the control run's height
    with the IDENTICAL AppHash — and with no half-published snapshot
    visible. The control runs with the whole recovery plane OFF, so
    the sweep also pins snapshot/prune heights as behavior-neutral."""
    target = 6
    gen, key = _gen("snap-sweep")

    control = _make_node(str(tmp_path / "control"), gen, key)
    control.start()
    _inject(control, WAVE_A)
    _commit_to(control, 3)
    _inject(control, WAVE_B)
    _commit_to(control, target)
    _drain(control)
    control_hash = control.consensus.state.app_hash
    control.stop()
    assert control_hash

    monkeypatch.setenv("TM_TPU_SNAPSHOT_INTERVAL", "2")
    monkeypatch.setenv("TM_TPU_SNAPSHOT_KEEP", "2")
    monkeypatch.setenv("TM_TPU_RETAIN_HEIGHTS", "2")
    for point in RECOVERY_SWEEP_POINTS:
        home = str(tmp_path / point.replace(".", "_"))
        node = _make_node(home, gen, key)
        node.start()
        _inject(node, WAVE_A)
        _commit_to(node, 3)

        def crash(name):
            raise _Crash(name)

        fail.arm(point, crash)
        with pytest.raises(_Crash):
            _inject(node, WAVE_B)
            _commit_to(node, target)
            raise AssertionError(f"{point} never fired")
        fail.disarm_all()
        crashed_at = node.height
        node.consensus._stopped = True
        try:
            node.stop()
        except Exception:
            pass

        node2 = _make_node(home, gen, key)
        node2.start()
        assert node2.height >= crashed_at   # no committed height lost
        _inject(node2, WAVE_B)
        _commit_to(node2, target)
        _drain(node2)
        assert node2.consensus.state.app_hash == control_hash, (
            f"{point}: recovered AppHash diverged")
        # no half-published snapshot anywhere: every listed height has
        # a verifiable manifest + chunks
        for h in node2.snapshot_store.list_heights():
            node2.snapshot_store.assemble_payload(h)
        assert not [n for n in os.listdir(node2.snapshot_store.root_dir)
                    if n.startswith(".tmp-")]
        node2.stop()


def test_pruned_store_restart_recovers_app_from_snapshot(tmp_path,
                                                         monkeypatch):
    """After pruning, a restart can no longer replay the app from
    genesis — the handshake must rebuild it from the newest pinned
    snapshot plus the retained tail blocks, bit-identically."""
    monkeypatch.setenv("TM_TPU_SNAPSHOT_INTERVAL", "3")
    monkeypatch.setenv("TM_TPU_RETAIN_HEIGHTS", "2")
    gen, key = _gen("snap-restart")
    home = str(tmp_path)
    node = _make_node(home, gen, key)
    node.start()
    for w in range(8):
        _inject(node, [b"pr/k%d=v%d" % (w, w)])
        _commit_to(node, w + 1)
    _drain(node)
    app_hash = node.consensus.state.app_hash
    height = node.height
    assert node.block_store.base() > 1          # pruning really ran
    assert node.snapshot_store.list_heights()   # snapshots exist
    node.stop()

    node2 = _make_node(home, gen, key)
    assert node2.consensus.state.last_block_height == height
    assert node2.consensus.state.app_hash == app_hash
    assert node2.app.height == height
    # and the revived node keeps committing
    node2.start()
    _inject(node2, [b"pr/after=1"])
    _commit_to(node2, height + 1)
    node2.stop()


def test_light_verify_payload_rejects_forged_commit():
    """A snapshot whose commit does not carry +2/3 genuine signatures
    for the claimed block id must be rejected."""
    from tests.test_fast_sync import build_chain
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="lv", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    state, _, store, gen = build_chain(gen, key, 4)
    commit = store.load_seen_commit(state.last_block_height)
    payload = build_payload(state, commit,
                            [(b"k", b"v")])
    st, cm = light_verify_payload(payload, "lv")   # genuine: passes
    assert st.last_block_height == state.last_block_height

    forged = build_payload(state, commit, [(b"k", b"v")])
    forged["commit"] = dict(forged["commit"])
    pcs = [dict(p) if p else None
           for p in forged["commit"]["precommits"]]
    for p in pcs:
        if p is not None:
            p["signature"] = "00" * 64
    forged["commit"]["precommits"] = pcs
    with pytest.raises(ValueError):
        light_verify_payload(forged, "lv")
    # wrong chain id is refused before any crypto
    with pytest.raises(ValueError, match="chain_id"):
        light_verify_payload(payload, "other-chain")
