"""Compact consensus gossip (ISSUE 18): salted short ids, strike
backoff, knob off-hatch + legacy-peer byte parity, the mempool
tx-by-hash index, aggregated vote gossip, reconstruction fallback
(hostile fetch peers, timeouts), and compact/legacy mixed-net interop.
"""

import hashlib
import threading
import time

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.consensus import compact
from tendermint_tpu.consensus.reactor import (
    DATA_CHANNEL,
    VOTE_CHANNEL,
    ConsensusReactor,
    PeerRoundState,
)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey, \
    encoding
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.vote import Vote, VoteType

from tests.test_consensus_reactor import (
    make_validator_node,
    shutdown,
    wait_height,
)


@pytest.fixture(autouse=True)
def _fresh_knobs(monkeypatch):
    """Every test starts from the catalog defaults (auto = on) with no
    env overrides leaking in from the host."""
    monkeypatch.delenv("TM_TPU_COMPACT", raising=False)
    monkeypatch.delenv("TM_TPU_VOTE_AGG", raising=False)
    compact.configure()
    yield
    compact.configure()


@pytest.fixture
def metrics():
    telemetry.configure(enabled=True)
    yield telemetry.REGISTRY
    telemetry.configure(enabled=False)


def _gen(n, chain_id):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    return keys, gen


class CapturePeer:
    """Test double recording every send; optionally claims compact
    capabilities (a real peer's caps come from NodeInfo.other)."""

    def __init__(self, pid="capture-peer", caps=()):
        self.id = pid
        self.running = True
        self.sent = []           # (channel, decoded obj)

        class _Info:
            other = list(caps)
        self.node_info = _Info()

    def set(self, k, v):
        pass

    def send(self, ch, raw):
        self.sent.append((ch, encoding.cloads(raw)))
        return True

    def try_send_obj(self, ch, obj):
        self.sent.append((ch, obj))
        return True

    def of_type(self, t):
        return [m for _, m in self.sent if m.get("type") == t]


# ------------------------------------------------------------ short ids

def test_short_ids_deterministic_and_salted():
    sig = b"\x07" * 64
    salt = compact.proposal_salt(sig)
    assert len(salt) == 8
    assert salt == compact.proposal_salt(sig)
    assert salt != compact.proposal_salt(b"\x08" * 64)
    txs = [b"tx-a", b"tx-b", b"tx-a"]
    ids = compact.short_ids_for(salt, txs)
    assert ids[0] == ids[2] != ids[1]
    assert all(len(i) == compact.SHORT_ID_LEN for i in ids)
    # receivers match against FULL stored hashes, never tx bodies
    assert ids[0] == compact.short_id(salt,
                                      hashlib.sha256(b"tx-a").digest())
    # a different proposal's salt permutes every id
    assert compact.short_ids_for(b"\x00" * 8, txs) != ids


# -------------------------------------------------------------- strikes

def test_strike_ledger_exponential_backoff_and_forget():
    led = compact.StrikeLedger(base_s=1.0, cap_s=8.0)
    assert not led.in_backoff("p", 0.0)
    led.strike("p", 0.0, "timeout")          # 1s
    assert led.in_backoff("p", 0.5) and not led.in_backoff("p", 1.5)
    led.strike("p", 10.0, "timeout")         # 2s
    led.strike("p", 20.0, "timeout")         # 4s
    assert led.in_backoff("p", 23.9) and not led.in_backoff("p", 24.1)
    led.strike("p", 30.0, "nack")            # 8s (cap)
    led.strike("p", 40.0, "nack")            # still 8s, capped
    assert led.in_backoff("p", 47.9) and not led.in_backoff("p", 48.1)
    assert not led.in_backoff("q", 0.0)      # per-peer
    led.forget("p")
    assert not led.in_backoff("p", 41.0)


# ---------------------------------------------------------------- knobs

def test_knob_resolution_env_beats_config(monkeypatch):
    assert compact.compact_on() and compact.voteagg_on()   # auto = on
    compact.configure(compact_mode="off", voteagg_mode="off")
    assert not compact.compact_on() and not compact.voteagg_on()
    assert compact.wire_capabilities() == []
    monkeypatch.setenv("TM_TPU_COMPACT", "on")             # env > config
    assert compact.compact_on() and not compact.voteagg_on()
    assert compact.wire_capabilities() == [compact.CAP_COMPACT]
    monkeypatch.setenv("TM_TPU_COMPACT", "off")
    compact.configure()
    assert not compact.compact_on() and compact.voteagg_on()
    assert compact.wire_capabilities() == [compact.CAP_VOTEAGG]


def test_handshake_bytes_identical_with_knobs_off(monkeypatch):
    """Both knobs off: NodeInfo carries NO capability strings — the
    handshake wire bytes are byte-for-byte the legacy shape."""
    from tendermint_tpu.p2p.node_info import NodeInfo
    monkeypatch.setenv("TM_TPU_COMPACT", "off")
    monkeypatch.setenv("TM_TPU_VOTE_AGG", "off")
    pk = PrivKey.generate(b"\x31" * 32).pubkey.ed25519
    legacy = NodeInfo(pubkey=pk, moniker="m", network="n")
    ours = NodeInfo(pubkey=pk, moniker="m", network="n",
                    other=compact.wire_capabilities())
    assert encoding.cdumps(ours.to_obj()) == \
        encoding.cdumps(legacy.to_obj())


def test_reactor_snapshots_knobs_at_construction(monkeypatch):
    keys, gen = _gen(1, "knob-snap")
    monkeypatch.setenv("TM_TPU_COMPACT", "off")
    monkeypatch.setenv("TM_TPU_VOTE_AGG", "off")
    r = ConsensusReactor(make_validator_node(gen, keys[0]))
    assert not r._compact and not r._voteagg
    monkeypatch.setenv("TM_TPU_COMPACT", "auto")
    monkeypatch.setenv("TM_TPU_VOTE_AGG", "auto")
    r2 = ConsensusReactor(make_validator_node(gen, keys[0]))
    assert r2._compact and r2._voteagg
    assert compact.peer_capabilities(
        CapturePeer(caps=[compact.CAP_COMPACT])) == (True, False)
    assert compact.peer_capabilities(object()) == (False, False)


# -------------------------------------------------- mempool hash index

def test_mempool_get_by_hash_lifecycle():
    from tests.test_mempool import make_mempool
    mp, _ = make_mempool()
    txs = [b"idx-tx-%d" % i for i in range(4)]
    for tx in txs:
        mp.check_tx(tx)
    hashes = [hashlib.sha256(tx).digest() for tx in txs]
    for h, tx in zip(hashes, txs):
        assert mp.get_by_hash(h) == tx
    assert set(mp.pending_hashes()) == set(hashes)
    assert mp.get_by_hash(b"\x00" * 32) is None
    # commit two: their index entries drop, the rest survive recheck
    mp.update(1, txs[:2])
    assert mp.get_by_hash(hashes[0]) is None
    assert mp.get_by_hash(hashes[1]) is None
    assert mp.get_by_hash(hashes[2]) == txs[2]
    assert set(mp.pending_hashes()) == set(hashes[2:])
    mp.flush()
    assert mp.pending_hashes() == []


def test_mempool_batch_check_indexes_too():
    from tests.test_mempool import make_mempool
    mp, _ = make_mempool()
    txs = [b"batch-%d" % i for i in range(8)]
    mp.check_tx_batch(txs)
    for tx in txs:
        assert mp.get_by_hash(hashlib.sha256(tx).digest()) == tx


# ------------------------------------------------- vote agg: state side

def _signed_prevotes(keys, gen, cs, round_=0):
    """One nil prevote per validator except cs's own (index 0)."""
    nil = BlockID(b"", PartSetHeader(0, b""))
    votes = []
    for i, k in enumerate(keys):
        if i == 0:
            continue
        v = Vote(validator_address=k.pubkey.address, validator_index=i,
                 height=cs.rs.height, round=round_,
                 type=VoteType.PREVOTE, block_id=nil,
                 timestamp_ns=1000 + i)
        v.signature = k.sign(v.sign_bytes(gen.chain_id))
        votes.append(v)
    return votes


def test_vote_agg_input_applies_whole_batch():
    """A vote_agg submit applies every vote through the bulk VoteSet
    path — same end state as n scalar vote submits."""
    keys, gen = _gen(4, "agg-state")
    cs = make_validator_node(gen, keys[0])
    votes = _signed_prevotes(keys, gen, cs)
    cs.submit({"type": "vote_agg",
               "votes": [v.to_obj() for v in votes]}, "peer-x")
    prevotes = cs.rs.votes.prevotes(0)
    got = {v.validator_index for v in prevotes.votes if v is not None}
    assert {1, 2, 3} <= got
    # duplicates re-delivered in an aggregate are silently absorbed
    cs.submit({"type": "vote_agg",
               "votes": [v.to_obj() for v in votes]}, "peer-y")
    assert {v.validator_index
            for v in cs.rs.votes.prevotes(0).votes
            if v is not None} == got


def test_height_vote_set_bulk_matches_scalar():
    keys, gen = _gen(4, "agg-hvs")
    cs = make_validator_node(gen, keys[0])
    votes = _signed_prevotes(keys, gen, cs)
    results, errors = cs.rs.votes.add_votes(
        0, VoteType.PREVOTE, votes, "peer-z")
    assert results == [True] * 3 and errors == []
    # a second pass is all duplicates: no error, nothing added
    results2, errors2 = cs.rs.votes.add_votes(
        0, VoteType.PREVOTE, votes, "peer-z")
    assert results2 == [False] * 3 and errors2 == []


# ---------------------------------------------- vote agg: gossip bytes

def _reactor_with_votes(chain_id):
    keys, gen = _gen(4, chain_id)
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    votes = _signed_prevotes(keys, gen, cs)
    for v in votes:
        cs.rs.votes.add_vote(v)
    return reactor, cs, votes


def test_legacy_peer_receives_byte_identical_single_votes():
    """Toward a peer that did NOT advertise voteagg/1 the vote pass
    emits exactly the legacy single-vote message — byte-for-byte."""
    reactor, cs, votes = _reactor_with_votes("agg-legacy")
    peer = CapturePeer()                      # no capabilities
    ps = PeerRoundState()
    ps.apply_new_round_step({"height": cs.rs.height, "round": 0,
                             "step": 4})
    reactor.peer_states[peer.id] = ps
    assert reactor._gossip_votes_pass(peer, ps, {"idle": 0})
    ch, msg = peer.sent[0]
    assert ch == VOTE_CHANNEL
    by_index = {v.validator_index: v for v in votes}
    expect = {"type": "vote",
              "vote": by_index[msg["vote"]["validator_index"]].to_obj()}
    assert encoding.cdumps(msg) == encoding.cdumps(expect)


def _register(reactor, peer):
    """Manual peer registration (add_peer would spawn real gossip
    threads against the test double and race the manual passes)."""
    ps = PeerRoundState()
    ps.caps = compact.peer_capabilities(peer)
    reactor.peer_states[peer.id] = ps
    return ps


def test_capable_peer_receives_vote_aggregate():
    reactor, cs, votes = _reactor_with_votes("agg-wire")
    peer = CapturePeer(caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    ps = _register(reactor, peer)
    assert ps.caps == (True, True)
    ps.apply_new_round_step({"height": cs.rs.height, "round": 0,
                             "step": 4})
    assert reactor._gossip_votes_pass(peer, ps, {"idle": 0})
    aggs = peer.of_type("vote_agg")
    assert len(aggs) == 1 and len(aggs[0]["votes"]) == 3
    # every aggregated vote is marked known: the next pass goes idle
    assert not reactor._gossip_votes_pass(peer, ps, {"idle": 0})


def test_voteagg_off_never_aggregates_even_to_capable_peer(monkeypatch):
    monkeypatch.setenv("TM_TPU_VOTE_AGG", "off")
    reactor, cs, votes = _reactor_with_votes("agg-off")
    peer = CapturePeer(caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    ps = _register(reactor, peer)
    ps.apply_new_round_step({"height": cs.rs.height, "round": 0,
                             "step": 4})
    assert reactor._gossip_votes_pass(peer, ps, {"idle": 0})
    assert not peer.of_type("vote_agg")
    assert peer.of_type("vote")


def test_oversized_vote_aggregate_dropped_on_receive():
    keys, gen = _gen(4, "agg-bound")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    peer = CapturePeer()
    reactor.peer_states[peer.id] = PeerRoundState()
    fake = {"height": 1, "round": 0, "type": 1, "validator_index": 1}
    too_many = [dict(fake) for _ in range(compact.MAX_AGG_VOTES + 1)]
    reactor.receive(VOTE_CHANNEL, peer, encoding.cdumps(
        {"type": "vote_agg", "votes": too_many}))
    reactor.receive(VOTE_CHANNEL, peer, encoding.cdumps(
        {"type": "vote_agg", "votes": []}))
    reactor.receive(VOTE_CHANNEL, peer, encoding.cdumps(
        {"type": "vote_agg", "votes": "bogus"}))
    assert cs.rs.votes.prevotes(0).power == 0


# ------------------------------------- compact relay: fallback + hostility

def _compact_msg_for(cs, short_ids, salt=b"\x05" * 8):
    """A plausible compact offer for cs's CURRENT (height, round) with
    attacker-chosen short ids (header content is irrelevant to the
    resolve/fetch phases under test)."""
    return {"type": "compact_block", "height": cs.rs.height,
            "round": cs.rs.round, "salt": salt.hex(),
            "short_ids": [s.hex() for s in short_ids],
            "header": {}, "evidence": [], "last_commit": None}


def test_hostile_peer_never_serves_fetch_falls_back(metrics):
    """A peer advertising txs it never serves: the fetch deadline
    expires, every offerer is nacked (their parts flow), the liar is
    struck, and its NEXT offer is refused while in backoff."""
    keys, gen = _gen(4, "hostile")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    peer = CapturePeer(pid="liar",
                       caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    _register(reactor, peer)
    salt = b"\x05" * 8
    ghost = compact.short_id(salt, hashlib.sha256(b"ghost-tx").digest())
    reactor.receive(DATA_CHANNEL, peer, encoding.cdumps(
        _compact_msg_for(cs, [ghost], salt)))
    # nothing in the mempool matches -> one bounded fetch to the liar
    fetches = peer.of_type("tx_fetch")
    assert len(fetches) == 1 and fetches[0]["indices"] == [0]
    assert reactor._compact_rx is not None
    # ...which is never answered: the deadline nacks and strikes
    reactor._compact_rx["deadline"] = time.monotonic() - 1.0
    reactor._compact_rx_tick(time.monotonic())
    assert reactor._compact_rx is None
    nacks = [m for m in peer.of_type("compact_ack") if not m["ok"]]
    assert len(nacks) == 1
    assert reactor._strikes.in_backoff("liar", time.monotonic())
    # while in backoff, further offers are refused outright
    reactor.receive(DATA_CHANNEL, peer, encoding.cdumps(
        _compact_msg_for(cs, [ghost], salt)))
    assert reactor._compact_rx is None
    assert len([m for m in peer.of_type("compact_ack")
                if not m["ok"]]) == 2
    assert metrics.value("compact_reconstruct_total",
                         {"outcome": "fallback"}) >= 1


def test_bogus_fetch_reply_strikes_and_falls_back():
    """A fetch reply whose tx does not hash to the advertised short id
    is a lying sender: strike + immediate fallback, never a rebuilt
    block from unverified bytes."""
    keys, gen = _gen(4, "bogus")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    peer = CapturePeer(pid="forger",
                       caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    _register(reactor, peer)
    salt = b"\x06" * 8
    ghost = compact.short_id(salt, hashlib.sha256(b"real-tx").digest())
    reactor.receive(DATA_CHANNEL, peer, encoding.cdumps(
        _compact_msg_for(cs, [ghost], salt)))
    assert peer.of_type("tx_fetch")
    reactor.receive(DATA_CHANNEL, peer, encoding.cdumps(
        {"type": "tx_fetch_reply", "height": cs.rs.height,
         "round": cs.rs.round, "txs": [[0, b"WRONG-tx".hex()]]}))
    assert reactor._compact_rx is None
    assert reactor._strikes.in_backoff("forger", time.monotonic())
    assert [m for m in peer.of_type("compact_ack") if not m["ok"]]


def test_stale_compact_offer_nacked():
    keys, gen = _gen(4, "stale")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    peer = CapturePeer(pid="slow",
                       caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    _register(reactor, peer)
    msg = _compact_msg_for(cs, [])
    msg["height"] = cs.rs.height + 7
    reactor.receive(DATA_CHANNEL, peer, encoding.cdumps(msg))
    assert reactor._compact_rx is None
    assert [m for m in peer.of_type("compact_ack") if not m["ok"]]
    # a stale offer is not the peer's fault: no strike
    assert not reactor._strikes.in_backoff("slow", time.monotonic())


def test_benign_nack_never_strikes_fault_nack_does():
    """Sender side: a stale/backoff nack is routine at round edges and
    must not open a backoff window (one stale offer would otherwise
    cascade into mutual backoff); a fault nack (reconstruction failed)
    still strikes."""
    keys, gen = _gen(4, "nack-kind")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    peer = CapturePeer(pid="edge",
                       caps=[compact.CAP_COMPACT, compact.CAP_VOTEAGG])
    ps = _register(reactor, peer)
    key = (cs.rs.height, cs.rs.round)
    now = time.monotonic()
    for reason in ("stale", "backoff", "busy"):
        with reactor._compact_lock:
            reactor._compact_sent["edge"] = {
                "key": key, "deadline": now + 10.0}
        reactor._on_compact_ack(peer, ps, {
            "height": key[0], "round": key[1], "ok": False,
            "reason": reason})
        assert not reactor._strikes.in_backoff("edge", now), reason
        # the entry is written off either way: parts flow, no re-offer
        assert reactor._compact_sent["edge"]["done"]
    with reactor._compact_lock:
        reactor._compact_sent["edge"] = {
            "key": key, "deadline": now + 10.0}
    reactor._on_compact_ack(peer, ps, {
        "height": key[0], "round": key[1], "ok": False,
        "reason": "failed"})
    assert reactor._strikes.in_backoff("edge", now)


def test_compact_sender_timeout_strikes_and_ships_parts():
    """Sender side: an unacked offer past its deadline flips that peer
    to the parts path (and a strike suppresses re-offering)."""
    keys, gen = _gen(4, "sender-to")
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs)
    ps = PeerRoundState()
    peer = CapturePeer(pid="quiet")
    now = time.monotonic()
    with reactor._compact_lock:
        reactor._compact_sent["quiet"] = {
            "key": (cs.rs.height, cs.rs.round), "deadline": now - 1.0}
    with cs._lock:
        mode, msg = reactor._compact_tx_phase(peer, ps, cs.rs, now)
    assert (mode, msg) == ("parts", None)
    assert reactor._strikes.in_backoff("quiet", now)
    with cs._lock:   # struck: no fresh offer either
        mode, _ = reactor._compact_tx_phase(peer, ps, cs.rs, now)
    assert mode == "parts"


# ------------------------------------------------------ net integration

def _make_capable_net(n, chain_id, caps_for):
    """make_connected_switches, but node i's NodeInfo advertises
    caps_for(i) — the real handshake negotiates the compact plane."""
    from tendermint_tpu.config import P2PConfig
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.switch import Switch
    from tendermint_tpu.p2p.test_util import connect_switches

    keys, gen = _gen(n, chain_id)
    css = [make_validator_node(gen, k, with_mempool=True) for k in keys]
    reactors = [ConsensusReactor(cs, gossip_sleep_s=0.005) for cs in css]
    switches = []
    for i in range(n):
        nk = NodeKey(PrivKey.generate(bytes([0x40 + i]) * 32))
        info = NodeInfo(pubkey=nk.pubkey, moniker=f"node{i}",
                        network=chain_id, other=list(caps_for(i)))
        sw = Switch(P2PConfig(), nk, info)
        sw.add_reactor("consensus", reactors[i])
        sw.start()
        switches.append(sw)
    for i in range(n):
        for j in range(i + 1, n):
            connect_switches(switches[i], switches[j])
    return css, reactors, switches


def _warm_mempools(css, txs):
    for cs in css:
        for tx in txs:
            try:
                cs.mempool.check_tx(tx)
            except Exception:
                pass


def test_compact_net_converges_with_reconstruction(metrics):
    """All-capable 4-node net with warm mempools: blocks flow through
    the compact plane (reconstructions recorded), votes aggregate, the
    chain converges on one tip, and app state matches everywhere."""
    all_caps = [compact.CAP_COMPACT, compact.CAP_VOTEAGG]
    css, reactors, switches = _make_capable_net(
        4, "compact-net", lambda i: all_caps)
    try:
        for r in reactors:
            for ps in r.peer_states.values():
                assert ps.caps == (True, True)
        assert wait_height(css, 1)
        _warm_mempools(css, [b"compact=yes", b"agg=yes"])
        base = max(cs.state.last_block_height for cs in css)
        assert wait_height(css, base + 3), (
            f"heights: {[cs.state.last_block_height for cs in css]}")
        tips = {cs.state.last_block_id.key() for cs in css
                if cs.state.last_block_height ==
                css[0].state.last_block_height}
        assert len(tips) == 1
        assert all(cs.app.store.get(b"compact") == b"yes" for cs in css)
        recon = sum(
            metrics.value("compact_reconstruct_total", {"outcome": o})
            or 0 for o in ("hit", "fetched"))
        assert recon > 0, "no block ever travelled compact"
        assert (metrics.value("voteagg_msgs_sent_total") or 0) > 0
        agg = metrics.value("voteagg_batch_votes")
        assert agg and agg["count"] > 0 and \
            agg["sum"] / agg["count"] > 1.0
    finally:
        shutdown(reactors, switches)


def test_mixed_compact_legacy_net_converges():
    """Interop both directions: two capable + two legacy nodes commit
    together; capable->legacy traffic stays legacy-shaped, and txs
    still reach every app."""
    all_caps = [compact.CAP_COMPACT, compact.CAP_VOTEAGG]
    css, reactors, switches = _make_capable_net(
        4, "mixed-net", lambda i: all_caps if i < 2 else [])
    try:
        # capable nodes see the legacy half as (False, False)
        for i in (0, 1):
            caps_seen = sorted(ps.caps
                               for ps in reactors[i].peer_states.values())
            assert caps_seen == [(False, False), (False, False),
                                 (True, True)]
        assert wait_height(css, 1)
        _warm_mempools(css, [b"mixed=net"])
        base = max(cs.state.last_block_height for cs in css)
        assert wait_height(css, base + 3), (
            f"heights: {[cs.state.last_block_height for cs in css]}")
        tips = {cs.state.last_block_id.key() for cs in css
                if cs.state.last_block_height ==
                css[0].state.last_block_height}
        assert len(tips) == 1
        assert all(cs.app.store.get(b"mixed") == b"net" for cs in css)
    finally:
        shutdown(reactors, switches)


def test_knobs_off_net_sends_zero_compact_messages(monkeypatch):
    """Both knobs off: even a fully capable-peer net never puts a new
    message type on the wire — the traffic is the legacy shape."""
    monkeypatch.setenv("TM_TPU_COMPACT", "off")
    monkeypatch.setenv("TM_TPU_VOTE_AGG", "off")
    seen = []
    orig = ConsensusReactor.receive

    def spying_receive(self, ch_id, peer, msg_bytes):
        seen.append(encoding.cloads(msg_bytes).get("type"))
        return orig(self, ch_id, peer, msg_bytes)

    monkeypatch.setattr(ConsensusReactor, "receive", spying_receive)
    all_caps = [compact.CAP_COMPACT, compact.CAP_VOTEAGG]
    css, reactors, switches = _make_capable_net(
        3, "off-net", lambda i: all_caps)
    try:
        assert all(not r._compact and not r._voteagg for r in reactors)
        assert wait_height(css, 2)
        legacy = {"proposal", "block_part", "vote", "new_round_step",
                  "has_vote", "commit_step", "heartbeat",
                  "vote_set_maj23", "vote_set_bits"}
        assert set(seen) <= legacy, sorted(set(seen) - legacy)
    finally:
        shutdown(reactors, switches)


# ------------------------------------------------------------ wire chaos

@pytest.mark.slow
def test_compact_plane_survives_wire_faults():
    """The compact plane under the PR 13 TCP fault proxy (drop + delay
    + corruption on every link): the net keeps committing, converges
    on one tip, and any reconstruction that the faults break falls
    back without wedging a peer (no stall = heights advance within the
    budget)."""
    from tendermint_tpu.chaos.wire import WireProxy, WireSchedule
    from tendermint_tpu.config import P2PConfig
    from tendermint_tpu.p2p import NetAddress
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.p2p.node_info import NodeInfo
    from tendermint_tpu.p2p.switch import Switch

    n = 4
    caps = [compact.CAP_COMPACT, compact.CAP_VOTEAGG]
    keys, gen = _gen(n, "wire-compact")
    css = [make_validator_node(gen, k, with_mempool=True) for k in keys]
    reactors = [ConsensusReactor(cs, gossip_sleep_s=0.005) for cs in css]
    switches = []
    for i in range(n):
        nk = NodeKey(PrivKey.generate(bytes([0x60 + i]) * 32))
        info = NodeInfo(pubkey=nk.pubkey, moniker=f"node{i}",
                        network="wire-compact", other=list(caps))
        sw = Switch(P2PConfig(), nk, info, encrypt=True)
        sw._ban_score = 0          # corrupt frames must not ban peers
        sw.add_reactor("consensus", reactors[i])
        switches.append(sw)
    addrs = [sw.listen("127.0.0.1", 0) for sw in switches]
    spec = {"drop": 0.01, "delay": 0.05, "delay_steps": [1, 2],
            "corrupt": 0.001, "step_ms": 20}
    sched = WireSchedule(spec, seed=18, n_nodes=n)
    mapping = {(i, j): ("127.0.0.1", addrs[j].port)
               for i in range(n) for j in range(n) if i < j}
    proxy = WireProxy(sched, mapping)
    ports = proxy.listen()
    proxy.start()
    try:
        for sw in switches:
            sw.start()
        for (i, j), port in ports.items():
            switches[i].dial_peer(
                NetAddress("127.0.0.1", port, switches[j].node_info.id),
                persistent=True)
        proxy.arm()
        _warm_mempools(css, [b"wire=chaos"])
        assert wait_height(css, 3, timeout=120.0), (
            f"stalled under wire faults: "
            f"{[cs.state.last_block_height for cs in css]}")
        top = min(cs.state.last_block_height for cs in css)
        ids = {cs.block_store.load_block_meta(top).block_id.key()
               for cs in css}
        assert len(ids) == 1, "chain divergence under wire faults"
    finally:
        for sw in switches:
            sw.stop()
        proxy.stop()
