"""Regression tests for review findings (storage + types hardening)."""

import pytest

from tendermint_tpu.storage.db import MemDB, SQLiteDB, _prefix_upper_bound
from tendermint_tpu.storage.wal import WAL
from tendermint_tpu.types import (
    BlockID, GenesisDoc, GenesisValidator, PrivKey, Validator, ValidatorSet,
    Vote, VoteSet, VoteType,
)
from tendermint_tpu.types.events import Query
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def _vote(key, idx, height=1, round_=0, ts=100, type_=VoteType.PRECOMMIT,
          block_id=None):
    return Vote(key.pubkey.address, idx, height, round_, ts, type_,
                block_id if block_id is not None else BlockID(b"h" * 32))


# -- priv validator ----------------------------------------------------------

def test_replayed_vote_reuses_stored_timestamp_and_signature():
    """A vote regenerated after crash-replay with a newer wall clock must go
    out with the ORIGINAL timestamp so the reused signature verifies
    (types/priv_validator.go signVote)."""
    key = PrivKey.generate(b"\x01" * 32)
    pv = PrivValidator(LocalSigner(key))
    v1 = _vote(key, 0, ts=100)
    pv.sign_vote("chain", v1)

    v2 = _vote(key, 0, ts=999)  # same HRS, only time differs
    pv.sign_vote("chain", v2)
    assert v2.timestamp_ns == 100
    assert v2.signature == v1.signature
    assert key.pubkey.verify(v2.sign_bytes("chain"), v2.signature)


def test_failed_signer_does_not_poison_last_sign_state():
    """If the signer raises, last-sign state must not advance — a retry must
    produce a real signature, never the previous height's signature."""
    key = PrivKey.generate(b"\x02" * 32)

    class FlakySigner(LocalSigner):
        fail_next = False

        def sign(self, msg):
            if self.fail_next:
                self.fail_next = False
                raise IOError("hsm glitch")
            return super().sign(msg)

    signer = FlakySigner(key)
    pv = PrivValidator(signer)
    v1 = _vote(key, 0, height=1)
    pv.sign_vote("chain", v1)

    signer.fail_next = True
    v2 = _vote(key, 0, height=2)
    with pytest.raises(IOError):
        pv.sign_vote("chain", v2)
    # retry must sign the new message, not replay v1's signature
    v3 = _vote(key, 0, height=2)
    pv.sign_vote("chain", v3)
    assert v3.signature != v1.signature
    assert key.pubkey.verify(v3.sign_bytes("chain"), v3.signature)


# -- vote set batch ----------------------------------------------------------

def test_one_bad_signature_does_not_poison_the_batch():
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    valset = ValidatorSet([Validator(k.pubkey.ed25519, 10) for k in keys])
    vs = VoteSet("chain", 1, 0, VoteType.PRECOMMIT, valset)

    votes = []
    for i, k in enumerate(keys):
        _, val = valset.get_by_address(k.pubkey.address)
        v = _vote(k, valset.get_by_address(k.pubkey.address)[0])
        v.validator_index = valset.get_by_address(k.pubkey.address)[0]
        v.signature = k.sign(v.sign_bytes("chain"))
        votes.append(v)
    votes[0].signature = b"\x00" * 64  # corrupt first

    results, errors = vs.add_votes_batch(votes)
    assert results == [False, True, True, True]
    assert len(errors) == 1 and errors[0][0] == 0
    assert "signature" in str(errors[0][1])


# -- query parsing -----------------------------------------------------------

def test_query_quoted_and_inside_value():
    q = Query("tm.event = 'Tx' AND tx.memo = 'cats AND dogs'")
    assert len(q.conds) == 2
    assert q.matches({"tm.event": "Tx", "tx.memo": "cats AND dogs"})
    assert not q.matches({"tm.event": "Tx", "tx.memo": "other"})


def test_query_variant_whitespace():
    q = Query("a = 1  AND   b = 2")
    assert len(q.conds) == 2
    assert q.matches({"a": 1, "b": 2})


# -- db prefix bound ---------------------------------------------------------

def test_prefix_upper_bound_edge_cases(tmp_path):
    assert _prefix_upper_bound(b"a") == b"b"
    assert _prefix_upper_bound(b"a\xff") == b"b"
    assert _prefix_upper_bound(b"\xff\xff") is None

    sq = SQLiteDB(str(tmp_path / "kv.db"))
    mem = MemDB()
    keys = [b"x\xff" + b"\xff" * 18, b"x\xff\x01", b"y", b"x\xfe"]
    for db in (sq, mem):
        for k in keys:
            db.set(k, b"v")
    assert [k for k, _ in sq.iterate(b"x\xff")] == \
        [k for k, _ in mem.iterate(b"x\xff")] == \
        sorted([b"x\xff" + b"\xff" * 18, b"x\xff\x01"])
    sq.close()


# -- wal oversize frame ------------------------------------------------------

def test_wal_rejects_oversized_frame_at_write_time(tmp_path):
    wal = WAL(str(tmp_path / "wal"))
    with pytest.raises(ValueError, match="exceeds"):
        wal.save({"type": "part", "data": "ab" * (3 << 20)})
    # WAL still readable afterwards
    wal.save({"type": "ok"})
    wal.close()
    wal2 = WAL(str(tmp_path / "wal"))
    assert [m.msg["type"] for m in wal2.all_messages()] == \
        ["endheight", "ok"]
    wal2.close()
