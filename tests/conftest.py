"""Test configuration: force an 8-device virtual CPU mesh.

Tests must not depend on real TPU hardware; multi-chip sharding paths are
exercised on a virtual CPU mesh exactly as the driver's dryrun does.
This must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
