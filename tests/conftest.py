"""Test configuration: force an 8-device virtual CPU mesh.

Tests must not depend on real TPU hardware; multi-chip sharding paths
are exercised on a virtual CPU mesh exactly as the driver's dryrun does.
This is also the CI multi-device story (ISSUE 6): every tier-1 run gets
`--xla_force_host_platform_device_count=8` (override via
TM_TPU_MESH_FORCE_HOST_DEVICES, the same knob bench.py's mesh arms
use), so the shard_map/NamedSharding code paths run on 1-core hosts on
every push — 8 covers the 2- and 4-wide sub-meshes the mesh tests also
exercise. Only tests that explicitly build a mesh pay a sharded
compile; TM_TPU_MESH defaults to "off" below so nothing else does.

On hosts where a TPU PJRT plugin is registered from sitecustomize (the
axon tunnel pins JAX_PLATFORMS=axon before any of our code runs), env
vars alone are too late — jax.config already captured them. The backend
*client* however is not created until the first jax.devices() call, so
steering jax.config here (before any test imports jax symbols that touch
a backend) still lands us on an 8-device virtual CPU platform.

The persistent XLA compilation cache is deliberately OFF here: making a
CPU executable serializable forces XLA:CPU through its AOT pipeline,
which for the 8-way SPMD merkle program (shard_map + all_gather) takes
>400s vs 32s for the plain JIT compile — the cache turns a one-minute
suite warmup into a hang. Within one pytest process each kernel shape
compiles once anyway.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f
          and "xla_backend_optimization_level" not in f]
_n_dev = (os.environ.get("TM_TPU_MESH_FORCE_HOST_DEVICES") or "8").strip()
_flags.append(f"--xla_force_host_platform_device_count={_n_dev}")
# the suite is COMPILE-bound on this 1-core host (the interpreted pallas
# kernel alone costs ~4 min at full opt); O0 keeps semantics, cuts ~30%
if not os.environ.get("TM_TEST_NO_O0"):
    _flags.append("--xla_backend_optimization_level=0")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
# default_verifier()'s mesh="auto" would see the 8 virtual devices and
# add the 8-way sharded compile (minutes on this 1-core host) to EVERY
# test that does a batched verify; only the explicit mesh tests should
# pay that. They construct BatchVerifier(mesh=...) directly.
os.environ.setdefault("TM_TPU_MESH", "off")

import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

import jax  # noqa: E402  (after env setup, before any backend use)

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; the long chaos schedules (full
    # acceptance scenario, partition/byzantine sweeps) opt out with it
    config.addinivalue_line(
        "markers", "slow: long-running schedule, excluded from tier-1")


@pytest.fixture(autouse=True)
def _reset_fail_points():
    """Fail-point hooks are process-global; a test that set a callback,
    a programmatic target, or an armed named trigger and raised before
    clearing it would silently redirect the NEXT test's commits."""
    yield
    from tendermint_tpu.utils import fail
    fail.clear_callback()
    fail.set_target(None)
    fail.disarm_all()
    fail.reset()


@pytest.fixture(autouse=True)
def _no_leaked_tm_threads():
    """Leaktest (the reference runs fortytw2/leaktest on its goroutine
    code, glide.yaml:46-48): no framework-named thread created by a test
    may outlive it. Catches un-stopped tickers/reactors whose late fires
    log into torn-down streams — the round-2 'Logging error' class.

    Only tm-* names opt in; the process-wide verify fetch pool
    (tm-verify-fetch), the verifier coalescer dispatcher
    (tm-verify-coalesce — shared by the default verifier, daemon,
    idle-parked and self-reaping after 30s), and the introspection
    plane's singletons (tm-queue-watch / tm-prof-sampler — process-
    global daemons shared by every in-process node; tests that start
    them explicitly stop them via queues.reset()/profile.stop()) are
    deliberately long-lived and excluded."""
    before = {t.ident for t in threading.enumerate()}
    # a longer-scoped fixture (module-scoped node) legitimately keeps
    # respawning its threads (each ticker schedule is a fresh Timer
    # thread) — a name that was already live before the test is its
    before_names = {t.name for t in threading.enumerate()}

    def leaked():
        return [t.name for t in threading.enumerate()
                if t.ident not in before and t.is_alive()
                and t.name.startswith("tm-")
                and t.name not in before_names
                and not t.name.startswith("tm-verify-fetch")
                and not t.name.startswith("tm-verify-coalesce")
                and not t.name.startswith("tm-queue-watch")
                and not t.name.startswith("tm-prof-sampler")]

    yield
    deadline = time.monotonic() + 3.0
    while leaked() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not leaked(), f"leaked framework threads: {leaked()}"

# NOTE: no jax.devices() here — that would pay backend-client creation at
# collection time for every run, including pure-Python test files.
# tests/test_mesh.py asserts the 8-device CPU platform when it runs.
