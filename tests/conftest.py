"""Test configuration: force an 8-device virtual CPU mesh.

Tests must not depend on real TPU hardware; multi-chip sharding paths
are exercised on a virtual CPU mesh exactly as the driver's dryrun does.

On hosts where a TPU PJRT plugin is registered from sitecustomize (the
axon tunnel pins JAX_PLATFORMS=axon before any of our code runs), env
vars alone are too late — jax.config already captured them. The backend
*client* however is not created until the first jax.devices() call, so
steering jax.config here (before any test imports jax symbols that touch
a backend) still lands us on an 8-device virtual CPU platform.

Also enables a persistent XLA compilation cache so repeated test runs
skip the expensive CPU recompiles of the Ed25519 ladder.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/tm_tpu_xla"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax  # noqa: E402  (after env setup, before any backend use)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# NOTE: no jax.devices() here — that would pay backend-client creation at
# collection time for every run, including pure-Python test files.
# tests/test_mesh.py asserts the 8-device CPU platform when it runs.
