"""EvidencePool + EvidenceStore tests (models evidence/pool_test.go,
store_test.go)."""

import pytest

from tendermint_tpu.evidence import EvidencePool, EvidenceStore
from tendermint_tpu.state.state import make_genesis_state
from tendermint_tpu.state.validation import BlockValidationError
from tendermint_tpu.storage import MemDB
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator
from tendermint_tpu.types.vote import Vote, VoteType


CHAIN = "ev-test"


def make_state_and_keys(n=3):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id=CHAIN, genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10 + i)
                                 for i, k in enumerate(keys)])
    state = make_genesis_state(gen)
    state.last_block_height = 1  # evidence must be for height >= 1
    return state, keys


def make_duplicate_vote_evidence(key, height=1, good=True):
    pv = PrivValidator(LocalSigner(key))
    bid_a = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xab" * 32))
    bid_b = BlockID(b"\xbb" * 32, PartSetHeader(1, b"\xbc" * 32))
    votes = []
    for bid in (bid_a, bid_b):
        v = Vote(pv.address, 0, height, 0, 1000, VoteType.PREVOTE, bid)
        pv.last_height = 0  # reset double-sign guard between the two signs
        pv.last_round = -1
        pv.last_step = 0
        pv.sign_vote(CHAIN, v)
        votes.append(v)
    ev = DuplicateVoteEvidence(key.pubkey.ed25519, votes[0], votes[1])
    if not good:
        ev.vote_b.signature = b"\x00" * 64
    return ev


def test_store_add_pending_mark_committed():
    store = EvidenceStore(MemDB())
    _, keys = make_state_and_keys()
    ev = make_duplicate_vote_evidence(keys[0])
    assert store.add_new_evidence(ev, priority=10)
    assert not store.add_new_evidence(ev, priority=10)  # dup
    assert store.pending_evidence() == [ev]
    assert store.priority_evidence() == [ev]
    assert not store.is_committed(ev)
    store.mark_evidence_as_committed(ev)
    assert store.pending_evidence() == []
    assert store.priority_evidence() == []
    assert store.is_committed(ev)


def test_store_priority_order():
    store = EvidenceStore(MemDB())
    _, keys = make_state_and_keys(3)
    evs = [make_duplicate_vote_evidence(k) for k in keys]
    for ev, prio in zip(evs, (5, 50, 20)):
        store.add_new_evidence(ev, prio)
    assert store.priority_evidence() == [evs[1], evs[2], evs[0]]


def test_pool_verifies_and_prioritizes():
    state, keys = make_state_and_keys()
    pool = EvidencePool(EvidenceStore(MemDB()), state)
    ev = make_duplicate_vote_evidence(keys[2])  # power 12
    pool.add_evidence(ev)
    assert pool.pending_evidence() == [ev]
    assert pool.drain(timeout=0.1) == ev


def test_pool_rejects_bad_signature():
    state, keys = make_state_and_keys()
    pool = EvidencePool(EvidenceStore(MemDB()), state)
    with pytest.raises(BlockValidationError):
        pool.add_evidence(make_duplicate_vote_evidence(keys[0], good=False))
    assert pool.pending_evidence() == []


def test_pool_rejects_non_validator_and_stale():
    state, keys = make_state_and_keys()
    stranger = PrivKey.generate(b"\x77" * 32)
    pool = EvidencePool(EvidenceStore(MemDB()), state)
    with pytest.raises(BlockValidationError):
        pool.add_evidence(make_duplicate_vote_evidence(stranger))
    # stale: beyond max_age
    state.last_block_height = \
        state.consensus_params.evidence.max_age + 5
    with pytest.raises(BlockValidationError):
        pool.add_evidence(make_duplicate_vote_evidence(keys[0], height=1))


def test_pool_update_marks_committed_and_blocks_readd():
    state, keys = make_state_and_keys()
    pool = EvidencePool(EvidenceStore(MemDB()), state)
    ev = make_duplicate_vote_evidence(keys[0])
    pool.add_evidence(ev)

    class FakeBlock:
        class evidence:
            evidence = [ev]

    pool.update(FakeBlock())
    assert pool.pending_evidence() == []
    # re-adding committed evidence is a silent no-op (in-flight gossip
    # of just-committed evidence is a normal race, not misbehavior) —
    # it must neither raise nor re-enter the pending set
    pool.add_evidence(ev)
    assert pool.pending_evidence() == []
