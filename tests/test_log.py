"""Structured logging layer (utils/log.py) — tmlibs/log parity surface."""

import io
import logging

from tendermint_tpu.utils import log as tmlog


def capture():
    buf = io.StringIO()
    tmlog.setup_logging("debug", stream=buf)
    return buf


def test_kv_format_and_levels():
    buf = capture()
    lg = tmlog.get_logger("consensus")
    lg.info("entering new round", height=5, round=0)
    lg.error("bad vote", peer="abc")
    lg.debug("gossip detail", part=3)
    out = buf.getvalue()
    lines = out.strip().split("\n")
    assert lines[0].startswith("I[")
    assert "entering new round" in lines[0]
    assert "module=consensus" in lines[0]
    assert "height=5" in lines[0] and "round=0" in lines[0]
    assert lines[1].startswith("E[") and "peer=abc" in lines[1]
    assert lines[2].startswith("D[") and "part=3" in lines[2]


def test_with_fields_sticky():
    buf = capture()
    lg = tmlog.get_logger("p2p").with_fields(peer="deadbeef")
    lg.info("msg one")
    lg.info("msg two", ch=0x20)
    out = buf.getvalue()
    assert out.count("peer=deadbeef") == 2
    assert "ch=32" in out


def test_per_module_level_spec():
    buf = io.StringIO()
    # config/config.go:114-style spec: p2p silenced to error, default info
    tmlog.setup_logging("p2p:error,*:info", stream=buf)
    tmlog.get_logger("p2p").info("chatty p2p")
    tmlog.get_logger("p2p").error("p2p failure")
    tmlog.get_logger("state").info("state progress")
    tmlog.get_logger("state").debug("state detail")
    out = buf.getvalue()
    assert "chatty p2p" not in out
    assert "p2p failure" in out
    assert "state progress" in out
    assert "state detail" not in out
    # restore default for other tests
    tmlog.setup_logging("info")


def test_bytes_rendered_as_hex_prefix():
    buf = capture()
    tmlog.get_logger("consensus").info("commit", hash=b"\xab\xcd" * 16)
    assert "hash=abcdabcdabcdabcd" in buf.getvalue()


def test_consensus_state_log_hooked():
    """VERDICT round-1: ConsensusState._log was `pass`; errors must now
    reach the log stream."""
    from tendermint_tpu.consensus.state import ConsensusState
    buf = capture()
    cs = ConsensusState.__new__(ConsensusState)  # no full wiring needed
    cs.logger = tmlog.get_logger("consensus")
    from tendermint_tpu.consensus.rstate import RoundState
    cs.rs = RoundState(height=7)
    cs._log("something went wrong")
    out = buf.getvalue()
    assert "something went wrong" in out and "height=7" in out
