"""Structured logging layer (utils/log.py) — tmlibs/log parity surface."""

import io
import logging

from tendermint_tpu.utils import log as tmlog


def capture():
    buf = io.StringIO()
    tmlog.setup_logging("debug", stream=buf)
    return buf


def test_kv_format_and_levels():
    buf = capture()
    lg = tmlog.get_logger("consensus")
    lg.info("entering new round", height=5, round=0)
    lg.error("bad vote", peer="abc")
    lg.debug("gossip detail", part=3)
    out = buf.getvalue()
    lines = out.strip().split("\n")
    assert lines[0].startswith("I[")
    assert "entering new round" in lines[0]
    assert "module=consensus" in lines[0]
    assert "height=5" in lines[0] and "round=0" in lines[0]
    assert lines[1].startswith("E[") and "peer=abc" in lines[1]
    assert lines[2].startswith("D[") and "part=3" in lines[2]


def test_with_fields_sticky():
    buf = capture()
    lg = tmlog.get_logger("p2p").with_fields(peer="deadbeef")
    lg.info("msg one")
    lg.info("msg two", ch=0x20)
    out = buf.getvalue()
    assert out.count("peer=deadbeef") == 2
    assert "ch=32" in out


def test_per_module_level_spec():
    buf = io.StringIO()
    # config/config.go:114-style spec: p2p silenced to error, default info
    tmlog.setup_logging("p2p:error,*:info", stream=buf)
    tmlog.get_logger("p2p").info("chatty p2p")
    tmlog.get_logger("p2p").error("p2p failure")
    tmlog.get_logger("state").info("state progress")
    tmlog.get_logger("state").debug("state detail")
    out = buf.getvalue()
    assert "chatty p2p" not in out
    assert "p2p failure" in out
    assert "state progress" in out
    assert "state detail" not in out
    # restore default for other tests
    tmlog.setup_logging("info")


def test_bytes_rendered_as_hex_prefix():
    buf = capture()
    tmlog.get_logger("consensus").info("commit", hash=b"\xab\xcd" * 16)
    assert "hash=abcdabcdabcdabcd" in buf.getvalue()


def test_consensus_state_log_hooked():
    """VERDICT round-1: ConsensusState._log was `pass`; errors must now
    reach the log stream."""
    from tendermint_tpu.consensus.state import ConsensusState
    buf = capture()
    cs = ConsensusState.__new__(ConsensusState)  # no full wiring needed
    cs.logger = tmlog.get_logger("consensus")
    from tendermint_tpu.consensus.rstate import RoundState
    cs.rs = RoundState(height=7)
    cs._log("something went wrong")
    out = buf.getvalue()
    assert "something went wrong" in out and "height=7" in out


def test_global_bound_context_in_every_line():
    """ISSUE 8 satellite: process-global bind() (node.py binds node=<id>)
    rides along on every tm.* line, lowest precedence."""
    saved = tmlog.bound()
    tmlog.unbind(*saved)  # a Node built by an earlier test binds node=
    buf = capture()
    tmlog.bind(node="deadbeef", height=3)
    try:
        tmlog.get_logger("consensus").info("entering new round")
        tmlog.get_logger("p2p").info("peer up")
        out = buf.getvalue()
        assert out.count("node=deadbeef") == 2
        assert out.count("height=3") == 2
        # explicit kv and logger fields override the global context
        buf2 = capture()
        tmlog.get_logger("consensus").info("override", height=9)
        assert "height=9" in buf2.getvalue()
        assert "height=3" not in buf2.getvalue()
    finally:
        tmlog.unbind("node", "height")
    buf3 = capture()
    tmlog.get_logger("consensus").info("after unbind")
    assert "node=" not in buf3.getvalue()
    assert tmlog.bound() == {}
    tmlog.bind(**saved)


def test_consensus_logger_rebinds_height_round_per_step():
    """grep-by-height: every consensus line after a step change carries
    that step's height/round without the call site passing them."""
    from tendermint_tpu.consensus.rstate import RoundState
    from tendermint_tpu.consensus.state import ConsensusState
    buf = capture()
    cs = ConsensusState.__new__(ConsensusState)
    cs._logger_base = tmlog.get_logger("consensus")
    cs.logger = cs._logger_base
    cs.rs = RoundState(height=17)
    cs.rs.round = 2
    cs.n_steps = 0
    cs.replay_mode = True          # skip WAL/publish/broadcast wiring
    from tendermint_tpu.storage.wal import NilWAL
    cs.wal = NilWAL()
    cs.event_bus = None
    cs.broadcast_hooks = []
    cs._step_open = None
    cs._publish = lambda *a, **k: None
    cs._broadcast = lambda *a, **k: None
    cs._new_step()
    cs.logger.info("plain call site")
    out = buf.getvalue()
    assert "height=17" in out and "round=2" in out
