"""Deployment-driver smoke (ISSUE 19 satellite 5): a declarative
Topology becomes a real 3-process net — two validators + one keyless
edge replica over real TCP — which boots, commits, certifies, serves a
client-verified proven read, survives a process crash via the
supervisor, and tears down leak-clean."""

import os
import time

import pytest

from tendermint_tpu.serving import Deployment, Topology


def _wait(cond, timeout=90.0, step=0.3):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_deployment_smoke_boot_certify_read_crash_restart(tmp_path):
    topo = Topology(kind="validators", n_validators=2, n_replicas=1,
                    chain_id="deploy-smoke", max_seconds=300,
                    env={"TM_TPU_STATE_TREE": "on"})
    out = str(tmp_path / "net")
    d = Deployment(topo, out, max_restarts=2)

    # the trust-model floor on disk: replicas carry NO signing key
    for spec in d.specs:
        pv = os.path.join(spec.home, "config", "priv_validator.json")
        assert os.path.exists(pv) == (spec.kind == "validator"), \
            spec.name

    d.start()
    try:
        # validators commit 3 heights over real sockets
        d.wait_height(3, timeout_s=120)

        # the replica (fast-sync follower) certifies from its own
        # stores and stamps every response with honest staleness
        rep = d.clients(kind="replica")[0]

        def certified(h):
            try:
                return rep.call("status")["edge"][
                    "certified_height"] >= h
            except OSError:
                return False
        assert _wait(lambda: certified(2)), d.log_tail("replica0")

        # write through a validator, read PROVEN through the replica,
        # verify client-side from the genesis valset — zero trust in
        # the replica (every replica-served read is verifiable)
        val = d.clients(kind="validator")[0]
        val.call("broadcast_tx_commit", tx=b"dk=dv".hex())
        assert _wait(lambda: certified(
            val.call("status")["latest_block_height"])), \
            d.log_tail("replica0")
        doc = rep.call("replica_read", key=b"dk".hex())
        assert bytes.fromhex(doc["value"]) == b"dv"
        assert doc["value_proof"] is not None
        assert doc["edge"]["certified_height"] >= doc["height"]
        from tendermint_tpu.lite.certifier import ContinuousCertifier
        from tendermint_tpu.shard.reads import CertifiedReader, _genesis_valset
        from tendermint_tpu.types import GenesisDoc
        gen = GenesisDoc.load(os.path.join(
            d.spec("replica0").home, "config", "genesis.json"))
        cert = ContinuousCertifier(gen.chain_id, _genesis_valset(gen))
        CertifiedReader.verify(doc, cert)
        assert cert.certified_height >= doc["height"]

        # healthz folds the edge verdict for load balancers
        hz = rep.call("healthz")
        assert hz["edge"]["role"] == "replica"
        assert hz["edge"]["lag"] <= hz["edge"]["max_lag"]

        # crash/restart: hard-kill the replica; the supervisor
        # respawns it (same argv) and it certifies again
        d.kill("replica0")
        assert _wait(lambda: d.restarts.get("replica0", 0) >= 1,
                     timeout=30)
        assert _wait(lambda: d.alive("replica0"), timeout=30)
        assert _wait(lambda: certified(2), timeout=90), \
            d.log_tail("replica0")
        assert not d.dead
    finally:
        d.stop()

    # leak-clean teardown: no live processes, logs closed, tree gone
    assert all(p.poll() is not None for p in d._procs.values())
    assert not d._logs
    assert not os.path.exists(out)
