"""p2p/trust.py — rollover/decay math + store persistence (ISSUE 13
satellite: the trust plane gained enforcement, so its scoring math is
now load-bearing and needs direct coverage)."""

import math

from tendermint_tpu.p2p.trust import (
    INTEGRAL_WEIGHT,
    MAX_HISTORY,
    PROPORTIONAL_WEIGHT,
    TrustMetric,
    TrustMetricStore,
)
from tendermint_tpu.storage import MemDB


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_metric(interval_s=10.0, history=None):
    clk = FakeClock()
    return TrustMetric(interval_s=interval_s, history=history,
                       now_fn=clk), clk


# ------------------------------------------------------------- rollover


def test_roll_closes_interval_into_history_newest_first():
    m, clk = make_metric()
    m.good_events(3)
    m.bad_events(1)
    clk.advance(10.0)
    m.good_events(1)          # triggers the roll of the prior interval
    assert m.history == [0.75]
    # events after the roll belong to the fresh interval
    assert (m.good, m.bad) == (1.0, 0.0)
    clk.advance(10.0)
    m.bad_events(1)
    assert m.history == [1.0, 0.75]  # newest first


def test_roll_covers_multiple_elapsed_intervals():
    m, clk = make_metric()
    m.bad_events(1)
    clk.advance(35.0)          # 3 full intervals elapsed
    m.good_events(1)
    # interval 1 rolled its 0.0 ratio; the two EMPTY elapsed intervals
    # rolled the benefit-of-the-doubt 1.0
    assert m.history == [1.0, 1.0, 0.0]


def test_history_bounded_at_max():
    m, clk = make_metric()
    for i in range(MAX_HISTORY + 5):
        m.good_events(1)
        clk.advance(10.0)
    m.good_events(1)
    assert len(m.history) == MAX_HISTORY


def test_history_value_fades_with_inverse_sqrt_age():
    m, _ = make_metric(history=[0.0, 1.0, 1.0])
    w = [1.0 / math.sqrt(i + 1) for i in range(3)]
    expected = (0.0 * w[0] + 1.0 * w[1] + 1.0 * w[2]) / sum(w)
    assert abs(m._history_value() - expected) < 1e-12
    # the same ratios with the bad interval OLDEST score higher: age
    # fades influence
    m2, _ = make_metric(history=[1.0, 1.0, 0.0])
    assert m2._history_value() > m._history_value()


# ----------------------------------------------------------- trust_value


def test_trust_value_downswing_penalty_only_punishes_drops():
    # falling ratio: current interval much worse than history
    falling, _ = make_metric(history=[1.0] * 4)
    falling.good_events(1)
    falling.bad_events(9)
    r, h = 0.1, 1.0
    d = (r - h) * PROPORTIONAL_WEIGHT
    expected = PROPORTIONAL_WEIGHT * r + INTEGRAL_WEIGHT * h + d
    assert abs(falling.trust_value() - expected) < 1e-12

    # rising ratio: no derivative bonus, just the weighted sum
    rising, _ = make_metric(history=[0.5] * 4)
    rising.good_events(10)
    expected_rising = PROPORTIONAL_WEIGHT * 1.0 + INTEGRAL_WEIGHT * 0.5
    assert abs(rising.trust_value() - expected_rising) < 1e-12


def test_trust_value_clamped_to_unit_interval():
    m, _ = make_metric(history=[0.0] * MAX_HISTORY)
    m.bad_events(100)
    assert m.trust_value() == 0.0
    fresh, _ = make_metric()
    fresh.good_events(100)
    assert fresh.trust_value() == 1.0
    assert fresh.trust_score() == 100


def test_trust_score_floor_without_history_is_twenty():
    """With an empty history the integral term's benefit of the doubt
    floors the score at 20 — the reason the ban threshold defaults
    ABOVE 20 (a fresh peer's first garbage burst must be bannable)."""
    m, _ = make_metric()
    m.bad_events(1000)
    assert m.trust_score() == 20


# ------------------------------------------------------------ persistence


def test_to_obj_folds_open_interval_only_when_it_saw_events():
    m, _ = make_metric(history=[0.5])
    m.good_events(1)
    m.bad_events(1)
    assert TrustMetric.from_obj(m.to_obj()).history == [0.5, 0.5]
    # an EMPTY open interval must not launder a synthetic 1.0 in
    empty, _ = make_metric(history=[0.25])
    assert TrustMetric.from_obj(empty.to_obj()).history == [0.25]


def test_store_round_trip_preserves_per_peer_history():
    db = MemDB()
    store = TrustMetricStore(db, interval_s=10.0)
    a = store.get_metric("peer-a")
    a.good_events(3)
    a.bad_events(1)
    store.get_metric("peer-b").bad_events(2)
    store.save()

    loaded = TrustMetricStore(db, interval_s=10.0)
    ra = loaded.get_metric("peer-a")
    rb = loaded.get_metric("peer-b")
    assert ra.history == [0.75]       # open interval folded on save
    assert rb.history == [0.0]
    assert ra.interval_s == 10.0
    # unknown peers start fresh, not poisoned by neighbors
    assert loaded.get_metric("peer-c").history == []


def test_store_disconnect_persists():
    db = MemDB()
    store = TrustMetricStore(db)
    store.get_metric("p").bad_events(4)
    store.peer_disconnected("p")
    assert TrustMetricStore(db).get_metric("p").history == [0.0]
