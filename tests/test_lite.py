"""Lite client tests (models lite/*_test.go): static/dynamic/inquiring
certifiers, bisection through valset changes, providers, batch chain
certification, and the proof-checking proxy against a live RPC node."""

import pytest

from tendermint_tpu.lite import (
    CertificationError,
    ContinuousCertifier,
    DynamicCertifier,
    FileProvider,
    FullCommit,
    InquiringCertifier,
    MemProvider,
    SignedHeader,
    StaticCertifier,
    ValidatorsChangedError,
    certify_chain,
)
from tendermint_tpu.types import PrivKey
from tendermint_tpu.types.block import BlockID, Commit, Header, PartSetHeader
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote, VoteType

CHAIN = "lite-test"


class ValKeys:
    """Ordered keys matching a ValidatorSet (lite test helper, the
    reference's ValKeys in lite/helpers.go)."""

    def __init__(self, n, power=10, seed_base=1):
        self.keys = [PrivKey.generate(bytes([seed_base + i]) * 32)
                     for i in range(n)]
        self.power = power
        self.valset = ValidatorSet(
            [Validator(k.pubkey.ed25519, power) for k in self.keys])

    def sign_header(self, height, app_hash=b"\x01" * 32,
                    first=0, last=None) -> FullCommit:
        """FullCommit for a synthetic header signed by keys[first:last]."""
        header = Header(chain_id=CHAIN, height=height, time_ns=height,
                        validators_hash=self.valset.hash(),
                        app_hash=app_hash)
        bid = BlockID(header.hash(), PartSetHeader(1, b"\x22" * 32))
        precommits = [None] * len(self.keys)
        last = len(self.keys) if last is None else last
        # sorted-by-address order must match the valset's
        by_addr = {v.address: i for i, v in
                   enumerate(self.valset.validators)}
        for k in self.keys[first:last]:
            idx = by_addr[k.pubkey.address]
            v = Vote(k.pubkey.address, idx, height, 0, height,
                     VoteType.PRECOMMIT, bid)
            pv = PrivValidator(LocalSigner(k))
            pv.sign_vote(CHAIN, v)
            precommits[idx] = v
        return FullCommit(SignedHeader(header, Commit(bid, precommits), bid),
                          self.valset)


def test_static_certifier_accepts_and_rejects():
    vk = ValKeys(4)
    cert = StaticCertifier(CHAIN, vk.valset)
    cert.certify(vk.sign_header(5))
    # only 2 of 4 signed: not +2/3
    with pytest.raises(CertificationError):
        cert.certify(vk.sign_header(6, last=2))
    # different valset entirely
    other = ValKeys(4, seed_base=50)
    with pytest.raises(CertificationError):
        cert.certify(other.sign_header(7))
    # tampered header (valset hash mismatch caught structurally)
    fc = vk.sign_header(8)
    fc.signed_header.header.app_hash = b"\x99" * 32
    with pytest.raises(CertificationError):
        cert.certify(fc)


def test_dynamic_certifier_updates_through_change():
    vk = ValKeys(4)
    cert = DynamicCertifier(CHAIN, vk.valset, height=1)
    cert.certify(vk.sign_header(2))
    # new set: 3 of the old 4 plus one new key — overlap way above +1/3
    vk2 = ValKeys(4)
    vk2.keys = vk.keys[:3] + [PrivKey.generate(b"\x63" * 32)]
    vk2.valset = ValidatorSet(
        [Validator(k.pubkey.ed25519, 10) for k in vk2.keys])
    fc = ValKeysView(vk2).sign_header(10)
    cert.update(fc)
    assert cert.last_height == 10
    cert.certify(ValKeysView(vk2).sign_header(11))
    # old-set certify now fails
    with pytest.raises(CertificationError):
        cert.certify(vk.sign_header(12))


class ValKeysView(ValKeys):
    """Wrap an existing ValKeys-like object without re-generating keys."""

    def __init__(self, src):
        self.keys = src.keys
        self.power = src.power
        self.valset = src.valset


def test_dynamic_update_rejects_insufficient_old_overlap():
    vk = ValKeys(4)
    cert = DynamicCertifier(CHAIN, vk.valset, height=1)
    stranger = ValKeys(4, seed_base=80)  # zero overlap with trusted set
    with pytest.raises(CertificationError):
        cert.update(stranger.sign_header(10))


def test_inquiring_certifier_bisects():
    """Trust bridges a big valset jump via the provider's intermediate
    commits (lite/inquiring_certifier.go:137-163)."""
    vk1 = ValKeys(4)                       # heights 1-10
    vk2 = ValKeysView(vk1)                 # rotate 1 key at height 10
    vk2 = type("VK", (ValKeysView,), {})(vk1)
    vk2.keys = vk1.keys[:3] + [PrivKey.generate(b"\x70" * 32)]
    vk2.valset = ValidatorSet(
        [Validator(k.pubkey.ed25519, 10) for k in vk2.keys])
    # vk3 rotates ONE MORE key: 3/4 overlap with vk2 (> 2/3, bridgeable
    # under the v0.16 VerifyCommitAny rule) but only 2/4 with vk1
    # (<= 2/3) -> direct update from height 1 must fail
    vk3 = type("VK", (ValKeysView,), {})(vk2)
    vk3.keys = vk2.keys[:2] + \
        [vk2.keys[3], PrivKey.generate(b"\x71" * 32)]
    vk3.valset = ValidatorSet(
        [Validator(k.pubkey.ed25519, 10) for k in vk3.keys])

    provider = MemProvider()
    provider.store_commit(vk2.sign_header(10))   # the bridge commit
    provider.store_commit(vk3.sign_header(20))

    trusted = vk1.sign_header(1)
    cert = InquiringCertifier(CHAIN, trusted, provider)
    # direct update 1 -> 25 fails (vk3 overlaps vk1 by only 2/4 power);
    # bisection finds height 10 (vk2: 3/4 overlap), then 20, then 25
    cert.certify(vk3.sign_header(25))
    assert cert.last_height >= 20


def _derive(vk, keys):
    """ValKeys view over an explicit key list (churn helper)."""
    out = ValKeysView(vk)
    out.keys = keys
    out.valset = ValidatorSet(
        [Validator(k.pubkey.ed25519, 10) for k in keys])
    return out


def test_continuous_certifier_tracks_consecutive_deltas():
    """ISSUE 11 satellite: sequential certification across >=3
    consecutive valset deltas — join, leave, and power change, each
    its own height — with unchanged heights certified statically in
    between. The certifier must end trusting the final set, having
    crossed every delta."""
    vk1 = ValKeys(4)
    extra = PrivKey.generate(b"\x41" * 32)
    vk2 = _derive(vk1, vk1.keys + [extra])          # height 3: join
    vk3 = ValKeysView(vk2)                          # height 4: stake
    vk3.valset = ValidatorSet(
        [Validator(k.pubkey.ed25519, 20 if i == 0 else 10)
         for i, k in enumerate(vk2.keys)])
    vk4 = _derive(vk3, vk2.keys[1:])                # height 5: leave

    cert = ContinuousCertifier(CHAIN, vk1.valset)
    chain = [(1, vk1), (2, vk1), (3, vk2), (4, vk3), (5, vk4), (6, vk4)]
    for h, vk in chain:
        cert.advance(vk.sign_header(h))
    assert cert.certified_height == 6
    assert cert.updates == 3
    assert cert.static_certified == 3
    assert cert.validators.hash() == vk4.valset.hash()
    # stale or skipped heights are refused outright — continuity is
    # the whole safety argument
    with pytest.raises(CertificationError, match="expects height"):
        cert.advance(vk4.sign_header(6))
    with pytest.raises(CertificationError, match="expects height"):
        cert.advance(vk4.sign_header(9))


def test_continuous_certifier_quorum_sparse_commit_over_churn():
    """The realistic case that breaks naive overlap counting: the
    commit carries only a +2/3 QUORUM of signatures (not everyone),
    at the height where a validator joined. Sequential certification
    must still succeed — the signing set's own +2/3 plus >1/3 trusted
    endorsement are both satisfiable from a sparse commit."""
    vk1 = ValKeys(4)
    extra = PrivKey.generate(b"\x42" * 32)
    vk2 = _derive(vk1, vk1.keys + [extra])
    cert = ContinuousCertifier(CHAIN, vk1.valset)
    cert.advance(vk1.sign_header(1))
    # 4 of 5 sign (40/50 > 2/3 of new set; all 4 are trusted members
    # -> endorsement 40/40 > 1/3 of trusted power)
    cert.advance(vk2.sign_header(2, last=4))
    assert cert.updates == 1
    assert cert.certified_height == 2


def test_continuous_certifier_loud_on_large_power_move():
    """Loud-failure coverage (ISSUE 11 satellite): transitions that
    move too much power between trusted heights must raise, not
    quietly adopt the new set.

    (a) one delta replacing >2/3 of the trusted power: the trusted
        set's endorsement among the signers falls to 1/3 or less ->
        CertificationError from the continuous tracker;
    (b) a JUMP between trusted heights where >1/3 of the power
        changed: DynamicCertifier.update's strict v0.16 rule refuses
        (old-set overlap needs >2/3), and the continuous tracker
        refuses the jump outright."""
    # (a) 3 of 4 equal-power validators replaced in one height
    vk1 = ValKeys(4)
    vk_swap = _derive(vk1, vk1.keys[:1]
                      + [PrivKey.generate(bytes([0x50 + i]) * 32)
                         for i in range(3)])
    cert = ContinuousCertifier(CHAIN, vk1.valset)
    cert.advance(vk1.sign_header(1))
    with pytest.raises(CertificationError,
                       match="insufficient trusted-set endorsement"):
        cert.advance(vk_swap.sign_header(2))
    # trust did NOT advance past the failed height
    assert cert.certified_height == 1
    assert cert.validators.hash() == vk1.valset.hash()

    # (b) 2 of 4 rotated between height 1 and 10 (50% of power — more
    # than 1/3): the jump bridge must refuse
    vk_jump = _derive(vk1, vk1.keys[:2]
                      + [PrivKey.generate(bytes([0x60 + i]) * 32)
                         for i in range(2)])
    dyn = DynamicCertifier(CHAIN, vk1.valset, height=1)
    with pytest.raises(CertificationError):
        dyn.update(vk_jump.sign_header(10))


def test_providers_roundtrip(tmp_path):
    vk = ValKeys(3)
    mem = MemProvider()
    f = FileProvider(str(tmp_path / "certs"))
    for p in (mem, f):
        p.store_commit(vk.sign_header(5))
        p.store_commit(vk.sign_header(9))
        assert p.get_by_height(9).height == 9
        assert p.get_by_height(7).height == 5   # largest <= 7
        assert p.get_by_height(4) is None
        assert p.latest_commit().height == 9
    # file provider round-trips through JSON intact
    fc = f.get_by_height(9)
    StaticCertifier(CHAIN, vk.valset).certify(fc)


def test_certify_chain_batches_and_detects_forgery():
    vk = ValKeys(4)
    chain = [vk.sign_header(h) for h in range(1, 9)]
    certify_chain(CHAIN, chain)  # one pooled batch

    # forge one signature mid-chain
    bad = [vk.sign_header(h) for h in range(1, 9)]
    victim = bad[4].signed_header.commit.precommits[1]
    victim.signature = b"\x00" * 64
    with pytest.raises(CertificationError) as e:
        certify_chain(CHAIN, bad)
    assert "height 5" in str(e.value)

    # valset discontinuity split point surfaces as ValidatorsChanged
    other = ValKeys(4, seed_base=40)
    mixed = chain[:3] + [other.sign_header(4)]
    with pytest.raises(ValidatorsChangedError):
        certify_chain(CHAIN, mixed)


def test_secure_proxy_against_live_node():
    """SecureClient verifies blocks/commits/txs from a real RPC node."""
    import time
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.lite.provider import HTTPProvider
    from tendermint_tpu.node import Node
    from tendermint_tpu.rpc import JSONRPCClient
    from tendermint_tpu.lite import SecureClient
    from tendermint_tpu.types import GenesisDoc, GenesisValidator

    key = PrivKey.generate(b"\x0c" * 32)
    gen = GenesisDoc(chain_id="lite-live", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cfg = make_test_config("")
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.tx_index.index_all_tags = True
    node = Node(cfg, gen,
                priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True, with_rpc=True)
    node.start()
    try:
        host, port = node.rpc_address
        rpc = JSONRPCClient(f"http://{host}:{port}")
        rpc.call("broadcast_tx_commit", tx=b"lite=proof")
        deadline = time.monotonic() + 30
        while node.height < 3 and time.monotonic() < deadline:
            time.sleep(0.05)

        provider = HTTPProvider(rpc)
        trusted = provider.get_by_height(1)
        assert trusted is not None
        cert = InquiringCertifier("lite-live", trusted, MemProvider())
        sc = SecureClient(rpc, cert)
        blk = sc.block(2)
        assert blk["block"]["header"]["height"] == 2
        cm = sc.commit(2)
        assert cm["certified"]
        vals = sc.validators(2)
        assert vals["certified"]
        # tx with verified merkle proof
        import hashlib
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                res = sc.tx(hashlib.sha256(b"lite=proof").digest())
                break
            except Exception:
                time.sleep(0.1)
        else:
            pytest.fail("tx never certified")
        assert bytes.fromhex(res["tx"]) == b"lite=proof"
    finally:
        node.stop()
