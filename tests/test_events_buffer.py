"""Bounded per-subscriber event buffers (VERDICT r5 item 8): a slow
subscriber loses oldest history — counted, surfaced — never the newest
event a waiter like broadcast_tx_commit is blocked on."""

import queue

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.types import events


def test_full_buffer_evicts_oldest_and_counts():
    bus = events.EventBus()
    sub = bus.subscribe("slow", "tm.event = 'Vote'", capacity=3)
    for i in range(10):
        bus.publish(events.EventVote, {"n": i})
    assert sub.dropped == 7
    assert bus.dropped_total == 7
    got = [sub.get_nowait().data["n"] for _ in range(3)]
    assert got == [7, 8, 9]  # newest retained, oldest evicted
    assert sub.get_nowait() is None


def test_slow_subscriber_keeps_newest_eventtx():
    """The broadcast_tx_commit contract: after any amount of backlog on
    a tiny buffer, the LAST published EventTx is still deliverable —
    eviction is oldest-first, so the event the RPC waiter needs can
    never be displaced by history it doesn't care about."""
    bus = events.EventBus()
    sub = bus.subscribe("waiter", "tm.event = 'Tx'", capacity=2)
    for i in range(50):
        bus.publish_tx(height=1, index=i, tx=b"tx-%d" % i, result=None)
    last = None
    while True:
        item = sub.get_nowait()
        if item is None:
            break
        last = item
    assert last is not None
    assert last.data["index"] == 49
    assert sub.dropped == 48


def test_dropped_total_metric_moves():
    was = telemetry.enabled()
    telemetry.set_enabled(True)
    try:
        before = telemetry.value("event_dropped_total") or 0
        bus = events.EventBus()
        bus.subscribe("s", "tm.event = 'Vote'", capacity=1)
        for i in range(5):
            bus.publish(events.EventVote, {"n": i})
        assert (telemetry.value("event_dropped_total") or 0) == before + 4
    finally:
        telemetry.set_enabled(was)


def test_get_blocks_with_timeout_and_raises_empty():
    bus = events.EventBus()
    sub = bus.subscribe("s", "tm.event = 'Vote'")
    with pytest.raises(queue.Empty):
        sub.get(timeout=0.05)
    bus.publish(events.EventVote, {"n": 1})
    assert sub.get(timeout=1).data["n"] == 1


def test_queue_facade_back_compat():
    """Callers that drained sub.queue directly keep working."""
    bus = events.EventBus()
    sub = bus.subscribe("s", "tm.event = 'Vote'")
    assert sub.queue.empty()
    bus.publish(events.EventVote, {"n": 1})
    assert not sub.queue.empty()
    assert sub.queue.get_nowait().data["n"] == 1
