"""Data-model semantics tests (modeled on the reference's types/ tests:
vote_set_test.go quorum/conflicts, validator_set_test.go rotation,
priv_validator_test.go double-sign protection)."""

import os
import tempfile

import numpy as np
import pytest

from tendermint_tpu.models.verifier import BatchVerifier
from tendermint_tpu.types import (
    Block, BlockID, Commit, ConsensusParams, DuplicateVoteEvidence, GenesisDoc,
    GenesisValidator, Header, PartSetHeader, PrivKey, PrivValidatorFile,
    Proposal, Validator, ValidatorSet, Vote, VoteSet)
from tendermint_tpu.types.block import Data
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.priv_validator import DoubleSignError
from tendermint_tpu.types.vote import VoteType
from tendermint_tpu.types.vote_set import ConflictingVoteError
from tendermint_tpu.types.events import EventBus, Query

CHAIN = "test-chain"
PYV = BatchVerifier("python")


def make_valset(n, power=10):
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    vals = [Validator(p.pubkey.ed25519, power) for p in privs]
    vs = ValidatorSet(vals)
    # order privs to match sorted validator order
    by_addr = {p.pubkey.address: p for p in privs}
    privs_sorted = [by_addr[v.address] for v in vs.validators]
    return vs, privs_sorted


def make_block_id(tag=b"blk"):
    return BlockID(hash=tag.ljust(32, b"\0"), parts=PartSetHeader(1, b"p" * 32))


def signed_vote(priv, idx, height, round_, type_, block_id, ts=1000):
    v = Vote(validator_address=priv.pubkey.address, validator_index=idx,
             height=height, round=round_, timestamp_ns=ts, type=type_,
             block_id=block_id)
    v.signature = priv.sign(v.sign_bytes(CHAIN))
    return v


# ---------------------------------------------------------------- VoteSet --

def test_vote_set_quorum():
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)
    for i in range(2):
        assert vset.add_vote(signed_vote(privs[i], i, 1, 0, VoteType.PREVOTE, bid))
    assert not vset.has_two_thirds_majority()  # 20/40 power
    assert vset.add_vote(signed_vote(privs[2], 2, 1, 0, VoteType.PREVOTE, bid))
    assert vset.has_two_thirds_majority()      # 30/40 > 2/3*40
    assert vset.two_thirds_majority() == bid


def test_vote_set_nil_votes_and_mixed():
    vs, privs = make_valset(4)
    bid, nil = make_block_id(), BlockID()
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)
    vset.add_vote(signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, bid))
    vset.add_vote(signed_vote(privs[1], 1, 1, 0, VoteType.PREVOTE, nil))
    vset.add_vote(signed_vote(privs[2], 2, 1, 0, VoteType.PREVOTE, nil))
    assert vset.has_two_thirds_any()
    assert not vset.has_two_thirds_majority()
    vset.add_vote(signed_vote(privs[3], 3, 1, 0, VoteType.PREVOTE, nil))
    assert vset.two_thirds_majority() == nil  # nil majority


def test_vote_set_rejects_bad():
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)
    # wrong height
    with pytest.raises(ValueError):
        vset.add_vote(signed_vote(privs[0], 0, 2, 0, VoteType.PREVOTE, bid))
    # forged signature
    v = signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, bid)
    v.signature = bytes(64)
    with pytest.raises(ValueError):
        vset.add_vote(v)
    # wrong index/address pairing
    v2 = signed_vote(privs[1], 0, 1, 0, VoteType.PREVOTE, bid)
    with pytest.raises(ValueError):
        vset.add_vote(v2)


def test_vote_set_conflicting_votes():
    vs, privs = make_valset(4)
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)
    v1 = signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, make_block_id(b"a"))
    v2 = signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, make_block_id(b"b"))
    assert vset.add_vote(v1)
    assert not vset.add_vote(v1)  # duplicate: no-op
    with pytest.raises(ConflictingVoteError):
        vset.add_vote(v2)


def test_vote_set_make_commit():
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 3, 1, VoteType.PRECOMMIT, vs, verifier=PYV)
    for i in range(3):
        vset.add_vote(signed_vote(privs[i], i, 3, 1, VoteType.PRECOMMIT, bid))
    commit = vset.make_commit()
    commit.validate_basic()
    assert commit.block_id == bid
    assert sum(1 for p in commit.precommits if p) == 3
    # commit verifies against the valset (batched, python backend)
    vs.verify_commit(CHAIN, bid, 3, commit, verifier=PYV)


# ----------------------------------------------------------- ValidatorSet --

def test_verify_commit_batched_jax():
    """The flagship path: one jax kernel call verifies the whole commit."""
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 1, 0, VoteType.PRECOMMIT, vs, verifier=PYV)
    for i in range(4):
        vset.add_vote(signed_vote(privs[i], i, 1, 0, VoteType.PRECOMMIT, bid))
    commit = vset.make_commit()
    jv = BatchVerifier("jax")
    vs.verify_commit(CHAIN, bid, 1, commit, verifier=jv)
    assert jv.stats["jax_sigs"] == 4
    # tampered signature fails
    commit.precommits[0].signature = bytes(64)
    with pytest.raises(ValueError):
        vs.verify_commit(CHAIN, bid, 1, commit, verifier=BatchVerifier("jax"))


def test_verify_commit_insufficient_power():
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 1, 0, VoteType.PRECOMMIT, vs, verifier=PYV)
    for i in range(2):
        vset.add_vote(signed_vote(privs[i], i, 1, 0, VoteType.PRECOMMIT, bid))
    commit = Commit(block_id=bid, precommits=[
        vset.get_by_index(i) for i in range(4)])
    with pytest.raises(ValueError, match="voting power"):
        vs.verify_commit(CHAIN, bid, 1, commit, verifier=PYV)


def test_proposer_rotation():
    vs, _ = make_valset(3)
    vs.validators[0].voting_power = 30  # heavier validator proposes more
    seen = []
    for _ in range(10):
        vs.increment_accum()
        seen.append(vs.proposer().address)
    heavy = vs.validators[0].address
    assert seen.count(heavy) == 6  # 30/(30+10+10) of 10 rounds
    # determinism
    vs2, _ = make_valset(3)
    vs2.validators[0].voting_power = 30
    seen2 = []
    for _ in range(10):
        vs2.increment_accum()
        seen2.append(vs2.proposer().address)
    assert seen == seen2


def test_valset_updates():
    vs, privs = make_valset(3)
    newkey = PrivKey.generate(b"\x77" * 32)
    vs2 = vs.update_with_changes([Validator(newkey.pubkey.ed25519, 5)])
    assert len(vs2) == 4 and vs2.total_voting_power() == 35
    vs3 = vs2.update_with_changes([Validator(newkey.pubkey.ed25519, 0)])
    assert len(vs3) == 3
    assert vs.hash() == vs3.hash()  # back to original membership
    with pytest.raises(ValueError):
        vs3.update_with_changes([Validator(newkey.pubkey.ed25519, 0)])  # unknown


def test_valset_remove_to_single_validator():
    """Churn edge (ISSUE 11 satellite): the set may legally shrink to
    ONE validator (a solo chain is valid), but never to zero — the
    delta that would empty it is rejected atomically (no partial
    application: the surviving set is untouched)."""
    vs, _ = make_valset(3)
    a, b, c = [v.pubkey for v in vs.validators]
    vs1 = vs.update_with_changes([Validator(a, 0), Validator(b, 0)])
    assert len(vs1) == 1 and vs1.validators[0].pubkey == c
    assert vs1.proposer().pubkey == c
    vs1.increment_accum(5)  # rotation over a singleton must not blow up
    assert vs1.proposer().pubkey == c
    with pytest.raises(ValueError, match="empty"):
        vs1.update_with_changes([Validator(c, 0)])
    assert len(vs1) == 1  # rejection left the set intact


def test_valset_rejects_delta_that_empties_set():
    """One batch removing every member is refused even when each
    individual removal names a known validator."""
    vs, _ = make_valset(4)
    with pytest.raises(ValueError, match="empty"):
        vs.update_with_changes(
            [Validator(v.pubkey, 0) for v in vs.validators])


def test_valset_readd_of_removed_key_starts_fresh_accum():
    """Leave then re-join of the same key: the re-added validator is a
    NEW member — its proposer-priority accumulator restarts at 0
    instead of resuming the stale pre-removal value (a resumed accum
    would hand a rejoining validator an immediate, unearned proposer
    slot or an unfair deficit)."""
    vs, _ = make_valset(4)
    target = vs.validators[0].pubkey
    vs.increment_accum(7)  # build up non-trivial accums
    removed = vs.update_with_changes([Validator(target, 0)])
    assert not removed.has_address(vs.validators[0].address)
    readded = removed.update_with_changes([Validator(target, 10)])
    assert len(readded) == 4
    _, val = readded.get_by_address(vs.validators[0].address)
    assert val.accum == 0
    # survivors carried their mid-rotation accums over (reference
    # Add/Update/Remove semantics: _fresh=False, no re-increment)
    for v in removed.validators:
        _, after = readded.get_by_address(v.address)
        assert after.accum == v.accum
    assert readded.hash() == vs.hash()  # same membership+powers again


def test_valset_proposer_fairness_across_join_leave_sequence():
    """Proposer selection stays power-proportional THROUGH a
    join/leave churn sequence: over a long window every member
    proposes ~power/total of the rounds, including validators that
    joined mid-sequence (a join/leave that skewed rotation would
    starve or favor someone for many heights — the live-net symptom
    is one validator proposing twice in a row or never)."""
    vs, _ = make_valset(3)
    joiner = PrivKey.generate(b"\x66" * 32).pubkey.ed25519
    counts = {}
    rounds_before, rounds_after = 30, 120
    for _ in range(rounds_before):
        vs.increment_accum(1)
        counts[vs.proposer().pubkey] = \
            counts.get(vs.proposer().pubkey, 0) + 1
    vs = vs.update_with_changes([Validator(joiner, 10)])  # join
    back_to_back = 0
    last = None
    counts_after = {}
    for _ in range(rounds_after):
        vs.increment_accum(1)
        p = vs.proposer().pubkey
        counts_after[p] = counts_after.get(p, 0) + 1
        back_to_back += (p == last)
        last = p
    # 4 equal-power members over 120 rounds: exactly 30 each, and an
    # equal-power set never hands anyone consecutive slots
    assert sorted(counts_after.values()) == [30, 30, 30, 30]
    assert joiner in counts_after
    assert back_to_back == 0
    # now a leave: remaining members re-converge to thirds
    vs = vs.update_with_changes([Validator(joiner, 0)])
    counts_final = {}
    for _ in range(90):
        vs.increment_accum(1)
        p = vs.proposer().pubkey
        counts_final[p] = counts_final.get(p, 0) + 1
    assert joiner not in counts_final
    assert sorted(counts_final.values()) == [30, 30, 30]


# ---------------------------------------------------------- PrivValidator --

def test_priv_validator_double_sign_protection(tmp_path):
    path = str(tmp_path / "priv.json")
    pv = PrivValidatorFile.generate(path, b"\x11" * 32)
    bid_a, bid_b = make_block_id(b"a"), make_block_id(b"b")
    va = Vote(pv.address, 0, 5, 0, 111, VoteType.PREVOTE, bid_a)
    pv.sign_vote(CHAIN, va)
    # same vote, different timestamp: returns SAME signature
    va2 = Vote(pv.address, 0, 5, 0, 999, VoteType.PREVOTE, bid_a)
    pv.sign_vote(CHAIN, va2)
    assert va2.signature == va.signature
    # different block at same HRS: refused
    vb = Vote(pv.address, 0, 5, 0, 111, VoteType.PREVOTE, bid_b)
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN, vb)
    # height regression refused, later height fine
    v_later = Vote(pv.address, 0, 6, 0, 111, VoteType.PREVOTE, bid_b)
    pv.sign_vote(CHAIN, v_later)
    with pytest.raises(DoubleSignError):
        pv.sign_vote(CHAIN, Vote(pv.address, 0, 4, 0, 1, VoteType.PREVOTE, bid_a))
    # persistence survives reload
    pv2 = PrivValidatorFile.load(path)
    assert (pv2.last_height, pv2.last_step) == (6, 2)
    with pytest.raises(DoubleSignError):
        pv2.sign_vote(CHAIN, Vote(pv.address, 0, 5, 0, 1, VoteType.PREVOTE, bid_a))


# ------------------------------------------------------------------ Block --

def make_commit_for(vs, privs, height, bid):
    vset = VoteSet(CHAIN, height, 0, VoteType.PRECOMMIT, vs, verifier=PYV)
    for i, p in enumerate(privs):
        vset.add_vote(signed_vote(p, i, height, 0, VoteType.PRECOMMIT, bid))
    return vset.make_commit()


def test_block_roundtrip_and_partset():
    vs, privs = make_valset(4)
    last_bid = make_block_id(b"prev")
    commit = make_commit_for(vs, privs, 1, last_bid)
    block = Block(
        header=Header(chain_id=CHAIN, height=2, time_ns=123, num_txs=2,
                      total_txs=5, last_block_id=last_bid,
                      validators_hash=vs.hash(), consensus_hash=b"c" * 32,
                      app_hash=b"a" * 32, last_results_hash=b"r" * 32),
        data=Data(txs=[b"tx1", b"tx2"]),
        last_commit=commit)
    block.fill_header()
    block.validate_basic()
    h1 = block.hash()
    # serialization roundtrip preserves hash
    block2 = Block.from_bytes(block.to_bytes())
    assert block2.hash() == h1
    # part set splits and reassembles
    ps = block.make_part_set(64)
    assert ps.is_complete()
    ps2 = PartSet.from_header(ps.header())
    for i in range(ps.total):
        assert ps2.add_part(ps.get_part(i))
    assert ps2.is_complete()
    assert Block.from_bytes(ps2.get_data()).hash() == h1
    # corrupt part rejected
    ps3 = PartSet.from_header(ps.header())
    bad = Part = ps.get_part(0)
    import copy
    bad = copy.deepcopy(ps.get_part(0))
    bad.payload = b"x" + bad.payload[1:]
    with pytest.raises(ValueError):
        ps3.add_part(bad)
    # tampering with header fields changes the hash
    block2.header.app_hash = b"z" * 32
    assert block2.header.hash() != h1
    # num_txs mismatch caught
    block.header.num_txs = 3
    with pytest.raises(ValueError):
        block.validate_basic()


# --------------------------------------------------------------- Evidence --

def test_duplicate_vote_evidence():
    vs, privs = make_valset(4)
    p = privs[0]
    va = signed_vote(p, 0, 1, 0, VoteType.PREVOTE, make_block_id(b"a"))
    vb = signed_vote(p, 0, 1, 0, VoteType.PREVOTE, make_block_id(b"b"))
    ev = DuplicateVoteEvidence(p.pubkey.ed25519, va, vb)
    ev.verify(CHAIN, p.pubkey.ed25519, verifier=PYV)
    # same block twice is not duplicity
    ev2 = DuplicateVoteEvidence(p.pubkey.ed25519, va, va)
    with pytest.raises(ValueError):
        ev2.verify(CHAIN, p.pubkey.ed25519, verifier=PYV)
    # forged second vote
    vb_forged = signed_vote(p, 0, 1, 0, VoteType.PREVOTE, make_block_id(b"c"))
    vb_forged.signature = bytes(64)
    ev3 = DuplicateVoteEvidence(p.pubkey.ed25519, va, vb_forged)
    with pytest.raises(ValueError):
        ev3.verify(CHAIN, p.pubkey.ed25519, verifier=PYV)


# ------------------------------------------------------- Events + queries --

def test_event_query_language():
    q = Query("tm.event = 'Tx' AND tx.height > 3")
    assert q.matches({"tm.event": "Tx", "tx.height": 5})
    assert not q.matches({"tm.event": "Tx", "tx.height": 2})
    assert not q.matches({"tm.event": "NewBlock", "tx.height": 5})
    q2 = Query("tx.hash = 'ABCD'")
    assert q2.matches({"tx.hash": "ABCD", "tm.event": "Tx"})
    with pytest.raises(ValueError):
        Query("tm.event ~ 'Tx'")


def test_event_bus_pubsub():
    bus = EventBus()
    sub = bus.subscribe("test", "tm.event = 'Tx' AND tx.height = 7")
    bus.publish_tx(7, 0, b"txdata", {"code": 0})
    bus.publish_tx(8, 0, b"other", {"code": 0})
    item = sub.get(timeout=1)
    assert item.data["height"] == 7
    assert sub.get_nowait() is None  # height-8 event filtered out
    bus.unsubscribe("test", "tm.event = 'Tx' AND tx.height = 7")
    bus.publish_tx(7, 1, b"txdata2", {"code": 0})
    assert sub.get_nowait() is None


# ------------------------------------------------------- Params + Genesis --

def test_params_genesis_roundtrip(tmp_path):
    params = ConsensusParams()
    params.validate()
    assert params.hash() == ConsensusParams.from_obj(params.to_obj()).hash()
    upd = params.update({"block_size": {"max_txs": 5}})
    assert upd.block_size.max_txs == 5 and params.block_size.max_txs == 100000

    priv = PrivKey.generate(b"\x22" * 32)
    doc = GenesisDoc(chain_id=CHAIN, validators=[
        GenesisValidator(priv.pubkey.ed25519, 10, "v0")])
    doc.validate_and_complete()
    path = str(tmp_path / "genesis.json")
    doc.save(path)
    doc2 = GenesisDoc.load(path)
    assert doc2.bytes() == doc.bytes()
    assert doc2.validator_hash() == doc.validator_hash()


# ------------------------------------------- ValidatorSet lookup scaling --

def test_get_by_address_large_set():
    """O(1) addr->index map vs the reference's binary search
    (types/validator_set.go:93-101): 10k validators, lookups must not be
    a linear scan (the round-1 implementation was O(V) per vote)."""
    import time
    n = 10_000
    vals = [Validator(bytes([i & 0xFF, (i >> 8) & 0xFF]) + b"\x01" * 30, 1)
            for i in range(n)]
    vs = ValidatorSet(vals)
    # correctness: every address found at the right index; misses miss
    for i in (0, 1, n // 2, n - 1):
        v = vs.validators[i]
        idx, got = vs.get_by_address(v.address)
        assert idx == i and got is v
    assert vs.get_by_address(b"\xff" * 20) == (-1, None)
    # scaling: 3 full-set lookup sweeps of 10k addrs each finish fast;
    # a linear scan (~5k compares/lookup) would take tens of seconds
    addrs = [v.address for v in vs.validators]
    t0 = time.monotonic()
    for _ in range(3):
        for a in addrs:
            vs.get_by_address(a)
    assert time.monotonic() - t0 < 2.0


# --------------------------------------------- HeightVoteSet catchup --

def test_height_vote_set_peer_catchup_rounds():
    """A peer may open vote sets for rounds far beyond ours — up to
    MAX_CATCHUP_ROUNDS distinct rounds per peer (the reference's
    peerCatchupRounds bound, consensus/types/height_vote_set.go:107-129).
    This is how a late joiner accepts a commit that happened at round 6
    while it still sits at round 0."""
    from tendermint_tpu.consensus.rstate import HeightVoteSet

    vs, privs = make_valset(4)
    hvs = HeightVoteSet(CHAIN, 1, vs, verifier=PYV)
    bid = make_block_id()

    # rounds 0 and 1 are pre-made; round 6 is a peer catchup round
    v6 = signed_vote(privs[0], 0, 1, 6, VoteType.PRECOMMIT, bid)
    assert hvs.add_vote(v6, peer_id="peerA")
    assert hvs.precommits(6) is not None
    # same peer, second catchup round: still allowed
    v9 = signed_vote(privs[1], 1, 1, 9, VoteType.PRECOMMIT, bid)
    assert hvs.add_vote(v9, peer_id="peerA")
    # third distinct round from the same peer: allowance exhausted
    v12 = signed_vote(privs[2], 2, 1, 12, VoteType.PRECOMMIT, bid)
    with pytest.raises(ValueError):
        hvs.add_vote(v12, peer_id="peerA")
    # ...but more votes into an ALREADY-OPEN round don't burn allowance
    v6b = signed_vote(privs[3], 3, 1, 6, VoteType.PRECOMMIT, bid)
    assert hvs.add_vote(v6b, peer_id="peerA")
    # another peer has its own allowance
    assert hvs.add_vote(v12, peer_id="peerB")
    # internal votes (no peer) are never limited
    v20 = signed_vote(privs[0], 0, 1, 20, VoteType.PREVOTE, bid)
    assert hvs.add_vote(v20)


def test_height_vote_set_gap_rounds_do_not_burn_allowance():
    """After a round-skip, votes for the skipped-over rounds are normal
    gossip — they must NOT charge the peer's catchup allowance (the
    reference pre-makes every round up to the current one)."""
    from tendermint_tpu.consensus.rstate import HeightVoteSet

    vs, privs = make_valset(4)
    hvs = HeightVoteSet(CHAIN, 1, vs, verifier=PYV)
    bid = make_block_id()
    hvs.set_round(7)  # skip 0 -> 7: rounds 0..8 all pre-made
    # gap-round votes from one peer: free
    for r in (1, 3, 5):
        v = signed_vote(privs[0], 0, 1, r, VoteType.PRECOMMIT, bid)
        assert hvs.add_vote(v, peer_id="peerA")
    # the same peer still has its full 2-round catchup allowance
    v12 = signed_vote(privs[1], 1, 1, 12, VoteType.PRECOMMIT, bid)
    v15 = signed_vote(privs[2], 2, 1, 15, VoteType.PRECOMMIT, bid)
    assert hvs.add_vote(v12, peer_id="peerA")
    assert hvs.add_vote(v15, peer_id="peerA")


def test_vote_sign_bytes_fast_path():
    """Vote.sign_bytes emits canonical JSON directly (hot path); it must
    stay byte-identical to the generic canonical encoder over sign_obj,
    including exotic chain ids needing JSON escapes."""
    from tendermint_tpu.types import encoding

    for cid in ("test-chain", 'quote"backslash\\', "unicode-ü-λ", ""):
        for bid in (make_block_id(), BlockID()):
            v = Vote(validator_address=b"\x01" * 20, validator_index=3,
                     height=7, round=2, timestamp_ns=123456789,
                     type=VoteType.PRECOMMIT, block_id=bid)
            assert v.sign_bytes(cid) == encoding.cdumps(v.sign_obj(cid))


# -------------------------------------------------- verify_commit_any -------
# Pins the v0.16 VerifyCommitAny semantics (types/validator_set.go:288-353):
# STRICT >2/3 of the OLD (trusted) set — round 2 shipped a 1/3 rule (the
# later-Tendermint light-client model); v0.16 is stricter and these tests
# pin the chosen rule at its exact boundaries.

def _valset_powers(seed_powers):
    """[(seed_byte, power)] -> (ValidatorSet, {address: priv})."""
    privs, vals = {}, []
    for sb, pw in seed_powers:
        p = PrivKey.generate(bytes([sb]) * 32)
        privs[p.pubkey.address] = p
        vals.append(Validator(p.pubkey.ed25519, pw))
    return ValidatorSet(vals), privs


def _commit_for(new_vs, privs, height, bid, garbage=()):
    """Commit indexed by new_vs order; addresses in `garbage` get a
    syntactically-valid but forged signature."""
    pcs = []
    for idx, val in enumerate(new_vs.validators):
        p = privs.get(val.address)
        if p is None:
            pcs.append(None)
            continue
        v = signed_vote(p, idx, height, 0, VoteType.PRECOMMIT, bid)
        if val.address in garbage:
            v.signature = bytes(64)
        pcs.append(v)
    return Commit(block_id=bid, precommits=pcs)


def test_verify_commit_any_full_overlap_accepts():
    old, privs = _valset_powers([(1, 10), (2, 10), (3, 10)])
    bid = make_block_id()
    commit = _commit_for(old, privs, 7, bid)
    old.verify_commit_any(old, CHAIN, bid, 7, commit, verifier=PYV)


def test_verify_commit_any_exactly_two_thirds_old_rejected():
    # old total 30; overlap signs exactly 20 = 2/3 -> REJECT (strict >)
    old, privs = _valset_powers([(1, 10), (2, 10), (3, 10)])
    new, nprivs = _valset_powers([(1, 10), (2, 10)])
    privs.update(nprivs)
    bid = make_block_id()
    commit = _commit_for(new, privs, 7, bid)
    with pytest.raises(ValueError, match="insufficient old"):
        old.verify_commit_any(new, CHAIN, bid, 7, commit, verifier=PYV)


def test_verify_commit_any_just_above_two_thirds_accepts():
    # old total 30; overlap signs 21 > 2/3 -> accept
    old, privs = _valset_powers([(1, 11), (2, 10), (3, 9)])
    new, nprivs = _valset_powers([(1, 11), (2, 10)])
    privs.update(nprivs)
    bid = make_block_id()
    commit = _commit_for(new, privs, 7, bid)
    old.verify_commit_any(new, CHAIN, bid, 7, commit, verifier=PYV)


def test_verify_commit_any_middle_overlap_rejected():
    # overlap 15/30: above 1/3 (round-2 rule would ACCEPT), below 2/3 ->
    # v0.16 rejects. This is the divergence-closing pin.
    old, privs = _valset_powers([(1, 15), (2, 8), (3, 7)])
    new, nprivs = _valset_powers([(1, 15), (9, 5)])
    privs.update(nprivs)
    bid = make_block_id()
    commit = _commit_for(new, privs, 7, bid)
    with pytest.raises(ValueError, match="insufficient old"):
        old.verify_commit_any(new, CHAIN, bid, 7, commit, verifier=PYV)


def test_verify_commit_any_unknown_validator_never_verified():
    # a validator unknown to the trusted set is SKIPPED (:322-327): its
    # garbage signature must not fail the commit, and it contributes no
    # power to either side
    old, privs = _valset_powers([(1, 11), (2, 10), (3, 9)])
    new, nprivs = _valset_powers([(1, 11), (2, 10), (9, 2)])
    privs.update(nprivs)
    bid = make_block_id()
    ghost_addr = PrivKey.generate(bytes([9]) * 32).pubkey.address
    commit = _commit_for(new, privs, 7, bid, garbage={ghost_addr})
    old.verify_commit_any(new, CHAIN, bid, 7, commit, verifier=PYV)


def test_verify_commit_any_invalid_overlap_signature_fails():
    old, privs = _valset_powers([(1, 10), (2, 10), (3, 10)])
    bid = make_block_id()
    bad_addr = old.validators[0].address
    commit = _commit_for(old, privs, 7, bid, garbage={bad_addr})
    with pytest.raises(ValueError, match="invalid signature"):
        old.verify_commit_any(old, CHAIN, bid, 7, commit, verifier=PYV)


def test_commit_items_sign_bytes_match_vote_sign_bytes():
    """commit_verification_items' templated sign-bytes fast path must be
    byte-identical to Vote.sign_bytes (which is itself pinned to the
    generic canonical encoding)."""
    vs, privs = make_valset(4)
    bid = make_block_id()
    vset = VoteSet(CHAIN, 3, 1, VoteType.PRECOMMIT, vs, verifier=PYV)
    for i in range(4):
        vset.add_vote(signed_vote(privs[i], i, 3, 1, VoteType.PRECOMMIT,
                                  bid, ts=5000 + 17 * i))
    commit = vset.make_commit()
    items, _ = vs.commit_verification_items(CHAIN, bid, 3, commit)
    got = [sb for _, sb, _ in items]
    want = [pc.sign_bytes(CHAIN) for pc in commit.precommits
            if pc is not None]
    assert got == want


# ----------------------------------------------------------- secp256k1 -----

def test_secp256k1_roundtrip_and_verify():
    """go-crypto's second key type (exercised by the reference's
    lite/performance_test.go:10-105): generate, obj round-trip, sign,
    verify, tamper-reject."""
    from tendermint_tpu.types.keys import (Secp256k1PrivKey,
                                           Secp256k1PubKey,
                                           privkey_from_obj,
                                           pubkey_from_obj, verify_any)

    k = Secp256k1PrivKey.generate(b"\x07" * 32)
    pub = k.pubkey
    assert len(pub.secp256k1) == 33 and pub.secp256k1[0] in (2, 3)
    assert len(pub.address) == 20

    # deterministic key from seed; obj round-trips through the factory
    k2 = privkey_from_obj(k.to_obj())
    assert k2.pubkey == pub
    assert pubkey_from_obj(pub.to_obj()) == pub

    msg = b"secp message"
    sig = k.sign(msg)
    assert pub.verify(msg, sig)
    assert verify_any(pub.secp256k1, msg, sig)
    assert not pub.verify(msg + b"x", sig)
    assert not pub.verify(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    # ed25519 keys still route through verify_any
    ed = PrivKey.generate(b"\x08" * 32)
    ed_sig = ed.sign(b"m")
    assert verify_any(ed.pubkey.ed25519, b"m", ed_sig)


def test_mixed_keytype_valset_commit():
    """A validator set mixing ed25519 and secp256k1 members verifies a
    commit through BOTH verifier backends: ed25519 signatures batch to
    the device kernel, secp256k1 ones verify on host, verdicts merge."""
    from tendermint_tpu.types.keys import Secp256k1PrivKey

    ed_keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(3)]
    secp_keys = [Secp256k1PrivKey.generate(bytes([i + 0x40]) * 32)
                 for i in range(2)]
    vals = [Validator(k.pubkey.ed25519, 10) for k in ed_keys] + \
           [Validator(k.pubkey.secp256k1, 10) for k in secp_keys]
    vs = ValidatorSet(vals)
    by_addr = {}
    for k in ed_keys + secp_keys:
        by_addr[k.pubkey.address] = k

    bid = make_block_id()
    precommits = []
    for idx, val in enumerate(vs.validators):
        k = by_addr[val.address]
        v = Vote(validator_address=val.address, validator_index=idx,
                 height=9, round=0, timestamp_ns=2000 + idx,
                 type=VoteType.PRECOMMIT, block_id=bid)
        v.signature = k.sign(v.sign_bytes(CHAIN))
        precommits.append(v)
    commit = Commit(block_id=bid, precommits=precommits)

    for backend in ("python", "jax"):
        vs.verify_commit(CHAIN, bid, 9, commit,
                         verifier=BatchVerifier(backend))

    # tamper one secp signature and one ed signature: each must fail
    for idx, val in enumerate(vs.validators):
        if len(val.pubkey) == 33:
            break
    bad = Commit(block_id=bid, precommits=list(precommits))
    sig = bad.precommits[idx].signature
    bad.precommits[idx] = Vote(
        validator_address=bad.precommits[idx].validator_address,
        validator_index=idx, height=9, round=0,
        timestamp_ns=2000 + idx, type=VoteType.PRECOMMIT, block_id=bid,
        signature=sig[:-1] + bytes([sig[-1] ^ 1]))
    with pytest.raises(ValueError):
        vs.verify_commit(CHAIN, bid, 9, bad,
                         verifier=BatchVerifier("jax"))


# --------------------------------------------- proposer selection parity --

def _vals_by_power(powers):
    """3+ validators whose SORTED-by-address order carries `powers` in
    order — the rotation algorithm sees only (sorted position, power),
    so reference fixtures keyed by address names map onto positions."""
    privs = [PrivKey.generate(bytes([40 + i]) * 32) for i in range(len(powers))]
    addrs = sorted(p.pubkey.address for p in privs)
    by_addr = {p.pubkey.address: p for p in privs}
    vals = [Validator(by_addr[a].pubkey.ed25519, pw)
            for a, pw in zip(addrs, powers)]
    vs = ValidatorSet(vals)
    pos = {vs.validators[i].address: i for i in range(len(powers))}
    return vs, pos


def test_proposer_selection_reference_sequence():
    """types/validator_set_test.go:51 TestProposerSelection1 — the exact
    99-proposer sequence for powers (bar=300, baz=330, foo=1000) with
    bar < baz < foo by address. Mapped to sorted positions 0/1/2; any
    deviation in the accum algorithm (constructor increment, decrement
    order, tie-break) shifts this fixture."""
    expected = (
        "foo baz foo bar foo foo baz foo bar foo foo baz foo foo bar "
        "foo baz foo foo bar foo foo baz foo bar foo foo baz foo bar "
        "foo foo baz foo foo bar foo baz foo foo bar foo baz foo foo "
        "bar foo baz foo foo bar foo baz foo foo foo baz bar foo foo "
        "foo baz foo bar foo foo baz foo bar foo foo baz foo bar foo "
        "foo baz foo bar foo foo baz foo foo bar foo baz foo foo bar "
        "foo baz foo foo bar foo baz foo foo").split()
    name_of_pos = {0: "bar", 1: "baz", 2: "foo"}
    vs, pos = _vals_by_power([300, 330, 1000])
    got = []
    for _ in range(99):
        got.append(name_of_pos[pos[vs.proposer().address]])
        vs.increment_accum(1)
    assert got == expected


def test_proposer_selection_order_and_runs():
    """types/validator_set_test.go:73 TestProposerSelection2: equal
    powers rotate in address order; a heavier validator leads but only
    proposes twice in a row when strictly heavier than the rest
    combined; proposal counts are proportional over a cycle."""
    # equal power: address order
    vs, pos = _vals_by_power([100, 100, 100])
    for i in range(15):
        assert pos[vs.proposer().address] == i % 3
        vs.increment_accum(1)
    # 400 vs 100+100: leads, but not twice in a row
    vs, pos = _vals_by_power([100, 100, 400])
    assert pos[vs.proposer().address] == 2
    vs.increment_accum(1)
    assert pos[vs.proposer().address] == 0
    # 401: strictly heavier -> proposes twice, then the smallest address
    vs, pos = _vals_by_power([100, 100, 401])
    assert pos[vs.proposer().address] == 2
    vs.increment_accum(1)
    assert pos[vs.proposer().address] == 2
    vs.increment_accum(1)
    assert pos[vs.proposer().address] == 0
    # proportionality over a full cycle (4:5:3 of 12 over 120 rounds)
    vs, pos = _vals_by_power([4, 5, 3])
    counts = [0, 0, 0]
    for _ in range(120):
        counts[pos[vs.proposer().address]] += 1
        vs.increment_accum(1)
    assert counts == [40, 50, 30]


def test_proposer_increment_times_matches_stepwise_reference():
    """increment_accum(times) must equal the reference's add-all-then-
    decrement-times algorithm — NOT `times` single steps (those differ:
    the intermediate maxima see less re-added power). Pins the round-
    skip path (consensus _enter_new_round jumping rounds)."""
    vs, pos = _vals_by_power([300, 330, 1000])
    ref = vs.copy()
    vs.increment_accum(3)
    # manual reference algorithm on the copy
    for v in ref.validators:
        v.accum += v.voting_power * 3
    total = ref.total_voting_power()
    for _ in range(3):
        mostest = ref.validators[0]
        for v in ref.validators[1:]:
            mostest = mostest.compare_accum(v)
        mostest.accum -= total
    assert [v.accum for v in vs.validators] == \
        [v.accum for v in ref.validators]
    assert vs.proposer().address == mostest.address


def test_proposer_survives_serialization_roundtrip():
    """A restarted node must agree with live peers about the proposer:
    after an increment the proposer is the pre-decrement maximum, which
    accums alone no longer identify — to_obj/from_obj must carry it
    (the reference persists its Proposer field for the same reason)."""
    vs, _ = _vals_by_power([300, 330, 1000])
    for _ in range(5):
        live = vs.proposer().address
        vs2 = ValidatorSet.from_obj(vs.to_obj())
        assert vs2.proposer().address == live
        # and the reloaded set continues the SAME rotation
        vs.increment_accum(1)
        vs2.increment_accum(1)
        assert vs2.proposer().address == vs.proposer().address


def test_vote_set_majority_keys_on_full_block_id():
    """types/vote_set_test.go:159 Test2_3MajorityRedux: the quorum is
    keyed on the FULL BlockID — votes for the same hash but a different
    PartSetHeader hash or total are DIFFERENT blocks and must never pool
    into one majority. 100 validators: 66 for the block, then one nil,
    one wrong parts-hash, one wrong parts-total, one wrong hash (still
    no 2/3); the 71st correct vote tips it."""
    vs, privs = make_valset(100)
    bid = BlockID(hash=b"R".ljust(32, b"\1"),
                  parts=PartSetHeader(123, b"q" * 32))
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)
    for i in range(66):
        assert vset.add_vote(
            signed_vote(privs[i], i, 1, 0, VoteType.PREVOTE, bid))
    assert vset.two_thirds_majority() is None

    variants = [
        BlockID(b"", PartSetHeader(0, b"")),                    # nil
        BlockID(bid.hash, PartSetHeader(123, b"z" * 32)),       # parts hash
        BlockID(bid.hash, PartSetHeader(124, bid.parts.hash)),  # parts total
        BlockID(b"X".ljust(32, b"\2"), bid.parts),              # block hash
    ]
    for j, vbid in enumerate(variants):
        i = 66 + j
        assert vset.add_vote(
            signed_vote(privs[i], i, 1, 0, VoteType.PREVOTE, vbid))
        assert vset.two_thirds_majority() is None, vbid

    assert vset.add_vote(
        signed_vote(privs[70], 70, 1, 0, VoteType.PREVOTE, bid))
    maj = vset.two_thirds_majority()
    assert maj == bid
    assert maj.parts.total == 123 and maj.parts.hash == b"q" * 32


def test_vote_set_conflicts_with_peer_maj23_tracking():
    """types/vote_set_test.go:318 TestConflicts, end to end: conflicting
    votes are dropped for untracked blocks, ADMITTED (counted AND
    reported) for blocks a peer claims +2/3 for, a same-peer conflicting
    claim is rejected without state change, and admitted conflicting
    votes carry the tracked block across quorum."""
    from tendermint_tpu.types.vote_set import ConflictingVoteError

    vs, privs = make_valset(4)
    nil_bid = BlockID(b"", PartSetHeader(0, b""))
    bid1 = BlockID(b"one".ljust(32, b"\1"), PartSetHeader(0, b""))
    bid2 = BlockID(b"two".ljust(32, b"\2"), PartSetHeader(0, b""))
    vset = VoteSet(CHAIN, 1, 0, VoteType.PREVOTE, vs, verifier=PYV)

    # val0 votes nil, then conflictingly for bid1 (untracked): dropped
    assert vset.add_vote(signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE,
                                     nil_bid))
    with pytest.raises(ConflictingVoteError):
        vset.add_vote(signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, bid1))
    assert vset.bit_array_by_block_id(bid1) is None or \
        not any(vset.bit_array_by_block_id(bid1))

    # peerA claims +2/3 for bid1: val0's conflicting re-vote now COUNTS
    vset.set_peer_maj23("peerA", bid1)
    with pytest.raises(ConflictingVoteError):
        vset.add_vote(signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, bid1))
    assert any(vset.bit_array_by_block_id(bid1))

    # peerA cannot switch claims; bid2 stays untracked for conflicts
    with pytest.raises(ValueError):
        vset.set_peer_maj23("peerA", bid2)
    with pytest.raises(ConflictingVoteError):
        vset.add_vote(signed_vote(privs[0], 0, 1, 0, VoteType.PREVOTE, bid2))

    # val1 -> bid1 (clean); no majority yet, not even 2/3 "any"
    assert vset.add_vote(signed_vote(privs[1], 1, 1, 0, VoteType.PREVOTE,
                                     bid1))
    assert not vset.has_two_thirds_majority()
    assert not vset.has_two_thirds_any()

    # val2 -> bid2 (clean): 2/3 "any" but no block majority
    assert vset.add_vote(signed_vote(privs[2], 2, 1, 0, VoteType.PREVOTE,
                                     bid2))
    assert not vset.has_two_thirds_majority()
    assert vset.has_two_thirds_any()

    # peerB claims bid1; val2's conflicting bid1 vote is admitted and
    # tips bid1 over quorum: val0(conflict) + val1 + val2(conflict)
    vset.set_peer_maj23("peerB", bid1)
    with pytest.raises(ConflictingVoteError) as exc:
        vset.add_vote(signed_vote(privs[2], 2, 1, 0, VoteType.PREVOTE, bid1))
    assert exc.value.added, "counted conflict must report added=True"
    assert vset.has_two_thirds_majority()
    assert vset.two_thirds_majority() == bid1
    assert vset.has_two_thirds_any()

    # a REGOSSIPED copy of the counted conflicting vote is a silent
    # duplicate (reference getVote, types/vote_set.go:202-216) — no
    # fresh ConflictingVoteError, no evidence re-filing, no crypto
    assert vset.add_vote(
        signed_vote(privs[2], 2, 1, 0, VoteType.PREVOTE, bid1)) is False
