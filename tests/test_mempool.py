"""Mempool + CList tests (models mempool/mempool_test.go + clist tests)."""

import threading
import time

import pytest

from tendermint_tpu.abci.apps import CounterApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.mempool import CList, Mempool, TxAlreadyInCache, TxCache


def make_mempool(app=None):
    app = app or CounterApp(serial=False)
    conns = AppConns(local_client_creator(app))
    return Mempool(conns.mempool), app


# ------------------------------------------------------------------- CList

def test_clist_push_iterate_remove():
    cl = CList()
    els = [cl.push_back(i) for i in range(5)]
    assert len(cl) == 5
    assert [e.value for e in cl] == [0, 1, 2, 3, 4]
    cl.remove(els[2])
    assert [e.value for e in cl] == [0, 1, 3, 4]
    # removed element still reaches the live suffix
    assert els[2].next().value == 3
    cl.remove(els[0])
    assert cl.front().value == 1


def test_clist_next_wait_wakes_on_push():
    cl = CList()
    el = cl.push_back("a")
    got = []

    def waiter():
        got.append(el.next_wait(timeout=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    cl.push_back("b")
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert got[0].value == "b"


def test_clist_front_wait_timeout():
    cl = CList()
    t0 = time.monotonic()
    assert cl.front_wait(timeout=0.05) is None
    assert time.monotonic() - t0 >= 0.04


# ------------------------------------------------------------------ TxCache

def test_tx_cache_dedup_and_eviction():
    c = TxCache(size=2)
    assert c.push(b"a") and not c.push(b"a")
    assert c.push(b"b")
    assert c.push(b"c")        # evicts a (FIFO)
    assert c.push(b"a")        # a admitted again
    c.remove(b"c")
    assert c.push(b"c")


# ------------------------------------------------------------------ Mempool

def test_checktx_reap_order_and_dedup():
    mp, _ = make_mempool()
    for i in range(10):
        res = mp.check_tx(bytes([i]))
        assert res.ok
    assert mp.size() == 10
    assert mp.reap(4) == [bytes([i]) for i in range(4)]
    assert mp.reap(-1) == [bytes([i]) for i in range(10)]
    with pytest.raises(TxAlreadyInCache):
        mp.check_tx(bytes([3]))


def test_invalid_tx_not_queued_not_cached():
    # serial counter app rejects txs below its count (abci/apps/counter.py)
    app = CounterApp(serial=True)
    mp, _ = make_mempool(app)
    for i in range(3):
        app.deliver_tx(i.to_bytes(8, "big"))  # count -> 3
    bad = (1).to_bytes(8, "big")
    res = mp.check_tx(bad)
    assert not res.ok and mp.size() == 0
    # rejected txs leave the cache so a later resubmit re-checks
    res = mp.check_tx(bad)
    assert not res.ok


def test_update_removes_committed_and_keeps_cache():
    mp, _ = make_mempool()
    txs = [bytes([i]) for i in range(6)]
    for tx in txs:
        mp.check_tx(tx)
    mp.lock()
    mp.update(1, txs[:3])
    mp.unlock()
    assert mp.reap(-1) == txs[3:]
    # committed txs stay cached: resubmit is a dup
    with pytest.raises(TxAlreadyInCache):
        mp.check_tx(txs[0])


def test_update_recheck_drops_newly_invalid():
    app = CounterApp(serial=True)
    conns = AppConns(local_client_creator(app))
    mp = Mempool(conns.mempool)
    good = [(i).to_bytes(8, "big") for i in range(4)]
    for tx in good:
        assert mp.check_tx(tx).ok
    # app advanced to count 3 out-of-band, but only [0,1] were committed:
    # the recheck after update must drop the now-stale tx 2, keep tx 3
    for tx in good[:3]:
        app.deliver_tx(tx)
    mp.update(1, good[:2])
    assert mp.reap(-1) == good[3:]


def test_txs_available_fires_once_per_height():
    mp, _ = make_mempool()
    fired = []
    mp.txs_available_hook = lambda: fired.append(mp.height)
    mp.check_tx(b"x")
    mp.check_tx(b"y")
    assert fired == [0]          # once, not per tx
    mp.update(1, [b"x"])
    assert fired == [0, 1]       # txs remain -> re-notify at new height


def test_mempool_full_raises():
    class Cfg:
        size = 3
        recheck = True
        cache_size = 100
    app = CounterApp(serial=False)
    conns = AppConns(local_client_creator(app))
    mp = Mempool(conns.mempool, config=Cfg())
    for i in range(3):
        mp.check_tx(bytes([i]))
    from tendermint_tpu.mempool.mempool import MempoolFull
    with pytest.raises(MempoolFull):
        mp.check_tx(b"overflow")


def test_wal_replay_restores_pending_txs(tmp_path):
    wal_dir = str(tmp_path / "mwal")
    app = CounterApp(serial=False)
    conns = AppConns(local_client_creator(app))
    mp = Mempool(conns.mempool, wal_dir=wal_dir)
    txs = [b"\n\x00weird" + bytes([i]) for i in range(5)]  # embedded newlines
    for tx in txs:
        mp.check_tx(tx)
    mp.close()
    # crash + restart: a fresh mempool replays the WAL through CheckTx
    mp2 = Mempool(AppConns(local_client_creator(CounterApp())).mempool,
                  wal_dir=wal_dir)
    assert mp2.reap(-1) == txs


def test_wal_committed_txs_never_replay(tmp_path):
    wal_dir = str(tmp_path / "mwal")
    conns = AppConns(local_client_creator(CounterApp()))
    mp = Mempool(conns.mempool, wal_dir=wal_dir)
    txs = [bytes([i]) for i in range(4)]
    for tx in txs:
        mp.check_tx(tx)
    mp.update(1, txs[:2])  # commit 0,1 -> WAL rewritten to pending only
    mp.close()
    mp2 = Mempool(AppConns(local_client_creator(CounterApp())).mempool,
                  wal_dir=wal_dir)
    assert mp2.reap(-1) == txs[2:]


def test_pending_tx_resubmit_after_cache_eviction_is_dup():
    class Cfg:
        size = 1000
        recheck = True
        cache_size = 2  # tiny: pending txs outlive their cache entries
    conns = AppConns(local_client_creator(CounterApp()))
    mp = Mempool(conns.mempool, config=Cfg())
    mp.check_tx(b"T")
    mp.check_tx(b"a")
    mp.check_tx(b"b")  # evicts T from cache; T still pending
    with pytest.raises(TxAlreadyInCache):
        mp.check_tx(b"T")
    assert mp.reap(-1) == [b"T", b"a", b"b"]  # no duplicate element


def test_wal_replay_drops_torn_tail(tmp_path):
    import os
    wal_dir = str(tmp_path / "mwal")
    conns = AppConns(local_client_creator(CounterApp()))
    mp = Mempool(conns.mempool, wal_dir=wal_dir)
    mp.check_tx(b"complete")
    mp.close()
    path = os.path.join(wal_dir, "wal")
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x00\xffhalf-a-record")  # truncated frame
    mp2 = Mempool(AppConns(local_client_creator(CounterApp())).mempool,
                  wal_dir=wal_dir)
    assert mp2.reap(-1) == [b"complete"]


def test_concurrent_checktx_threadsafe():
    mp, _ = make_mempool()
    errs = []

    def feed(base):
        try:
            for i in range(50):
                mp.check_tx(base + i.to_bytes(2, "big"))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=feed, args=(bytes([t]),))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert mp.size() == 200
