"""p2p stack tests: secret connection, MConnection multiplexing, switch +
reactors (models p2p/conn/connection_test.go, secret_connection_test.go,
switch_test.go)."""

import socket
import threading
import time

import pytest

from tendermint_tpu.config import P2PConfig
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    MConnection,
    NetAddress,
    NodeKey,
    Reactor,
    SecretConnection,
    SwitchError,
    pubkey_to_id,
)
from tendermint_tpu.p2p.conn.mconn import PlainFramedConn
from tendermint_tpu.p2p.test_util import (
    connect_switches,
    make_connected_switches,
    make_switch,
)
from tendermint_tpu.types.keys import PrivKey


def make_secret_pair():
    s1, s2 = socket.socketpair()
    nk1 = NodeKey(PrivKey.generate(b"\x01" * 32))
    nk2 = NodeKey(PrivKey.generate(b"\x02" * 32))
    out = {}

    def mk(name, sock, nk):
        out[name] = SecretConnection.make(sock, nk)

    t1 = threading.Thread(target=mk, args=("a", s1, nk1))
    t2 = threading.Thread(target=mk, args=("b", s2, nk2))
    t1.start(); t2.start(); t1.join(10); t2.join(10)
    return out["a"], out["b"], nk1, nk2


# --------------------------------------------------------- SecretConnection

def test_secret_connection_roundtrip_and_identity():
    a, b, nk1, nk2 = make_secret_pair()
    assert a.remote_pubkey == nk2.pubkey
    assert b.remote_pubkey == nk1.pubkey
    a.write(b"hello")
    assert b.read() == b"hello"
    b.write(b"world")
    assert a.read() == b"world"
    # large message fragments transparently
    big = bytes(range(256)) * 20  # 5120 bytes
    a.write(big)
    got = b""
    while len(got) < len(big):
        got += b.read()
    assert got == big
    a.close(); b.close()


def test_secret_connection_parallel_writers():
    """Reference parity (p2p/conn/secret_connection_test.go parallel
    read/write): concurrent writers on one SecretConnection must not
    interleave nonce order — AEAD would fail loudly at the reader on
    any desync, and every message must arrive intact exactly once."""
    a, b, _, _ = make_secret_pair()
    n_writers, per = 4, 50
    sent = [f"w{w}-m{i}".encode() for w in range(n_writers)
            for i in range(per)]

    def writer(w):
        for i in range(per):
            a.write(f"w{w}-m{i}".encode())

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_writers)]
    for t in threads:
        t.start()
    got = [b.read() for _ in range(n_writers * per)]
    for t in threads:
        t.join(10)
    assert sorted(got) == sorted(sent)
    a.close(); b.close()


def test_secret_connection_ciphertext_not_plaintext():
    s1, s2 = socket.socketpair()
    nk1 = NodeKey(PrivKey.generate(b"\x01" * 32))
    nk2 = NodeKey(PrivKey.generate(b"\x02" * 32))
    wire = []

    class SpySocket:
        def __init__(self, sock):
            self._sock = sock

        def sendall(self, data):
            wire.append(bytes(data))
            self._sock.sendall(data)

        def __getattr__(self, name):
            return getattr(self._sock, name)

    spy1 = SpySocket(s1)
    out = {}
    t1 = threading.Thread(
        target=lambda: out.update(a=SecretConnection.make(spy1, nk1)))
    t2 = threading.Thread(
        target=lambda: out.update(b=SecretConnection.make(s2, nk2)))
    t1.start(); t2.start(); t1.join(10); t2.join(10)
    out["a"].write(b"super-secret-payload")
    out["b"].read()
    assert not any(b"super-secret-payload" in w for w in wire)


def test_secret_connection_tampering_detected():
    a, b, _, _ = make_secret_pair()
    # write a frame, flip ciphertext bits in transit by writing garbage
    # directly on the raw socket with valid length framing
    import struct
    bad = bytes(40)
    a.conn.sendall(struct.pack(">I", len(bad)) + bad)
    with pytest.raises(Exception):
        b.read()


# -------------------------------------------------------------- MConnection

def make_mconn_pair(descs1=None, descs2=None, **kw):
    s1, s2 = socket.socketpair()
    descs1 = descs1 or [ChannelDescriptor(0x01, priority=1)]
    descs2 = descs2 or descs1
    recv1, recv2 = [], []
    errs = []
    m1 = MConnection(PlainFramedConn(s1), descs1,
                     on_receive=lambda ch, m: recv1.append((ch, m)),
                     on_error=lambda e: errs.append(e), **kw)
    m2 = MConnection(PlainFramedConn(s2), descs2,
                     on_receive=lambda ch, m: recv2.append((ch, m)),
                     on_error=lambda e: errs.append(e), **kw)
    m1.start(); m2.start()
    return m1, m2, recv1, recv2, errs


def wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_mconn_send_receive():
    m1, m2, recv1, recv2, errs = make_mconn_pair()
    assert m1.send(0x01, b"ping-message")
    assert wait_for(lambda: recv2 == [(0x01, b"ping-message")])
    assert m2.send(0x01, b"reply")
    assert wait_for(lambda: recv1 == [(0x01, b"reply")])
    m1.stop(); m2.stop()


def test_mconn_large_message_reassembled():
    m1, m2, _, recv2, _ = make_mconn_pair()
    big = bytes(range(256)) * 64  # 16KB, ~17 packets
    assert m1.send(0x01, big)
    assert wait_for(lambda: recv2 and recv2[0][1] == big)
    m1.stop(); m2.stop()


def test_mconn_unknown_channel_send_fails():
    m1, m2, *_ = make_mconn_pair()
    assert not m1.send(0x55, b"nope")
    m1.stop(); m2.stop()


def test_mconn_priority_scheduling():
    """High-priority channel data drains ahead of low-priority backlog."""
    descs = [ChannelDescriptor(0x01, priority=1),
             ChannelDescriptor(0x02, priority=10)]
    m1, m2, _, recv2, _ = make_mconn_pair(descs, descs)
    payload = bytes(900)
    # flood the low-priority channel, then queue one high-priority msg
    for _ in range(50):
        m1.try_send(0x01, payload)
    m1.send(0x02, b"urgent")
    assert wait_for(lambda: any(ch == 0x02 for ch, _ in recv2))
    idx_urgent = next(i for i, (ch, _) in enumerate(recv2) if ch == 0x02)
    assert idx_urgent < 45, f"urgent message arrived at index {idx_urgent}"
    m1.stop(); m2.stop()


def test_mconn_peer_close_triggers_error():
    m1, m2, _, _, errs = make_mconn_pair()
    m2.stop()  # closes the underlying socket
    assert wait_for(lambda: errs)
    assert not m1.running or wait_for(lambda: not m1.running)
    m1.stop()


def test_mconn_ping_keeps_idle_connection_alive():
    m1, m2, _, _, errs = make_mconn_pair(
        ping_interval=0.1, idle_timeout=1.0)
    time.sleep(1.5)  # > idle_timeout: only pings flow
    assert not errs
    assert m1.running and m2.running
    m1.stop(); m2.stop()


# ------------------------------------------------------------------- Switch

class EchoReactor(Reactor):
    """Echoes every message back on the same channel; records receipts."""

    def __init__(self, name, ch_id, echo=True):
        super().__init__(name)
        self.ch_id = ch_id
        self.echo = echo
        self.received = []
        self.peers_added = []
        self.peers_removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.ch_id)]

    def add_peer(self, peer):
        self.peers_added.append(peer.id)

    def remove_peer(self, peer, reason):
        self.peers_removed.append(peer.id)

    def receive(self, ch_id, peer, msg):
        self.received.append((peer.id, msg))
        if self.echo:
            peer.try_send(ch_id, b"echo:" + msg)


def test_switch_two_nodes_exchange_messages():
    r1 = EchoReactor("echo", 0x10, echo=False)
    r2 = EchoReactor("echo", 0x10, echo=True)
    sw1 = make_switch(seed=b"\x01" * 32)
    sw2 = make_switch(seed=b"\x02" * 32)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start(); sw2.start()
    p1, p2 = connect_switches(sw1, sw2)
    assert r1.peers_added and r2.peers_added
    assert p1.send(0x10, b"hello")
    assert wait_for(lambda: r2.received)
    assert r2.received[0][1] == b"hello"
    assert wait_for(lambda: r1.received)
    assert r1.received[0][1] == b"echo:hello"
    sw1.stop(); sw2.stop()


def test_switch_encrypted_handshake_and_routing():
    r1 = EchoReactor("echo", 0x10, echo=False)
    r2 = EchoReactor("echo", 0x10, echo=True)
    sw1 = make_switch(seed=b"\x01" * 32, encrypt=True)
    sw2 = make_switch(seed=b"\x02" * 32, encrypt=True)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start(); sw2.start()
    p1, _ = connect_switches(sw1, sw2)
    # authenticated identity = NodeInfo identity
    assert p1.id == sw2.node_info.id
    p1.send(0x10, b"enc")
    assert wait_for(lambda: r1.received)
    sw1.stop(); sw2.stop()


def test_switch_rejects_network_mismatch():
    sw1 = make_switch(network="chain-A", seed=b"\x01" * 32)
    sw2 = make_switch(network="chain-B", seed=b"\x02" * 32)
    sw1.add_reactor("echo", EchoReactor("echo", 0x10))
    sw2.add_reactor("echo", EchoReactor("echo", 0x10))
    with pytest.raises(RuntimeError):
        connect_switches(sw1, sw2)
    assert sw1.peers.size() == 0 and sw2.peers.size() == 0


def test_switch_listen_and_dial():
    r1 = EchoReactor("echo", 0x10, echo=True)
    r2 = EchoReactor("echo", 0x10, echo=False)
    sw1 = make_switch(seed=b"\x01" * 32)
    sw2 = make_switch(seed=b"\x02" * 32)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start(); sw2.start()
    addr = sw1.listen("127.0.0.1", 0)
    peer = sw2.dial_peer(addr)
    assert peer.id == sw1.node_info.id
    assert wait_for(lambda: sw1.peers.size() == 1)
    peer.send(0x10, b"dial-hello")
    assert wait_for(lambda: r2.received)
    assert r2.received[0][1] == b"echo:dial-hello"
    sw1.stop(); sw2.stop()


def test_switch_dial_wrong_id_rejected():
    sw1 = make_switch(seed=b"\x01" * 32, encrypt=True)
    sw2 = make_switch(seed=b"\x02" * 32, encrypt=True)
    sw1.add_reactor("e", EchoReactor("e", 0x10))
    sw2.add_reactor("e", EchoReactor("e", 0x10))
    sw1.start(); sw2.start()
    addr = sw1.listen("127.0.0.1", 0)
    wrong_id = pubkey_to_id(b"\xff" * 32)
    bad_addr = NetAddress(addr.ip, addr.port, wrong_id)
    with pytest.raises(SwitchError):
        sw2.dial_peer(bad_addr)
    sw1.stop(); sw2.stop()


def test_switch_peer_disconnect_notifies_reactors():
    r1 = EchoReactor("echo", 0x10)
    r2 = EchoReactor("echo", 0x10)
    switches = make_connected_switches(
        2, lambda i: {"echo": r1 if i == 0 else r2})
    peer = switches[0].peers.list()[0]
    switches[0].stop_peer_for_error(peer, RuntimeError("test"))
    assert r1.peers_removed == [peer.id]
    assert switches[0].peers.size() == 0
    # the other side notices the dead connection too
    assert wait_for(lambda: switches[1].peers.size() == 0)
    for sw in switches:
        sw.stop()


def test_make_connected_switches_full_mesh():
    n = 4
    reactors = [EchoReactor(f"r", 0x10, echo=False) for _ in range(n)]
    switches = make_connected_switches(n, lambda i: {"r": reactors[i]})
    for sw in switches:
        assert sw.peers.size() == n - 1
    # broadcast reaches everyone
    switches[0].broadcast(0x10, b"flood")
    assert wait_for(
        lambda: all(len(r.received) == 1 for r in reactors[1:]))
    for sw in switches:
        sw.stop()


def test_netaddress_parse_and_classify():
    a = NetAddress.from_string("127.0.0.1:46656")
    assert a.local() and not a.routable()
    b = NetAddress.from_string("8.8.8.8:26656")
    assert b.routable() and b.valid()
    nk = NodeKey(PrivKey.generate(b"\x05" * 32))
    c = NetAddress.from_string(f"{nk.id()}@10.0.0.1:26656")
    assert c.id == nk.id() and not c.routable()  # rfc1918
    with pytest.raises(ValueError):
        NetAddress.from_string("nohost")
    with pytest.raises(ValueError):
        NetAddress.from_string("zz@1.2.3.4:80")
    assert NetAddress.from_string("10.0.1.5:80").same_group(
        NetAddress.from_string("10.0.99.9:80"))


def test_node_key_persistence(tmp_path):
    path = str(tmp_path / "node_key.json")
    nk = NodeKey.load_or_generate(path)
    nk2 = NodeKey.load_or_generate(path)
    assert nk.id() == nk2.id()


# ------------------------------------------------------------- flow rate --

def test_flow_monitor_windowed_eviction_signal():
    """A previously-fast peer that stalls must drop below the eviction
    floor within one window (tmlibs/flowrate semantics used at
    blockchain/pool.go:35-42) — the lifetime average would not."""
    import time as _time
    from tendermint_tpu.p2p.conn.flowrate import FlowMonitor
    m = FlowMonitor(window_s=0.5)
    for _ in range(20):
        m.update(10_000)
    fast = m.rate
    assert fast > 7_680  # well above MIN_RECV_RATE while transferring
    _time.sleep(0.8)     # stall for > window
    assert m.rate < 1_000, m.rate      # windowed signal collapsed
    assert m.lifetime_rate > 7_680     # lifetime stat still high
    assert m.total == 200_000


def test_bp_peer_slow_after_stall(monkeypatch):
    import time as _time
    from tendermint_tpu.blockchain import pool as bpool
    monkeypatch.setattr(bpool, "MIN_RATE_GRACE_S", 0.2)
    p = bpool.BpPeer("p1", height=100)
    p.on_request()
    p.recv_monitor.window_s = 0.4
    p.recv_monitor.update(500_000)   # fast burst
    p.on_request()                   # still-pending requests
    assert not p.is_slow()           # fast while transferring
    _time.sleep(0.7)                 # stall past grace + window
    assert p.is_slow()


def test_dial_tiebreak_rule_is_symmetric():
    """Both ends must independently pick the SAME surviving connection
    (the one dialed by the smaller node id), else a simultaneous dial
    leaves each side holding the conn the other side closed — a
    permanently dead link at boot (no dial_addr on the kept-inbound
    side means no redial, and a 3-node net then stalls at height 0)."""
    from tendermint_tpu.p2p.switch import dial_tiebreak_keep_new
    a, b = "aa" * 20, "bb" * 20
    # on A (id a, smaller): A-dialed conn is outbound. It must win
    # whether it registers first (inbound dup rejected) or second
    # (replaces the inbound).
    assert dial_tiebreak_keep_new(a, b, True, False)       # new=A-dialed
    assert not dial_tiebreak_keep_new(a, b, False, True)   # new=B-dialed
    # on B (id b, larger): the A-dialed conn is INBOUND and must win.
    assert dial_tiebreak_keep_new(b, a, False, True)
    assert not dial_tiebreak_keep_new(b, a, True, False)
    # same-direction duplicates keep the existing conn (double dial)
    assert not dial_tiebreak_keep_new(a, b, True, True)
    assert not dial_tiebreak_keep_new(b, a, False, False)


def test_simultaneous_dial_converges_to_one_live_link():
    """Two switches dial each other at the same moment; after the
    tiebreak each side must hold exactly ONE peer entry and the link
    must actually CARRY TRAFFIC both ways (the pre-fix failure kept a
    dead socket registered on both sides)."""
    r1 = EchoReactor("echo", 0x10, echo=True)
    r2 = EchoReactor("echo", 0x10, echo=False)
    sw1 = make_switch(seed=b"\x11" * 32)
    sw2 = make_switch(seed=b"\x12" * 32)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start(); sw2.start()
    addr1 = sw1.listen("127.0.0.1", 0)
    addr2 = sw2.listen("127.0.0.1", 0)
    errs = []

    def dial(sw, addr):
        try:
            sw.dial_peer(addr)
        except SwitchError:
            pass  # the losing conn of the tiebreak
        except Exception as e:  # pragma: no cover - diagnostics
            errs.append(e)

    t1 = threading.Thread(target=dial, args=(sw1, addr2))
    t2 = threading.Thread(target=dial, args=(sw2, addr1))
    t1.start(); t2.start()
    t1.join(15); t2.join(15)
    assert not errs
    assert wait_for(lambda: sw1.peers.size() == 1 and
                    sw2.peers.size() == 1, timeout=10)
    # the surviving link is LIVE end to end: a message from sw2 reaches
    # sw1's echo reactor and the echo comes back
    deadline = time.monotonic() + 10
    ok = False
    while time.monotonic() < deadline and not ok:
        for p in sw2.peers.list():
            p.try_send(0x10, b"tiebreak-ping")
        ok = any(m == b"echo:tiebreak-ping" for _, m in r2.received)
        if not ok:
            time.sleep(0.1)
    assert ok, "surviving connection does not carry traffic"
    # both sides kept the SAME conn: the one dialed by the smaller id
    p1, p2 = sw1.peers.list()[0], sw2.peers.list()[0]
    small_first = sw1.node_info.id < sw2.node_info.id
    assert p1.outbound == small_first
    assert p2.outbound == (not small_first)
    sw1.stop(); sw2.stop()
