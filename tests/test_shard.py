"""Shard plane (ISSUE 15): N independent chains in one process behind
one front door — router determinism, shard isolation under a chaos
crash point, certified cross-shard reads (incl. forged-proof
rejection), arbitrary-order teardown vs the shared verifier, and the
per-shard observability labels (tm_shard_*, tm_rpc_call_seconds chain,
SLO chain attribution)."""

import copy
import subprocess
import sys
import time

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.shard import (
    CertifiedReader,
    ReadProofError,
    ShardSet,
)
from tendermint_tpu.shard.router import ShardMap, key_prefix


def wait_for(cond, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def shard2():
    s = ShardSet(2, chain_prefix="tshard")
    s.start()
    try:
        assert wait_for(lambda: s.frontier() >= 2), s.heights()
        yield s
    finally:
        s.stop()


# ------------------------------------------------------- determinism --

def test_shard_map_is_a_pure_function_of_key_and_count():
    m = ShardMap(["a", "b", "c"])
    keys = [b"k%d" % i for i in range(256)]
    first = [m.shard_of(k) for k in keys]
    assert first == [ShardMap(["a", "b", "c"]).shard_of(k)
                     for k in keys]
    # every shard owns a piece of a modest keyspace
    assert set(first) == {0, 1, 2}
    # in range, and chain_of agrees
    assert all(0 <= i < 3 for i in first)
    assert all(m.chain_of(k) == m.chains[i]
               for k, i in zip(keys, first))


def test_shard_map_deterministic_across_processes():
    """Same key -> same shard in a DIFFERENT process: the mapping has
    no per-process state (no seed, no salt, no iteration order)."""
    keys = [b"user/%d" % i for i in range(32)]
    local = [ShardMap(["a"] * 8).shard_of(k) for k in keys]
    code = (
        "from tendermint_tpu.shard.router import ShardMap\n"
        "m = ShardMap(['a'] * 8)\n"
        "print(','.join(str(m.shard_of(b'user/%d' % i)) "
        "for i in range(32)))\n")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, check=True, env={"JAX_PLATFORMS": "cpu",
                                      "PATH": "/usr/bin:/bin",
                                      "PYTHONPATH": "."},
        cwd=__import__("os").path.dirname(
            __import__("os").path.dirname(__file__)))
    remote = [int(x) for x in out.stdout.strip().split(",")]
    assert remote == local


def test_shard_map_stable_across_mapping_versions():
    """A version bump with the same chain list (rebalance bookkeeping,
    not a count change) moves NO key; a count change is visible via
    the version, which responses quote."""
    m1 = ShardMap(["a", "b", "c", "d"])
    m2 = m1.rebalanced(["a", "b", "c", "d"])
    assert m2.version == m1.version + 1
    keys = [b"acct-%d" % i for i in range(128)]
    assert [m1.shard_of(k) for k in keys] == \
        [m2.shard_of(k) for k in keys]
    obj = m2.to_obj()
    assert obj["version"] == 2 and obj["n_shards"] == 4
    assert len(obj["ranges"]) == 4
    assert obj["ranges"][0]["lo"] == "0" * 16


def test_key_prefix_routes_tx_and_query_identically():
    m = ShardMap(["a"] * 16)
    assert key_prefix(b"balance/7=100") == b"balance/7"
    assert key_prefix(b"no-equals-tx") == b"no-equals-tx"
    assert m.shard_of(key_prefix(b"balance/7=100")) == \
        m.shard_of(b"balance/7")


# ---------------------------------------------------------- assembly --

def test_shards_share_default_verifier_and_one_loop(shard2):
    v0, v1 = (n.verifier for n in shard2.nodes)
    assert v0 is v1, "shards must share the process-default verifier"
    assert all(not n._owns_verifier for n in shard2.nodes)
    assert all(n.loop is shard2.loop for n in shard2.nodes)
    assert all(not n._owns_loop for n in shard2.nodes)
    # distinct chains, distinct valsets, independent heights
    assert len(set(shard2.chains)) == 2
    pks = {n.consensus.priv_validator.pubkey.ed25519
           for n in shard2.nodes}
    assert len(pks) == 2


def test_stop_in_arbitrary_order_keeps_shared_verifier_alive():
    """The ISSUE 15 small fix: closing one shard must not close (or
    leak) the shared verifier — ownership is recorded at CONSTRUCTION,
    so even a set_default_verifier() swap between build and stop
    cannot trick a node into closing a verifier it never owned."""
    from tendermint_tpu.models.verifier import (
        default_verifier,
        set_default_verifier,
    )
    s = ShardSet(3, chain_prefix="tdown")
    shared = s.nodes[0].verifier
    assert shared is default_verifier()
    s.start()
    try:
        assert wait_for(lambda: s.frontier() >= 1), s.heights()
        # adversarial: swap the module default mid-run — the old
        # identity check (verifier is not _default) would now close
        # the SHARED verifier on the first node.stop()
        set_default_verifier(shared)  # idempotent swap, same object
        for node in (s.nodes[1], s.nodes[0], s.nodes[2]):  # odd order
            node.stop()
        # the shared verifier still verifies after every stop
        from tendermint_tpu.types.keys import PrivKey
        k = PrivKey.generate(b"\x07" * 32)
        sig = k.sign(b"still-alive")
        ok = shared.verify(
            [(k.pubkey.ed25519, b"still-alive", sig)])
        assert bool(ok.all())
        assert getattr(shared, "_closed", False) is False
    finally:
        s.nodes = []       # already stopped, arbitrary order
        s.stop()           # idempotent: loop teardown only


# --------------------------------------------------------- isolation --

@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_crashed_shard_leaves_siblings_committing():
    """Chaos crash point: one shard's consensus thread dies mid-commit
    (ChaosCrash from an armed commit fail point); its height freezes
    while every sibling keeps committing."""
    from tendermint_tpu.chaos.runner import ChaosCrash
    from tendermint_tpu.utils import fail

    s = ShardSet(3, chain_prefix="tcrash")
    s.start()
    try:
        assert wait_for(lambda: s.frontier() >= 2), s.heights()
        fired = []

        def boom(name):
            fired.append(name)
            raise ChaosCrash(f"shard crash at {name}")

        # one-shot: the NEXT shard to reach its commit-critical point
        # dies mid-commit (ChaosCrash is a BaseException — it escapes
        # the state machine exactly like the chaos runner's crash
        # plane); the before_save_block abort leaves no scheduled
        # timeout behind, so that shard is halted for good
        fail.arm("consensus.before_save_block", boom)
        assert wait_for(lambda: bool(fired)), \
            "armed commit point never fired"
        h1 = {n.gen_doc.chain_id: n.height for n in s.nodes}
        # siblings commit >= 3 more heights while exactly one shard is
        # frozen — fault isolation across chains in one process
        assert wait_for(lambda: sum(
            1 for n in s.nodes
            if n.height >= h1[n.gen_doc.chain_id] + 3) == 2), \
            s.heights()
        victims = [n for n in s.nodes
                   if n.height < h1[n.gen_doc.chain_id] + 3]
        assert len(victims) == 1
        dead = victims[0]
        h_dead = dead.height
        time.sleep(0.5)
        assert dead.height == h_dead, "crashed shard kept committing"
        living = [n for n in s.nodes if n is not dead]
        assert all(n.height > h1[n.gen_doc.chain_id] + 3
                   or n.height >= h_dead for n in living)
    finally:
        fail.disarm_all()
        s.stop()


# ----------------------------------------------------- certified reads --

def test_certified_cross_shard_read_e2e(shard2):
    addr = shard2.serve()
    from tendermint_tpu.rpc.client import JSONRPCClient
    c = JSONRPCClient(f"http://{addr[0]}:{addr[1]}")

    # write keys through the ONE front door; the router splits them
    keys = [b"acct/%d" % i for i in range(8)]
    r = c.call("broadcast_tx_batch",
               txs=[(k + b"=v/" + k).hex() for k in keys])
    assert all(x["code"] == 0 for x in r["results"])
    assert r["mapping_version"] == 1
    placed = {k: shard2.router.map.chain_of(k) for k in keys}
    assert len(set(placed.values())) == 2, \
        "expected keys on both shards"
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        vals = {k: bytes.fromhex(c.call(
            "abci_query", data=k.hex())["response"]["value"] or "")
            for k in keys}
        if all(v == b"v/" + k for k, v in vals.items()):
            break
        time.sleep(0.2)
    assert all(v == b"v/" + k for k, v in vals.items()), vals

    # in-process certified reader (a client resident on shard A
    # reading shard B): advances a ContinuousCertifier per chain
    reader = shard2.reader()
    for k in keys:
        res = reader.read(k)
        assert res["value"] == b"v/" + k
        assert res["chain_id"] == placed[k]
        assert res["certified_height"] >= res["height"] > 0
    assert reader.verified_reads == len(keys)
    assert set(reader._certifiers) == set(shard2.chains)

    # a SECOND read pays only the delta since the last certified
    # height (the continuous-certification contract)
    cert = reader._certifiers[placed[keys[0]]]
    before = cert.certified_height
    res = reader.read(keys[0])
    assert res["certified_height"] >= before

    # the HTTP transport shape verifies identically
    http_reader = CertifiedReader(call=lambda m, **p: c.call(m, **p))
    res = http_reader.read(keys[0])
    assert res["value"] == b"v/" + keys[0]

    v = telemetry.value("shard_cross_reads_total",
                        {"result": "verified"})
    assert v and v >= len(keys) + 2


def test_forged_cross_shard_proof_is_rejected(shard2):
    """Forged proofs die loudly: a flipped signature bit, a truncated
    proof chain, and a wrong-chain proof each raise ReadProofError and
    do NOT advance trust."""
    from tendermint_tpu.lite.certifier import ContinuousCertifier
    from tendermint_tpu.shard import reads

    node = shard2.node_for_key(b"forge-me")
    chain = node.gen_doc.chain_id
    genesis_vals = node.state_store.load_validators(1)
    doc = reads.serve_read(node, b"forge-me", 0)
    assert doc["height"] >= 1 and doc["proof_commits"]

    # 1. tampered signature in the newest commit
    forged = copy.deepcopy(doc)
    for v in forged["proof_commits"][-1]["signed_header"]["commit"][
            "precommits"]:
        if v:
            sig = bytearray(bytes.fromhex(v["signature"]))
            sig[0] ^= 0xFF
            v["signature"] = bytes(sig).hex()
    cert = ContinuousCertifier(chain, genesis_vals)
    with pytest.raises(ReadProofError, match="certification failed"):
        CertifiedReader.verify(forged, cert)
    # trust did not advance past the forged height
    assert cert.certified_height < doc["height"]

    # 2. truncated proof chain (value height not covered)
    truncated = copy.deepcopy(doc)
    truncated["proof_commits"] = truncated["proof_commits"][:-1]
    cert2 = ContinuousCertifier(chain, genesis_vals)
    with pytest.raises(ReadProofError, match="stops at"):
        CertifiedReader.verify(truncated, cert2)

    # 3. proof for a different chain
    wrong = copy.deepcopy(doc)
    wrong["chain_id"] = "not-" + chain
    cert3 = ContinuousCertifier(chain, genesis_vals)
    with pytest.raises(ReadProofError, match="certifier follows"):
        CertifiedReader.verify(wrong, cert3)

    rej = telemetry.value("shard_cross_reads_total",
                          {"result": "rejected"})
    # verify() raises through read()'s accounting only when called via
    # read(); the direct calls above don't count — exercise one:
    reader = shard2.reader()
    reader._certifiers[chain] = ContinuousCertifier(
        chain, genesis_vals)
    orig = reads.serve_read

    def forge(node, key, since, **kw):
        d = orig(node, key, since, **kw)
        for v in d["proof_commits"][-1]["signed_header"]["commit"][
                "precommits"]:
            if v:
                sig = bytearray(bytes.fromhex(v["signature"]))
                sig[0] ^= 0xFF
                v["signature"] = bytes(sig).hex()
        return d

    reads.serve_read = forge
    try:
        with pytest.raises(ReadProofError):
            reader.read(b"forge-me")
    finally:
        reads.serve_read = orig
    rej2 = telemetry.value("shard_cross_reads_total",
                           {"result": "rejected"})
    assert (rej2 or 0) == (rej or 0) + 1


# ------------------------------------- authenticated value proofs --

@pytest.fixture
def shard2_tree(monkeypatch):
    """Two shards whose KVStore runs the authenticated state tree
    (TM_TPU_STATE_TREE=on, ISSUE 16): certified reads carry per-key
    value proofs bound to the certified app_hash."""
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    s = ShardSet(2, chain_prefix="ttree")
    s.start()
    try:
        assert wait_for(lambda: s.frontier() >= 2), s.heights()
        yield s
    finally:
        s.stop()


def _put_and_settle(s, key, value):
    """Write via the owning shard and wait until the value is provable
    at the stable-read version (frontier - 1, what serve_read serves)."""
    node = s.node_for_key(key)
    node.mempool.check_tx(key + b"=" + value)

    def provable():
        h = node.block_store.height()
        if h < 2:
            return False
        res = node.app_conns.query.query("", key, height=h - 1,
                                         prove=True)
        return res.code == 0 and res.value == value
    assert wait_for(provable), node.height
    return node


def test_tree_backend_certified_read_proves_value_and_absence(
        shard2_tree):
    """End-to-end chain of custody: value -> tree root -> app_hash ->
    certified commit. The reader reports proven=True, and ABSENCE is
    proven the same way — a missing key never falls back to trust."""
    key = b"proved/key"
    node = _put_and_settle(shard2_tree, key, b"certified!")
    reader = shard2_tree.reader()
    res = reader.read(key)
    assert res["proven"] is True
    assert res["value"] == b"certified!"
    assert res["value_height"] == res["height"] - 1
    # the anchor was the CERTIFIED header app hash, cached on advance
    cert = reader._certifiers[node.gen_doc.chain_id]
    assert res["value_height"] + 1 in cert.app_hashes
    res2 = reader.read(b"proved/absent-key")
    assert res2["value"] == b"" and res2["proven"] is True
    assert reader.verified_reads == 2


def test_forged_value_proofs_are_rejected(shard2_tree):
    """The ISSUE 16 forged STATE-proof matrix, stacked on PR 15's
    forged COMMIT-proof matrix: tampered leaf value, truncated path,
    sibling swap, absence-proof-for-a-present-key, wrong root. Every
    case raises ReadProofError, counts a rejected read, advances no
    verified_reads — and a later honest read still succeeds."""
    from tendermint_tpu.shard import reads

    key = b"forge/value"
    # pad the OWNING shard's tree so the proof has sibling steps to
    # tamper (a single-key tree proves with an empty path)
    owner = shard2_tree.node_for_key(key)
    for i in range(8):
        owner.mempool.check_tx(b"forge/pad%d=p" % i)
    _put_and_settle(shard2_tree, key, b"honest")
    reader = shard2_tree.reader()
    base = reader.read(key)
    assert base["proven"] and base["value"] == b"honest"

    orig = reads.serve_read

    def tampered(mutate):
        def forge(node, k, since, **kw):
            d = orig(node, k, since, **kw)
            assert d.get("value_proof"), "expected a proven read"
            mutate(d)
            return d
        return forge

    def swap_sibling(d):
        steps = d["value_proof"]["steps"]
        assert steps, "proof has no sibling steps to tamper"
        steps[0][1] = "11" * 32

    cases = {
        "tampered leaf value": lambda d: d.__setitem__(
            "value", b"forged".hex()),
        "truncated path": lambda d: d["value_proof"].__setitem__(
            "steps", d["value_proof"]["steps"][:-1]),
        "sibling swap": swap_sibling,
        "absence proof for a present key": lambda d: (
            d["value_proof"].update(present=False,
                                    other_key_hash="01" * 32,
                                    other_value_hash="02" * 32),
            d.__setitem__("value", "")),
        "wrong root (n_keys binding)": lambda d:
            d["value_proof"].update(
                n_keys=d["value_proof"]["n_keys"] + 1),
    }
    for name, mutate in cases.items():
        rej = telemetry.value("shard_cross_reads_total",
                              {"result": "rejected"}) or 0
        verified = reader.verified_reads
        reads.serve_read = tampered(mutate)
        try:
            with pytest.raises(ReadProofError, match="value proof"):
                reader.read(key)
        finally:
            reads.serve_read = orig
        assert telemetry.value("shard_cross_reads_total",
                               {"result": "rejected"}) == rej + 1, name
        assert reader.verified_reads == verified, name
    # forgeries never poisoned the certifier: honest read verifies
    res = reader.read(key)
    assert res["proven"] and res["value"] == b"honest"


def test_proof_carrying_abci_query_over_http(shard2_tree):
    """ISSUE 16 satellite: prove=True abci_query over the REAL HTTP
    front door (loop mode). The proof bytes decode client-side and
    verify against the app_hash of the NEXT height's header fetched
    via /commit — plus the tamper counterexample on the same shape."""
    from tendermint_tpu import statetree
    from tendermint_tpu.rpc.client import JSONRPCClient

    key = b"http/proved"
    node = _put_and_settle(shard2_tree, key, b"over-the-wire")
    addr = shard2_tree.serve()
    chain = shard2_tree.router.map.chain_of(key)
    c = JSONRPCClient(f"http://{addr[0]}:{addr[1]}")

    # retry: the shard commits continuously and the tree retains a
    # bounded version window, so re-pin `version` per attempt
    r = {}
    version = 0
    for _ in range(8):
        version = node.block_store.height() - 1
        r = c.call("abci_query", data=key.hex(), height=version,
                   prove=True)["response"]
        if int(r.get("code") or 0) == 0 and r.get("proof"):
            break
    assert bytes.fromhex(r["value"]) == b"over-the-wire"
    assert int(r["height"]) == version
    pf = statetree.proof_from_bytes(bytes.fromhex(r["proof"]))
    hdr = c.call("commit", height=version + 1, chain_id=chain)["header"]
    anchor = bytes.fromhex(hdr["app_hash"])
    statetree.verify(pf, key, b"over-the-wire", anchor)
    with pytest.raises(statetree.ProofError):
        statetree.verify(pf, key, b"tampered-on-the-wire", anchor)


def test_tx_search_through_front_door(shard2):
    """ISSUE 16 satellite: tx_search fans out to every shard's KV
    indexer and merges — chain-tagged records, (height, index, chain)
    order, pagination over the MERGED set, chain_id scoping."""
    import hashlib

    from tendermint_tpu.rpc.client import JSONRPCClient
    addr = shard2.serve()
    c = JSONRPCClient(f"http://{addr[0]}:{addr[1]}")

    keys = [b"srch/%d" % i for i in range(8)]
    txs = [k + b"=x" for k in keys]
    placed = {k: shard2.router.map.chain_of(k) for k in keys}
    assert len(set(placed.values())) == 2
    r = c.call("broadcast_tx_batch", txs=[t.hex() for t in txs])
    assert all(x["code"] == 0 for x in r["results"])

    # point lookup by hash WITHOUT naming the shard
    h0 = hashlib.sha256(txs[0]).hexdigest()
    assert wait_for(lambda: c.call(
        "tx_search", query=f"tx.hash='{h0}'")["total_count"] == 1)
    doc = c.call("tx_search", query=f"tx.hash='{h0}'")
    rec = doc["txs"][0]
    assert rec["chain_id"] == placed[keys[0]]
    assert bytes.fromhex(rec["tx"]) == txs[0]
    assert doc["mapping_version"] == 1

    # reserved-tag range query merges BOTH shards' results in order
    assert wait_for(lambda: c.call(
        "tx_search", query="tx.height >= 1",
        per_page=100)["total_count"] >= len(txs))
    doc = c.call("tx_search", query="tx.height >= 1", per_page=100)
    recs = doc["txs"]
    assert {x["chain_id"] for x in recs} == set(shard2.chains)
    order = [(x["height"], x["index"], x["chain_id"]) for x in recs]
    assert order == sorted(order)

    page1 = c.call("tx_search", query="tx.height >= 1", per_page=3)
    assert len(page1["txs"]) == 3
    assert page1["total_count"] == doc["total_count"]
    page2 = c.call("tx_search", query="tx.height >= 1", per_page=3,
                   page=2)
    assert page2["txs"][0] == doc["txs"][3]

    one = c.call("tx_search", query="tx.height >= 1", per_page=100,
                 chain_id=shard2.chains[0])
    assert {x["chain_id"] for x in one["txs"]} == {shard2.chains[0]}


# ------------------------------------------------------ observability --

def test_front_door_labels_and_shard_telemetry(shard2):
    addr = shard2.serve()
    from tendermint_tpu.rpc.client import JSONRPCClient
    c = JSONRPCClient(f"http://{addr[0]}:{addr[1]}")

    key = b"labelled-key"
    chain = shard2.router.map.chain_of(key)
    before = telemetry.value(
        "rpc_call_seconds",
        {"route": "broadcast_tx_sync", "chain": chain})
    r = c.call("broadcast_tx_sync", tx=(key + b"=1").hex())
    assert r["code"] == 0
    after = telemetry.value(
        "rpc_call_seconds",
        {"route": "broadcast_tx_sync", "chain": chain})
    assert after["count"] == (before["count"] if before else 0) + 1

    # chain_id params a client mints do NOT label: unknown ids fall
    # back to "" (bounded label contract)
    resolved = shard2.router.chain_of_call(
        "status", {"chain_id": "client-minted"})
    assert resolved == ""
    assert shard2.router.chain_of_call(
        "status", {"chain_id": chain}) == chain

    # per-shard height gauge updated on the commit path
    doc = c.call("shards")
    for ch in shard2.chains:
        g = telemetry.value("shard_height", {"chain": ch})
        assert g and g >= 1
    assert doc["heights"][chain] >= 1
    assert telemetry.value("shard_mapping_version") == 1

    # chain-scoped passthrough: status of a NAMED shard
    st = c.call("status", chain_id=shard2.chains[1])
    assert st["latest_block_height"] >= 1
    with pytest.raises(Exception):
        c.call("status", chain_id="no-such-chain")

    hz = c.call("healthz")
    assert hz["shards"]["n_shards"] == 2
    assert set(hz["shards"]["heights"]) == set(shard2.chains)


def test_slo_chain_attribution(monkeypatch):
    """telemetry/slo.py shard attribution: admit(chain=) flows to the
    tm_slo_stage_seconds chain label and the per-chain snapshot
    section; the chain value is server-supplied, never client-minted
    (rpc/core stamps its OWN genesis chain id)."""
    from tendermint_tpu.telemetry import slo

    monkeypatch.setenv("TM_TPU_SLO", "on")
    slo.reset()
    try:
        tx = b"slo-shard-tx"
        slo.admit(tx, chain="chain-A")
        slo.mark(tx, "checktx")
        slo.mark(tx, "commit", height=3)
        v = telemetry.value("slo_stage_seconds",
                            {"stage": "checktx", "chain": "chain-A"})
        assert v and v["count"] >= 1
        v2 = telemetry.value("slo_stage_seconds",
                             {"stage": "e2e_commit",
                              "chain": "chain-A"})
        assert v2 and v2["count"] >= 1
        snap = slo.snapshot(windows=False)
        assert snap["chains"]["chain-A"]["sampled"] == 1
        # an unattributed (gossip-arrived) tx labels chain=""
        tx2 = b"slo-plain-tx"
        slo.admit(tx2)
        slo.mark(tx2, "checktx")
        v3 = telemetry.value("slo_stage_seconds",
                             {"stage": "checktx", "chain": ""})
        assert v3 and v3["count"] >= 1
    finally:
        monkeypatch.delenv("TM_TPU_SLO")
        slo.reset()
