"""Edge serving plane (ISSUE 19): certifier-follower staleness
honesty, replica self-verification, forged-proof rejection through a
replica, and the PR 12 admission plane on the edge tier driven by the
open-loop harness."""

import time

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.serving.edge import (
    CertifierFollower,
    ReplicaCore,
    make_replica_server,
)
from tendermint_tpu.shard import ShardSet
from tendermint_tpu.shard.reads import CertifiedReader, ReadProofError


def wait_for(cond, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def serving1(monkeypatch):
    """One tree-backed in-process chain — the stores a replica's
    follower certifies from (the follower only reads block/state
    stores, so a local committing node is a faithful stand-in for a
    fast-synced one)."""
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    s = ShardSet(1, chain_prefix="tserve")
    s.start()
    try:
        assert wait_for(lambda: s.frontier() >= 3), s.heights()
        yield s
    finally:
        s.stop()


def _put_and_settle(node, key, value):
    node.mempool.check_tx(key + b"=" + value)

    def provable():
        h = node.block_store.height()
        if h < 2:
            return False
        res = node.app_conns.query.query("", key, height=h - 1,
                                         prove=True)
        return res.code == 0 and res.value == value
    assert wait_for(provable), node.height
    return node


# --------------------------------------------- staleness honesty --

def test_follower_certifies_to_frontier_and_reports_zero_lag(serving1):
    node = serving1.nodes[0]
    f = CertifierFollower(node, max_lag=5)
    assert f.catch_up() > 0
    assert f.certified_height == node.block_store.height() - f.lag
    assert f.lag <= 1          # frontier may move mid-assert
    st = f.status()
    assert st["role"] == "replica" and st["failed"] is None
    assert st["trust_anchor"] == 0     # genesis-seeded
    assert telemetry.value("edge_certified_height") == \
        f.certified_height


def test_follower_behind_by_k_reports_honest_lag_and_flips_healthz(
        serving1):
    """A replica behind by k heights says so in every response, and
    /healthz flips once k passes the configured threshold — staleness
    is never hidden (the satellite-3 surface)."""
    node = serving1.nodes[0]
    assert wait_for(lambda: node.block_store.height() >= 5)
    f = CertifierFollower(node, max_lag=2)
    h = node.block_store.height()
    f.catch_up(up_to=h - 4)
    assert f.certified_height == h - 4
    assert f.lag >= 4          # honest: frontier only grows
    assert not f.ok            # 4 > max_lag=2
    core = ReplicaCore.__new__(ReplicaCore)
    from tendermint_tpu.rpc.core import RPCCore, RPCEnv
    core._core = RPCCore(RPCEnv.from_node(node))
    core.node, core.follower = node, f
    doc = core.status()
    assert doc["edge"]["certified_height"] == h - 4
    assert doc["edge"]["lag"] >= 4
    hz = core.healthz()
    assert hz["ok"] is False and hz["edge"]["ok"] is False
    # catching up recovers the verdict
    f.catch_up()
    assert f.ok
    assert core.healthz()["edge"]["ok"] is True


def test_forged_commit_in_stores_freezes_trust_and_fails_health(
        serving1, monkeypatch):
    """A forged commit below the frontier halts certification exactly
    where it broke: certified_height freezes, the failure is recorded,
    lag grows honestly, and /healthz goes not-ok."""
    from tendermint_tpu.shard import reads as _reads

    node = serving1.nodes[0]
    orig = _reads.full_commit_at

    def forged(store, state_store, height):
        import copy
        fc = orig(store, state_store, height)
        if fc is not None and height >= 2:
            fc = copy.deepcopy(fc)    # never mutate live-store objects
            for v in fc.signed_header.commit.precommits:
                if v is not None:
                    sig = bytearray(v.signature)
                    sig[0] ^= 0xFF
                    v.signature = bytes(sig)
        return fc

    monkeypatch.setattr(_reads, "full_commit_at", forged)
    f = CertifierFollower(node, max_lag=100)
    f.catch_up()
    assert f.failed is not None and "height 2" in f.failed
    assert f.certified_height == 1     # trust never passed the forgery
    assert not f.ok
    assert (telemetry.value("edge_cert_failures_total") or 0) >= 1
    # catch_up refuses to advance past the recorded failure
    before = f.certified_height
    f.catch_up()
    assert f.certified_height == before


# ------------------------------------------ replica-served reads --

def test_replica_read_serves_verified_proof_and_stamps_staleness(
        serving1):
    node = serving1.nodes[0]
    _put_and_settle(node, b"edge/k1", b"v1")
    f = CertifierFollower(node, max_lag=50)
    f.catch_up()
    server, core = make_replica_server(node, f)
    doc = core.replica_read(b"edge/k1")
    assert doc["edge"]["certified_height"] >= doc["height"]
    assert doc["value_proof"] is not None
    assert bytes.fromhex(doc["value"]) == b"v1"
    assert telemetry.value("edge_reads_total",
                           {"result": "verified"})
    # an untrusting client re-verifies the whole chain of custody
    # from the GENESIS valset — e2e through a replica response
    from tendermint_tpu.lite.certifier import ContinuousCertifier
    cert = ContinuousCertifier(node.gen_doc.chain_id,
                               node.state_store.load_validators(1))
    CertifiedReader.verify(doc, cert)
    assert cert.certified_height >= doc["height"]


def test_replica_self_verification_rejects_tampered_value(
        serving1, monkeypatch):
    """A replica whose read path hands out a value that does not match
    the certified proof REFUSES to serve it (forged-proof rejection
    e2e through the replica, server side)."""
    from tendermint_tpu.rpc.server import RPCError
    from tendermint_tpu.shard import reads as _reads

    node = serving1.nodes[0]
    _put_and_settle(node, b"edge/forged", b"honest")
    f = CertifierFollower(node, max_lag=50)
    f.catch_up()
    server, core = make_replica_server(node, f)
    orig = _reads.serve_read

    def tampered(n, key, since_height=0, **kw):
        d = orig(n, key, since_height=since_height, **kw)
        d["value"] = b"forged!".hex()
        return d

    monkeypatch.setattr(_reads, "serve_read", tampered)
    before = telemetry.value("edge_reads_total",
                             {"result": "rejected"}) or 0
    with pytest.raises(RPCError, match="self-verification failed"):
        core.replica_read(b"edge/forged")
    assert telemetry.value("edge_reads_total",
                           {"result": "rejected"}) == before + 1
    # the client-side certifier rejects the same tampering
    monkeypatch.setattr(_reads, "serve_read", orig)
    doc = core.replica_read(b"edge/forged")
    doc["value"] = b"forged!".hex()
    from tendermint_tpu.lite.certifier import ContinuousCertifier
    cert = ContinuousCertifier(node.gen_doc.chain_id,
                               node.state_store.load_validators(1))
    with pytest.raises(ReadProofError):
        CertifiedReader.verify(doc, cert)


# ------------------------------- admission control at the edge --

def test_edge_admission_sheds_conns_and_rate_limits_under_harness(
        serving1, monkeypatch):
    """Satellite 2: the PR 12 admission plane guards replica RPC
    servers — over-cap connections get the 503 handshake refusal, an
    over-rate client gets structured -32005 — driven by the open-loop
    fleet itself, which classifies both shed modes."""
    from tendermint_tpu.serving.loadgen import OpenLoopFleet, op_query_prove

    monkeypatch.setenv("TM_TPU_RPC_MAX_CONNS", "20")
    monkeypatch.setenv("TM_TPU_RPC_RATE", "40")
    node = serving1.nodes[0]
    _put_and_settle(node, b"edge/adm", b"v")
    f = CertifierFollower(node, max_lag=50)
    f.catch_up()
    loop = serving1.ensure_loop()
    if not loop.running:
        loop.start()
    server, core = make_replica_server(node, f, loop=loop)
    host, port = server.serve("127.0.0.1", 0)
    fleet = OpenLoopFleet(host, port, seed=7)
    try:
        admitted = fleet.connect(30)
        assert admitted <= 20
        assert fleet.shed_conns >= 10       # conn-cap refusals
        point = fleet.run(
            2.0, rate=200.0,
            mix=[("query_prove", 1.0, op_query_prove(
                keyspace=1, prefix="edge/adm"))],
            drain_s=3.0)
        # one client IP at 5x the bucket rate: most ops shed with the
        # structured rate-limit error, the rest complete
        assert point["errors"]["rate_limited"] > 0
        assert point["completed_ok"] > 0
        assert point["per_kind"]["query_prove"]["offered"] >= 300
    finally:
        fleet.close()
        server.stop()
