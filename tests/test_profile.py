"""Runtime introspection plane (telemetry/profile.py + queues.py):
profiler on/off neutrality of the hot path, subsystem attribution on a
synthetic busy thread, lock-wait recognition, collapsed-stack caps,
queue gauge correctness under fill/drain, saturation watchdog
fires-once-and-re-arms, weakref pruning, /healthz + /debug/pprof over
HTTP, debug_profile RPC actions, cluster profile merging (the
scripts/profile_merge.py path), the stall flight recorder's embedded
profile + queue table, and bench_trend's trajectory gate."""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

# the operational CLIs under test (profile_merge, bench_trend) live in
# scripts/, which is not a package — importable the way trace_merge's
# own header does it
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry import profile, queues


@pytest.fixture(autouse=True)
def _introspection_reset(monkeypatch):
    """Profiler and observatory are process-global; every test starts
    from the off/empty state and leaves nothing running."""
    monkeypatch.delenv("TM_TPU_PROF", raising=False)
    monkeypatch.delenv("TM_TPU_PROF_HZ", raising=False)
    monkeypatch.delenv("TM_TPU_QUEUE_WATCH", raising=False)
    profile.configure("off")
    queues.configure("on")
    queues.reset()
    yield
    profile.stop()
    p = profile.get()
    if p is not None:
        p.clear()
    profile.configure("off")
    queues.configure("on")
    queues.reset()


# ------------------------------------------------------------- profiler

def _spin_in_ops(stop: threading.Event) -> threading.Thread:
    """A busy thread whose leaf frames live under tendermint_tpu/ops —
    the subsystem the attribution test expects to dominate."""
    from tendermint_tpu.ops import merkle

    def busy():
        data = [b"x%d" % i for i in range(32)]
        while not stop.is_set():
            merkle.root_host(data)

    t = threading.Thread(target=busy, daemon=True, name="tm-prof-busy")
    t.start()
    return t


def test_off_means_no_thread_and_noop_entry_points():
    assert profile.enabled() is False
    assert profile.maybe_start() is None
    assert profile.get() is None or not profile.get().running
    # the unconditional snapshot (healthz/stall embed) is still safe
    snap = profile.snapshot()
    assert snap["running"] is False and snap["samples"] == 0


def test_knob_enables_and_sets_hz(monkeypatch):
    monkeypatch.setenv("TM_TPU_PROF", "on")
    monkeypatch.setenv("TM_TPU_PROF_HZ", "123.0")
    assert profile.enabled() is True
    assert profile.default_hz() == 123.0
    p = profile.maybe_start()
    assert p is not None and p.running and p.hz == 123.0
    profile.stop()
    assert not p.running


def test_hot_path_bytes_identical_with_profiler_running():
    """The profiler only OBSERVES: block serialization + part-set
    roots under active sampling are byte-for-byte the unprofiled
    ones."""
    from tendermint_tpu.types.block import Block, Data, Header

    def build():
        h = Header(chain_id="prof-test", height=3, time_ns=1,
                   validators_hash=b"\x02" * 32)
        blk = Block(h, Data([b"k=v", b"a=b"]))
        blk.fill_header()
        return blk

    ref = build()
    before = (ref.to_bytes(), ref.make_part_set(64).header().hash)
    p = profile.start(hz=500)
    assert p.running
    try:
        for _ in range(25):
            blk = build()
            during = (blk.to_bytes(),
                      blk.make_part_set(64).header().hash)
            assert during == before
    finally:
        profile.stop()


def test_subsystem_attribution_on_busy_thread():
    """The synthetic busy thread's samples land under its OWN thread
    label with an ops/native subsystem (root_host dispatches into
    native/ when the C plane is available, ops/ otherwise — the split
    itself is the attribution working). Asserted per-thread, not on
    global shares: in a full-suite run other modules' leftover
    threads legitimately share the core."""
    def our_samples():
        return sum(telemetry.value(
            "prof_samples_total",
            {"subsystem": s, "thread": "tm-prof-busy"}) or 0
            for s in ("ops", "native"))

    base = our_samples()
    stop = threading.Event()
    t = _spin_in_ops(stop)
    p = profile.start(hz=300)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            time.sleep(0.05)
            if our_samples() - base >= 5:
                break
        else:
            pytest.fail(
                f"busy thread never attributed: "
                f"{p.subsystem_shares()} (ours: {our_samples() - base})")
    finally:
        profile.stop()
        stop.set()
        t.join(timeout=2.0)
    # shares are a distribution over busy samples
    assert abs(sum(p.subsystem_shares().values()) - 1.0) < 0.01
    # and the busy tree shows up in the distribution at all
    shares = p.subsystem_shares()
    assert shares.get("ops", 0.0) + shares.get("native", 0.0) > 0.0


def test_lock_wait_recognized_not_counted_busy():
    """A thread parked in Condition.wait (a threading.py leaf frame) is
    a lock-wait sample: excluded from busy shares, charged to
    tm_prof_lock_wait_samples_total, flagged in the collapsed stack."""
    cond = threading.Condition()
    stop = threading.Event()

    def parked():
        with cond:
            while not stop.is_set():
                cond.wait(timeout=5.0)

    t = threading.Thread(target=parked, daemon=True,
                         name="tm-prof-parked")
    t.start()
    p = profile.start(hz=300)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                p.snapshot()["wait_samples"] < 5:
            time.sleep(0.05)
    finally:
        profile.stop()
        stop.set()
        with cond:
            cond.notify_all()
        t.join(timeout=2.0)
    snap = p.snapshot()
    assert snap["wait_samples"] >= 5
    assert "[lock_wait]" in p.collapsed()
    # parked time is not CPU share: busy totals reconcile without it
    assert snap["samples"] == sum(snap["subsystems"].values())
    assert sum(snap["lock_wait"].values()) == snap["wait_samples"]


def test_collapsed_format_and_stack_cap():
    p = profile.SamplingProfiler(hz=100, max_stacks=2)
    # synthesize records via the internal recorder on real frames
    import sys
    frame = sys._current_frames()[threading.get_ident()]
    for _ in range(5):
        p._record(frame, "t-a")
    lines = [ln for ln in p.collapsed().splitlines() if ln]
    assert all(" " in ln and ";" in ln for ln in lines)
    # every line is "stack N" with integer N
    for ln in lines:
        stack, n = ln.rsplit(" ", 1)
        assert int(n) >= 1 and stack.startswith("t-a;")
    # overflow past max_stacks aggregates under (truncated)
    snap_before = p.snapshot()["stacks"]
    assert snap_before <= 2
    # force two distinct stacks then a third: the third truncates

    def one_deeper():
        return sys._current_frames()[threading.get_ident()]

    p._record(one_deeper(), "t-b")
    p._record(frame, "t-c")
    assert p.snapshot()["stacks_dropped"] >= 1
    assert any("(truncated)" in ln for ln in p.collapsed().splitlines())


def test_thread_name_normalization():
    assert profile._normalize_thread("Thread-12 (worker)") == "Thread"
    assert profile._normalize_thread("tm-verify-fetch-3") == \
        "tm-verify-fetch"
    assert profile._normalize_thread("mconn-send") == "mconn-send"
    assert profile._normalize_thread("rpc-http") == "rpc-http"


# ------------------------------------------------------ queue observatory

class _FakeQueue:
    def __init__(self):
        self.items = []


def test_queue_gauges_under_fill_and_drain():
    q = _FakeQueue()
    queues.register("test.fill", q, depth=lambda o: len(o.items),
                    capacity=8)
    telemetry.set_enabled(True)
    try:
        q.items = [1, 2, 3]
        queues.poll()
        assert telemetry.value("queue_depth", {"queue": "test.fill"}) == 3
        assert telemetry.value("queue_capacity",
                               {"queue": "test.fill"}) == 8
        assert telemetry.value("queue_high_water",
                               {"queue": "test.fill"}) == 3
        assert telemetry.value("queue_saturation",
                               {"queue": "test.fill"}) == pytest.approx(
            3 / 8)
        q.items = []
        queues.poll()
        assert telemetry.value("queue_depth",
                               {"queue": "test.fill"}) == 0
        # high water survives the drain
        assert telemetry.value("queue_high_water",
                               {"queue": "test.fill"}) == 3
        t = queues.table()["test.fill"]
        assert t["high_water"] == 3 and t["depth"] == 0
        assert t["instances"] == 1 and t["wait_s"] == 0.0
    finally:
        telemetry.set_enabled(True)


def test_fullest_instance_wins_and_weakref_prunes():
    a, b = _FakeQueue(), _FakeQueue()
    queues.register("test.multi", a, depth=lambda o: len(o.items),
                    capacity=10)
    queues.register("test.multi", b, depth=lambda o: len(o.items),
                    capacity=10)
    a.items, b.items = [1], [1, 2, 3, 4, 5]
    queues.poll()
    t = queues.table()["test.multi"]
    assert t["depth"] == 5 and t["instances"] == 2
    del b
    import gc
    gc.collect()
    queues.poll()
    t = queues.table()["test.multi"]
    assert t["instances"] == 1 and t["depth"] == 1


def test_watchdog_fires_once_and_rearms():
    q = _FakeQueue()
    queues.register("test.sat", q, depth=lambda o: len(o.items),
                    capacity=10)
    fired = []
    queues.on_saturation(lambda k, s, d: fired.append((k, d)))
    q.items = list(range(9))          # 90% > threshold
    queues.poll()
    queues.poll()                     # still saturated: same episode
    queues.poll()
    assert fired == [("test.sat", 9)]
    assert queues.saturated() == ["test.sat"]
    q.items = [1]                     # drains: re-arm
    queues.poll()
    assert queues.saturated() == []
    q.items = list(range(10))         # second episode
    queues.poll()
    assert fired == [("test.sat", 9), ("test.sat", 10)]
    assert queues.table()["test.sat"]["events"] == 2


def test_watch_thread_and_off_knob(monkeypatch):
    # on: the watcher thread runs sweeps without explicit poll()
    q = _FakeQueue()
    queues.register("test.watch", q, depth=lambda o: len(o.items),
                    capacity=4)
    monkeypatch.setenv("TM_TPU_QUEUE_WATCH", "0.02")
    assert queues.ensure_watch() is True
    q.items = [1, 2]
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        if queues.table().get("test.watch", {}).get("depth") == 2:
            break
        time.sleep(0.02)
    else:
        pytest.fail("watcher never swept")
    queues.stop_watch()
    # off: registration short-circuits to the no-op probe
    monkeypatch.setenv("TM_TPU_QUEUE_WATCH", "off")
    probe = queues.register("test.noop", q,
                            depth=lambda o: len(o.items), capacity=4)
    assert probe is queues._NOOP_PROBE
    assert queues.ensure_watch() is False


def test_real_owners_register_into_catalog():
    """The wired owners (EventBus subscription, coalescer) land in the
    catalog with live depths; unsubscribe/close removes them."""
    from tendermint_tpu.types.events import EventBus
    bus = EventBus()
    sub = bus.subscribe("obs-test", "tm.event = 'Tx'", capacity=4)
    queues.poll()
    t = queues.table()["event.subscriber"]
    assert t["instances"] >= 1 and t["capacity"] == 4
    bus.publish("Tx", {"n": 1}, {"tx.hash": "AA"})
    queues.poll()
    assert queues.table()["event.subscriber"]["depth"] == 1
    assert sub.qsize() == 1
    bus.unsubscribe_all("obs-test")
    queues.poll()
    assert queues.table()["event.subscriber"]["instances"] == 0

    from tendermint_tpu.models.coalescer import DispatchCoalescer
    co = DispatchCoalescer(lambda items: (lambda: [True] * len(items)),
                           max_batch=64)
    queues.poll()
    assert queues.table()["verifier.coalesce"]["capacity"] == 64
    co.close()
    queues.poll()
    assert queues.table()["verifier.coalesce"]["instances"] == 0


# --------------------------------------------------------- RPC surface

def test_healthz_and_pprof_over_http(monkeypatch):
    from tendermint_tpu.rpc.client import JSONRPCClient
    from tendermint_tpu.rpc.core import RPCEnv, make_server
    q = _FakeQueue()
    queues.register("test.http", q, depth=lambda o: len(o.items),
                    capacity=10)
    server, _core = make_server(RPCEnv())
    host, port = server.serve("127.0.0.1", 0)
    try:
        # healthy: nothing saturated, no stall detector, profiler off
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["ok"] is True
        assert doc["queues"]["saturated"] == []
        assert "test.http" in doc["queues"]["table"]
        assert doc["profile"]["running"] is False
        # saturate: the verdict flips
        q.items = list(range(10))
        queues.poll()
        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["ok"] is False
        assert doc["queues"]["saturated"] == ["test.http"]

        # debug_profile RPC: start -> dump -> stop
        c = JSONRPCClient(f"http://{host}:{port}")
        st = c.call("debug_profile", action="status")
        assert st["running"] is False
        c.call("debug_profile", action="start", hz=200)
        stop = threading.Event()
        t = _spin_in_ops(stop)
        deadline = time.monotonic() + 5.0
        dump = {}
        while time.monotonic() < deadline:
            time.sleep(0.05)
            dump = c.call("debug_profile", action="dump")
            if dump["samples"] >= 10:
                break
        stop.set()
        t.join(timeout=2.0)
        assert dump["samples"] >= 10 and dump["collapsed"]
        # raw pprof path serves the same collapsed text, text/plain
        with urllib.request.urlopen(
                f"http://{host}:{port}/debug/pprof", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert text.strip() and ";" in text
        out = c.call("debug_profile", action="stop")
        assert out["running"] is False
        assert c.call("debug_profile", action="status")[
            "running"] is False
    finally:
        server.stop()
        profile.stop()


# ------------------------------------------------------------- merging

def _synthetic_dump(node: str, subsys: dict, waits: dict,
                    stacks: dict) -> dict:
    return {
        "node": node,
        "samples": sum(subsys.values()),
        "wait_samples": sum(waits.values()),
        "subsystems": subsys, "lock_wait": waits,
        "shares": {}, "collapsed": "\n".join(
            f"{k} {v}" for k, v in stacks.items()),
    }


def test_profile_merge_two_nodes():
    d1 = _synthetic_dump("aaa", {"consensus": 60, "p2p": 40},
                         {"consensus": 10},
                         {"main;a.f;b.g": 60, "main;a.f;c.h": 40})
    d2 = _synthetic_dump("bbb", {"consensus": 20, "verifier": 80},
                         {"p2p": 5},
                         {"main;a.f;b.g": 100})
    merged = profile.merge_dumps([d1, d2])
    assert merged["nodes"] == ["aaa", "bbb"]
    assert merged["samples"] == 200 and merged["wait_samples"] == 15
    assert merged["subsystems"] == {"consensus": 80, "p2p": 40,
                                    "verifier": 80}
    assert merged["shares"]["consensus"] == pytest.approx(0.4)
    assert abs(sum(merged["shares"].values()) - 1.0) < 0.01
    # per-node trees re-rooted so one flamegraph holds the cluster
    lines = merged["collapsed"].splitlines()
    assert "node:aaa;main;a.f;b.g 60" in lines
    assert "node:bbb;main;a.f;b.g 100" in lines


def test_profile_merge_script_on_files(tmp_path):
    import profile_merge
    d1 = _synthetic_dump("n0", {"consensus": 10}, {}, {"m.f;m.g": 10})
    d2 = _synthetic_dump("n1", {"p2p": 30}, {}, {"m.f;m.h": 30})
    f1, f2 = tmp_path / "d0.json", tmp_path / "d1.json"
    f1.write_text(json.dumps(d1))
    f2.write_text(json.dumps(d2))
    out = tmp_path / "merged.collapsed"
    report = tmp_path / "report.json"
    rc = profile_merge.main(["--files", str(f1), str(f2),
                             "--out", str(out),
                             "--report", str(report)])
    assert rc == 0
    text = out.read_text()
    assert "node:n0;" in text and "node:n1;" in text
    rep = json.loads(report.read_text())
    assert rep["samples_busy"] == 40
    assert rep["shares"]["p2p"] == pytest.approx(0.75)


# ------------------------------------------------- stall flight recorder

def test_stall_dump_embeds_profile_and_queue_table(tmp_path):
    """Satellite: a stall capture is self-diagnosing — the flight
    recorder document carries the profiler snapshot and the queue
    high-water table alongside the causal timeline."""
    from tendermint_tpu.telemetry import causal

    q = _FakeQueue()
    queues.register("test.stall", q, depth=lambda o: len(o.items),
                    capacity=5)
    q.items = [1, 2, 3, 4]
    queues.poll()
    p = profile.start(hz=100)
    time.sleep(0.05)

    # the node's _on_stall path, driven without a full Node: replicate
    # its doc assembly through the same module entry points
    doc = {"height": 7, "stalled_s": 1.5,
           "timeline": causal.dump(),
           "profile": profile.snapshot(),
           "queues": queues.table()}
    profile.stop()
    path = tmp_path / "tm_stall_h7.json"
    path.write_text(json.dumps(doc))
    back = json.loads(path.read_text())
    assert back["queues"]["test.stall"]["high_water"] == 4
    assert back["profile"]["running"] in (True, False)
    assert "collapsed" in back["profile"]
    assert back["timeline"]["events"] >= 0


def test_node_on_stall_writes_self_diagnosing_dump(tmp_path,
                                                   monkeypatch):
    """The REAL Node._on_stall: build an in-memory node, invoke the
    stall callback directly, and assert the dump file embeds profile +
    queues next to the timeline."""
    from tendermint_tpu.config import test_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator,
                                      PrivKey)
    from tendermint_tpu.types.priv_validator import (LocalSigner,
                                                     PrivValidator)

    key = PrivKey.generate(b"\x0b" * 32)
    gen = GenesisDoc(chain_id="stall-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519,
                                                  10)])
    cfg = test_config("")
    monkeypatch.setattr("tempfile.gettempdir", lambda: str(tmp_path))
    node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True)
    try:
        node._on_stall(3, 2.0)
    finally:
        node.stop()
    dumps = list(tmp_path.glob("tm_stall_h3_*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert "profile" in doc and "queues" in doc
    assert doc["profile"]["running"] is False  # knob off: observed only
    assert isinstance(doc["queues"], dict)
    assert "consensus" in doc


# ------------------------------------------------------------ trendline

def test_bench_trend_walk_and_gate(tmp_path):
    import bench_trend
    assert bench_trend.walk({"a": {"b": [1, 2, 3]}}, "a.b[-1]") == 3
    assert bench_trend.walk(
        {"points": [{"callers": 4, "v": 9}, {"callers": 16, "v": 11}]},
        "points[callers=16].v") == 11
    assert bench_trend.walk({"a": 1}, "missing") is None

    pts = [
        {"metric": "m", "pr": "PR 7", "value": 10.0, "unit": "x",
         "direction": "up"},
        {"metric": "m", "pr": "PR 10", "value": 7.0, "unit": "x",
         "direction": "up"},
    ]
    regs = bench_trend.gate([dict(p) for p in pts], threshold=0.20)
    assert len(regs) == 1 and regs[0]["regression"] == pytest.approx(0.3)
    # within threshold: clean
    pts[1]["value"] = 9.0
    assert bench_trend.gate([dict(p) for p in pts], 0.20) == []
    # direction-aware: lower-is-better regression
    down = [
        {"metric": "lat", "pr": "PR 8", "value": 100.0, "unit": "ms",
         "direction": "down"},
        {"metric": "lat", "pr": "PR 10", "value": 130.0, "unit": "ms",
         "direction": "down"},
    ]
    regs = bench_trend.gate(down, 0.20)
    assert len(regs) == 1


def test_bench_trend_runs_on_the_committed_artifacts(tmp_path):
    """The real repo artifacts parse, attribute to PRs, and pass the
    gate (committing a regression would fail tier-1 right here)."""
    import bench_trend
    points = bench_trend.collect(bench_trend.REPO)
    assert len(points) >= 8
    metrics = {p["metric"] for p in points}
    assert "socket_blocks_per_sec" in metrics
    regs = bench_trend.gate(points, 0.20)
    assert regs == [], f"bench trajectory regressed: {regs}"


# ------------------------------------------------------------- catalog

def test_metrics_catalog_includes_prof_and_queue():
    from tendermint_tpu.analysis.checkers import metrics as mcheck
    assert "prof" in mcheck.KNOWN_SUBSYSTEMS
    assert "queue" in mcheck.KNOWN_SUBSYSTEMS
    assert "tendermint_tpu.telemetry.profile" in \
        mcheck.INSTRUMENTED_MODULES
    assert "tendermint_tpu.telemetry.queues" in \
        mcheck.INSTRUMENTED_MODULES
    findings = mcheck.run()
    assert findings == [], [f.message for f in findings]
