"""Pure-python SecretConnection fallback primitives vs RFC test vectors
(the interop contract with the OpenSSL-backed path)."""

import pytest

from tendermint_tpu.p2p.conn import purecrypto as pc


def test_x25519_rfc7748_vector():
    k = bytes.fromhex("a546e36bf0527c9d3b16154b82465edd"
                      "62144c0ac1fc5a18506a2244ba449ac4")
    u = bytes.fromhex("e6db6867583030db3594c1a424b15f7c"
                      "726624ec26b3353b10a903a6d0ab1c4c")
    assert pc.x25519(k, u) == bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f"
        "32eccf03491c71f754b4075577a28552")


def test_x25519_dh_agreement_rfc7748():
    a = bytes.fromhex("77076d0a7318a57d3c16c17251b26645"
                      "df4c2f87ebc0992ab177fba51db92c2a")
    b = bytes.fromhex("5dab087e624a8a4b79e17f8b83800ee6"
                      "6f3bb1292618b6fd1c2f8b27ff88e0eb")
    a_pub = pc.x25519(a, pc.X25519_BASE)
    b_pub = pc.x25519(b, pc.X25519_BASE)
    assert a_pub == bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a"
        "0dbf3a0d26381af4eba4a98eaa9b4e6a")
    shared = bytes.fromhex("4a5d9d5ba4ce2de1728e3bf480350f25"
                           "e07e21c947d19e3376f09b3c1e161742")
    assert pc.x25519(a, b_pub) == shared
    assert pc.x25519(b, a_pub) == shared


def test_hkdf_sha256_rfc5869_case1():
    okm = pc.hkdf_sha256(
        bytes.fromhex("0b" * 22),
        info=bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"),
        length=42,
        salt=bytes.fromhex("000102030405060708090a0b0c"))
    assert okm == bytes.fromhex(
        "3cb25f25faacd57a90434f64d0362f2a2d2d0a90cf1a5a4c5db02d56"
        "ecc4c5bf34007208d5b887185865")


def test_chacha20poly1305_rfc8439_vector():
    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (b"Ladies and Gentlemen of the class of '99: If I could "
          b"offer you only one tip for the future, sunscreen would "
          b"be it.")
    ct = pc.ChaCha20Poly1305(key).encrypt(nonce, pt, aad)
    assert ct[:16] == bytes.fromhex("d31a8d34648e60db7b86afbc53ef7ec2")
    assert ct[-16:] == bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    assert pc.ChaCha20Poly1305(key).decrypt(nonce, ct, aad) == pt


def test_chacha20poly1305_rejects_tampering():
    key = b"\x01" * 32
    nonce = b"\x00" * 12
    box = pc.ChaCha20Poly1305(key)
    ct = box.encrypt(nonce, b"payload", b"")
    with pytest.raises(pc.InvalidTag):
        box.decrypt(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), b"")
    with pytest.raises(pc.InvalidTag):
        box.decrypt(nonce, ct, b"wrong aad")
    with pytest.raises(pc.InvalidTag):
        box.decrypt(nonce, ct[:8], b"")  # shorter than a tag


def test_secp256k1_ref_rfc6979_vector():
    """Deterministic-nonce ECDSA vector (key=1, 'Satoshi Nakamoto' —
    the canonical published secp256k1/SHA-256 RFC 6979 case)."""
    import hashlib

    from tendermint_tpu.utils import secp256k1_ref as sr
    h1 = hashlib.sha256(b"Satoshi Nakamoto").digest()
    assert sr._rfc6979_k(1, h1) == int(
        "8F8A276C19F4149656B280621E358CCE"
        "24F5F52542772691EE69063B74F15D15", 16)
    d = (1).to_bytes(32, "big")
    r, s = sr._der_decode(sr.sign(d, b"Satoshi Nakamoto"))
    assert r == int("934b1ea10a4b3c1757e2b0c017d0b614"
                    "3ce3c9a7e6a4a49860d7a6ab210ee3d8", 16)
    low_s = int("2442ce9d2b916064108014783e923ec3"
                "6b49743e2ffa1c4496f01a512aafd9e5", 16)
    assert s in (low_s, sr.N - low_s)  # published vector is low-s form
    # generator point compresses to the known even-y encoding
    assert sr.pubkey_of(d).hex() == (
        "0279be667ef9dcbbac55a06295ce870b"
        "07029bfcdb2dce28d959f2815b16f81798")


def test_secp256k1_ref_sign_verify_reject():
    from tendermint_tpu.utils import secp256k1_ref as sr
    d = b"\x07" * 32
    pub = sr.pubkey_of(d)
    sig = sr.sign(d, b"payload")
    assert sr.verify(pub, b"payload", sig)
    assert not sr.verify(pub, b"payloaX", sig)
    assert not sr.verify(pub, b"payload", sig[:-1] + b"\x00")
    assert not sr.verify(pub, b"payload", b"not-der")
    other = sr.pubkey_of(b"\x08" * 32)
    assert not sr.verify(other, b"payload", sig)


def test_secret_connection_roundtrip_over_socketpair():
    """Full handshake + framed traffic with whichever backend is active
    (on containers without `cryptography` this exercises the fallback)."""
    import socket
    import threading

    from tendermint_tpu.p2p.conn.secret import SecretConnection
    from tendermint_tpu.p2p.key import NodeKey
    from tendermint_tpu.types.keys import PrivKey

    s1, s2 = socket.socketpair()
    nk1 = NodeKey(PrivKey.generate(b"\x11" * 32))
    nk2 = NodeKey(PrivKey.generate(b"\x22" * 32))
    out = {}
    t = threading.Thread(
        target=lambda: out.update(a=SecretConnection.make(s1, nk1)))
    t.start()
    b = SecretConnection.make(s2, nk2)
    t.join(timeout=30)
    a = out["a"]
    assert a.remote_pubkey == nk2.pubkey
    assert b.remote_pubkey == nk1.pubkey
    msg = b"0123456789" * 300  # spans multiple 1024B frames
    a.write(msg)
    got = b""
    while len(got) < len(msg):
        got += b.read()
    assert got == msg
    a.close()
    b.close()
