"""CLI tests: init/testnet/replay/show_* commands + a testnet file tree
that actually boots into a committing network (cmd/tendermint parity)."""

import json
import os
import time

import pytest

from tendermint_tpu.cli import main as cli_main


def run_cli(*argv):
    return cli_main(list(argv))


def test_init_show_validator_show_node_id(tmp_path, capsys):
    home = str(tmp_path / "h")
    assert run_cli("--home", home, "init") == 0
    assert os.path.exists(os.path.join(home, "config", "genesis.json"))
    assert run_cli("--home", home, "show_validator") == 0
    out = capsys.readouterr().out
    assert '"ed25519"' in out
    assert run_cli("--home", home, "show_node_id") == 0
    node_id = capsys.readouterr().out.strip()
    assert len(node_id) == 40


def test_gen_validator(capsys):
    assert run_cli("gen_validator") == 0
    o = json.loads(capsys.readouterr().out)
    assert "priv_key" in o and "pub_key" in o


def test_unsafe_reset_all(tmp_path):
    home = str(tmp_path / "h")
    run_cli("--home", home, "init")
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    with open(os.path.join(home, "data", "junk"), "w") as f:
        f.write("x")
    assert run_cli("--home", home, "unsafe_reset_all") == 0
    assert not os.path.exists(os.path.join(home, "data"))


def test_node_runs_and_commits(tmp_path, capsys):
    home = str(tmp_path / "h")
    run_cli("--home", home, "init")
    assert run_cli("--home", home, "node", "--max-height", "2",
                   "--max-seconds", "60") == 0
    out = capsys.readouterr().out
    assert "committed height=2" in out


def test_replay_steps_through_wal(tmp_path, capsys):
    home = str(tmp_path / "h")
    run_cli("--home", home, "init")
    run_cli("--home", home, "node", "--max-height", "2",
            "--max-seconds", "60")
    capsys.readouterr()
    assert run_cli("--home", home, "replay") == 0
    out = capsys.readouterr().out
    assert "replayed" in out


def test_testnet_tree_boots_into_network(tmp_path):
    out_dir = str(tmp_path / "net")
    assert run_cli("testnet", "--n", "3", "--output", out_dir,
                   "--base-port", "0", "--chain-id", "cli-net") == 0
    # per-node files exist
    for i in range(3):
        cfg_dir = os.path.join(out_dir, f"node{i}", "config")
        for f in ("genesis.json", "priv_validator.json", "node_key.json",
                  "config.json"):
            assert os.path.exists(os.path.join(cfg_dir, f)), f
    # genesis is shared and lists all 3 validators
    g0 = json.load(open(os.path.join(out_dir, "node0", "config",
                                     "genesis.json")))
    g2 = json.load(open(os.path.join(out_dir, "node2", "config",
                                     "genesis.json")))
    assert g0 == g2 and len(g0["validators"]) == 3

    # boot the tree in-process: base_port 0 means each node picks its own
    # port, so rewrite persistent_peers after the first node binds
    from tendermint_tpu.node import default_node
    from tendermint_tpu.config import test_config as make_test_config

    nodes = []
    try:
        for i in range(3):
            home = os.path.join(out_dir, f"node{i}")
            node = default_node(home, with_p2p=True, fast_sync=False)
            # test-speed consensus timeouts
            node.consensus.config = make_test_config().consensus
            node.config.p2p.laddr = "tcp://127.0.0.1:0"
            node.config.p2p.persistent_peers = ""
            node.start()
            nodes.append(node)
        for n in nodes[1:]:
            n.switch.dial_peer(nodes[0].switch.listen_address)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and \
                not all(n.height >= 2 for n in nodes):
            time.sleep(0.1)
        assert all(n.height >= 2 for n in nodes), \
            [n.height for n in nodes]
    finally:
        for n in nodes:
            n.stop()
