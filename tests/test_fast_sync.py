"""Fast-sync tests: BlockPool scheduling + the BlockchainReactor syncing a
fresh node from a peer with batched commit verification (models
blockchain/pool_test.go + reactor behavior §3.3)."""

import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.abci.types import ValidatorUpdate
from tendermint_tpu.blockchain import BlockchainReactor, BlockPool
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState, MockTicker
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.p2p.test_util import connect_switches, make_switch
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import BlockStore, MemDB, StateStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def build_chain(gen_doc, key, n_blocks):
    """Run a single-validator consensus to height n_blocks; returns
    (state, state_store, block_store)."""
    conns = AppConns(local_client_creator(KVStoreApp()))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen_doc)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen_doc.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, block_store,
        priv_validator=PrivValidator(LocalSigner(key)),
        ticker_factory=MockTicker)
    cs.start()
    for _ in range(40 * n_blocks):
        if cs.state.last_block_height >= n_blocks:
            break
        cs.ticker.fire_next()
    assert cs.state.last_block_height >= n_blocks
    return cs.state, state_store, block_store, gen_doc


def fresh_node(gen_doc, consensus_key=None):
    conns = AppConns(local_client_creator(KVStoreApp()))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen_doc)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen_doc.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    return state, exec_, block_store


# --------------------------------------------------------------- BlockPool

class FakeBlock:
    def __init__(self, h):
        class H:
            height = h
        self.header = H()


def test_pool_requests_and_ordering():
    sent = []
    pool = BlockPool(start_height=1,
                     send_request=lambda p, h: sent.append((p, h)) or True,
                     on_peer_error=lambda p, r: None)
    pool.set_peer_height("peerA", 10)
    pool.set_peer_height("peerB", 5)
    pool.make_next_requests()
    assert {h for _, h in sent} == set(range(1, 11))
    # blocks arrive out of order; window only yields consecutive prefix
    for h in (3, 1, 2, 5):
        req_peer = next(p for p, hh in sent if hh == h)
        assert pool.add_block(req_peer, FakeBlock(h), 100)
    window = pool.peek_window(10)
    assert [b.header.height for b in window] == [1, 2, 3]
    first, second = pool.peek_two_blocks()
    assert first.header.height == 1 and second.header.height == 2
    pool.pop_request()
    assert pool.height == 2


def test_pool_unsolicited_block_rejected():
    pool = BlockPool(1, lambda p, h: True, lambda p, r: None)
    pool.set_peer_height("peerA", 3)
    pool.make_next_requests()
    assert not pool.add_block("stranger", FakeBlock(1), 100)
    assert not pool.add_block("peerA", FakeBlock(99), 100)


def test_pool_peer_removal_reassigns():
    sent = []
    pool = BlockPool(1, lambda p, h: sent.append((p, h)) or True,
                     lambda p, r: None)
    pool.set_peer_height("peerA", 4)
    pool.make_next_requests()
    pool.remove_peer("peerA")
    pool.set_peer_height("peerB", 4)
    pool.retry_stale_requests()
    assert ("peerB", 1) in sent


def test_pool_timeout_strikes_backoff_and_reroute():
    """A timed-out request strikes its peer (exponential backoff with
    deterministic jitter) and reroutes to a responsive peer; only
    MAX_STRIKES consecutive failures evict."""
    from tendermint_tpu.blockchain import pool as bpool
    from tendermint_tpu.utils import clock
    t = [1000.0]
    clock.set_source(lambda: int(t[0] * 1e9))
    try:
        sent, dropped = [], []
        pool = BlockPool(1, lambda p, h: sent.append((p, h)) or True,
                         lambda p, r: dropped.append(p))
        pool.set_peer_height("peerA", 10)
        pool.make_next_requests()
        assert all(p == "peerA" for p, _ in sent)
        # second peer appears; peerA times out -> strike + backoff,
        # its heights reassigned to peerB
        pool.set_peer_height("peerB", 10)
        t[0] += bpool.REQUEST_TIMEOUT_S + 1
        pool.retry_stale_requests()
        a = pool.peers["peerA"]
        assert a.strikes == 1 and a.in_backoff(clock.now_s())
        assert {p for p, _ in sent[10:]} == {"peerB"}
        assert dropped == []              # one strike never evicts
        # deterministic jitter: same (peer, strike) -> same backoff
        assert bpool._jitter("peerA", 1) == bpool._jitter("peerA", 1)
        assert a.backoff_until > clock.now_s()
        # strikes 2 and 3: now (with another peer present) evicted
        for _ in range(bpool.MAX_STRIKES - 1):
            for req in pool.requests.values():
                req.peer_id = "peerA"   # force re-assignment to peerA
                req.sent_at = t[0]
            t[0] += bpool.REQUEST_TIMEOUT_S + bpool.BACKOFF_CAP_S + 1
            pool.retry_stale_requests()
        assert dropped == ["peerA"]
    finally:
        clock.set_source(None)


def test_pool_never_evicts_last_peer():
    from tendermint_tpu.blockchain import pool as bpool
    from tendermint_tpu.utils import clock
    t = [1000.0]
    clock.set_source(lambda: int(t[0] * 1e9))
    try:
        dropped = []
        pool = BlockPool(1, lambda p, h: True,
                         lambda p, r: dropped.append(p))
        pool.set_peer_height("only", 5)
        pool.make_next_requests()
        for _ in range(bpool.MAX_STRIKES + 2):
            for req in pool.requests.values():
                req.peer_id = "only"
                req.sent_at = t[0]
            t[0] += bpool.REQUEST_TIMEOUT_S + bpool.BACKOFF_CAP_S + 1
            pool.retry_stale_requests()
        # struck out many times over, but it is the only peer we have:
        # throttled (backoff), never evicted — a slow sync beats none
        assert dropped == []
        assert pool.num_peers() == 1
    finally:
        clock.set_source(None)


def test_reactor_tracks_peer_heights_for_prune_floor():
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="ph-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    state, exec_, store = fresh_node(gen)
    r = BlockchainReactor(state, exec_, store, fast_sync=False)
    assert r.min_peer_height() > 1 << 60   # no peers: unconstrained

    class P:
        id = "peer1"

        @staticmethod
        def try_send_obj(ch, obj):
            return True

    r.receive(0x40, P, __import__(
        "tendermint_tpu.types.encoding", fromlist=["cdumps"]).cdumps(
        {"type": "status_response", "height": 7}))
    assert r.min_peer_height() == 7
    r.remove_peer(P, "bye")
    assert r.min_peer_height() > 1 << 60


def test_pool_caught_up():
    pool = BlockPool(5, lambda p, h: True, lambda p, r: None)
    pool.set_peer_height("peerA", 4)
    assert pool.is_caught_up()  # we're past every peer
    pool.set_peer_height("peerB", 9)
    assert not pool.is_caught_up()


# ------------------------------------------------------- reactor end-to-end

def test_fast_sync_from_peer_and_switch_to_consensus():
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="fs-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    src_state, _, src_store, gen = build_chain(gen, key, 12)

    # source node: serves blocks, not fast-syncing
    src_reactor = BlockchainReactor(
        src_state, None, src_store, fast_sync=False)
    sw_src = make_switch(network="fs-test", seed=b"\x01" * 32)
    sw_src.add_reactor("blockchain", src_reactor)
    sw_src.start()

    # fresh node: fast-syncs then flips its consensus reactor on
    state, exec_, store = fresh_node(gen)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, store,
        priv_validator=None, ticker_factory=MockTicker)
    cons_reactor = ConsensusReactor(cs, fast_sync=True)
    new_reactor = BlockchainReactor(
        state, exec_, store, fast_sync=True,
        consensus_reactor=cons_reactor, verify_window=5)
    sw_new = make_switch(network="fs-test", seed=b"\x02" * 32)
    sw_new.add_reactor("consensus", cons_reactor)
    sw_new.add_reactor("blockchain", new_reactor)
    sw_new.start()

    connect_switches(sw_src, sw_new)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not new_reactor.synced:
        time.sleep(0.05)
    assert new_reactor.synced, (
        f"stuck at height {new_reactor.pool.height}, "
        f"store {store.height()}")
    # synced within one block of the source (the tip block has no child
    # commit yet, so fast-sync stops one short and consensus finishes)
    assert store.height() >= src_store.height() - 1
    assert not cons_reactor.fast_sync  # handoff happened
    # the synced state's app replayed every tx: app hashes line up
    meta_src = src_store.load_block_meta(store.height())
    meta_new = store.load_block_meta(store.height())
    assert meta_src.block_id.key() == meta_new.block_id.key()
    sw_src.stop(); sw_new.stop()


def test_fast_sync_bad_peer_detected():
    """A peer serving a block with a forged commit gets dropped."""
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="fs-bad", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    src_state, _, src_store, gen = build_chain(gen, key, 6)

    class EvilReactor(BlockchainReactor):
        def _respond_to_block_request(self, peer, height):
            block = self.block_store.load_block(height)
            if block is None:
                peer.try_send_obj(0x40, {"type": "no_block_response",
                                         "height": height})
                return
            obj = block.to_obj()
            if height == 3:  # corrupt one block's data
                obj["data"]["txs"] = ["deadbeef"]
            peer.try_send_obj(0x40, {"type": "block_response", "block": obj})

    evil = EvilReactor(src_state, None, src_store, fast_sync=False)
    sw_evil = make_switch(network="fs-bad", seed=b"\x01" * 32)
    sw_evil.add_reactor("blockchain", evil)
    sw_evil.start()

    state, exec_, store = fresh_node(gen)
    new_reactor = BlockchainReactor(state, exec_, store, fast_sync=True,
                                    verify_window=4)
    sw_new = make_switch(network="fs-bad", seed=b"\x02" * 32)
    sw_new.add_reactor("blockchain", new_reactor)
    sw_new.start()
    connect_switches(sw_evil, sw_new)

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and sw_new.peers.size() > 0:
        time.sleep(0.05)
    # the evil peer was dropped; the chain cannot progress past the forgery
    assert sw_new.peers.size() == 0
    assert store.height() < 6
    sw_evil.stop(); sw_new.stop()
