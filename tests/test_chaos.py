"""Chaos plane tests: seeded fault schedule determinism, the tier-1
smoke scenario (drop + delay + one crash-restart, seconds on the CI
host), the zero-overhead off-hatch, monitor self-checks (an oracle that
cannot fail proves nothing), and the full acceptance scenario (slow)."""

import shutil
import tempfile
import types

import pytest

from tendermint_tpu.chaos.monitor import InvariantMonitor
from tendermint_tpu.chaos.schedule import FaultSchedule


# --------------------------------------------------------- schedule --

def _drive(schedule, n=300):
    """Synthetic deterministic event stream through every decision."""
    for step in range(n):
        schedule.link_deliveries(step, step % 4, (step + 1) % 4, "vote")


def test_same_seed_identical_fault_sequence():
    spec = {"drop": 0.1, "delay": 0.2, "duplicate": 0.05,
            "reorder": 0.05}
    a, b = FaultSchedule(spec, seed=11), FaultSchedule(spec, seed=11)
    _drive(a)
    _drive(b)
    assert a.signature() == b.signature()
    assert a.counts == b.counts and a.counts  # faults actually fired

    c = FaultSchedule(spec, seed=12)
    _drive(c)
    assert a.signature() != c.signature()


def test_schedule_rejects_unknown_crash_point():
    with pytest.raises(ValueError, match="unknown crash point"):
        FaultSchedule({"crashes": [{"node": 0, "point": "no_such"}]})


def test_partition_and_skew_lookup():
    s = FaultSchedule({"partitions": [{"start": 10, "stop": 20,
                                       "groups": [[0], [1, 2]]}],
                       "clock_skew": {"2": 3}})
    assert s.cross_partition(15, 0, 1)
    assert not s.cross_partition(15, 1, 2)
    assert not s.cross_partition(25, 0, 1)  # healed
    assert s.clock_skew == {2: 3}


# ------------------------------------------------------------ knobs --

def test_chaos_off_is_zero_overhead_noop(monkeypatch):
    from tendermint_tpu import chaos
    monkeypatch.delenv("TM_TPU_CHAOS", raising=False)
    chaos.configure("off", 0)
    link = object()
    assert chaos.maybe_wrap_link(link, "peer") is link  # same object

    monkeypatch.setenv("TM_TPU_CHAOS", "drop=0.5,seed=3")
    wrapped = chaos.maybe_wrap_link(link, "peer")
    assert wrapped is not link
    from tendermint_tpu.p2p.fuzz import FuzzedLink
    assert isinstance(wrapped, FuzzedLink)

    # env wins over configure(); off in env beats a configured spec
    chaos.configure("drop=0.5", 1)
    monkeypatch.setenv("TM_TPU_CHAOS", "off")
    assert chaos.maybe_wrap_link(link, "peer") is link


def test_spec_string_parse_rejects_typos():
    from tendermint_tpu import chaos
    assert chaos.parse_spec("drop=0.1,delay=0.2,delay_ms=25,seed=9") == {
        "drop": 0.1, "delay": 0.2, "delay_ms": 25.0, "seed": 9}
    with pytest.raises(ValueError, match="unknown chaos spec key"):
        chaos.parse_spec("dorp=0.1")


# ---------------------------------------------------------- monitor --

def _fake_block(height, tag=b"A", evidence=()):
    blk = types.SimpleNamespace()
    blk.header = types.SimpleNamespace(height=height)
    blk.evidence = types.SimpleNamespace(evidence=list(evidence))
    blk.hash = lambda: tag * 32
    return blk


def test_monitor_detects_disagreement():
    m = InvariantMonitor()
    m._on_commit(1, 0, _fake_block(3, b"A"))
    m._on_commit(2, 1, _fake_block(3, b"B"))  # different block, same h
    assert [v["invariant"] for v in m.violations] == ["agreement"]


def test_monitor_detects_height_regression():
    m = InvariantMonitor()
    m._on_commit(1, 0, _fake_block(3, b"A"))
    m._on_commit(2, 0, _fake_block(3, b"A"))  # same node re-commits 3
    assert [v["invariant"] for v in m.violations] == ["validity"]


def test_monitor_flags_missing_evidence_and_liveness():
    m = InvariantMonitor()
    m.expect_double_sign(("ab", 2, 0, 1))
    m._on_commit(5, 0, _fake_block(2))
    sched = FaultSchedule({"partitions": [
        {"start": 1, "stop": 10, "groups": [[0], [1]]}]})
    rep = m.finalize(sched, final_step=400, liveness_bound=50)
    kinds = sorted(v["invariant"] for v in rep["violations"])
    # the double-sign never committed AND no commit followed the heal
    assert kinds == ["evidence", "liveness"]


# ------------------------------------------------------------ runs --

def test_chaos_smoke_drop_delay_crash():
    """Tier-1 seeded smoke (ISSUE 4 satellite): drop + delay + one
    crash-restart through WAL/handshake replay, zero invariant
    violations, all nodes caught up. Seconds on the 1-core host."""
    from tendermint_tpu.chaos.runner import SMOKE_SPEC, run_chaos
    r = run_chaos(spec=SMOKE_SPEC, seed=7, target_height=4,
                  max_steps=400)
    assert r["violations"] == []
    assert r["max_height"] >= 4
    assert set(r["heights"]) == {0, 1, 2, 3}
    assert min(r["heights"].values()) >= 4
    f = r["faults_injected"]
    assert f.get("drop", 0) > 0 and f.get("delay", 0) > 0
    assert f.get("crash") == 1 and f.get("restart") == 1
    assert r["checks"]["agreement"] > 0


@pytest.mark.slow
def test_chaos_acceptance_scenario():
    """The BENCH_chaos.json scenario: drop/delay/duplicate/reorder,
    partition + heal, crash-restart, equivocating validator, clock
    skew — zero violations, every injected double-sign committed."""
    from tendermint_tpu.chaos.runner import run_chaos
    r = run_chaos(seed=42)
    assert r["violations"] == []
    f = r["faults_injected"]
    for kind in ("drop", "delay", "duplicate", "reorder", "partition",
                 "heal", "crash", "restart", "equivocation"):
        assert f.get(kind, 0) >= 1, f"{kind} never fired: {f}"
    ev = r["evidence"]
    assert ev["injected_double_signs"] > 0
    assert ev["committed"] == ev["injected_double_signs"]
    assert r["recovery"]["latency_steps"]["n"] >= 3


@pytest.mark.slow
def test_chaos_partition_heals_and_recovers():
    """Partition-only schedule: the majority side keeps committing, the
    isolated node catches up after the heal (buffered delivery + the
    runner's reactor-style catch-up), liveness check passes."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"partitions": [{"start": 20, "stop": 60,
                            "groups": [[0], [1, 2, 3]]}]}
    r = run_chaos(spec=spec, seed=5, target_height=8, max_steps=600)
    assert r["violations"] == []
    assert min(r["heights"].values()) >= 8
    assert r["faults_injected"].get("partition") == 1
    assert r["faults_injected"].get("heal") == 1


def test_switch_links_get_chaos_wrapped_and_still_deliver(monkeypatch):
    """TM_TPU_CHAOS on a real switch: both peers' links come back as
    FuzzedLinks (per-frame fault injection live on the encrypted burst
    path) and traffic still flows through a delay-only spec."""
    from tests.test_p2p import (EchoReactor, connect_switches,
                                make_switch, wait_for)
    from tendermint_tpu.p2p.fuzz import FuzzedLink

    monkeypatch.setenv("TM_TPU_CHAOS", "delay=0.3,delay_ms=5,seed=1")
    r1 = EchoReactor("echo", 0x10, echo=False)
    r2 = EchoReactor("echo", 0x10, echo=True)
    sw1 = make_switch(seed=b"\x01" * 32, encrypt=True)
    sw2 = make_switch(seed=b"\x02" * 32, encrypt=True)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start()
    sw2.start()
    p1, p2 = connect_switches(sw1, sw2)
    try:
        assert isinstance(p1.mconn.link, FuzzedLink)
        assert isinstance(p2.mconn.link, FuzzedLink)
        assert p1.send(0x10, b"through-chaos")
        assert wait_for(lambda: r2.received, timeout=5.0)
        assert r2.received[0][1] == b"through-chaos"
    finally:
        sw1.stop()
        sw2.stop()


def test_violation_trace_is_written_and_replayable(tmp_path):
    """A run asked for a trace dumps seed + spec + fault log + commits;
    the trace's (spec, seed) rebuild an identical schedule."""
    import json
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"drop": 0.05, "delay": 0.1}
    trace = str(tmp_path / "trace.json")
    r = run_chaos(spec=spec, seed=3, target_height=3, max_steps=300,
                  trace_path=trace)
    assert r["violations"] == []
    doc = json.load(open(trace))
    assert doc["seed"] == 3 and doc["spec"] == spec
    assert doc["fault_log"]  # replayed decisions are all there
    assert doc["commits"]
