"""Chaos plane tests: seeded fault schedule determinism, the tier-1
smoke scenario (drop + delay + one crash-restart, seconds on the CI
host), the zero-overhead off-hatch, monitor self-checks (an oracle that
cannot fail proves nothing), and the full acceptance scenario (slow)."""

import shutil
import tempfile
import types

import pytest

from tendermint_tpu.chaos.monitor import InvariantMonitor
from tendermint_tpu.chaos.schedule import FaultSchedule


# --------------------------------------------------------- schedule --

def _drive(schedule, n=300):
    """Synthetic deterministic event stream through every decision."""
    for step in range(n):
        schedule.link_deliveries(step, step % 4, (step + 1) % 4, "vote")


def test_same_seed_identical_fault_sequence():
    spec = {"drop": 0.1, "delay": 0.2, "duplicate": 0.05,
            "reorder": 0.05}
    a, b = FaultSchedule(spec, seed=11), FaultSchedule(spec, seed=11)
    _drive(a)
    _drive(b)
    assert a.signature() == b.signature()
    assert a.counts == b.counts and a.counts  # faults actually fired

    c = FaultSchedule(spec, seed=12)
    _drive(c)
    assert a.signature() != c.signature()


def test_schedule_rejects_unknown_crash_point():
    with pytest.raises(ValueError, match="unknown crash point"):
        FaultSchedule({"crashes": [{"node": 0, "point": "no_such"}]})


def test_pinned_spec_signatures():
    """Back-compat pin (ISSUE 11 satellite): the geo/churn spec keys
    must not shift a single RNG draw for any PRE-EXISTING spec — the
    fault sequence of every committed scenario is part of the
    replayability contract. These digests were recorded on the
    pre-geo/churn code; if this test fails, a code change silently
    rewrote every pinned seeded trajectory."""
    import hashlib
    from tendermint_tpu.chaos.runner import ACCEPTANCE_SPEC, SMOKE_SPEC

    def drive_digest(spec, seed=11, n=400, nodes=4):
        s = FaultSchedule(spec, seed=seed)
        for step in range(n):
            for src in range(nodes):
                for dst in range(nodes):
                    if src != dst:
                        s.link_deliveries(step, src, dst, "vote")
        return hashlib.sha256(repr(s.signature()).encode()).hexdigest()

    rate_spec = {"drop": 0.1, "delay": 0.2, "duplicate": 0.05,
                 "reorder": 0.05}
    assert drive_digest(ACCEPTANCE_SPEC) == (
        "e6ac7aee7d9e7877f8ec0d8003457ab3462c1000d0f707aec1c0b910148f6331")
    assert drive_digest(SMOKE_SPEC) == (
        "d2feacb993a35596ec39f6840ad1419d925165d7b8c307d8bb3d0bdbbadaad0c")
    assert drive_digest(rate_spec) == (
        "d3c4ea864a6572f7792871ed4639eb0e15792ccebb061cf3b494d52cd3fa70d6")


def test_geo_profile_shapes_links_deterministically():
    """Geo matrices: cross-region messages pick up the profile's
    latency (+ seeded jitter), intra-region ones don't; losses and
    throttles are seeded (same seed = same sequence) and recorded as
    geo_* fault kinds; regions assign round-robin unless mapped."""
    spec = {"geo": {"profile": "wan3"}, "drop": 0.02}

    def drive(seed):
        s = FaultSchedule(spec, seed=seed)
        for step in range(300):
            for src in range(6):
                for dst in range(6):
                    if src != dst:
                        s.link_deliveries(step, src, dst, "vote")
        return s

    a, b = drive(5), drive(5)
    assert a.signature() == b.signature()
    assert a.counts.get("geo_drop", 0) > 0
    assert drive(6).signature() != a.signature()

    s = FaultSchedule({"geo": {"profile": "wan3"}})
    assert [s.region_of(i) for i in range(6)] == [0, 1, 2, 0, 1, 2]
    # region 0 -> 2 carries wan3's 5-step base latency (+ jitter);
    # 0 -> 3 is intra-region region-0 traffic: free
    assert min(s.link_deliveries(1, 0, 2, "vote")) >= 5
    assert s.link_deliveries(1, 0, 3, "vote") == [0]
    # explicit assignment overrides round-robin
    s2 = FaultSchedule({"geo": {"profile": "wan2",
                                "assign": {0: 1, 1: 1, 2: 0}}})
    assert s2.region_of(0) == 1 and s2.region_of(2) == 0
    assert s2.region_of(5) == 1  # unmapped: round-robin over 2 regions


def test_geo_bandwidth_cap_spills_to_later_steps():
    """A thin long-haul pipe queues, it does not destroy: messages
    beyond the per-step cap on one region pair are DELAYED by their
    queue position and recorded as geo_throttle."""
    spec = {"geo": {"latency_steps": [[0, 1], [1, 0]],
                    "jitter_steps": 0,
                    "bandwidth_msgs": [[0, 3], [3, 0]]}}
    s = FaultSchedule(spec, seed=1)
    delays = [s.link_deliveries(7, 0, 1, "vote")[0] for _ in range(7)]
    # first 3 ride the base latency; 4-6 spill 1 step; 7th spills 2
    assert delays == [1, 1, 1, 2, 2, 2, 3]
    assert s.counts.get("geo_throttle") == 4
    # a new step resets the pipe
    assert s.link_deliveries(8, 0, 1, "vote") == [1]


def test_geo_and_churn_spec_validation():
    with pytest.raises(ValueError, match="unknown geo profile"):
        FaultSchedule({"geo": {"profile": "atlantis"}})
    with pytest.raises(ValueError, match="unknown geo spec key"):
        FaultSchedule({"geo": {"profile": "wan3", "latencey": 1}})
    with pytest.raises(ValueError, match="must be 2x2"):
        FaultSchedule({"geo": {"latency_steps": [[0, 1], [1]]}})
    with pytest.raises(ValueError, match="unknown churn op"):
        FaultSchedule({"churn": {"ops": ["jion"]}})
    with pytest.raises(ValueError, match="unknown churn spec key"):
        FaultSchedule({"churn": {"every": 3}})
    c = FaultSchedule({"churn": {"standby": 2}}).churn
    assert c["ops"] == ["join", "leave", "stake"]
    assert c["every_heights"] == 2 and c["standby"] == 2


def test_partition_and_skew_lookup():
    s = FaultSchedule({"partitions": [{"start": 10, "stop": 20,
                                       "groups": [[0], [1, 2]]}],
                       "clock_skew": {"2": 3}})
    assert s.cross_partition(15, 0, 1)
    assert not s.cross_partition(15, 1, 2)
    assert not s.cross_partition(25, 0, 1)  # healed
    assert s.clock_skew == {2: 3}


# ------------------------------------------------------------ knobs --

def test_chaos_off_is_zero_overhead_noop(monkeypatch):
    from tendermint_tpu import chaos
    monkeypatch.delenv("TM_TPU_CHAOS", raising=False)
    chaos.configure("off", 0)
    link = object()
    assert chaos.maybe_wrap_link(link, "peer") is link  # same object

    monkeypatch.setenv("TM_TPU_CHAOS", "drop=0.5,seed=3")
    wrapped = chaos.maybe_wrap_link(link, "peer")
    assert wrapped is not link
    from tendermint_tpu.p2p.fuzz import FuzzedLink
    assert isinstance(wrapped, FuzzedLink)

    # env wins over configure(); off in env beats a configured spec
    chaos.configure("drop=0.5", 1)
    monkeypatch.setenv("TM_TPU_CHAOS", "off")
    assert chaos.maybe_wrap_link(link, "peer") is link


def test_spec_string_parse_rejects_typos():
    from tendermint_tpu import chaos
    assert chaos.parse_spec("drop=0.1,delay=0.2,delay_ms=25,seed=9") == {
        "drop": 0.1, "delay": 0.2, "delay_ms": 25.0, "seed": 9}
    with pytest.raises(ValueError, match="unknown chaos spec key"):
        chaos.parse_spec("dorp=0.1")


# ---------------------------------------------------------- monitor --

def _fake_block(height, tag=b"A", evidence=()):
    blk = types.SimpleNamespace()
    blk.header = types.SimpleNamespace(height=height)
    blk.evidence = types.SimpleNamespace(evidence=list(evidence))
    blk.hash = lambda: tag * 32
    return blk


def test_monitor_detects_disagreement():
    m = InvariantMonitor()
    m._on_commit(1, 0, _fake_block(3, b"A"))
    m._on_commit(2, 1, _fake_block(3, b"B"))  # different block, same h
    assert [v["invariant"] for v in m.violations] == ["agreement"]


def test_monitor_detects_height_regression():
    m = InvariantMonitor()
    m._on_commit(1, 0, _fake_block(3, b"A"))
    m._on_commit(2, 0, _fake_block(3, b"A"))  # same node re-commits 3
    assert [v["invariant"] for v in m.violations] == ["validity"]


def test_monitor_flags_missing_evidence_and_liveness():
    m = InvariantMonitor()
    m.expect_double_sign(("ab", 2, 0, 1))
    m._on_commit(5, 0, _fake_block(2))
    sched = FaultSchedule({"partitions": [
        {"start": 1, "stop": 10, "groups": [[0], [1]]}]})
    rep = m.finalize(sched, final_step=400, liveness_bound=50)
    kinds = sorted(v["invariant"] for v in rep["violations"])
    # the double-sign never committed AND no commit followed the heal
    assert kinds == ["evidence", "liveness"]


# ------------------------------------------------------------ runs --

def test_chaos_smoke_drop_delay_crash():
    """Tier-1 seeded smoke (ISSUE 4 satellite): drop + delay + one
    crash-restart through WAL/handshake replay, zero invariant
    violations, all nodes caught up. Seconds on the 1-core host."""
    from tendermint_tpu.chaos.runner import SMOKE_SPEC, run_chaos
    r = run_chaos(spec=SMOKE_SPEC, seed=7, target_height=4,
                  max_steps=400)
    assert r["violations"] == []
    assert r["max_height"] >= 4
    assert set(r["heights"]) == {0, 1, 2, 3}
    assert min(r["heights"].values()) >= 4
    f = r["faults_injected"]
    assert f.get("drop", 0) > 0 and f.get("delay", 0) > 0
    assert f.get("crash") == 1 and f.get("restart") == 1
    assert r["checks"]["agreement"] > 0


def test_relay_gossip_dedup_skips_redundant_deliveries():
    """ISSUE 12 satellite: a duplicate-heavy run must skip re-
    delivering byte-identical vote/part messages a destination already
    consumed (the O(n²) residual PR 11 flagged) — with the SAME
    verdict: zero violations, every node caught up. And dedup must not
    break the determinism witness: two runs of one (spec, seed) still
    produce one fault log."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"drop": 0.02, "duplicate": 0.5, "delay": 0.05,
            "delay_steps": [1, 2]}
    r1 = run_chaos(spec=spec, seed=11, target_height=4, max_steps=400)
    assert r1["violations"] == []
    assert r1["max_height"] >= 4
    assert r1["relay_dedup_skips"] > 0, \
        "duplicate faults must produce provably-redundant deliveries"
    r2 = run_chaos(spec=spec, seed=11, target_height=4, max_steps=400)
    assert r1["fault_log_sha256"] == r2["fault_log_sha256"]
    assert r1["relay_dedup_skips"] == r2["relay_dedup_skips"]


@pytest.mark.slow
def test_chaos_acceptance_scenario():
    """The BENCH_chaos.json scenario: drop/delay/duplicate/reorder,
    partition + heal, crash-restart, equivocating validator, clock
    skew — zero violations, every injected double-sign committed."""
    from tendermint_tpu.chaos.runner import run_chaos
    r = run_chaos(seed=42)
    assert r["violations"] == []
    f = r["faults_injected"]
    for kind in ("drop", "delay", "duplicate", "reorder", "partition",
                 "heal", "crash", "restart", "equivocation"):
        assert f.get(kind, 0) >= 1, f"{kind} never fired: {f}"
    ev = r["evidence"]
    assert ev["injected_double_signs"] > 0
    assert ev["committed"] == ev["injected_double_signs"]
    assert r["recovery"]["latency_steps"]["n"] >= 3


@pytest.mark.slow
def test_chaos_partition_heals_and_recovers():
    """Partition-only schedule: the majority side keeps committing, the
    isolated node catches up after the heal (buffered delivery + the
    runner's reactor-style catch-up), liveness check passes."""
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"partitions": [{"start": 20, "stop": 60,
                            "groups": [[0], [1, 2, 3]]}]}
    r = run_chaos(spec=spec, seed=5, target_height=8, max_steps=600)
    assert r["violations"] == []
    assert min(r["heights"].values()) >= 8
    assert r["faults_injected"].get("partition") == 1
    assert r["faults_injected"].get("heal") == 1


def test_switch_links_get_chaos_wrapped_and_still_deliver(monkeypatch):
    """TM_TPU_CHAOS on a real switch: both peers' links come back as
    FuzzedLinks (per-frame fault injection live on the encrypted burst
    path) and traffic still flows through a delay-only spec."""
    from tests.test_p2p import (EchoReactor, connect_switches,
                                make_switch, wait_for)
    from tendermint_tpu.p2p.fuzz import FuzzedLink

    monkeypatch.setenv("TM_TPU_CHAOS", "delay=0.3,delay_ms=5,seed=1")
    r1 = EchoReactor("echo", 0x10, echo=False)
    r2 = EchoReactor("echo", 0x10, echo=True)
    sw1 = make_switch(seed=b"\x01" * 32, encrypt=True)
    sw2 = make_switch(seed=b"\x02" * 32, encrypt=True)
    sw1.add_reactor("echo", r1)
    sw2.add_reactor("echo", r2)
    sw1.start()
    sw2.start()
    p1, p2 = connect_switches(sw1, sw2)
    try:
        assert isinstance(p1.mconn.link, FuzzedLink)
        assert isinstance(p2.mconn.link, FuzzedLink)
        assert p1.send(0x10, b"through-chaos")
        assert wait_for(lambda: r2.received, timeout=5.0)
        assert r2.received[0][1] == b"through-chaos"
    finally:
        sw1.stop()
        sw2.stop()


def test_violation_trace_is_written_and_replayable(tmp_path):
    """A run asked for a trace dumps seed + spec + fault log + commits;
    the trace's (spec, seed) rebuild an identical schedule."""
    import json
    from tendermint_tpu.chaos.runner import run_chaos
    spec = {"drop": 0.05, "delay": 0.1}
    trace = str(tmp_path / "trace.json")
    r = run_chaos(spec=spec, seed=3, target_height=3, max_steps=300,
                  trace_path=trace)
    assert r["violations"] == []
    doc = json.load(open(trace))
    assert doc["seed"] == 3 and doc["spec"] == spec
    assert doc["fault_log"]  # replayed decisions are all there
    assert doc["commits"]
