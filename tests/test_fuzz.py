"""FuzzedLink regression tests for the vectored (burst) link API
(ISSUE 4 satellite): per-frame fuzzing must apply on BOTH the scalar
write/read path and the write_many/read_burst path — PR 3's burst-mode
connections must not silently bypass fault injection — plus the
deterministic decider the chaos plane drives links with."""

import socket
import threading

import pytest

from tendermint_tpu.p2p.conn.mconn import PlainFramedConn
from tendermint_tpu.p2p.fuzz import FuzzConfig, FuzzedLink


class _RecordingLink:
    """Inner link double recording exactly which API got each frame."""

    def __init__(self, bursts=()):
        self.writes = []
        self.write_manys = []
        self._bursts = list(bursts)
        self.closed = False

    def write(self, data):
        self.writes.append(bytes(data))
        return len(data)

    def write_many(self, chunks):
        self.write_manys.append([bytes(c) for c in chunks])
        return sum(len(c) for c in chunks)

    def read(self):
        burst = self.read_burst()
        return burst[0] if burst else b""

    def read_burst(self):
        return self._bursts.pop(0) if self._bursts else []

    def close(self):
        self.closed = True


class _ScalarOnlyLink(_RecordingLink):
    """No vectored API: FuzzedLink must degrade to per-frame calls."""
    write_many = None
    read_burst = None

    def __init__(self, frames=()):
        super().__init__()
        del self.write_manys
        self._frames = list(frames)

    def read(self):
        return self._frames.pop(0) if self._frames else b""


def _pattern_decider(pattern):
    """Deterministic decider: one action per call, in order."""
    it = iter(pattern)

    def decide(op):
        return next(it, None)

    return decide


def test_write_many_fuzzes_per_frame_and_keeps_burst():
    inner = _RecordingLink()
    link = FuzzedLink(inner, decider=_pattern_decider(
        [None, "drop", None]))
    n = link.write_many([b"aa", b"bb", b"cc"])
    assert n == 6                       # caller sees full acceptance
    assert inner.write_manys == [[b"aa", b"cc"]]  # one burst, survivor-only
    assert inner.writes == []


def test_write_many_falls_back_to_scalar_writes():
    inner = _ScalarOnlyLink()
    link = FuzzedLink(inner, decider=_pattern_decider([None, "drop"]))
    assert link.write_many([b"xx", b"yy"]) == 4
    assert inner.writes == [b"xx"]


def test_read_burst_filters_frames_and_retries_until_survivor():
    inner = _RecordingLink(bursts=[[b"p", b"q"], [b"r"], []])
    # first burst entirely dropped -> must pull the next one
    link = FuzzedLink(inner, decider=_pattern_decider(
        ["drop", "drop", None]))
    assert link.read_burst() == [b"r"]
    assert link.read_burst() == []      # clean EOF propagates


def test_read_burst_falls_back_to_scalar_read():
    inner = _ScalarOnlyLink(frames=[b"one", b"two", b""])
    link = FuzzedLink(inner, decider=_pattern_decider(
        ["drop", None]))
    assert link.read_burst() == [b"two"]
    assert link.read_burst() == []


def test_scalar_paths_still_fuzz():
    inner = _RecordingLink(bursts=[[b"m1"], [b"m2"]])
    link = FuzzedLink(inner, decider=_pattern_decider(
        ["drop", None, "drop", None]))
    assert link.write(b"w1") == 2       # dropped silently
    assert link.write(b"w2") == 2       # delivered
    assert inner.writes == [b"w2"]
    assert link.read() == b"m2"         # m1 dropped, reads until one


def test_on_fault_hook_counts_drops_and_delays():
    faults = []
    inner = _RecordingLink()
    link = FuzzedLink(inner, decider=_pattern_decider(
        ["drop", ("delay", 0.0), None]), on_fault=faults.append)
    link.write(b"a")
    link.write(b"b")
    link.write(b"c")
    assert faults == ["drop", "delay"]
    assert inner.writes == [b"b", b"c"]


def test_seeded_config_is_deterministic():
    def run(seed):
        inner = _RecordingLink()
        link = FuzzedLink(inner, FuzzConfig(mode="drop",
                                            prob_drop_rw=0.5, seed=seed))
        for i in range(64):
            link.write(bytes([i]))
        return inner.writes

    assert run(123) == run(123)
    assert run(123) != run(321)


def test_burst_and_scalar_paths_interop_over_sockets():
    """End-to-end both paths (the satellite's regression): frames sent
    through a fuzzed burst write arrive through a fuzzed burst read —
    and the same wire works per-frame — with fault injection live on
    every frame either way."""
    for vectored in (True, False):
        s1, s2 = socket.socketpair()
        drops = iter([True, False, False, False])
        tx = FuzzedLink(PlainFramedConn(s1),
                        decider=lambda op: "drop" if next(drops, False)
                        else None)
        rx = FuzzedLink(PlainFramedConn(s2), decider=lambda op: None)
        try:
            frames = [b"f1", b"f2", b"f3", b"f4"]
            if vectored:
                tx.write_many(frames)
            else:
                for f in frames:
                    tx.write(f)
            got = []
            while len(got) < 3:
                burst = rx.read_burst() if vectored else [rx.read()]
                assert burst, "EOF before surviving frames arrived"
                got.extend(burst)
            assert got == [b"f2", b"f3", b"f4"]  # f1 dropped pre-wire
        finally:
            tx.close()
            rx.close()
