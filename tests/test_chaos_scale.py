"""Validator-scale adversarial plane tests (ISSUE 11): churn through
real EndBlock deltas, geo-profiled links, continuous lite
certification as a run invariant, statesync joining a churned net, and
the long seeded soaks. The tier-1 members stay at small n (seconds on
the 1-core CI host); the 32-validator acceptance run and the soaks are
slow-marked."""

import pytest

from tendermint_tpu.chaos.runner import run_chaos, scale_spec

# Tier-1 churn+geo scenario: small enough for seconds, rich enough to
# cross every churn op and certify continuously through the deltas.
CHURN_SMOKE_SPEC = {
    "drop": 0.02,
    "delay": 0.05,
    "delay_steps": [1, 2],
    "geo": {"profile": "wan3"},
    "churn": {"start_height": 2, "every_heights": 2, "standby": 2,
              "ops": ["join", "leave", "stake"], "max_events": 4},
    # the relay's drops are final (no reactor re-gossip), and
    # WAN-calibrated rounds are long — a dropped precommit would wedge
    # a height for tens of steps, so the smoke opts into the
    # deterministic stall re-delivery like every scale spec
    "stall_assist": True,
}


def test_scale_spec_shape():
    from tendermint_tpu.chaos.schedule import GEO_PROFILES
    full = scale_spec(32)
    assert full["geo"]["profile"] == "wan3"
    # bandwidth caps carry the same per-node-pair budget at every n:
    # the 4-node calibration scaled by (n/4)^2
    base = GEO_PROFILES["wan3"]["bandwidth_msgs"]
    assert full["geo"]["bandwidth_msgs"] == \
        [[c * 64 for c in row] for row in base]
    assert full["churn"]["ops"] == ["join", "leave", "stake"]
    assert full["churn"]["standby"] == 2
    assert full["stall_assist"] is True
    trimmed = scale_spec(128, full_churn=False)
    assert trimmed["churn"]["ops"] == ["join", "leave"]
    assert trimmed["churn"]["standby"] == 8
    assert trimmed["churn"]["every_heights"] == 1


def test_churn_geo_smoke_certifies_every_height():
    """Tier-1 smoke: 6 nodes (4 genesis validators + 2 standby), wan3
    geo links, drop/delay faults, and a full join/leave/stake churn
    cycle applied THROUGH consensus — zero invariant violations and
    every committed height continuously lite-certified across the
    valset deltas."""
    r = run_chaos(spec=CHURN_SMOKE_SPEC, seed=7, n=6, target_height=6,
                  max_steps=600, settle_steps=20)
    assert r["violations"] == []
    assert r["n_genesis_validators"] == 4
    churn = r["churn"]
    assert churn["churn_join"] >= 1
    assert churn["churn_leave"] >= 1
    assert churn["churn_stake"] >= 1
    lite = r["lite"]
    assert lite["active"], "lite certification halted mid-run"
    assert lite["certified_height"] == r["max_height"]
    # the certifier really crossed valset deltas (joins/leaves/stake
    # changes each rewrite the valset hash)
    assert lite["valset_updates"] >= 2
    assert lite["valset_size_max"] > lite["valset_size_min"]
    # geo links actually shaped traffic
    f = r["faults_injected"]
    assert f.get("geo_drop", 0) + f.get("geo_throttle", 0) >= 1
    assert r["fault_log_sha256"]


def test_bench_testnet_churn_rotates_valset():
    """bench_testnet's in-process engine under churn (tier-1 smoke):
    valset rotation flows through EndBlock while blocks keep
    committing; the final set differs from genesis."""
    import bench_testnet
    r = bench_testnet.run(n_blocks=8, n_vals=4, n_txs=5, churn_every=2)
    assert r["blocks"] >= 8
    churn = r["churn"]
    # a full join -> stake -> leave cycle ran (the set may legally be
    # back at genesis power by the end — the change HEIGHT is the
    # evidence the deltas flowed through EndBlock mid-run)
    assert churn["ops_injected"] >= 3
    assert churn["last_height_validators_changed"] > 2


def test_monitor_lite_flags_uncertifiable_commit():
    """The certified invariant is loud: a provider serving a commit
    the certifier cannot verify (forged signatures) must record a
    'certified' violation and halt certification."""
    import types

    from tendermint_tpu.chaos.monitor import InvariantMonitor
    from tests.test_lite import CHAIN, ValKeys

    vk = ValKeys(4)
    good = vk.sign_header(1)
    bad = vk.sign_header(2)
    for pc in bad.signed_header.commit.precommits:
        if pc is not None:
            pc.signature = b"\x00" * 64

    m = InvariantMonitor()
    m.attach_lite(CHAIN, vk.valset,
                  {1: good, 2: bad}.get)
    blk = types.SimpleNamespace()
    blk.header = types.SimpleNamespace(height=2)
    blk.evidence = types.SimpleNamespace(evidence=[])
    blk.hash = lambda: b"\xaa" * 32
    m._on_commit(1, 0, blk)       # max_height -> 2
    m._advance_lite(step=1)
    assert [v["invariant"] for v in m.violations] == ["certified"]
    assert m.violations[0]["height"] == 2
    assert m.lite.certified_height == 1
    # halted: no further checks after the loud failure
    checks = dict(m.checks)
    m._advance_lite(step=2)
    assert m.checks == checks


# ------------------------------------------------------------- slow --

@pytest.mark.slow
def test_chaos_32_validators_churn_geo_acceptance(tmp_path):
    """THE ISSUE 11 acceptance run: a 32-validator ChaosNet with
    valset churn (>=1 join, >=1 leave, >=1 stake change applied
    through EndBlock), the wan3 geo latency profile, and injected
    faults — 0 invariant violations, every height continuously
    lite-certified against the churning valset."""
    spec = scale_spec(32)
    r = run_chaos(spec=spec, seed=42, n=32,
                  workdir=str(tmp_path / "net32"),
                  target_height=4, max_steps=400, settle_steps=10)
    assert r["violations"] == []
    assert r["n_nodes"] == 32
    churn = r["churn"]
    assert churn["churn_join"] >= 1
    assert churn["churn_leave"] >= 1
    assert churn["churn_stake"] >= 1
    f = r["faults_injected"]
    assert f.get("drop", 0) >= 1 and f.get("delay", 0) >= 1
    assert f.get("geo_drop", 0) + f.get("geo_throttle", 0) >= 1
    assert f.get("crash") == 1 and f.get("restart") == 1
    lite = r["lite"]
    assert lite["active"]
    assert lite["certified_height"] == r["max_height"]
    assert lite["valset_updates"] >= 3
    assert r["fault_log_sha256"]


@pytest.mark.slow
def test_chaos_long_soak_thousands_of_faults(tmp_path):
    """Long seeded soak: thousands of faults across churn + geo +
    partitions + crashes + a byzantine window on an 8-node net — zero
    invariant violations, every injected double-sign committed as
    evidence, every height lite-certified."""
    spec = {
        "drop": 0.05,
        "delay": 0.10,
        "delay_steps": [1, 3],
        "duplicate": 0.03,
        "reorder": 0.04,
        "geo": {"profile": "wan3"},
        "churn": {"start_height": 3, "every_heights": 3, "standby": 2,
                  "ops": ["join", "leave", "stake"], "max_events": 4},
        "partitions": [{"start": 120, "stop": 170,
                        "groups": [[0, 1], [2, 3, 4, 5, 6, 7]]},
                       {"start": 900, "stop": 980,
                        "groups": [[2, 3], [0, 1, 4, 5, 6, 7]]}],
        "crashes": [{"node": 3, "after_height": 3,
                     "point": "consensus.before_save_block",
                     "down_steps": 20},
                    {"node": 5, "after_height": 6,
                     "point": "execution.after_app_commit",
                     "down_steps": 15}],
        # the byzantine window sits in steady state AFTER the first
        # partition heals: equivocations at the genesis heights (while
        # WAN rounds are still long and churn txs are landing) can
        # miss the honest-capture window entirely — observed as 4
        # uncommitted double-signs with a step-30 start. Like
        # ACCEPTANCE_SPEC, the scenario phases are staggered so every
        # injected double-sign is CAPTURABLE; the oracle then insists
        # all of them commit
        "byzantine": [{"node": 1, "behavior": "equivocate",
                       "start": 190, "stop": 330}],
        "stall_assist": True,
    }
    r = run_chaos(spec=spec, seed=1234, n=8,
                  workdir=str(tmp_path / "soak"),
                  target_height=35, max_steps=2600, settle_steps=60)
    assert r["violations"] == []
    assert r["faults_injected_total"] >= 2000, r["faults_injected"]
    f = r["faults_injected"]
    for kind in ("drop", "delay", "duplicate", "reorder", "partition",
                 "heal", "crash", "restart", "equivocation",
                 "churn_join"):
        assert f.get(kind, 0) >= 1, f"{kind} never fired: {f}"
    ev = r["evidence"]
    assert ev["committed"] == ev["injected_double_signs"] > 0
    lite = r["lite"]
    assert lite["active"]
    assert lite["certified_height"] >= r["max_height"] - 1
    assert lite["valset_updates"] >= 2


@pytest.mark.slow
def test_statesync_joins_net_whose_valset_rotated(tmp_path, monkeypatch):
    """Statesync under churn (ISSUE 11 tentpole): a fresh node
    restores from a snapshot taken BEFORE the valset rotated, then
    fast-syncs the tail across the EndBlock deltas — and the restored
    chain lite-certifies from the snapshot's valset to the frontier
    through every delta."""
    import time as _time

    monkeypatch.setenv("TM_TPU_SNAPSHOT_INTERVAL", "5")
    monkeypatch.setenv("TM_TPU_SNAPSHOT_KEEP", "2")
    from tendermint_tpu.chaos.runner import ChaosNet
    from tendermint_tpu.lite import ContinuousCertifier
    from tendermint_tpu.lite.types import FullCommit, SignedHeader
    from tests.test_statesync import _fresh_side, _serving_switch, _wait
    from tendermint_tpu.p2p.test_util import connect_switches

    spec = {
        "churn": {"start_height": 5, "every_heights": 2, "standby": 1,
                  "ops": ["join", "stake"], "max_events": 2},
        "stall_assist": True,
    }
    net = ChaosNet(str(tmp_path / "src-net"), spec, seed=3, n=4,
                   chain_id="ss-net")
    net.start()
    try:
        net.run(9, max_steps=800, settle_steps=10)
        rep = net.report()
        assert rep["violations"] == []
        assert rep["churn"]["events"] >= 2
        node0 = net.nodes[0]
        snap_heights = node0.snapshot_store.list_heights()
        assert snap_heights, "source produced no snapshots"
        snap_h = max(h for h in snap_heights if h <= 5)
        # the valset REALLY rotated after the snapshot height
        vals_at_snap = node0.state_store.load_validators(snap_h)
        vals_at_top = node0.state_store.load_validators(
            node0.block_store.height())
        assert vals_at_snap.hash() != vals_at_top.hash()
        # serve only the pre-rotation snapshot: the joiner must cross
        # the churn through the fast-synced tail, not the snapshot
        for h in snap_heights:
            if h != snap_h:
                node0.snapshot_store.delete(h)

        src = {"gen": net.gen, "cs": node0.consensus,
               "block_store": node0.block_store,
               "state_store": node0.state_store,
               "snap_store": node0.snapshot_store}
        sw_src = _serving_switch(src, b"\x31" * 32)
        new = _fresh_side(tmp_path, net.gen)
        new["sw"].start()
        connect_switches(sw_src, new["sw"])
        try:
            _wait(lambda: new["bc"].synced, 60, "never synced")
            restored = new["ss"].restored_state
            assert restored is not None
            assert restored.last_block_height == snap_h
            assert new["block_store"].base() == snap_h + 1
            top = new["block_store"].height()
            assert top >= node0.block_store.height() - 1
            # the tail crossed the rotation: the restored node's OWN
            # stores now hold the churned valsets
            assert new["state_store"].load_validators(top).hash() == \
                node0.state_store.load_validators(top).hash()

            # ...and the whole tail lite-certifies from the SNAPSHOT's
            # trusted valset across every delta, off the joiner's own
            # stores (exactly what a light client bootstrapping from
            # that snapshot would do)
            cert = ContinuousCertifier(
                "ss-net", restored.validators,
                next_height=snap_h + 1)
            for h in range(snap_h + 1, top + 1):
                meta = new["block_store"].load_block_meta(h)
                commit = new["block_store"].load_seen_commit(h) or \
                    new["block_store"].load_block_commit(h)
                vals = new["state_store"].load_validators(h)
                assert meta is not None and commit is not None
                cert.advance(FullCommit(
                    SignedHeader(meta.header, commit, meta.block_id),
                    vals))
            assert cert.certified_height == top
            assert cert.updates >= 1, "tail crossed no valset delta"
        finally:
            sw_src.stop()
            new["sw"].stop()
    finally:
        net.stop()
