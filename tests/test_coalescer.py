"""Dispatch-coalescer tests: cross-call micro-batching correctness
(per-caller verdict demux, error isolation, the TM_TPU_COALESCE=off
escape hatch), the stats-race regression, and the precomputed-table
host oracle's differential against the pure RFC 8032 reference."""

import sys
import threading
import time

import numpy as np
import pytest

from tendermint_tpu.models.coalescer import DispatchCoalescer
from tendermint_tpu.models.verifier import BatchVerifier
from tendermint_tpu.utils import ed25519_ref as ref


def _ed_item(i: int, valid: bool = True, msg: bytes = None):
    seed = (i + 1).to_bytes(32, "little")
    m = msg if msg is not None else b"coalesce-vote-%d" % i
    sig = ref.sign(seed, m) if valid else bytes(64)
    return (ref.public_key(seed), m, sig)


def _secp_item(i: int, valid: bool = True):
    from tendermint_tpu.types.keys import Secp256k1PrivKey
    k = Secp256k1PrivKey.generate((0x5EC0 + i).to_bytes(32, "big"))
    m = b"coalesce-secp-%d" % i
    sig = k.sign(m) if valid else b"\x30\x06\x02\x01\x01\x02\x01\x01"
    return (k.pubkey.secp256k1, m, sig)


# ---------------------------------------------------------------- coalescer


def test_coalescer_merges_while_dispatch_busy():
    """Deterministic merge: hold the first dispatch on a gate, pile 10
    more single-item calls into the queue, release — the second drain
    must merge all 10 into ONE dispatch and every caller must get back
    exactly its own verdict slice."""
    entered = threading.Event()
    gate = threading.Event()
    sizes = []

    def dispatch(items):
        sizes.append(len(items))
        if len(sizes) == 1:
            entered.set()
            assert gate.wait(10)
        arr = np.array([x % 2 == 0 for x in items], np.bool_)
        return lambda: arr

    c = DispatchCoalescer(dispatch, max_batch=4096, max_wait_s=0.002)
    try:
        r0 = c.submit([0])
        assert entered.wait(10)
        rs = [c.submit([i, i + 1]) for i in range(1, 21, 2)]
        gate.set()
        assert r0().tolist() == [True]
        for i, r in zip(range(1, 21, 2), rs):
            assert r().tolist() == [i % 2 == 0, (i + 1) % 2 == 0]
        assert sizes[0] == 1
        assert sizes[1] == 20, sizes  # 10 calls x 2 items, one dispatch
    finally:
        c.close()


def test_coalescer_error_isolation():
    """One caller's malformed items must surface as THAT caller's
    exception while every other merged caller still gets verdicts."""
    entered = threading.Event()
    gate = threading.Event()
    n_disp = []

    def dispatch(items):
        n_disp.append(len(items))
        if len(n_disp) == 1:
            entered.set()
            assert gate.wait(10)
        if any(not isinstance(x, int) for x in items):
            raise TypeError("bad item")
        arr = np.ones(len(items), np.bool_)
        return lambda: arr

    c = DispatchCoalescer(dispatch, max_batch=4096, max_wait_s=0.002)
    try:
        r0 = c.submit([1])
        assert entered.wait(10)
        good = [c.submit([i]) for i in range(4)]
        bad = c.submit(["poison"])
        good2 = [c.submit([i]) for i in range(4)]
        gate.set()
        assert r0().tolist() == [True]
        for r in good + good2:
            assert r().tolist() == [True]
        with pytest.raises(TypeError):
            bad()
    finally:
        c.close()


def test_coalescer_close_drains_queue():
    arrs = []

    def dispatch(items):
        arr = np.ones(len(items), np.bool_)
        arrs.append(arr)
        return lambda: arr

    c = DispatchCoalescer(dispatch, max_batch=64, max_wait_s=0.001)
    rs = [c.submit([i]) for i in range(5)]
    c.close()
    for r in rs:
        assert r().tolist() == [True]
    with pytest.raises(RuntimeError):
        c.submit([1])


# ------------------------------------------------- verifier + threads


def test_threaded_single_vote_callers_mixed_keys():
    """The ISSUE acceptance test: N threads submitting 1-vote batches
    with mixed ed25519/secp256k1 keys and some invalid signatures —
    every caller gets exactly its own verdicts, in order, through a
    coalescing verifier."""
    cases = [
        (_ed_item(0), True),
        (_ed_item(1, valid=False), False),
        (_secp_item(0), True),
        (_ed_item(2), True),
        (_secp_item(1, valid=False), False),
        (_ed_item(3, msg=b"other", valid=True), True),
        (_ed_item(4, valid=False), False),
        (_ed_item(5), True),
    ]
    v = BatchVerifier("auto", coalesce="on", coalesce_wait_ms=4.0)
    try:
        results = {}

        def worker(i):
            item, want = cases[i % len(cases)]
            got = []
            for _ in range(4):
                got.append(bool(v.verify([item])[0]))
            results[i] = (got, want)

        ths = [threading.Thread(target=worker, args=(i,))
               for i in range(len(cases) * 2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert len(results) == len(cases) * 2
        for i, (got, want) in results.items():
            assert got == [want] * 4, (i, got, want)
        assert v.stats["coalesced_calls"] == len(cases) * 2 * 4
        # merged dispatches: every submitted call accounted for exactly
        # once (calls = merged dispatch count <= submissions)
        assert 1 <= v.stats["calls"] <= v.stats["coalesced_calls"]
        assert v.stats["sigs"] == v.stats["coalesced_calls"]
    finally:
        v.close()


def test_coalesce_off_escape_hatch(monkeypatch):
    """TM_TPU_COALESCE=off restores single-call behavior: no coalescer
    is ever built, verdicts are byte-for-byte those of the direct path,
    and the env var wins over the constructor knob."""
    monkeypatch.setenv("TM_TPU_COALESCE", "off")
    v_off = BatchVerifier("auto", coalesce="on")  # env wins
    assert v_off.coalesce == "off"
    items = [_ed_item(0), _ed_item(1, valid=False), _secp_item(0)]
    out_off = v_off.verify(items)
    assert v_off._coalescer is None
    assert v_off.stats["coalesced_calls"] == 0

    monkeypatch.setenv("TM_TPU_COALESCE", "on")
    v_on = BatchVerifier("auto")
    try:
        out_on = v_on.verify(items)
        assert v_on._coalescer is not None
        assert out_off.dtype == out_on.dtype
        assert out_off.tobytes() == out_on.tobytes()
        assert out_off.tolist() == [True, False, True]
    finally:
        v_on.close()

    monkeypatch.delenv("TM_TPU_COALESCE")
    with pytest.raises(ValueError):
        BatchVerifier("auto", coalesce="sometimes")


def test_stats_thread_safety():
    """Satellite regression: stats read-modify-writes from concurrent
    reactor threads must not lose updates (they were unsynchronized
    before the stats lock)."""
    v = BatchVerifier("python", coalesce="off")
    n_threads, n_iter = 8, 400
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # force frequent preemption
    try:
        def worker():
            for _ in range(n_iter):
                v.verify([])

        ths = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert v.stats["calls"] == n_threads * n_iter


def test_mixed_path_stats_compensation():
    """The mixed-key re-dispatch must still count the outer call once
    (the -= compensation, now under the stats lock)."""
    v = BatchVerifier("jax", coalesce="off")
    items = [_ed_item(0), _secp_item(0), _ed_item(1)]
    out = v.verify(items)
    assert out.tolist() == [True, True, True]
    assert v.stats["calls"] == 1
    assert v.stats["sigs"] == 3


# ------------------------------------------------- async opt-in paths


def test_add_vote_async_and_verify_commit_async():
    from tendermint_tpu.types import PrivKey, Validator, ValidatorSet
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType
    from tendermint_tpu.types.vote_set import VoteSet

    chain = "coalesce-async"
    keys = [PrivKey.generate((i + 1).to_bytes(32, "little"))
            for i in range(4)]
    vs = ValidatorSet([Validator(k.pubkey.ed25519, 10) for k in keys])
    bid = BlockID(b"\x42" * 32, PartSetHeader(1, b"\x24" * 32))
    v = BatchVerifier("python", coalesce="on", coalesce_wait_ms=2.0)
    try:
        vset = VoteSet(chain, 1, 0, VoteType.PRECOMMIT, vs, verifier=v)
        resolvers = []
        for idx, val in enumerate(vs.validators):
            key = next(k for k in keys
                       if k.pubkey.ed25519 == val.pubkey)
            vote = Vote(val.address, idx, 1, 0, 1000 + idx,
                        VoteType.PRECOMMIT, bid)
            vote.signature = key.sign(vote.sign_bytes(chain))
            resolvers.append(vset.add_vote_async(vote))
        # crypto dispatched for all four; apply on the owning thread
        assert all(r() for r in resolvers)
        assert vset.has_two_thirds_majority()
        commit = vset.make_commit()

        finish = vs.verify_commit_async(chain, bid, 1, commit, verifier=v)
        finish()  # no raise: valid commit
        commit.precommits[0].signature = bytes(64)
        bad = vs.verify_commit_async(chain, bid, 1, commit, verifier=v)
        with pytest.raises(ValueError):
            bad()
        # invalid-signature votes fail at the resolver, like add_vote
        vset2 = VoteSet(chain, 1, 0, VoteType.PREVOTE, vs, verifier=v)
        vote = Vote(vs.validators[0].address, 0, 1, 0, 1, VoteType.PREVOTE,
                    bid)
        vote.signature = bytes(64)
        r = vset2.add_vote_async(vote)
        with pytest.raises(ValueError, match="invalid signature"):
            r()
    finally:
        v.close()


# ------------------------------------------- precomputed-table oracle


def test_fast_verify_matches_oracle():
    """utils/ed25519_fast must be verdict-identical to the pure RFC 8032
    oracle on valid, tampered, non-canonical and garbage inputs — a
    split here is a consensus fork on the no-OpenSSL host path."""
    import random

    from tendermint_tpu.utils import ed25519_fast as fast

    rng = random.Random(20260804)
    p255 = (1 << 255) - 19
    fast.cache_clear()
    for i in range(8):
        seed = rng.randbytes(32)
        pk = ref.public_key(seed)
        msg = rng.randbytes(rng.randrange(0, 64))
        sig = ref.sign(seed, msg)
        high_s = sig[:32] + (
            (int.from_bytes(sig[32:], "little") + ref.L) %
            (1 << 256)).to_bytes(32, "little")
        cases = [
            (pk, msg, sig),                                  # valid
            (pk, msg + b"x", sig),                           # wrong msg
            (pk, msg, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]),
            (pk, msg, sig[:-1]),                             # short sig
            (pk, msg, rng.randbytes(64)),                    # garbage
            (rng.randbytes(32), msg, sig),                   # wrong key
            (pk, msg, high_s),                               # s >= L
            (pk[:-1], msg, sig),                             # short key
        ]
        for p, m, s in cases:
            assert fast.verify(p, m, s) == ref.verify(p, m, s), \
                (i, p.hex(), s.hex())
    # adversarial non-canonical encodings (the OpenSSL leniency gap set)
    msg = b"adversarial"
    ncid = (1).to_bytes(32, "little")
    ncid = ncid[:31] + bytes([ncid[31] | 0x80])       # y=1, sign=1
    ncid2 = (p255 - 1).to_bytes(32, "little")
    ncid2 = ncid2[:31] + bytes([ncid2[31] | 0x80])    # y=-1, sign=1
    ybig = (p255 + 2).to_bytes(32, "little")          # y >= p
    seed = b"\x07" * 32
    for bad in (ncid, ncid2, ybig):
        for pkey, sg in ((bad, bad + bytes(32)),
                         (ref.public_key(seed), bad + bytes(32)),
                         (bad, ref.sign(seed, msg))):
            assert fast.verify(pkey, msg, sg) == ref.verify(pkey, msg, sg)
    # repeat hits (cached tables) keep identical verdicts
    pk = ref.public_key(seed)
    sig = ref.sign(seed, msg)
    for _ in range(3):
        assert fast.verify(pk, msg, sig)
        assert not fast.verify(pk, msg + b"!", sig)


def test_verify_many_matches_verify_any():
    from tendermint_tpu.types.keys import verify_any, verify_many

    items = [_ed_item(0), _ed_item(1, valid=False), _secp_item(0),
             _ed_item(2), _ed_item(3), (b"\x00" * 7, b"m", b"s"),
             _secp_item(1, valid=False)]
    got = verify_many(items)
    assert got == [verify_any(*it) for it in items]
    assert got == [True, False, True, True, True, False, False]
    # below the table threshold: still exact
    small = items[:2]
    assert verify_many(small) == [verify_any(*it) for it in small]


def test_coalesce_metrics_registered():
    """The tm_verifier_coalesce_* catalog passes the metrics lint."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "check_metrics.py")
    spec = importlib.util.spec_from_file_location("_check_metrics", path)
    cm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cm)
    assert "tendermint_tpu.models.coalescer" in cm.INSTRUMENTED_MODULES
    assert cm.main() == 0
    from tendermint_tpu import telemetry
    for name in ("verifier_coalesce_calls_total",
                 "verifier_coalesce_dispatches_total",
                 "verifier_coalesce_batch_calls",
                 "verifier_coalesce_queue_depth",
                 "verifier_coalesce_wait_seconds",
                 "verifier_coalesce_fallback_total"):
        assert telemetry.REGISTRY.get(name) is not None, name
