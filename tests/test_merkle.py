"""SHA-256 device kernel vs hashlib; Merkle device tree vs host spec."""

import hashlib
import random

import numpy as np
import jax.numpy as jnp

from tendermint_tpu.ops import merkle, sha256

rng = random.Random(7)


def test_sha256_fixed_matches_hashlib():
    for L in (0, 1, 33, 55, 56, 63, 64, 65, 100, 128, 1000):
        msgs = [rng.randbytes(L) for _ in range(4)]
        arr = jnp.asarray(np.stack(
            [np.frombuffer(m, np.uint8).reshape(L) if L else np.zeros(0, np.uint8)
             for m in msgs]))
        got = np.asarray(sha256.hash_fixed(arr))
        for i, m in enumerate(msgs):
            assert got[i].tobytes() == hashlib.sha256(m).digest(), L


def test_root_device_matches_host():
    for n in (1, 2, 3, 5, 8, 9):  # padded sizes 1,2,4,8,16 — bounded compiles
        items = [rng.randbytes(rng.randrange(0, 50)) for _ in range(n)]
        assert merkle.root(items) == merkle.root_host(items), n


def test_empty_and_singleton():
    assert merkle.root([]) == merkle.root_host([])
    assert merkle.root([b""]) == merkle.root_host([b""])
    # empty item != empty tree
    assert merkle.root([b""]) != merkle.root([])
    # size binding: same digests, different count -> different root
    assert merkle.root([b"a"]) != merkle.root([b"a", bytes.fromhex("00" * 32)])


def test_proofs_roundtrip_and_reject():
    items = [rng.randbytes(10) for _ in range(11)]
    root = merkle.root_host(items)
    for idx in (0, 1, 5, 10):
        proof_root, aunts = merkle.proof_host(items, idx)
        assert proof_root == root
        assert merkle.verify_proof_host(root, len(items), idx, items[idx], aunts)
        # wrong item
        assert not merkle.verify_proof_host(root, len(items), idx, b"evil", aunts)
        # wrong index
        assert not merkle.verify_proof_host(root, len(items), (idx + 1) % 11,
                                            items[idx], aunts)
        # truncated proof
        assert not merkle.verify_proof_host(root, len(items), idx, items[idx],
                                            aunts[:-1])
    # wrong total
    proof_root, aunts = merkle.proof_host(items, 3)
    assert not merkle.verify_proof_host(root, 12, 3, items[3], aunts)


def test_order_sensitivity():
    items = [b"a", b"b", b"c"]
    swapped = [b"b", b"a", b"c"]
    assert merkle.root_host(items) != merkle.root_host(swapped)


def test_root_from_repeated_digest_matches_generic():
    from tendermint_tpu.ops import merkle

    d = merkle.leaf_hash(b"repeat-me")
    for n in [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 31, 100, 1000, 5000]:
        assert merkle.root_from_repeated_digest(d, n) == \
            merkle.root_from_digests_host(d * n), n
    assert merkle.root_from_repeated_digest(d, 0) == \
        merkle.root_from_digests_host(b"")
