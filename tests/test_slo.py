"""Tx-lifecycle SLO plane (ISSUE 14): quantile-sketch accuracy vs
sorted ground truth, deterministic hash sampling, TM_TPU_SLO=off
zero-state neutrality, stage ordering + leg accounting, overflow and
timeout eviction, rolling windows, the /healthz verdict fold-in, tail
attribution, cross-node snapshot merging (the scripts/slo_report.py
path), /slo + /healthz over HTTP in loop mode, the rpc_call_seconds
route label, and end-to-end stage ordering on a 2-node socket net."""

import json
import math
import os
import sys
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry import slo
from tendermint_tpu.telemetry.registry import (QuantileSketch,
                                               quantile_of_items)


@pytest.fixture(autouse=True)
def _slo_reset(monkeypatch):
    """The tracker is process-global; every test starts off/empty."""
    monkeypatch.delenv("TM_TPU_SLO", raising=False)
    monkeypatch.delenv("TM_TPU_SLO_SAMPLE", raising=False)
    slo.configure("off")
    slo.reset()
    yield
    slo.configure("off")
    slo.reset()


def _enable(monkeypatch, sample: str = "1.0"):
    monkeypatch.setenv("TM_TPU_SLO", "on")
    monkeypatch.setenv("TM_TPU_SLO_SAMPLE", sample)
    slo.reset()


# ------------------------------------------------------------- sketch

def test_sketch_exact_under_cap():
    s = QuantileSketch(64)
    vals = [7.0, 1.0, 5.0, 3.0, 9.0]
    for v in vals:
        s.observe(v)
    assert s.count == 5 and s.sum == sum(vals)
    assert s.quantile(0.0) == 1.0
    assert s.quantile(1.0) == 9.0
    assert s.quantile(0.5) == 5.0
    # empty sketch: NaN, not an exception
    assert math.isnan(QuantileSketch(64).quantile(0.5))


def test_sketch_accuracy_bounds_vs_sorted_ground_truth():
    """After many compactions, every reported quantile's TRUE rank in
    the sorted ground truth stays within 3% of the requested one."""
    n, cap = 20000, 256
    s = QuantileSketch(cap)
    truth = []
    for i in range(n):
        v = float((i * 7919) % n)   # a permutation of 0..n-1
        truth.append(v)
        s.observe(v)
    truth.sort()
    assert s.count == n
    assert s.sum == sum(truth)
    for q in (0.5, 0.9, 0.95, 0.99, 0.999):
        est = s.quantile(q)
        true_rank = truth.index(est) / (n - 1)
        assert abs(true_rank - q) < 0.03, (q, est, true_rank)
    # weight conservation: the compacted items still represent n obs
    assert sum(w for _, w in s.items()) == pytest.approx(n, rel=0.02)


def test_sketch_deterministic_across_instances():
    a, b = QuantileSketch(64), QuantileSketch(64)
    for i in range(5000):
        v = float((i * 31) % 997)
        a.observe(v)
        b.observe(v)
    assert a.items() == b.items()
    assert a.quantile(0.99) == b.quantile(0.99)


def test_quantile_of_items_weighted():
    # weight 3 at 1.0, weight 1 at 10.0 -> p50 sits on the heavy value
    items = [(1.0, 3), (10.0, 1)]
    assert quantile_of_items(items, 0.5) == 1.0
    assert quantile_of_items(items, 1.0) == 10.0
    assert math.isnan(quantile_of_items([], 0.5))


def test_summary_family_exposes_quantiles():
    reg = telemetry.Registry()
    fam = reg.summary("slo_test_seconds", "t", ("stage",))
    fam.labels("x").observe(0.5)
    fam.labels("x").observe(1.5)
    val = reg.value("slo_test_seconds", {"stage": "x"})
    assert val["count"] == 2 and val["sum"] == 2.0
    assert val["quantiles"][0.5] in (0.5, 1.5)
    text = reg.expose()
    assert 'slo_test_seconds{stage="x",quantile="0.5"}' in \
        text.replace("tm_", "")
    assert "slo_test_seconds_count" in text
    # conflicting re-registration is loud, identical is idempotent
    assert reg.summary("slo_test_seconds", "t", ("stage",)) is fam
    with pytest.raises(ValueError):
        reg.summary("slo_test_seconds", "t", ("stage",),
                    quantiles=(0.5,))


# ----------------------------------------------------------- sampling

def test_sampling_deterministic_and_rate_shaped(monkeypatch):
    _enable(monkeypatch, "0.5")
    import hashlib
    txs = [b"tx-%d" % i for i in range(4000)]
    decisions = [slo.sampled(hashlib.sha256(tx).digest()) for tx in txs]
    # same hash -> same decision, every time (what makes the
    # cross-node report a join instead of a guess)
    again = [slo.sampled(hashlib.sha256(tx).digest()) for tx in txs]
    assert decisions == again
    frac = sum(decisions) / len(decisions)
    assert 0.45 < frac < 0.55, frac
    monkeypatch.setenv("TM_TPU_SLO_SAMPLE", "1.0")
    slo.reset()
    assert all(slo.sampled(hashlib.sha256(tx).digest()) for tx in txs)
    monkeypatch.setenv("TM_TPU_SLO_SAMPLE", "0")
    slo.reset()
    assert not any(slo.sampled(hashlib.sha256(tx).digest())
                   for tx in txs)


def test_off_means_zero_state_and_identical_mempool_results():
    """Default-off: no entry point records anything, and the mempool's
    CheckTx surface returns field-identical results whether the plane
    exists or not (it never touches the wire by construction)."""
    assert slo.enabled() is False
    before = telemetry.value("slo_sampled_total")
    slo.admit(b"tx")
    slo.mark(b"tx", "checktx")
    slo.mark_many([b"tx"], "commit", 3)
    assert len(slo.TRACKER._inflight) == 0
    assert slo.TRACKER.sampled_total == 0
    assert telemetry.value("slo_sampled_total") == before
    snap = slo.snapshot()
    assert snap["enabled"] is False
    assert slo.verdict()["ok"] is True

    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import local_client_creator
    from tendermint_tpu.mempool import Mempool
    mp = Mempool(local_client_creator(KVStoreApp())(), height=0)
    res = mp.check_tx(b"k=v")
    assert res.ok and slo.TRACKER.sampled_total == 0


# ------------------------------------------------------ stage stamping

def _mk_tracker(now):
    return slo.SLOTracker(now_ns=lambda: now[0])


def test_lifecycle_legs_and_monotonic_accounting(monkeypatch):
    _enable(monkeypatch)
    now = [1_000_000_000]
    t = _mk_tracker(now)
    tx = b"journey"
    t.admit(tx)
    for stage, step_ms in (("checktx", 1), ("propose", 20),
                           ("commit", 200), ("publish", 2),
                           ("deliver", 5)):
        now[0] += step_ms * 1_000_000
        t.mark(tx, stage, height=7)
    assert t.completed_total == 1 and not t._inflight
    assert t.monotonic_violations == 0
    snap = t.snapshot(windows=False)
    st = snap["stages"]
    assert st["checktx"]["p50_ms"] == 1.0
    assert st["propose"]["p50_ms"] == 20.0
    assert st["commit"]["p50_ms"] == 200.0
    assert st["e2e_commit"]["p50_ms"] == 221.0
    assert st["e2e_delivery"]["p50_ms"] == 228.0
    (rec,) = t._completed
    assert rec["h"] == 7 and rec["total_ms"] == 228.0
    # stamps are first-wins idempotent: re-marking changes nothing
    t.mark(tx, "commit", height=9)
    assert t.completed_total == 1


def test_missing_intermediate_stage_closes_from_nearest(monkeypatch):
    """A leg whose natural predecessor never stamped (e.g. no local
    propose observation) closes from the nearest EARLIER stamp."""
    _enable(monkeypatch)
    now = [0]
    t = _mk_tracker(now)
    t.admit(b"x")
    now[0] += 10_000_000
    t.mark(b"x", "checktx")
    now[0] += 90_000_000
    t.mark(b"x", "commit", height=2)    # no propose stamp
    snap = t.snapshot(windows=False)
    assert snap["stages"]["commit"]["p50_ms"] == 90.0  # from checktx
    assert "propose" not in snap["stages"]
    assert snap["stages"]["e2e_commit"]["p50_ms"] == 100.0


def test_unknown_stage_is_loud(monkeypatch):
    _enable(monkeypatch)
    t = _mk_tracker([0])
    t.admit(b"x")
    with pytest.raises(ValueError, match="unknown SLO stage"):
        t.mark_hex(slo.tx_key(b"x"), "telaported")


def test_overflow_eviction_counts(monkeypatch):
    _enable(monkeypatch)
    now = [0]
    t = slo.SLOTracker(now_ns=lambda: now[0], inflight_cap=4)
    for i in range(6):
        t.admit(b"tx-%d" % i)
    assert len(t._inflight) == 4
    assert t.dropped["overflow"] == 2
    assert t.sampled_total == 6


def test_timeout_sweep_splits_undelivered(monkeypatch):
    """Expired txs that never committed count as `timeout` (a health
    failure); committed-but-never-delivered ones as `undelivered`
    (no subscriber was listening — accounting, not alarm)."""
    _enable(monkeypatch)
    now = [0]
    t = slo.SLOTracker(now_ns=lambda: now[0], timeout_s=1.0)
    t.admit(b"stuck")
    t.admit(b"committed")
    t.mark(b"committed", "commit", height=1)
    now[0] += 2_000_000_000
    t.sweep()
    assert not t._inflight
    assert t.dropped["timeout"] == 1
    assert t.dropped["undelivered"] == 1
    assert t.timeout_last_stage == {"admit": 1, "commit": 1}
    # the verdict flags the real failure class only
    v = t.verdict()
    assert v["ok"] is False
    assert "drops_exceed_5pct_of_completions" in v["reasons"]


def test_windows_roll_off(monkeypatch):
    _enable(monkeypatch)
    now = [0]
    t = _mk_tracker(now)
    t.admit(b"old")
    now[0] += 1_000_000
    t.mark(b"old", "checktx")
    # 30s later: a second tx
    now[0] += 30_000_000_000
    t.admit(b"new")
    now[0] += 2_000_000
    t.mark(b"new", "checktx")
    snap = t.snapshot()
    w = snap["windows"]
    assert w["1s"]["checktx"]["count"] == 1    # only the new one
    assert w["1s"]["checktx"]["p50_ms"] == 2.0
    assert w["60s"]["checktx"]["count"] == 2   # both
    assert snap["stages"]["checktx"]["count"] == 2  # cumulative


def test_tail_attribution_names_dominant_stage(monkeypatch):
    _enable(monkeypatch)
    now = [0]
    t = _mk_tracker(now)
    for i in range(40):
        tx = b"tx-%d" % i
        t.admit(tx)
        now[0] += 1_000_000
        t.mark(tx, "checktx")
        # the commit leg dominates, and the slowest txs are commit-heavy
        now[0] += (100 + 10 * i) * 1_000_000
        t.mark(tx, "commit", height=i + 1)
        now[0] += 1_000_000
        t.mark(tx, "publish")
        now[0] += 1_000_000
        t.mark(tx, "deliver")
    att = t.tail_attribution()
    assert att["ready"] is True
    assert att["dominant_stage"] == "commit"
    assert att["tail_count"] >= 1
    assert att["heights"], "tail heights must be joinable"
    assert att["mean_leg_ms"]["commit"] > att["mean_leg_ms"]["checktx"]


def test_merge_snapshots_is_weighted_union(monkeypatch):
    _enable(monkeypatch)
    now = [0]
    docs = []
    for node, ms in (("a", 10), ("b", 30)):
        t = _mk_tracker(now)
        t.admit(b"tx-" + node.encode())
        now[0] += ms * 1_000_000
        t.mark(b"tx-" + node.encode(), "commit", height=1)
        d = t.snapshot(sketches=True)
        d["node"] = node
        docs.append(d)
    merged = slo.merge_snapshots(docs)
    assert merged["nodes"] == ["a", "b"]
    assert merged["sampled_total"] == 2
    assert merged["stages"]["e2e_commit"]["count"] == 2
    assert merged["stages"]["e2e_commit"]["p999_ms"] == 30.0
    # a disabled node is skipped, not merged as zeros
    merged2 = slo.merge_snapshots(docs + [{"enabled": False}])
    assert merged2["sampled_total"] == 2


def test_slo_report_cli_on_files(tmp_path, monkeypatch, capsys):
    _enable(monkeypatch)
    now = [0]
    t = _mk_tracker(now)
    for i in range(3):
        tx = b"r-%d" % i
        t.admit(tx)
        now[0] += 5_000_000
        t.mark(tx, "commit", height=1)
    doc = t.snapshot(sketches=True)
    doc["node"] = "filenode"
    p = tmp_path / "slo0.json"
    p.write_text(json.dumps(doc))
    import slo_report
    out = tmp_path / "report.json"
    rc = slo_report.main(["--files", str(p), "--report", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "e2e_commit" in text and "3 sampled" in text
    rep = json.loads(out.read_text())
    assert rep["merged"]["stages"]["e2e_commit"]["count"] == 3
    assert rep["per_node"][0]["node"] == "filenode"
    # a plane-off node is skipped loudly, and no nodes -> rc 1
    off = tmp_path / "off.json"
    off.write_text(json.dumps({"enabled": False, "node": "off"}))
    assert slo_report.main(["--files", str(off)]) == 1


# --------------------------------------------------- operational plane

def test_slo_route_healthz_and_call_label_over_http(monkeypatch):
    """Loop mode end to end: GET /slo serves the table, the `slo`
    JSON-RPC route honors sketches=true, /healthz folds the verdict,
    and tm_rpc_call_seconds carries the {route} label."""
    _enable(monkeypatch)
    from tendermint_tpu.p2p.conn.loop import ReactorLoop
    from tendermint_tpu.rpc.core import RPCEnv, make_server

    tx = b"http-tx"
    slo.admit(tx)
    slo.mark(tx, "checktx")
    slo.mark(tx, "commit", height=4)

    loop = ReactorLoop(name="slo-test-loop")
    server, _core = make_server(RPCEnv(), loop=loop)
    host, port = server.serve("127.0.0.1", 0)
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/slo", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["enabled"] is True
        assert doc["sampled_total"] == 1
        assert doc["stages"]["e2e_commit"]["count"] == 1
        assert "sketches" not in doc

        from tendermint_tpu.rpc.client import JSONRPCClient
        c = JSONRPCClient(f"http://{host}:{port}")
        rich = c.call("slo", sketches=True)
        assert rich["sketches"]["e2e_commit"]

        with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read())
        assert hz["slo"]["enabled"] is True
        assert hz["slo"]["ok"] is True and hz["ok"] is True

        # the route label: the JSON-RPC `slo` call above was timed
        # (chain="" — this is a single-chain server; a shard front
        # door's chain_resolver fills it, tests/test_shard.py)
        v = telemetry.value("rpc_call_seconds",
                            {"route": "slo", "chain": ""})
        assert v is not None and v["count"] >= 1
        # unknown methods collapse into one label value
        try:
            c.call("no_such_route")
        except Exception:
            pass
        vu = telemetry.value("rpc_call_seconds",
                             {"route": "unknown", "chain": ""})
        assert vu is not None and vu["count"] >= 1
    finally:
        server.stop()
        loop.stop()


def test_healthz_ok_flips_on_slo_degradation(monkeypatch):
    _enable(monkeypatch)
    from tendermint_tpu.rpc.core import RPCCore, RPCEnv
    core = RPCCore(RPCEnv())
    assert core.healthz()["ok"] is True
    # saturate the tracker: verdict (and the top-level bit) flip
    slo.TRACKER.inflight_cap = 2
    slo.admit(b"a")
    slo.admit(b"b")
    try:
        doc = core.healthz()
        assert doc["slo"]["ok"] is False
        assert "tracker_saturated" in doc["slo"]["reasons"]
        assert doc["ok"] is False
    finally:
        slo.TRACKER.inflight_cap = slo.INFLIGHT_CAP


def test_rpc_core_broadcast_routes_admit_and_checktx(monkeypatch):
    """The front-door stamps ride the real RPC handlers: a
    broadcast_tx_sync admission lands admit + checktx for a sampled
    tx, and broadcast_tx_batch admits the whole list."""
    _enable(monkeypatch)
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import local_client_creator
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.rpc.core import RPCCore, RPCEnv
    mp = Mempool(local_client_creator(KVStoreApp())(), height=0)
    core = RPCCore(RPCEnv(mempool=mp))
    tx = b"front=door"
    core.broadcast_tx_sync(tx)
    e = slo.TRACKER._inflight[slo.tx_key(tx)]
    assert "admit" in e.stamps and "checktx" in e.stamps
    core.broadcast_tx_batch([b"b1=v".hex(), b"b2=v".hex()])
    assert slo.TRACKER.sampled_total == 3
    assert "checktx" in slo.TRACKER._inflight[
        slo.tx_key(b"b1=v")].stamps


def test_metrics_catalog_includes_slo():
    from tendermint_tpu.analysis.checkers import metrics as mcheck
    assert "slo" in mcheck.KNOWN_SUBSYSTEMS
    assert "tendermint_tpu.telemetry.slo" in mcheck.INSTRUMENTED_MODULES
    assert not mcheck.run(), "metrics lint must stay clean"


def test_slo_sample_causal_span_declared():
    from tendermint_tpu.telemetry.causal import SPAN_CATALOG
    assert "slo.sample" in SPAN_CATALOG


# ------------------------------------------------------------- e2e net

def test_e2e_stage_ordering_two_node_socket_net(tmp_path, monkeypatch):
    """TM_TPU_SLO=on across a real 2-node TCP net with a live WS
    subscriber: a tx broadcast through node0's RPC front door reaches
    every stage, the stamps are monotonic, and /slo over HTTP serves
    the journey. (Both in-process nodes share the process-global
    tracker; stamps are first-wins, so ordering still holds.)"""
    monkeypatch.setenv("TM_TPU_SLO", "on")
    monkeypatch.setenv("TM_TPU_SLO_SAMPLE", "1.0")
    slo.reset()
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.rpc.client import JSONRPCClient, WSClient
    from tendermint_tpu.rpc.core import RPCEnv, make_server
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator,
                                      PrivKey)
    from tendermint_tpu.types.priv_validator import (LocalSigner,
                                                     PrivValidator)
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    gen = GenesisDoc(chain_id="slo-net", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    nodes = []
    for i, k in enumerate(keys):
        cfg = make_test_config(str(tmp_path / f"node{i}"))
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.addr_book_strict = False
        nodes.append(Node(cfg, gen,
                          priv_validator=PrivValidator(LocalSigner(k)),
                          in_memory=True, with_p2p=True))
    server = ws = None
    try:
        for n in nodes:
            n.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        server, _core = make_server(RPCEnv.from_node(nodes[0]),
                                    loop=nodes[0].loop)
        host, port = server.serve("127.0.0.1", 0)
        ws = WSClient(host, port)
        ws.subscribe("tm.event = 'Tx'")
        tx = b"slo-e2e=1"
        key = slo.tx_key(tx)
        JSONRPCClient(f"http://{host}:{port}").call(
            "broadcast_tx_sync", tx=tx)
        ev = ws.next_event(timeout=60.0)
        assert ev["tags"]["tx.hash"] == key
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                not any(r["hash"] == key[:16]
                        for r in slo.TRACKER._completed):
            time.sleep(0.05)
        rec = next(r for r in slo.TRACKER._completed
                   if r["hash"] == key[:16])
        # the full journey, in order, with every leg non-negative
        assert set(rec["legs_ms"]) == {"checktx", "propose", "commit",
                                       "publish", "deliver"}
        assert all(ms >= 0 for ms in rec["legs_ms"].values())
        assert rec["h"] >= 1
        assert slo.TRACKER.monotonic_violations == 0
        with urllib.request.urlopen(
                f"http://{host}:{port}/slo", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["completed_total"] >= 1
        assert doc["stages"]["e2e_delivery"]["count"] >= 1
    finally:
        if ws is not None:
            ws.close()
        if server is not None:
            server.stop()
        for n in nodes:
            n.stop()
