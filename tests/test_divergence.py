"""Divergence plane tests: the dual-PYTHONHASHSEED differential replay
(the taint pass's blessed seam for TM_TPU_DIVERGENCE — the seam catalog
names test_dual_hash_seed_replay_bit_identical, so renaming it without
updating analysis/checkers/taint.py turns the seam stale and fails
lint), the canonical transition digest itself, the off-hatch, and the
chaos monitor's divergence invariant (a perturbed digest must surface
as a loud violation, never be silently absorbed)."""

import types

from tendermint_tpu.analysis import divergence
from tendermint_tpu.chaos.monitor import INVARIANTS, InvariantMonitor


# ------------------------------------------------- differential replay


def test_dual_hash_seed_replay_bit_identical():
    """The scripted 5-height trajectory produces bit-identical digest
    streams under two different PYTHONHASHSEED values. Any hash-order
    dependence in the block/ABCI/app_hash path breaks this."""
    out = divergence.run_dual_seed_replay()
    assert out["identical"], (
        "digest streams diverged across PYTHONHASHSEED "
        f"{out['hash_seeds']}:\n--- a ---\n{out['streams'][0]}"
        f"--- b ---\n{out['streams'][1]}")
    assert out["heights"] == len(divergence._SCRIPT)
    # streams are "height hexdigest" lines, strictly increasing heights
    lines = out["streams"][0].splitlines()
    heights = [int(ln.split()[0]) for ln in lines]
    assert heights == sorted(heights) == list(
        range(1, len(divergence._SCRIPT) + 1))
    for ln in lines:
        hexd = ln.split()[1]
        assert len(hexd) == 64 and int(hexd, 16) >= 0


def test_in_process_replay_is_seed_deterministic():
    """Same seed -> identical stream; a different seed moves the pinned
    clock base, so block times (and therefore digests) change."""
    a = divergence.replay_digests(seed=7)
    b = divergence.replay_digests(seed=7)
    c = divergence.replay_digests(seed=8)
    assert a == b
    assert len(a) == len(divergence._SCRIPT)
    assert a != c


def test_recorder_off_hatch(monkeypatch):
    monkeypatch.delenv("TM_TPU_DIVERGENCE", raising=False)
    assert not divergence.enabled()
    assert divergence.maybe_recorder() is None
    monkeypatch.setenv("TM_TPU_DIVERGENCE", "on")
    assert divergence.enabled()
    rec = divergence.maybe_recorder()
    assert rec is not None and rec.stream() == []


def test_cross_check_reports_per_height_mismatch():
    good = types.SimpleNamespace(
        stream=lambda: [(1, "aa"), (2, "bb"), (3, "cc")])
    bad = types.SimpleNamespace(
        stream=lambda: [(1, "aa"), (2, "XX")])
    out = divergence.cross_check({"n0": good, "n1": bad})
    assert out == [{"height": 2, "digests": {"n0": "bb", "n1": "XX"}}]


# ------------------------------------------ chaos divergence invariant


def _recorder(pairs):
    return types.SimpleNamespace(stream=lambda: list(pairs))


def _sched():
    return types.SimpleNamespace(episodes=lambda: [])


def test_monitor_divergence_invariant_is_loud():
    """A deliberately perturbed transition digest on one node must show
    up as a `divergence` violation with the full witness (height, node,
    both digests) and in the finalize report's mismatch count."""
    assert "divergence" in INVARIANTS
    mon = InvariantMonitor()
    mon.attach_divergence(0, _recorder([(1, "d1"), (2, "d2")]))
    mon.attach_divergence(1, _recorder([(1, "d1"), (2, "EVIL")]))
    mon.poll(step=5)

    vio = [v for v in mon.violations if v["invariant"] == "divergence"]
    assert len(vio) == 1
    assert vio[0]["height"] == 2 and vio[0]["node"] == 1
    assert vio[0]["digest"] == "EVIL" and vio[0]["expected"] == "d2"
    # the matching height was checked too (the oracle can fire)
    assert mon.checks["divergence"] == 2

    report = mon.finalize(_sched(), final_step=5)
    assert report["divergence"] == {
        "nodes": 2, "heights_checked": 2, "mismatches": 1}


def test_monitor_divergence_agreeing_nodes_clean():
    mon = InvariantMonitor()
    mon.attach_divergence(0, _recorder([(1, "d1")]))
    mon.attach_divergence(1, _recorder([(1, "d1")]))
    mon.poll(step=1)
    # crash-restart: fresh recorder replays height 1 with the same
    # digest, then extends — re-attach must re-check, not double-count
    mon.attach_divergence(1, _recorder([(1, "d1"), (2, "d2")]))
    mon.attach_divergence(0, _recorder([(1, "d1"), (2, "d2")]))
    mon.poll(step=2)
    assert not [v for v in mon.violations
                if v["invariant"] == "divergence"]
    report = mon.finalize(_sched(), final_step=2)
    assert report["divergence"]["mismatches"] == 0
    assert report["divergence"]["heights_checked"] == 2

    # None recorder (knob off) is ignored — no divergence section
    empty = InvariantMonitor()
    empty.attach_divergence(0, None)
    empty.poll(step=1)
    assert "divergence" not in empty.finalize(_sched(), final_step=1)
