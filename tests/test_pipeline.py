"""Pipelined block hot path (ISSUE 7): byte-parity of the native
part-set builder, streaming-vs-batch proposal gossip wire equality, the
TM_TPU_PIPELINE=off escape hatch, the make_part_set cache's invalidation
discipline, group-commit staging, and the scalar-crypto fast paths that
sit on the commit-path critical chain."""

import pytest

from tendermint_tpu import native
from tendermint_tpu.ops import merkle
from tendermint_tpu.types import encoding
from tendermint_tpu.types.block import Block, Data, Header
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.utils import clock

from tests.test_consensus import ListMempool, make_net


def _data(n: int) -> bytes:
    return bytes((i * 131 + 7) % 256 for i in range(n))


# ------------------------------------------------- native builder parity


@pytest.mark.parametrize("size,part_size", [
    (0, 64),        # empty block -> exactly one empty part
    (1, 64), (63, 64), (64, 64), (65, 64),    # 1-part boundaries
    (1000, 64), (4096, 64),                   # multi-part, power of two
    (12345, 777), (5000, 4999), (5000, 5001),  # odd sizes
])
def test_partset_build_native_matches_python(size, part_size):
    data = _data(size)
    chunks = [data[i:i + part_size]
              for i in range(0, len(data), part_size)] or [b""]
    want_root, want_proofs = merkle.tree_proofs_host(chunks)
    out = native.partset_build(data, part_size)
    if out is None:
        pytest.skip("native plane unavailable")
    root, proofs = out
    assert root == want_root
    assert proofs == want_proofs


@pytest.mark.parametrize("size,part_size", [(0, 64), (65, 64), (5000, 512)])
def test_from_data_same_bytes_all_impls(monkeypatch, size, part_size):
    """PartSet.from_data is byte-identical with the pipeline on (native
    one-call builder), off (serial chunk split), and with the native
    plane disabled entirely."""
    data = _data(size)

    def snap(ps):
        return (ps.total, ps.root,
                [(p.index, p.payload, p.proof) for p in ps.parts])

    monkeypatch.setenv("TM_TPU_PIPELINE", "on")
    on = snap(PartSet.from_data(data, part_size))
    monkeypatch.setenv("TM_TPU_PIPELINE", "off")
    off = snap(PartSet.from_data(data, part_size))
    assert on == off
    # proofs must verify under the host spec either way
    total, root, parts = on
    for i, payload, proof in parts:
        assert merkle.verify_proof_host(root, total, i, payload, proof)


def test_from_data_streaming_equals_batch():
    data = _data(5000)
    batch = PartSet.from_data(data, 512)
    ps, it = PartSet.from_data_streaming(data, 512)
    # header usable before any part materializes (the proposal ships it)
    assert ps.header() == batch.header()
    assert not ps.is_complete()
    yielded = list(it)
    assert ps.is_complete()
    assert ps.get_data() == data
    assert [(p.index, p.payload, p.proof) for p in yielded] == \
        [(p.index, p.payload, p.proof) for p in batch.parts]


# ------------------------------------------------- make_part_set cache


def test_make_part_set_cached_and_header_mutation_invalidates():
    h = Header(chain_id="c", height=1, time_ns=1,
               validators_hash=b"\x01" * 32)
    blk = Block(h, Data([b"k1=v1", b"k2=v2"]))
    blk.fill_header()
    ps = blk.make_part_set(64)
    assert blk.make_part_set(64) is ps          # cached per (hash, size)
    assert blk.make_part_set(32) is not ps      # different split
    bid = blk.block_id(64)
    assert bid.parts == ps.header()
    # ANY header mutation must invalidate: a stale part set under a new
    # header hash would be a consensus bug
    blk.header.time_ns = 2
    ps2 = blk.make_part_set(64)
    assert ps2 is not ps
    assert ps2.root != ps.root
    assert blk.block_id(64).parts == ps2.header()
    # unfilled headers (hash() == b"") are never cached
    h2 = Header(chain_id="c", height=1, time_ns=1)
    blk2 = Block(h2, Data([b"x=y"]))
    assert blk2.header.hash() == b""
    assert blk2.make_part_set(64) is not blk2.make_part_set(64)


# ---------------------------------------- proposal gossip wire parity


def _drive_one_height(monkeypatch, pipeline_mode: str):
    """Single-validator net: commit height 1 with a fixed clock and
    capture every broadcast message, serialized canonically."""
    monkeypatch.setenv("TM_TPU_PIPELINE", pipeline_mode)
    clock.set_source(lambda: 1_700_000_000_000_000_000)
    try:
        nodes, _keys = make_net(1, chain_id="pipe-wire")
        cs = nodes[0]
        mp = ListMempool()
        mp.txs = [b"wire/k%d=v%d" % (i, i) for i in range(50)]
        cs.mempool = mp
        wire = []
        cs.broadcast_hooks.append(
            lambda msg: wire.append(encoding.cdumps(msg)))
        cs.start()
        for _ in range(100):
            if cs.state.last_block_height >= 1:
                break
            cs.ticker.fire_next()
        assert cs.state.last_block_height >= 1
        cs.stop()
        return wire
    finally:
        clock.set_source(None)


def test_streaming_gossip_wire_equals_serial(monkeypatch):
    """The pipelined proposer (streaming part gossip, precompute,
    group commit) puts byte-identical proposal/part/vote messages on
    the wire, in the same broadcast order, as the serial path — the
    fixed clock pins timestamps, Ed25519 signing is deterministic."""
    on = _drive_one_height(monkeypatch, "on")
    off = _drive_one_height(monkeypatch, "off")

    def interesting(wire):
        keep = []
        for raw in wire:
            obj = encoding.cloads(raw)
            if obj.get("type") in ("proposal", "block_part", "vote"):
                keep.append(raw)
        return keep

    assert interesting(on) == interesting(off)


def test_pipeline_off_serial_broadcast_shape(monkeypatch):
    """TM_TPU_PIPELINE=off produces today's exact sequence: the
    proposal, then every part in index order, each part message equal
    to the canonical encoding of the proposer's own part set."""
    off = [encoding.cloads(raw)
           for raw in _drive_one_height(monkeypatch, "off")]
    data_msgs = [m for m in off if m.get("type") in ("proposal",
                                                     "block_part")]
    assert data_msgs[0]["type"] == "proposal"
    total = data_msgs[0]["proposal"]["block_parts_header"]["total"]
    parts = [m for m in data_msgs if m["type"] == "block_part"]
    assert [p["part"]["index"] for p in parts[:total]] == list(range(total))


# --------------------------------------------------- group-commit plane


def test_staged_db_read_your_writes_and_flush():
    from tendermint_tpu.storage.db import MemDB, StagedDB
    inner = MemDB()
    inner.set(b"a", b"1")
    inner.set(b"b", b"2")
    s = StagedDB(inner)
    s.set(b"b", b"2x")
    s.set(b"c", b"3")
    s.delete(b"a")
    # read-your-writes through the overlay; inner untouched
    assert s.get(b"b") == b"2x" and s.get(b"c") == b"3"
    assert s.get(b"a") is None
    assert inner.get(b"b") == b"2" and inner.get(b"c") is None
    assert list(s.iterate(b"")) == [(b"b", b"2x"), (b"c", b"3")]
    s.flush_into_inner()
    assert inner.get(b"a") is None
    assert inner.get(b"b") == b"2x" and inner.get(b"c") == b"3"
    assert s.staged == {}


def test_group_commit_flush_order_and_after_flush():
    from tendermint_tpu.pipeline import GroupCommit
    from tendermint_tpu.storage.db import MemDB
    db_a, db_b = MemDB(), MemDB()
    order = []

    class Spy(MemDB):
        def __init__(self, name):
            super().__init__()
            self.name = name

        def set_batch(self, pairs):
            order.append(self.name)
            super().set_batch(pairs)

    a, b = Spy("block"), Spy("state")
    g = GroupCommit()
    g.staged(a).set(b"k", b"v")       # registration order = flush order
    g.staged(b).set(b"k", b"v")
    assert g.staged(a) is g.staged(a)  # one overlay per db
    fired = []
    g.after_flush(lambda: fired.append(order[:]))
    g.flush()
    assert order == ["block", "state"]
    assert fired == [["block", "state"]]  # events strictly after writes


def test_precompute_used_on_stable_mempool(monkeypatch):
    """With the pipeline on and a mempool that does not change between
    finalize and propose, the precomputed next proposal is used — and
    its block is byte-identical to what the serial build would have
    produced (the wire-parity test above pins that globally; here we
    pin the precompute handoff specifically)."""
    import time as _t

    from tendermint_tpu import telemetry
    monkeypatch.setenv("TM_TPU_PIPELINE", "on")
    used_before = telemetry.value("pipeline_precompute_total",
                                  {"outcome": "used"}) or 0
    nodes, _keys = make_net(1, chain_id="pipe-pre")
    cs = nodes[0]
    mp = ListMempool()
    mp.txs = [b"pre/k%d=v" % i for i in range(20)]
    cs.mempool = mp
    cs.start()
    for _ in range(200):
        if cs.state.last_block_height >= 2:
            break
        cs.ticker.fire_next()
    assert cs.state.last_block_height >= 2
    # wait for the height-3 precompute worker to land its handoff, THEN
    # let the propose step run — deterministic, no tick/worker race
    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline:
        with cs._pre_lock:
            pre = cs._precomputed
            if pre is not None and pre["height"] == 3:
                break
        _t.sleep(0.005)
    with cs._pre_lock:
        pre = cs._precomputed
        assert pre is not None and pre["height"] == 3, \
            "height-3 precompute never landed"
    for _ in range(200):
        if cs.state.last_block_height >= 3:
            break
        cs.ticker.fire_next()
    assert cs.state.last_block_height >= 3
    cs.stop()
    used = telemetry.value("pipeline_precompute_total",
                           {"outcome": "used"}) or 0
    assert used > used_before


# --------------------------------------------------- batched tx ingest


def test_mempool_check_tx_batch_matches_scalar_admission():
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.config import MempoolConfig
    from tendermint_tpu.mempool import Mempool
    mp = Mempool(AppConns(local_client_creator(KVStoreApp())).mempool,
                 config=MempoolConfig(wal_dir="", size=5), height=0)
    txs = [b"bk%d=v" % i for i in range(4)]
    res = mp.check_tx_batch(txs + [txs[0], b"", b"bk9=v", b"bk10=v"])
    codes = [r.code for r in res]
    # 4 admitted, dup rejected, empty rejected by the app, one more
    # admitted (hits size 5), last rejected full
    assert codes[:4] == [0, 0, 0, 0]
    assert codes[4] != 0 and "cache" in res[4].log
    assert codes[5] != 0            # app-invalid (empty tx)
    assert codes[6] == 0
    assert codes[7] != 0 and "full" in res[7].log
    assert mp.size() == 5
    assert mp.reap(-1) == txs + [b"bk9=v"]


def test_rpc_broadcast_tx_batch_route():
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.config import MempoolConfig
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.rpc.core import RPCCore, RPCEnv
    mp = Mempool(AppConns(local_client_creator(KVStoreApp())).mempool,
                 config=MempoolConfig(wal_dir=""), height=0)
    core = RPCCore(RPCEnv(mempool=mp))
    out = core.broadcast_tx_batch(
        [b"rt%d=v".replace(b"%d", b"%d" % i).hex() for i in range(3)]
        + [b"rt0=v".hex()])
    codes = [r["code"] for r in out["results"]]
    assert codes == [0, 0, 0, 1]
    assert mp.size() == 3
    assert "broadcast_tx_batch" in core.routes()


# ----------------------------------------------- scalar-crypto fast path


def test_fast_sign_matches_reference_oracle():
    from tendermint_tpu.types.keys import PrivKey
    from tendermint_tpu.utils import ed25519_ref as ref
    for i in range(6):
        seed = bytes([i + 3]) * 32
        msg = _data(17 * i + 1)
        k = PrivKey(seed)
        assert k.sign(msg) == ref.sign(seed, msg)


def test_verify_any_table_upgrade_matches_reference():
    from tendermint_tpu.types.keys import PrivKey, verify_any
    from tendermint_tpu.utils import ed25519_fast as fast
    k = PrivKey(b"\x42" * 32)
    pub = k.pubkey.ed25519
    msg, sig = b"commit-path vote", k.sign(b"commit-path vote")
    fast.cache_clear()
    assert not fast.has_table(pub)
    assert verify_any(pub, msg, sig)           # cold: reference ladder
    fast._negA_table(pub)                      # resident: table path
    assert fast.has_table(pub)
    assert verify_any(pub, msg, sig)
    bad = sig[:-1] + bytes([sig[-1] ^ 1])
    assert not verify_any(pub, msg, bad)
    garbage = b"\xff" * 32
    fast._negA_table(garbage)                  # cached-invalid key
    assert not verify_any(garbage, msg, sig)
