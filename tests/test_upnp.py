"""UPnP against a loopback fake IGD (p2p/upnp parity without a network):
a UDP SSDP responder + an HTTP server answering the device-description
and SOAP control requests the way a router's IGD stack does."""

import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from tendermint_tpu.p2p import upnp

DESC_XML = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device>
  <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
  <deviceList><device>
   <serviceList><service>
    <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
    <controlURL>/ctl</controlURL>
   </service></serviceList>
  </device></deviceList>
 </device>
</root>"""


class FakeIGD:
    """Loopback SSDP + HTTP IGD. Records port mappings."""

    def __init__(self):
        self.mappings = {}
        # HTTP part (description + SOAP control)
        igd = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, body: bytes, code=200):
                self.send_response(code)
                self.send_header("Content-Type", "text/xml")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(DESC_XML.encode())

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                action = self.headers.get("SOAPAction", "").split("#")[-1]
                action = action.strip('"')
                if action == "GetExternalIPAddress":
                    self._reply(_soap_resp(action, {
                        "NewExternalIPAddress": "203.0.113.7"}))
                elif action == "AddPortMapping":
                    port = _extract(body, "NewExternalPort")
                    igd.mappings[port] = _extract(body, "NewInternalClient")
                    self._reply(_soap_resp(action, {}))
                elif action == "DeletePortMapping":
                    igd.mappings.pop(_extract(body, "NewExternalPort"), None)
                    self._reply(_soap_resp(action, {}))
                else:
                    self._reply(b"unknown action", 500)

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.http_port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        # SSDP part: plain loopback UDP (no multicast in the sandbox)
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind(("127.0.0.1", 0))
        self.ssdp_addr = self.udp.getsockname()
        threading.Thread(target=self._ssdp_loop, daemon=True).start()

    def _ssdp_loop(self):
        while True:
            try:
                data, addr = self.udp.recvfrom(2048)
            except OSError:
                return
            if b"M-SEARCH" in data:
                resp = ("HTTP/1.1 200 OK\r\n"
                        f"LOCATION: http://127.0.0.1:{self.http_port}/desc.xml\r\n"
                        f"ST: {upnp.ST_IGD}\r\n\r\n").encode()
                self.udp.sendto(resp, addr)

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.udp.close()


def _soap_resp(action: str, fields: dict) -> bytes:
    inner = "".join(f"<{k}>{v}</{k}>" for k, v in fields.items())
    return (f'<?xml version="1.0"?><s:Envelope xmlns:s='
            f'"http://schemas.xmlsoap.org/soap/envelope/"><s:Body>'
            f'<u:{action}Response xmlns:u="svc">{inner}'
            f"</u:{action}Response></s:Body></s:Envelope>").encode()


def _extract(body: str, tag: str) -> str:
    return body.split(f"<{tag}>")[1].split(f"</{tag}>")[0]


def test_discover_and_port_mapping_roundtrip():
    igd_srv = FakeIGD()
    try:
        igd = upnp.discover(timeout=2.0, ssdp_addr=igd_srv.ssdp_addr)
        assert igd.service_type.endswith("WANIPConnection:1")
        assert igd.external_ip() == "203.0.113.7"
        igd.add_port_mapping(46656, 46656)
        assert "46656" in igd_srv.mappings
        igd.delete_port_mapping(46656)
        assert "46656" not in igd_srv.mappings
    finally:
        igd_srv.close()


def test_probe_reports_capabilities():
    igd_srv = FakeIGD()
    try:
        report = upnp.probe(timeout=2.0, ssdp_addr=igd_srv.ssdp_addr)
        assert report["external_ip"] == "203.0.113.7"
        assert report["port_mapping"] is True
        assert not igd_srv.mappings  # probe cleans up its test mapping
    finally:
        igd_srv.close()


def test_no_igd_raises():
    import pytest
    # a bound-but-silent UDP port: discovery must time out cleanly
    silent = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    silent.bind(("127.0.0.1", 0))
    try:
        with pytest.raises(upnp.UPnPError):
            upnp.discover(timeout=0.3, ssdp_addr=silent.getsockname())
    finally:
        silent.close()
