"""Authenticated state tree (ISSUE 16): structure determinism,
incremental commits vs full rebuilds, copy-on-write version retention,
inclusion/absence proofs + the forged-proof matrix, wire codec
validation, the KVStore tree backend (A/B app-hash divergence pinned),
snapshot streaming, and the crash-at-every-statetree-fail-point
recovery sweep (pattern from tests/test_snapshot.py)."""

import hashlib
import os
import random

import pytest

from tendermint_tpu import statetree
from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import MockTicker
from tendermint_tpu.node import Node
from tendermint_tpu.ops import merkle
from tendermint_tpu.statetree import ProofError, StateTree
from tendermint_tpu.statetree.tree import _bit, _first_diff_bit
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import PrivValidatorFile
from tendermint_tpu.utils import fail


def _fill(tree, pairs):
    for k, v in pairs:
        tree.set(k, v)


def _pairs(n, tag=b"v"):
    return [(b"key/%d" % i, tag + b"%d" % i) for i in range(n)]


# ------------------------------------------------------------ structure --

def test_root_is_insertion_order_independent():
    pairs = _pairs(400)
    t1 = StateTree()
    _fill(t1, pairs)
    shuffled = pairs[:]
    random.Random(13).shuffle(shuffled)
    t2 = StateTree()
    _fill(t2, shuffled)
    assert t1.commit(1) == t2.commit(1)


def test_incremental_equals_rebuild_under_churn():
    """Random set/update/delete churn across several commits lands on
    exactly the root a fresh tree over the surviving state computes —
    the incremental dirty-subtree rehash hides nothing."""
    rng = random.Random(29)
    tree = StateTree()
    model = {}
    for version in range(1, 6):
        for _ in range(300):
            op = rng.random()
            k = b"churn/%d" % rng.randrange(500)
            if op < 0.6 or k not in model:
                v = b"val-%d" % rng.randrange(10 ** 6)
                tree.set(k, v)
                model[k] = v
            else:
                assert tree.delete(k)
                del model[k]
        root = tree.commit(version)
        rebuilt = StateTree()
        _fill(rebuilt, sorted(model.items()))
        assert rebuilt.commit(1) == root
        assert len(tree) == len(model)
    assert dict(tree.items_at(5)) == model


def test_bit_helpers():
    kh = bytes([0b10110000] + [0] * 31)
    assert [_bit(kh, i) for i in range(4)] == [1, 0, 1, 1]
    other = bytes([0b10100000] + [0] * 31)
    assert _first_diff_bit(kh, other) == 3
    with pytest.raises(ValueError):
        _first_diff_bit(kh, kh)


def test_copy_on_write_versions_stay_provable():
    tree = StateTree(retain=3)
    _fill(tree, _pairs(50))
    r1 = tree.commit(1)
    tree.set(b"key/7", b"seven")
    tree.delete(b"key/9")
    r2 = tree.commit(2)
    # version 1 unchanged under the mutation: old value still proves
    v, p = tree.prove(b"key/7", 1)
    assert v == b"v7"
    statetree.verify(p, b"key/7", v, r1)
    v, p = tree.prove(b"key/9", 1)
    statetree.verify(p, b"key/9", v, r1)
    # version 2 sees the new world
    v, p = tree.prove(b"key/7", 2)
    assert v == b"seven"
    statetree.verify(p, b"key/7", v, r2)
    v, p = tree.prove(b"key/9", 2)
    assert v is None and not p.present
    statetree.verify(p, b"key/9", None, r2)
    # retention: the registry keeps the newest `retain` versions
    tree.commit(3)
    tree.commit(4)
    with pytest.raises(KeyError):
        tree.prove(b"key/7", 1)
    assert tree.store.versions() == [2, 3, 4]


def test_empty_and_single_key_trees():
    tree = StateTree()
    r0 = tree.commit(1)
    v, p = tree.prove(b"ghost", 1)
    assert v is None and p.n_keys == 0
    statetree.verify(p, b"ghost", None, r0)
    tree.set(b"only", b"one")
    r1 = tree.commit(2)
    assert r1 != r0
    v, p = tree.prove(b"only", 2)
    assert v == b"one" and p.steps == []
    statetree.verify(p, b"only", v, r1)
    v, p = tree.prove(b"ghost", 2)
    assert not p.present and p.other_key_hash == \
        hashlib.sha256(b"only").digest()
    statetree.verify(p, b"ghost", None, r1)
    # deleting the last key returns to the (size-bound) empty root
    assert tree.delete(b"only")
    assert tree.commit(3) == r0


# --------------------------------------------------------------- proofs --

def test_forged_proofs_raise():
    """The forgery matrix: every tampering of a valid proof must raise
    ProofError — never verify, never return a soft False."""
    tree = StateTree()
    _fill(tree, _pairs(200))
    root = tree.commit(1)
    value, good = tree.prove(b"key/55", 1)
    statetree.verify(good, b"key/55", value, root)

    import copy

    def variant(mutate):
        p = copy.deepcopy(good)
        mutate(p)
        return p

    forgeries = {
        "tampered value": (good, b"evil-value"),
        "truncated path": (variant(
            lambda p: setattr(p, "steps", p.steps[:-1])), value),
        "extended path": (variant(
            lambda p: p.steps.append((255, b"\x11" * 32))), value),
        "sibling swap": (variant(
            lambda p: p.steps.__setitem__(
                0, (p.steps[0][0], b"\x22" * 32))), value),
        "step reorder": (variant(
            lambda p: setattr(p, "steps", list(reversed(p.steps)))),
            value),
        "wrong n_keys (root binding)": (variant(
            lambda p: setattr(p, "n_keys", p.n_keys + 1)), value),
        "absence claim for present key": (variant(
            lambda p: (setattr(p, "present", False),
                       setattr(p, "other_key_hash", b"\x01" * 32),
                       setattr(p, "other_value_hash", b"\x02" * 32))),
            None),
    }
    for name, (proof, val) in forgeries.items():
        with pytest.raises(ProofError):
            statetree.verify(proof, b"key/55", val, root)
            pytest.fail(f"forgery accepted: {name}")
    # wrong key entirely
    with pytest.raises(ProofError):
        statetree.verify(good, b"key/56", value, root)
    # wrong root
    with pytest.raises(ProofError):
        statetree.verify(good, b"key/55", value, b"\x00" * 32)
    # absence proof whose divergent leaf IS the key's own leaf
    _, absent = tree.prove(b"not-there", 1)
    bad = copy.deepcopy(absent)
    bad.other_key_hash = hashlib.sha256(b"not-there").digest()
    with pytest.raises(ProofError):
        statetree.verify(bad, b"not-there", None, root)


def test_codec_round_trip_and_malformed_rejection():
    tree = StateTree()
    _fill(tree, _pairs(30))
    root = tree.commit(1)
    for key in (b"key/3", b"nope"):
        value, proof = tree.prove(key, 1)
        raw = statetree.proof_to_bytes(proof)
        decoded = statetree.proof_from_bytes(raw)
        statetree.verify(decoded, key, value, root)
        assert statetree.proof_to_bytes(decoded) == raw
    for blob in (b"", b"not json", b"[]", b'{"n_keys": -1}',
                 b'{"n_keys": 1, "key_hash": "zz"}',
                 b'{"n_keys": 1, "key_hash": "ab", "steps": 3}',
                 b'{"n_keys": 1, "key_hash": "' + b"ab" * 32 +
                 b'", "steps": [[256, "' + b"ab" * 32 + b'"]]}'):
        with pytest.raises(ProofError):
            statetree.proof_from_bytes(blob)


def test_sha256_many_host_matches_hashlib():
    payloads = [os.urandom(67) for _ in range(600)] + [b"", b"x"]
    want = [hashlib.sha256(p).digest() for p in payloads]
    assert merkle.sha256_many_host(payloads) == want
    assert merkle.sha256_many_host([]) == []


# -------------------------------------------------------- app  backend --

def test_kvstore_tree_backend_proves_and_ab_hashes_diverge(monkeypatch):
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    app = KVStoreApp()
    for i in range(40):
        app.deliver_tx(b"ab/%d=w%d" % (i, i))
    r1 = app.commit()
    app.deliver_tx(b"ab/7=updated")
    r2 = app.commit()
    res = app.query("", b"ab/7", 0, True)
    assert res.value == b"updated" and res.height == 2
    statetree.verify(statetree.proof_from_bytes(res.proof),
                     b"ab/7", res.value, r2)
    # the PREVIOUS version still proves (the header-binding seam)
    res = app.query("", b"ab/7", 1, True)
    assert res.value == b"w7"
    statetree.verify(statetree.proof_from_bytes(res.proof),
                     b"ab/7", res.value, r1)
    # absence, proven
    res = app.query("", b"ab/404", 0, True)
    pf = statetree.proof_from_bytes(res.proof)
    assert not pf.present and res.value == b""
    statetree.verify(pf, b"ab/404", None, r2)
    # unproven query shape is untouched
    res = app.query("", b"ab/7", 0, False)
    assert res.value == b"updated" and res.proof == b""
    # an unretained version is a soft error, not a crash
    for _ in range(12):
        app.commit()
    assert app.query("", b"ab/7", 1, True).code == 1

    # A/B: the bucket backend over the SAME txs hashes differently —
    # expected and pinned, never silently reconciled
    monkeypatch.delenv("TM_TPU_STATE_TREE")
    bucket = KVStoreApp()
    for i in range(40):
        bucket.deliver_tx(b"ab/%d=w%d" % (i, i))
    assert bucket.commit() != r1
    assert bucket.query("", b"ab/7", 0, True).proof == b""


def test_kvstore_tree_snapshot_streams_and_restores(monkeypatch):
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    app = KVStoreApp()
    for i in range(60):
        app.deliver_tx(b"sn/%d=p%d" % (i, i))
    r1 = app.commit()
    items = app.snapshot_items()
    # streamed, not materialized: a generator over tree nodes
    assert not isinstance(items, (list, tuple))
    consumed = []
    it = iter(items)
    for _ in range(10):
        consumed.append(next(it))
    # copy-on-write keeps the in-flight stream consistent across a
    # later commit that mutates half the state
    for i in range(0, 60, 2):
        app.deliver_tx(b"sn/%d=MUT" % i)
    app.commit()
    consumed.extend(it)
    assert dict(consumed) == {b"sn/%d" % i: b"p%d" % i
                              for i in range(60)}
    # restore replays into a fresh tree and must land on r1 exactly
    app2 = KVStoreApp()
    assert app2.restore_items(consumed, 1, None) == r1
    assert app2.height == 1
    v, p = app2._tree.prove(b"sn/5", 1)
    statetree.verify(p, b"sn/5", v, r1)


# ------------------------------------------------- crash-recovery sweep --

class _Crash(BaseException):
    """Simulated process death at a fail point (BaseException: nothing
    between the fail point and the test may swallow it)."""


def _gen(chain_id):
    key = PrivKey.generate(b"\x0e" * 32)
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519,
                                                  10)])
    return gen, key


def _make_node(home, gen, key):
    pv_path = os.path.join(home, "priv_validator.json")
    if os.path.exists(pv_path):
        pv = PrivValidatorFile.load(pv_path)
    else:
        pv = PrivValidatorFile(pv_path, key)
        pv._persist()
    node = Node(make_test_config(home), gen, priv_validator=pv,
                app=KVStoreApp())
    node.consensus.ticker.stop()
    node.consensus.ticker = MockTicker(node.consensus._on_timeout_fire)
    return node


def _inject(node, txs):
    for tx in txs:
        try:
            node.mempool.check_tx(tx)
        except Exception:
            pass


def _commit_to(node, target_height, max_ticks=400):
    for _ in range(max_ticks):
        if node.height >= target_height:
            return
        node.consensus.ticker.fire_next()
    raise AssertionError(f"stuck at height {node.height}")


WAVE_A = [b"st/a%d=v%d" % (i, i) for i in range(1, 4)]
WAVE_B = [b"st/b%d=w%d" % (i, i) for i in range(1, 4)]

STATETREE_POINTS = ("statetree.before_root_flush",
                    "statetree.after_node_write")


def test_crash_at_statetree_points_recovers_control_root(tmp_path,
                                                         monkeypatch):
    """Kill a tree-backed node at each statetree fail point mid-commit;
    WAL catchup + handshake replay must rebuild the SAME tree root as
    an uncrashed control — and the recovered tree must still prove."""
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    target = 3
    gen, key = _gen("st-sweep")

    control = _make_node(str(tmp_path / "control"), gen, key)
    control.start()
    _inject(control, WAVE_A)
    _commit_to(control, 1)
    _inject(control, WAVE_B)
    _commit_to(control, target)
    control_hash = control.consensus.state.app_hash
    control.stop()
    assert control_hash

    for point in STATETREE_POINTS:
        home = str(tmp_path / point.replace(".", "_"))
        node = _make_node(home, gen, key)
        node.start()
        _inject(node, WAVE_A)
        _commit_to(node, 1)

        def crash(name):
            raise _Crash(name)

        fail.arm(point, crash)
        with pytest.raises(_Crash):
            _inject(node, WAVE_B)
            _commit_to(node, target)
        fail.disarm_all()
        crashed_at = node.height
        node.consensus._stopped = True
        try:
            node.stop()
        except Exception:
            pass

        node2 = _make_node(home, gen, key)   # handshake replay here
        node2.start()                        # WAL catchup replay here
        assert node2.height >= crashed_at
        _inject(node2, WAVE_B)
        _commit_to(node2, target)
        assert node2.consensus.state.app_hash == control_hash, (
            f"{point}: recovered tree root diverged")
        # the replayed tree still serves verifiable proofs
        res = node2.app.query("", b"st/a1", 0, True)
        statetree.verify(statetree.proof_from_bytes(res.proof),
                         b"st/a1", res.value, node2.app.app_hash)
        node2.stop()
