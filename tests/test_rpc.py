"""RPC tests: JSON-RPC server framework (POST/URI/WS), core routes over a
live node, clients (models rpc/lib tests + rpc/core behavior)."""

import time

import pytest

from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.node import Node
from tendermint_tpu.rpc import (
    JSONRPCClient,
    RPCClientError,
    RPCError,
    RPCServer,
    URIClient,
    WSClient,
)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


# ------------------------------------------------------------- lib framework

def make_lib_server():
    srv = RPCServer()
    srv.register("add", lambda a: int(a) + 1)
    srv.register("concat", lambda x, y="def": f"{x}{y}")
    srv.register("boom", lambda: 1 / 0)

    def typed(n: int = 0, flag: bool = False, blob: bytes = b""):
        return {"n": n, "flag": flag, "blob": blob.hex()}
    srv.register("typed", typed)
    addr = srv.serve("127.0.0.1", 0)
    return srv, addr


def test_jsonrpc_post_and_uri_roundtrip():
    srv, (host, port) = make_lib_server()
    try:
        http = JSONRPCClient(f"http://{host}:{port}")
        assert http.call("add", a=41) == 42
        assert http.call("concat", x="abc") == "abcdef"
        uri = URIClient(f"http://{host}:{port}")
        assert uri.call("add", a=41) == 42
        # URI string params coerced to annotated types
        assert uri.call("typed", n="7", flag="true", blob="beef") == \
            {"n": 7, "flag": True, "blob": "beef"}
    finally:
        srv.stop()


def test_rpc_errors_surface():
    srv, (host, port) = make_lib_server()
    try:
        http = JSONRPCClient(f"http://{host}:{port}")
        with pytest.raises(RPCClientError) as e:
            http.call("nope")
        assert e.value.code == -32601
        with pytest.raises(RPCClientError) as e:
            http.call("boom")  # handler exception -> structured error
        assert e.value.code == -32603
        with pytest.raises(RPCClientError):
            http.call("add")   # missing param
    finally:
        srv.stop()


def test_websocket_jsonrpc_call():
    srv, (host, port) = make_lib_server()
    try:
        ws = WSClient(host, port)
        assert ws.call("add", a=1) == 2
        assert ws.call("concat", x="a", y="b") == "ab"
        ws.close()
    finally:
        srv.stop()


# ------------------------------------------------------------ node + routes

@pytest.fixture(scope="module")
def rpc_node():
    key = PrivKey.generate(b"\x0a" * 32)
    gen = GenesisDoc(chain_id="rpc-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cfg = make_test_config("")
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.unsafe = True
    node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True, with_rpc=True)
    node.start()
    deadline = time.monotonic() + 30
    while node.height < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert node.height >= 2
    yield node
    node.stop()


def client(node):
    host, port = node.rpc_address
    return JSONRPCClient(f"http://{host}:{port}")


def test_status_and_genesis(rpc_node):
    c = client(rpc_node)
    st = c.call("status")
    assert st["latest_block_height"] >= 2
    assert st["latest_block_hash"]
    g = c.call("genesis")
    assert g["genesis"]["chain_id"] == "rpc-test"


def test_block_blockchain_commit_validators(rpc_node):
    c = client(rpc_node)
    info = c.call("blockchain", min_height=1, max_height=2)
    assert len(info["block_metas"]) == 2
    assert info["block_metas"][0]["header"]["height"] == 2  # newest first
    blk = c.call("block", height=1)
    assert blk["block"]["header"]["height"] == 1
    cm = c.call("commit", height=1)
    assert cm["canonical"] is True
    assert cm["commit"]["precommits"]
    vals = c.call("validators")
    assert len(vals["validators"]["validators"]) == 1
    with pytest.raises(RPCClientError):
        c.call("block", height=10**9)


def test_broadcast_tx_sync_and_commit(rpc_node):
    c = client(rpc_node)
    res = c.call("broadcast_tx_sync", tx=b"rpc-key=rpc-val")
    assert res["code"] == 0
    # the tx lands in a block
    res2 = c.call("broadcast_tx_commit", tx=b"rpc-commit=yes")
    assert res2["deliver_tx"]["code"] == 0
    assert res2["height"] >= 1
    assert rpc_node.app.store.get(b"rpc-commit") == b"yes"


def test_abci_query_and_info(rpc_node):
    c = client(rpc_node)
    c.call("broadcast_tx_commit", tx=b"qk=qv")
    res = c.call("abci_query", path="/store", data=b"qk")
    assert bytes.fromhex(res["response"]["value"]) == b"qv"
    info = c.call("abci_info")
    assert "kvstore" in info["response"]["data"]


def test_unconfirmed_and_unsafe_flush(rpc_node):
    c = client(rpc_node)
    assert "n_txs" in c.call("num_unconfirmed_txs")
    assert c.call("unsafe_flush_mempool") == {}


def test_dump_consensus_state_and_net_info(rpc_node):
    c = client(rpc_node)
    dcs = c.call("dump_consensus_state")
    assert dcs["round_state"]["height"] >= 1
    assert "peer_round_states" in dcs  # {} here: no p2p in this node
    ni = c.call("net_info")
    assert ni["listening"] is False  # no p2p in this node


def test_ws_subscribe_new_block(rpc_node):
    host, port = rpc_node.rpc_address
    ws = WSClient(host, port)
    ws.subscribe("tm.event = 'NewBlock'")
    ev = ws.next_event(timeout=30)
    assert ev["data"]["block"]["header"]["height"] >= 1
    ws.close()


def test_ws_subscribe_tx_event(rpc_node):
    host, port = rpc_node.rpc_address
    ws = WSClient(host, port)
    ws.subscribe("tm.event = 'Tx'")
    c = client(rpc_node)
    c.call("broadcast_tx_sync", tx=b"wsevent=1")
    ev = ws.next_event(timeout=30)
    assert bytes.fromhex(ev["data"]["tx"]) == b"wsevent=1"
    ws.close()


def test_http_connection_flood_bounded():
    """A plain-HTTP connection flood is bounded: over-limit connections
    get an immediate 503 with NO handler thread spawned, in-limit slow
    requests all complete, and the server keeps serving afterwards."""
    import socket as socket_mod
    import threading as threading_mod
    import time as time_mod

    from tendermint_tpu.rpc.server import RPCServer

    gate = threading_mod.Event()

    def slow():
        gate.wait(timeout=10)
        return {"ok": True}

    srv = RPCServer(max_http_conns=6)
    srv.register("slow", slow)
    srv.register("ping", lambda: {"pong": True})
    host, port = srv.serve("127.0.0.1", 0)
    try:
        n_before = threading_mod.active_count()
        # 6 slow requests occupy every slot
        socks = []
        for _ in range(6):
            s = socket_mod.create_connection((host, port), timeout=10)
            s.sendall(b"GET /slow HTTP/1.1\r\nHost: x\r\n\r\n")
            socks.append(s)
        time_mod.sleep(0.3)
        # the flood: 30 more connections -> all must be rejected 503
        rejected = 0
        for _ in range(30):
            s = socket_mod.create_connection((host, port), timeout=10)
            s.sendall(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
            line = s.recv(64)
            if b"503" in line:
                rejected += 1
            s.close()
        assert rejected == 30, f"only {rejected}/30 rejected"
        # thread growth stayed bounded by the cap (6 handlers + slack)
        assert threading_mod.active_count() - n_before <= 8, \
            threading_mod.active_count() - n_before
        # release the slow handlers: everyone completes
        gate.set()
        for s in socks:
            assert b"200" in s.recv(256)
            s.close()
        time_mod.sleep(0.3)
        # slots freed: normal service resumes
        s = socket_mod.create_connection((host, port), timeout=10)
        s.sendall(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        assert b"200" in s.recv(256)
        s.close()
    finally:
        gate.set()
        srv.stop()


def test_ws_client_auto_reconnects_and_resubscribes():
    """The reference's auto-reconnecting WSClient (rpc/lib/client/
    ws_client.go:30-140): kill the server mid-subscription, bring it
    back on the same port — the client redials with backoff,
    re-subscribes, and events resume through the same queue. Call
    latency is tracked."""
    import threading as th
    import time as tm

    from tendermint_tpu.rpc.client import ReconnectingWSClient
    from tendermint_tpu.rpc.server import RPCServer

    def make_server(port):
        srv = RPCServer()

        def subscribe(query="", ws=None):
            def pump():
                i = 0
                while ws.open:
                    try:
                        ws.send_json({"jsonrpc": "2.0", "id": "#event",
                                      "result": {"q": query, "n": i}})
                    except ConnectionError:
                        return
                    i += 1
                    tm.sleep(0.05)
            th.Thread(target=pump, daemon=True).start()
            return {}

        srv.register("subscribe", subscribe, ws_only=True)
        srv.register("ping", lambda: {"pong": True})
        host, p = srv.serve("127.0.0.1", port)
        return srv, p

    srv, port = make_server(0)
    c = ReconnectingWSClient("127.0.0.1", port, max_backoff_s=0.5)
    try:
        c.subscribe("tm.event = 'X'")
        ev = c.next_event(timeout=10)
        assert ev["q"] == "tm.event = 'X'"
        assert c.call("ping")["pong"] is True
        assert c.latency["count"] >= 2 and c.latency["max_s"] > 0

        # kill the server mid-subscription
        srv.stop()
        deadline = tm.monotonic() + 10
        while c._client.open and tm.monotonic() < deadline:
            tm.sleep(0.05)
        assert not c._client.open, "client never noticed the outage"
        # calls during the outage fail fast
        import pytest as _pytest
        from tendermint_tpu.rpc.client import RPCClientError
        with _pytest.raises(RPCClientError):
            c.call("ping")

        # server returns on the SAME port: client must recover alone
        srv2, _ = make_server(port)
        try:
            deadline = tm.monotonic() + 15
            while c.reconnects == 0 and tm.monotonic() < deadline:
                tm.sleep(0.05)
            assert c.reconnects >= 1, "no reconnect within 15s"
            # the re-subscribed stream flows into the SAME queue
            while not c.events.empty():
                c.events.get_nowait()
            ev = c.next_event(timeout=10)
            assert ev["q"] == "tm.event = 'X'"
            assert c.call("ping")["pong"] is True
        finally:
            srv2.stop()
    finally:
        c.close()


def test_block_results_expands_uniform_batches(rpc_node):
    """Blocks applied through the native batch path persist the compact
    deliver_txs_uniform form; block_results must still serve the
    external per-tx deliver_txs shape."""
    c = client(rpc_node)
    res = c.call("broadcast_tx_commit", tx=b"uniform-k=uniform-v")
    h = res["height"]
    br = c.call("block_results", height=h)
    dt = br["results"]["deliver_txs"]
    assert "deliver_txs_uniform" not in br["results"]
    assert any(r["code"] == 0 and
               r.get("tags", {}).get("app.key") == "uniform-k"
               for r in dt)
