"""RPC tests: JSON-RPC server framework (POST/URI/WS), core routes over a
live node, clients (models rpc/lib tests + rpc/core behavior)."""

import time

import pytest

from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.node import Node
from tendermint_tpu.rpc import (
    JSONRPCClient,
    RPCClientError,
    RPCError,
    RPCServer,
    URIClient,
    WSClient,
)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


# ------------------------------------------------------------- lib framework

def make_lib_server():
    srv = RPCServer()
    srv.register("add", lambda a: int(a) + 1)
    srv.register("concat", lambda x, y="def": f"{x}{y}")
    srv.register("boom", lambda: 1 / 0)

    def typed(n: int = 0, flag: bool = False, blob: bytes = b""):
        return {"n": n, "flag": flag, "blob": blob.hex()}
    srv.register("typed", typed)
    addr = srv.serve("127.0.0.1", 0)
    return srv, addr


def test_jsonrpc_post_and_uri_roundtrip():
    srv, (host, port) = make_lib_server()
    try:
        http = JSONRPCClient(f"http://{host}:{port}")
        assert http.call("add", a=41) == 42
        assert http.call("concat", x="abc") == "abcdef"
        uri = URIClient(f"http://{host}:{port}")
        assert uri.call("add", a=41) == 42
        # URI string params coerced to annotated types
        assert uri.call("typed", n="7", flag="true", blob="beef") == \
            {"n": 7, "flag": True, "blob": "beef"}
    finally:
        srv.stop()


def test_rpc_errors_surface():
    srv, (host, port) = make_lib_server()
    try:
        http = JSONRPCClient(f"http://{host}:{port}")
        with pytest.raises(RPCClientError) as e:
            http.call("nope")
        assert e.value.code == -32601
        with pytest.raises(RPCClientError) as e:
            http.call("boom")  # handler exception -> structured error
        assert e.value.code == -32603
        with pytest.raises(RPCClientError):
            http.call("add")   # missing param
    finally:
        srv.stop()


def test_websocket_jsonrpc_call():
    srv, (host, port) = make_lib_server()
    try:
        ws = WSClient(host, port)
        assert ws.call("add", a=1) == 2
        assert ws.call("concat", x="a", y="b") == "ab"
        ws.close()
    finally:
        srv.stop()


# ------------------------------------------------------------ node + routes

@pytest.fixture(scope="module")
def rpc_node():
    key = PrivKey.generate(b"\x0a" * 32)
    gen = GenesisDoc(chain_id="rpc-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cfg = make_test_config("")
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.unsafe = True
    node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True, with_rpc=True)
    node.start()
    deadline = time.monotonic() + 30
    while node.height < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert node.height >= 2
    yield node
    node.stop()


def client(node):
    host, port = node.rpc_address
    return JSONRPCClient(f"http://{host}:{port}")


def test_status_and_genesis(rpc_node):
    c = client(rpc_node)
    st = c.call("status")
    assert st["latest_block_height"] >= 2
    assert st["latest_block_hash"]
    g = c.call("genesis")
    assert g["genesis"]["chain_id"] == "rpc-test"


def test_block_blockchain_commit_validators(rpc_node):
    c = client(rpc_node)
    info = c.call("blockchain", min_height=1, max_height=2)
    assert len(info["block_metas"]) == 2
    assert info["block_metas"][0]["header"]["height"] == 2  # newest first
    blk = c.call("block", height=1)
    assert blk["block"]["header"]["height"] == 1
    cm = c.call("commit", height=1)
    assert cm["canonical"] is True
    assert cm["commit"]["precommits"]
    vals = c.call("validators")
    assert len(vals["validators"]["validators"]) == 1
    with pytest.raises(RPCClientError):
        c.call("block", height=10**9)


def test_broadcast_tx_sync_and_commit(rpc_node):
    c = client(rpc_node)
    res = c.call("broadcast_tx_sync", tx=b"rpc-key=rpc-val")
    assert res["code"] == 0
    # the tx lands in a block
    res2 = c.call("broadcast_tx_commit", tx=b"rpc-commit=yes")
    assert res2["deliver_tx"]["code"] == 0
    assert res2["height"] >= 1
    assert rpc_node.app.store.get(b"rpc-commit") == b"yes"


def test_abci_query_and_info(rpc_node):
    c = client(rpc_node)
    c.call("broadcast_tx_commit", tx=b"qk=qv")
    res = c.call("abci_query", path="/store", data=b"qk")
    assert bytes.fromhex(res["response"]["value"]) == b"qv"
    info = c.call("abci_info")
    assert "kvstore" in info["response"]["data"]


def test_unconfirmed_and_unsafe_flush(rpc_node):
    c = client(rpc_node)
    assert "n_txs" in c.call("num_unconfirmed_txs")
    assert c.call("unsafe_flush_mempool") == {}


def test_dump_consensus_state_and_net_info(rpc_node):
    c = client(rpc_node)
    dcs = c.call("dump_consensus_state")
    assert dcs["round_state"]["height"] >= 1
    assert "peer_round_states" in dcs  # {} here: no p2p in this node
    ni = c.call("net_info")
    assert ni["listening"] is False  # no p2p in this node


def test_ws_subscribe_new_block(rpc_node):
    host, port = rpc_node.rpc_address
    ws = WSClient(host, port)
    ws.subscribe("tm.event = 'NewBlock'")
    ev = ws.next_event(timeout=30)
    assert ev["data"]["block"]["header"]["height"] >= 1
    ws.close()


def test_ws_subscribe_tx_event(rpc_node):
    host, port = rpc_node.rpc_address
    ws = WSClient(host, port)
    ws.subscribe("tm.event = 'Tx'")
    c = client(rpc_node)
    c.call("broadcast_tx_sync", tx=b"wsevent=1")
    ev = ws.next_event(timeout=30)
    assert bytes.fromhex(ev["data"]["tx"]) == b"wsevent=1"
    ws.close()
