"""p2p burst frame plane (ISSUE 3): native AEAD kernel parity with the
RFC 8439 vectors and the cryptography/purecrypto per-frame paths,
burst-vs-per-frame wire byte-stream equality, burst/non-burst interop,
and the recv-side locking regression."""

import socket
import struct
import threading

import pytest

from tendermint_tpu import native, telemetry
from tendermint_tpu.p2p.conn import purecrypto as pc
from tendermint_tpu.p2p.conn.mconn import (
    ChannelDescriptor,
    MConnection,
    PlainFramedConn,
)
from tendermint_tpu.p2p.conn.secret import DATA_MAX_SIZE, SecretConnection, _Cipher
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.types.keys import PrivKey

pytestmark = pytest.mark.filterwarnings("ignore::pytest.PytestUnraisableExceptionWarning")

_KEY1 = bytes(range(32))
_KEY2 = bytes(range(32, 64))

# RFC 8439 §2.8.2 AEAD vector
_RFC_KEY = bytes(range(0x80, 0xA0))
_RFC_NONCE = bytes.fromhex("070000004041424344454647")
_RFC_AAD = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
_RFC_PT = (b"Ladies and Gentlemen of the class of '99: If I could "
           b"offer you only one tip for the future, sunscreen would "
           b"be it.")
_RFC_CT_HEAD = bytes.fromhex("d31a8d34648e60db7b86afbc53ef7ec2")
_RFC_TAG = bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")


def _backends():
    """Every AEAD implementation present in this container, as
    (name, encrypt(nonce, pt) -> ct||tag) over the RFC key."""
    out = [("purecrypto",
            lambda nonce, pt, aad: pc.ChaCha20Poly1305(
                _RFC_KEY).encrypt(nonce, pt, aad))]
    if native.aead_available():
        out.append(("native",
                    lambda nonce, pt, aad: native.aead_seal_one(
                        _RFC_KEY, nonce, aad, pt)))
    try:
        from cryptography.hazmat.primitives.ciphers.aead import (
            ChaCha20Poly1305 as _OsslAead,
        )
        out.append(("cryptography",
                    lambda nonce, pt, aad: _OsslAead(_RFC_KEY).encrypt(
                        nonce, pt, aad)))
    except ImportError:
        pass
    return out


def test_rfc8439_vector_parity_across_backends():
    """Every available backend (native burst kernels included) must
    reproduce the §2.8.2 vector bit-for-bit — the cross-implementation
    contract that lets burst and per-frame nodes interoperate."""
    for name, seal in _backends():
        ct = seal(_RFC_NONCE, _RFC_PT, _RFC_AAD)
        assert ct[:16] == _RFC_CT_HEAD, name
        assert ct[-16:] == _RFC_TAG, name
        assert len(ct) == len(_RFC_PT) + 16, name


@pytest.mark.skipif(not native.aead_available(),
                    reason="native AEAD kernels unavailable")
def test_native_burst_seal_open_matches_per_frame():
    """aead_seal_burst must emit the exact wire bytes of sealing each
    frame separately (same counter nonces), and aead_open_burst must
    invert it and reject tampering at the right frame."""
    chunks = [b"", b"x", b"hello world", b"a" * DATA_MAX_SIZE]
    nonce0 = 7
    wire = native.aead_seal_burst(_KEY1, nonce0, chunks)
    box = pc.ChaCha20Poly1305(_KEY1)
    expect = b""
    for i, chunk in enumerate(chunks):
        sealed = box.encrypt((nonce0 + i).to_bytes(12, "little"),
                             struct.pack(">H", len(chunk)) + chunk, b"")
        expect += struct.pack(">I", len(sealed)) + sealed
    assert wire == expect

    frames, pos = [], 0
    while pos < len(wire):
        clen = int.from_bytes(wire[pos:pos + 4], "big")
        frames.append(wire[pos + 4:pos + 4 + clen])
        pos += 4 + clen
    plains = native.aead_open_burst(_KEY1, nonce0, frames)
    assert [p[2:2 + int.from_bytes(p[:2], "big")] for p in plains] == chunks

    bad = bytearray(frames[2])
    bad[5] ^= 0x40
    with pytest.raises(native.AeadTagError):
        native.aead_open_burst(_KEY1, nonce0,
                               frames[:2] + [bytes(bad)] + frames[3:])


class _SpyConn:
    """Socket stand-in that records every sendall (wire capture)."""

    def __init__(self):
        self.wire = []

    def sendall(self, data):
        self.wire.append(bytes(data))


def _direct_pair(monkeypatch, mode_a="on", mode_b="on"):
    """Two SecretConnections over a real socketpair with FIXED session
    keys (no handshake), so wire bytes are comparable across modes."""
    s1, s2 = socket.socketpair()
    monkeypatch.setenv("TM_TPU_P2P_BURST", mode_a)
    a = SecretConnection(s1, _Cipher(_KEY1), _Cipher(_KEY2))
    monkeypatch.setenv("TM_TPU_P2P_BURST", mode_b)
    b = SecretConnection(s2, _Cipher(_KEY2), _Cipher(_KEY1))
    monkeypatch.delenv("TM_TPU_P2P_BURST")
    return a, b


def test_burst_wire_bytes_identical_to_per_frame(monkeypatch):
    """The whole point of the burst plane: same nonces, same ciphertext
    byte stream — only the call/syscall count changes. A burst-off
    connection's wire output is the parity reference for pre-PR
    behavior."""
    payloads = [b"tiny", b"q" * (3 * DATA_MAX_SIZE + 17), b""]
    wires = {}
    for mode in ("off", "on"):
        monkeypatch.setenv("TM_TPU_P2P_BURST", mode)
        spy = _SpyConn()
        conn = SecretConnection(spy, _Cipher(_KEY1), _Cipher(_KEY2))
        for p in payloads:
            conn.write(p)
        conn.write_many([b"pkt-1", b"pkt-2", b"pkt-3"])
        wires[mode] = b"".join(spy.wire)
    assert wires["on"] == wires["off"]
    # and the python-seal fallback (no native) is the same bytes too
    monkeypatch.setattr(native, "aead_seal_burst", lambda *a: None)
    monkeypatch.setenv("TM_TPU_P2P_BURST", "on")
    spy = _SpyConn()
    conn = SecretConnection(spy, _Cipher(_KEY1), _Cipher(_KEY2))
    for p in payloads:
        conn.write(p)
    conn.write_many([b"pkt-1", b"pkt-2", b"pkt-3"])
    assert b"".join(spy.wire) == wires["off"]


@pytest.mark.parametrize("sender_mode,reader_mode", [
    ("on", "off"), ("off", "on"), ("on", "on")])
def test_burst_interop_mixed_modes(monkeypatch, sender_mode, reader_mode):
    """Burst sender <-> per-frame reader and vice versa: burst is a
    batching decision, not a wire format, so mixed deployments must
    exchange frames losslessly in both directions."""
    a, b = _direct_pair(monkeypatch, sender_mode, reader_mode)
    small = [b"m%d" % i for i in range(20)]
    big = b"big" * 700  # 2100B -> 3 frames
    for m in small:
        a.write(m)
    a.write(big)
    # 20 one-frame messages + 3 fragments of the big one = 23 frames
    frames = []
    while len(frames) < 23:
        batch = b.read_burst()
        assert batch, "EOF before all frames arrived"
        frames.extend(batch)
    assert frames[:20] == small
    assert b"".join(frames[20:]) == big
    # reverse direction (reader becomes sender)
    for m in small[:5]:
        b.write(m)
    assert [a.read() for _ in range(5)] == small[:5]
    a.close()
    b.close()


def test_write_many_rejects_oversized_chunk(monkeypatch):
    a, _ = _direct_pair(monkeypatch)
    with pytest.raises(ValueError):
        a.write_many([b"x" * (DATA_MAX_SIZE + 1)])
    a.close()


def test_concurrent_readers_do_not_poison_stream(monkeypatch):
    """Regression (ISSUE 3 satellite): read() had no recv-side lock, so
    two readers could interleave counter nonces and kill the connection
    with spurious InvalidTags. With _rlock, N readers drain one stream
    losslessly."""
    a, b = _direct_pair(monkeypatch)
    n = 200
    msgs = [b"msg-%03d" % i for i in range(n)]
    got, errs = [], []
    lock = threading.Lock()

    def reader():
        try:
            while True:
                m = b.read()
                if m == b"":
                    return
                with lock:
                    got.append(m)
                    if len(got) == n:
                        return
        except (OSError, ConnectionError):
            return  # the close() race after the last message
        except Exception as e:  # InvalidTag etc: the regression
            errs.append(e)

    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers:
        t.start()
    for m in msgs:
        a.write(m)
    for t in readers:
        t.join(10)
    assert not errs
    assert sorted(got) == msgs
    a.close()
    b.close()


def _mconn_pair(on_recv_a, on_recv_b, descs=None):
    s1, s2 = socket.socketpair()
    descs = descs or [ChannelDescriptor(id=0x01, priority=1),
                      ChannelDescriptor(id=0x20, priority=10)]
    m1 = MConnection(PlainFramedConn(s1), descs, on_recv_a)
    m2 = MConnection(PlainFramedConn(s2), descs, on_recv_b)
    return m1, m2


def test_mconn_burst_end_to_end(monkeypatch):
    """MConnection over a bursty link: many messages across two
    channels all arrive intact, and the frames-per-burst telemetry
    moves when bursts actually form."""
    monkeypatch.setenv("TM_TPU_P2P_BURST", "on")
    got = []
    done = threading.Event()
    n = 60

    def on_recv(ch, msg):
        got.append((ch, msg))
        if len(got) == n:
            done.set()

    m1, m2 = _mconn_pair(lambda ch, m: None, on_recv)
    before = telemetry.value("p2p_frames_per_burst",
                             {"direction": "send"})
    before_n = before["count"] if before else 0
    m1.start()
    m2.start()
    try:
        for i in range(n):
            ch = 0x01 if i % 2 else 0x20
            assert m1.send(ch, b"payload-%04d" % i)
        assert done.wait(10), f"only {len(got)}/{n} messages arrived"
        sent = {(0x01 if i % 2 else 0x20, b"payload-%04d" % i)
                for i in range(n)}
        assert set(got) == sent
    finally:
        m1.stop(join=True)
        m2.stop(join=True)
    after = telemetry.value("p2p_frames_per_burst",
                            {"direction": "send"})
    if telemetry.enabled():
        assert after and after["count"] >= before_n


def test_mconn_burst_off_matches_legacy_behavior(monkeypatch):
    """Escape hatch: TM_TPU_P2P_BURST=off must leave the per-frame
    routines in place (no write_many/read_burst use at all)."""
    monkeypatch.setenv("TM_TPU_P2P_BURST", "off")
    got = []
    done = threading.Event()

    def on_recv(ch, msg):
        got.append(msg)
        if len(got) == 10:
            done.set()

    m1, m2 = _mconn_pair(lambda ch, m: None, on_recv)
    assert not m1._burst_write and not m1._burst_read
    m1.start()
    m2.start()
    try:
        for i in range(10):
            assert m1.send(0x01, b"legacy-%d" % i)
        assert done.wait(10)
        assert sorted(got) == [b"legacy-%d" % i for i in range(10)]
    finally:
        m1.stop(join=True)
        m2.stop(join=True)


def test_secret_connection_burst_over_handshake():
    """Full product path: handshaked SecretConnections exchanging
    bursts (whatever backend this container has)."""
    s1, s2 = socket.socketpair()
    nk1 = NodeKey(PrivKey.generate(b"\x11" * 32))
    nk2 = NodeKey(PrivKey.generate(b"\x22" * 32))
    out = {}
    t1 = threading.Thread(
        target=lambda: out.__setitem__("a", SecretConnection.make(s1, nk1)))
    t2 = threading.Thread(
        target=lambda: out.__setitem__("b", SecretConnection.make(s2, nk2)))
    t1.start(); t2.start(); t1.join(10); t2.join(10)
    a, b = out["a"], out["b"]
    chunks = [b"c%d" % i for i in range(32)]
    a.write_many(chunks)
    got = []
    while len(got) < len(chunks):
        frames = b.read_burst()
        assert frames
        got.extend(frames)
    assert got == chunks
    a.close()
    b.close()
