"""Differential tests for the fused pallas Ed25519 kernel
(ops/ladder_pallas.py) via the pallas interpreter — validates the
transposed field/point/byte helpers and the full verify pipeline against
the pure-Python RFC 8032 reference on CPU.

Interpreter economics (VERDICT r5 item 7): the fused kernel costs ~100s
of XLA:CPU compile (fixed — the window loop is rolled) plus runtime
proportional to the ladder's fori_loop trip count. The verify/sign
pipeline tests therefore run the SAME kernel code path with
`n_windows=8` and CRAFTED small scalars (s, h < 2^32, top digits zero),
cutting interpreter runtime ~3x with no loss of differential power: the
truncated ladder executes the identical per-window body (table build,
digit select, 4 doublings, both adds, invert/encode tail) eight times
instead of sixty-four, and full-64-window coverage of real RFC 8032
signatures is pinned on every run by the jnp-kernel tests
(test_ed25519) and on hardware by the bench."""

import numpy as np
import jax.numpy as jnp

from tendermint_tpu.ops import ed25519, ladder_pallas
from tendermint_tpu.utils import ed25519_ref as ref

N_WINDOWS = 8            # 32-bit crafted scalars
SCALAR_BOUND = 1 << (4 * N_WINDOWS)


def make_small_scalar_batch(n):
    """Crafted verification instances with s, h < 2^(4*N_WINDOWS):
    random A = a*B, random small s and h, R = s*B - h*A — satisfying
    the kernel's group equation enc(s*B + h*(-A)) == R by construction.
    The kernel's contract is exactly that equation over its (pk, R,
    s-digits, h-digits) inputs (the SHA-512 that derives h in real
    verification lives in host prep, covered by prepare_batch tests)."""
    rng = np.random.RandomState(42)
    pks, rbs, ss, hs = [], [], [], []
    for i in range(n):
        a = int.from_bytes(rng.bytes(32), "little") % ref.L
        s = int.from_bytes(rng.bytes(4), "little") % SCALAR_BOUND
        h = int.from_bytes(rng.bytes(4), "little") % SCALAR_BOUND
        A = ref.point_mul(a, ref.BASE)
        # R = s*B - h*A  =  s*B + (L-h)*A
        R = ref.point_add(ref.point_mul(s, ref.BASE),
                          ref.point_mul((ref.L - h) % ref.L, A))
        pks.append(ref.point_compress(A))
        rbs.append(ref.point_compress(R))
        ss.append(s.to_bytes(32, "little"))
        hs.append(h.to_bytes(32, "little"))
    to_u8 = lambda bs: np.stack([np.frombuffer(b, np.uint8) for b in bs])
    return to_u8(pks), to_u8(rbs), to_u8(ss), to_u8(hs)


def run_pallas(pk, rb, sbits, hbits, tile=8, n_windows=64):
    # jit around the interpret call: eager interpret executes the
    # kernel primitive-by-primitive (~3x the wall time of one compiled
    # pass on this host — 209s vs ~70s measured); under jit the whole
    # interpreted kernel compiles once and runs fused
    import jax
    import functools
    fn = jax.jit(functools.partial(ladder_pallas.verify_pallas,
                                   tile=tile, interpret=True,
                                   n_windows=n_windows))
    return np.asarray(fn(jnp.asarray(pk), jnp.asarray(rb),
                         jnp.asarray(sbits), jnp.asarray(hbits)))


def test_pallas_verify_pipeline_one_pass():
    """One mixed batch of 8 through the interpreted fused kernel at
    n_windows=8 (crafted 32-bit scalars):

    lane 0: valid                      lane 4: valid
    lane 1: corrupted signature R      lane 5: corrupted h scalar
    lane 2: valid                      lane 6: random-bit-flip R
    lane 3: non-point pubkey (0xFF..)  lane 7: random-bit-flip pubkey

    Asserts the expected verdict per lane AND verdict-identity with the
    jnp kernel over the identical inputs (the two implementations must
    agree on every lane, valid or not; the jnp kernel runs its full
    64-window ladder — the crafted scalars' top digits are zero, so the
    results must coincide)."""
    pk, rb, sb, hb = make_small_scalar_batch(8)

    rng = np.random.RandomState(11)
    pk2 = np.array(pk)
    rb2 = np.array(rb)
    hb2 = np.array(hb)
    rb2[1, 0] ^= 0x01                                # targeted R corrupt
    pk2[3] = 0xFF                                    # non-point pubkey
    hb2[5, 0] ^= 1                                   # scalar corrupt
    rb2[6, rng.randint(32)] ^= 1 << rng.randint(8)   # random R flip
    pk2[7, rng.randint(32)] ^= 1 << rng.randint(8)   # random pk flip

    sbits = np.asarray(ed25519._bits_le(sb))
    hbits2 = np.asarray(ed25519._bits_le(hb2))
    got = run_pallas(pk2, rb2, sbits, hbits2, n_windows=N_WINDOWS)
    expect = np.array([1, 0, 1, 0, 1, 0, 0, 0], np.bool_)
    # lane 7's random pubkey flip may still decompress (~50%); it must
    # then fail the group equation instead. Either way: invalid.
    assert (got == expect).all(), got

    # verdict-identity with the full 64-window jnp kernel on the SAME
    # inputs (top digits zero -> identical mathematical statement)
    want = np.asarray(ed25519._verify_from_bytes_jnp(
        jnp.asarray(pk2), jnp.asarray(rb2), jnp.asarray(sb),
        jnp.asarray(hb2)))
    assert (got == want).all(), (got, want)


def test_transposed_byte_roundtrip():
    """_from_bytes_t / _to_bytes_t agree with fe.from_bytes/to_bytes."""
    import jax
    from tendermint_tpu.ops import field as fe
    rng = np.random.RandomState(3)
    vals = [int.from_bytes(rng.bytes(32), "little") % fe.P
            for _ in range(6)]
    b = np.stack([np.frombuffer(v.to_bytes(32, "little"), np.uint8)
                  for v in vals]).astype(np.int32)
    limbs, high = jax.jit(ladder_pallas._from_bytes_t)(jnp.asarray(b.T))
    back = jax.jit(ladder_pallas._to_bytes_t)(limbs)
    assert (np.asarray(back).T == b).all()
    assert (np.asarray(high) == 0).all()  # values < p have bit 255 clear


def test_sign_kernel_interpret_matches_reference():
    """The sign kernel's enc(r*B) at n_windows=8 against the pure
    reference for crafted small nonces, AND the full native
    phase1/phase2 pipeline against a scalar RFC 8032 signer (OpenSSL
    when installed, the pure oracle otherwise — identical bytes either
    way) with the device step stubbed by the reference ladder —
    together they pin everything the old monolithic 64-window interpret
    run did, at ~1/6 the runtime: kernel math (truncated, same body) +
    host nonce/finalize bytes."""
    # (a) kernel: small-r enc(r*B) differential
    rng = np.random.RandomState(5)
    rs = [int.from_bytes(rng.bytes(4), "little") % SCALAR_BOUND
          for _ in range(8)]
    r_bytes = np.stack([np.frombuffer(r.to_bytes(32, "little"), np.uint8)
                        for r in rs])
    import jax
    import functools
    sign_fn = jax.jit(functools.partial(
        ladder_pallas.sign_pallas_rB, tile=8, interpret=True,
        n_windows=N_WINDOWS))
    out = np.asarray(sign_fn(jnp.asarray(r_bytes)))
    for i, r in enumerate(rs):
        want = ref.point_compress(ref.point_mul(r, ref.BASE))
        assert out[i].tobytes() == want, i

    # (b) pipeline: native phase1 nonce + phase2 finalize around a
    # reference-computed R, byte-identical to a conforming scalar
    # signer end to end. Ed25519 signing is deterministic, so OpenSSL
    # (when installed) and the pure RFC 8032 oracle produce the SAME
    # bytes — the cross-check degrades gracefully on no-OpenSSL images
    # instead of killing the whole kernel differential (cryptography
    # has been optional tree-wide since PR 1).
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import \
            Ed25519PrivateKey

        def _scalar_sign(seed, m):
            return Ed25519PrivateKey.from_private_bytes(seed).sign(m)
    except ImportError:
        _scalar_sign = ref.sign

    seeds = [bytes([i + 1] * 32) for i in range(8)]
    msgs = [b"sign-batch-%d" % i * (i + 1) for i in range(8)]
    orig_pallas = ed25519._pallas_available
    orig_dev = ed25519._sign_rb_pallas

    def _ref_rb(r_u8):
        arr = np.asarray(r_u8)
        out = np.zeros_like(arr)
        for i in range(arr.shape[0]):
            r = int.from_bytes(arr[i].tobytes(), "little")
            out[i] = np.frombuffer(
                ref.point_compress(ref.point_mul(r, ref.BASE)), np.uint8)
        return jnp.asarray(out)

    ed25519._pallas_available = lambda: True
    ed25519._sign_rb_pallas = _ref_rb
    try:
        sigs = ed25519.sign_batch(seeds, msgs)
    finally:
        ed25519._pallas_available = orig_pallas
        ed25519._sign_rb_pallas = orig_dev
    for seed, m, sig in zip(seeds, msgs, sigs):
        assert sig == _scalar_sign(seed, m)
