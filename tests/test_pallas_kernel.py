"""Differential tests for the fused pallas Ed25519 kernel
(ops/ladder_pallas.py) via the pallas interpreter — validates the
transposed field/point/byte helpers and the full verify pipeline against
the pure-Python RFC 8032 reference on CPU."""

import numpy as np
import pytest
import jax.numpy as jnp

from tendermint_tpu.ops import ed25519, ladder_pallas
from tendermint_tpu.utils import ed25519_ref as ref


def make_batch(n, salt=b""):
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = (i + 7).to_bytes(32, "little")
        pk = ref.public_key(seed)
        m = b"plk-%d-" % i + salt
        pubs.append(pk)
        msgs.append(m)
        sigs.append(ref.sign(seed, m))
    return pubs, msgs, sigs


def run_pallas(pk, rb, sbits, hbits, tile=8):
    return np.asarray(ladder_pallas.verify_pallas(
        jnp.asarray(pk), jnp.asarray(rb), jnp.asarray(sbits),
        jnp.asarray(hbits), tile=tile, interpret=True))


def test_pallas_verify_valid_batch():
    pubs, msgs, sigs = make_batch(8)
    pk, rb, sbits, hbits, pre = ed25519.prepare_batch(pubs, msgs, sigs)
    assert pre.all()
    out = run_pallas(pk, rb, sbits, hbits)
    assert out.all()


def test_pallas_verify_rejects_corruptions():
    pubs, msgs, sigs = make_batch(8)
    pk, rb, sbits, hbits, _ = ed25519.prepare_batch(pubs, msgs, sigs)
    # corrupt R of sig 1, pubkey of sig 3 (non-point), scalar of sig 5
    rb2 = np.array(rb); rb2[1, 0] ^= 0x01
    pk2 = np.array(pk); pk2[3] = 0xFF
    hb2 = np.array(hbits); hb2[5, 0] ^= 1
    out = run_pallas(pk2, rb2, sbits, hb2)
    assert not out[1] and not out[3] and not out[5]
    assert out[0] and out[2] and out[4] and out[6] and out[7]


def test_pallas_matches_jnp_kernel():
    """The fused kernel and the jnp kernel must agree bit-for-bit on a
    mixed valid/invalid batch."""
    pubs, msgs, sigs = make_batch(8)
    pk, rb, sbits, hbits, _ = ed25519.prepare_batch(pubs, msgs, sigs)
    rng = np.random.RandomState(11)
    pk2 = np.array(pk)
    rb2 = np.array(rb)
    for i in range(0, 8, 2):  # corrupt half the batch in assorted ways
        if i % 4 == 0:
            rb2[i, rng.randint(32)] ^= 1 << rng.randint(8)
        else:
            pk2[i, rng.randint(32)] ^= 1 << rng.randint(8)
    want = np.asarray(ed25519.verify_kernel_jit(
        jnp.asarray(pk2), jnp.asarray(rb2), jnp.asarray(sbits),
        jnp.asarray(hbits)))
    got = run_pallas(pk2, rb2, sbits, hbits)
    assert (got == want).all(), (got, want)


def test_transposed_byte_roundtrip():
    """_from_bytes_t / _to_bytes_t agree with fe.from_bytes/to_bytes."""
    import jax
    from tendermint_tpu.ops import field as fe
    rng = np.random.RandomState(3)
    vals = [int.from_bytes(rng.bytes(32), "little") % fe.P
            for _ in range(6)]
    b = np.stack([np.frombuffer(v.to_bytes(32, "little"), np.uint8)
                  for v in vals]).astype(np.int32)
    limbs, high = jax.jit(ladder_pallas._from_bytes_t)(jnp.asarray(b.T))
    back = jax.jit(ladder_pallas._to_bytes_t)(limbs)
    assert (np.asarray(back).T == b).all()
    assert (np.asarray(high) == 0).all()  # values < p have bit 255 clear
