"""Differential tests for the fused pallas Ed25519 kernel
(ops/ladder_pallas.py) via the pallas interpreter — validates the
transposed field/point/byte helpers and the full verify pipeline against
the pure-Python RFC 8032 reference on CPU.

The interpreter pays a full single-core XLA compile of the fused kernel
(~4 min on the 1-core CI host), so ALL verify-pipeline coverage — valid
batch, every corruption class, bit-identity with the jnp kernel — runs
in ONE interpreter invocation over one mixed batch."""

import numpy as np
import pytest
import jax.numpy as jnp

from tendermint_tpu.ops import ed25519, ladder_pallas
from tendermint_tpu.utils import ed25519_ref as ref


def make_batch(n, salt=b""):
    # OpenSSL signing (bit-identical to ref.sign, ~1000x faster — the
    # pure-python ladder costs ~0.5s per signature)
    from bench_util import fast_signer
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = (i + 7).to_bytes(32, "little")
        pk = ref.public_key(seed)
        m = b"plk-%d-" % i + salt
        pubs.append(pk)
        msgs.append(m)
        sigs.append(fast_signer(seed)(m))
    return pubs, msgs, sigs


def run_pallas(pk, rb, sbits, hbits, tile=8):
    return np.asarray(ladder_pallas.verify_pallas(
        jnp.asarray(pk), jnp.asarray(rb), jnp.asarray(sbits),
        jnp.asarray(hbits), tile=tile, interpret=True))


def test_pallas_verify_pipeline_one_pass():
    """One mixed batch of 8 through the interpreted fused kernel:

    lane 0: valid                      lane 4: valid
    lane 1: corrupted signature R      lane 5: corrupted h scalar
    lane 2: valid                      lane 6: random-bit-flip R
    lane 3: non-point pubkey (0xFF..)  lane 7: random-bit-flip pubkey

    Asserts the expected verdict per lane AND bit-identity with the jnp
    kernel over the identical inputs (the two implementations must agree
    on every lane, valid or not)."""
    pubs, msgs, sigs = make_batch(8)
    pk, rb, s_bytes, h_bytes, pre = ed25519.prepare_batch_bytes(
        pubs, msgs, sigs)
    assert pre.all()

    rng = np.random.RandomState(11)
    pk2 = np.array(pk)
    rb2 = np.array(rb)
    hb2 = np.array(h_bytes)
    rb2[1, 0] ^= 0x01                                # targeted R corrupt
    pk2[3] = 0xFF                                    # non-point pubkey
    hb2[5, 0] ^= 1                                   # scalar corrupt
    rb2[6, rng.randint(32)] ^= 1 << rng.randint(8)   # random R flip
    pk2[7, rng.randint(32)] ^= 1 << rng.randint(8)   # random pk flip

    sbits = np.asarray(ed25519._bits_le(s_bytes))
    hbits2 = np.asarray(ed25519._bits_le(hb2))
    got = run_pallas(pk2, rb2, sbits, hbits2)
    expect = np.array([1, 0, 1, 0, 1, 0, 0, 0], np.bool_)
    assert (got == expect).all(), got

    # bit-identity with the jnp kernel, through the SAME @8 from-bytes
    # entry the earlier test files already compiled
    want = np.asarray(ed25519._verify_from_bytes_jnp(
        jnp.asarray(pk2), jnp.asarray(rb2), jnp.asarray(s_bytes),
        jnp.asarray(hb2)))
    assert (got == want).all(), (got, want)


def test_transposed_byte_roundtrip():
    """_from_bytes_t / _to_bytes_t agree with fe.from_bytes/to_bytes."""
    import jax
    from tendermint_tpu.ops import field as fe
    rng = np.random.RandomState(3)
    vals = [int.from_bytes(rng.bytes(32), "little") % fe.P
            for _ in range(6)]
    b = np.stack([np.frombuffer(v.to_bytes(32, "little"), np.uint8)
                  for v in vals]).astype(np.int32)
    limbs, high = jax.jit(ladder_pallas._from_bytes_t)(jnp.asarray(b.T))
    back = jax.jit(ladder_pallas._to_bytes_t)(limbs)
    assert (np.asarray(back).T == b).all()
    assert (np.asarray(high) == 0).all()  # values < p have bit 255 clear


def test_sign_kernel_interpret_matches_reference():
    """The full sign_batch pipeline (native phase1 nonce, pallas-
    interpreted R = r*B, native phase2 finalize) must produce
    signatures byte-identical to scalar OpenSSL. ONE interpreter
    invocation covers everything: sig[:32] equality pins the kernel's
    enc(r*B) output (the nonce r is deterministic per RFC 8032), and
    sig[32:] pins the host k/s finalization."""
    from cryptography.hazmat.primitives.asymmetric.ed25519 import \
        Ed25519PrivateKey

    from tendermint_tpu.ops import ed25519, ladder_pallas

    seeds = [bytes([i + 1] * 32) for i in range(8)]
    msgs = [b"sign-batch-%d" % i * (i + 1) for i in range(8)]
    orig_pallas = ed25519._pallas_available
    orig_dev = ed25519._sign_rb_pallas
    ed25519._pallas_available = lambda: True
    # strip sign_batch's 512 padding before the interpreter (each tile
    # is a full 64-window ladder interpretation — 64 tiles would take
    # minutes; the 8 real rows are one tile)
    ed25519._sign_rb_pallas = lambda r: ladder_pallas.sign_pallas_rB(
        r[:8], tile=8, interpret=True)
    try:
        sigs = ed25519.sign_batch(seeds, msgs)
    finally:
        ed25519._pallas_available = orig_pallas
        ed25519._sign_rb_pallas = orig_dev
    for seed, m, sig in zip(seeds, msgs, sigs):
        want = Ed25519PrivateKey.from_private_bytes(seed).sign(m)
        assert sig == want
