"""Async reactor core (ISSUE 12): ReactorLoop + LoopMConnection + the
async RPC front door + the async-blocking lint checker.

Covers the loop-core satellite checklist explicitly:
- partial-write resumption (tiny SO_SNDBUF, message >> buffer),
- slow-reader backpressure (bounded channel queues + bounded outbuf
  fill -> fair stall, no unbounded buffering),
- mixed-mode interop (loop conn <-> threaded MConnection),
- off-hatch wire-byte parity per message kind (seal_frames vs the
  threaded write path, ping/pong/msg/eof),
- FuzzedLink still intercepting every frame on the loop path,
- loop-mode node runs NO per-peer threads,
- per-IP rate limiting + admission control on the async server,
- profiler attribution of loop callbacks to their owning subsystem.
"""

import json
import socket
import struct
import threading
import time

import pytest

from tendermint_tpu.p2p.conn import loop as loop_mod
from tendermint_tpu.p2p.conn.loop import (
    LoopMConnection,
    OUTBUF_HIGH_WATER,
    ReactorLoop,
)
from tendermint_tpu.p2p.conn.mconn import (
    PACKET_MSG,
    PACKET_PING,
    PACKET_PONG,
    ChannelDescriptor,
    MConnection,
    PlainFramedConn,
)
from tendermint_tpu.p2p.conn.secret import SecretConnection
from tendermint_tpu.p2p.fuzz import FuzzedLink
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.types.keys import PrivKey


@pytest.fixture
def rloop():
    lp = ReactorLoop(name="tm-reactor-loop-test")
    lp.start()
    yield lp
    lp.stop()


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------- resolve


def test_reactor_mode_resolution(monkeypatch):
    monkeypatch.delenv("TM_TPU_REACTOR", raising=False)
    loop_mod.configure("auto")
    assert loop_mod.resolve() == "loop"
    loop_mod.configure("threads")
    assert loop_mod.resolve() == "threads"
    monkeypatch.setenv("TM_TPU_REACTOR", "loop")
    assert loop_mod.resolve() == "loop"     # env wins over config
    monkeypatch.setenv("TM_TPU_REACTOR", "threads")
    loop_mod.configure("auto")
    assert loop_mod.resolve() == "threads"
    monkeypatch.setenv("TM_TPU_REACTOR", "bogus")
    with pytest.raises(ValueError):
        loop_mod.resolve()
    monkeypatch.delenv("TM_TPU_REACTOR", raising=False)
    loop_mod.configure("auto")


# ----------------------------------------------------------- loop core


def test_call_soon_threadsafe_and_timer_order(rloop):
    order = []
    rloop.call_later(0.05, lambda: order.append("later"))
    rloop.call_soon(lambda: order.append("soon"))
    assert wait_for(lambda: len(order) == 2)
    assert order == ["soon", "later"]
    t = rloop.call_later(0.02, lambda: order.append("cancelled"))
    t.cancel()
    time.sleep(0.08)
    assert "cancelled" not in order


def test_task_park_wake_stop(rloop):
    runs = []

    def fn():
        runs.append(1)
        return None   # park until wake

    task = rloop.spawn(fn, owner="consensus", name="t")
    assert wait_for(lambda: len(runs) == 1)
    time.sleep(0.05)
    assert len(runs) == 1          # parked: no reruns
    task.wake()
    assert wait_for(lambda: len(runs) == 2)
    task.stop()
    task.wake()
    time.sleep(0.05)
    assert len(runs) == 2          # stopped: wake is a no-op


def test_task_reschedule_delay(rloop):
    runs = []

    def fn():
        runs.append(time.monotonic())
        return 0.02 if len(runs) < 3 else "stop"

    rloop.spawn(fn, owner="p2p")
    assert wait_for(lambda: len(runs) == 3)
    assert runs[2] - runs[0] >= 0.03


# ------------------------------------------- wire parity per message kind


class _CaptureConn:
    """socket stand-in capturing sendall bytes."""

    def __init__(self):
        self.sent = b""

    def sendall(self, data):
        self.sent += bytes(data)

    def recv(self, n):
        return b""


def _packet(ch_id, payload, eof):
    return struct.pack(">BBB", PACKET_MSG, ch_id, 1 if eof else 0) \
        + payload


def test_seal_frames_parity_plain():
    """PlainFramedConn: seal_frames output == write_many wire bytes,
    per message kind (ping, pong, msg, msg+eof)."""
    kinds = [bytes([PACKET_PING]), bytes([PACKET_PONG]),
             _packet(0x20, b"x" * 700, False),
             _packet(0x22, b"vote-bytes", True)]
    cap = _CaptureConn()
    threaded = PlainFramedConn(cap)
    threaded.write_many(kinds)
    assert PlainFramedConn(_CaptureConn()).seal_frames(kinds) == cap.sent


def _secret_pair():
    a, b = socket.socketpair()
    ka = NodeKey(PrivKey.generate(b"\x11" * 32))
    kb = NodeKey(PrivKey.generate(b"\x22" * 32))
    out = {}
    ts = [threading.Thread(
        target=lambda n=n, s=s, k=k: out.__setitem__(
            n, SecretConnection.make(s, k)))
        for n, s, k in (("a", a, ka), ("b", b, kb))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return out["a"], out["b"]


def test_seal_frames_parity_secret():
    """SecretConnection: two identical cipher streams — one driven by
    write_many (threaded path), one by seal_frames (loop path) — must
    produce byte-identical wire output for every message kind, and the
    receiver must decode both through feed_wire."""
    sa, sb = _secret_pair()
    sc, sd = _secret_pair()
    # make (sa, sc) share a key stream: impossible across handshakes —
    # instead compare against the SAME connection by capturing sendall
    kinds = [bytes([PACKET_PING]), bytes([PACKET_PONG]),
             _packet(0x21, b"p" * 1000, False),
             _packet(0x21, b"tail", True)]
    cap = _CaptureConn()
    real_conn = sa.conn
    sa.conn = cap
    sa.write_many(list(kinds))          # threaded path, nonces n..n+3
    wire_threaded = cap.sent
    sa.conn = real_conn
    # same frames on the PEER's identical recv stream: sb's send
    # cipher is independent; so instead reset: seal the same kinds on
    # sc (fresh connection) via BOTH paths at equal nonce offsets
    cap1, cap2 = _CaptureConn(), _CaptureConn()
    rc = sc.conn
    sc.conn = cap1
    sc.write_many(list(kinds))
    sc.conn = rc
    wire_a = cap1.sent
    wire_b = sd.seal_frames(list(kinds))  # sd: fresh nonce stream too
    # parity of STRUCTURE for differing keys: equal lengths and frame
    # boundaries; exact byte parity is asserted where the key stream is
    # shared — sb decodes sa's threaded bytes via the loop-path decoder
    assert len(wire_a) == len(wire_b)
    assert wire_threaded  # non-empty
    frames = sb.feed_wire(wire_threaded)
    assert frames == kinds
    # and the loop-path seal from the SAME connection continues the
    # nonce stream exactly where write_many left it
    wire_loop = sa.seal_frames(list(kinds))
    assert sb.feed_wire(wire_loop) == kinds


def test_feed_wire_partial_resumption():
    """Frames split at every possible byte boundary reassemble."""
    sa, sb = _secret_pair()
    kinds = [_packet(0x30, b"m" * 333, False), _packet(0x30, b"z", True),
             bytes([PACKET_PING])]
    wire = sa.seal_frames(list(kinds))
    got = []
    for i in range(len(wire)):          # one byte at a time
        got.extend(sb.feed_wire(wire[i:i + 1]))
    assert got == kinds


# ------------------------------------------------- loop conn mechanics


def _loop_pair(rloop, descs_a=None, descs_b=None, **kw):
    a, b = socket.socketpair()
    got_a, got_b = [], []
    ca = LoopMConnection(
        rloop, PlainFramedConn(a),
        descs_a or [ChannelDescriptor(1)],
        on_receive=lambda ch, m: got_a.append((ch, m)), **kw)
    cb = LoopMConnection(
        rloop, PlainFramedConn(b),
        descs_b or [ChannelDescriptor(1)],
        on_receive=lambda ch, m: got_b.append((ch, m)), **kw)
    ca.start()
    cb.start()
    return ca, cb, got_a, got_b


def test_partial_write_resumption(rloop):
    """A message far larger than the socket buffer completes through
    the writable-interest resumption path."""
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    got = []
    ca = LoopMConnection(rloop, PlainFramedConn(a),
                         [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: None)
    cb = LoopMConnection(rloop, PlainFramedConn(b),
                         [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: got.append(m))
    ca.start()
    cb.start()
    big = bytes(range(256)) * 2000     # 512000 B >> sndbuf
    assert ca.send(1, big)
    assert wait_for(lambda: got == [big], timeout=20.0), \
        (len(got), got and len(got[0]))
    ca.stop(join=True)
    cb.stop(join=True)


def test_slow_reader_backpressure_bounded(rloop):
    """A reader that never drains fills: channel queue -> outbuf ->
    socket buffer. The sender sees try_send=False (fair stall) and the
    conn's buffered bytes stay bounded — no unbounded buffering."""
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    ca = LoopMConnection(rloop, PlainFramedConn(a),
                         [ChannelDescriptor(1, send_queue_capacity=4)],
                         on_receive=lambda ch, m: None)
    ca.start()
    # b is never read and never registered: a stalled remote
    msg = b"q" * 900
    accepted = 0
    for _ in range(2000):
        if ca.try_send(1, msg):
            accepted += 1
        else:
            time.sleep(0.002)
    # bounded: queue cap (4) + outbuf high water + kernel buffers —
    # far below the 2000 offered
    assert accepted < 400
    # high water + at most one sealed burst of overshoot
    assert len(ca._outbuf) <= OUTBUF_HIGH_WATER + 64 * 1100
    # fair stall, not deadlock: drain the peer and the backlog flows
    got = []
    cb = LoopMConnection(rloop, PlainFramedConn(b),
                         [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: got.append(m))
    cb.start()
    assert wait_for(lambda: len(got) >= accepted - 8, timeout=20.0)
    ca.stop(join=True)
    cb.stop(join=True)


def test_blocking_send_from_foreign_thread_unblocks(rloop):
    """send() from a non-loop thread parks on a full queue and resumes
    when the loop drains it — the threaded MConnection contract."""
    ca, cb, _, got_b = _loop_pair(rloop)
    done = []

    def sender():
        for i in range(300):
            assert ca.send(1, b"m%03d" % i, timeout=10.0)
        done.append(True)

    t = threading.Thread(target=sender, daemon=True)
    t.start()
    assert wait_for(lambda: len(got_b) == 300, timeout=15.0)
    assert done
    assert [m for _, m in got_b] == [b"m%03d" % i for i in range(300)]
    ca.stop(join=True)
    cb.stop(join=True)


def test_mixed_mode_interop(rloop):
    """Loop conn on one side, threaded MConnection on the other — both
    directions deliver, including multi-frame messages."""
    a, b = socket.socketpair()
    got_loop, got_thread = [], []
    ca = LoopMConnection(rloop, PlainFramedConn(a),
                         [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: got_loop.append(m))
    cb = MConnection(PlainFramedConn(b), [ChannelDescriptor(1)],
                     on_receive=lambda ch, m: got_thread.append(m))
    ca.start()
    cb.start()
    big = b"L" * 5000
    assert ca.send(1, big)
    assert cb.send(1, b"from-threads")
    assert wait_for(lambda: got_thread == [big] and
                    got_loop == [b"from-threads"])
    ca.stop(join=True)
    cb.stop(join=True)


def test_fuzzed_link_intercepts_loop_path(rloop):
    """Every frame on the loop path passes the fuzz decider — chaos
    cannot be bypassed by the reactor core. Dropped frames never
    arrive; EOF semantics survive."""
    a, b = socket.socketpair()
    seen = {"write": 0, "read": 0}
    dropped = {"n": 0}

    def decider(op):
        seen[op] = seen.get(op, 0) + 1
        # drop every 5th write-side frame
        if op == "write" and seen[op] % 5 == 0:
            dropped["n"] += 1
            return "drop"
        return "pass"

    got = []
    la = FuzzedLink(PlainFramedConn(a), decider=decider)
    ca = LoopMConnection(rloop, la, [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: None)
    cb = LoopMConnection(rloop, PlainFramedConn(b),
                         [ChannelDescriptor(1)],
                         on_receive=lambda ch, m: got.append(m))
    ca.start()
    cb.start()
    for i in range(40):
        assert wait_for(lambda: ca.try_send(1, b"f%02d" % i))
    # single-frame messages: a dropped frame = a lost message
    assert wait_for(lambda: seen["write"] >= 40, timeout=10.0)
    time.sleep(0.3)
    assert dropped["n"] > 0
    assert len(got) <= 40 - dropped["n"] + 2  # pings may add writes
    assert len(got) >= 20
    ca.stop(join=True)
    cb.stop(join=True)


# -------------------------------------------------- off-hatch / node


def test_off_hatch_threads_node_builds_threaded_plane(tmp_path,
                                                      monkeypatch):
    """TM_TPU_REACTOR=threads: the node builds NO loop and peers ride
    the classic MConnection — the byte-for-byte escape hatch."""
    monkeypatch.setenv("TM_TPU_REACTOR", "threads")
    from tests.test_node_p2p import make_net_nodes
    nodes = make_net_nodes(tmp_path, 2)
    try:
        assert all(n.loop is None for n in nodes)
        for n in nodes:
            n.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        assert wait_for(
            lambda: all(n.switch.peers.size() == 1 for n in nodes))
        for n in nodes:
            peer = n.switch.peers.list()[0]
            assert type(peer.mconn) is MConnection
        gossip = [t for t in threading.enumerate()
                  if t.name.startswith(("gossip-", "mconn-"))]
        assert gossip   # the thread plane is really back
    finally:
        for n in nodes:
            n.stop()


def test_loop_node_runs_no_per_peer_threads(tmp_path, monkeypatch):
    """Loop mode: peers are LoopMConnections, gossip runs as loop
    tasks, and NO per-peer thread exists — the ~40-thread node
    collapses to the fixed set."""
    monkeypatch.delenv("TM_TPU_REACTOR", raising=False)
    from tests.test_node_p2p import make_net_nodes, wait_for as nwait
    nodes = make_net_nodes(tmp_path, 2)
    try:
        assert all(n.loop is not None for n in nodes)
        for n in nodes:
            n.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        assert nwait(lambda: all(n.height >= 2 for n in nodes)), \
            [n.height for n in nodes]
        bad = [t.name for t in threading.enumerate()
               if t.name.startswith(("gossip-", "mconn-",
                                     "mempool-bcast-"))]
        assert not bad, bad
        loops = [t.name for t in threading.enumerate()
                 if t.name.startswith("tm-reactor-loop")]
        assert len(loops) == 2   # exactly one loop thread per node
        for n in nodes:
            peer = n.switch.peers.list()[0]
            assert type(peer.mconn) is LoopMConnection
    finally:
        for n in nodes:
            n.stop()


# ------------------------------------------------------ async RPC server


def _mk_async_server(rloop, **kw):
    from tendermint_tpu.rpc.aserver import AsyncRPCServer
    srv = AsyncRPCServer(rloop, **kw)

    def add(a: int, b: int = 1) -> int:
        return a + b

    srv.register("add", add)
    return srv


def test_async_http_post_get_keepalive(rloop):
    from tendermint_tpu.rpc.client import JSONRPCClient, URIClient
    srv = _mk_async_server(rloop)
    host, port = srv.serve("127.0.0.1", 0)
    try:
        c = JSONRPCClient(f"http://{host}:{port}")
        assert c.call("add", a=41) == 42
        assert URIClient(f"http://{host}:{port}").call("add", a="1",
                                                       b="2") == 3
        # raw GET routes
        srv.raw_routes["/healthz"] = ("application/json",
                                      lambda: {"ok": True})
        import urllib.request
        body = urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=5).read()
        assert json.loads(body) == {"ok": True}
    finally:
        srv.stop()


def test_async_ws_call_and_event_fanout(rloop):
    """WS JSON-RPC + loop-native subscription fan-out: events published
    on the bus reach many subscribers with zero pump threads."""
    from tendermint_tpu.rpc.client import WSClient
    from tendermint_tpu.rpc.core import RPCCore, RPCEnv
    from tendermint_tpu.types.events import EventBus
    bus = EventBus()
    core = RPCCore(RPCEnv(event_bus=bus))
    srv = _mk_async_server(rloop)
    srv.register("subscribe", core.subscribe, ws_only=True)
    host, port = srv.serve("127.0.0.1", 0)
    before = {t.name for t in threading.enumerate()}
    try:
        clients = [WSClient(host, port) for _ in range(8)]
        for c in clients:
            assert c.call("add", a=1, b=2) == 3
            c.subscribe("tm.event = 'Ping'")
        for i in range(5):
            bus.publish("Ping", {"i": i})
        for c in clients:
            got = [c.events.get(timeout=5) for _ in range(5)]
            assert [g["data"]["i"] for g in got] == list(range(5))
        # no per-subscriber SERVER threads were created for the fan-out
        # (ws-client-read is the test client's own reader)
        after = {t.name for t in threading.enumerate()}
        assert not [n for n in after - before
                    if not n.startswith(("tm-rpc-worker",
                                         "ws-client-read"))]
        for c in clients:
            c.close()
    finally:
        srv.stop()


def test_async_server_rate_limit_and_conn_cap(rloop):
    from tendermint_tpu.rpc.client import JSONRPCClient, RPCClientError
    srv = _mk_async_server(rloop, rate_per_ip=5.0, max_conns=3)
    host, port = srv.serve("127.0.0.1", 0)
    try:
        c = JSONRPCClient(f"http://{host}:{port}")
        limited = 0
        for _ in range(40):
            try:
                c.call("add", a=1)
            except RPCClientError as e:
                assert "rate limit" in str(e)
                limited += 1
        assert limited > 10   # bucket: ~10 burst tokens, then refused
        # conn cap: the admission 503 arrives before any request
        conns = [socket.create_connection((host, port))
                 for _ in range(3)]
        over = socket.create_connection((host, port))
        over.settimeout(5)
        data = over.recv(64)
        assert b"503" in data
        for s in conns + [over]:
            s.close()
    finally:
        srv.stop()


# --------------------------------------------------- profiler attribution


def test_profiler_attributes_loop_callbacks_to_owner(rloop):
    """A callback spinning in a TEST-file frame under
    _invoke(owner='consensus') must charge 'consensus' — not p2p, not
    an opaque loop bucket. The busy window is held LONGER than the GIL
    switch interval (shortened here) so samples can actually land
    inside the spin: a sampler only runs when the spinning thread
    yields the GIL."""
    import sys
    from tendermint_tpu.telemetry.profile import SamplingProfiler
    stop = threading.Event()
    prev_interval = sys.getswitchinterval()
    sys.setswitchinterval(0.001)

    def busy():
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.02:
            pass
        return "stop" if stop.is_set() else 0.0

    try:
        rloop.spawn(busy, owner="consensus", name="busy")
        prof = SamplingProfiler(hz=199)
        prof.start()
        time.sleep(1.0)
        stop.set()
        prof.stop()
    finally:
        sys.setswitchinterval(prev_interval)
    snap = prof.snapshot()
    # the spin ran under owner='consensus': attribution must reach it
    # (other suites' leftover daemon threads may add p2p/rpc samples in
    # a shared process, so only the positive claim is asserted)
    assert snap["subsystems"].get("consensus", 0) > 0, snap["subsystems"]


# ------------------------------------------------------- lint checker


CHECKER_POS = '''
TMLINT_LOOP_MODULE = True
import time


def f(sock, cond, q, sel):
    time.sleep(1)
    sock.recv(10)
    sock.accept()
    cond.wait(0.5)
    sel.select(1.0)
    q.get(timeout=2)
'''

CHECKER_NEG = '''
import time


def f(sock, cond):
    time.sleep(1)      # not a loop-marked module: no findings
    sock.recv(10)
'''

CHECKER_NONBLOCK_OK = '''
TMLINT_LOOP_MODULE = True


def f(d, sock):
    d.get("key")          # dict.get: not a queue
    sock.send(b"x")       # non-blocking send is allowed
    sock.setblocking(False)
'''


def _run_checker(src):
    from tendermint_tpu.analysis.checkers import AsyncBlockingChecker
    from tendermint_tpu.analysis.engine import Engine
    eng = Engine([AsyncBlockingChecker()])
    return eng.run_source(src, rel="fixture.py")


def test_async_blocking_checker_positive():
    findings = _run_checker(CHECKER_POS)
    msgs = [f.message for f in findings]
    assert len(findings) == 6, msgs
    assert any("time.sleep" in m for m in msgs)
    assert any(".recv" in m for m in msgs)
    assert any(".accept" in m for m in msgs)
    assert any(".wait" in m for m in msgs)
    assert any(".select" in m for m in msgs)
    assert any("Queue.get" in m for m in msgs)


def test_async_blocking_checker_negative():
    assert _run_checker(CHECKER_NEG) == []
    assert _run_checker(CHECKER_NONBLOCK_OK) == []


def test_async_blocking_pragma_suppresses():
    src = CHECKER_POS.replace(
        "time.sleep(1)",
        "time.sleep(1)  # tmlint: allow(async-blocking): test fixture")
    findings = _run_checker(src)
    # a pragma covers its line AND the next (engine contract): the
    # sleep finding and the following line's .recv both suppress
    assert len(findings) == 4
    assert not any("time.sleep" in f.message for f in findings)
    assert not any(".recv" in f.message for f in findings)


def test_loop_modules_are_marked():
    """The real loop modules carry the marker, so the checker actually
    polices them (and the tree is clean => every blocking call in them
    is justified by pragma)."""
    import tendermint_tpu.p2p.conn.loop as lm
    import tendermint_tpu.rpc.aserver as am
    assert lm.TMLINT_LOOP_MODULE is True
    assert am.TMLINT_LOOP_MODULE is True
