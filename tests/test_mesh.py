"""parallel/mesh.py sharded kernels on the 8-device virtual CPU mesh.

Validates the multi-chip story end to end: the shard_map-wrapped verify
kernel agrees with the unsharded kernel (including invalid signatures
landing on different shards), the all_gather Merkle tree-finish agrees
with the host spec for non-power-of-two leaf counts, and verify_step —
the dryrun's full sharded step — runs on the conftest mesh.
"""

import random

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tendermint_tpu.ops import ed25519, merkle
from tendermint_tpu.parallel.mesh import (make_mesh, sharded_merkle_root,
                                          sharded_verify_kernel, verify_step)
from tendermint_tpu.utils import ed25519_ref as ref

rng = random.Random(41)

# Fail loudly (not skip) if conftest's platform steering broke: the whole
# multi-chip story depends on these tests actually running on 8 devices.
# A fixture (not module-level) so deselected runs don't pay backend init.
@pytest.fixture(autouse=True, scope="module")
def _require_virtual_mesh():
    assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, \
        f"test mesh misconfigured: {jax.devices()}"


def signed_batch(n, tamper=()):
    """n (pub, msg, sig) triples; indices in `tamper` get a corrupted sig."""
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = rng.randbytes(32)
        m = b"mesh test %d" % i
        sig = ref.sign(seed, m)
        if i in tamper:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        pubs.append(ref.public_key(seed))
        msgs.append(m)
        sigs.append(sig)
    return pubs, msgs, sigs


def test_sharded_verify_matches_unsharded():
    mesh = make_mesh(8)
    n = 16
    # invalid sigs spread over different shards (2 sigs per device)
    tamper = {1, 7, 14}
    pubs, msgs, sigs = signed_batch(n, tamper)
    pk, rb, sbits, hbits, pre = ed25519.prepare_batch(pubs, msgs, sigs)
    assert pre.all()
    args = (jnp.asarray(pk), jnp.asarray(rb),
            jnp.asarray(sbits), jnp.asarray(hbits))
    got = np.asarray(sharded_verify_kernel(mesh)(*args))
    want = np.asarray(ed25519.verify_kernel(*args))
    assert got.shape == (n,)
    np.testing.assert_array_equal(got, want)
    for i in range(n):
        assert got[i] == (i not in tamper), i


def test_sharded_kernel_on_smaller_mesh():
    """make_mesh(n < all devices) shards correctly. Exercised through
    the MERKLE kernel: the 2-device ed25519 SPMD program costs a ~40s
    extra compile for no additional coverage (the verify kernel's
    sharding is already proven on the 8-device mesh above; mesh-width
    partitioning is kernel-agnostic in shard_map)."""
    mesh = make_mesh(2)
    items = [bytes([i]) * 9 for i in range(16)]
    digests = merkle.pad_digests(np.stack(
        [np.frombuffer(merkle.leaf_hash(it), np.uint8) for it in items]))
    got = np.asarray(sharded_merkle_root(mesh)(
        jnp.asarray(digests), len(items))).tobytes()
    assert got == merkle.root_host(items)


@pytest.mark.parametrize("n_leaves", [8, 9, 13, 16, 100, 128])
def test_sharded_merkle_root_matches_host(n_leaves):
    # padded size must be divisible by the mesh size (>= 8 leaves here);
    # sub-mesh-width trees take the unsharded kernel path in production
    mesh = make_mesh(8)
    items = [rng.randbytes(rng.randrange(1, 40)) for _ in range(n_leaves)]
    digests = merkle.pad_digests(np.stack(
        [np.frombuffer(merkle.leaf_hash(it), np.uint8) for it in items]))
    root = sharded_merkle_root(mesh)
    got = np.asarray(root(jnp.asarray(digests), n_leaves)).tobytes()
    assert got == merkle.root_host(items), n_leaves


def test_verify_step_end_to_end():
    mesh = make_mesh(8)
    step = verify_step(mesh)
    n = 16
    pubs, msgs, sigs = signed_batch(n)
    pk, rb, sbits, hbits, pre = ed25519.prepare_batch(pubs, msgs, sigs)
    assert pre.all()
    leaves = [bytes([i]) * 8 for i in range(n)]
    digests = merkle.pad_digests(np.stack(
        [np.frombuffer(merkle.leaf_hash(it), np.uint8) for it in leaves]))
    ok, root = step(jnp.asarray(pk), jnp.asarray(rb), jnp.asarray(sbits),
                    jnp.asarray(hbits), jnp.asarray(digests), n)
    assert np.asarray(ok).all()
    assert np.asarray(root).tobytes() == merkle.root_host(leaves)


# ----------------------------------------------- product path (VERDICT r2 #1)

def test_batch_verifier_mesh_knob():
    """BatchVerifier(mesh=...) builds the sharded kernel lazily and its
    verdicts agree with the scalar oracle — the production multi-chip
    wiring (models/verifier.py), not a bespoke kernel call."""
    from tendermint_tpu.models.verifier import BatchVerifier

    # 16 items: same padded batch shape as the other 8-dev mesh tests,
    # so the (cached) kernel closure compiles this shape exactly once
    # across the file
    pubs, msgs, sigs = signed_batch(16, tamper={3})
    items = list(zip(pubs, msgs, sigs))

    v = BatchVerifier("jax", mesh="8")
    assert v.kernel is None and v.mesh_devices == 0  # lazy until dispatch
    ok = v.verify(items)
    assert v.mesh_devices == 8 and v.kernel is not None
    assert ok.tolist() == [i != 3 for i in range(16)]

    # auto on this 8-device host also shards 8-wide (same cached kernel)
    va = BatchVerifier("jax", mesh="auto")
    assert va.verify(items).tolist() == ok.tolist()
    assert va.mesh_devices == 8 and va.kernel is v.kernel

    # off / single-chip spec -> plain kernel path. 8 items: the plain
    # @8 jnp shape is already compiled by test_ed25519, so this arm
    # proves the ROUTING without paying a fresh @16 plain compile
    voff = BatchVerifier("jax", mesh="off")
    assert voff.verify(items[:8]).tolist() == ok.tolist()[:8]
    assert voff.mesh_devices == 0 and voff.kernel is None


def test_batch_verifier_mesh_spec_errors():
    from tendermint_tpu.models.verifier import BatchVerifier
    # spec validation is eager (at construction, i.e. node startup) ...
    with pytest.raises(ValueError):
        BatchVerifier("jax", mesh="3")
    with pytest.raises(ValueError):
        BatchVerifier("jax", mesh="bogus")
    # ... only the device-count check needs jax and stays lazy, and it
    # raises RuntimeError, which no verify-path caller catches as a
    # bad-input signal
    with pytest.raises(RuntimeError):
        BatchVerifier("jax", mesh="64")._resolve_mesh()


def test_mesh_auto_noop_on_single_device_host(monkeypatch):
    """mesh='auto' on a 1-device host is a no-op: no sharded kernel, no
    min-bucket bump, scalar-friendly defaults untouched — and an
    explicit mesh=N beyond the host raises the loud RuntimeError (the
    knob contract, not a bad-peer-data signal)."""
    from tendermint_tpu.models.verifier import BatchVerifier

    one = jax.devices()[:1]
    monkeypatch.setattr(jax, "devices", lambda *a: one)
    v = BatchVerifier("jax", mesh="auto")
    v._resolve_mesh()
    assert v._mesh_resolved
    assert v.kernel is None and v.mesh_devices == 0
    assert v._min_bucket == 8
    with pytest.raises(RuntimeError):
        BatchVerifier("jax", mesh="2")._resolve_mesh()


def test_coalesced_batches_pad_mesh_divisible():
    """Cross-caller batches merged by the dispatch coalescer (PR 2)
    land on the sharded kernel with a mesh-divisible padded axis: the
    mesh-derived min bucket flows through _verify_async_direct (the
    coalescer's merge target), so every dispatched shape is a power of
    two >= the mesh width. Forced 4-device mesh on the 8-device host."""
    from concurrent.futures import ThreadPoolExecutor

    from tendermint_tpu.models.verifier import BatchVerifier

    pubs, msgs, sigs = signed_batch(8, tamper={5})
    items = list(zip(pubs, msgs, sigs))

    v = BatchVerifier("jax", mesh="4", coalesce="on",
                      coalesce_wait_ms=25.0)
    v._resolve_mesh()
    assert v.mesh_devices == 4 and v._min_bucket == 8

    shapes = []
    inner = v.kernel

    def recording(pk, rb, sbits, hbits):
        shapes.append(int(pk.shape[0]))
        return inner(pk, rb, sbits, hbits)

    v.kernel = recording
    try:
        # two concurrent sub-threshold callers -> the coalescer merges
        # (or, on an unlucky linger, dispatches each separately; either
        # way every dispatch must be mesh-divisible)
        with ThreadPoolExecutor(max_workers=2) as pool:
            futs = [pool.submit(v.verify, items[:4]),
                    pool.submit(v.verify, items[4:])]
            first, second = futs[0].result(), futs[1].result()
    finally:
        v.close()
    assert first.tolist() == [True] * 4
    assert second.tolist() == [True, False, True, True]  # tamper at 5
    assert v.stats["coalesced_calls"] == 2
    assert shapes, "no sharded dispatch recorded"
    assert all(s % 4 == 0 for s in shapes), shapes


def test_mesh_telemetry_surfaces():
    """tm_verifier_mesh_devices reports the active mesh width and every
    sharded dispatch lands in tm_mesh_dispatch_total +
    tm_mesh_shard_occupancy (the new mesh catalog, also policed by the
    metrics lint)."""
    from tendermint_tpu import telemetry
    from tendermint_tpu.models.verifier import BatchVerifier

    pubs, msgs, sigs = signed_batch(16)
    was = telemetry.enabled()
    telemetry.set_enabled(True)
    try:
        v = BatchVerifier("jax", mesh="8")
        d0 = telemetry.value("mesh_dispatch_total",
                             {"kind": "verify"}) or 0
        assert v.verify(list(zip(pubs, msgs, sigs))).all()
        assert telemetry.value("verifier_mesh_devices") == 8
        assert telemetry.value("mesh_dispatch_total",
                               {"kind": "verify"}) == d0 + 1
        occ = telemetry.value("mesh_shard_occupancy")
        assert occ["count"] >= 1
        # a full 16-item batch in a 16-wide bucket: occupancy 1.0
        assert occ["sum"] >= 1.0
    finally:
        telemetry.set_enabled(was)


def test_root_host_mesh_dispatch_bit_equality(monkeypatch):
    """ops.merkle's host-facing roots (tx root, part-set root) route
    through the sharded device kernel when a mesh is active, and the
    bytes match the native/hashlib host path exactly. 100 leaves ->
    the padded-128 shape the parametrized kernel tests already
    compiled."""
    from tendermint_tpu import telemetry

    items = [rng.randbytes(rng.randrange(1, 40)) for _ in range(100)]
    digests = [merkle.leaf_hash(it) for it in items]
    want = merkle.root_host(items)  # TM_TPU_MESH=off in conftest: host

    kern = sharded_merkle_root(make_mesh(8))
    monkeypatch.setattr(merkle, "_mesh_state", (kern, 8))
    was = telemetry.enabled()
    telemetry.set_enabled(True)
    try:
        d0 = telemetry.value("mesh_dispatch_total",
                             {"kind": "merkle"}) or 0
        assert merkle.root_host(items) == want
        # both digest-list and flat-blob forms take the mesh path
        assert merkle.root_from_digests_host(digests) == want
        assert merkle.root_from_digests_host(b"".join(digests)) == want
        assert telemetry.value("mesh_dispatch_total",
                               {"kind": "merkle"}) == d0 + 3
        assert telemetry.value("merkle_roots_total",
                               {"impl": "mesh"}) >= 3
    finally:
        telemetry.set_enabled(was)
    # sub-threshold trees stay on host (no mesh dispatch)
    small = [b"x"] * (merkle._MESH_MIN_LEAVES - 1)
    assert merkle.root_host(small) == merkle.root_from_digests_host(
        [merkle.leaf_hash(b"x")] * len(small))


def test_merkle_mesh_env_resolution(monkeypatch):
    """TM_TPU_MESH=N resolves the merkle mesh dispatch lazily through
    the same parallel.mesh spec grammar the verifier uses (env wins,
    power-of-two validation, loud overshoot)."""
    items = [bytes([i]) * 11 for i in range(100)]
    want = merkle.root_host(items)  # resolved off: host path

    monkeypatch.setenv("TM_TPU_MESH", "8")
    monkeypatch.setattr(merkle, "_mesh_state", None)
    assert merkle.root_host(items) == want
    kern, ndev = merkle._mesh_state
    assert ndev == 8 and kern is not None

    # overshooting the host fails loudly, same contract as the verifier
    monkeypatch.setenv("TM_TPU_MESH", "64")
    monkeypatch.setattr(merkle, "_mesh_state", None)
    with pytest.raises(RuntimeError):
        merkle.root_host(items)


def test_fast_sync_window_verifies_through_mesh():
    """fast-sync's _sync_window drains its batched window through a
    mesh-sharded BatchVerifier injected via BlockExecutor — the node
    config path (base.verifier_mesh) on a multi-device host."""
    from test_fast_sync import build_chain
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.abci.types import ValidatorUpdate
    from tendermint_tpu.blockchain import BlockchainReactor, BlockPool
    from tendermint_tpu.models.verifier import BatchVerifier
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.storage import BlockStore, MemDB, StateStore
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator, PrivKey)

    key = PrivKey.generate(b"\x2a" * 32)
    gen = GenesisDoc(chain_id="mesh-fs", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    # 17 blocks -> a 16-signature window: shares the compiled batch
    # shape with the rest of the file (one compile per shape per mesh)
    _, _, src_store, gen = build_chain(gen, key, 17)

    conns = AppConns(local_client_creator(KVStoreApp()))
    state_store = StateStore(MemDB())
    store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen.chain_id)
    verifier = BatchVerifier("jax", mesh="8")
    exec_ = BlockExecutor(state_store, conns.consensus, verifier=verifier)

    reactor = BlockchainReactor(state, exec_, store, fast_sync=True,
                                verify_window=16)
    pool = BlockPool(start_height=1, send_request=lambda p, h: True,
                     on_peer_error=lambda p, r: None)
    reactor.pool = pool
    pool.set_peer_height("src", src_store.height())
    pool.make_next_requests()
    for h in range(1, src_store.height() + 1):
        assert pool.add_block("src", src_store.load_block(h), 100)

    while reactor._sync_window():
        pass
    # synced to tip-1 (tip has no child commit in the window)
    assert store.height() == src_store.height() - 1
    assert verifier.mesh_devices == 8, "window did not use the mesh kernel"
    assert verifier.stats["jax_sigs"] > 0
