"""parallel/mesh.py sharded kernels on the 8-device virtual CPU mesh.

Validates the multi-chip story end to end: the shard_map-wrapped verify
kernel agrees with the unsharded kernel (including invalid signatures
landing on different shards), the all_gather Merkle tree-finish agrees
with the host spec for non-power-of-two leaf counts, and verify_step —
the dryrun's full sharded step — runs on the conftest mesh.
"""

import random

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tendermint_tpu.ops import ed25519, merkle
from tendermint_tpu.parallel.mesh import (make_mesh, sharded_merkle_root,
                                          sharded_verify_kernel, verify_step)
from tendermint_tpu.utils import ed25519_ref as ref

rng = random.Random(41)

# Fail loudly (not skip) if conftest's platform steering broke: the whole
# multi-chip story depends on these tests actually running on 8 devices.
# A fixture (not module-level) so deselected runs don't pay backend init.
@pytest.fixture(autouse=True, scope="module")
def _require_virtual_mesh():
    assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, \
        f"test mesh misconfigured: {jax.devices()}"


def signed_batch(n, tamper=()):
    """n (pub, msg, sig) triples; indices in `tamper` get a corrupted sig."""
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = rng.randbytes(32)
        m = b"mesh test %d" % i
        sig = ref.sign(seed, m)
        if i in tamper:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        pubs.append(ref.public_key(seed))
        msgs.append(m)
        sigs.append(sig)
    return pubs, msgs, sigs


def test_sharded_verify_matches_unsharded():
    mesh = make_mesh(8)
    n = 16
    # invalid sigs spread over different shards (2 sigs per device)
    tamper = {1, 7, 14}
    pubs, msgs, sigs = signed_batch(n, tamper)
    pk, rb, sbits, hbits, pre = ed25519.prepare_batch(pubs, msgs, sigs)
    assert pre.all()
    args = (jnp.asarray(pk), jnp.asarray(rb),
            jnp.asarray(sbits), jnp.asarray(hbits))
    got = np.asarray(sharded_verify_kernel(mesh)(*args))
    want = np.asarray(ed25519.verify_kernel(*args))
    assert got.shape == (n,)
    np.testing.assert_array_equal(got, want)
    for i in range(n):
        assert got[i] == (i not in tamper), i


def test_sharded_verify_on_smaller_mesh():
    # 2-device mesh from the same 8 virtual devices
    mesh = make_mesh(2)
    pubs, msgs, sigs = signed_batch(4, tamper={2})
    pk, rb, sbits, hbits, _ = ed25519.prepare_batch(pubs, msgs, sigs)
    got = np.asarray(sharded_verify_kernel(mesh)(
        jnp.asarray(pk), jnp.asarray(rb),
        jnp.asarray(sbits), jnp.asarray(hbits)))
    assert got.tolist() == [True, True, False, True]


@pytest.mark.parametrize("n_leaves", [8, 9, 13, 16, 100, 128])
def test_sharded_merkle_root_matches_host(n_leaves):
    # padded size must be divisible by the mesh size (>= 8 leaves here);
    # sub-mesh-width trees take the unsharded kernel path in production
    mesh = make_mesh(8)
    items = [rng.randbytes(rng.randrange(1, 40)) for _ in range(n_leaves)]
    digests = merkle.pad_digests(np.stack(
        [np.frombuffer(merkle.leaf_hash(it), np.uint8) for it in items]))
    root = sharded_merkle_root(mesh)
    got = np.asarray(root(jnp.asarray(digests), n_leaves)).tobytes()
    assert got == merkle.root_host(items), n_leaves


def test_verify_step_end_to_end():
    mesh = make_mesh(8)
    step = verify_step(mesh)
    n = 16
    pubs, msgs, sigs = signed_batch(n)
    pk, rb, sbits, hbits, pre = ed25519.prepare_batch(pubs, msgs, sigs)
    assert pre.all()
    leaves = [bytes([i]) * 8 for i in range(n)]
    digests = merkle.pad_digests(np.stack(
        [np.frombuffer(merkle.leaf_hash(it), np.uint8) for it in leaves]))
    ok, root = step(jnp.asarray(pk), jnp.asarray(rb), jnp.asarray(sbits),
                    jnp.asarray(hbits), jnp.asarray(digests), n)
    assert np.asarray(ok).all()
    assert np.asarray(root).tobytes() == merkle.root_host(leaves)
