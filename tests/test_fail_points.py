"""Fail-point plane tests: the stable commit-point catalog, the
arm/clear test APIs, and the crash-at-every-index recovery sweep (the
in-process equivalent of the reference's test_failure_indices.sh loop —
kill one commit at EVERY commit-critical step, restart, and require WAL
+ handshake replay to reach the same AppHash a clean run reaches)."""

import os

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import MockTicker
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import PrivValidatorFile
from tendermint_tpu.utils import fail


class _Crash(BaseException):
    """Simulated process death (BaseException: nothing between the fail
    point and the test may swallow it)."""


def _gen(chain_id):
    key = PrivKey.generate(b"\x0a" * 32)
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    return gen, key


def _make_node(home, gen, key):
    pv_path = os.path.join(home, "priv_validator.json")
    if os.path.exists(pv_path):
        pv = PrivValidatorFile.load(pv_path)
    else:
        pv = PrivValidatorFile(pv_path, key)
        pv._persist()
    node = Node(make_test_config(home), gen, priv_validator=pv,
                app=KVStoreApp())
    node.consensus.ticker.stop()
    node.consensus.ticker = MockTicker(node.consensus._on_timeout_fire)
    return node


WAVE_A = [b"fp/a%d=v%d" % (i, i) for i in range(1, 4)]
WAVE_B = [b"fp/b%d=w%d" % (i, i) for i in range(1, 4)]


def _inject(node, txs):
    """Dup-tolerant injection: after a restart the mempool WAL replays
    pending txs, and committed ones may be re-proposed — KVStore sets
    are idempotent, so the final app STATE converges either way."""
    for tx in txs:
        try:
            node.mempool.check_tx(tx)
        except Exception:
            pass


def _commit_to(node, target_height, max_ticks=400):
    for _ in range(max_ticks):
        if node.height >= target_height:
            return
        node.consensus.ticker.fire_next()
    raise AssertionError(f"stuck at height {node.height}")


def _drain(node, max_ticks=200):
    """Commit until the mempool is empty: the final KV state is then
    exactly the injected key set, comparable across runs."""
    for _ in range(max_ticks):
        if node.mempool.size() == 0:
            return
        node.consensus.ticker.fire_next()
    raise AssertionError(f"mempool never drained ({node.mempool.size()})")


# ---------------------------------------------------------- catalog --

def test_commit_points_fire_in_catalog_order(tmp_path, monkeypatch):
    """One commit passes every COMMIT_POINTS entry, in order — the
    catalog is what schedules and docs reference, so it must match the
    code path exactly. COMMIT_POINTS documents the default (pipelined)
    order; the serial escape hatch is pinned separately below. The
    statetree points only fire with the tree backend on, so the
    catalog-order pin runs with TM_TPU_STATE_TREE set."""
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    seen = []
    for name in fail.COMMIT_POINTS:
        fail.arm(name, seen.append)
    gen, key = _gen("fp-order")
    node = _make_node(str(tmp_path), gen, key)
    node.start()
    _inject(node, WAVE_A)
    _commit_to(node, 1)
    node.stop()
    assert seen == list(fail.COMMIT_POINTS)


def test_commit_points_serial_order_with_pipeline_off(tmp_path,
                                                      monkeypatch):
    """TM_TPU_PIPELINE=off restores the serial commit path: save_block
    commits immediately, ENDHEIGHT fsyncs BEFORE ApplyBlock, and the
    group-flush brackets never fire (SERIAL_COMMIT_POINTS order)."""
    monkeypatch.setenv("TM_TPU_PIPELINE", "off")
    monkeypatch.setenv("TM_TPU_STATE_TREE", "on")
    seen = []
    for name in fail.COMMIT_POINTS:
        fail.arm(name, seen.append)
    gen, key = _gen("fp-serial-order")
    node = _make_node(str(tmp_path), gen, key)
    node.start()
    _inject(node, WAVE_A)
    _commit_to(node, 1)
    node.stop()
    fail.disarm_all()
    assert seen == list(fail.SERIAL_COMMIT_POINTS)


def test_set_target_and_callback_and_clear():
    fail.reset()
    hits = []
    fail.set_callback(hits.append)
    fail.set_target(2)
    fail.fail_point("a")
    fail.fail_point("b")
    fail.fail_point("c")
    assert hits == [2]  # only the target index fires
    fail.clear_callback()
    fail.set_target(None)
    fail.reset()
    fail.fail_point("d")  # no target: must be a no-op (not os._exit)


def test_arm_is_one_shot_and_name_scoped():
    fired = []
    fail.arm("consensus.before_save_block", fired.append)
    fail.fail_point("execution.after_save_state")   # other name: no-op
    assert fired == []
    fail.fail_point("consensus.before_save_block")
    fail.fail_point("consensus.before_save_block")  # disarmed after one
    assert fired == ["consensus.before_save_block"]


# ------------------------------------------------ crash-index sweep --

def test_crash_at_every_index_recovers_same_apphash(tmp_path,
                                                    monkeypatch):
    """For EVERY commit-critical fail point of the PIPELINED path (the
    default — group-commit staging, batch flush, post-flush ENDHEIGHT):
    run two heights clean, crash the third height's commit at that
    index, restart from disk, and require the recovered node to reach
    the control run's height with the IDENTICAL AppHash — WAL catchup +
    ABCI handshake replay must reconcile whatever prefix of the commit
    reached disk. The control runs with TM_TPU_PIPELINE=off, so the
    sweep simultaneously pins pipelined recovery AGAINST the serial
    path's AppHash (bit-identical across modes)."""
    target = 4
    gen, key = _gen("fp-sweep")

    monkeypatch.setenv("TM_TPU_PIPELINE", "off")
    control = _make_node(str(tmp_path / "control"), gen, key)
    monkeypatch.delenv("TM_TPU_PIPELINE")
    control.start()
    _inject(control, WAVE_A)
    _commit_to(control, 2)
    _inject(control, WAVE_B)
    _commit_to(control, target)
    _drain(control)
    control_hash = control.consensus.state.app_hash
    control.stop()
    assert control_hash

    for index in range(1, len(fail.COMMIT_POINTS) + 1):
        home = str(tmp_path / f"crash{index}")
        node = _make_node(home, gen, key)
        node.start()
        _inject(node, WAVE_A)
        _commit_to(node, 2)

        def crash(i):
            raise _Crash(f"index {i}")

        # armed BEFORE wave B: its injection may commit inline via the
        # txs_available hook, and the first commit after arming is the
        # one that must die at `index`
        fail.reset()
        fail.set_callback(crash)
        fail.set_target(index)
        with pytest.raises(_Crash):
            _inject(node, WAVE_B)
            _commit_to(node, target)
        fail.set_target(None)
        fail.clear_callback()
        crashed_at = node.height
        node.consensus._stopped = True
        try:
            node.stop()
        except Exception:
            pass

        node2 = _make_node(home, gen, key)   # handshake replay here
        node2.start()                        # WAL catchup replay here
        assert node2.height >= crashed_at    # no committed height lost
        _inject(node2, WAVE_B)
        _commit_to(node2, target)
        _drain(node2)
        assert node2.consensus.state.app_hash == control_hash, (
            f"index {index} ({fail.COMMIT_POINTS[index - 1]}): "
            f"recovered AppHash diverged")
        # the fresh app was really rebuilt from the stores, not trusted
        assert node2.app.height == node2.block_store.height()
        node2.stop()
