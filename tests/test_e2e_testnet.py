"""True multi-process testnet e2e — the reference's dockerized p2p tests
(test/p2p/{basic,atomic_broadcast}/test.sh) in-repo: `testnet` writes the
file tree, three SEPARATE OS processes run `cli node` over real TCP
sockets, a transaction enters via one node's RPC and must reach every
node's app state (atomic broadcast)."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_block(k):
    from bench_util import free_port_block
    return free_port_block(k)


def _node_env():
    from bench_util import node_child_env
    return node_child_env(REPO)


def test_three_process_testnet_atomic_broadcast(tmp_path):
    net = str(tmp_path / "net")
    n = 3
    base = _free_port_block(2 * n)
    r = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--n", str(n), "--output", net, "--base-port", str(base),
         "--chain-id", "e2e-net"],
        env=_node_env(), capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr

    # test-speed consensus timeouts for every node
    for i in range(n):
        cfg_path = os.path.join(net, f"node{i}", "config", "config.json")
        cfg = json.load(open(cfg_path))
        cfg["consensus"].update({
            "timeout_propose": 400, "timeout_propose_delta": 100,
            "timeout_prevote": 200, "timeout_prevote_delta": 100,
            "timeout_precommit": 200, "timeout_precommit_delta": 100,
            "timeout_commit": 100})
        json.dump(cfg, open(cfg_path, "w"))

    procs = []
    logs = []
    try:
        for i in range(n):
            log = open(os.path.join(net, f"node{i}.log"), "w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.cli",
                 "--home", os.path.join(net, f"node{i}"),
                 "node", "--p2p", "--no-fast-sync",
                 "--rpc-laddr", f"tcp://127.0.0.1:{base + 2 * i + 1}",
                 "--max-seconds", "600"],
                env=_node_env(), stdout=log, stderr=subprocess.STDOUT))

        from tendermint_tpu.rpc.client import JSONRPCClient
        clients = [JSONRPCClient(f"http://127.0.0.1:{base + 2 * i + 1}")
                   for i in range(n)]

        def wait_all(pred, timeout_s, what):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if all(p.poll() is None for p in procs):
                    try:
                        if all(pred(c) for c in clients):
                            return
                    except Exception:
                        pass
                else:
                    break
                time.sleep(0.5)
            for i, log in enumerate(logs):
                log.flush()
                log.seek(0)
                tail = log.read()[-1500:]
                print(f"--- node{i} log tail ---\n{tail}", file=sys.stderr)
            raise AssertionError(
                f"{what}: procs alive="
                f"{[p.poll() is None for p in procs]}")

        # all three nodes commit blocks (basic connectivity + consensus)
        wait_all(lambda c: c.call("status")["latest_block_height"] >= 2,
                 120, "no 3-node consensus progress")

        # atomic broadcast: tx via node1, state visible on ALL nodes
        res = clients[1].call("broadcast_tx_commit", tx=b"e2e=ok".hex())
        assert res["deliver_tx"]["code"] == 0
        h_commit = res["height"]

        def sees_tx(c):
            if c.call("status")["latest_block_height"] < h_commit:
                return False
            q = c.call("abci_query", data=b"e2e".hex())
            return bytes.fromhex(q["response"]["value"] or "") == b"ok"

        wait_all(sees_tx, 60, "tx did not reach every node's app state")

        # all nodes agree on the block at the commit height
        hashes = {c.call("block", height=h_commit)["block_meta"]
                  ["block_id"]["hash"] for c in clients}
        assert len(hashes) == 1
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_killed_node_fast_syncs_back(tmp_path):
    """The reference's test/p2p/fast_sync/test.sh: kill one of three
    nodes, let the others advance, restart it WITH fast-sync — it must
    catch up to the live chain and keep following it."""
    net = str(tmp_path / "net")
    n = 4  # kill 1 of 4: the rest hold 30/40 > 2/3 (2 of 3 would be
    # exactly 2/3, which is NOT a supermajority)
    base = _free_port_block(2 * n)
    r = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--n", str(n), "--output", net, "--base-port", str(base),
         "--chain-id", "e2e-sync"],
        env=_node_env(), capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    for i in range(n):
        cfg_path = os.path.join(net, f"node{i}", "config", "config.json")
        cfg = json.load(open(cfg_path))
        cfg["consensus"].update({
            "timeout_propose": 400, "timeout_propose_delta": 100,
            "timeout_prevote": 200, "timeout_prevote_delta": 100,
            "timeout_precommit": 200, "timeout_precommit_delta": 100,
            "timeout_commit": 100})
        json.dump(cfg, open(cfg_path, "w"))

    def spawn(i, fast_sync):
        log = open(os.path.join(net, f"node{i}.log"), "a+")
        args = [sys.executable, "-m", "tendermint_tpu.cli",
                "--home", os.path.join(net, f"node{i}"),
                "node", "--p2p",
                "--rpc-laddr", f"tcp://127.0.0.1:{base + 2 * i + 1}",
                "--max-seconds", "600"]
        if not fast_sync:
            args.append("--no-fast-sync")
        return subprocess.Popen(args, env=_node_env(), stdout=log,
                                stderr=subprocess.STDOUT), log

    from tendermint_tpu.rpc.client import JSONRPCClient
    clients = [JSONRPCClient(f"http://127.0.0.1:{base + 2 * i + 1}")
               for i in range(n)]

    def height_of(c, default=-1):
        try:
            return c.call("status")["latest_block_height"]
        except Exception:
            return default

    def wait(pred, timeout_s, what, procs):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return
            assert all(p.poll() is None for p in procs), f"{what}: node died"
            time.sleep(0.5)
        raise AssertionError(what)

    procs_logs = [spawn(i, fast_sync=False) for i in range(n)]
    procs = [p for p, _ in procs_logs]
    try:
        wait(lambda: all(height_of(c) >= 2 for c in clients), 120,
             "initial 3-node consensus", procs)

        # kill node3 hard; the remaining 30/40 power keeps committing.
        # Budget note: 30/40 is the MINIMAL supermajority — every
        # height needs all three survivors in lockstep, so on an
        # oversubscribed 1-core host each commit can take tens of
        # seconds of round churn; the generous budget de-flakes the
        # phase without weakening what it asserts (4 net-new heights).
        h_dead = height_of(clients[3], default=0)  # read BEFORE the kill
        procs[3].kill()
        procs[3].wait(timeout=10)
        wait(lambda: all(height_of(c) >= h_dead + 4
                         for c in clients[:3]), 240,
             "3-node supermajority progress", procs[:3])

        # restart node3 with fast-sync: must catch up and keep following
        procs_logs[3] = spawn(3, fast_sync=True)
        procs[3] = procs_logs[3][0]
        target = max(height_of(c) for c in clients[:3])
        wait(lambda: height_of(clients[3]) >= target, 180,
             f"fast-sync catchup to {target}", procs)
        # ...and participates in NEW heights after catching up
        wait(lambda: height_of(clients[3]) >= target + 2, 120,
             "post-sync liveness", procs)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for _, log in procs_logs:
            log.close()


def test_unknown_validator_removal_rejected_not_halting(tmp_path):
    """A val tx removing an UNKNOWN validator must be rejected by the
    app at DeliverTx (persistent_dummy's updateValidator guard) so the
    invalid update never reaches EndBlock — one unauthenticated
    broadcast_tx must NOT halt the network. The node keeps committing.
    (The halt-on-ApplyBlockError path itself stays covered by
    test_consensus.test_invalid_app_validator_update_fails_loudly,
    which injects a bad update behind the app's guard.)"""
    home = str(tmp_path / "node")
    port = _free_port_block(1)
    r = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "init"], env=_node_env(), capture_output=True, text=True,
        timeout=120)
    assert r.returncode == 0, r.stderr
    # init writes keys+genesis but no config.json; create one with
    # test-speed timeouts
    from tendermint_tpu.config import default_config, save_config
    cfg = default_config(home)
    cfg.consensus.timeout_propose = 400
    cfg.consensus.timeout_propose_delta = 100
    cfg.consensus.timeout_prevote = 200
    cfg.consensus.timeout_prevote_delta = 100
    cfg.consensus.timeout_precommit = 200
    cfg.consensus.timeout_precommit_delta = 100
    cfg.consensus.timeout_commit = 100
    save_config(cfg)

    log = open(os.path.join(home, "node.log"), "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "node", "--rpc-laddr", f"tcp://127.0.0.1:{port}",
         "--max-seconds", "120"],
        env=_node_env(), stdout=log, stderr=subprocess.STDOUT)
    try:
        from tendermint_tpu.rpc.client import JSONRPCClient
        c = JSONRPCClient(f"http://127.0.0.1:{port}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if c.call("status")["latest_block_height"] >= 1:
                    break
            except Exception:
                time.sleep(0.5)
        else:
            raise AssertionError("node never started committing")

        ghost = "22" * 32
        res = c.call("broadcast_tx_commit",
                     tx=f"val:{ghost}/0".encode().hex())
        # CheckTx passes (format is fine), DeliverTx rejects: the app
        # refuses to remove a validator it doesn't know
        assert res["check_tx"].get("code", 0) == 0, res
        assert res["deliver_tx"]["code"] == 2, res
        assert "unknown validator" in res["deliver_tx"].get("log", "")

        # ...and the chain keeps committing afterwards
        h0 = c.call("status")["latest_block_height"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if c.call("status")["latest_block_height"] > h0:
                break
            time.sleep(0.3)
        else:
            raise AssertionError("node stopped committing after bad val tx")
        assert proc.poll() is None, "node process died on a rejected tx"
        log.flush()
        log.seek(0)
        assert "CONSENSUS FAILURE" not in log.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        log.close()


def test_node_process_exits_on_consensus_failure(tmp_path):
    """The reference panics the process on an ApplyBlock failure; our
    node must print CONSENSUS FAILURE and exit code 1 — not sit frozen.
    The KVStore app's DeliverTx guard normally keeps invalid updates
    from ever reaching the core, so this drives the halt path behind
    the guard with the TM_KVSTORE_UNSAFE_VAL_UPDATES fail-point."""
    home = str(tmp_path / "node")
    port = _free_port_block(1)
    env = _node_env()
    env["TM_KVSTORE_UNSAFE_VAL_UPDATES"] = "1"
    r = subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "init"], env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    from tendermint_tpu.config import default_config, save_config
    cfg = default_config(home)
    cfg.consensus.timeout_propose = 400
    cfg.consensus.timeout_propose_delta = 100
    cfg.consensus.timeout_prevote = 200
    cfg.consensus.timeout_prevote_delta = 100
    cfg.consensus.timeout_precommit = 200
    cfg.consensus.timeout_precommit_delta = 100
    cfg.consensus.timeout_commit = 100
    save_config(cfg)

    log = open(os.path.join(home, "node.log"), "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cli", "--home", home,
         "node", "--rpc-laddr", f"tcp://127.0.0.1:{port}",
         "--max-seconds", "120"],
        env=env, stdout=log, stderr=subprocess.STDOUT)
    try:
        from tendermint_tpu.rpc.client import JSONRPCClient, RPCClientError
        c = JSONRPCClient(f"http://127.0.0.1:{port}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if c.call("status")["latest_block_height"] >= 1:
                    break
            except Exception:
                time.sleep(0.5)
        else:
            raise AssertionError("node never started committing")

        ghost = "22" * 32
        try:
            res = c.call("broadcast_tx_sync",
                         tx=f"val:{ghost}/0".encode().hex())
        except (RPCClientError, OSError):
            # the single-writer drain may run propose->commit->apply
            # INLINE on the RPC handler's own thread, so the
            # ApplyBlockError can surface as this call's error reply —
            # equally valid; the process must still die below
            res = None
        if res is not None:
            assert res.get("code", 0) == 0, f"tx rejected: {res}"

        rc = proc.wait(timeout=60)
        assert rc == 1, f"expected loud exit 1, got {rc}"
        log.flush()
        log.seek(0)
        out = log.read()
        assert "CONSENSUS FAILURE" in out
        assert "removing unknown validator" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        log.close()
