"""Storage layer tests: DB backends, block store, state store, WAL.

Models the reference's store tests (blockchain/store_test.go,
state/store semantics, consensus/wal_test.go:19-44 incl. corruption).
"""

import os

import pytest

from tendermint_tpu.state.state import State, make_genesis_state
from tendermint_tpu.storage import (
    WAL, BlockStore, MemDB, NilWAL, SQLiteDB, StateStore, WALCorruptionError,
    open_db,
)
from tendermint_tpu.storage.wal import WALMessage, decode_frames, encode_frame
from tendermint_tpu.types import (
    Block, BlockID, Commit, GenesisDoc, GenesisValidator, PrivKey,
    Validator, ValidatorSet, Vote, VoteType,
)
from tendermint_tpu.types.block import Data, Header
from tendermint_tpu.types.params import ConsensusParams


def _keys(n, seed=7):
    return [PrivKey.generate(bytes([seed + i]) * 32) for i in range(n)]


def _genesis(keys, chain_id="test-chain"):
    return GenesisDoc(
        chain_id=chain_id, genesis_time_ns=1,
        validators=[GenesisValidator(k.pubkey.ed25519, 10) for k in keys])


def _make_block(state: State, height: int, txs, last_commit=None):
    return state.make_block(height, txs, last_commit or Commit(), time_ns=height)


# -- db backends -------------------------------------------------------------

@pytest.mark.parametrize("mk", [lambda tmp: MemDB(),
                                lambda tmp: SQLiteDB(str(tmp / "kv.db"))])
def test_kv_roundtrip_and_prefix_iteration(tmp_path, mk):
    db = mk(tmp_path)
    db.set(b"a:1", b"v1")
    db.set(b"a:2", b"v2")
    db.set(b"b:1", b"v3")
    assert db.get(b"a:1") == b"v1"
    assert db.get(b"missing") is None
    assert [k for k, _ in db.iterate(b"a:")] == [b"a:1", b"a:2"]
    db.delete(b"a:1")
    assert db.get(b"a:1") is None
    db.close()


def test_sqlite_persists_across_reopen(tmp_path):
    path = str(tmp_path / "kv.db")
    db = SQLiteDB(path)
    db.set(b"k", b"v")
    db.close()
    db2 = SQLiteDB(path)
    assert db2.get(b"k") == b"v"
    db2.close()


def test_open_db_dispatch(tmp_path):
    assert isinstance(open_db(None), MemDB)
    assert isinstance(open_db(":memory:"), MemDB)
    assert isinstance(open_db(str(tmp_path / "x.db")), SQLiteDB)


# -- block store -------------------------------------------------------------

def test_block_store_save_load_roundtrip():
    keys = _keys(4)
    state = make_genesis_state(_genesis(keys))
    bs = BlockStore(MemDB())
    assert bs.height() == 0

    block = _make_block(state, 1, [b"tx1", b"tx2"])
    parts = block.make_part_set(64)
    seen = Commit(block.block_id(64), [])
    bs.save_block(block, parts, seen)

    assert bs.height() == 1
    loaded = bs.load_block(1)
    assert loaded.hash() == block.hash()
    assert loaded.data.txs == [b"tx1", b"tx2"]
    meta = bs.load_block_meta(1)
    assert meta.block_id.hash == block.hash()
    assert meta.header.height == 1
    part = bs.load_block_part(1, 0)
    assert part.index == 0
    assert bs.load_seen_commit(1).block_id == block.block_id(64)
    assert bs.load_block(2) is None
    assert bs.load_block_meta(99) is None


def test_block_store_rejects_wrong_height_and_incomplete_parts():
    keys = _keys(1)
    state = make_genesis_state(_genesis(keys))
    bs = BlockStore(MemDB())
    block = _make_block(state, 2, [])
    with pytest.raises(ValueError, match="expected height"):
        bs.save_block(block, block.make_part_set(64), Commit())
    block1 = _make_block(state, 1, [])
    from tendermint_tpu.types.part_set import PartSet
    incomplete = PartSet.from_header(block1.make_part_set(64).header())
    with pytest.raises(ValueError, match="not complete"):
        bs.save_block(block1, incomplete, Commit())


def test_block_store_last_commit_stored_under_prev_height():
    keys = _keys(4)
    state = make_genesis_state(_genesis(keys))
    bs = BlockStore(MemDB())
    b1 = _make_block(state, 1, [])
    bs.save_block(b1, b1.make_part_set(64), Commit(b1.block_id(64), []))

    # block 2 carries commit for height 1
    pc = [Vote(keys[i].pubkey.address, i, 1, 0, 5, VoteType.PRECOMMIT,
               b1.block_id(64)) for i in range(4)]
    commit1 = Commit(b1.block_id(64), pc)
    state2 = state.copy()
    state2.last_block_height = 1
    state2.last_block_id = b1.block_id(64)
    b2 = state2.make_block(2, [], commit1, time_ns=2)
    bs.save_block(b2, b2.make_part_set(64), Commit(b2.block_id(64), []))

    got = bs.load_block_commit(1)
    assert got.height() == 1
    assert got.block_id == b1.block_id(64)


# -- state store -------------------------------------------------------------

def test_state_store_roundtrip_and_genesis():
    keys = _keys(4)
    gen = _genesis(keys)
    ss = StateStore(MemDB())
    assert ss.load() is None
    state = ss.load_or_genesis(gen)
    assert state.chain_id == "test-chain"
    assert len(state.validators) == 4
    # reload hits the stored row
    state2 = ss.load_or_genesis(gen)
    assert state2.equals(state)
    # chain-id mismatch is an error
    with pytest.raises(ValueError, match="chain_id"):
        ss.load_or_genesis(_genesis(keys, chain_id="other-chain"))


def test_state_store_historical_validators_indirection():
    keys = _keys(4)
    ss = StateStore(MemDB())
    state = ss.load_or_genesis(_genesis(keys))  # writes row for height 1

    # heights 1..4: no valset change -> pointer rows
    for h in range(1, 4):
        state = state.copy()
        state.last_block_height = h
        ss.save(state)
    vs1 = ss.load_validators(1)
    vs4 = ss.load_validators(4)
    assert vs4.hash() == vs1.hash() == state.validators.hash()

    # change at height 5
    newkeys = _keys(5, seed=40)
    state = state.copy()
    state.last_block_height = 4
    state.validators = ValidatorSet(
        [Validator(k.pubkey.ed25519, 7) for k in newkeys])
    state.last_height_validators_changed = 5
    ss.save(state)
    assert ss.load_validators(5).hash() == state.validators.hash()
    assert ss.load_validators(4).hash() == vs1.hash()
    with pytest.raises(LookupError):
        ss.load_validators(99)


def test_state_store_params_and_abci_responses():
    keys = _keys(1)
    ss = StateStore(MemDB())
    state = ss.load_or_genesis(_genesis(keys))
    assert ss.load_consensus_params(1).to_obj() == \
        state.consensus_params.to_obj()
    ss.save_abci_responses(3, {"deliver_tx": [{"code": 0}], "end_block": {}})
    assert ss.load_abci_responses(3)["deliver_tx"][0]["code"] == 0
    assert ss.load_abci_responses(4) is None


# -- WAL ---------------------------------------------------------------------

def test_wal_roundtrip_and_endheight_search(tmp_path):
    wal = WAL(str(tmp_path / "wal"))
    wal.save({"type": "vote", "h": 1}, time_ns=10)
    wal.save_end_height(1)
    wal.save({"type": "proposal", "h": 2}, time_ns=20)
    wal.save({"type": "vote", "h": 2}, time_ns=21)
    wal.close()

    wal2 = WAL(str(tmp_path / "wal"))
    tail = wal2.messages_after_end_height(1)
    assert [m.msg["type"] for m in tail] == ["proposal", "vote"]
    assert wal2.messages_after_end_height(7) is None
    # 4 saved + the ENDHEIGHT-0 marker a fresh WAL writes on creation
    assert len(wal2.all_messages()) == 5
    wal2.close()


def test_fresh_wal_has_endheight_zero(tmp_path):
    """A brand-new WAL must anchor catchup replay for the FIRST height
    (consensus/wal.go:99-104): a validator that crashes mid-height-1
    finds its own proposal/votes via messages_after_end_height(0); with
    no marker the tail is None, replay is skipped, and double-sign
    protection strands the node (the fail-point-index-1 stall)."""
    wal = WAL(str(tmp_path / "wal"))
    assert wal.messages_after_end_height(0) == []
    wal.save({"type": "vote", "h": 1})
    wal.close()
    wal2 = WAL(str(tmp_path / "wal"))  # reopen must not re-write it
    msgs = wal2.all_messages()
    assert [m.msg["type"] for m in msgs] == ["endheight", "vote"]
    assert [m.msg["type"] for m in wal2.messages_after_end_height(0)] == \
        ["vote"]
    wal2.close()


def test_wal_truncated_tail_is_tolerated(tmp_path):
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.save({"type": "a"})
    wal.save({"type": "b"})
    wal.close()
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:  # crash mid-write of the last frame
        f.write(data[:-3])
    wal2 = WAL(path)
    msgs = wal2.all_messages()
    assert [m.msg["type"] for m in msgs] == ["endheight", "a"]
    wal2.close()


def test_wal_appends_after_torn_tail_stay_readable(tmp_path):
    """A crash mid-write leaves a torn final frame; reopening must trim
    it so frames appended afterwards remain decodable (decode_frames
    stops at the first truncated frame, so appending past a torn tail
    would silently hide everything after it)."""
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.save({"type": "a"})
    wal.save({"type": "b"})
    wal.close()
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:  # crash mid-write of frame "b"
        f.write(data[:-3])
    wal2 = WAL(path)
    wal2.save({"type": "c"})  # append after the (trimmed) torn tail
    wal2.close()
    wal3 = WAL(path)
    assert [m.msg["type"] for m in wal3.all_messages()] == \
        ["endheight", "a", "c"]
    wal3.close()


def test_wal_torn_initial_marker_rewritten(tmp_path):
    """If the crash tore the very first frame (the ENDHEIGHT-0 marker
    itself), reopen trims to empty and re-plants the marker."""
    path = str(tmp_path / "wal")
    WAL(path).close()
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:5])  # partial header only
    wal = WAL(path)
    assert wal.messages_after_end_height(0) == []
    wal.close()


def test_wal_zero_filled_tail_is_trimmed(tmp_path):
    """Power loss classically extends the file to a block boundary and
    zero-fills the tail. Zero bytes must read as torn garbage (8 zero
    bytes 'CRC-validate' because crc32(b'')==0), be trimmed at open,
    and never veto the trim as fake 'resync' evidence."""
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.save({"type": "a"})
    wal.save({"type": "b"})
    wal.close()
    good = open(path, "rb").read()
    # a torn write is a PREFIX of a valid frame; build one from a real
    # frame ("c") so its header length points past the zeros/EOF
    frame_c = encode_frame(WALMessage(0, {"type": "c", "pad": "y" * 48}))
    for tail in (b"\x00" * 24,                    # aligned zero run
                 b"\x00" * 13,                    # ragged zero run
                 frame_c[:12],                    # classic torn write
                 frame_c[:12] + b"\x00" * 16):    # torn write + zero fill
        with open(path, "wb") as f:
            f.write(good + tail)
        wal2 = WAL(path)
        assert [m.msg["type"] for m in wal2.all_messages()] == \
            ["endheight", "a", "b"], tail
        wal2.save({"type": "c"})  # appends land after the trim point
        wal2.close()
        wal3 = WAL(path)
        assert [m.msg["type"] for m in wal3.all_messages()] == \
            ["endheight", "a", "b", "c"], tail
        wal3.close()


def test_wal_large_zero_tail_trims_fast(tmp_path):
    """A multi-MB zero-filled tail (fallocate/journal zero-extension)
    must trim in well under a second: the resync scan jumps zero runs
    with a C-level search instead of a per-byte Python loop."""
    import time
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.save({"type": "a"})
    wal.close()
    with open(path, "ab") as f:
        f.write(b"\x00" * (8 << 20))
    t0 = time.perf_counter()
    wal2 = WAL(path)
    took = time.perf_counter() - t0
    assert [m.msg["type"] for m in wal2.all_messages()] == \
        ["endheight", "a"]
    wal2.close()
    assert took < 1.0, f"zero-tail repair took {took:.2f}s"


def test_wal_midfile_length_corruption_not_trimmed(tmp_path):
    """A bit-flipped LENGTH field mid-file makes a good frame look like
    it extends past EOF (i.e. torn). Open-time repair must notice the
    valid frames that resume after it and leave the file byte-identical
    — truncating would silently destroy committed consensus messages."""
    path = str(tmp_path / "wal")
    wal = WAL(path)
    wal.save({"type": "a"})
    wal.save({"type": "b", "pad": "x" * 40})
    wal.save({"type": "c"})
    wal.close()
    with open(path, "rb") as f:
        data = bytearray(f.read())
    # find frame "b"'s header: walk one frame (endheight) + one ("a")
    import struct
    off = 0
    for _ in range(2):
        _, ln = struct.unpack_from(">II", data, off)
        off += 8 + ln
    crc_b, ln_b = struct.unpack_from(">II", data, off)
    struct.pack_into(">II", data, off, crc_b, ln_b + 64)  # past EOF
    with open(path, "wb") as f:
        f.write(data)
    wal2 = WAL(path)  # reopen triggers the repair scan
    with open(path, "rb") as f:
        assert f.read() == bytes(data), "corrupt WAL was mutated"
    # and reading must reject loudly, NOT silently drop frames b and c
    # as a "tolerated truncated tail"
    with pytest.raises(WALCorruptionError, match="resume after"):
        wal2.all_messages()
    wal2.close()

    # same corruption PLUS a genuinely torn final frame: the resumed
    # b->c chain no longer reaches EOF, but one valid frame after the
    # corruption is still proof — must refuse the trim and read loudly
    frame_d = encode_frame(WALMessage(0, {"type": "d"}))
    data_torn = bytes(data) + frame_d[:11]
    with open(path, "wb") as f:
        f.write(data_torn)
    wal3 = WAL(path)
    with open(path, "rb") as f:
        assert f.read() == data_torn, "corrupt+torn WAL was mutated"
    with pytest.raises(WALCorruptionError, match="resume after"):
        wal3.all_messages()
    wal3.close()


def test_wal_rotated_empty_head_gets_no_spurious_marker(tmp_path):
    """Restarting on a just-rotated (empty) head file must NOT write a
    second ENDHEIGHT-0 marker into the middle of the logical log."""
    path = str(tmp_path / "wal")
    wal = WAL(path, rotate_bytes=1)  # every save rotates
    wal.save_end_height(3)
    wal.close()
    assert os.path.getsize(path) == 0 and os.path.exists(path + ".1")
    wal2 = WAL(path, rotate_bytes=1)
    types = [m.msg for m in wal2.all_messages()]
    assert types[-1] == {"type": "endheight", "height": 3}
    assert wal2.messages_after_end_height(3) == []
    wal2.close()


def test_wal_corruption_detected():
    frame = bytearray(encode_frame(WALMessage(0, {"type": "x"})))
    frame[-1] ^= 0xFF  # flip a payload byte -> CRC mismatch
    with pytest.raises(WALCorruptionError, match="crc"):
        list(decode_frames(bytes(frame)))


def test_wal_rotation_spans_endheight_search(tmp_path):
    path = str(tmp_path / "wal")
    wal = WAL(path, rotate_bytes=256)
    for h in range(1, 8):
        wal.save({"type": "vote", "h": h, "pad": "x" * 64})
        wal.save_end_height(h)
    wal.close()
    assert os.path.exists(path + ".1")  # rotation happened
    wal2 = WAL(path, rotate_bytes=256)
    tail = wal2.messages_after_end_height(3)
    assert tail[0].msg == {"type": "vote", "h": 4, "pad": "x" * 64}
    assert len(wal2.messages_after_end_height(7)) == 0
    wal2.close()


def test_nil_wal():
    w = NilWAL()
    w.save({"type": "x"})
    w.save_end_height(1)
    assert w.all_messages() == []
    assert w.messages_after_end_height(1) is None
