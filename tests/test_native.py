"""Native C++ host-ops: differential tests against the pure-Python spec
implementation and hashlib (ops/merkle.py's host reference)."""

import hashlib
import os
import struct

import pytest

from tendermint_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for native hostops")


def py_leaf(item):
    return hashlib.sha256(b"\x00" + item).digest()


def py_node(l, r):
    return hashlib.sha256(b"\x01" + l + r).digest()


def py_final(n, tr):
    return hashlib.sha256(b"\x02" + struct.pack("<Q", n) + tr).digest()


def py_root(items):
    n = len(items)
    if n == 0:
        return py_final(0, b"\x00" * 32)
    m = 1
    while m < n:
        m *= 2
    level = [py_leaf(it) for it in items] + [b"\x00" * 32] * (m - n)
    while len(level) > 1:
        level = [py_node(level[i], level[i + 1])
                 for i in range(0, len(level), 2)]
    return py_final(n, level[0])


def test_sha256_batch_matches_hashlib():
    items = [b"", b"a", b"ab" * 100, os.urandom(1000), b"\x00" * 64,
             os.urandom(63), os.urandom(65)]
    got = native.sha256_batch(items)
    want = [hashlib.sha256(it).digest() for it in items]
    assert got == want


@pytest.mark.parametrize("n", [0, 1, 2, 3, 4, 5, 7, 8, 9, 100, 257])
def test_merkle_root_matches_spec(n):
    items = [b"item-%d" % i for i in range(n)]
    assert native.merkle_root(items) == py_root(items)


def test_merkle_root_from_digests():
    digests = [hashlib.sha256(b"%d" % i).digest() for i in range(37)]
    m = 1
    while m < 37:
        m *= 2
    level = list(digests) + [b"\x00" * 32] * (m - 37)
    while len(level) > 1:
        level = [py_node(level[i], level[i + 1])
                 for i in range(0, len(level), 2)]
    assert native.merkle_root_from_digests(digests) == py_final(37, level[0])


@pytest.mark.parametrize("n,idx", [(1, 0), (5, 0), (5, 4), (8, 3),
                                   (100, 77)])
def test_merkle_proof_verifies(n, idx):
    from tendermint_tpu.ops import merkle
    items = [b"p-%d" % i for i in range(n)]
    root, aunts = native.merkle_proof(items, idx)
    assert root == py_root(items)
    assert merkle.verify_proof_host(root, n, idx, items[idx], aunts)
    # tampered item fails
    assert not merkle.verify_proof_host(root, n, idx, b"evil", aunts)


def test_merkle_host_functions_use_native_consistently():
    """ops/merkle host entry points agree with the pure spec regardless of
    which path (native or hashlib) served them."""
    from tendermint_tpu.ops import merkle
    items = [os.urandom(50) for _ in range(23)]
    assert merkle.root_host(items) == py_root(items)
    root, aunts = merkle.proof_host(items, 11)
    assert root == py_root(items)
    assert merkle.verify_proof_host(root, 23, 11, items[11], aunts)


def test_native_speedup_on_large_tree():
    """The point of the C++ path: whole-tree builds beat per-node hashlib
    loops. Soft-asserted (>=2x) to avoid CI flakiness."""
    import time
    from tendermint_tpu.ops import merkle

    items = [os.urandom(100) for _ in range(4096)]
    t0 = time.perf_counter()
    native_root = native.merkle_root(items)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    py = merkle.root_from_digests_host.__wrapped__ \
        if hasattr(merkle.root_from_digests_host, "__wrapped__") else None
    want = py_root(items)
    t_py = time.perf_counter() - t0

    assert native_root == want
    assert t_native < t_py, (t_native, t_py)
