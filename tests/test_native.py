"""Native C++ host-ops: differential tests against the pure-Python spec
implementation and hashlib (ops/merkle.py's host reference)."""

import hashlib
import os
import struct

import pytest

from tendermint_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for native hostops")


def py_leaf(item):
    return hashlib.sha256(b"\x00" + item).digest()


def py_node(l, r):
    return hashlib.sha256(b"\x01" + l + r).digest()


def py_final(n, tr):
    return hashlib.sha256(b"\x02" + struct.pack("<Q", n) + tr).digest()


def py_root(items):
    n = len(items)
    if n == 0:
        return py_final(0, b"\x00" * 32)
    m = 1
    while m < n:
        m *= 2
    level = [py_leaf(it) for it in items] + [b"\x00" * 32] * (m - n)
    while len(level) > 1:
        level = [py_node(level[i], level[i + 1])
                 for i in range(0, len(level), 2)]
    return py_final(n, level[0])


def test_sha256_batch_matches_hashlib():
    items = [b"", b"a", b"ab" * 100, os.urandom(1000), b"\x00" * 64,
             os.urandom(63), os.urandom(65)]
    got = native.sha256_batch(items)
    want = [hashlib.sha256(it).digest() for it in items]
    assert got == want


@pytest.mark.parametrize("n", [0, 1, 2, 3, 4, 5, 7, 8, 9, 100, 257])
def test_merkle_root_matches_spec(n):
    items = [b"item-%d" % i for i in range(n)]
    assert native.merkle_root(items) == py_root(items)


def test_merkle_root_from_digests():
    digests = [hashlib.sha256(b"%d" % i).digest() for i in range(37)]
    m = 1
    while m < 37:
        m *= 2
    level = list(digests) + [b"\x00" * 32] * (m - 37)
    while len(level) > 1:
        level = [py_node(level[i], level[i + 1])
                 for i in range(0, len(level), 2)]
    assert native.merkle_root_from_digests(digests) == py_final(37, level[0])


@pytest.mark.parametrize("n,idx", [(1, 0), (5, 0), (5, 4), (8, 3),
                                   (100, 77)])
def test_merkle_proof_verifies(n, idx):
    from tendermint_tpu.ops import merkle
    items = [b"p-%d" % i for i in range(n)]
    root, aunts = native.merkle_proof(items, idx)
    assert root == py_root(items)
    assert merkle.verify_proof_host(root, n, idx, items[idx], aunts)
    # tampered item fails
    assert not merkle.verify_proof_host(root, n, idx, b"evil", aunts)


def test_merkle_host_functions_use_native_consistently():
    """ops/merkle host entry points agree with the pure spec regardless of
    which path (native or hashlib) served them."""
    from tendermint_tpu.ops import merkle
    items = [os.urandom(50) for _ in range(23)]
    assert merkle.root_host(items) == py_root(items)
    root, aunts = merkle.proof_host(items, 11)
    assert root == py_root(items)
    assert merkle.verify_proof_host(root, 23, 11, items[11], aunts)


def test_native_speedup_on_large_tree():
    """The point of the C++ path: whole-tree builds beat per-node hashlib
    loops. Soft-asserted (>=2x) to avoid CI flakiness."""
    import time
    from tendermint_tpu.ops import merkle

    items = [os.urandom(100) for _ in range(4096)]
    t0 = time.perf_counter()
    native_root = native.merkle_root(items)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    py = merkle.root_from_digests_host.__wrapped__ \
        if hasattr(merkle.root_from_digests_host, "__wrapped__") else None
    want = py_root(items)
    t_py = time.perf_counter() - t0

    assert native_root == want
    assert t_native < t_py, (t_native, t_py)


def test_codec_differential_vs_pure():
    """native/codec.cpp canonical_dumps must be byte-equal to the pure
    _canon+json.dumps specification path on randomized object trees,
    raise TypeError on floats, and Fallback (-> pure path) on non-str
    dict keys."""
    import random
    import string

    import pytest

    from tendermint_tpu import native
    from tendermint_tpu.types import encoding

    mod = native.codec()
    if mod is None:
        pytest.skip("native codec unavailable")

    rng = random.Random(1234)

    def rand_obj(depth=0):
        r = rng.random()
        if depth > 4 or r < 0.25:
            return rng.choice([
                None, True, False,
                rng.randrange(-2 ** 70, 2 ** 70),
                rng.randrange(-1000, 1000),
                ''.join(rng.choice(string.printable)
                        for _ in range(rng.randrange(0, 30))),
                'unicode: ñ→🎉 \x01\x1f "quoted" back\\slash',
                rng.randbytes(rng.randrange(0, 40)),
                bytearray(rng.randbytes(5)),
            ])
        if r < 0.55:
            return {''.join(rng.choice(string.ascii_letters + 'é\n"\\')
                            for _ in range(rng.randrange(1, 10))):
                    rand_obj(depth + 1)
                    for _ in range(rng.randrange(0, 8))}
        return [rand_obj(depth + 1) for _ in range(rng.randrange(0, 8))]

    for _ in range(1500):
        o = rand_obj()
        assert mod.canonical_dumps(o) == encoding._pure_cdumps(o), o

    class Wrapped:
        def to_obj(self):
            return {"x": b"\x01\x02", "n": [1, None]}

    assert mod.canonical_dumps(Wrapped()) == \
        encoding._pure_cdumps(Wrapped())

    with pytest.raises(TypeError):
        mod.canonical_dumps({"a": 1.5})
    with pytest.raises(mod.Fallback):
        mod.canonical_dumps({1: "a"})
    # cdumps itself falls back and matches pure for non-str keys
    assert encoding.cdumps({1: "a"}) == encoding._pure_cdumps({1: "a"})


def test_prep_items_differential_vs_python():
    """native.prep_items must byte-match prepare_batch_bytes (the
    Python/ctypes path) across valid, malformed, and boundary inputs,
    and return None for shapes routed to the general path."""
    import random

    import numpy as np

    from tendermint_tpu.ops import ed25519
    from tendermint_tpu.utils import ed25519_ref as ref

    if native._prep() is None:
        pytest.skip("prep extension unavailable")

    rng = random.Random(7)
    items = []
    for i in range(64):
        seed = (i + 1).to_bytes(32, "little")
        pk = ref.public_key(seed)
        m = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 400)))
        items.append((pk, m, ref.sign(seed, m)))
    items[5] = (items[5][0][:31], items[5][1], items[5][2])      # short pk
    items[7] = (items[7][0], items[7][1], items[7][2][:63])      # short sig
    items[9] = (items[9][0], items[9][1],
                items[9][2][:32] + ed25519.L_ORDER.to_bytes(32, "little"))
    items[11] = (items[11][0], b"\x55" * 700, items[11][2])      # long msg
    items[13] = (b"\x00" * 32, items[13][1], items[13][2])       # non-point

    out = native.prep_items(items)
    assert out is not None
    pk, rb, sb, hb, pre = out
    ref_out = ed25519.prepare_batch_bytes(
        [i[0] for i in items], [i[1] for i in items],
        [i[2] for i in items])
    for got, want in zip((pk, rb, sb, hb, pre), ref_out):
        assert np.array_equal(got, want)
    assert not pre[5] and not pre[7] and not pre[9] and pre[13]

    # shapes the fast path must hand back to the general path
    assert native.prep_items(
        [(b"\x02" + b"\x01" * 32, b"m", b"s" * 64)]) is None  # secp256k1
    assert native.prep_items(
        [(bytearray(32), b"m", b"s" * 64)]) is None           # non-bytes
    assert native.prep_items([(b"a" * 32, b"m")]) is None     # 2-tuple
    empty = native.prep_items([])
    assert empty is not None and empty[4].shape == (0,)


def test_kvcore_differential_vs_python_app():
    """Native KV core vs the pure-Python KVStoreApp: identical app
    hashes, store contents, and results hashes across mixed batches,
    key overwrites, and val: txs (which route to the Python path)."""
    import random

    from tendermint_tpu.abci.apps.kvstore import KVStoreApp
    from tendermint_tpu.state.execution import results_hash

    if native.kv() is None:
        pytest.skip("kv extension unavailable")

    pure = KVStoreApp(use_native=False)
    assert pure._core is None
    nat = KVStoreApp()
    assert nat._core is not None

    rng = random.Random(13)
    for block in range(6):
        txs = []
        for i in range(200):
            k = b"k%d" % rng.randrange(150)   # frequent overwrites
            v = bytes(rng.randrange(256) for _ in range(rng.randrange(20)))
            txs.append(k + b"=" + v if rng.random() < 0.8 else k)
        if block == 3:
            txs.insert(7, b"val:" + b"aa" * 32 + b"/5")  # python fallback
        r_nat = nat.deliver_tx_batch(txs)
        r_pure = [pure.deliver_tx(tx) for tx in txs]
        assert results_hash(r_nat) == results_hash(r_pure)
        assert [r.to_obj() for r in r_nat] == [r.to_obj() for r in r_pure]
        assert nat.commit() == pure.commit(), f"block {block}"
    assert dict(nat.store.items()) == pure.store
    assert len(nat.store) == len(pure.store)
    assert nat.store.get(b"k1") == pure.store.get(b"k1")
    assert nat.tx_count == pure.tx_count
