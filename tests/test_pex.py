"""AddrBook + PEX reactor tests (models p2p/pex/addrbook_test.go,
pex_reactor_test.go)."""

import time

import pytest

from tendermint_tpu.p2p import NetAddress, pubkey_to_id
from tendermint_tpu.p2p.fuzz import FuzzConfig, FuzzedLink
from tendermint_tpu.p2p.pex import PEX_CHANNEL, AddrBook, PEXReactor
from tendermint_tpu.p2p.test_util import connect_switches, make_switch


def ra(i, j=0, port=26656, with_id=True):
    """Routable address i.j in distinct /16 groups."""
    id_ = pubkey_to_id(bytes([i, j]) + bytes(30)) if with_id else ""
    return NetAddress(f"8.{i}.{j}.1", port, id_)


def test_addrbook_add_pick_markgood():
    book = AddrBook(key=b"k" * 24)
    src = ra(0)
    for i in range(1, 20):
        assert book.add_address(ra(i), src)
    assert book.size() == 19
    a = book.pick_address()
    assert a is not None and book.has(a)
    # promote: moves to old bucket, re-add rejected
    book.mark_good(ra(1))
    assert not book.add_address(ra(1), src)
    # old addrs still picked with bias toward old
    picked_old = any(book.pick_address(new_bias_pct=0) == ra(1)
                     for _ in range(100))
    assert picked_old


def test_addrbook_rejects_unroutable_when_strict():
    book = AddrBook(strict=True, key=b"k" * 24)
    assert not book.add_address(
        NetAddress("127.0.0.1", 26656, ""), ra(0))
    assert not book.add_address(
        NetAddress("10.1.2.3", 26656, ""), ra(0))
    loose = AddrBook(strict=False, key=b"k" * 24)
    assert loose.add_address(NetAddress("127.0.0.1", 26656, ""), ra(0))


def test_addrbook_own_address_excluded():
    book = AddrBook(key=b"k" * 24)
    me = ra(5)
    book.add_our_address(me)
    assert not book.add_address(me, ra(0))


def test_addrbook_selection_bounds():
    book = AddrBook(key=b"k" * 24)
    assert book.get_selection() == []
    src = ra(0)
    for i in range(1, 50):
        book.add_address(ra(i), src)
    sel = book.get_selection()
    assert 1 <= len(sel) <= 250
    assert all(book.has(a) for a in sel)


def test_addrbook_eviction_on_full_bucket():
    book = AddrBook(key=b"k" * 24)
    src = ra(0)
    # same /16 group + same src: all land in one new bucket (64 cap)
    added = 0
    for j in range(1, 200):
        if book.add_address(NetAddress("8.1.0.%d" % (j % 250 + 1),
                                       20000 + j,
                                       pubkey_to_id(bytes([7, j % 256]) +
                                                    bytes(30))), src):
            added += 1
    assert added >= 64  # kept absorbing via eviction
    assert book.size() <= added


def test_addrbook_persistence(tmp_path):
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path=path, key=b"k" * 24)
    src = ra(0)
    for i in range(1, 10):
        book.add_address(ra(i), src)
    book.mark_good(ra(3))
    book.save()
    book2 = AddrBook(path=path)
    assert book2.size() == book.size()
    assert book2.has(ra(3))
    assert book2._addrs[book2._addr_key(ra(3))].is_old()


def test_pex_request_response_fills_book():
    book1 = AddrBook(strict=False, key=b"a" * 24)
    book2 = AddrBook(strict=False, key=b"b" * 24)
    for i in range(1, 30):
        book2.add_address(ra(i), ra(0))
    r1 = PEXReactor(book1, ensure_peers_period=1000)
    r2 = PEXReactor(book2, ensure_peers_period=1000)
    sw1 = make_switch(seed=b"\x01" * 32)
    sw2 = make_switch(seed=b"\x02" * 32)
    sw1.add_reactor("pex", r1)
    sw2.add_reactor("pex", r2)
    p1, p2 = connect_switches(sw1, sw2)
    # add_peer auto-requested addresses (book empty); they flow back
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and book1.size() == 0:
        time.sleep(0.02)
    assert book1.size() > 0
    sw1.stop(); sw2.stop()


def test_pex_unsolicited_addrs_disconnects_peer():
    book = AddrBook(strict=False, key=b"a" * 24)
    r1 = PEXReactor(book, ensure_peers_period=1000)
    sw1 = make_switch(seed=b"\x01" * 32)
    sw2 = make_switch(seed=b"\x02" * 32)
    sw1.add_reactor("pex", r1)
    sw2.add_reactor("pex", PEXReactor(
        AddrBook(strict=False, key=b"b" * 24), ensure_peers_period=1000))
    p1, p2 = connect_switches(sw1, sw2)
    # sw2 pushes addrs sw1 never asked for
    from tendermint_tpu.types import encoding
    p2.send(PEX_CHANNEL, encoding.cdumps(
        {"type": "pex_addrs", "addrs": [ra(1).to_obj()]}))
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and sw1.peers.size() > 0:
        time.sleep(0.02)
    assert sw1.peers.size() == 0
    sw1.stop(); sw2.stop()


def test_fuzzed_link_drops_but_mconn_survives():
    """Reactor messages still arrive (eventually) across a lossy link in
    delay mode; drop mode drops whole frames without crashing."""
    import socket
    import threading
    from tendermint_tpu.p2p import ChannelDescriptor, MConnection
    from tendermint_tpu.p2p.conn.mconn import PlainFramedConn

    s1, s2 = socket.socketpair()
    recv2 = []
    errs = []
    fuzz = FuzzedLink(PlainFramedConn(s1),
                      FuzzConfig(mode="delay", prob_sleep=0.5,
                                 max_delay_s=0.01, seed=7))
    m1 = MConnection(fuzz, [ChannelDescriptor(1)],
                     on_receive=lambda ch, m: None,
                     on_error=errs.append)
    m2 = MConnection(PlainFramedConn(s2), [ChannelDescriptor(1)],
                     on_receive=lambda ch, m: recv2.append(m),
                     on_error=errs.append)
    m1.start(); m2.start()
    for i in range(20):
        m1.send(1, b"msg%d" % i)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(recv2) < 20:
        time.sleep(0.02)
    assert len(recv2) == 20
    m1.stop(); m2.stop()


def test_fuzzed_link_drop_mode_loses_frames():
    class FakeLink:
        def __init__(self):
            self.wrote = []

        def write(self, b):
            self.wrote.append(b)
            return len(b)

        def close(self):
            pass

    fake = FakeLink()
    fuzz = FuzzedLink(fake, FuzzConfig(mode="drop", prob_drop_rw=0.5,
                                       seed=42))
    for i in range(100):
        fuzz.write(b"x")
    assert 10 < len(fake.wrote) < 90  # some dropped, some delivered
