"""Lock/unlock proof-of-lock safety scenarios, scripted against a single
ConsensusState with injected votes and a MockTicker — the deterministic
analog of the reference's crown-jewel safety table
(consensus/state_test.go:718 TestStateLockPOLSafety1, :841 ...2, and the
TestStateLock* family).

Harness: our node is the round-0 proposer of a 4-validator set; the
other three validators are scripted keys whose (pre)votes the test
forges and submits. The node's own votes are captured off its broadcast
hook."""

import pytest

from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.block import BlockID, PartSetHeader
from tendermint_tpu.types.vote import Vote, VoteType

from test_consensus import make_node

CHAIN = "pol-test"


class Script:
    """One scripted node + helpers to forge votes and observe its own."""

    def __init__(self):
        keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
        gen = GenesisDoc(
            chain_id=CHAIN, genesis_time_ns=1,
            validators=[GenesisValidator(k.pubkey.ed25519, 10)
                        for k in keys])
        # our node must be the height-1 round-0 proposer so it proposes
        # without any peer interaction; proposer choice is deterministic,
        # so probe once and rebuild with the right key if needed
        cs = make_node(gen, keys[0])
        cs.start()
        cs.ticker.fire_next()  # NEW_HEIGHT -> round 0
        prop = cs.rs.validators.proposer().address
        key = next(k for k in keys if k.pubkey.address == prop)
        # rebuild with the proposer's key, hook attached BEFORE start so
        # the round-0 proposal/prevote is captured
        cs = make_node(gen, key)
        self.cs = cs
        self.key = key
        self.others = [k for k in keys
                       if k.pubkey.address != key.pubkey.address]
        self.own_votes = []
        cs.broadcast_hooks.append(
            lambda m: self.own_votes.append(m["vote"])
            if m.get("type") == "vote" else None)
        cs.start()
        cs.ticker.fire_next()  # NEW_HEIGHT -> round 0: propose + prevote

    def inject_vote(self, key, type_, round_, block_id=None):
        """Forge + submit a vote from a scripted validator."""
        rs = self.cs.rs
        idx, _ = rs.validators.get_by_address(key.pubkey.address)
        bid = block_id if block_id is not None else BlockID()
        v = Vote(key.pubkey.address, idx, rs.height, round_, 1000 + round_,
                 type_, bid)
        v.signature = key.sign(v.sign_bytes(CHAIN))
        self.cs.submit({"type": "vote", "vote": v.to_obj()},
                       peer_id="scripted")

    def own_last(self, type_, round_):
        for v in reversed(self.own_votes):
            if v["type"] == type_ and v["round"] == round_:
                return v
        return None

    def proposal_block_id(self):
        rs = self.cs.rs
        return BlockID(rs.proposal_block.hash(),
                       rs.proposal_block_parts.header())


def _lock_in_round0(s: Script) -> BlockID:
    """Drive the node to lock its own proposal B in round 0, then push
    it to round 1 with nil precommits. Returns B's BlockID."""
    cs = s.cs
    assert cs.rs.proposal_block is not None, "node did not propose"
    bid = s.proposal_block_id()
    own_pv = s.own_last(VoteType.PREVOTE, 0)
    assert own_pv is not None and \
        bytes.fromhex(own_pv["block_id"]["hash"]) == bid.hash

    # polka for B at round 0: 2 scripted prevotes + our own = 3/4
    for k in s.others[:2]:
        s.inject_vote(k, VoteType.PREVOTE, 0, bid)
    assert cs.rs.locked_block is not None and \
        cs.rs.locked_block.hash() == bid.hash
    assert cs.rs.locked_round == 0
    own_pc = s.own_last(VoteType.PRECOMMIT, 0)
    assert own_pc is not None and \
        bytes.fromhex(own_pc["block_id"]["hash"]) == bid.hash

    # 2 nil precommits -> +2/3 any -> precommit-wait; fire it -> round 1
    for k in s.others[:2]:
        s.inject_vote(k, VoteType.PRECOMMIT, 0)
    fired = cs.ticker.fire_next()
    assert fired is not None
    assert cs.rs.round == 1
    return bid


def test_lock_no_pol_prevote_locked_block():
    """Locked with no newer polka: the node must keep prevoting and
    precommitting ONLY the locked block across rounds, and must still
    be locked after a round with no polka (TestStateLock* behavior)."""
    s = Script()
    cs = s.cs
    bid = _lock_in_round0(s)

    # round 1: we are (possibly) not proposer and see no proposal; the
    # propose timeout fires -> the node must prevote the LOCKED block
    if s.own_last(VoteType.PREVOTE, 1) is None:
        cs.ticker.fire_next()
    pv1 = s.own_last(VoteType.PREVOTE, 1)
    assert pv1 is not None
    assert bytes.fromhex(pv1["block_id"]["hash"]) == bid.hash, \
        "locked node must prevote its locked block"

    # no polka in round 1 (2 scripted nil prevotes + ours-for-B): after
    # prevote-wait the node precommits nil but MUST STAY LOCKED
    for k in s.others[:2]:
        s.inject_vote(k, VoteType.PREVOTE, 1)
    cs.ticker.fire_next()  # prevote-wait -> enter precommit round 1
    pc1 = s.own_last(VoteType.PRECOMMIT, 1)
    assert pc1 is not None and pc1["block_id"]["hash"] == ""
    assert cs.rs.locked_block is not None and \
        cs.rs.locked_block.hash() == bid.hash
    assert cs.rs.locked_round == 0


def test_relock_on_newer_polka_same_block():
    """A new polka for the SAME locked block re-locks at the new round
    and precommits it (the relock arm of enterPrecommit)."""
    s = Script()
    cs = s.cs
    bid = _lock_in_round0(s)

    if s.own_last(VoteType.PREVOTE, 1) is None:
        cs.ticker.fire_next()  # propose timeout -> prevote locked B

    # polka for B again at round 1
    for k in s.others[:2]:
        s.inject_vote(k, VoteType.PREVOTE, 1, bid)
    pc1 = s.own_last(VoteType.PRECOMMIT, 1)
    assert pc1 is not None
    assert bytes.fromhex(pc1["block_id"]["hash"]) == bid.hash
    assert cs.rs.locked_round == 1
    assert cs.rs.locked_block.hash() == bid.hash


def test_unlock_on_nil_polka():
    """+2/3 nil prevotes in a later round UNLOCK the node and it
    precommits nil (TestStateLockPOLUnlock's release arm)."""
    s = Script()
    cs = s.cs
    _lock_in_round0(s)

    # all 3 scripted validators prevote nil at round 1: nil polka
    for k in s.others:
        s.inject_vote(k, VoteType.PREVOTE, 1)
    assert cs.rs.locked_block is None, "nil polka must unlock"
    pc1 = s.own_last(VoteType.PRECOMMIT, 1)
    assert pc1 is not None and pc1["block_id"]["hash"] == ""


def test_no_unlock_on_older_round_votes():
    """Safety: votes from the ALREADY-DECIDED round 0 arriving late must
    not perturb the lock state (stale-vote handling)."""
    s = Script()
    cs = s.cs
    bid = _lock_in_round0(s)
    # late duplicate round-0 nil prevote from the third validator
    s.inject_vote(s.others[2], VoteType.PREVOTE, 0)
    assert cs.rs.locked_block is not None
    assert cs.rs.locked_block.hash() == bid.hash


def test_halt_commits_from_older_round_on_late_precommit():
    """TestStateHalt1 (consensus/state_test.go:1020): lock B in round 0
    with precommits {ours: B, ext1: B, ext2: nil} (2/3-any, no maj),
    advance to round 1 — then the WITHHELD round-0 precommit for B
    arrives. Round 0 now has +2/3 precommits for B and the node must
    commit B immediately, even though it sits in round 1."""
    s = Script()
    cs = s.cs
    assert cs.rs.proposal_block is not None
    bid = s.proposal_block_id()

    # polka + lock in round 0 (2 ext prevotes + ours)
    for k in s.others[:2]:
        s.inject_vote(k, VoteType.PREVOTE, 0, bid)
    assert cs.rs.locked_block is not None

    # round-0 precommits: ext0 for B, ext1 nil (ours for B already in)
    s.inject_vote(s.others[0], VoteType.PRECOMMIT, 0, bid)
    s.inject_vote(s.others[1], VoteType.PRECOMMIT, 0)
    cs.ticker.fire_next()  # precommit-wait -> round 1
    assert cs.rs.round == 1
    assert cs.state.last_block_height == 0  # nothing committed yet

    if s.own_last(VoteType.PREVOTE, 1) is None:
        cs.ticker.fire_next()  # propose timeout -> prevote locked B
    pv1 = s.own_last(VoteType.PREVOTE, 1)
    assert pv1 is not None and bytes.fromhex(pv1["block_id"]["hash"]) == bid.hash

    # the late round-0 precommit: +2/3 for B at round 0 -> COMMIT
    s.inject_vote(s.others[2], VoteType.PRECOMMIT, 0, bid)
    # skip_timeout_commit may schedule a zero-delay NEW_HEIGHT tick
    cs.ticker.fire_next()
    assert cs.state.last_block_height == 1, (
        f"node must halt-commit from round 0; at "
        f"h={cs.rs.height} r={cs.rs.round} step={cs.rs.step.name}")
    assert cs.state.last_block_id.hash == bid.hash
