"""Consensus reactor integration: N full validator nodes gossiping over
in-process switches reach consensus (models consensus/reactor_test.go:81+
TestReactorBasic / voting-power scenarios)."""

import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.abci.types import ValidatorUpdate
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.p2p.test_util import make_connected_switches
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.storage import BlockStore, MemDB, StateStore
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def make_validator_node(gen_doc, key, with_mempool=False):
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen_doc)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen_doc.chain_id)
    mempool = None
    if with_mempool:
        from tendermint_tpu.mempool import Mempool
        mempool = Mempool(conns.mempool)
    exec_ = BlockExecutor(state_store, conns.consensus, mempool=mempool)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, block_store,
        mempool=mempool,
        priv_validator=PrivValidator(LocalSigner(key)),
        ticker_factory=TimeoutTicker)
    cs.app = app
    return cs


def make_reactor_net(n, chain_id="reactor-test", with_mempool=False):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id=chain_id, genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    css = [make_validator_node(gen, k, with_mempool=with_mempool)
           for k in keys]
    reactors = [ConsensusReactor(cs, gossip_sleep_s=0.005) for cs in css]
    switches = make_connected_switches(
        n, lambda i: {"consensus": reactors[i]}, network=chain_id)
    return css, reactors, switches


def wait_height(css, height, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(cs.state.last_block_height >= height for cs in css):
            return True
        time.sleep(0.05)
    return False


def shutdown(reactors, switches):
    for sw in switches:
        sw.stop()


def test_reactor_net_commits_blocks():
    css, reactors, switches = make_reactor_net(4)
    try:
        assert wait_height(css, 3), (
            f"heights: {[cs.state.last_block_height for cs in css]}, "
            f"steps: {[(cs.rs.height, cs.rs.round, int(cs.rs.step)) for cs in css]}")
        tips = {cs.state.last_block_id.key() for cs in css
                if cs.state.last_block_height ==
                css[0].state.last_block_height}
        assert len(tips) == 1
    finally:
        shutdown(reactors, switches)


def test_late_joiner_catches_up_via_gossip():
    """A validator connected after the net has advanced catches up through
    the reactor's block-part + seen-commit gossip (the consensus-level
    catchup path, consensus/reactor.go gossipDataRoutine catchup arm)."""
    from tendermint_tpu.p2p.test_util import connect_switches, make_switch

    n = 4
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id="catchup-test", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    css = [make_validator_node(gen, k) for k in keys]
    reactors = [ConsensusReactor(cs, gossip_sleep_s=0.005) for cs in css]
    # start only 3 of 4 (30/40 power > 2/3): they can commit alone
    switches = make_connected_switches(
        3, lambda i: {"consensus": reactors[i]}, network="catchup-test")
    try:
        assert wait_height(css[:3], 3)
        # now bring up the 4th node and connect it to everyone
        sw3 = make_switch(network="catchup-test", seed=b"\x44" * 32)
        sw3.add_reactor("consensus", reactors[3])
        sw3.start()
        switches.append(sw3)
        for sw in switches[:3]:
            connect_switches(sw3, sw)
        target = css[0].state.last_block_height
        assert wait_height([css[3]], target, timeout=60), (
            f"late joiner at {css[3].state.last_block_height}, "
            f"net at {target}")
    finally:
        shutdown(reactors, switches)


def test_reactor_net_with_txs_converges_app_state():
    css, reactors, switches = make_reactor_net(4, with_mempool=True)
    try:
        assert wait_height(css, 1)
        # submit the tx everywhere (mempool gossip is a separate reactor);
        # whoever proposes next includes it and all apps converge
        tx = b"answer=42"
        for cs in css:
            try:
                cs.mempool.check_tx(tx)
            except Exception:
                pass
        base = css[0].state.last_block_height
        assert wait_height(css, base + 2)
        assert all(cs.app.store.get(b"answer") == b"42" for cs in css), \
            [cs.app.store for cs in css]
        app_hashes = {cs.state.app_hash for cs in css
                      if cs.state.last_block_height ==
                      css[0].state.last_block_height}
        assert len(app_hashes) == 1
    finally:
        shutdown(reactors, switches)


def test_heartbeat_receive_verifies_signature():
    """Received proposal heartbeats are signature- and membership-
    checked before reaching the event bus: forged or non-validator
    heartbeats are dropped silently."""
    from tendermint_tpu.types import encoding
    from tendermint_tpu.types.events import EventBus
    from tendermint_tpu.types.proposal import Heartbeat

    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    gen = GenesisDoc(chain_id="hb-rx", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    cs = make_validator_node(gen, keys[0])
    bus = EventBus()
    cs.event_bus = bus
    reactor = ConsensusReactor(cs)
    sub = bus.subscribe("hb-test", "tm.event='ProposalHeartbeat'")

    def got():
        out = []
        while not sub.queue.empty():
            out.append(sub.queue.get_nowait())
        return out

    class FakePeer:
        id = "fakepeer"
        running = True
        def set(self, k, v): pass
        def try_send_obj(self, ch, obj): return True

    peer = FakePeer()
    reactor.peer_states[peer.id] = __import__(
        "tendermint_tpu.consensus.reactor",
        fromlist=["PeerRoundState"]).PeerRoundState()

    idx, _ = cs.rs.validators.get_by_address(keys[1].pubkey.address)
    hb = Heartbeat(keys[1].pubkey.address, idx, cs.rs.height, 0, 0)
    hb.signature = keys[1].sign(hb.sign_bytes("hb-rx"))
    msg = {"type": "heartbeat", "heartbeat": hb.to_obj()}
    reactor.receive(0x20, peer, encoding.cdumps(msg))
    assert len(got()) == 1, "valid heartbeat must publish"

    forged = Heartbeat(keys[1].pubkey.address, idx, cs.rs.height, 0, 0,
                       signature=b"\x01" * 64)
    reactor.receive(0x20, peer, encoding.cdumps(
        {"type": "heartbeat", "heartbeat": forged.to_obj()}))
    ghost = PrivKey.generate(b"\x66" * 32)
    outsider = Heartbeat(ghost.pubkey.address, 0, cs.rs.height, 0, 0)
    outsider.signature = ghost.sign(outsider.sign_bytes("hb-rx"))
    reactor.receive(0x20, peer, encoding.cdumps(
        {"type": "heartbeat", "heartbeat": outsider.to_obj()}))
    assert not got(), "forged/non-validator heartbeats must drop"


def test_heartbeat_replay_deduped_and_stale_dropped():
    """A validly-signed heartbeat publishes ONCE: replays are dropped at
    the dedup set before re-verifying (a replay loop must not burn the
    receive thread on ms-scale sig checks), and heartbeats for another
    height / an already-passed round never reach verification."""
    from tendermint_tpu.types import encoding
    from tendermint_tpu.types.events import EventBus
    from tendermint_tpu.types.proposal import Heartbeat

    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    gen = GenesisDoc(chain_id="hb-replay", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    cs = make_validator_node(gen, keys[0])
    bus = EventBus()
    cs.event_bus = bus
    reactor = ConsensusReactor(cs)
    sub = bus.subscribe("hb-replay", "tm.event='ProposalHeartbeat'")

    def drain():
        out = []
        while not sub.queue.empty():
            out.append(sub.queue.get_nowait())
        return out

    class FakePeer:
        id = "fakepeer"
        running = True
        def set(self, k, v): pass
        def try_send_obj(self, ch, obj): return True

    peer = FakePeer()
    reactor.peer_states[peer.id] = __import__(
        "tendermint_tpu.consensus.reactor",
        fromlist=["PeerRoundState"]).PeerRoundState()

    # heartbeats verify through the BatchVerifier boundary (so a
    # coalescing verifier can merge them with vote traffic) — count
    # there, not at the scalar PubKey.verify the reactor no longer uses
    verifies = 0
    from tendermint_tpu.models.verifier import BatchVerifier
    orig_verify = BatchVerifier.verify_one
    def counting_verify(self, *a, **k):
        nonlocal verifies
        verifies += 1
        return orig_verify(self, *a, **k)
    BatchVerifier.verify_one = counting_verify
    try:
        idx, _ = cs.rs.validators.get_by_address(keys[1].pubkey.address)
        hb = Heartbeat(keys[1].pubkey.address, idx, cs.rs.height, 0, 3)
        hb.signature = keys[1].sign(hb.sign_bytes("hb-replay"))
        wire = encoding.cdumps({"type": "heartbeat",
                                "heartbeat": hb.to_obj()})
        for _ in range(5):          # replay loop
            reactor.receive(0x20, peer, wire)
        assert len(drain()) == 1, "replayed heartbeat must publish once"
        assert verifies == 1, f"replays re-verified {verifies} times"

        # wrong height / stale round: dropped BEFORE verification
        stale = Heartbeat(keys[1].pubkey.address, idx,
                          cs.rs.height + 7, 0, 0)
        stale.signature = keys[1].sign(stale.sign_bytes("hb-replay"))
        reactor.receive(0x20, peer, encoding.cdumps(
            {"type": "heartbeat", "heartbeat": stale.to_obj()}))
        assert verifies == 1 and not drain()
    finally:
        BatchVerifier.verify_one = orig_verify


def test_commit_cache_invalidates_on_mutation():
    """Commit.hash()/to_obj() caches must never serve stale bytes after
    the commit is mutated — whole-field writes AND in-place precommit
    tampering (the evidence/tamper idiom) both invalidate."""
    from tendermint_tpu.types.block import BlockID, Commit, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType

    key = PrivKey.generate(b"\x01" * 32)
    bid = BlockID(b"\xaa" * 32, PartSetHeader(1, b"\xbb" * 32))
    votes = []
    for i in range(3):
        v = Vote(validator_address=key.pubkey.address, validator_index=i,
                 height=5, round=0, type=VoteType.PRECOMMIT, block_id=bid,
                 timestamp_ns=1000 + i)
        v.signature = key.sign(v.sign_bytes("c"))
        votes.append(v)
    commit = Commit(block_id=bid, precommits=list(votes))

    h0 = commit.hash()
    o0 = commit.to_obj()
    # in-place tamper: __setattr__ never fires, fingerprint must catch it
    commit.precommits[1].signature = bytes(64)
    assert commit.hash() != h0
    assert commit.to_obj() != o0
    # field write invalidates too
    h1 = commit.hash()
    commit.precommits = commit.precommits[:2]
    assert commit.hash() != h1


def test_idle_vote_gossip_reannounces_round_step():
    """Genesis-wedge regression (PR 10): the add_peer NewRoundStep
    announcement is a try_send into a just-built conn, and receive()
    drops messages arriving before the peer state registers — either
    end of the connect race can eat it, leaving the PEER's view of us
    blank at (0, -1). The side with the stale view cannot know it, so
    the side with NOTHING TO SEND must re-announce: an idle vote
    gossip loop re-sends our new_round_step after ~2s, repeatedly,
    until the peer can place us."""
    import threading
    import time

    from tendermint_tpu.consensus.reactor import PeerRoundState

    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    gen = GenesisDoc(chain_id="reannounce", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    cs = make_validator_node(gen, keys[0])
    reactor = ConsensusReactor(cs, gossip_sleep_s=0.02)

    sent = []

    class FakePeer:
        id = "fakepeer"
        running = True

        def set(self, k, v):
            pass

        def try_send_obj(self, ch, obj):
            sent.append((ch, obj))
            return True

        def send(self, ch, raw):
            return True

    peer = FakePeer()
    ps = PeerRoundState()  # blank: the lost-announcement shape
    reactor.peer_states[peer.id] = ps
    t = threading.Thread(target=reactor._gossip_votes_routine,
                         args=(peer, ps), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(obj.get("type") == "new_round_step"
                   for _, obj in sent):
                break
            time.sleep(0.05)
        announcements = [obj for _, obj in sent
                         if obj.get("type") == "new_round_step"]
        assert announcements, "idle gossip never re-announced"
        assert announcements[0]["height"] == cs.rs.height
        # and it repeats while the peer stays blank (the first copy
        # may be lost the same way the add_peer one was)
        n0 = len(announcements)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len([obj for _, obj in sent
                    if obj.get("type") == "new_round_step"]) > n0:
                break
            time.sleep(0.05)
        assert len([obj for _, obj in sent
                    if obj.get("type") == "new_round_step"]) > n0
        # once the peer's view catches up, the idle loop goes quiet
        ps.apply_new_round_step({"height": cs.rs.height,
                                 "round": cs.rs.round,
                                 "step": int(cs.rs.step),
                                 "last_commit_round": -1})
    finally:
        peer.running = False
        t.join(timeout=3.0)
