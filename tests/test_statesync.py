"""State-sync reactor tests: a fresh node joining over real in-process
switches via snapshot restore (then fast-syncing the tail), the
adversarial chunk plane (corrupted chunk -> ban + re-fetch elsewhere,
forged manifest, snapshot failing light verification -> poisoned +
fallback), and crash-resume of a torn restore."""

import os
import tempfile
import threading
import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.proxy import AppConns, local_client_creator
from tendermint_tpu.abci.types import ValidatorUpdate
from tendermint_tpu.blockchain import BlockchainReactor
from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.consensus import ConsensusState, MockTicker
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.p2p.test_util import connect_switches, make_switch
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.statesync import (
    STATESYNC_CHANNEL, StateSyncReactor, resume_pending_restore,
)
from tendermint_tpu.storage import (
    BlockStore, MemDB, SnapshotManager, SnapshotStore, StateStore,
)
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator
from tendermint_tpu.utils import fail


class _Crash(BaseException):
    pass


def _build_source(tmp_path, n_blocks=14, interval=4, chunk_size=256):
    """Single-validator chain with interval snapshots; returns a dict
    of everything the serving side needs."""
    key = PrivKey.generate(b"\x09" * 32)
    gen = GenesisDoc(chain_id="ss-net", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    snap_store = SnapshotStore(str(tmp_path / "src-snapshots"))
    mgr = SnapshotManager(snap_store, state_store, block_store, app,
                          interval=interval, keep=2,
                          chunk_size=chunk_size)
    cs = ConsensusState(
        make_test_config().consensus, state, exec_, block_store,
        priv_validator=PrivValidator(LocalSigner(key)),
        ticker_factory=MockTicker)
    cs.post_commit_hooks.append(mgr.maybe_snapshot)
    cs.start()
    wave = 0
    for _ in range(120 * n_blocks):
        if cs.state.last_block_height >= n_blocks:
            break
        if cs.state.last_block_height >= wave:
            wave += 1
            try:
                cs.mempool.check_tx(b"ss/k%d=v%d" % (wave, wave))
            except Exception:
                pass
        cs.ticker.fire_next()
    assert cs.state.last_block_height >= n_blocks
    assert snap_store.list_heights(), "source produced no snapshots"
    return {"gen": gen, "cs": cs, "app": app, "block_store": block_store,
            "state_store": state_store, "snap_store": snap_store}


def _serving_switch(src, seed, reactor_cls=StateSyncReactor,
                    snap_store=None):
    ss = reactor_cls(snap_store or src["snap_store"], "ss-net")
    bc = BlockchainReactor(src["cs"].state, None, src["block_store"],
                           fast_sync=False)
    sw = make_switch(network="ss-net", seed=seed)
    sw.add_reactor("blockchain", bc)
    sw.add_reactor("statesync", ss)
    sw.start()
    return sw


def _fresh_side(tmp_path, gen, name="new", give_up_s=8.0):
    """Restoring-node assembly; returns components + its switch."""
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    gate = threading.Event()
    cs = ConsensusState(make_test_config().consensus, state, exec_,
                        block_store, priv_validator=None,
                        ticker_factory=MockTicker)
    cons = ConsensusReactor(cs, fast_sync=True)
    bc = BlockchainReactor(state, exec_, block_store, fast_sync=True,
                           consensus_reactor=cons, verify_window=5,
                           gate=gate)
    local_snaps = SnapshotStore(str(tmp_path / f"{name}-snapshots"))
    statesync_dir = str(tmp_path / f"{name}-statesync")

    def on_done(restored, _cs=cs, _bc=bc, _gate=gate):
        if restored is not None:
            _cs.state = restored
            _bc.adopt_restored(restored)
        _gate.set()

    ss = StateSyncReactor(local_snaps, "ss-net", restore=True,
                          statesync_dir=statesync_dir,
                          block_store=block_store,
                          state_store=state_store, app=app,
                          on_restored=on_done, give_up_s=give_up_s)
    sw = make_switch(network="ss-net", seed=b"\x7f" * 32)
    sw.add_reactor("consensus", cons)
    sw.add_reactor("blockchain", bc)
    sw.add_reactor("statesync", ss)
    return {"app": app, "block_store": block_store,
            "state_store": state_store, "bc": bc, "ss": ss, "sw": sw,
            "gate": gate, "statesync_dir": statesync_dir,
            "local_snaps": local_snaps}


def _wait(pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(what)


def test_fresh_node_joins_via_snapshot_then_fast_syncs_tail(tmp_path):
    src = _build_source(tmp_path)
    sw_src = _serving_switch(src, b"\x01" * 32)
    new = _fresh_side(tmp_path, src["gen"])
    new["sw"].start()
    connect_switches(sw_src, new["sw"])
    try:
        _wait(lambda: new["bc"].synced, 40, "never synced")
        restored = new["ss"].restored_state
        assert restored is not None
        snap_h = restored.last_block_height
        assert snap_h == max(src["snap_store"].list_heights())
        # the restore bootstrapped the stores AT the snapshot height:
        # no block below it was ever fetched or stored
        assert new["block_store"].base() == snap_h + 1
        # ...and fast-sync carried the node to the frontier
        assert new["block_store"].height() >= \
            src["block_store"].height() - 1
        top = new["block_store"].height()
        meta_src = src["block_store"].load_block_meta(top)
        meta_new = new["block_store"].load_block_meta(top)
        assert meta_src.block_id.key() == meta_new.block_id.key()
        # the app really followed: replayed tail on top of the restore
        assert new["app"].height == top
    finally:
        sw_src.stop()
        new["sw"].stop()


def test_corrupted_chunk_bans_peer_and_refetches_elsewhere(tmp_path):
    """One of two serving peers corrupts every chunk it serves: the
    restorer must ban it on the first bad digest and complete the
    restore from the honest peer."""
    src = _build_source(tmp_path, chunk_size=64)  # many chunks

    class EvilChunks(StateSyncReactor):
        served = 0

        def _serve_chunk(self, peer, msg):
            m = self.snapshot_store.load_manifest(
                int(msg.get("height", 0)))
            if m is None:
                return super()._serve_chunk(peer, msg)
            EvilChunks.served += 1
            peer.try_send_obj(STATESYNC_CHANNEL, {
                "type": "chunk_response", "height": m["height"],
                "index": int(msg.get("index", 0)),
                "root": msg.get("root", ""),
                "data": (b"\xde\xad" * 40).hex()})

    sw_honest = _serving_switch(src, b"\x01" * 32)
    sw_evil = _serving_switch(src, b"\x02" * 32,
                              reactor_cls=EvilChunks)
    new = _fresh_side(tmp_path, src["gen"])
    new["sw"].start()
    connect_switches(sw_evil, new["sw"])
    connect_switches(sw_honest, new["sw"])
    try:
        _wait(lambda: new["ss"].finished.is_set(), 40,
              "restore never concluded")
        assert new["ss"].restored_state is not None
        evil_id = sw_evil.node_info.id
        assert evil_id in new["ss"]._banned
        assert EvilChunks.served >= 1      # it really served bad data
        _wait(lambda: new["bc"].synced, 30, "tail sync never finished")
        assert new["bc"].state.app_hash == src["cs"].state.app_hash or \
            new["block_store"].height() >= \
            src["block_store"].height() - 1
    finally:
        sw_honest.stop()
        sw_evil.stop()
        new["sw"].stop()


def test_forged_manifest_rejected_and_peer_banned(tmp_path):
    """A manifest whose chunk list does not hash to the advertised
    root is refused before a single chunk is requested."""
    src = _build_source(tmp_path)

    class EvilManifest(StateSyncReactor):
        def _serve_manifest(self, peer, msg):
            m = self.snapshot_store.load_manifest(
                int(msg.get("height", 0)))
            if m is None:
                return super()._serve_manifest(peer, msg)
            m = dict(m)
            m["chunks"] = ["00" * 32] * len(m["chunks"])  # truncate/forge
            peer.try_send_obj(STATESYNC_CHANNEL, {
                "type": "manifest_response", "height": m["height"],
                "manifest": m})

    sw_evil = _serving_switch(src, b"\x02" * 32,
                              reactor_cls=EvilManifest)
    new = _fresh_side(tmp_path, src["gen"], give_up_s=6.0)
    new["sw"].start()
    connect_switches(sw_evil, new["sw"])
    try:
        _wait(lambda: new["ss"].finished.is_set(), 40,
              "restore never concluded")
        # only peer lied -> no restore; node falls back to block sync
        assert new["ss"].restored_state is None
        assert sw_evil.node_info.id in new["ss"]._banned
        assert new["gate"].is_set()
    finally:
        sw_evil.stop()
        new["sw"].stop()


def test_snapshot_failing_light_verification_aborts_restore(tmp_path):
    """A snapshot whose payload carries a forged commit passes every
    chunk digest (the peer built it honestly from bad data) but fails
    the light verification at apply time: the restore is aborted, the
    snapshot poisoned, and the node falls back to block replay."""
    src = _build_source(tmp_path, n_blocks=8, interval=4)
    from tendermint_tpu.storage.snapshot import build_payload
    # rebuild the latest snapshot from a payload with zeroed signatures
    h = max(src["snap_store"].list_heights())
    payload = src["snap_store"].assemble_payload(h)
    for p in payload["commit"]["precommits"]:
        if p is not None:
            p["signature"] = "00" * 64
    evil_store = SnapshotStore(str(tmp_path / "evil-snapshots"))
    evil_store.take(h, payload, chunk_size=256)

    sw_evil = _serving_switch(src, b"\x02" * 32, snap_store=evil_store)
    new = _fresh_side(tmp_path, src["gen"], give_up_s=6.0)
    new["sw"].start()
    connect_switches(sw_evil, new["sw"])
    try:
        _wait(lambda: new["ss"].finished.is_set(), 40,
              "restore never concluded")
        assert new["ss"].restored_state is None
        # the poisoned snapshot key is remembered
        assert any(k[0] == h for k in new["ss"]._poisoned)
        # stores untouched: fallback starts from genesis
        assert new["block_store"].height() == 0
        _wait(lambda: new["bc"].synced, 40, "fallback sync never ran")
        assert new["block_store"].height() >= \
            src["block_store"].height() - 1
    finally:
        sw_evil.stop()
        new["sw"].stop()


def test_crash_mid_restore_resumes_from_disk(tmp_path):
    """Kill the restore at statesync.before_apply (all chunks on disk,
    stores untouched) and at statesync.after_restore (stores
    bootstrapped, dir not yet adopted): in both cases a restart's
    resume_pending_restore completes the restore idempotently."""
    src = _build_source(tmp_path, n_blocks=8, interval=4)
    h = max(src["snap_store"].list_heights())
    manifest = src["snap_store"].load_manifest(h)

    for point in ("statesync.before_apply", "statesync.after_restore"):
        tag = point.replace(".", "_")
        # simulate the fetch phase having completed: the restore dir
        # holds the manifest + every chunk (content-addressed files)
        statesync_dir = str(tmp_path / f"{tag}-statesync")
        restore_store = SnapshotStore(statesync_dir)
        os.makedirs(restore_store.dir_for(h))
        import shutil
        for name in os.listdir(src["snap_store"].dir_for(h)):
            shutil.copy(os.path.join(src["snap_store"].dir_for(h), name),
                        os.path.join(restore_store.dir_for(h), name))
        app = KVStoreApp()
        state_store = StateStore(MemDB())
        block_store = BlockStore(MemDB())
        state_store.load_or_genesis(src["gen"])
        local_snaps = SnapshotStore(str(tmp_path / f"{tag}-snapshots"))

        def crash(name):
            raise _Crash(name)

        fail.arm(point, crash)
        from tendermint_tpu.statesync.reactor import apply_restore
        with pytest.raises(_Crash):
            apply_restore(restore_store, manifest, block_store,
                          state_store, local_snaps, app, "ss-net")
        fail.disarm_all()
        # the restore dir is still there (not adopted): resumable
        assert restore_store.load_manifest(h) is not None

        # "restart": a fresh app + the same disk; resume must finish
        app2 = KVStoreApp()
        state = resume_pending_restore(
            statesync_dir, block_store, state_store, local_snaps, app2,
            "ss-net")
        assert state is not None
        assert state.last_block_height == h
        assert block_store.height() == h
        assert block_store.base() == h + 1
        assert state_store.load().last_block_height == h
        assert state_store.latest_snapshot_height() == h
        assert app2.height == h
        assert app2.app_hash == state.app_hash
        # adopted: restore dir gone, snapshot in the local library
        assert restore_store.list_heights() == []
        assert local_snaps.list_heights() == [h]
        # nothing pending anymore
        assert resume_pending_restore(
            statesync_dir, block_store, state_store, local_snaps,
            KVStoreApp(), "ss-net") is None


def test_restore_resumes_partial_chunk_dir(tmp_path):
    """A restore dir already holding SOME verified chunks (a previous
    crash mid-download) only fetches the remainder."""
    src = _build_source(tmp_path, chunk_size=64)
    h = max(src["snap_store"].list_heights())
    manifest = src["snap_store"].load_manifest(h)
    assert len(manifest["chunks"]) >= 3

    new = _fresh_side(tmp_path, src["gen"])
    # pre-seed the restore dir with manifest + half the chunks, plus
    # one TORN chunk file that must be re-fetched, not trusted
    restore_store = SnapshotStore(new["statesync_dir"])
    os.makedirs(restore_store.dir_for(h))
    src_dir = src["snap_store"].dir_for(h)
    import shutil
    shutil.copy(os.path.join(src_dir, "manifest.json"),
                os.path.join(restore_store.dir_for(h), "manifest.json"))
    from tendermint_tpu.storage.snapshot import chunk_name
    half = manifest["chunks"][:len(manifest["chunks"]) // 2]
    for digest in half:
        shutil.copy(os.path.join(src_dir, chunk_name(digest)),
                    os.path.join(restore_store.dir_for(h),
                                 chunk_name(digest)))
    torn = manifest["chunks"][-1]
    with open(os.path.join(restore_store.dir_for(h),
                           chunk_name(torn)), "wb") as f:
        f.write(b"torn")

    sw_src = _serving_switch(src, b"\x01" * 32)
    new["sw"].start()
    connect_switches(sw_src, new["sw"])
    try:
        _wait(lambda: new["ss"].finished.is_set(), 40,
              "restore never concluded")
        assert new["ss"].restored_state is not None
        assert new["ss"].restored_state.last_block_height == h
    finally:
        sw_src.stop()
        new["sw"].stop()


# --------------------------------------------------- chaos acceptance --

@pytest.mark.slow
def test_chaos_with_snapshot_plane_and_crashes_stays_clean(
        tmp_path, monkeypatch):
    """ChaosNet soak with the whole recovery plane ON (interval
    snapshots + pruning on every node) and a crash armed at a snapshot
    fail point mid-run: every invariant check must stay clean and the
    net must keep committing through the crash-restart."""
    monkeypatch.setenv("TM_TPU_SNAPSHOT_INTERVAL", "2")
    monkeypatch.setenv("TM_TPU_SNAPSHOT_KEEP", "2")
    monkeypatch.setenv("TM_TPU_RETAIN_HEIGHTS", "4")
    from tendermint_tpu.chaos.runner import run_chaos
    for point in ("snapshot.before_publish", "snapshot.after_chunk",
                  "prune.mid_range"):
        spec = {
            "drop": 0.02,
            "delay": 0.05,
            "delay_steps": [1, 2],
            "stall_assist": True,
            "crashes": [{"node": 2, "after_height": 2, "point": point,
                         "down_steps": 12}],
        }
        report = run_chaos(
            spec=spec, seed=7,
            workdir=str(tmp_path / point.replace(".", "_")),
            target_height=8, max_steps=500)
        assert report["violations"] == [], (point, report["violations"])
        assert report["faults_injected"].get("crash", 0) >= 1, point
