"""Telemetry subsystem: registry semantics, exposition format, no-op
mode, tracing, the /metrics HTTP route, and the check_metrics lint."""

import http.client
import json
import os
import subprocess
import sys

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry.registry import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- registry --

def test_counter_basics():
    r = Registry()
    c = r.counter("sub_hits_total", "hits")
    c.inc()
    c.inc(2.5)
    assert r.value("sub_hits_total") == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_counter_labels_independent():
    r = Registry()
    c = r.counter("sub_ops_total", "ops", ("kind",))
    c.labels("a").inc()
    c.labels(kind="b").inc(4)
    c.labels("a").inc()
    assert r.value("sub_ops_total", {"kind": "a"}) == 2
    assert r.value("sub_ops_total", {"kind": "b"}) == 4
    assert r.value("sub_ops_total", {"kind": "never"}) is None
    # a labelled family rejects implicit-child ops and wrong labels
    with pytest.raises(ValueError):
        c.inc()
    with pytest.raises(ValueError):
        c.labels("a", "b")
    with pytest.raises(ValueError):
        c.labels(wrong="x")


def test_gauge_set_inc_dec():
    r = Registry()
    g = r.gauge("sub_depth", "depth")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert r.value("sub_depth") == 13


def test_histogram_bucket_semantics():
    r = Registry()
    h = r.histogram("sub_len", "lengths", buckets=(1, 2, 4, 8))
    for v in (0.5, 1, 2, 3, 8, 9):
        h.observe(v)
    out = r.value("sub_len")
    assert out["count"] == 6
    assert out["sum"] == 23.5
    # le buckets are INCLUSIVE upper bounds, cumulative
    assert out["buckets"][1.0] == 2      # 0.5, 1
    assert out["buckets"][2.0] == 3      # + 2
    assert out["buckets"][4.0] == 4      # + 3
    assert out["buckets"][8.0] == 5      # + 8
    assert out["buckets"][float("inf")] == 6  # + 9


def test_duplicate_registration():
    r = Registry()
    a = r.counter("sub_x_total", "x")
    assert r.counter("sub_x_total", "x") is a       # idempotent
    with pytest.raises(ValueError):
        r.gauge("sub_x_total", "x")                 # kind mismatch
    with pytest.raises(ValueError):
        r.counter("sub_x_total", "x", ("l",))       # label mismatch
    r.histogram("sub_h", "h", buckets=(1, 2))
    with pytest.raises(ValueError):
        r.histogram("sub_h", "h", buckets=(1, 2, 3))  # bucket mismatch


def test_name_validation():
    r = Registry()
    for bad in ("", "1x", "Has-Dash", "UPPER", "sp ace"):
        with pytest.raises(ValueError):
            r.counter(bad, "bad")
    with pytest.raises(ValueError):
        r.counter("sub_ok_total", "x", ("0bad",))


def test_noop_mode_records_nothing():
    r = Registry()
    c = r.counter("sub_n_total", "n")
    h = r.histogram("sub_nh", "nh", buckets=(1,))
    lc = r.counter("sub_nl_total", "nl", ("k",))
    c.inc()
    telemetry.set_enabled(False)
    try:
        c.inc(100)
        h.observe(5)
        lc.labels("a").inc()          # returns the shared no-op child
        assert not telemetry.enabled()
    finally:
        telemetry.set_enabled(True)
    assert r.value("sub_n_total") == 1
    assert r.value("sub_nh")["count"] == 0
    assert r.value("sub_nl_total", {"k": "a"}) is None


def test_reset_zeroes_but_keeps_families():
    r = Registry()
    c = r.counter("sub_r_total", "r", ("k",))
    c.labels("a").inc(7)
    r.reset()
    assert r.value("sub_r_total", {"k": "a"}) == 0
    assert "sub_r_total" in r.names()


# ----------------------------------------------------------- exposition --

def test_exposition_golden():
    r = Registry()
    r.counter("app_reqs_total", "Requests served", ("code",))\
        .labels(code="200").inc(3)
    r.gauge("app_depth", "Queue depth").set(2.5)
    h = r.histogram("app_lat_seconds", "Latency", buckets=(0.1, 1))
    h.observe(0.05)
    h.observe(0.5)
    assert r.expose(namespace="ns") == (
        "# HELP ns_app_depth Queue depth\n"
        "# TYPE ns_app_depth gauge\n"
        "ns_app_depth 2.5\n"
        "# HELP ns_app_lat_seconds Latency\n"
        "# TYPE ns_app_lat_seconds histogram\n"
        'ns_app_lat_seconds_bucket{le="0.1"} 1\n'
        'ns_app_lat_seconds_bucket{le="1"} 2\n'
        'ns_app_lat_seconds_bucket{le="+Inf"} 2\n'
        "ns_app_lat_seconds_sum 0.55\n"
        "ns_app_lat_seconds_count 2\n"
        "# HELP ns_app_reqs_total Requests served\n"
        "# TYPE ns_app_reqs_total counter\n"
        'ns_app_reqs_total{code="200"} 3\n')


def test_exposition_escaping():
    r = Registry()
    r.counter("sub_esc_total", 'help with \\ and\nnewline', ("v",))\
        .labels(v='quo"te\\back\nline').inc()
    text = r.expose(namespace="t")
    assert r'# HELP t_sub_esc_total help with \\ and\nnewline' in text
    assert 't_sub_esc_total{v="quo\\"te\\\\back\\nline"} 1' in text


def test_labelless_family_exposes_header_and_zero():
    r = Registry()
    r.counter("sub_zero_total", "never incremented")
    text = r.expose(namespace="tm")
    assert "# TYPE tm_sub_zero_total counter" in text
    assert "tm_sub_zero_total 0" in text


# ------------------------------------------------------------- tracing --

def test_tracer_span_and_instant():
    from tendermint_tpu.telemetry.trace import Tracer
    t = Tracer()
    with t.span("work", height=3):
        pass
    t.instant("mark", round=1)
    t.complete("step", 0.5, 0.75, step="PROPOSE")
    evs = t.events()
    assert [e["ph"] for e in evs] == ["X", "i", "X"]
    assert evs[0]["name"] == "work" and evs[0]["args"] == {"height": 3}
    assert evs[0]["dur"] >= 0
    assert evs[2]["dur"] == pytest.approx(0.25e6)
    ct = t.chrome_trace()
    assert ct["traceEvents"] == evs


def test_tracer_dump_and_ring(tmp_path):
    from tendermint_tpu.telemetry.trace import Tracer
    t = Tracer(capacity=4)
    for i in range(10):
        t.instant(f"e{i}")
    assert len(t.events()) == 4  # ring evicts oldest
    assert t.events()[0]["name"] == "e6"
    p = t.dump(str(tmp_path / "trace.json"))
    with open(p) as f:
        obj = json.load(f)
    assert len(obj["traceEvents"]) == 4
    assert obj["displayTimeUnit"] == "ms"


def test_tracer_disabled_is_noop():
    from tendermint_tpu.telemetry.trace import Tracer
    t = Tracer()
    telemetry.set_enabled(False)
    try:
        with t.span("x"):
            pass
        t.instant("y")
    finally:
        telemetry.set_enabled(True)
    assert t.events() == []


# ------------------------------------------------- instrumented modules --

def _small_commit():
    from tendermint_tpu.types import (PrivKey, Validator, ValidatorSet)
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType
    from tendermint_tpu.types.vote_set import VoteSet
    from tendermint_tpu.models.verifier import BatchVerifier
    privs = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(4)]
    vs = ValidatorSet([Validator(p.pubkey.ed25519, 10) for p in privs])
    by_addr = {p.pubkey.address: p for p in privs}
    bid = BlockID(b"b" * 32, PartSetHeader(1, b"p" * 32))
    pyv = BatchVerifier("python")
    vset = VoteSet("telemetry-chain", 1, 0, VoteType.PRECOMMIT, vs,
                   verifier=pyv)
    for i, val in enumerate(vs.validators):
        v = Vote(val.address, i, 1, 0, 1000, VoteType.PRECOMMIT, bid)
        v.signature = by_addr[val.address].sign(
            v.sign_bytes("telemetry-chain"))
        vset.add_vote(v)
    return vs, bid, vset.make_commit(), pyv


def test_verifier_metrics_after_verify_commit():
    vs, bid, commit, pyv = _small_commit()
    before = telemetry.value("verifier_sigs_total",
                             {"backend": "python"}) or 0
    vs.verify_commit("telemetry-chain", bid, 1, commit, verifier=pyv)
    after = telemetry.value("verifier_sigs_total", {"backend": "python"})
    assert after >= before + 4
    assert telemetry.value("verifier_batch_size")["count"] > 0
    assert telemetry.value("verifier_dispatch_seconds",
                           {"backend": "python"})["count"] > 0


def test_metrics_route_serves_prometheus_text():
    """Acceptance shape: /metrics serves valid exposition including the
    verifier families after a verify_commit, plus the consensus round
    duration family (registered at import)."""
    import tendermint_tpu.consensus.state  # noqa: F401 — registers families
    from tendermint_tpu.rpc.core import RPCEnv, make_server
    vs, bid, commit, pyv = _small_commit()
    vs.verify_commit("telemetry-chain", bid, 1, commit, verifier=pyv)
    server, _core = make_server(RPCEnv())
    host, port = server.serve("127.0.0.1", 0)
    try:
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        assert "# TYPE tm_verifier_batch_size histogram" in body
        assert "tm_verifier_batch_size_bucket" in body
        assert "# TYPE tm_consensus_round_duration_seconds histogram" \
            in body
        assert 'tm_verifier_calls_total{backend="python"}' in body
        # every non-comment line is `name{labels} value`
        for line in body.splitlines():
            if not line or line.startswith("#"):
                continue
            name_part, _, value = line.rpartition(" ")
            assert name_part and float(value) is not None
    finally:
        server.stop()


def test_env_off_makes_call_sites_noop():
    """TM_TPU_TELEMETRY=off: instrumented paths record nothing, and a
    config asking for telemetry=True cannot re-enable it."""
    code = (
        "from tendermint_tpu import telemetry\n"
        "assert not telemetry.enabled()\n"
        "telemetry.configure(enabled=True)  # config must NOT win\n"
        "assert not telemetry.enabled()\n"
        "from tendermint_tpu.models.verifier import BatchVerifier\n"
        "from tendermint_tpu.types.keys import PrivKey\n"
        "v = BatchVerifier('python')\n"
        "k = PrivKey.generate(b'\\x01' * 32)\n"
        "assert v.verify_one(k.pubkey.ed25519, b'm', k.sign(b'm'))\n"
        "assert telemetry.value('verifier_batch_size')['count'] == 0\n"
        "assert telemetry.value('verifier_calls_total',\n"
        "                       {'backend': 'python'}) is None\n"
        "print('NOOP-OK')\n"
    )
    env = dict(os.environ, TM_TPU_TELEMETRY="off", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "NOOP-OK" in out.stdout


def test_namespace_configurable():
    telemetry.configure(namespace="acme")
    try:
        assert "acme_verifier_batch_size" in telemetry.expose()
    finally:
        telemetry.configure(namespace="tm")
    with pytest.raises(ValueError):
        telemetry.configure(namespace="Bad Namespace")


def test_consensus_round_metrics_after_committed_heights():
    """Acceptance: after a small in-process consensus run, the round
    duration histogram, step counters and height gauge have samples and
    the trace ring holds the per-step timeline."""
    import tests.test_consensus as tc

    dur0 = telemetry.value("consensus_round_duration_seconds")["count"]
    commits0 = telemetry.value("consensus_commits_total") or 0
    ev0 = len(telemetry.TRACER.events())
    nodes, _ = tc.make_net(1)
    nodes[0].start()
    tc.run_until_height(nodes, 2)
    dur1 = telemetry.value("consensus_round_duration_seconds")["count"]
    assert dur1 >= dur0 + 2                      # one per committed round
    assert telemetry.value("consensus_commits_total") >= commits0 + 2
    assert telemetry.value("consensus_height") >= 2
    assert telemetry.value("consensus_steps_total",
                           {"step": "COMMIT"}) >= 2
    names = {e["name"] for e in telemetry.TRACER.events()[ev0:]}
    assert "cs:finalize_commit" in names
    assert any(n.startswith("cs:") and n != "cs:finalize_commit"
               for n in names)
    assert "tm_consensus_round_duration_seconds_sum" in telemetry.expose()


# ------------------------------------------------------- check_metrics --

def test_check_metrics_lint_passes():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_metrics.py")],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout
