"""Tx indexer tests (models state/txindex/kv/kv_test.go) + the tx /
tx_search RPC routes over a live node."""

import hashlib
import time

import pytest

from tendermint_tpu.state.txindex import IndexerService, KVTxIndexer, NullTxIndexer
from tendermint_tpu.storage import MemDB


def entry(height, index, tx, tags=None, code=0):
    return {"height": height, "index": index, "tx": tx,
            "result": {"code": code}, "tags": dict(tags or {})}


def test_kv_index_get_by_hash():
    idx = KVTxIndexer(MemDB(), index_all_tags=True)
    idx.add_batch([entry(1, 0, b"tx-one", {"account.name": "alice"})])
    h = hashlib.sha256(b"tx-one").digest()
    rec = idx.get(h)
    assert rec["height"] == 1 and rec["tx"] == b"tx-one"
    assert idx.get(b"\x00" * 32) is None


def test_kv_search_by_tag_and_hash():
    idx = KVTxIndexer(MemDB(), index_all_tags=True)
    idx.add_batch([
        entry(1, 0, b"a", {"account.name": "alice"}),
        entry(1, 1, b"b", {"account.name": "bob"}),
        entry(2, 0, b"c", {"account.name": "alice"}),
    ])
    res = idx.search("account.name = 'alice'")
    assert [r["tx"] for r in res] == [b"a", b"c"]  # height order
    h = hashlib.sha256(b"b").digest()
    res = idx.search(f"tx.hash = '{h.hex()}'")
    assert [r["tx"] for r in res] == [b"b"]


def test_kv_search_height_ranges():
    idx = KVTxIndexer(MemDB(), index_all_tags=True)
    idx.add_batch([entry(h, 0, b"tx%d" % h) for h in range(1, 8)])
    assert [r["height"] for r in idx.search("tx.height > 5")] == [6, 7]
    assert [r["height"] for r in idx.search("tx.height <= 2")] == [1, 2]
    assert [r["height"]
            for r in idx.search("tx.height > 2 AND tx.height < 5")] == [3, 4]


def test_kv_selective_tags():
    idx = KVTxIndexer(MemDB(), index_tags=["app.key"])
    idx.add_batch([entry(1, 0, b"x", {"app.key": "k1", "secret": "v"})])
    assert len(idx.search("app.key = 'k1'")) == 1
    assert idx.search("secret = 'v'") == []


def test_null_indexer():
    idx = NullTxIndexer()
    idx.add_batch([entry(1, 0, b"z")])
    assert idx.get(hashlib.sha256(b"z").digest()) is None
    assert idx.search("tx.height > 0") == []


def test_indexer_service_feeds_from_event_bus():
    from tendermint_tpu.abci.types import ResultDeliverTx
    from tendermint_tpu.types.events import EventBus
    bus = EventBus()
    idx = KVTxIndexer(MemDB(), index_all_tags=True)
    svc = IndexerService(idx, bus)
    svc.start()
    bus.publish_tx(5, 0, b"evtx", ResultDeliverTx(tags={"k": "v"}))
    deadline = time.monotonic() + 5
    h = hashlib.sha256(b"evtx").digest()
    while time.monotonic() < deadline and idx.get(h) is None:
        time.sleep(0.02)
    rec = idx.get(h)
    assert rec is not None and rec["height"] == 5
    assert idx.search("k = 'v'")
    svc.stop()


def test_tx_rpc_routes_live():
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.rpc import JSONRPCClient
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
    from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator

    key = PrivKey.generate(b"\x0b" * 32)
    gen = GenesisDoc(chain_id="txi-test", genesis_time_ns=1,
                     validators=[GenesisValidator(key.pubkey.ed25519, 10)])
    cfg = make_test_config("")
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.tx_index.index_all_tags = True
    node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(key)),
                in_memory=True, with_rpc=True)
    node.start()
    try:
        host, port = node.rpc_address
        c = JSONRPCClient(f"http://{host}:{port}")
        res = c.call("broadcast_tx_commit", tx=b"find=me")
        tx_hash = hashlib.sha256(b"find=me").digest()
        # give the indexer service a beat to drain the event
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                rec = c.call("tx", hash=tx_hash, prove=True)
                break
            except Exception:
                time.sleep(0.05)
        else:
            pytest.fail("tx never indexed")
        assert bytes.fromhex(rec["tx"]) == b"find=me"
        assert rec["proof"]["total"] >= 1
        found = c.call("tx_search", query="app.key = 'find'")
        assert found["total_count"] >= 1
        byh = c.call("tx_search", query=f"tx.height = {rec['height']}")
        assert byh["total_count"] >= 1
    finally:
        node.stop()
