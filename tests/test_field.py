"""Differential tests: JAX limb field arithmetic vs Python bigints."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from tendermint_tpu.ops import field as fe

P = fe.P
rng = random.Random(1234)

EDGE = [0, 1, 2, 19, P - 1, P - 2, P + 1 - 1, (1 << 255) - 1, 1 << 254, P // 2]


def rand_vals(n):
    return [rng.randrange(0, P) for _ in range(n)]


def as_batch(vals):
    return jnp.asarray(fe.batch_to_limbs(vals))


def check_batch(limbs, expected):
    got = [fe.from_limbs(np.asarray(limbs)[i]) % P for i in range(len(expected))]
    want = [e % P for e in expected]
    assert got == want


def test_roundtrip_to_from_limbs():
    for v in EDGE + rand_vals(20):
        assert fe.from_limbs(fe.to_limbs(v)) == v % P


def test_add_sub_mul():
    a_vals = EDGE + rand_vals(30)
    b_vals = rand_vals(len(a_vals))
    a, b = as_batch(a_vals), as_batch(b_vals)
    check_batch(fe.add(a, b), [x + y for x, y in zip(a_vals, b_vals)])
    check_batch(fe.sub(a, b), [x - y for x, y in zip(a_vals, b_vals)])
    check_batch(fe.mul(a, b), [x * y for x, y in zip(a_vals, b_vals)])
    check_batch(fe.square(a), [x * x for x in a_vals])
    check_batch(fe.neg(a), [-x for x in a_vals])


def test_mul_small():
    a_vals = EDGE + rand_vals(10)
    a = as_batch(a_vals)
    check_batch(fe.mul_small(a, 121666), [x * 121666 for x in a_vals])


def test_repeated_ops_stay_exact():
    # chains of ops exercise normalization invariants
    a_vals = rand_vals(8)
    b_vals = rand_vals(8)
    a, b = as_batch(a_vals), as_batch(b_vals)
    x = fe.mul(fe.add(a, b), fe.sub(a, b))
    expected = [(av + bv) * (av - bv) for av, bv in zip(a_vals, b_vals)]
    check_batch(x, expected)
    y = fe.mul(x, x)
    check_batch(y, [e * e for e in expected])


def test_inv():
    vals = [1, 2, P - 1] + rand_vals(10)
    a = as_batch(vals)
    check_batch(fe.inv(a), [pow(v, P - 2, P) for v in vals])
    # inv(0) == 0 by convention
    z = as_batch([0])
    assert fe.from_limbs(np.asarray(fe.inv(z))[0]) == 0


def raw_limbs(x: int) -> np.ndarray:
    """Encode WITHOUT reducing mod P (so values >= p actually reach canonical)."""
    assert 0 <= x < 1 << 260
    out = np.zeros(fe.NLIMBS, dtype=np.int32)
    for i in range(fe.NLIMBS):
        out[i] = x & fe.MASK
        x >>= fe.LIMB_BITS
    return out


def test_canonical_and_compare():
    vals = [0, 1, P - 1, P, P + 1, 2 * P - 1, 2 * P, (1 << 255) - 19,
            (1 << 255) - 1, (1 << 256) - 1, (1 << 260) - 1]
    a = jnp.asarray(np.stack([raw_limbs(v) for v in vals]))
    c = np.asarray(fe.canonical(a))
    for i, v in enumerate(vals):
        assert fe.from_limbs(c[i]) == v % P, v
    assert list(np.asarray(fe.is_zero(a))) == [v % P == 0 for v in vals]


def test_bytes_roundtrip():
    vals = EDGE + rand_vals(10)
    a = as_batch(vals)
    by = np.asarray(fe.to_bytes(a))
    for i, v in enumerate(vals):
        assert int.from_bytes(by[i].tobytes(), "little") == v % P
    limbs, high = fe.from_bytes(jnp.asarray(by))
    check_batch(limbs, vals)
    assert not np.asarray(high).any()
    # high bit detection
    raw = bytearray((P - 5).to_bytes(32, "little"))
    raw[31] |= 0x80
    limbs2, high2 = fe.from_bytes(jnp.asarray(np.frombuffer(bytes(raw), np.uint8)))
    assert int(np.asarray(high2)) == 1
    assert fe.from_limbs(np.asarray(limbs2)) == P - 5


def test_sqrt_ratio():
    # squares have roots; non-squares flagged
    vals = rand_vals(8)
    squares = [v * v % P for v in vals]
    u = as_batch(squares)
    v = as_batch([1] * len(squares))
    r, ok = fe.sqrt_ratio(u, v)
    assert np.asarray(ok).all()
    r_ints = [fe.from_limbs(np.asarray(r)[i]) for i in range(len(squares))]
    for ri, sq in zip(r_ints, squares):
        assert ri * ri % P == sq
    # a known non-residue: 2 is a non-square mod p (p ≡ 5 mod 8 -> 2 is non-QR)
    nonsq = 2
    assert pow(nonsq, (P - 1) // 2, P) == P - 1
    _, ok2 = fe.sqrt_ratio(as_batch([nonsq]), as_batch([1]))
    assert not np.asarray(ok2).any()
