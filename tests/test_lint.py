"""tmlint: engine + the five checkers + pragmas + lockwatch + the
tree-wide zero-findings gate (ISSUE 5).

The fixture tests feed deliberately-broken snippets through the same
engine the real run uses (run_source with a chosen repo-relative path,
so dir-scoped checkers fire); the tree gate runs the full scan set and
is what keeps the repository at zero findings from inside tier-1.
"""

import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tendermint_tpu.analysis import Engine, run_tree  # noqa: E402
from tendermint_tpu.analysis.checkers import all_checkers  # noqa: E402
from tendermint_tpu.analysis.engine import (  # noqa: E402
    parse_guard_annotations,
)


def lint_source(src, rel="tendermint_tpu/consensus/fixture.py",
                finish=False):
    eng = Engine(all_checkers(), root=REPO)
    found = eng.run_source(src, rel=rel)
    if finish:
        eng.finish()
        return eng.findings
    return found


def ids(findings):
    return sorted({f.checker for f in findings})


# ---------------------------------------------------------- determinism --

def test_determinism_flags_wallclock_and_random():
    src = (
        "import time, random\n"
        "def ts():\n"
        "    return time.time_ns()\n"
        "def jitter():\n"
        "    return random.random()\n"
    )
    found = lint_source(src)
    assert ids(found) == ["determinism"]
    assert len(found) == 2
    assert any("time.time_ns" in f.message for f in found)
    assert any("random.random" in f.message for f in found)


def test_determinism_flags_bare_imports_and_set_iteration():
    src = (
        "from time import time\n"
        "def ts():\n"
        "    return time()\n"
        "def order(xs):\n"
        "    for x in set(xs):\n"
        "        yield x\n"
    )
    found = lint_source(src, rel="tendermint_tpu/types/fixture.py")
    assert len(found) == 2
    assert any("imported from time" in f.message for f in found)
    assert any("set expression" in f.message for f in found)


def test_determinism_allows_monotonic_seeded_sorted():
    src = (
        "import random, time\n"
        "from tendermint_tpu.utils import clock\n"
        "def good(xs):\n"
        "    t0 = time.monotonic(); tp = time.perf_counter()\n"
        "    ts = clock.now_ns()\n"
        "    rng = random.Random(7); v = rng.random()\n"
        "    for x in sorted(set(xs)):\n"
        "        pass\n"
        "    return t0, tp, ts, v\n"
    )
    assert lint_source(src) == []


def test_determinism_scoped_to_consensus_dirs():
    src = "import time\nts = time.time()\n"
    assert lint_source(src, rel="tendermint_tpu/rpc/fixture.py") == []
    assert len(lint_source(src, rel="tendermint_tpu/ops/fixture.py")) == 1
    assert len(lint_source(src, rel="tendermint_tpu/state/fx.py")) == 1


# ------------------------------------------------------ lock-discipline --

LOCKED_CLASS = (
    "import threading\n"
    "class Box:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._items = []  #: guarded_by _lock\n"
    "%s"
)


def test_locks_flags_unguarded_access():
    src = LOCKED_CLASS % (
        "    def bad(self):\n"
        "        return len(self._items)\n"
    )
    found = lint_source(src)
    assert len(found) == 1 and found[0].checker == "lock-discipline"
    assert "Box._items" in found[0].message


def test_locks_allows_with_block_init_and_locked_suffix():
    src = LOCKED_CLASS % (
        "    def good(self):\n"
        "        with self._lock:\n"
        "            return self._drain_locked()\n"
        "    def _drain_locked(self):\n"
        "        out = list(self._items)\n"
        "        self._items = []\n"
        "        return out\n"
    )
    assert lint_source(src) == []


def test_locks_flags_store_and_reports_verb():
    src = LOCKED_CLASS % (
        "    def bad(self):\n"
        "        self._items = []\n"
    )
    found = lint_source(src)
    assert len(found) == 1 and "written" in found[0].message


def test_locks_thread_daemon_rule():
    bad = (
        "import threading\n"
        "def spawn(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
    )
    found = lint_source(bad)
    assert len(found) == 1 and found[0].checker == "lock-discipline"
    good_daemon = bad.replace("Thread(target=fn)",
                              "Thread(target=fn, daemon=True)")
    assert lint_source(good_daemon) == []
    good_joined = bad + "    t.join()\n"
    assert lint_source(good_joined) == []


def test_parse_guard_annotations():
    anns = parse_guard_annotations(LOCKED_CLASS % "")
    assert [(a.cls, a.attr, a.lock) for a in anns] == \
        [("Box", "_items", "_lock")]


# -------------------------------------------------------- knob-registry --

def test_knobs_flags_uncataloged_name():
    src = "import os\nv = os.environ.get('TM_TPU_BOGUS_KNOB')\n"
    found = lint_source(src)
    assert len(found) == 1 and found[0].checker == "knob-registry"
    assert "TM_TPU_BOGUS_KNOB" in found[0].message


def test_knobs_allows_cataloged_and_exempts_catalog_file():
    ok = "import os\nv = os.environ.get('TM_TPU_TELEMETRY')\n"
    assert lint_source(ok) == []
    bogus = "NAMES = ['TM_TPU_NOT_REAL']\n"
    assert lint_source(bogus,
                       rel="tendermint_tpu/utils/knobs.py") == []
    assert len(lint_source(bogus)) == 1


# ---------------------------------------------------- exception-hygiene --

def test_exceptions_flags_silent_broad_in_loop():
    src = (
        "def pump(q):\n"
        "    while True:\n"
        "        try:\n"
        "            q.get()\n"
        "        except Exception:\n"
        "            pass\n"
    )
    found = lint_source(src)
    assert len(found) == 1 and found[0].checker == "exception-hygiene"


def test_exceptions_allows_logged_narrow_or_unlooped():
    logged = (
        "def pump(q, log):\n"
        "    while True:\n"
        "        try:\n"
        "            q.get()\n"
        "        except Exception as e:\n"
        "            log.error('pump failed', err=repr(e))\n"
    )
    narrow = (
        "import queue\n"
        "def pump(q):\n"
        "    while True:\n"
        "        try:\n"
        "            q.get()\n"
        "        except queue.Empty:\n"
        "            continue\n"
    )
    unlooped = (
        "def close(conn):\n"
        "    try:\n"
        "        conn.close()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    for src in (logged, narrow, unlooped):
        assert lint_source(src) == []


# ---------------------------------------------------- ambient-singleton --

def test_ambient_flags_global_rebind():
    """ISSUE 15 ratchet: a module-level name a function rebinds via
    `global` is an ambient process singleton — a finding unless
    blessed in analysis/checkers/ambient.py."""
    src = (
        "_default = None\n"
        "def get_default():\n"
        "    global _default\n"
        "    if _default is None:\n"
        "        _default = object()\n"
        "    return _default\n"
    )
    found = lint_source(src)
    assert ids(found) == ["ambient-singleton"]
    assert len(found) == 1 and found[0].line == 1
    assert "global" in found[0].message


def test_ambient_flags_mutated_module_container():
    src = (
        "_registry = {}\n"
        "def register(name, fn):\n"
        "    _registry[name] = fn\n"
        "_order = []\n"
        "def push(x):\n"
        "    _order.append(x)\n"
    )
    found = lint_source(src)
    assert ids(found) == ["ambient-singleton"]
    assert sorted(f.line for f in found) == [1, 4]


def test_ambient_allows_readonly_tables_locals_and_blessed():
    # read-only import-time lookup tables, function locals, class
    # attributes and constant tuples are NOT ambient singletons
    clean = (
        "_LEVELS = {'debug': 10, 'info': 20}\n"
        "_IDX = {s: i for i, s in enumerate(('a', 'b'))}\n"
        "NAMES = ('x', 'y')\n"
        "class Reg:\n"
        "    table = {}\n"
        "    def put(self, k, v):\n"
        "        self.table[k] = v\n"
        "def lookup(name):\n"
        "    cache = {}\n"
        "    cache[name] = _LEVELS.get(name)\n"
        "    return cache[name]\n"
    )
    assert lint_source(clean) == []
    # a blessed catalog entry stays quiet at its recorded path
    blessed = (
        "_default = None\n"
        "def default_verifier():\n"
        "    global _default\n"
        "    _default = _default or object()\n"
        "    return _default\n"
    )
    assert lint_source(
        blessed, rel="tendermint_tpu/models/verifier.py") == []
    # ...but the SAME code in a new module is a finding (the ratchet)
    assert len(lint_source(
        blessed, rel="tendermint_tpu/shard/newmod.py")) == 1


def test_ambient_pragma_suppresses_at_binding():
    src = (
        "_cache = {}  # tmlint: allow(ambient-singleton): bounded "
        "LRU, reset() in tests\n"
        "def put(k, v):\n"
        "    _cache[k] = v\n"
    )
    assert lint_source(src) == []


# -------------------------------------------------------------- metrics --

def test_metrics_checker_flags_bad_family():
    """The fifth checker on a deliberately-broken fixture: a counter in
    no known subsystem and without the _total suffix produces findings
    (and the clean registry passes — the tree gate relies on it)."""
    from tendermint_tpu import telemetry
    from tendermint_tpu.analysis.checkers import metrics
    name = "bogus_subsystem_thing"
    telemetry.REGISTRY.counter(name, "deliberately broken fixture")
    try:
        found = metrics.run()
        msgs = [f.message for f in found]
        assert any("not namespaced" in m and name in m for m in msgs)
        assert any("_total" in m and name in m for m in msgs)
        assert all(f.checker == "metrics" for f in found)
    finally:
        with telemetry.REGISTRY._lock:
            del telemetry.REGISTRY._families[name]
    assert metrics.run() == []


# --------------------------------------------------------------- pragma --

def test_pragma_suppresses_with_justification():
    src = (
        "import time\n"
        "# tmlint: allow(determinism): fixture needs a real clock\n"
        "ts = time.time()\n"
    )
    assert lint_source(src, finish=True) == []


def test_pragma_same_line_works_too():
    src = ("import time\n"
           "ts = time.time()  "
           "# tmlint: allow(determinism): fixture clock\n")
    assert lint_source(src, finish=True) == []


def test_pragma_without_justification_is_a_finding():
    src = (
        "import time\n"
        "ts = time.time()  # tmlint: allow(determinism)\n"
    )
    found = lint_source(src, finish=True)
    assert ids(found) == ["pragma"]
    assert "justification" in found[0].message


def test_stale_and_unknown_pragmas_are_findings():
    stale = "x = 1  # tmlint: allow(determinism): nothing here\n"
    found = lint_source(stale, finish=True)
    assert ids(found) == ["pragma"] and "stale" in found[0].message
    unknown = "x = 1  # tmlint: allow(nonesuch): misspelled\n"
    found = lint_source(unknown, finish=True)
    assert ids(found) == ["pragma"] and "no known checker" in \
        found[0].message


# ------------------------------------------------------------ the tree --

def test_tree_is_clean_with_pragma_budget():
    """THE gate: the whole scan set at zero findings, <= 15 pragmas,
    every pragma justified (pragma hygiene runs inside). The budget
    went 10 -> 15 with the taint checker (ISSUE 20): five honest
    suppressions for observe-only fan-out, id()-keyed compile caches
    and the kvstore test fault hook."""
    findings, pragmas, n_files = run_tree(REPO)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert n_files > 100
    assert len(pragmas) <= 15
    assert all(p.justification for p in pragmas)


def test_knobs_md_matches_catalog():
    from tendermint_tpu.utils import knobs
    with open(os.path.join(REPO, "docs", "knobs.md"),
              encoding="utf-8") as f:
        assert f.read() == knobs.knobs_md(), \
            "docs/knobs.md drifted — python scripts/lint.py --knobs-md"


def test_lint_cli_passes_on_tree():
    """scripts/lint.py exits 0 (AST + knob drift; --no-metrics keeps
    this test light — the metrics half runs via check_metrics in
    test_telemetry and in the committed LINT_report.json)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--no-metrics"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lint: OK" in r.stdout


def test_lint_report_is_committed_and_clean():
    import json
    with open(os.path.join(REPO, "LINT_report.json"),
              encoding="utf-8") as f:
        rep = json.load(f)
    assert rep["clean"] is True
    assert rep["findings"] == []
    assert rep["files_scanned"] > 100
    assert "metrics" in rep["checkers"]
    assert "taint" in rep["checkers"]
    assert rep["taint"]["findings"] == 0
    assert rep["lint_seconds"] > 0


# ---------------------------------------------------------- knobs/clock --

def test_knob_helpers_env_wins_over_config(monkeypatch):
    from tendermint_tpu.utils import knobs
    monkeypatch.delenv("TM_TPU_COALESCE", raising=False)
    assert knobs.knob_str("TM_TPU_COALESCE", config="on") == "on"
    assert knobs.knob_str("TM_TPU_COALESCE", default="auto") == "auto"
    monkeypatch.setenv("TM_TPU_COALESCE", "OFF")
    assert knobs.knob_str("TM_TPU_COALESCE", config="on") == "off"
    monkeypatch.setenv("TM_TPU_AUTO_THRESHOLD", "7")
    assert knobs.knob_int("TM_TPU_AUTO_THRESHOLD", config=3) == 7
    monkeypatch.delenv("TM_TPU_AUTO_THRESHOLD")
    assert knobs.knob_int("TM_TPU_AUTO_THRESHOLD", config=3) == 3
    for v in ("off", "0", "false", "no", "none", "disabled", "OFF"):
        monkeypatch.setenv("TM_TPU_LOCKCHECK", v)
        assert knobs.knob_bool("TM_TPU_LOCKCHECK", default=True) is False
    monkeypatch.setenv("TM_TPU_LOCKCHECK", "on")
    assert knobs.knob_bool("TM_TPU_LOCKCHECK") is True
    # NO_* contract: any non-blank value counts as set, even "0"
    monkeypatch.setenv("TM_TPU_NO_NATIVE", "0")
    assert knobs.knob_set("TM_TPU_NO_NATIVE") is True
    monkeypatch.delenv("TM_TPU_NO_NATIVE")
    assert knobs.knob_set("TM_TPU_NO_NATIVE") is False


def test_knob_helpers_reject_uncataloged_names():
    from tendermint_tpu.utils import knobs
    with pytest.raises(KeyError):
        knobs.knob_raw("TM_TPU_TYPO")


def test_clock_source_substitution():
    from tendermint_tpu.utils import clock
    try:
        clock.set_source(lambda: 12345)
        assert clock.now_ns() == 12345
        from tendermint_tpu.types.vote import now_ns
        assert now_ns() == 12345
    finally:
        clock.set_source(None)
    a = clock.now_ns()
    assert isinstance(a, int) and a > 1e18  # real ns epoch again


# ------------------------------------------------------------ lockwatch --

@pytest.fixture
def watch():
    from tendermint_tpu.analysis import lockwatch
    lockwatch.install()
    lockwatch.clear()
    yield lockwatch
    lockwatch.uninstall()
    lockwatch.clear()


def test_lockwatch_detects_abba_inversion(watch):
    A = watch.make_lock(site="fixture.py:A")
    B = watch.make_lock(site="fixture.py:B")

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass

    for fn in (ab, ba):  # serialized: records the inversion, no hang
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join()
    cys = watch.cycles()
    assert cys == [["fixture.py:A", "fixture.py:B"]]
    rep = watch.report()
    assert rep["cycles"] == cys and len(rep["edges"]) == 2


def test_lockwatch_consistent_order_is_clean(watch):
    A = watch.make_lock(site="fixture.py:A")
    B = watch.make_lock(site="fixture.py:B")
    for _ in range(3):
        with A:
            with B:
                pass
    assert watch.cycles() == []


def test_lockwatch_condition_wait_keeps_held_set_honest(watch):
    cond = threading.Condition(watch.make_lock("RLock", "fixture.py:C"))
    other = watch.make_lock(site="fixture.py:D")
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time
    time.sleep(0.1)
    # while the waiter sleeps its lock must NOT count as held — taking
    # `other` under it would otherwise fabricate a C->D edge
    with cond:
        cond.notify_all()
    t.join(5)
    assert hits == [1]
    with other:
        pass
    assert watch.cycles() == []


def test_lockwatch_guarded_attr_cross_thread_violation(watch):
    import numpy as np

    from tendermint_tpu.models.coalescer import DispatchCoalescer
    assert watch.watch_annotated(
        ("tendermint_tpu.models.coalescer",)) >= 4
    c = DispatchCoalescer(
        lambda items: (lambda: np.zeros(len(items), bool)))
    resolve = c.submit([1, 2])
    assert list(resolve()) == [False, False]
    c.close()
    # the dispatcher thread touches _queue/_closed under _cond: clean
    assert watch.report()["attr_violations"] == []

    def poke():  # second thread, no lock: the race the watch exists for
        _ = c._closed

    t = threading.Thread(target=poke, daemon=True)
    t.start()
    t.join()
    viol = watch.report()["attr_violations"]
    assert viol and viol[0]["attr"] == "_closed" and \
        viol[0]["lock"] == "_cond"


def test_lockwatch_uninstall_restores_primitives():
    from tendermint_tpu.analysis import lockwatch
    lockwatch.install()
    lockwatch.uninstall()
    assert threading.Lock is lockwatch._real_Lock
    assert threading.RLock is lockwatch._real_RLock


# -------------------------------------------- chaos as a race harness --

def test_chaos_smoke_under_lockcheck(monkeypatch):
    """ISSUE 5 acceptance: the tier-1 chaos smoke with
    TM_TPU_LOCKCHECK=on reports zero acquisition-order cycles (and no
    guarded-attr races) across a real multi-node consensus run."""
    monkeypatch.setenv("TM_TPU_LOCKCHECK", "on")
    from tendermint_tpu.analysis import lockwatch
    lockwatch.clear()
    try:
        from tendermint_tpu.chaos.runner import SMOKE_SPEC, run_chaos
        r = run_chaos(spec=SMOKE_SPEC, seed=7, target_height=4,
                      max_steps=400)
        assert r["violations"] == []
        lw = r["lockwatch"]
        assert lw["locks_watched"] > 50      # the watch really ran
        assert lw["edges"]                   # and saw real nesting
        assert lw["cycles"] == []
        assert lw["attr_violations"] == []
    finally:
        lockwatch.uninstall()
        lockwatch.clear()


# ------------------------------------------- regression: mconn fixes --

def test_mconn_send_refuses_after_stop():
    """Regression for the lock-discipline fix: the _stopped checks in
    send/try_send moved under _cond — semantics must hold (no sends
    accepted after stop, running flips false)."""
    from tendermint_tpu.p2p.conn.mconn import (ChannelDescriptor,
                                               MConnection)

    class _NullLink:
        def write(self, b):
            return len(b)

        def read(self):
            return b""

        def close(self):
            pass

    mc = MConnection(_NullLink(), [ChannelDescriptor(0x01)],
                     on_receive=lambda ch, msg: None)
    assert mc.running
    assert mc.try_send(0x01, b"x")
    mc.stop()
    assert not mc.running
    assert mc.send(0x01, b"y", timeout=0.05) is False
    assert mc.try_send(0x01, b"y") is False
