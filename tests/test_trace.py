"""Causal tracing plane (telemetry/causal.py + telemetry/merge.py):
wire-format equivalence with TM_TPU_TRACE off, cross-node trace-id
propagation over a real 2-node TCP net, ring cap + drop accounting,
stall-detector flight recorder, clock alignment on synthetic skewed
inputs, attribution table, span-name catalog lint, RPC/debug surface,
and the keepalive RTT sample the merger cross-checks against."""

import json
import socket
import time
import urllib.request

import pytest

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry import causal, merge
from tendermint_tpu.telemetry import trace as ttrace
from tendermint_tpu.types import encoding


@pytest.fixture(autouse=True)
def _trace_reset(monkeypatch):
    """The causal plane is process-global state (ring, node id,
    configure snapshot); every test starts from the off/empty state."""
    monkeypatch.delenv("TM_TPU_TRACE", raising=False)
    causal.configure("off")
    causal.clear()
    causal.set_capacity(None)
    causal.set_node("")
    causal.set_rtt_provider(None)
    yield
    causal.configure("off")
    causal.clear()
    causal.set_capacity(None)
    causal.set_node("")
    causal.set_rtt_provider(None)


# the envelope kinds the reactors stamp (consensus DATA/VOTE/STATE
# channels + mempool tx gossip), in their exact PR 7 wire shapes
_ENVELOPES = [
    {"type": "proposal", "proposal": {"height": 7, "round": 0,
                                      "block_parts_header":
                                          {"total": 3, "hash": "aa"}}},
    {"type": "block_part", "height": 7, "round": 0,
     "part": {"index": 1, "bytes": "00ff", "proof": []}},
    {"type": "vote", "vote": {"height": 7, "round": 0, "type": 1,
                              "validator_index": 2}},
    {"type": "new_round_step", "height": 7, "round": 0, "step": 3,
     "last_commit_round": 0},
    {"type": "has_vote", "height": 7, "round": 0, "vote_type": 1,
     "index": 2},
    {"type": "txs", "txs": ["aabb", "ccdd"]},
]


def test_wire_bytes_identical_when_off():
    """TM_TPU_TRACE off: stamp() must return the envelope object
    UNTOUCHED — encoded wire bytes byte-for-byte the untraced format
    for every stamped message kind."""
    assert not causal.enabled()
    for msg in _ENVELOPES:
        baseline = encoding.cdumps(msg)
        out = causal.stamp(msg, 7, 0)
        assert out is msg, msg["type"]
        assert "tr" not in msg
        assert encoding.cdumps(out) == baseline, msg["type"]
        # receive side: take() on an untraced envelope is a no-op
        before = dict(msg)
        assert causal.take(msg, msg["type"]) is None
        assert msg == before


def test_stamp_take_roundtrip_on():
    causal.configure("on")
    causal.set_node("origin-node")
    msg = dict(_ENVELOPES[2])
    out = causal.stamp(msg, 7, 1)
    assert out["tr"][0] == "7.1" and out["tr"][1] == "origin-node"
    assert isinstance(out["tr"][2], int)
    # the receiver pops the stamp (the state machine and its WAL see
    # the untraced shape) and records the link span
    causal.set_node("recv-node")
    causal.take(out, "vote")
    assert "tr" not in out
    spans = causal.dump()["spans"]
    assert len(spans) == 1
    ev = spans[0]
    assert ev["n"] == "p2p.recv" and ev["h"] == 7 and ev["r"] == 1
    assert ev["a"]["origin"] == "origin-node"
    assert ev["a"]["sent"] <= ev["t"]


def test_mempool_kind_maps_to_mempool_recv():
    causal.configure("on")
    msg = causal.stamp(dict(_ENVELOPES[5]), 4)
    causal.take(msg, "txs")
    assert causal.dump()["spans"][0]["n"] == "mempool.recv"


def test_span_catalog_enforced_at_record():
    causal.configure("on")
    with pytest.raises(ValueError):
        causal.record("not.a.declared.span", 1)
    # declared names record fine, spans measure a duration
    with causal.span("apply", 3, txs=10):
        time.sleep(0.01)
    ev = causal.dump()["spans"][-1]
    assert ev["n"] == "apply" and ev["d"] >= 5_000_000


def test_causal_ring_cap_and_drop_counter():
    causal.configure("on")
    causal.set_capacity(10)
    before = telemetry.value("trace_events_dropped_total") or 0.0
    for i in range(25):
        causal.point("commit", i + 1)
    d = causal.dump()
    assert d["events"] == 10
    # oldest rolled off; the newest height survives
    assert d["spans"][-1]["h"] == 25
    after = telemetry.value("trace_events_dropped_total") or 0.0
    assert after - before == 15


def test_tracer_ring_cap_regression():
    """PR 1 Tracer satellite: explicit cap + drop accounting (was a
    silent deque(maxlen) eviction)."""
    t = ttrace.Tracer(capacity=5)
    before = telemetry.value("trace_events_dropped_total") or 0.0
    for i in range(8):
        t.instant(f"e{i}")
    assert len(t.events()) == 5
    assert t.dropped == 3
    assert (telemetry.value("trace_events_dropped_total") or 0.0) \
        - before == 3
    # the survivors are the NEWEST five
    assert [e["name"] for e in t.events()] == \
        [f"e{i}" for i in range(3, 8)]


def test_stall_detector_fires_once_per_episode_and_rearms():
    causal.configure("on")
    h = [5]
    fired = []
    det = causal.StallDetector(lambda: h[0],
                               lambda hh, s: fired.append((hh, s)),
                               window_s=0.15, poll_s=0.03)
    det.start()
    try:
        deadline = time.monotonic() + 3.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fired and fired[0][0] == 5 and fired[0][1] >= 0.15
        n = len(fired)
        time.sleep(0.3)          # still stalled: must NOT refire
        assert len(fired) == n
        h[0] = 6                 # progress re-arms
        time.sleep(0.05)
        deadline = time.monotonic() + 3.0
        while len(fired) <= n and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(fired) == n + 1 and fired[-1][0] == 6
    finally:
        det.stop()
    # the ring carries the flight-recorder markers
    stalls = [e for e in causal.dump()["spans"] if e["n"] == "stall"]
    assert len(stalls) >= 2


# --------------------------------------------------------- merge plane

def _mk_dump(node, spans, rtt=None):
    return {"node": node, "pid": 1, "wall_ns": 0, "enabled": True,
            "capacity": 65536, "events": len(spans),
            "rtt_s": rtt or {}, "spans": spans}


def _recv(origin, sent_ns, recv_ns, h=1):
    return {"n": "p2p.recv", "h": h, "r": 0, "t": recv_ns, "d": 0,
            "a": {"origin": origin, "sent": sent_ns, "kind": "vote"}}


def test_clock_alignment_recovers_synthetic_skew():
    """Node b's clock runs 50 ms ahead; symmetric 2 ms one-way delay.
    The pairwise minimum estimator must recover the offset to well
    under the delay floor."""
    ms = 1_000_000
    skew, delay = 50 * ms, 2 * ms
    a_spans, b_spans = [], []
    for i in range(10):
        t = i * 100 * ms
        jitter = (i % 3) * ms          # asymmetric queueing noise
        # a -> b: sent on a's clock, received on b's (true + skew)
        b_spans.append(_recv("a", t, t + delay + jitter + skew))
        # b -> a: sent on b's clock (true + skew), received on a's
        a_spans.append(_recv("b", t + skew, t + delay + jitter))
    offsets = merge.estimate_offsets(
        [_mk_dump("a", a_spans), _mk_dump("b", b_spans)])
    assert offsets["a"] == 0
    assert abs(offsets["b"] - skew) <= delay
    rtts = merge.pair_rtt_floor_s(
        [_mk_dump("a", a_spans), _mk_dump("b", b_spans)])
    assert abs(rtts["a<->b"] - 2 * delay / 1e9) < 1e-3


def _height_spans(h, t0, off=0):
    """One height's boundary events starting at t0 (ns), shifted by a
    clock offset: begin +0, first part +5ms, full +15ms, prevote quorum
    +25ms, precommit quorum +35ms, apply 35-50ms, fsync 50-60ms."""
    ms = 1_000_000

    def ev(name, at, dur=0, r=0):
        return {"n": name, "h": h, "r": r, "t": t0 + at + off, "d": dur}

    return [
        ev("height.begin", 0),
        ev("part.first", 5 * ms),
        ev("block.full", 15 * ms),
        ev("quorum.prevote", 25 * ms),
        ev("quorum.precommit", 35 * ms),
        ev("apply", 35 * ms, dur=15 * ms),
        ev("wal.fsync", 50 * ms, dur=10 * ms),
        ev("commit", 60 * ms),
    ]


def test_attribution_table_and_coverage():
    ms = 1_000_000
    skew = 40 * ms
    a_spans, b_spans = [], []
    for h in range(1, 6):
        t0 = h * 200 * ms
        a_spans += _height_spans(h, t0)
        # node b sees everything 3 ms later on a skewed clock
        b_spans += _height_spans(h, t0 + 3 * ms, off=skew)
        a_spans.append(_recv("b", t0 + skew, t0 + 2 * ms, h=h))
        b_spans.append(_recv("a", t0, t0 + 2 * ms + skew, h=h))
    dumps = [_mk_dump("a", a_spans), _mk_dump("b", b_spans)]
    rep = merge.attribution(dumps)
    assert rep["heights"] == 5 and rep["heights_skipped"] == 0
    # stages are consecutive boundary deltas: coverage is exact
    assert rep["coverage_mean"] >= 0.99
    s = rep["stages_ms_p50_p95"]
    assert abs(s["first_part"]["p50_ms"] - 5.0) < 2.5
    assert abs(s["full_block"]["p50_ms"] - 10.0) < 2.5
    assert abs(s["apply"]["p50_ms"] - 15.0) < 2.5
    assert abs(s["persist"]["p50_ms"] - 10.0) < 2.5
    assert abs(s["height_wall"]["p50_ms"] - 60.0) < 5.0
    row = rep["per_height"][0]
    assert row["coverage"] >= 0.99


def test_perfetto_merge_one_pid_per_node():
    ms = 1_000_000
    dumps = [_mk_dump("a", _height_spans(1, 10 * ms)),
             _mk_dump("b", _height_spans(1, 13 * ms))]
    doc = merge.to_perfetto(dumps, offsets={"a": 0, "b": 0})
    evs = doc["traceEvents"]
    metas = [e for e in evs if e.get("ph") == "M"]
    assert {m["pid"] for m in metas} == {0, 1}
    body = [e for e in evs if e.get("ph") != "M"]
    assert all(e["ts"] >= 0 for e in body)
    assert any(e["ph"] == "X" and e["name"] == "apply" for e in body)
    # merge_report composes the whole pipeline
    rep = merge.merge_report(dumps)
    assert rep["nodes"] == ["a", "b"]
    assert rep["attribution"]["heights"] == 1


# ------------------------------------------------------- span-name lint

def test_span_catalog_lint_flags_undeclared_names(tmp_path):
    from tendermint_tpu.analysis.checkers import metrics as mcheck
    bad = tmp_path / "bad.py"
    bad.write_text('from tendermint_tpu.telemetry import causal\n'
                   'causal.point("bogus.span", 1)\n'
                   'with causal.span("apply", 2):\n'
                   '    pass\n')
    findings = mcheck.span_findings(str(tmp_path))
    assert len(findings) == 1
    assert "bogus.span" in findings[0].message
    assert findings[0].line == 2
    # the real tree is clean (the same gate scripts/lint.py runs)
    assert mcheck.span_findings() == []


# ------------------------------------------------------- RPC surface

def test_dump_route_and_debug_endpoint():
    from tendermint_tpu.rpc.client import JSONRPCClient
    from tendermint_tpu.rpc.core import RPCEnv, make_server
    causal.configure("on")
    causal.set_node("rpc-node")
    causal.point("commit", 9, txs=3)
    causal.point("commit", 12, txs=1)
    server, _core = make_server(RPCEnv())
    host, port = server.serve("127.0.0.1", 0)
    try:
        c = JSONRPCClient(f"http://{host}:{port}")
        d = c.call("dump_height_timeline")
        assert d["node"] == "rpc-node" and d["enabled"] is True
        assert [e["h"] for e in d["spans"]] == [9, 12]
        # height filter keeps only the asked-for window
        d2 = c.call("dump_height_timeline", min_height=10)
        assert [e["h"] for e in d2["spans"]] == [12]
        # raw GET endpoint serves the same payload, no JSON-RPC envelope
        with urllib.request.urlopen(
                f"http://{host}:{port}/debug/timeline", timeout=10) as r:
            raw = json.loads(r.read())
        assert raw["node"] == "rpc-node"
        assert [e["h"] for e in raw["spans"]] == [9, 12]
    finally:
        server.stop()


# --------------------------------------------------- keepalive RTT

def test_mconn_keepalive_rtt_sample():
    from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnection
    from tendermint_tpu.p2p.conn.mconn import PlainFramedConn
    s1, s2 = socket.socketpair()
    descs = [ChannelDescriptor(0x01, priority=1)]
    m1 = MConnection(PlainFramedConn(s1), descs,
                     on_receive=lambda ch, m: None,
                     ping_interval=0.05, idle_timeout=30.0)
    m2 = MConnection(PlainFramedConn(s2), descs,
                     on_receive=lambda ch, m: None,
                     ping_interval=0.05, idle_timeout=30.0)
    m1.start()
    m2.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                not (m1.rtt_s() > 0 or m2.rtt_s() > 0):
            time.sleep(0.02)
        assert m1.rtt_s() > 0 or m2.rtt_s() > 0
        assert max(m1.rtt_s(), m2.rtt_s()) < 5.0
    finally:
        m1.stop()
        m2.stop()


# ---------------------------------------- cross-node propagation (TCP)

def test_trace_propagation_two_node_tcp_net(tmp_path, monkeypatch):
    """TM_TPU_TRACE=on across a real 2-node TCP net: receive-side link
    spans appear with the sender's origin id and sane (send <= recv +
    slack) clock pairs, consensus spans cover the committed heights,
    and consensus itself is unaffected. (Both in-process nodes share
    the process-global ring and node label, so per-node attribution is
    exercised in the socket bench / merge tests; THIS test proves the
    wire stamps round-trip end to end.)"""
    monkeypatch.setenv("TM_TPU_TRACE", "on")
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator,
                                      PrivKey)
    from tendermint_tpu.types.priv_validator import (LocalSigner,
                                                     PrivValidator)
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(2)]
    gen = GenesisDoc(chain_id="trace-net", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    nodes = []
    for i, k in enumerate(keys):
        cfg = make_test_config(str(tmp_path / f"node{i}"))
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.addr_book_strict = False
        nodes.append(Node(cfg, gen,
                          priv_validator=PrivValidator(LocalSigner(k)),
                          in_memory=True, with_p2p=True))
    ids = {n.switch.node_info.id[:12] for n in nodes}
    try:
        for n in nodes:
            n.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and \
                not all(n.height >= 3 for n in nodes):
            time.sleep(0.05)
        assert all(n.height >= 3 for n in nodes), \
            [n.height for n in nodes]
    finally:
        for n in nodes:
            n.stop()
    spans = causal.dump()["spans"]
    by_name: dict = {}
    for e in spans:
        by_name.setdefault(e["n"], []).append(e)
    # wire stamps arrived and were linked: origin ids are real node ids
    recvs = by_name.get("p2p.recv", [])
    assert recvs, "no receive-side link spans recorded"
    assert {e["a"]["origin"] for e in recvs} <= ids
    assert all(e["a"]["sent"] <= e["t"] + 50_000_000 for e in recvs)
    assert any(e["h"] >= 1 for e in recvs)
    # the consensus timeline covers the committed heights
    for name in ("height.begin", "quorum.prevote", "quorum.precommit",
                 "apply", "wal.fsync", "commit"):
        hs = {e["h"] for e in by_name.get(name, [])}
        assert any(h >= 1 for h in hs), f"missing {name} spans"
    # trace ids keyed the envelopes to real heights: a recv span's
    # height matches a height the cluster actually ran
    run_heights = {e["h"] for e in by_name.get("commit", [])}
    assert {e["h"] for e in recvs if e["h"] > 0} & run_heights
