"""tmtaint: the project call graph (analysis/flowgraph) + the
inter-procedural consensus-determinism taint pass (ISSUE 20).

Fixture tests feed deliberately order/clock/seed-dependent snippets
through the same source scanner the real pass uses; the tree gates run
the full call graph and keep the repository at zero unsuppressed taint
findings with every blessed seam naming a live differential test.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tendermint_tpu.analysis.checkers import taint  # noqa: E402
from tendermint_tpu.analysis.checkers.taint import (  # noqa: E402
    BLESSED,
    SINKS,
    Seam,
    _SourceScan,
    _apply_pragmas,
    _stale_seams,
    blessed_knobs,
    run_taint,
)
from tendermint_tpu.analysis.engine import Finding  # noqa: E402
from tendermint_tpu.analysis.flowgraph import (  # noqa: E402
    FlowGraph,
    module_qname,
)

FIXTURE_REL = "tendermint_tpu/fixture/s.py"


def graph_of(*sources):
    g = FlowGraph()
    for rel, src in sources:
        g.add_source(src, rel)
    g.link()
    return g


def scan(src, func="f"):
    """Run the taint source scanner over one fixture function."""
    g = graph_of((FIXTURE_REL, src))
    qname = f"{module_qname(FIXTURE_REL)}.{func}"
    fi = g.functions[qname]
    mod = g.modules[fi.module]
    return _SourceScan(fi, mod.imports, False, blessed_knobs()).run()


def kinds(hits):
    return sorted(h.kind for h in hits)


# ------------------------------------------------------------ flowgraph --

def test_flowgraph_resolves_direct_alias_self_and_ctor():
    g = graph_of(
        ("tendermint_tpu/fixture/a.py",
         "import helper\n"
         "from helper import util as u\n"
         "class Box:\n"
         "    def __init__(self):\n"
         "        self.n = 0\n"
         "    def put_thing(self, x):\n"
         "        self.bump()\n"
         "        helper.work(x)\n"
         "        u(x)\n"
         "    def bump(self):\n"
         "        self.n += 1\n"
         "def make():\n"
         "    b = Box()\n"
         "    b.put_thing(1)\n"),
        ("helper.py",
         "def work(x):\n"
         "    return x\n"
         "def util(x):\n"
         "    return x\n"),
    )
    put = g.callees("tendermint_tpu.fixture.a.Box.put_thing")
    by_label = {c.label: c for c in put}
    assert by_label["self.bump"].kind == "self"
    assert by_label["self.bump"].targets == (
        "tendermint_tpu.fixture.a.Box.bump",)
    assert by_label["helper.work"].kind == "alias"
    assert by_label["helper.work"].targets == ("helper.work",)
    assert by_label["u"].targets == ("helper.util",)

    make = {c.label: c for c in g.callees("tendermint_tpu.fixture.a.make")}
    assert make["Box"].kind == "class"
    assert make["Box"].targets == ("tendermint_tpu.fixture.a.Box.__init__",)
    # put_thing is unique across project classes -> duck dispatch finds it
    assert make["b.put_thing"].targets == (
        "tendermint_tpu.fixture.a.Box.put_thing",)

    st = g.stats()
    assert st["files"] == 2 and st["functions"] == 6
    assert st["parse_errors"] == 0
    assert 0 < st["resolution_rate"] <= 1


def test_flowgraph_external_calls_not_counted_against_resolution():
    g = graph_of((FIXTURE_REL,
                  "import json\n"
                  "def f(x):\n"
                  "    return json.dumps(x)\n"))
    (cs,) = g.callees(f"{module_qname(FIXTURE_REL)}.f")
    assert cs.kind == "external" and cs.targets == ()
    assert g.stats()["resolution_rate"] == 0.0  # nothing resolvable


def test_flowgraph_stats_on_real_tree():
    g = FlowGraph.build(REPO)
    st = g.stats()
    assert st["parse_errors"] == 0
    assert st["files"] > 150 and st["functions"] > 2000
    assert st["resolution_rate"] > 0.5  # the graph is genuinely linked


# ------------------------------------------------------ source scanner --

def test_scan_wallclock_rng_env():
    hits = scan(
        "import os, random, time\n"
        "def f():\n"
        "    a = time.time()\n"
        "    b = random.random()\n"
        "    c = os.getenv('HOME')\n"
        "    d = os.environ['HOME']\n"
        "    return a, b, c, d\n")
    assert kinds(hits) == ["env", "env", "rng", "wallclock"]


def test_scan_order_sources_and_laundering():
    hits = scan(
        "def f(xs, m):\n"
        "    for x in {1, 2, 3}:\n"
        "        pass\n"
        "    for k in m.table.keys():\n"
        "        pass\n"
        "    s = set(xs)\n"
        "    for x in s:\n"
        "        pass\n")
    assert kinds(hits) == ["order", "order", "order"]

    clean = scan(
        "def f(xs, m):\n"
        "    for x in sorted(set(xs)):\n"
        "        pass\n"
        "    for k in sorted(m.table.keys()):\n"
        "        pass\n"
        "    s = set(xs)\n"
        "    s = sorted(s)\n"   # rebinding launders the name
        "    for x in s:\n"
        "        pass\n"
        "    total = sum(v for v in m.table.values())\n")
    assert clean == []


def test_scan_hashid_lookup_key_exemption():
    hits = scan(
        "def f(x, cache):\n"
        "    cache[id(x)] = 1\n"        # subscript key: benign
        "    v = cache.get(id(x))\n"    # lookup arg: benign
        "    same = id(x) == id(v)\n"   # compare: benign
        "    return hash(x)\n")         # output bytes: finding
    assert kinds(hits) == ["hashid"]
    assert "hash()" in hits[0].detail


def test_scan_devicefloat_and_integer_evidence():
    hits = scan(
        "import jax.numpy as jnp\n"
        "def f(a):\n"
        "    x = jnp.sum(a)\n"
        "    y = jnp.sum(a, dtype=jnp.uint32)\n"   # integer: exact
        "    z = jnp.sum(a << 8)\n"                # bit-packing: exact
        "    return x, y, z\n")
    assert kinds(hits) == ["devicefloat"]
    assert hits[0].lineno == 3


def test_scan_knob_reads_against_blessed_set():
    assert "TM_TPU_PIPELINE" in blessed_knobs()
    hits = scan(
        "from tendermint_tpu.utils.knobs import knob_bool, knob_str\n"
        "def f(name):\n"
        "    a = knob_bool('TM_TPU_PIPELINE')\n"     # blessed seam
        "    b = knob_str('TM_TPU_TELEMETRY')\n"     # not blessed
        "    c = knob_str(name)\n")                  # dynamic
    assert kinds(hits) == ["knob", "knob"]
    assert any("TM_TPU_TELEMETRY" in h.detail for h in hits)
    assert any("dynamic" in h.detail for h in hits)


# ------------------------------------------------------ seams/pragmas --

def test_catalogs_are_wellformed():
    assert len(SINKS) >= 15
    assert len({q for q, _ in SINKS}) == len(SINKS)
    for seam in BLESSED:
        assert seam.kind in ("function", "module", "knob")
        assert "::" in seam.test and seam.why


def test_stale_seam_is_a_finding(monkeypatch):
    dead = Seam("knob", "TM_TPU_PIPELINE",
                "tests/test_lint.py::test_no_such_test", "fixture")
    monkeypatch.setattr(taint, "BLESSED", (dead,))
    out = _stale_seams(REPO)
    assert len(out) == 1
    assert "stale blessed seam knob:TM_TPU_PIPELINE" in out[0].message
    assert "test_no_such_test" in out[0].message


def test_every_blessed_seam_names_a_live_test():
    assert _stale_seams(REPO) == []


def test_pragma_suppression_and_staleness(tmp_path):
    rel = "mod.py"
    (tmp_path / rel).write_text(
        "def f(xs):\n"
        "    # tmlint: allow(taint): fixture justification\n"
        "    for x in set(xs):\n"
        "        pass\n"
        "    y = 1  # tmlint: allow(taint): suppresses nothing\n",
        encoding="utf-8")
    g = FlowGraph.build(str(tmp_path), paths=[rel])
    findings = [Finding("taint", rel, 3, "order source in f")]
    kept, stale = _apply_pragmas(str(tmp_path), g, findings)
    assert kept == []
    assert len(stale) == 1 and stale[0].line == 5
    assert "suppresses nothing" in stale[0].message


# ------------------------------------------------------------ the tree --

def test_tree_has_zero_unsuppressed_taint_findings():
    """THE taint gate: every wall-clock/RNG/env/order/hash source that
    reaches a consensus sink is either fixed, pragma'd with a
    justification, or cut at a blessed seam with a live test."""
    rep = run_taint(REPO)
    assert rep.findings == [], "\n".join(str(f) for f in rep.findings)
    st = rep.stats
    assert st["sinks"] == len(SINKS)
    assert st["reachable_functions"] > 300   # the cone is real
    assert st["seam_cuts"] > 50              # and the seams do work
    assert st["blessed_seams"] == len(BLESSED)


def test_lint_cli_graph_stats():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--graph-stats"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    import json
    st = json.loads(r.stdout)
    assert st["parse_errors"] == 0 and st["resolution_rate"] > 0.5


@pytest.mark.slow
def test_lint_cli_taint_flag():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--no-metrics", "--taint"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "taint:" in r.stdout and "seam cuts" in r.stdout
