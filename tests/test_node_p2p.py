"""Full-node p2p integration: complete Node objects (stores + WAL + app +
mempool + evidence + all reactors + switch) forming a real TCP network —
the assembled system node/node.go builds (§3.1)."""

import time

import pytest

from tendermint_tpu.config import test_config as make_test_config
from tendermint_tpu.node import Node
from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator


def make_net_nodes(tmp_path, n, fast_sync=False):
    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n)]
    gen = GenesisDoc(chain_id="node-net", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    nodes = []
    for i, k in enumerate(keys):
        cfg = make_test_config(str(tmp_path / f"node{i}"))
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.addr_book_strict = False
        node = Node(cfg, gen, priv_validator=PrivValidator(LocalSigner(k)),
                    in_memory=True, with_p2p=True, fast_sync=fast_sync)
        nodes.append(node)
    return nodes


def wait_for(cond, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_two_full_nodes_reach_consensus_over_tcp(tmp_path):
    nodes = make_net_nodes(tmp_path, 2)
    try:
        for node in nodes:
            node.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        assert wait_for(lambda: all(n.height >= 3 for n in nodes)), \
            [n.height for n in nodes]
        assert nodes[0].consensus.state.last_block_id == \
            nodes[1].consensus.state.last_block_id
    finally:
        for node in nodes:
            node.stop()


def test_tx_gossips_between_full_nodes(tmp_path):
    nodes = make_net_nodes(tmp_path, 2)
    try:
        for node in nodes:
            node.start()
        nodes[1].switch.dial_peer(nodes[0].switch.listen_address)
        assert wait_for(lambda: all(n.height >= 1 for n in nodes))
        # submit ONLY to node 0; the mempool reactor must carry it to the
        # other node, and a block must deliver it to both apps
        nodes[0].mempool.check_tx(b"gossip=works")
        assert wait_for(
            lambda: all(n.app.store.get(b"gossip") == b"works"
                        for n in nodes)), \
            [dict(n.app.store) for n in nodes]
    finally:
        for node in nodes:
            node.stop()

