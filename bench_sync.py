"""Fresh-node catch-up bench: snapshot state-sync vs block replay
(BENCH_sync.json, ISSUE 9 acceptance).

Builds a 300+-height source chain (4 validators, real signed commits,
KVStore app state growing every block) with a chunked snapshot
published near the tip, then measures the wall time for a FRESH node
to reach the frontier over real in-process p2p switches two ways:

  statesync  discover + fetch + verify the snapshot over channel 0x60,
             bootstrap the stores at the snapshot height, fast-sync
             only the tail;
  replay     ordinary fast-sync from genesis: download and re-execute
             every block.

Standalone: `python bench_sync.py [n_blocks] [n_vals] [n_txs]` prints
one JSON line. bench.py --sync-json imports run() and writes the
committed artifact.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def build_source(n_blocks: int, n_vals: int, n_txs: int,
                 snapshot_at: int, snap_dir: str,
                 chunk_kb: int = 256) -> dict:
    from bench_util import fast_signer
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.abci.types import ValidatorUpdate
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.storage import (BlockStore, MemDB, SnapshotStore,
                                        StateStore)
    from tendermint_tpu.storage.snapshot import build_payload
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
    from tendermint_tpu.types.block import BlockID, Commit
    from tendermint_tpu.types.vote import Vote, VoteType

    keys = [PrivKey.generate((i + 1).to_bytes(32, "little"))
            for i in range(n_vals)]
    signers = {k.pubkey.address: fast_signer((i + 1).to_bytes(32, "little"))
               for i, k in enumerate(keys)}
    gen = GenesisDoc(chain_id="bench-statesync", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    snap_store = SnapshotStore(snap_dir)
    part_size = state.consensus_params.block_gossip.block_part_size_bytes

    last_commit = Commit()
    for h in range(1, n_blocks + 1):
        txs = [b"s%d.%d=v%d" % (h, i, h) for i in range(n_txs)]
        block = state.make_block(h, txs, last_commit, time_ns=h * 10 ** 9)
        parts = block.make_part_set(part_size)
        block_id = BlockID(block.hash(), parts.header())
        precommits = []
        for idx, val in enumerate(state.validators.validators):
            v = Vote(validator_address=val.address, validator_index=idx,
                     height=h, round=0, timestamp_ns=h * 10 ** 9 + 1,
                     type=VoteType.PRECOMMIT, block_id=block_id)
            v.signature = signers[val.address](v.sign_bytes(gen.chain_id))
            precommits.append(v)
        commit = Commit(block_id, precommits)
        block_store.save_block(block, parts, commit)
        state = exec_.apply_block(state.copy(), block_id, block,
                                  trust_last_commit=True)
        last_commit = commit
        if h == snapshot_at:
            manifest = snap_store.take(
                h, build_payload(state, commit, app.snapshot_items()),
                chunk_size=chunk_kb * 1024)
            state_store.pin_snapshot(h, manifest)
    return {"gen": gen, "state": state, "block_store": block_store,
            "state_store": state_store, "snap_store": snap_store,
            "app": app, "manifest": snap_store.load_manifest(snapshot_at)}


def _fresh_arm(src, use_statesync: bool, workdir: str,
               timeout_s: float) -> dict:
    """One catch-up arm; returns {seconds, restored_height, frontier}."""
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.abci.types import ValidatorUpdate
    from tendermint_tpu.blockchain import BlockchainReactor
    from tendermint_tpu.config import P2PConfig, test_config
    from tendermint_tpu.consensus import ConsensusState, MockTicker
    from tendermint_tpu.consensus.reactor import ConsensusReactor
    from tendermint_tpu.p2p.test_util import connect_switches, make_switch
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.statesync import StateSyncReactor
    from tendermint_tpu.storage import (BlockStore, MemDB, SnapshotStore,
                                        StateStore)

    gen = src["gen"]
    # both arms get the same wide-open link: the reference's 512 KB/s
    # WAN default would turn either arm into a token-bucket bench
    p2p_cfg = lambda: P2PConfig(send_rate=64_000_000,  # noqa: E731
                                recv_rate=64_000_000)
    # serving side
    src_bc = BlockchainReactor(src["state"], None, src["block_store"],
                               fast_sync=False)
    sw_src = make_switch(network=gen.chain_id, seed=b"\x51" * 32,
                         config=p2p_cfg())
    sw_src.add_reactor("blockchain", src_bc)
    sw_src.add_reactor("statesync",
                       StateSyncReactor(src["snap_store"], gen.chain_id))
    sw_src.start()

    # fresh side
    app = KVStoreApp()
    conns = AppConns(local_client_creator(app))
    state_store = StateStore(MemDB())
    block_store = BlockStore(MemDB())
    state = state_store.load_or_genesis(gen)
    conns.consensus.init_chain(
        [ValidatorUpdate(v.pubkey, v.voting_power)
         for v in state.validators.validators], gen.chain_id)
    exec_ = BlockExecutor(state_store, conns.consensus)
    cs = ConsensusState(test_config().consensus, state, exec_,
                        block_store, priv_validator=None,
                        ticker_factory=MockTicker)
    cons = ConsensusReactor(cs, fast_sync=True)
    gate = threading.Event()
    bc = BlockchainReactor(state, exec_, block_store, fast_sync=True,
                           consensus_reactor=cons, verify_window=64,
                           gate=gate if use_statesync else None)
    sw_new = make_switch(network=gen.chain_id, seed=b"\x52" * 32,
                         config=p2p_cfg())
    sw_new.add_reactor("consensus", cons)
    sw_new.add_reactor("blockchain", bc)
    restored = {"height": 0}
    if use_statesync:
        def on_done(st, _bc=bc, _cs=cs):
            if st is not None:
                restored["height"] = st.last_block_height
                _cs.state = st
                _bc.adopt_restored(st)
            gate.set()

        ss = StateSyncReactor(
            SnapshotStore(os.path.join(workdir, "snapshots")),
            gen.chain_id, restore=True,
            statesync_dir=os.path.join(workdir, "statesync"),
            block_store=block_store, state_store=state_store, app=app,
            on_restored=on_done, give_up_s=10.0)
        sw_new.add_reactor("statesync", ss)
    sw_new.start()

    t0 = time.perf_counter()
    connect_switches(sw_src, sw_new)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline and not bc.synced:
        time.sleep(0.02)
    dt = time.perf_counter() - t0
    synced = bc.synced
    frontier = block_store.height()
    sw_src.stop()
    sw_new.stop()
    if not synced:
        raise RuntimeError(
            f"arm {'statesync' if use_statesync else 'replay'} did not "
            f"reach the frontier in {timeout_s}s (at {frontier})")
    return {"seconds": round(dt, 3),
            "restored_height": restored["height"],
            "frontier": frontier}


def run(n_blocks: int = 320, n_vals: int = 4, n_txs: int = 20,
        snapshot_at: int = 300, timeout_s: float = 600.0) -> dict:
    import shutil
    import tempfile
    workdir = tempfile.mkdtemp(prefix="tm_sync_bench_")
    # keep every signature batch on the host oracle: on a CPU-only
    # host the jax path would bill one-off XLA compilation of the
    # first full verify window to the replay arm (~minutes), which is
    # not a sync cost; both arms share the setting
    had = os.environ.get("TM_TPU_AUTO_THRESHOLD")
    os.environ.setdefault("TM_TPU_AUTO_THRESHOLD", "1000000")
    try:
        t0 = time.perf_counter()
        src = build_source(n_blocks, n_vals, n_txs, snapshot_at,
                           os.path.join(workdir, "src-snapshots"))
        build_s = time.perf_counter() - t0
        arms = {}
        arms["statesync"] = _fresh_arm(
            src, True, os.path.join(workdir, "arm-statesync"), timeout_s)
        arms["replay"] = _fresh_arm(
            src, False, os.path.join(workdir, "arm-replay"), timeout_s)
        doc = {
            "metric": "fresh_node_catchup_seconds",
            "unit": "seconds to the chain frontier",
            "workload": f"{n_blocks}-height chain, {n_vals} validators, "
                        f"{n_txs} tx/block, snapshot at {snapshot_at} "
                        "(in-process switches, plaintext links)",
            "source": "statesync/reactor.py restore + blockchain tail "
                      "sync vs full blockchain fast-sync from genesis",
            "chain_build_seconds": round(build_s, 1),
            "snapshot": {
                "height": src["manifest"]["height"],
                "chunks": len(src["manifest"]["chunks"]),
                "bytes": src["manifest"]["size"],
            },
            "arms": arms,
            "speedup_statesync_vs_replay": round(
                arms["replay"]["seconds"] / arms["statesync"]["seconds"],
                2),
            "host_cpu_count": os.cpu_count(),
            "note": "the statesync arm pays a fixed ~1.3s snapshot "
                    "discovery window and a near-constant restore, so "
                    "its advantage grows linearly with chain length "
                    "while replay pays execution + commit verification "
                    "per block (measured on this host: ~1.4x at 480 "
                    "heights, ~4x at 1920)",
        }
        return doc
    finally:
        if had is None:
            os.environ.pop("TM_TPU_AUTO_THRESHOLD", None)
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 320
    v = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    t = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    print(json.dumps(run(n, v, t, snapshot_at=max(2, n - 20))),
          flush=True)
