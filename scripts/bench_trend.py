#!/usr/bin/env python
"""bench_trend — the committed BENCH_*.json artifacts as ONE trajectory.

Every PR committed its bench artifact and moved on; nothing aggregated
them, so the performance trajectory (and any quiet regression) was
invisible without opening eight JSON files. This script:

- extracts each artifact's headline metrics through a declarative
  extractor table (metric name, source file, JSON path, unit,
  direction), stamping each point with the PR that last touched the
  artifact (``git log -1`` on the file; falls back to "?" outside a
  git checkout);
- writes BENCH_trend.json: one ``points`` list (metric, pr, file,
  value, unit, direction) plus per-metric series for the metrics that
  appear in MORE THAN ONE artifact — the actual trajectories;
- exits NONZERO when any multi-point metric's newest value is >20%
  worse than the best prior value in its series (direction-aware) —
  the regression gate a future PR's CI can lean on.

Only points extracted from the SAME workload shape share a metric name
(e.g. ``socket_blocks_per_sec`` joins the untraced/unprofiled socket
arms of BENCH_p2p and BENCH_profile; the traced arm is its own metric
— tracing on is a different workload, not a regression).

Usage:
    python scripts/bench_trend.py [--out BENCH_trend.json]
        [--threshold 0.20] [--repo DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One row per headline number: (metric, file, path, unit, direction).
# `path` is a dotted/indexed walk into the artifact; direction "up"
# means bigger is better. Metrics listed under several files form a
# cross-PR series; the gate compares only within a series.
EXTRACTORS = (
    ("verifier_largest_batch_verifies_per_sec", "BENCH_verifier.json",
     "points[-1].verifies_per_sec", "verifies/sec", "up"),
    ("coalesce_speedup_16_callers", "BENCH_coalesce.json",
     "points[callers=16].speedup", "x", "up"),
    ("socket_blocks_per_sec", "BENCH_p2p.json",
     "pipeline_on.blocks_per_sec", "blocks/sec", "up"),
    # the profile bench's trajectory point is its session-best over
    # the identical workload (both arms; the same quiet-window policy
    # the headline bench uses) — cross-session host drift on this
    # shared container is ~±25%, so a single window would flag
    # phantom regressions (see BENCH_profile.json's own note)
    ("socket_blocks_per_sec", "BENCH_profile.json",
     "blocks_per_sec_best", "blocks/sec", "up"),
    ("socket_txs_per_sec", "BENCH_p2p.json",
     "pipeline_on.txs_per_sec", "txs/sec", "up"),
    ("socket_blocks_per_sec_traced", "BENCH_trace.json",
     "blocks_per_sec", "blocks/sec", "up"),
    ("socket_blocks_per_sec_profiled", "BENCH_profile.json",
     "prof_on.blocks_per_sec", "blocks/sec", "up"),
    ("profiler_overhead", "BENCH_profile.json",
     "profiler_overhead", "fraction", "down"),
    ("chaos_invariant_checks_passed", "BENCH_chaos.json",
     "value", "checks", "up"),
    # the ISSUE-11 validator-scale curve: commit rate at 32 and 128
    # validators under churn + wan3 geo + faults, and the
    # predecompression hit rate where the device path engages (128) —
    # regressions here mean the adversarial plane got slower or the
    # cache stopped surviving churn
    ("chaos_blocks_per_sec_32v", "BENCH_chaos.json",
     "scaling_curve[n_validators=32].blocks_per_sec", "blocks/sec",
     "up"),
    ("chaos_blocks_per_sec_128v", "BENCH_chaos.json",
     "scaling_curve[n_validators=128].blocks_per_sec", "blocks/sec",
     "up"),
    ("chaos_predecomp_hit_rate_128v", "BENCH_chaos.json",
     "scaling_curve[n_validators=128].predecomp_hit_rate", "fraction",
     "up"),
    ("chaos_lite_certified_height_32v", "BENCH_chaos.json",
     "scaling_curve[n_validators=32].lite.certified_height", "heights",
     "up"),
    # the ISSUE-12 front door: WS subscriber capacity and subscribe
    # latency under load in loop mode — the connection-capacity floor
    # the >=10x-vs-threads acceptance rode in on, and the latency that
    # must not quietly rot as the loop grows responsibilities
    ("rpc_ws_subscribers_loop", "BENCH_rpc.json",
     "loop.subscribed", "conns", "up"),
    ("rpc_subscribe_ack_p99_ms_loop", "BENCH_rpc.json",
     "loop.subscribe_ack_p99_ms", "ms", "down"),
    ("rpc_subscriber_ratio_loop_vs_threads", "BENCH_rpc.json",
     "subscriber_ratio_loop_vs_threads", "x", "up"),
    # the ISSUE-13 wire-chaos arm: how much of the clean commit rate
    # the loop plane keeps under the seeded wire-fault schedule +
    # hostile peers, and how fast the net recovers after each episode
    # heals — regressions mean the socket plane got more fragile
    ("wirechaos_blocks_ratio", "BENCH_wirechaos.json",
     "faulted_over_clean_blocks_ratio", "x", "up"),
    ("wirechaos_recovery_p50_s", "BENCH_wirechaos.json",
     "recovery.latency_seconds.p50", "s", "down"),
    # the ISSUE-14 tx-lifecycle SLO plane: user-visible latency from
    # broadcast_tx admission to block commit and to WS event delivery
    # (deterministically sampled txs through the async front door) —
    # the regression gate finally covers what a CLIENT experiences,
    # not just node-internal phase costs
    ("slo_commit_p50_ms", "BENCH_slo.json",
     "stages.e2e_commit.p50_ms", "ms", "down"),
    ("slo_commit_p99_ms", "BENCH_slo.json",
     "stages.e2e_commit.p99_ms", "ms", "down"),
    ("slo_delivery_p99_ms", "BENCH_slo.json",
     "stages.e2e_delivery.p99_ms", "ms", "down"),
    # the ISSUE-18 compact gossip plane: how often a compact block
    # offer resolves from the receiver's own mempool (hit, or a
    # bounded fetch of the few missing txs) instead of falling back to
    # full part relay, and the mean votes carried per aggregate gossip
    # message — regressions mean the consensus wire got chattier
    ("compact_reconstruct_hit_rate", "BENCH_slo.json",
     "compact.compact_reconstruct_hit_rate", "fraction", "up"),
    ("voteagg_mean_batch", "BENCH_slo.json",
     "compact.voteagg_mean_batch", "votes/msg", "up"),
    # the ISSUE-15 shard plane: aggregate commit rate and the coalesce
    # factor at 8 chains in one process — the paper's amortization
    # claim (concurrent sub-threshold verifies from many chains merge
    # into bigger device batches) as a gated number; regressions mean
    # the shard plane got slower or cross-chain coalescing stopped
    # engaging
    ("shard_agg_blocks_per_sec_8", "BENCH_shard.json",
     "curve[n_shards=8].agg_blocks_per_sec", "blocks/sec", "up"),
    ("shard_coalesce_factor_8", "BENCH_shard.json",
     "curve[n_shards=8].coalesce_factor", "x", "up"),
    ("mesh_8dev_verifies_per_sec", "BENCH_mesh.json",
     "points[devices=8].verifies_per_sec", "verifies/sec", "up"),
    ("statesync_speedup_vs_replay", "BENCH_sync.json",
     "speedup_statesync_vs_replay", "x", "up"),
    # the ISSUE-16 authenticated state tree: per-key commit cost at
    # 1M keys (sub-linear in state size is the whole point) and the
    # client-side proof verification cost a certified read pays
    ("state_commit_us_per_key_1m", "BENCH_state.json",
     "commit_curve[keys=1000000].us_per_key", "us", "down"),
    ("state_proof_verify_us", "BENCH_state.json",
     "proof.verify_us", "us", "down"),
    ("height_wall_p50_ms", "BENCH_trace.json",
     "attribution.per_height[-1].wall_ms", "ms", "down"),
    # the ISSUE-19 serving plane: the open-loop knee (highest offered
    # rate the multi-process front door absorbs with goodput intact),
    # tail latency AT that knee, and the edge read tier's capacity
    # scaling at 2 replicas — regressions mean the serving plane
    # saturates earlier, answers slower at the knee, or replica
    # fan-out stopped adding certified-read capacity
    ("load_knee_tx_per_sec", "BENCH_load.json",
     "knee.offered_rate", "ops/sec", "up"),
    ("load_p99_at_knee_ms", "BENCH_load.json",
     "knee.p99_ms", "ms", "down"),
    ("load_replica_scaling_2x", "BENCH_load.json",
     "replica_scaling.scaling_2x", "x", "up"),
    # the ISSUE-20 static-analysis plane: full tmlint wall time (AST
    # checkers + metrics registry + the inter-procedural taint pass
    # over the project call graph) — it runs inside tier-1, so a
    # superlinear blowup in the flowgraph/taint traversal shows up
    # here before it makes CI miserable
    ("lint_wall_seconds", "LINT_report.json",
     "lint_seconds", "s", "down"),
)

_STEP_RE = re.compile(
    r"(\w+)|\[(-?\d+)\]|\[(\w+)=(-?\d+(?:\.\d+)?)\]|\.")


def walk(doc, path: str):
    """Dotted/indexed path walk: a.b, [i], [key=value] list search."""
    pos = 0
    cur = doc
    while pos < len(path) and cur is not None:
        m = _STEP_RE.match(path, pos)
        if m is None:
            raise ValueError(f"bad path step at {path[pos:]!r}")
        pos = m.end()
        key, idx, skey, sval = m.groups()
        if key is not None:
            cur = cur.get(key) if isinstance(cur, dict) else None
        elif idx is not None:
            try:
                cur = cur[int(idx)]
            except (IndexError, TypeError):
                cur = None
        elif skey is not None:
            want = float(sval)
            cur = next((it for it in cur
                        if float(it.get(skey, "nan")) == want), None) \
                if isinstance(cur, list) else None
    return cur


# artifacts whose newest commit predates the 'PR N:' subject
# convention (the PR 1 seed commit)
_PR_FALLBACK = {"BENCH_verifier.json": "PR 1"}


def pr_of(path: str, repo: str) -> str:
    """The PR that last touched the artifact, from its newest commit
    subject ('PR 7: ...' -> 'PR 7')."""
    try:
        subj = subprocess.run(
            ["git", "log", "-1", "--format=%s", "--", path],
            cwd=repo, capture_output=True, text=True,
            timeout=30).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return _PR_FALLBACK.get(os.path.basename(path), "?")
    m = re.match(r"(PR \d+)", subj)
    if m:
        return m.group(1)
    if os.path.basename(path) in _PR_FALLBACK:
        return _PR_FALLBACK[os.path.basename(path)]
    return "uncommitted" if not subj else subj[:24]


def collect(repo: str) -> list:
    points = []
    for metric, fname, path, unit, direction in EXTRACTORS:
        full = os.path.join(repo, fname)
        if not os.path.exists(full):
            continue
        try:
            with open(full) as f:
                doc = json.load(f)
            value = walk(doc, path)
        except (ValueError, OSError) as e:
            print(f"[bench_trend] {fname}:{path}: {e}",
                  file=sys.stderr)
            continue
        if not isinstance(value, (int, float)):
            continue
        points.append({"metric": metric, "pr": pr_of(fname, repo),
                       "file": fname, "path": path,
                       "value": value, "unit": unit,
                       "direction": direction})
    return points


def _pr_order(pr: str) -> int:
    m = re.match(r"PR (\d+)", pr)
    return int(m.group(1)) if m else 10_000  # uncommitted = newest


def gate(points: list, threshold: float) -> list:
    """Regressions: per multi-point metric, the newest value vs the
    best PRIOR value; worse by more than `threshold` fails."""
    series: dict = {}
    for p in points:
        series.setdefault(p["metric"], []).append(p)
    regressions = []
    for metric, pts in series.items():
        if len(pts) < 2:
            continue
        pts.sort(key=lambda p: _pr_order(p["pr"]))
        *prior, newest = pts
        up = newest["direction"] == "up"
        best = max(p["value"] for p in prior) if up else \
            min(p["value"] for p in prior)
        if best == 0:
            continue
        change = (newest["value"] - best) / abs(best)
        worse = -change if up else change
        if worse > threshold:
            regressions.append({
                "metric": metric, "unit": newest["unit"],
                "best_prior": best, "newest": newest["value"],
                "newest_pr": newest["pr"],
                "regression": round(worse, 4)})
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_trend.json"))
    ap.add_argument("--threshold", type=float, default=0.20,
                    help="fail on >this fractional regression vs the "
                         "best prior value (default 0.20)")
    ap.add_argument("--repo", default=REPO)
    args = ap.parse_args(argv)

    points = collect(args.repo)
    if not points:
        print("[bench_trend] no BENCH_*.json artifacts found",
              file=sys.stderr)
        return 1
    regressions = gate(points, args.threshold)
    doc = {
        "metric": "bench_trajectory",
        "source": "scripts/bench_trend.py over the committed "
                  "BENCH_*.json artifacts (PR attribution via git log)",
        "threshold": args.threshold,
        "points": points,
        "regressions": regressions,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)

    width = max(len(p["metric"]) for p in points)
    print(f"  {'metric'.ljust(width)}  {'pr'.ljust(6)}  value")
    for p in points:
        print(f"  {p['metric'].ljust(width)}  "
              f"{p['pr'].ljust(6)}  {p['value']} {p['unit']}")
    print(f"[bench_trend] {len(points)} points -> "
          f"{os.path.relpath(args.out, args.repo)}")
    if regressions:
        for r in regressions:
            print(f"[bench_trend] REGRESSION {r['metric']}: "
                  f"{r['newest']} vs best prior {r['best_prior']} "
                  f"({r['regression']:.0%} worse, {r['newest_pr']})",
                  file=sys.stderr)
        return 1
    print("[bench_trend] no regression beyond "
          f"{args.threshold:.0%} in any multi-point series")
    return 0


if __name__ == "__main__":
    sys.exit(main())
