#!/usr/bin/env python
"""tmlint runner — the whole static-analysis suite in one command.

    python scripts/lint.py               # AST checkers + knob-md drift
                                         #   + metrics registry lint
    python scripts/lint.py --no-metrics  # skip the (import-heavy)
                                         #   metrics half — pure AST
    python scripts/lint.py --taint       # add the inter-procedural
                                         #   determinism taint pass
    python scripts/lint.py --json        # also write LINT_report.json
                                         #   (runs the taint pass too)
    python scripts/lint.py --graph-stats # print call-graph resolution
                                         #   stats (flowgraph) and exit
    python scripts/lint.py --knobs-md    # (re)generate docs/knobs.md
                                         #   from the knob catalog

Exit 0 with a summary when the tree is clean; 1 with one line per
finding otherwise. Tier-1 runs this via tests/test_lint.py, so a
finding anywhere in the scan set fails the build — fix it or add a
justified `tmlint: allow(<checker>)` pragma (the pragma budget is
policed too: every pragma needs a justification and must actually
suppress something).

docs/static-analysis.md documents the checkers and pragma syntax;
docs/knobs.md is generated from tendermint_tpu/utils/knobs.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KNOBS_MD = os.path.join(REPO, "docs", "knobs.md")
REPORT = os.path.join(REPO, "LINT_report.json")


def check_knobs_md():
    """docs/knobs.md must match the catalog byte-for-byte."""
    from tendermint_tpu.analysis.engine import Finding
    from tendermint_tpu.utils import knobs
    want = knobs.knobs_md()
    try:
        with open(KNOBS_MD, encoding="utf-8") as f:
            have = f.read()
    except FileNotFoundError:
        have = None
    if have != want:
        state = "missing" if have is None else "stale"
        return [Finding(
            "knob-registry", "docs/knobs.md", 0,
            f"docs/knobs.md is {state} — regenerate with "
            f"`python scripts/lint.py --knobs-md` and commit it")]
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", nargs="?", const=REPORT, default=None,
                    metavar="PATH",
                    help=f"write a JSON report (default {REPORT})")
    ap.add_argument("--knobs-md", action="store_true",
                    help="write docs/knobs.md from the catalog and exit")
    ap.add_argument("--no-metrics", action="store_true",
                    help="skip the metrics registry lint (no heavy "
                         "imports; pure-AST run)")
    ap.add_argument("--taint", action="store_true",
                    help="run the inter-procedural consensus-"
                         "determinism taint pass (implied by --json)")
    ap.add_argument("--graph-stats", action="store_true",
                    help="print project call-graph resolution stats "
                         "(analysis.flowgraph) as JSON and exit")
    ap.add_argument("--max-pragmas", type=int, default=15,
                    help="fail when the tree carries more allow "
                         "pragmas than this (default 15)")
    ap.add_argument("paths", nargs="*",
                    help="scan set override (default: the package, "
                         "scripts/, bench*.py, benchmarks/)")
    args = ap.parse_args(argv)

    import time
    t0 = time.monotonic()

    from tendermint_tpu.utils import knobs
    if args.graph_stats:
        from tendermint_tpu.analysis.flowgraph import FlowGraph
        graph = FlowGraph.build(REPO)
        print(json.dumps(graph.stats(), indent=1, sort_keys=True))
        return 0

    if args.knobs_md:
        os.makedirs(os.path.dirname(KNOBS_MD), exist_ok=True)
        with open(KNOBS_MD, "w", encoding="utf-8") as f:
            f.write(knobs.knobs_md())
        print(f"lint: wrote {os.path.relpath(KNOBS_MD, REPO)} "
              f"({len(knobs.CATALOG)} knobs)")
        return 0

    from tendermint_tpu.analysis import run_tree
    from tendermint_tpu.analysis.checkers import all_checkers
    from tendermint_tpu.analysis.engine import Finding
    findings, pragmas, n_files = run_tree(
        REPO, paths=args.paths or None)
    findings += check_knobs_md()

    checkers_run = [c.id for c in all_checkers()] + ["pragma"]
    metrics_summary = "skipped"
    if not args.no_metrics:
        from tendermint_tpu.analysis.checkers import metrics
        findings += metrics.run()
        metrics_summary = metrics.run.summary or "failed"
        checkers_run.append("metrics")

    taint_stats = None
    if args.taint or args.json:
        from tendermint_tpu.analysis.checkers.taint import run_taint
        taint_report = run_taint(REPO)
        findings += taint_report.findings
        taint_stats = taint_report.stats
        checkers_run.append("taint")

    if len(pragmas) > args.max_pragmas:
        findings.append(Finding(
            "pragma", "(tree)", 0,
            f"{len(pragmas)} allow pragmas exceed the budget of "
            f"{args.max_pragmas} — fix code instead of suppressing"))

    if args.json:
        report = {
            "tool": "tmlint (scripts/lint.py)",
            "files_scanned": n_files,
            "checkers": checkers_run,
            "metrics": metrics_summary,
            "taint": taint_stats,
            "lint_seconds": round(time.monotonic() - t0, 3),
            "clean": not findings,
            "findings": [f.to_obj() for f in findings],
            "pragmas": [p.to_obj() for p in pragmas],
        }
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"lint: wrote {os.path.relpath(args.json, REPO)}")

    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        print(f"lint: {f}")
    if findings:
        print(f"lint: FAILED — {len(findings)} finding(s) across "
              f"{n_files} files")
        return 1
    taint_summary = "skipped" if taint_stats is None else (
        f"{taint_stats['reachable_functions']} reachable fns, "
        f"{taint_stats['seam_cuts']} seam cuts")
    print(f"lint: OK — {n_files} files, "
          f"{len(pragmas)} pragma(s), metrics: {metrics_summary}, "
          f"taint: {taint_summary}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
