#!/usr/bin/env python
"""profile_merge — N per-node profiler dumps -> one cluster flamegraph.

Fetches every node's sampling-profiler table (the `debug_profile`
RPC route with action=dump, or dump files on disk), merges the
collapsed stacks — each node's tree re-rooted under a ``node:<id>``
frame so one flamegraph shows the whole cluster side by side — and
writes the merged collapsed-stack text (flamegraph.pl / speedscope
"collapsed" format). A per-subsystem busy/lock-wait summary table
prints to stdout.

Usage:
    python scripts/profile_merge.py --out merged.collapsed \
        http://127.0.0.1:46657 http://127.0.0.1:46659 ...
    python scripts/profile_merge.py --files dump0.json dump1.json ...
        [--out merged.collapsed] [--report report.json]

Nodes must run with TM_TPU_PROF=on (or have had the profiler started
via `debug_profile action=start`); a dump with zero samples is
reported and skipped. The merge itself lives in
tendermint_tpu/telemetry/profile.py (importable, unit-tested).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tendermint_tpu.telemetry import profile  # noqa: E402


def fetch(url: str) -> dict:
    """One node's profiler table over its JSON-RPC endpoint."""
    from tendermint_tpu.rpc.client import JSONRPCClient
    return JSONRPCClient(url).call("debug_profile", action="dump")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sources", nargs="*",
                    help="node RPC base URLs (http://host:port)")
    ap.add_argument("--files", nargs="*", default=[],
                    help="read dump files instead of fetching over RPC")
    ap.add_argument("--out", default="merged.collapsed",
                    help="merged collapsed-stack output path")
    ap.add_argument("--report", default="",
                    help="also write the merge summary (per-node and "
                         "cluster subsystem shares) as JSON")
    args = ap.parse_args(argv)

    dumps = []
    for path in args.files:
        with open(path) as f:
            dumps.append(json.load(f))
    for url in args.sources:
        dumps.append(fetch(url))
    if not dumps:
        ap.error("no sources: pass node URLs or --files")

    live = []
    for d in dumps:
        prof = d.get("profile", d)
        if not prof.get("samples") and not prof.get("wait_samples"):
            print(f"[profile_merge] node {d.get('node', '?')}: no "
                  f"samples (TM_TPU_PROF off?), skipped",
                  file=sys.stderr)
            continue
        live.append(d)
    if not live:
        print("[profile_merge] no profiled nodes", file=sys.stderr)
        return 1

    merged = profile.merge_dumps(live)
    with open(args.out, "w") as f:
        f.write(merged["collapsed"] + "\n")
    n_stacks = len(merged["collapsed"].splitlines())
    print(f"[profile_merge] {len(live)} nodes, {merged['samples']} "
          f"busy + {merged['wait_samples']} lock-wait samples, "
          f"{n_stacks} stacks -> {args.out}")
    print("[profile_merge] render: flamegraph.pl < "
          f"{args.out} > flame.svg  (or paste into speedscope.app)")

    shares = merged["shares"]
    if shares:
        width = max(len(s) for s in shares)
        print(f"  {'subsystem'.ljust(width)}  busy%   lock-wait")
        for sub, share in shares.items():
            waits = merged["lock_wait"].get(sub, 0)
            print(f"  {sub.ljust(width)} {share * 100:6.2f}   {waits}")

    if args.report:
        report = {
            "nodes": merged["nodes"],
            "samples_busy": merged["samples"],
            "samples_lock_wait": merged["wait_samples"],
            "shares": shares,
            "lock_wait_by_subsystem": merged["lock_wait"],
            "per_node": [
                {"node": d.get("node", "?"),
                 "samples": d.get("profile", d).get("samples", 0),
                 "shares": d.get("profile", d).get("shares", {})}
                for d in live],
        }
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
        print(f"[profile_merge] full report -> {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
