#!/usr/bin/env python
"""Metric-catalog lint — thin shim over the tmlint metrics checker.

The real rules live in tendermint_tpu/analysis/checkers/metrics.py
(run by scripts/lint.py and tier-1 via tests/test_lint.py); this entry
point is kept because test_telemetry and operator muscle memory invoke
it directly. Exit 0 + "OK" when clean; 1 with one line per violation.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.analysis.checkers.metrics import (  # noqa: E402,F401
    INSTRUMENTED_MODULES,
    KNOWN_SUBSYSTEMS,
)


def main() -> int:
    from tendermint_tpu.analysis.checkers import metrics
    findings = metrics.run()
    if findings:
        for f in findings:
            print(f"check_metrics: {f.message}")
        return 1
    print(f"check_metrics: OK ({metrics.run.summary})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
