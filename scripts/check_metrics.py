#!/usr/bin/env python
"""Metric-catalog lint (run in the tier-1 flow and by test_telemetry).

Imports every instrumented module so each registers its families into
the process-wide registry, then fails on:

  - duplicate FULL names after namespacing (a histogram `x` and a
    counter `x_bucket` would collide in exposition)
  - un-namespaced names: every metric must lead with a known subsystem
    prefix (`verifier_`, `consensus_`, ...) so dashboards can group
  - convention breaks: counters must end in `_total`; `_seconds` /
    `_bytes` metrics must be histograms or gauges
  - an exposition that fails its own line grammar

Exit 0 + "OK" when clean; 1 with one line per violation otherwise.
"""

import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Every subsystem that registers metrics must appear here — a new
# instrumented module extends this set alongside docs/observability.md.
KNOWN_SUBSYSTEMS = {
    "verifier", "consensus", "mempool", "fastsync", "p2p", "merkle",
    "rpc", "node", "storage", "evidence", "lite", "telemetry", "event",
    "chaos",
}

INSTRUMENTED_MODULES = [
    "tendermint_tpu.models.verifier",
    "tendermint_tpu.models.coalescer",
    "tendermint_tpu.ops.merkle",
    "tendermint_tpu.consensus.state",
    "tendermint_tpu.mempool.mempool",
    "tendermint_tpu.blockchain.pool",
    "tendermint_tpu.p2p.switch",
    "tendermint_tpu.p2p.conn.secret",    # tm_p2p_seal/open_seconds
    "tendermint_tpu.p2p.conn.mconn",     # tm_p2p_frames_per_burst
    "tendermint_tpu.types.events",       # tm_event_dropped_total
    "tendermint_tpu.rpc.core",
    "tendermint_tpu.chaos",              # tm_chaos_* fault/invariant plane
]

_LINE_RE = re.compile(
    r'^[a-z_][a-z0-9_]*(\{[a-z0-9_]+="(?:[^"\\]|\\.)*"'
    r'(,[a-z0-9_]+="(?:[^"\\]|\\.)*")*\})? -?[0-9.e+Inf-]+$')


def main() -> int:
    import importlib
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from tendermint_tpu import telemetry

    problems = []
    names = telemetry.REGISTRY.names()
    if not names:
        problems.append("registry is empty — instrumented modules "
                        "registered nothing")

    # subsystem prefixes + kind conventions
    exposed = set()
    for name in names:
        fam = telemetry.REGISTRY.get(name)
        subsystem = name.split("_", 1)[0]
        if subsystem not in KNOWN_SUBSYSTEMS or "_" not in name:
            problems.append(
                f"{name}: not namespaced by a known subsystem "
                f"(known: {sorted(KNOWN_SUBSYSTEMS)})")
        if fam.kind == "counter" and not name.endswith("_total"):
            problems.append(f"{name}: counters must end in _total")
        if fam.kind == "counter" and (
                name.endswith("_seconds") or name.endswith("_bytes")):
            problems.append(f"{name}: unit-suffixed metrics must be "
                            f"histograms or gauges")
        # exposition-level collisions (histogram series suffixes)
        series = {name}
        if fam.kind == "histogram":
            series = {name + s for s in ("_bucket", "_sum", "_count")}
        clash = series & exposed
        if clash:
            problems.append(f"{name}: exposition series collide: {clash}")
        exposed |= series

    # the exposition must parse line by line
    for line in telemetry.expose().splitlines():
        if not line or line.startswith("#"):
            continue
        if not _LINE_RE.match(line):
            problems.append(f"unparseable exposition line: {line!r}")

    if problems:
        for p in problems:
            print(f"check_metrics: {p}")
        return 1
    print(f"check_metrics: OK ({len(names)} families, "
          f"{len(exposed)} exposed series names)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
