#!/usr/bin/env python
"""slo_report — N per-node /slo payloads -> one cluster latency table.

Fetches every node's tx-lifecycle SLO snapshot (the `slo` RPC route
with sketches=true, or snapshot files on disk), concatenates the
weighted quantile-sketch samples — sampling is deterministic and
hash-based, so every node tracked the SAME txs and the merge is a
straight weighted union — and prints one per-stage p50/p95/p99/p999
table for the cluster, plus per-node completion/drop accounting.

Usage:
    python scripts/slo_report.py \
        http://127.0.0.1:46657 http://127.0.0.1:46659 ...
    python scripts/slo_report.py --files slo0.json slo1.json ...
        [--report report.json]

Nodes must run with TM_TPU_SLO=on; a node with the plane off is
reported and skipped. The merge itself lives in
tendermint_tpu/telemetry/slo.py (importable, unit-tested)."""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tendermint_tpu.telemetry import slo  # noqa: E402


def fetch(url: str) -> dict:
    """One node's SLO snapshot (with mergeable sketches) over its
    JSON-RPC endpoint."""
    from tendermint_tpu.rpc.client import JSONRPCClient
    return JSONRPCClient(url).call("slo", sketches=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sources", nargs="*",
                    help="node RPC base URLs (http://host:port)")
    ap.add_argument("--files", nargs="*", default=[],
                    help="read snapshot files instead of fetching "
                         "over RPC")
    ap.add_argument("--report", default="",
                    help="also write the merged table + per-node "
                         "accounting as JSON")
    args = ap.parse_args(argv)

    docs = []
    for path in args.files:
        with open(path) as f:
            docs.append(json.load(f))
    for url in args.sources:
        docs.append(fetch(url))
    if not docs:
        ap.error("no sources: pass node URLs or --files")

    live = []
    for d in docs:
        if not d.get("enabled"):
            print(f"[slo_report] node {d.get('node', '?')}: SLO plane "
                  f"off (TM_TPU_SLO?), skipped", file=sys.stderr)
            continue
        if not d.get("sketches"):
            print(f"[slo_report] node {d.get('node', '?')}: no "
                  f"sketches in payload (call with sketches=true), "
                  f"skipped", file=sys.stderr)
            continue
        live.append(d)
    if not live:
        print("[slo_report] no SLO-enabled nodes", file=sys.stderr)
        return 1

    merged = slo.merge_snapshots(live)
    print(f"[slo_report] {len(live)} nodes, "
          f"{merged['sampled_total']} sampled, "
          f"{merged['completed_total']} delivered, "
          f"{merged['dropped']} dropped, "
          f"{merged['in_flight']} in flight")
    stages = merged["stages"]
    if stages:
        width = max(len(s) for s in stages)
        print(f"  {'stage'.ljust(width)}  {'count':>7}  {'p50':>9}  "
              f"{'p95':>9}  {'p99':>9}  {'p999':>9}  (ms)")
        for name in slo.SERIES:
            row = stages.get(name)
            if row is None:
                continue
            print(f"  {name.ljust(width)}  {row['count']:>7}  "
                  f"{row['p50_ms']:>9}  {row['p95_ms']:>9}  "
                  f"{row['p99_ms']:>9}  {row['p999_ms']:>9}")
    for d in live:
        att = d.get("attribution", {})
        if att.get("ready"):
            print(f"  node {d.get('node', '?')}: p99 tail dominated by "
                  f"'{att['dominant_stage']}' "
                  f"(mean legs ms: {att['mean_leg_ms']})")

    if args.report:
        report = {
            "merged": merged,
            "per_node": [
                {"node": d.get("node", "?"),
                 "sampled_total": d.get("sampled_total", 0),
                 "completed_total": d.get("completed_total", 0),
                 "dropped": d.get("dropped", {}),
                 "verdict": d.get("verdict", {}),
                 "attribution": d.get("attribution", {})}
                for d in live],
        }
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
        print(f"[slo_report] full report -> {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
