#!/usr/bin/env python
"""trace_merge — N per-node span buffers -> one cluster timeline.

Fetches every node's causal span ring (the `dump_height_timeline` RPC
route, or `GET /debug/timeline`, or dump files on disk), aligns their
wall clocks from the paired (send, recv) readings trace-stamped p2p
envelopes carry (NTP-style pairwise minimum-delay estimate, propagated
over the peer graph; the keepalive RTT histograms are the sanity
cross-check), and writes:

- a single Perfetto/Chrome trace (load at https://ui.perfetto.dev):
  one track per node, every consensus span on the reference clock;
- a per-height latency-attribution table: time-to-first-part,
  full-block, +2/3 prevote, +2/3 precommit, apply, persist — p50/p95
  per stage, plus each height's coverage of observed wall-clock.

Usage:
    python scripts/trace_merge.py --out merged.json \
        http://127.0.0.1:46657 http://127.0.0.1:46659 ...
    python scripts/trace_merge.py --files dump0.json dump1.json ...
        [--out merged.json] [--report report.json] [--min-height H]

Nodes must run with TM_TPU_TRACE=on; an `enabled: false` dump is
reported and skipped. The heavy lifting lives in
tendermint_tpu/telemetry/merge.py (importable, unit-tested).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tendermint_tpu.telemetry import merge  # noqa: E402


def fetch(url: str, min_height: int = 0, max_height: int = 0) -> dict:
    """One node's span ring over its JSON-RPC endpoint."""
    from tendermint_tpu.rpc.client import JSONRPCClient
    return JSONRPCClient(url).call("dump_height_timeline",
                                   min_height=min_height,
                                   max_height=max_height)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sources", nargs="*",
                    help="node RPC base URLs (http://host:port)")
    ap.add_argument("--files", nargs="*", default=[],
                    help="read dump files instead of fetching over RPC")
    ap.add_argument("--out", default="merged_trace.json",
                    help="Perfetto trace output path")
    ap.add_argument("--report", default="",
                    help="also write the full merge report (offsets, "
                         "RTT floors, attribution) as JSON")
    ap.add_argument("--min-height", type=int, default=0)
    ap.add_argument("--max-height", type=int, default=0)
    args = ap.parse_args(argv)

    dumps = []
    for path in args.files:
        with open(path) as f:
            dumps.append(json.load(f))
    for url in args.sources:
        dumps.append(fetch(url, args.min_height, args.max_height))
    if not dumps:
        ap.error("no sources: pass node URLs or --files")

    live = []
    for d in dumps:
        if not d.get("enabled", True) and not d.get("spans"):
            print(f"[trace_merge] node {d.get('node', '?')}: tracing "
                  f"disabled (TM_TPU_TRACE off), skipped",
                  file=sys.stderr)
            continue
        live.append(d)
    if not live:
        print("[trace_merge] no traced nodes", file=sys.stderr)
        return 1

    report = merge.merge_report(live)
    with open(args.out, "w") as f:
        json.dump(report["perfetto"], f)
    print(f"[trace_merge] {len(live)} nodes, "
          f"{len(report['perfetto']['traceEvents'])} events -> "
          f"{args.out} (load at https://ui.perfetto.dev)")

    attr = report["attribution"]
    print(f"[trace_merge] clock offsets (ms): "
          f"{report['clock_offsets_ms']}")
    print(f"[trace_merge] {attr['heights']} heights attributed "
          f"(skipped {attr['heights_skipped']}), mean coverage "
          f"{attr['coverage_mean']:.1%}")
    stages = attr.get("stages_ms_p50_p95", {})
    if stages:
        width = max(len(s) for s in stages)
        print(f"  {'stage'.ljust(width)}   p50 ms   p95 ms")
        for stage, row in stages.items():
            print(f"  {stage.ljust(width)} {row['p50_ms']:8.2f} "
                  f"{row['p95_ms']:8.2f}")

    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
        print(f"[trace_merge] full report -> {args.report}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
