"""4-validator testnet commit-rate bench (BASELINE.json config 1).

The reference's config-1 baseline is a 4-validator local testnet running
the kvstore ABCI app with 1000-tx blocks. Here: four in-process
ConsensusStates over a full-mesh relay (the same wiring the consensus
test nets use), MockTicker-driven so the measured rate is the ENGINE's
throughput — proposal build + part gossip + vote verify + apply — not
the configured wall-clock timeouts. Each proposer reaps 1000 txs per
block from its mempool.

Standalone: `python bench_testnet.py [n_blocks] [n_vals] [n_txs]`
prints one JSON line. bench.py folds `run()` into `extra` for the
driver.
"""

from __future__ import annotations

import json
import sys
import time

from bench_util import enable_tpu_compilation_cache

enable_tpu_compilation_cache()  # must precede any jax import

from tendermint_tpu.utils import knobs  # noqa: E402 (post-cache-setup)


class _BenchMempool:
    """Endless reap: always has the next block's txs ready. `pending`
    carries real injected txs (the churn driver's val: txs) ahead of
    the fabricated filler — removed once seen committed, so every
    node's copy drains in step like a real mempool."""

    def __init__(self, n_txs: int):
        self.n_txs = n_txs
        self._next = 0
        self.committed = 0
        self.pending = []

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return self.n_txs

    def inject(self, tx: bytes):
        if tx not in self.pending:
            self.pending.append(tx)

    def reap(self, max_txs: int):
        base = self._next
        k = self.n_txs if max_txs < 0 else min(self.n_txs, max_txs)
        out = list(self.pending[:k])
        return out + [b"bench/k%d=v%d" % (base + i, i)
                      for i in range(k - len(out))]

    def update(self, height, txs):
        self._next += len(txs)
        self.committed += len(txs)
        if self.pending:
            committed = set(txs)
            self.pending = [t for t in self.pending
                            if t not in committed]

    def txs_available(self):
        return True


def run(n_blocks: int = 30, n_vals: int = 4, n_txs: int = 1000,
        churn_every: int = 0, churn_standby: int = 2) -> dict:
    """`churn_every` > 0 turns on the validator-churn driver: every
    that-many committed heights one `val:` tx (join a standby key /
    stake-change it / leave it, cycling) is injected into every
    node's mempool — the valset rotates through REAL EndBlock
    validator_updates while the bench measures. Standby keys run no
    ConsensusState (a joined-but-absent validator costs rounds when
    it wins proposer — that cost is part of what churn measures)."""
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.abci.types import ValidatorUpdate
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.consensus import ConsensusState, MockTicker
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.storage import BlockStore, MemDB, StateStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
    from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator

    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n_vals)]
    standby = [PrivKey.generate(bytes([200, i + 1]) * 16)
               for i in range(churn_standby if churn_every else 0)]
    gen = GenesisDoc(chain_id="bench-net", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])

    nodes = []
    for k in keys:
        conns = AppConns(local_client_creator(KVStoreApp()))
        state_store = StateStore(MemDB())
        block_store = BlockStore(MemDB())
        state = state_store.load_or_genesis(gen)
        conns.consensus.init_chain(
            [ValidatorUpdate(v.pubkey, v.voting_power)
             for v in state.validators.validators], gen.chain_id)
        mp = _BenchMempool(n_txs)
        exec_ = BlockExecutor(state_store, conns.consensus, mempool=mp)
        cs = ConsensusState(
            make_test_config().consensus, state, exec_, block_store,
            mempool=mp, priv_validator=PrivValidator(LocalSigner(k)),
            ticker_factory=MockTicker)
        nodes.append(cs)

    # full-mesh relay of proposal/part/vote broadcasts
    for i, src in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part",
                                              "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src.broadcast_hooks.append(relay)

    def fire_all():
        n = 0
        for node in nodes:
            if node.ticker.fire_next() is not None:
                n += 1
        return n

    for node in nodes:
        node.start()

    # churn driver: deterministic op cycle over the standby keys,
    # advanced by committed height, injected into EVERY mempool (the
    # next proposer includes it; absolute powers make a duplicate
    # inclusion idempotent)
    churn_state = {"next_h": churn_every + 1, "op_i": 0, "ops": 0,
                   "joined": []}

    def drive_churn():
        if not churn_every or not standby:
            return
        h = min(n.state.last_block_height for n in nodes)
        if h < churn_state["next_h"]:
            return
        churn_state["next_h"] = h + churn_every
        kind = ("join", "stake", "leave")[churn_state["op_i"] % 3]
        churn_state["op_i"] += 1
        tx = None
        if kind == "join":
            free = [k for k in standby
                    if k not in churn_state["joined"]]
            if free:
                churn_state["joined"].append(free[0])
                tx = b"val:%s/10" % free[0].pubkey.ed25519.hex().encode()
        elif kind == "stake" and churn_state["joined"]:
            tx = b"val:%s/15" % churn_state["joined"][0] \
                .pubkey.ed25519.hex().encode()
        elif kind == "leave" and churn_state["joined"]:
            k = churn_state["joined"].pop(0)
            tx = b"val:%s/0" % k.pubkey.ed25519.hex().encode()
        if tx is not None:
            churn_state["ops"] += 1
            for node in nodes:
                node.mempool.inject(tx)

    def run_to(height, max_ticks):
        for _ in range(max_ticks):
            if all(n.state.last_block_height >= height for n in nodes):
                return True
            drive_churn()
            fire_all()
        return all(n.state.last_block_height >= height for n in nodes)

    # warmup: first blocks pay kernel compiles + app-hash settling
    assert run_to(2, 400), "testnet warmup stalled"

    h0 = min(n.state.last_block_height for n in nodes)
    tx0 = nodes[0].mempool.committed
    t0 = time.perf_counter()
    target = h0 + n_blocks
    assert run_to(target, 400 * n_blocks), "testnet bench stalled"
    dt = time.perf_counter() - t0
    blocks = min(n.state.last_block_height for n in nodes) - h0
    txs = nodes[0].mempool.committed - tx0

    final_vals = nodes[0].state.validators
    out = {
        "blocks_per_sec": round(blocks / dt, 2),
        "txs_per_sec": round(txs / dt, 1),
        "blocks": blocks, "n_vals": n_vals, "txs_per_block": n_txs,
        "seconds": round(dt, 3),
    }
    if churn_every:
        out["churn"] = {
            "ops_injected": churn_state["ops"],
            "final_valset_size": len(final_vals),
            "final_total_power": final_vals.total_voting_power(),
            "last_height_validators_changed":
                nodes[0].state.last_height_validators_changed,
        }
    for node in nodes:
        node.stop()
    return out


def _scrape_p2p_metrics(client) -> dict:
    """Pull the frame-plane instruments from one node's /metrics
    exposition (the nodes are separate OS processes — telemetry lives
    behind their RPC, exactly where a production scrape would read)."""
    import re
    text = client.call("metrics")["exposition"]
    vals = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = re.match(r'^(tm_p2p_[a-z_]+?)(\{[^}]*\})? ([0-9.e+-]+)$', line)
        if not m:
            continue
        name, labels, v = m.group(1), m.group(2) or "", float(m.group(3))
        vals[name + labels] = vals.get(name + labels, 0.0) + v
    out = {}
    fsum = vals.get('tm_p2p_frames_per_burst_sum{direction="send"}', 0.0)
    fcnt = vals.get('tm_p2p_frames_per_burst_count{direction="send"}', 0.0)
    if fcnt:
        out["mean_frames_per_send_burst"] = round(fsum / fcnt, 2)
    sealed = vals.get("tm_p2p_frames_sealed_total", 0.0)
    seal_s = vals.get("tm_p2p_seal_seconds_sum", 0.0)
    if sealed:
        out["seal_us_per_frame"] = round(seal_s / sealed * 1e6, 2)
        out["frames_sealed"] = int(sealed)
    opened = vals.get("tm_p2p_frames_opened_total", 0.0)
    open_s = vals.get("tm_p2p_open_seconds_sum", 0.0)
    if opened:
        out["open_us_per_frame"] = round(open_s / opened * 1e6, 2)
    return out


def _scrape_pipeline_metrics(client) -> dict:
    """tm_pipeline_* / tm_partset_* from one node's /metrics — per-stage
    seconds, overlap ratio and precompute outcomes, so the bench arms
    can attribute the win to specific pipeline stages."""
    import re
    text = client.call("metrics")["exposition"]
    sums, counts, out = {}, {}, {}
    for line in text.splitlines():
        m = re.match(r'^(tm_(?:pipeline|partset)_[a-z_]+?)'
                     r'(\{[^}]*\})? ([0-9.e+-]+)$', line)
        if not m:
            continue
        name, labels, v = m.group(1), m.group(2) or "", float(m.group(3))
        if name.endswith("_sum"):
            sums[name[:-4] + labels] = v
        elif name.endswith("_count"):
            counts[name[:-6] + labels] = v
        elif name.endswith("_total"):
            out[name + labels] = int(v)
    for key, s in sums.items():
        n = counts.get(key, 0)
        if n:
            out[key + "_mean"] = round(s / n, 6)
            out[key + "_count"] = int(n)
    return out


def _scrape_compact_metrics(clients) -> dict:
    """tm_compact_* / tm_voteagg_* summed across EVERY node — one
    node's sends are another's reconstructions, so per-node numbers
    understate the plane. Adds the two derived ratios the trend gate
    tracks: reconstruct hit rate (hit+fetched over all attempts) and
    mean votes per aggregate."""
    import re
    out: dict = {}
    for c in clients:
        text = c.call("metrics")["exposition"]
        for line in text.splitlines():
            m = re.match(r'^(tm_(?:compact|voteagg)_[a-z_]+?)'
                         r'(\{[^}]*\})? ([0-9.e+-]+)$', line)
            if not m:
                continue
            key = m.group(1) + (m.group(2) or "")
            out[key] = out.get(key, 0.0) + float(m.group(3))
    if not out:
        return {}
    out = {k: (int(v) if float(v).is_integer() else v)
           for k, v in out.items()}
    hit = out.get('tm_compact_reconstruct_total{outcome="hit"}', 0)
    fetched = out.get(
        'tm_compact_reconstruct_total{outcome="fetched"}', 0)
    fallback = out.get(
        'tm_compact_reconstruct_total{outcome="fallback"}', 0)
    attempts = hit + fetched + fallback
    if attempts:
        out["compact_reconstruct_hit_rate"] = round(
            (hit + fetched) / attempts, 4)
    batch_sum = out.get("tm_voteagg_batch_votes_sum", 0)
    batch_n = out.get("tm_voteagg_batch_votes_count", 0)
    if batch_n:
        out["voteagg_mean_batch"] = round(batch_sum / batch_n, 2)
    return out


def _chain_parity(clients, part_size: int = 65536) -> dict:
    """Bit-identity audit of a finished arm's chain, recomputed SERIALLY
    in this (parent) process:

    - every block's bytes re-encode to the stored header hash
      (Block.from_obj -> to_bytes -> from_bytes round trip),
    - every block's header.app_hash equals a fresh serial KVStore
      replay of the txs so far (the AppHash chain is bit-identical to
      what the non-pipelined executor would produce),
    - the committed part-set roots equal both the serial Python split
      and the native one-call builder, recomputed from the block bytes,
    - all validators report the same height/app-hash frontier.

    Raises AssertionError on any mismatch; returns a summary dict."""
    from tendermint_tpu import native
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.ops import merkle
    from tendermint_tpu.types.block import Block

    h = min(c.call("status")["latest_block_height"] for c in clients)
    first = 1
    app = KVStoreApp()
    app_hash = b""
    partset_checks = 0
    for height in range(first, h + 1):
        r = clients[0].call("block", height=height)
        meta, blk_obj = r["block_meta"], r["block"]
        block = Block.from_obj(blk_obj)
        if height > 1:
            assert block.header.app_hash == app_hash, (
                f"height {height}: header.app_hash diverged from "
                f"serial replay")
        data = block.to_bytes()
        rt = Block.from_bytes(data)
        assert rt.hash().hex() == meta["block_id"]["hash"], (
            f"height {height}: block bytes do not re-encode to the "
            f"stored header hash")
        want_root = meta["block_id"]["parts"]["hash"]
        chunks = [data[i:i + part_size]
                  for i in range(0, len(data), part_size)] or [b""]
        serial_root, _ = merkle.tree_proofs_host(chunks)
        assert serial_root.hex() == want_root, (
            f"height {height}: serial part-set root != committed root")
        built = native.partset_build(data, part_size)
        if built is not None:
            assert built[0].hex() == want_root, (
                f"height {height}: native part-set root != committed")
        partset_checks += 1
        for tx in block.data.txs:
            app.deliver_tx(tx)
        app_hash = app.commit()
    frontiers = set()
    for c in clients:
        s = c.call("status")
        if s["latest_block_height"] >= h:
            b = c.call("block", height=h)
            frontiers.add((b["block_meta"]["block_id"]["hash"],
                           b["block"]["header"]["app_hash"]))
    assert len(frontiers) == 1, f"validators disagree at {h}: {frontiers}"
    return {"blocks_verified": h - first + 1,
            "app_hash_chain_bit_identical": True,
            "block_bytes_bit_identical": True,
            "partset_roots_bit_identical": partset_checks,
            "validators_agree_at": h}


def _scrape_chaos_metrics(client) -> dict:
    """tm_chaos_faults_injected_total by kind from one node's /metrics
    — evidence the chaos plane actually fired in a TM_TPU_CHAOS run."""
    import re
    text = client.call("metrics")["exposition"]
    out = {}
    for line in text.splitlines():
        m = re.match(r'^tm_chaos_faults_injected_total\{kind="([a-z_]+)"\}'
                     r' ([0-9.e+-]+)$', line)
        if m:
            out[m.group(1)] = int(float(m.group(2)))
    return out


def _scrape_ban_metrics(client) -> dict:
    """tm_p2p_bans/unbans/peer_errors/accept_shed/handshake_failures
    from one node's /metrics — the hostile-peer defense witness."""
    import re
    text = client.call("metrics")["exposition"]
    out = {}
    for line in text.splitlines():
        m = re.match(
            r'^(tm_p2p_(?:bans|unbans|peer_errors|accept_shed|'
            r'handshake_failures|frame_error_disconnects)_total|'
            r'tm_p2p_banned_peers)(\{[^}]*\})? ([0-9.e+-]+)$', line)
        if m:
            out[m.group(1) + (m.group(2) or "")] = int(float(m.group(3)))
    return out


def run_socket(n_vals: int = 4, n_txs_target: int = 1000,
               duration_s: float = 25.0, burst: str = "",
               chaos: str = "", pipeline: str = "",
               parity: bool = False, trace: str = "",
               profile: str = "", reactor: str = "",
               wire_chaos: dict = None, wire_seed: int = 0,
               hostile: tuple = (), liveness_bound_s: float = 30.0,
               child_env: dict = None, p2p_cfg: dict = None,
               slo: str = "", slo_sample: float = 0.0,
               tx_subscribers: int = 0) -> dict:
    """Config 1 over REAL sockets: n_vals separate OS processes
    (`cli node --p2p`), real TCP P2P + secret connections + local ABCI,
    txs injected over HTTP RPC by background spammer threads; commit
    rate and committed tx/s measured from block metas over a wall-clock
    window. The analogue of the reference's dockerized
    test/p2p/atomic_broadcast testnet, recorded as a NUMBER (the
    in-process `run()` above isolates the engine; this arm includes
    every socket, handshake, and gossip cost). On a 1-core bench host
    the four nodes and the spammers share one core — the figure is a
    floor, not the engine ceiling."""
    import json as _json
    import os
    import socket as _socket
    import subprocess
    import tempfile
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))

    from bench_util import free_port_block, node_child_env
    env = node_child_env(repo)
    if burst:  # per-arm override for the frame-plane A/B (bench.py
        #        --p2p-json); "" inherits whatever the caller exported
        env["TM_TPU_P2P_BURST"] = burst
    if chaos:  # chaos-plane link faults for every node (e.g.
        #        "drop=0.02,delay=0.05,seed=7"); "" inherits caller env
        env["TM_TPU_CHAOS"] = chaos
    if pipeline:  # per-arm hot-path pipeline A/B (bench.py --p2p-json);
        #          "" inherits whatever the caller exported
        env["TM_TPU_PIPELINE"] = pipeline
    if trace:  # causal tracing plane for every node (bench.py
        #       --trace-json); "" inherits whatever the caller exported
        env["TM_TPU_TRACE"] = trace
    if profile:  # sampling profiler A/B for every node (bench.py
        #         --profile-json); "" inherits the caller env
        env["TM_TPU_PROF"] = profile
    if reactor:  # async reactor core A/B (bench.py --p2p-json):
        #         loop = one event loop per node, threads = the
        #         per-connection thread plane; "" inherits caller env
        env["TM_TPU_REACTOR"] = reactor
    if slo:  # tx-lifecycle SLO plane A/B for every node (bench.py
        #     --slo-json); "" inherits whatever the caller exported
        env["TM_TPU_SLO"] = slo
        if slo_sample > 0:
            env["TM_TPU_SLO_SAMPLE"] = str(slo_sample)
    if child_env:  # per-run node knobs (bench.py --wirechaos-json uses
        #           this to shorten ban windows so the unban shows up
        #           inside the measured window)
        env.update(child_env)

    net = tempfile.mkdtemp(prefix="bench-socknet-")
    base = free_port_block(2 * n_vals)
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cli", "testnet",
         "--n", str(n_vals), "--output", net, "--base-port", str(base),
         "--chain-id", "bench-socknet"],
        env=env, check=True, capture_output=True, timeout=120)
    for i in range(n_vals):
        cfg_path = os.path.join(net, f"node{i}", "config", "config.json")
        cfg = _json.load(open(cfg_path))
        cfg["consensus"].update({
            "timeout_propose": 400, "timeout_propose_delta": 100,
            "timeout_prevote": 200, "timeout_prevote_delta": 100,
            "timeout_precommit": 200, "timeout_precommit_delta": 100,
            "timeout_commit": 100,
            "max_block_size_txs": n_txs_target})
        # a few blocks of backlog: enough to keep every block at
        # the 1000-tx reap cap, small enough that per-commit
        # recheck + mempool-WAL rewrite stay O(small)
        cfg["mempool"] = dict(cfg.get("mempool", {}), size=4000)
        if p2p_cfg:
            # per-run p2p overrides (the wirechaos bench shortens the
            # handshake deadline so slow-loris disconnects land inside
            # the measured window)
            cfg["p2p"] = dict(cfg.get("p2p", {}), **p2p_cfg)
        _json.dump(cfg, open(cfg_path, "w"))

    # wire-level chaos (ISSUE 13): route every directed p2p link
    # through the seeded TCP fault proxy — node i's persistent_peers
    # entry for node j points at proxy port (i, j), which forwards to
    # j's real listener injecting the schedule's faults. PEX is
    # disabled so no conn can discover a direct (unproxied) address.
    proxy = wire_sched = wire_monitor = None
    wire_t0 = None
    hostile_threads: list = []
    hostile_reports: list = []
    slo_subs: list = []
    if wire_chaos is not None:
        from tendermint_tpu.chaos import wire as wire_mod
        proxy, wire_sched = wire_mod.proxy_for_testnet(
            wire_chaos, wire_seed, n_vals, lambda j: base + 2 * j)
        for i in range(n_vals):
            cfg_path = os.path.join(net, f"node{i}", "config",
                                    "config.json")
            cfg = _json.load(open(cfg_path))
            peers = []
            for entry in cfg["p2p"]["persistent_peers"].split(","):
                if not entry:
                    continue
                pid, hostport = entry.split("@", 1)
                port = int(hostport.rsplit(":", 1)[1])
                j = (port - base) // 2
                peers.append(f"{pid}@127.0.0.1:{proxy.ports[(i, j)]}")
            cfg["p2p"]["persistent_peers"] = ",".join(peers)
            cfg["p2p"]["pex"] = False
            _json.dump(cfg, open(cfg_path, "w"))
        proxy.start()

    procs, logs = [], []
    cleanup_ok = [False]
    n_spammers = 2
    stop = threading.Event()
    sent = [0] * n_spammers
    try:
        for i in range(n_vals):
            log = open(os.path.join(net, f"node{i}.log"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.cli",
                 "--home", os.path.join(net, f"node{i}"),
                 "node", "--p2p", "--no-fast-sync",
                 "--rpc-laddr", f"tcp://127.0.0.1:{base + 2 * i + 1}",
                 "--max-seconds", "600"],
                env=env, stdout=log, stderr=subprocess.STDOUT))

        from tendermint_tpu.rpc.client import (JSONRPCClient,
                                               RPCClientError)
        clients = [JSONRPCClient(f"http://127.0.0.1:{base + 2 * i + 1}")
                   for i in range(n_vals)]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                if all(c.call("status")["latest_block_height"] >= 2
                       for c in clients):
                    break
            except (OSError, RPCClientError):
                pass  # still booting; the liveness check below decides
            if any(p.poll() is not None for p in procs):
                raise RuntimeError("socket-testnet node died during boot")
            time.sleep(0.5)
        else:
            raise RuntimeError("socket testnet made no progress")

        def spam(tid):
            # tm-bench shape, batched: fire-and-forget broadcast_tx_batch
            # casts of 128 txs over one persistent websocket. Per-tx
            # casts cost a server round trip each and capped injection
            # at ~500 tx/s on this shared core; the pipelined commit
            # path drains thousands per second, so the spammers must
            # keep up for blocks to stay at the 1000-tx reap cap.
            from tendermint_tpu.rpc.client import WSClient
            ws = None
            i = 0
            while not stop.is_set():
                try:
                    if ws is None:
                        ws = WSClient("127.0.0.1",
                                      base + 2 * (tid % n_vals) + 1)
                    for _ in range(4):
                        ws.cast("broadcast_tx_batch",
                                txs=[(b"s%d.%d=v" % (tid, i + k)).hex()
                                     for k in range(128)])
                        i += 128
                    sent[tid] = i  # per-thread slot: no racy +=
                    # periodic sync point: don't outrun the server,
                    # and back off while the backlog is deep enough
                    while not stop.is_set() and ws.call(
                            "num_unconfirmed_txs",
                            timeout=30.0)["n_txs"] > 3000:
                        time.sleep(0.05)
                except Exception:
                    if ws is not None:
                        try:
                            ws.close()
                        except OSError:
                            pass  # already torn down server-side
                        ws = None
                    time.sleep(0.2)

        spammers = [threading.Thread(target=spam, args=(t,), daemon=True)
                    for t in range(n_spammers)]
        for t in spammers:
            t.start()

        slo_on = bool(slo) and slo.lower() not in knobs.FALSY
        if tx_subscribers > 0:
            # Tx-event WS subscribers per node: the delivery-stage
            # witness for an SLO run (each node's deliver stamp is a
            # real fan-out socket write), attached INDEPENDENTLY of
            # the SLO knob so an off-vs-on A/B carries identical
            # event-delivery load on both arms; a bench-side thread
            # empties the client queues so nothing backlogs
            import queue as _queue
            from tendermint_tpu.rpc.client import WSClient
            for i in range(n_vals):
                for _ in range(tx_subscribers):
                    ws = WSClient("127.0.0.1", base + 2 * i + 1)
                    ws.subscribe("tm.event = 'Tx'")
                    slo_subs.append(ws)

            def drain_events():
                while not stop.is_set():
                    drained = False
                    for ws in slo_subs:
                        try:
                            for _ in range(4096):
                                ws.events.get_nowait()
                                drained = True
                        except _queue.Empty:
                            pass
                    if not drained:
                        time.sleep(0.05)

            threading.Thread(target=drain_events, daemon=True,
                             name="bench-slo-drain").start()
        # pre-fill: HTTP injection (~500 tx/s on this shared core) is
        # slower than commit throughput, so build a mempool BACKLOG
        # first — the measured window then reaps config-1-shaped
        # (1000-tx) blocks, the sustained-load profile of the
        # reference's atomic_broadcast testnet
        def check_alive():
            dead = [i for i, p in enumerate(procs)
                    if p.poll() is not None]
            if dead:
                raise RuntimeError(f"socket-testnet nodes died: {dead}")

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            check_alive()
            try:
                if clients[0].call("num_unconfirmed_txs")[
                        "n_txs"] >= 2500:
                    break
            except (OSError, RPCClientError):
                pass  # node busy/restarting; check_alive decides
            time.sleep(1.0)

        if proxy is not None:
            # faults begin WITH the measured window (boot + prefill ran
            # on a clean wire); the monitor sees exactly what an
            # operator's scrape would
            from tendermint_tpu.chaos import wire as wire_mod
            wire_t0 = proxy.arm()
            wire_monitor = wire_mod.SocketInvariantMonitor(
                [f"http://127.0.0.1:{base + 2 * i + 1}"
                 for i in range(n_vals)])
            wire_monitor.start()
        for script in hostile:
            # hostile peers aim at node0's REAL p2p listener — the
            # defenses under test live in the victim, not the proxy
            from tendermint_tpu.chaos import hostile as hostile_mod

            def run_script(s=script):
                kw = {}
                if s == "garbage_after_auth":
                    kw = {"rounds": 12, "retry_gap_s": 1.2,
                          "budget_s": duration_s + 10}
                elif s == "flood":
                    kw = {"count": 48, "hold_s": 2.0}
                elif s == "slow_handshake":
                    kw = {"byte_interval_s": 0.5,
                          "budget_s": min(20.0, duration_s)}
                elif s == "handshake_stall":
                    kw = {"budget_s": min(20.0, duration_s)}
                try:
                    hostile_reports.append(hostile_mod.run_hostile(
                        s, "127.0.0.1", base, network="bench-socknet",
                        channels=[], **kw))
                except Exception as e:
                    hostile_reports.append({"script": s,
                                            "error": repr(e)})
            t = threading.Thread(target=run_script, daemon=True,
                                 name=f"hostile-{script}")
            t.start()
            hostile_threads.append(t)

        h0 = clients[0].call("status")["latest_block_height"]
        t0 = time.perf_counter()
        end_at = time.monotonic() + duration_s
        while time.monotonic() < end_at:
            check_alive()
            time.sleep(1.0)
        h1 = clients[0].call("status")["latest_block_height"]
        dt = time.perf_counter() - t0
        stop.set()
        wire_report = {}
        if proxy is not None:
            for t in hostile_threads:
                t.join(timeout=20.0)
            # grace so the monitor can observe post-heal progress for
            # late episodes, then judge
            time.sleep(3.0)
            ends = []
            for ep in wire_sched.episodes():
                end_t = wire_t0 + ep["end"] * wire_sched.step_ms / 1e3
                if end_t <= time.monotonic():
                    ends.append((ep["kind"], end_t))
            wire_monitor.stop()
            bans = {}
            for c in clients:
                try:
                    for k, v in _scrape_ban_metrics(c).items():
                        bans[k] = bans.get(k, 0) + v
                except (OSError, RPCClientError) as e:
                    print(f"[bench] ban scrape failed: {e!r}",
                          file=sys.stderr)
            wire_report = {
                "spec": wire_sched.spec, "seed": wire_sched.seed,
                "step_ms": wire_sched.step_ms,
                "plan": wire_sched.plan,
                "plan_sha256": wire_sched.plan_digest(),
                "faults_applied": wire_sched.applied_counts(),
                "monitor": wire_monitor.finalize(
                    ends, liveness_bound_s=liveness_bound_s),
                "hostile": hostile_reports,
                "ban_metrics": bans,
            }
        try:
            p2p_metrics = _scrape_p2p_metrics(clients[0])
        except Exception:
            p2p_metrics = {}
        try:
            pipeline_metrics = _scrape_pipeline_metrics(clients[0])
        except Exception:
            pipeline_metrics = {}
        try:
            compact_metrics = _scrape_compact_metrics(clients)
        except Exception:
            compact_metrics = {}
        timelines = []
        if trace:
            # every node's span ring BEFORE teardown: the measured
            # window's heights plus all link spans (clock alignment);
            # bench.py merges them into the cluster timeline
            for c in clients:
                try:
                    timelines.append(c.call(
                        "dump_height_timeline",
                        min_height=h0 + 1, max_height=h1))
                except (OSError, RPCClientError) as e:
                    print(f"[bench] timeline fetch failed: {e!r}",
                          file=sys.stderr)
        profiles = []
        if profile and profile.lower() not in ("off", "0", "false"):
            # every node's sampling-profiler table BEFORE teardown:
            # collapsed stacks + per-subsystem busy/wait sample counts
            # (bench.py merges them into the cluster profile)
            for c in clients:
                try:
                    profiles.append(c.call("debug_profile",
                                           action="dump"))
                except (OSError, RPCClientError) as e:
                    print(f"[bench] profile fetch failed: {e!r}",
                          file=sys.stderr)
        slo_reports = []
        if slo_on:
            # every node's SLO snapshot WITH mergeable sketches before
            # teardown (bench.py / scripts/slo_report.py merge them)
            for c in clients:
                try:
                    slo_reports.append(c.call("slo", sketches=True))
                except (OSError, RPCClientError) as e:
                    print(f"[bench] slo fetch failed: {e!r}",
                          file=sys.stderr)
        parity_report = {}
        if parity:
            # bit-identity audit BEFORE teardown: serial replay of the
            # whole chain in this process (AssertionError on mismatch)
            parity_report = _chain_parity(clients)
        chaos_metrics = {}
        if chaos or (knobs.knob_raw("TM_TPU_CHAOS") or "off") \
                .lower() not in knobs.FALSY:
            try:
                chaos_metrics = _scrape_chaos_metrics(clients[0])
            except Exception:
                pass
        txs = 0
        # the blockchain route caps at 20 metas per call: page through
        lo = h0 + 1
        while lo <= h1:
            hi = min(lo + 19, h1)
            metas = clients[0].call("blockchain", min_height=lo,
                                    max_height=hi)["block_metas"]
            txs += sum(m["header"]["num_txs"] for m in metas)
            lo = hi + 1
        cleanup_ok[0] = True
        return {
            "blocks_per_sec": round((h1 - h0) / dt, 2),
            "txs_per_sec": round(txs / dt, 1),
            "blocks": h1 - h0,
            "avg_txs_per_block": round(txs / max(1, h1 - h0), 1),
            "n_vals": n_vals, "seconds": round(dt, 1),
            "txs_injected": sum(sent),
            "transport": "tcp sockets, 4 OS processes, secret conns",
            "burst": burst or "default",
            "pipeline": pipeline or "default",
            "reactor": reactor or "default",
            "p2p": p2p_metrics,
            **({"pipeline_metrics": pipeline_metrics}
               if pipeline_metrics else {}),
            **({"compact_metrics": compact_metrics}
               if compact_metrics else {}),
            **({"parity": parity_report} if parity_report else {}),
            **({"chaos": chaos, "chaos_faults": chaos_metrics}
               if chaos_metrics else {}),
            **({"wire": wire_report} if wire_report else {}),
            **({"timelines": timelines} if timelines else {}),
            **({"profiles": profiles} if profiles else {}),
            **({"slo_reports": slo_reports} if slo_reports else {}),
        }
    except BaseException:
        # keep the net tree and surface log tails: the node logs are
        # the only diagnostics for a boot/run failure
        for i, log in enumerate(logs):
            try:
                log.flush()
                with open(log.name) as f:
                    tail = f.read()[-1200:]
                print(f"--- socknet node{i} log tail ---\n{tail}",
                      file=sys.stderr)
            except OSError:
                pass
        raise
    finally:
        stop.set()
        for ws in slo_subs:
            try:
                ws.close()
            except OSError:
                pass
        if wire_monitor is not None:
            wire_monitor.stop()
        if proxy is not None:
            proxy.stop()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
        if cleanup_ok[0]:
            # only after every node process is down and logs are
            # closed: rmtree must not race live writers
            import shutil
            shutil.rmtree(net, ignore_errors=True)


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--socket":
        r = run_socket()
        print(json.dumps({
            "metric": "testnet_socket_commit_rate",
            "value": r["blocks_per_sec"], "unit": "blocks/sec",
            "vs_baseline": 0.0, "extra": r,
        }))
        return 0
    n_blocks = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    n_vals = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    n_txs = int(sys.argv[3]) if len(sys.argv) > 3 else 1000
    r = run(n_blocks, n_vals, n_txs)
    print(json.dumps({
        "metric": "testnet_commit_rate",
        "value": r["blocks_per_sec"],
        "unit": "blocks/sec",
        "vs_baseline": 0.0,
        "extra": r,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
