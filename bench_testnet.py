"""4-validator testnet commit-rate bench (BASELINE.json config 1).

The reference's config-1 baseline is a 4-validator local testnet running
the kvstore ABCI app with 1000-tx blocks. Here: four in-process
ConsensusStates over a full-mesh relay (the same wiring the consensus
test nets use), MockTicker-driven so the measured rate is the ENGINE's
throughput — proposal build + part gossip + vote verify + apply — not
the configured wall-clock timeouts. Each proposer reaps 1000 txs per
block from its mempool.

Standalone: `python bench_testnet.py [n_blocks] [n_vals] [n_txs]`
prints one JSON line. bench.py folds `run()` into `extra` for the
driver.
"""

from __future__ import annotations

import json
import sys
import time

from bench_util import enable_tpu_compilation_cache

enable_tpu_compilation_cache()  # must precede any jax import


class _BenchMempool:
    """Endless reap: always has the next block's txs ready."""

    def __init__(self, n_txs: int):
        self.n_txs = n_txs
        self._next = 0
        self.committed = 0

    def lock(self):
        pass

    def unlock(self):
        pass

    def size(self):
        return self.n_txs

    def reap(self, max_txs: int):
        base = self._next
        k = self.n_txs if max_txs < 0 else min(self.n_txs, max_txs)
        return [b"bench/k%d=v%d" % (base + i, i) for i in range(k)]

    def update(self, height, txs):
        self._next += len(txs)
        self.committed += len(txs)

    def txs_available(self):
        return True


def run(n_blocks: int = 30, n_vals: int = 4, n_txs: int = 1000) -> dict:
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.proxy import AppConns, local_client_creator
    from tendermint_tpu.abci.types import ValidatorUpdate
    from tendermint_tpu.config import test_config as make_test_config
    from tendermint_tpu.consensus import ConsensusState, MockTicker
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.storage import BlockStore, MemDB, StateStore
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivKey
    from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator

    keys = [PrivKey.generate(bytes([i + 1]) * 32) for i in range(n_vals)]
    gen = GenesisDoc(chain_id="bench-net", genesis_time_ns=1,
                     validators=[GenesisValidator(k.pubkey.ed25519, 10)
                                 for k in keys])

    nodes = []
    for k in keys:
        conns = AppConns(local_client_creator(KVStoreApp()))
        state_store = StateStore(MemDB())
        block_store = BlockStore(MemDB())
        state = state_store.load_or_genesis(gen)
        conns.consensus.init_chain(
            [ValidatorUpdate(v.pubkey, v.voting_power)
             for v in state.validators.validators], gen.chain_id)
        mp = _BenchMempool(n_txs)
        exec_ = BlockExecutor(state_store, conns.consensus, mempool=mp)
        cs = ConsensusState(
            make_test_config().consensus, state, exec_, block_store,
            mempool=mp, priv_validator=PrivValidator(LocalSigner(k)),
            ticker_factory=MockTicker)
        nodes.append(cs)

    # full-mesh relay of proposal/part/vote broadcasts
    for i, src in enumerate(nodes):
        def relay(msg, i=i):
            for j, dst in enumerate(nodes):
                if j != i and msg["type"] in ("proposal", "block_part",
                                              "vote"):
                    dst.submit(dict(msg), peer_id=f"node{i}")
        src.broadcast_hooks.append(relay)

    def fire_all():
        n = 0
        for node in nodes:
            if node.ticker.fire_next() is not None:
                n += 1
        return n

    for node in nodes:
        node.start()

    def run_to(height, max_ticks):
        for _ in range(max_ticks):
            if all(n.state.last_block_height >= height for n in nodes):
                return True
            fire_all()
        return all(n.state.last_block_height >= height for n in nodes)

    # warmup: first blocks pay kernel compiles + app-hash settling
    assert run_to(2, 400), "testnet warmup stalled"

    h0 = min(n.state.last_block_height for n in nodes)
    tx0 = nodes[0].mempool.committed
    t0 = time.perf_counter()
    target = h0 + n_blocks
    assert run_to(target, 400 * n_blocks), "testnet bench stalled"
    dt = time.perf_counter() - t0
    blocks = min(n.state.last_block_height for n in nodes) - h0
    txs = nodes[0].mempool.committed - tx0

    for node in nodes:
        node.stop()
    return {
        "blocks_per_sec": round(blocks / dt, 2),
        "txs_per_sec": round(txs / dt, 1),
        "blocks": blocks, "n_vals": n_vals, "txs_per_block": n_txs,
        "seconds": round(dt, 3),
    }


def main() -> int:
    n_blocks = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    n_vals = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    n_txs = int(sys.argv[3]) if len(sys.argv) > 3 else 1000
    r = run(n_blocks, n_vals, n_txs)
    print(json.dumps({
        "metric": "testnet_commit_rate",
        "value": r["blocks_per_sec"],
        "unit": "blocks/sec",
        "vs_baseline": 0.0,
        "extra": r,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
