"""Shared scalar-crypto shims for the bench suite (bench.py,
bench_fastsync.py, bench_lite.py).

Baselines model the reference's execution: one scalar Ed25519 op per
signature on a single core (types/validator_set.go:257). OpenSSL (via
`cryptography`) is used when available — it is FASTER than Go's
x/crypto ed25519, so every vs_baseline number is conservative; the
pure-python RFC 8032 oracle is the fallback.
"""

from __future__ import annotations

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - image always has cryptography
    Ed25519PrivateKey = Ed25519PublicKey = None
    HAVE_OPENSSL = False


def fast_signer(seed: bytes):
    """sign(msg) -> 64-byte signature for the given 32-byte seed;
    OpenSSL when available (ns/sig), bit-identical pure-python oracle
    otherwise."""
    if HAVE_OPENSSL:
        return Ed25519PrivateKey.from_private_bytes(seed).sign
    from tendermint_tpu.utils import ed25519_ref as ref
    return lambda msg: ref.sign(seed, msg)


def scalar_verify_one():
    """verify(pub, msg, sig) -> bool, one at a time, fastest scalar
    backend available."""
    if HAVE_OPENSSL:
        def verify(pub, msg, sig):
            try:
                Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
                return True
            except Exception:
                return False
        return verify
    from tendermint_tpu.utils import ed25519_ref as ref
    return lambda pub, msg, sig: ref.verify(pub, msg, sig)


class ScalarVerifier:
    """BatchVerifier-shaped adapter that verifies one-at-a-time on the
    scalar backend — the reference's execution model, used as the
    baseline arm of the fast-sync and lite benches."""

    def __init__(self):
        self.stats = {"calls": 0, "sigs": 0, "jax_sigs": 0}
        self._verify = scalar_verify_one()

    def verify(self, items):
        import numpy as np
        self.stats["calls"] += 1
        self.stats["sigs"] += len(items)
        return np.array([self._verify(p, m, s) for p, m, s in items],
                        np.bool_)

    def verify_one(self, pub, msg, sig) -> bool:
        return self._verify(pub, msg, sig)

    def verify_async(self, items):
        """Scalar work has no async dimension: verify now, hand back the
        result thunk (keeps the reactor's pipelined loop verifier-shape
        agnostic)."""
        out = self.verify(items)
        return lambda: out


def enable_tpu_compilation_cache(jax_module=None) -> None:
    """Point JAX at the repo-local .jax_cache — TPU backends ONLY.

    TPU executables serialize cheaply, so warm runs skip the 40-50s
    Mosaic compiles; on CPU the cache forces XLA:CPU's pathological
    serializable-AOT pipeline (>400s + ~30GB compiler RSS for SPMD
    programs — see tests/conftest.py), so a CPU backend must never see
    the cache config.

    Two phases: call with no argument BEFORE importing jax (env-marker
    fast path for tunneled/axon setups), and again AFTER importing jax
    passing the module (catches a locally attached TPU that jax
    auto-detects without any env marker)."""
    import os
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    if jax_module is not None:
        if jax_module.default_backend() == "tpu" and \
                not jax_module.config.jax_compilation_cache_dir:
            jax_module.config.update("jax_compilation_cache_dir", cache_dir)
        return
    if os.environ.get("PALLAS_AXON_POOL_IPS") or any(
            p in os.environ.get("JAX_PLATFORMS", "")
            for p in ("tpu", "axon")):
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)


def free_port_block(k: int) -> int:
    """A base port with k consecutively-bindable ports (multi-node
    harnesses need two per node; one busy port in the range reads as a
    consensus failure). Shared by the socket bench and the e2e tests.

    Ports come from BELOW the kernel's ephemeral range (32768-60999 on
    this host): the probe-then-bind window is seconds long, and an
    outgoing connection's auto-assigned source port can steal a probed
    ephemeral-range port in between — the flaky 'Address already in
    use' node-boot failure."""
    import random
    import socket
    for _ in range(50):
        base = random.randrange(20000, 32000, 2) | 1
        socks = []
        try:
            for off in range(k):
                s = socket.socket()
                s.bind(("127.0.0.1", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port block found")


def node_child_env(repo: str) -> dict:
    """Environment for spawned CPU node processes: strips the axon/TPU
    markers (children must land on the CPU backend even under the axon
    sitecustomize) and the CPU-hostile compilation cache."""
    import os
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env
