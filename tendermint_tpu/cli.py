"""CLI (cmd/tendermint): init, node, version, show_validator,
gen_validator, unsafe_reset_all. Testnet/replay/lite commands land with
their subsystems."""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time


def cmd_init(args) -> int:
    """Write genesis + priv validator + config skeleton (cmd init.go:48)."""
    from tendermint_tpu.types import GenesisDoc, PrivValidatorFile
    from tendermint_tpu.types.genesis import GenesisValidator
    home = args.home
    cfg_dir = os.path.join(home, "config")
    os.makedirs(cfg_dir, exist_ok=True)
    pv_path = os.path.join(cfg_dir, "priv_validator.json")
    pv = PrivValidatorFile.load_or_generate(pv_path)
    gen_path = os.path.join(cfg_dir, "genesis.json")
    if not os.path.exists(gen_path):
        gen = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{int(time.time())}",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pv.pubkey.ed25519, 10)])
        gen.save(gen_path)
        print(f"initialized genesis at {gen_path}")
    else:
        print(f"genesis already exists at {gen_path}")
    print(f"priv validator at {pv_path}")
    return 0


def cmd_node(args) -> int:
    """Run a (single-process) node committing blocks (cmd run_node.go)."""
    from tendermint_tpu.node import default_node
    from tendermint_tpu.abci.apps import CounterApp, KVStoreApp
    app = {"kvstore": KVStoreApp, "counter": CounterApp}[args.app]()
    node = default_node(args.home, app=app)
    node.start()
    print(f"node started: chain={node.gen_doc.chain_id} "
          f"height={node.height}", flush=True)
    try:
        last = -1
        deadline = time.time() + args.max_seconds if args.max_seconds else None
        while True:
            time.sleep(0.2)
            if node.height != last:
                last = node.height
                print(f"committed height={last} "
                      f"app_hash={node.consensus.state.app_hash.hex()[:16]}",
                      flush=True)
            if deadline and time.time() > deadline:
                break
            if args.max_height and node.height >= args.max_height:
                break
    except KeyboardInterrupt:
        pass
    node.stop()
    print(f"node stopped at height {node.height}")
    return 0


def cmd_show_validator(args) -> int:
    from tendermint_tpu.types import PrivValidatorFile
    pv = PrivValidatorFile.load(
        os.path.join(args.home, "config", "priv_validator.json"))
    print(json.dumps(pv.pubkey.to_obj()))
    return 0


def cmd_gen_validator(args) -> int:
    from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator
    from tendermint_tpu.types.keys import PrivKey
    key = PrivKey.generate()
    print(json.dumps({"priv_key": key.to_obj(),
                      "pub_key": key.pubkey.to_obj()}))
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Wipe data dir, keep genesis + reset priv validator height state."""
    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
        print(f"removed {data}")
    pv_path = os.path.join(args.home, "config", "priv_validator.json")
    if os.path.exists(pv_path):
        from tendermint_tpu.types import PrivValidatorFile
        pv = PrivValidatorFile.load(pv_path)
        pv.last_height = pv.last_round = pv.last_step = 0
        pv.last_sign_bytes = None
        pv.last_signature = None
        pv._persist()
        print(f"reset priv validator sign state at {pv_path}")
    return 0


def cmd_version(args) -> int:
    from tendermint_tpu import __version__
    print(__version__)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint_tpu")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="initialize genesis + priv validator")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("node", help="run a node")
    sp.add_argument("--app", default="kvstore",
                    choices=["kvstore", "counter"])
    sp.add_argument("--max-height", type=int, default=0)
    sp.add_argument("--max-seconds", type=float, default=0)
    sp.set_defaults(fn=cmd_node)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser("show_validator").set_defaults(fn=cmd_show_validator)
    sub.add_parser("gen_validator").set_defaults(fn=cmd_gen_validator)
    sub.add_parser("unsafe_reset_all").set_defaults(fn=cmd_unsafe_reset_all)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
