"""CLI (cmd/tendermint): init, node, version, show_validator,
gen_validator, unsafe_reset_all. Testnet/replay/lite commands land with
their subsystems."""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time


def cmd_init(args) -> int:
    """Write genesis + priv validator + config skeleton (cmd init.go:48)."""
    from tendermint_tpu.types import GenesisDoc, PrivValidatorFile
    from tendermint_tpu.types.genesis import GenesisValidator
    home = args.home
    cfg_dir = os.path.join(home, "config")
    os.makedirs(cfg_dir, exist_ok=True)
    pv_path = os.path.join(cfg_dir, "priv_validator.json")
    pv = PrivValidatorFile.load_or_generate(pv_path)
    gen_path = os.path.join(cfg_dir, "genesis.json")
    if not os.path.exists(gen_path):
        gen = GenesisDoc(
            chain_id=args.chain_id or f"test-chain-{int(time.time())}",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pv.pubkey.ed25519, 10)])
        gen.save(gen_path)
        print(f"initialized genesis at {gen_path}")
    else:
        print(f"genesis already exists at {gen_path}")
    print(f"priv validator at {pv_path}")
    return 0


def cmd_node(args) -> int:
    """Run a node (cmd run_node.go). With --p2p it listens, dials
    configured peers and serves RPC; otherwise it is a self-contained
    single-process validator."""
    from tendermint_tpu.node import default_node
    from tendermint_tpu.abci.apps import CounterApp, KVStoreApp
    from tendermint_tpu.config import default_config
    from tendermint_tpu.utils.log import setup_logging
    setup_logging(default_config(args.home).base.log_level)
    app = {"kvstore": KVStoreApp, "counter": CounterApp}[args.app]()
    # TM_NODE_PROFILE=<path>: sampling profiler over EVERY thread
    # (SIGPROF at ~97 Hz of CPU time, sys._current_frames) — the
    # profiling story for multi-process testnets, where each node
    # samples itself and dumps top frames on shutdown. cProfile can't
    # do this (per-thread, and its tracing overhead skews the 1-core
    # contention being measured); the unsafe RPC profiler routes cover
    # interactive single-node use.
    prof_path = os.environ.get("TM_NODE_PROFILE")
    if prof_path:
        import collections
        import signal as _signal
        samples = collections.Counter()

        def _sample(signum, frame):
            # NOTE: samples EVERY thread's current frame per tick, so
            # parked threads surface as wait/accept/select rows —
            # read those as thread residency; the remaining rows are
            # the CPU story
            for fr in sys._current_frames().values():
                # leaf frame + its caller: enough to attribute cost
                co = fr.f_code
                caller = fr.f_back.f_code if fr.f_back else None
                samples[(co.co_filename, co.co_name,
                         caller.co_name if caller else "")] += 1

        _signal.signal(_signal.SIGPROF, _sample)
        _signal.setitimer(_signal.ITIMER_PROF, 0.0103, 0.0103)
        import atexit

        def _dump():
            _signal.setitimer(_signal.ITIMER_PROF, 0)
            total = sum(samples.values()) or 1
            with open(prof_path, "w") as f:
                f.write(f"# {total} samples (CPU time, all threads)\n")
                for (fn, name, caller), c in samples.most_common(60):
                    f.write(f"{100*c/total:6.2f}% {name} <- {caller} "
                            f"({fn})\n")
        atexit.register(_dump)
    if getattr(args, "state_sync", False):
        # env wins over config everywhere the knob plane reads — the
        # flag is sugar for exporting it before Node construction
        os.environ["TM_TPU_STATE_SYNC"] = "on"
    node = default_node(args.home, app=app, with_p2p=args.p2p,
                        fast_sync=(args.fast_sync if args.p2p else False))
    if args.p2p_laddr:
        node.config.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        node.config.rpc.laddr = args.rpc_laddr
        node.with_rpc = True
    if args.grpc_laddr:
        # gRPC only — does not turn on the HTTP JSON-RPC listener
        node.config.rpc.grpc_laddr = args.grpc_laddr
    if args.persistent_peers:
        node.config.p2p.persistent_peers = args.persistent_peers
    node.start()
    if node.switch is not None:
        print(f"p2p listening on {node.switch.listen_address}", flush=True)
    if node.rpc_address is not None:
        print(f"rpc listening on {node.rpc_address[0]}:"
              f"{node.rpc_address[1]}", flush=True)
    print(f"node started: chain={node.gen_doc.chain_id} "
          f"height={node.height}", flush=True)
    try:
        last = -1
        deadline = time.time() + args.max_seconds if args.max_seconds else None
        while True:
            time.sleep(0.2)
            fatal = node.consensus.fatal_error or getattr(
                getattr(node, "blockchain_reactor", None), "sync_error",
                None)
            if fatal is not None:
                # consensus OR fast-sync halted unrecoverably (the
                # reference panics): die loudly rather than sit at a
                # frozen height
                print(f"CONSENSUS FAILURE: {fatal!r}", flush=True)
                node.stop()
                return 1
            if node.height != last:
                last = node.height
                print(f"committed height={last} "
                      f"app_hash={node.consensus.state.app_hash.hex()[:16]}",
                      flush=True)
            if deadline and time.time() > deadline:
                break
            if args.max_height and node.height >= args.max_height:
                break
    except KeyboardInterrupt:
        pass
    node.stop()
    print(f"node stopped at height {node.height}")
    return 0


def cmd_show_validator(args) -> int:
    from tendermint_tpu.types import PrivValidatorFile
    pv = PrivValidatorFile.load(
        os.path.join(args.home, "config", "priv_validator.json"))
    print(json.dumps(pv.pubkey.to_obj()))
    return 0


def cmd_gen_validator(args) -> int:
    from tendermint_tpu.types.priv_validator import LocalSigner, PrivValidator
    from tendermint_tpu.types.keys import PrivKey
    key = PrivKey.generate()
    print(json.dumps({"priv_key": key.to_obj(),
                      "pub_key": key.pubkey.to_obj()}))
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Wipe data dir, keep genesis + reset priv validator height state."""
    data = os.path.join(args.home, "data")
    if os.path.isdir(data):
        shutil.rmtree(data)
        print(f"removed {data}")
    return cmd_unsafe_reset_priv_validator(args)


def cmd_unsafe_reset_priv_validator(args) -> int:
    """Reset ONLY the double-sign protection state (the reference's
    unsafe_reset_priv_validator, cmd reset_priv_validator.go) — for a
    validator that must re-join after losing its state, accepting the
    double-sign risk."""
    pv_path = os.path.join(args.home, "config", "priv_validator.json")
    if os.path.exists(pv_path):
        from tendermint_tpu.types import PrivValidatorFile
        pv = PrivValidatorFile.load(pv_path)
        pv.last_height = pv.last_round = pv.last_step = 0
        pv.last_sign_bytes = None
        pv.last_signature = None
        pv._persist()
        print(f"reset priv validator sign state at {pv_path}")
    return 0


def cmd_lite(args) -> int:
    """Light-client proxy daemon (cmd lite.go:60): serve a local RPC
    whose results are certified against the chain before returning."""
    from tendermint_tpu.lite import (
        HTTPProvider, InquiringCertifier, MemProvider, SecureClient,
        CacheProvider, FileProvider)
    from tendermint_tpu.rpc import JSONRPCClient, RPCServer

    rpc = JSONRPCClient(args.node_addr)
    source = HTTPProvider(rpc)
    trusted = source.get_by_height(args.trust_height) \
        if args.trust_height else source.latest_commit()
    if trusted is None:
        print("cannot fetch a trusted commit from the node")
        return 1
    # the node itself is layered in as the outermost provider: bisection
    # must be able to FETCH intermediate commits, not just read the cache
    store = CacheProvider(
        MemProvider(), FileProvider(os.path.join(args.home, "lite")),
        source)
    chain_id = args.chain_id or \
        rpc.call("genesis")["genesis"]["chain_id"]
    cert = InquiringCertifier(chain_id, trusted, store)
    sc = SecureClient(rpc, cert)

    server = RPCServer()
    server.register("block", lambda height=0: sc.block(int(height)))
    server.register("commit", lambda height=0: sc.commit(int(height)))
    server.register("validators",
                    lambda height=0: sc.validators(int(height)))
    server.register("status", sc.status)
    server.register("tx", lambda hash=b"", prove=True: sc.tx(hash))
    # unverifiable routes proxied straight through
    for route in ("broadcast_tx_sync", "broadcast_tx_async",
                  "broadcast_tx_commit", "abci_info", "net_info",
                  "genesis"):
        server.register(route,
                        (lambda r: lambda **kw: rpc.call(r, **kw))(route))
    from tendermint_tpu.node import _parse_laddr
    host, port = server.serve(*_parse_laddr(args.laddr))
    print(f"lite proxy serving on {host}:{port} "
          f"(trusting height {cert.last_height})", flush=True)
    deadline = time.time() + args.max_seconds if args.max_seconds else None
    try:
        while deadline is None or time.time() < deadline:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


def cmd_version(args) -> int:
    from tendermint_tpu import __version__
    print(__version__)
    return 0


def cmd_probe_upnp(args) -> int:
    """cmd/tendermint/commands/probe_upnp.go: discover an IGD and report
    its capabilities as JSON."""
    import json as _json
    from tendermint_tpu.p2p import upnp
    try:
        report = upnp.probe(timeout=args.timeout)
    except upnp.UPnPError as e:
        print(_json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(_json.dumps({"ok": True, "capabilities": report}))
    return 0


def cmd_show_node_id(args) -> int:
    from tendermint_tpu.p2p import NodeKey
    nk = NodeKey.load_or_generate(
        os.path.join(args.home, "config", "node_key.json"))
    print(nk.id())
    return 0


def cmd_replica(args) -> int:
    """Run an edge read replica (serving/edge.py): a follower node
    with NO validator key serving lite-certified reads."""
    from tendermint_tpu.serving.edge import run_replica
    return run_replica(args)


def cmd_shardset(args) -> int:
    """Run one sharded front-door process (serving/deploy.py)."""
    from tendermint_tpu.serving.deploy import run_shardset
    return run_shardset(args)


def cmd_testnet(args) -> int:
    """Emit an N-validator testnet file tree (cmd testnet.go:97): a shared
    genesis listing every validator, per-node priv_validator + node_key +
    config.json with persistent_peers wired to all other nodes."""
    from tendermint_tpu.config import default_config, save_config
    from tendermint_tpu.p2p import NodeKey
    from tendermint_tpu.types import GenesisDoc, PrivValidatorFile
    from tendermint_tpu.types.genesis import GenesisValidator

    n = args.n
    out = args.output or args.home
    chain_id = args.chain_id or f"testnet-{int(time.time())}"
    pvs, node_keys = [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg_dir = os.path.join(home, "config")
        os.makedirs(cfg_dir, exist_ok=True)
        pvs.append(PrivValidatorFile.load_or_generate(
            os.path.join(cfg_dir, "priv_validator.json")))
        node_keys.append(NodeKey.load_or_generate(
            os.path.join(cfg_dir, "node_key.json")))
    gen = GenesisDoc(
        chain_id=chain_id, genesis_time_ns=time.time_ns(),
        validators=[GenesisValidator(pv.pubkey.ed25519, 10) for pv in pvs])
    base_port = args.base_port
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        gen.save(os.path.join(home, "config", "genesis.json"))
        cfg = default_config(home)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_port + 2 * i + 1}"
        cfg.p2p.addr_book_strict = False
        cfg.p2p.persistent_peers = ",".join(
            f"{node_keys[j].id()}@127.0.0.1:{base_port + 2 * j}"
            for j in range(n) if j != i)
        save_config(cfg)
    print(f"wrote {n}-node testnet (chain {chain_id}) under {out}")
    return 0


def cmd_replay(args, console: bool = False) -> int:
    """Step through the consensus WAL against a fresh state machine
    (consensus/replay_file.go:32 RunReplayFile). --console pauses for
    ENTER between messages and accepts 'quit'."""
    from tendermint_tpu.config import default_config
    from tendermint_tpu.consensus.replay import replay_messages, wal_tail_for
    from tendermint_tpu.node import Node
    from tendermint_tpu.types import GenesisDoc

    config = default_config(args.home)
    gen_doc = GenesisDoc.load(
        os.path.join(args.home, "config", "genesis.json"))
    # readonly WAL: a writable open would trim a live writer's
    # in-flight frame and corrupt the log. NOTE this protects the WAL
    # only — the node handshake still opens the state/block stores
    # writable (as the reference's replay_file does), so the tool is
    # for stopped nodes / copied data dirs, not a running node's home.
    print("replay: do not run against a RUNNING node's data dir "
          "(stores open writable; the WAL itself is opened read-only)",
          file=sys.stderr)
    node = Node(config, gen_doc, priv_validator=None, wal_readonly=True)
    cs, wal = node.consensus, node.wal
    height = cs.state.last_block_height
    # same tail selection as node-start catchup (incl. the legacy
    # genesis fallback) so this debugging tool reproduces the node
    from tendermint_tpu.storage import WALCorruptionError
    try:
        tail = wal_tail_for(wal, height)
    except (ValueError, WALCorruptionError) as e:
        print(f"cannot replay: {e}")
        node.stop()
        return 1
    if tail is None:
        print(f"WAL has no messages after height {height}")
        node.stop()
        return 1

    def before_submit(msg):
        if console:
            cmdline = input(
                f"> next: {msg.get('type')} (ENTER to apply, q to quit) ")
            if cmdline.strip().lower() in ("q", "quit"):
                return False
        return True

    def after_submit(msg):
        print(f"replayed {msg.get('type')} -> "
              f"H/R/S {cs.rs.height}/{cs.rs.round}/{int(cs.rs.step)}")

    # the feed loop itself is replay_messages — the SAME code node
    # startup runs, so what this tool shows is what recovery does
    n = replay_messages(cs, tail, before_submit=before_submit,
                        after_submit=after_submit)
    print(f"replayed {n} messages; final height {cs.rs.height}")
    node.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint_tpu")
    p.add_argument("--home", default=os.path.expanduser("~/.tendermint_tpu"))
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="initialize genesis + priv validator")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("node", help="run a node")
    sp.add_argument("--app", default="kvstore",
                    choices=["kvstore", "counter"])
    sp.add_argument("--max-height", type=int, default=0)
    sp.add_argument("--max-seconds", type=float, default=0)
    sp.add_argument("--p2p", action="store_true",
                    help="run the networking stack")
    sp.add_argument("--no-fast-sync", dest="fast_sync",
                    action="store_false", default=True)
    sp.add_argument("--p2p-laddr", default="",
                    help="override p2p listen address")
    sp.add_argument("--rpc-laddr", default="",
                    help="serve RPC on this address")
    sp.add_argument("--grpc-laddr", default="",
                    help="serve the gRPC BroadcastAPI on this address")
    sp.add_argument("--persistent-peers", default="",
                    help="comma-separated id@host:port")
    sp.add_argument("--state-sync", action="store_true",
                    help="join via p2p snapshot restore (fresh nodes)")
    sp.set_defaults(fn=cmd_node)

    sp = sub.add_parser("testnet",
                        help="write an N-validator testnet file tree")
    sp.add_argument("--n", type=int, default=4)
    sp.add_argument("--output", default="")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--base-port", type=int, default=46656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("replay", help="replay the consensus WAL")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("replay_console",
                        help="interactively replay the consensus WAL")
    sp.set_defaults(fn=lambda a: cmd_replay(a, console=True))

    sp = sub.add_parser("replica",
                        help="run an edge read replica (keyless "
                             "follower + lite-certified reads)")
    sp.add_argument("--app", default="kvstore",
                    choices=["kvstore", "counter"])
    sp.add_argument("--rpc-laddr", default="",
                    help="serve the replica RPC surface here")
    sp.add_argument("--persistent-peers", default="",
                    help="validators to follow (id@host:port,...)")
    sp.add_argument("--max-lag", type=int, default=0,
                    help="healthz staleness threshold in heights "
                         "(0 = TM_TPU_EDGE_MAX_LAG / default)")
    sp.add_argument("--max-seconds", type=float, default=0)
    sp.add_argument("--state-sync", action="store_true",
                    help="bootstrap from a peer snapshot before "
                         "tailing via fast sync")
    sp.set_defaults(fn=cmd_replica)

    sp = sub.add_parser("shardset",
                        help="run N chains behind one sharded RPC "
                             "front door in this process")
    sp.add_argument("--shards", type=int, default=2)
    sp.add_argument("--laddr", default="tcp://127.0.0.1:46657",
                    help="front-door RPC listen address")
    sp.add_argument("--max-seconds", type=float, default=0)
    sp.set_defaults(fn=cmd_shardset)

    sp = sub.add_parser("lite", help="light-client RPC proxy")
    sp.add_argument("--node-addr", default="http://127.0.0.1:46657")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--trust-height", type=int, default=0)
    sp.add_argument("--max-seconds", type=float, default=0)
    sp.set_defaults(fn=cmd_lite)

    sub.add_parser("version").set_defaults(fn=cmd_version)

    sp = sub.add_parser("probe_upnp",
                        help="probe the local network for a UPnP IGD")
    sp.add_argument("--timeout", type=float, default=3.0)
    sp.set_defaults(fn=cmd_probe_upnp)
    sub.add_parser("show_validator").set_defaults(fn=cmd_show_validator)
    sub.add_parser("show_node_id").set_defaults(fn=cmd_show_node_id)
    sub.add_parser("gen_validator").set_defaults(fn=cmd_gen_validator)
    sub.add_parser("unsafe_reset_all").set_defaults(fn=cmd_unsafe_reset_all)
    sub.add_parser("unsafe_reset_priv_validator").set_defaults(
        fn=cmd_unsafe_reset_priv_validator)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
