"""Dispatch coalescer — cross-call dynamic micro-batching for the
BatchVerifier.

The paper's headline win comes from batching at the VoteSet.AddVote /
VerifyCommit boundary, but in live consensus votes arrive ONE AT A TIME
from many concurrent peer/reactor threads: every call lands in
`BatchVerifier.verify_async` as a batch of 1 and takes the scalar host
path, so the device never sees the aggregate arrival rate. This module
is the standard inference-serving answer (continuous/dynamic batching):
sub-threshold calls enqueue their items into a shared queue and get
back a future-style resolver; a dispatcher thread drains the queue,
forms ONE merged batch per window, hands it to the verifier's direct
dispatch path (which applies the normal routing — scalar below the
auto threshold, device above, secp256k1 split to host), and demuxes
the verdicts back to each caller in submission order.

Batching policy (the knobs are TM_TPU_COALESCE / TM_TPU_COALESCE_WAIT_MS
/ TM_TPU_COALESCE_MAX_BATCH and config.base.verifier_coalesce_*):

  - The dispatcher wakes on the first arrival and then LINGERS only
    while traffic is dense: it keeps collecting until no new call has
    arrived for ~4x the EWMA inter-arrival gap, capped at max_wait
    (default 2ms) from the first drain, or until max_batch items
    (default BATCH_CHUNK) are queued. A solo sequential caller —
    whose inter-arrival gap is its own verify latency, necessarily
    above the cap — therefore dispatches immediately and pays only a
    thread handoff, while a burst of reactor threads merges into one
    batch per wave. This is the "adaptive max-wait tuned by arrival
    rate" split: latency for sparse traffic, throughput for dense.

Per-call error semantics are preserved by ISOLATION FALLBACK: if the
merged dispatch (or its resolution) raises, every call is re-dispatched
individually so one caller's malformed items surface as that caller's
exception while everyone else still gets verdicts.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

import numpy as np

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry import queues as queue_obs

# Catalog in docs/observability.md. The coalesce FACTOR — the number the
# tentpole is judged on — is coalesce_calls_total / dispatches_total,
# or the mean of the batch_calls histogram over a scrape window.
_m_calls = telemetry.counter(
    "verifier_coalesce_calls_total",
    "verify calls routed through the dispatch coalescer")
_m_dispatches = telemetry.counter(
    "verifier_coalesce_dispatches_total",
    "Merged dispatches formed by the coalescer")
_m_factor = telemetry.histogram(
    "verifier_coalesce_batch_calls",
    "verify() calls merged into one coalesced dispatch",
    buckets=telemetry.POW2_BUCKETS)
_m_queue = telemetry.histogram(
    "verifier_coalesce_queue_depth",
    "Calls pending in the coalescer queue at first drain",
    buckets=telemetry.POW2_BUCKETS)
_m_wait = telemetry.histogram(
    "verifier_coalesce_wait_seconds",
    "Per-call wait from submit to merged dispatch",
    buckets=(.0002, .0005, .001, .002, .004, .008, .016, .05, .1, .5))
_m_fallback = telemetry.counter(
    "verifier_coalesce_fallback_total",
    "Merged dispatches re-run per-call for error isolation")


class _Merged:
    """Shared result of one merged dispatch. The dispatcher never blocks
    on device results — the FIRST caller to resolve materializes the
    merged verdict array (under a once-lock), every other caller slices
    it. Failures demote the whole merged batch to per-call dispatches so
    exceptions stay with the call that caused them."""

    __slots__ = ("_dispatch", "calls", "_resolver", "_per", "_value",
                 "_done", "_lock")

    def __init__(self, dispatch: Callable, calls: list):
        self._dispatch = dispatch
        self.calls = calls
        self._resolver = None
        self._per = None      # per-call (kind, payload) after fallback
        self._value = None
        self._done = False
        self._lock = threading.Lock()

    def dispatch(self, items: list) -> None:
        """Run on the dispatcher thread: enqueue the merged batch."""
        try:
            self._resolver = self._dispatch(items)
        except Exception:
            self._isolate()

    def _isolate(self) -> None:
        """Per-call fallback: each caller gets its own dispatch outcome
        (resolver or exception) instead of sharing the batch's."""
        _m_fallback.inc()
        per = []
        for c in self.calls:
            try:
                per.append(("r", self._dispatch(c.items)))
            except Exception as e:  # this caller's own failure
                per.append(("e", e))
        self._per = per

    def result_for(self, call: "_Call") -> np.ndarray:
        with self._lock:
            if not self._done:
                if self._per is None:
                    try:
                        self._value = np.asarray(self._resolver())
                    except Exception:
                        self._isolate()
                self._done = True
        if self._per is None:
            return self._value[call.lo:call.lo + call.n]
        kind, payload = self._per[call.idx]
        if kind == "e":
            raise payload
        return np.asarray(payload())


class _Call:
    __slots__ = ("items", "n", "t_submit", "event", "merged", "lo", "idx")

    def __init__(self, items: list, t_submit: float):
        self.items = items
        self.n = len(items)
        self.t_submit = t_submit
        self.event = threading.Event()
        self.merged = None
        self.lo = 0
        self.idx = 0

    def resolve(self) -> np.ndarray:
        self.event.wait()
        return self.merged.result_for(self)


class DispatchCoalescer:
    """Merge concurrent verify calls into batched dispatches.

    dispatch: callable(items) -> zero-arg resolver — the verifier's
    DIRECT (non-coalescing) async path; it must never re-enter the
    coalescer or the dispatcher deadlocks on itself.
    """

    def __init__(self, dispatch: Callable, max_batch: int = 8192,
                 max_wait_s: float = 0.002):
        if max_batch < 1:
            raise ValueError(f"coalesce max_batch must be >= 1, "
                             f"got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"coalesce max_wait must be >= 0, "
                             f"got {max_wait_s}")
        self._dispatch = dispatch
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self._cond = threading.Condition()
        self._queue: list[_Call] = []      #: guarded_by _cond
        self._closed = False               #: guarded_by _cond
        # EWMA inter-arrival gap, seeded sparse (= no lingering) so the
        # first calls after startup never pay the window
        self._ewma_gap = max(max_wait_s, 1e-4)  #: guarded_by _cond
        self._last_arrival = 0.0           #: guarded_by _cond
        # the dispatcher thread is LAZY and self-reaping: spawned on the
        # first submit, exits after idle_timeout_s without traffic (and
        # respawns on the next submit) — so short-lived verifiers don't
        # accumulate parked threads for the process lifetime
        self.idle_timeout_s = 30.0
        self._running = False              #: guarded_by _cond
        self._thread = None                #: guarded_by _cond
        # queue observatory: items waiting for a merged dispatch vs the
        # early-out bound (an unlocked sum over a short list — a torn
        # read costs one slightly-stale gauge sample)
        self._queue_probe = queue_obs.register(
            "verifier.coalesce", self,
            depth=lambda c: sum(call.n for call in c._queue),
            capacity=max_batch)

    # ------------------------------------------------------------ callers

    def submit(self, items: Sequence) -> Callable[[], np.ndarray]:
        """Enqueue one call's items; returns a zero-arg resolver yielding
        this call's own bool[N] verdicts (or raising this call's own
        dispatch failure). Blocks only inside the resolver."""
        now = time.perf_counter()
        call = _Call(list(items), now)
        with self._cond:
            if self._closed:
                raise RuntimeError("coalescer is closed")
            if self._last_arrival:
                gap = now - self._last_arrival
                self._ewma_gap += 0.25 * (gap - self._ewma_gap)
            self._last_arrival = now
            self._queue.append(call)
            if not self._running:
                self._running = True
                self._thread = threading.Thread(
                    target=self._run, name="tm-verify-coalesce",
                    daemon=True)
                self._thread.start()
            self._cond.notify()
        _m_calls.inc()
        return call.resolve

    def close(self, timeout: float = 5.0) -> None:
        """Stop the dispatcher; queued calls are still dispatched."""
        self._queue_probe.close()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)

    # --------------------------------------------------------- dispatcher

    def _window_s_locked(self) -> float:
        """Linger budget for the current drain: ~4 inter-arrival gaps
        when traffic is dense enough that more arrivals are imminent,
        zero when the EWMA gap says waiting can't coalesce anything."""
        gap = self._ewma_gap
        if gap >= self.max_wait_s:
            return 0.0
        return min(self.max_wait_s, 4.0 * gap)

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    if not self._cond.wait(self.idle_timeout_s):
                        if not self._queue and not self._closed:
                            # idle: reap this thread; the next submit
                            # respawns one (the re-check is atomic with
                            # the flag — wait() reacquired the lock)
                            self._running = False
                            return
                if not self._queue and self._closed:
                    self._running = False
                    return
                t0 = time.perf_counter()
                calls = self._queue
                self._queue = []
                n = sum(c.n for c in calls)
                if telemetry.enabled():
                    _m_queue.observe(len(calls))
                # linger for the rest of the burst: quiesce after ~4
                # gaps without a new arrival, hard cap max_wait from
                # the first drain, early out at max_batch
                hard = t0 + self.max_wait_s
                deadline = t0 + self._window_s_locked()
                while not self._closed and n < self.max_batch:
                    now = time.perf_counter()
                    if now >= deadline:
                        break
                    self._cond.wait(deadline - now)
                    if self._queue:
                        calls += self._queue
                        self._queue = []
                        n = sum(c.n for c in calls)
                        deadline = min(
                            hard, time.perf_counter() + self._window_s_locked())
            self._dispatch_merged(calls)

    def _dispatch_merged(self, calls: list) -> None:
        items = []
        for idx, c in enumerate(calls):
            c.idx = idx
            c.lo = len(items)
            items.extend(c.items)
        merged = _Merged(self._dispatch, calls)
        merged.dispatch(items)
        if telemetry.enabled():
            now = time.perf_counter()
            _m_dispatches.inc()
            _m_factor.observe(len(calls))
            for c in calls:
                _m_wait.observe(now - c.t_submit)
        for c in calls:
            c.merged = merged
            c.event.set()
