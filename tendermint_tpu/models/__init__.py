"""Composed compute pipelines built on ops/ kernels.

  verifier.py   BatchVerifier — the pluggable batched signature-verify
                boundary (replaces go-crypto PubKey.VerifyBytes call sites,
                SURVEY.md §2.9) with TPU / CPU-jax / pure-python backends.
"""
