"""BatchVerifier — the framework-wide signature verification boundary.

Reference behavior being replaced (SURVEY.md §2.9, BASELINE.md): every vote
and commit verification calls PubKey.VerifyBytes one signature at a time
(types/vote_set.go:189, types/validator_set.go:257). Here, all call sites
(VoteSet.add_vote, ValidatorSet.verify_commit, fast-sync, lite client)
funnel into one API:

    verifier.verify(items: list[(pubkey, msg, sig)]) -> bool[N]

Backends:
  "jax"    — ops/ed25519.py batch kernel; the one TPU chip XLA targets, or
             CPU XLA when no TPU is present. Chunked to BATCH_CHUNK to stay
             in VMEM (large monolithic batches fall off a perf cliff).
  "python" — scalar host loop, routed by key type through
             types/keys.verify_any (OpenSSL ed25519 with the pure
             RFC 8032 oracle as fallback and for OpenSSL's
             leniency-gap encodings; secp256k1 via ECDSA).
  "auto"   — scalar at or below auto_threshold (default 128, env
             TM_TPU_AUTO_THRESHOLD), batch above: the dual-path split
             SURVEY.md §7 calls for — interactive votes and small
             commits stay off the dispatch round trip, bulk paths
             (fast-sync windows, lite chains, large commits) batch.

Multi-chip: `mesh="auto"` (the default via TM_TPU_MESH / config
`base.verifier_mesh`) makes the verifier shard its batches over every
available device with parallel/mesh.py's shard_map kernel — resolved
LAZILY on the first jax-path dispatch so scalar verifies never pay jax
backend init, and a no-op when only one device exists. `mesh=N` forces
an N-device mesh; `mesh="off"` disables sharding. A pre-built kernel can
still be injected via `kernel=` (tests, bespoke meshes).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

import numpy as np

from tendermint_tpu import telemetry
# import-light: parallel.mesh only pulls jax inside its kernel builders,
# so the spec helpers + tm_mesh_* instruments cost nothing at import
from tendermint_tpu.parallel import mesh as _pmesh
from tendermint_tpu.utils import knobs

# The paper's headline metric is sig-verifies/sec/chip; these families
# record exactly what that decomposes into: how big the batches arriving
# at the boundary are, which backend the routing policy picked, how full
# the padded device chunks run, and the dispatch->resolve wall time
# (docs/observability.md has the catalog).
_m_batch_size = telemetry.histogram(
    "verifier_batch_size", "Signatures per verify() call",
    buckets=telemetry.POW2_BUCKETS)
_m_calls = telemetry.counter(
    "verifier_calls_total", "verify() calls by chosen backend",
    ("backend",))
_m_sigs = telemetry.counter(
    "verifier_sigs_total", "Signatures verified by backend", ("backend",))
_m_dispatch = telemetry.histogram(
    "verifier_dispatch_seconds",
    "Wall time from verify dispatch to resolved verdicts", ("backend",))
_m_occupancy = telemetry.histogram(
    "verifier_chunk_occupancy",
    "Per-chunk fill ratio vs the padded power-of-two bucket",
    buckets=telemetry.RATIO_BUCKETS)
_m_mesh_devices = telemetry.gauge(
    "verifier_mesh_devices",
    "Devices in the verifier's active sharding mesh (0 = unsharded)")
# ed25519 predecompression cache (ops/ed25519): registered HERE so the
# import-light lint can see the families without importing jax; the
# ops module increments them lazily. hit = batch fully served from
# cached rows (pre kernel, no sqrt); fill = repeat-traffic batch
# decompressed once + rows stored; full = mostly-unseen batch routed
# to the fused full kernel (the churn signature: every valset rotation
# shows up as full->fill->hit over the next batches).
_m_predecomp = telemetry.counter(
    "verifier_predecomp_batches_total",
    "Device batches through the ed25519 predecompressed-pubkey cache, "
    "by outcome", ("outcome",))
_m_predecomp_evictions = telemetry.counter(
    "verifier_predecomp_evictions_total",
    "Per-pubkey rows evicted from the ed25519 predecompression LRU "
    "(valset churn beyond cache capacity)")
_m_predecomp_keys = telemetry.gauge(
    "verifier_predecomp_keys",
    "Pubkey rows currently resident in the predecompression LRU")

# Per-dispatch chunk. The fused pallas kernel tiles batches internally
# (512/VMEM tile), so big dispatches amortize launch overhead; the sweep
# on a v5e-1 peaks near 8192 (throughput still rising from 256 -> 8192,
# declining past 16384).
BATCH_CHUNK = 8192


# sharded kernels cached per device count: each sharded_verify_kernel()
# call returns a fresh jit closure with its own compile cache, and on the
# 1-core CI host every extra compile is minutes — one kernel per mesh
# size is shared by all verifiers in the process
_mesh_kernels: dict[int, Callable] = {}
_mesh_lock = threading.Lock()

# Shared pool for fetching chunk results: tunneled TPU links execute and
# transfer at fetch time and serialize per array, so fetching a
# multi-chunk batch's verdicts from several threads overlaps the
# per-chunk round trips (measured ~2x on 4 chunks).
_fetch_pool = None


def _fetch_pool_get():
    global _fetch_pool
    with _mesh_lock:
        if _fetch_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            # 8 workers: a deeply pipelined caller (fast-sync windows,
            # bench at 8 commits in flight) resolves 2 chunks per
            # 10k-sig batch — 4 workers serialized 16 concurrent chunk
            # fetches and capped sustained throughput ~30% below the
            # 8-worker rate (tunnel sweep, 2026-08-01). Threads are
            # idle-cheap; TM_TPU_FETCH_WORKERS overrides.
            _fetch_pool = ThreadPoolExecutor(
                max_workers=knobs.knob_int("TM_TPU_FETCH_WORKERS",
                                           default=8),
                thread_name_prefix="tm-verify-fetch")
        return _fetch_pool


def _mesh_kernel(n_devices: int) -> Callable:
    with _mesh_lock:
        if n_devices not in _mesh_kernels:
            _mesh_kernels[n_devices] = _pmesh.sharded_verify_kernel(
                _pmesh.make_mesh(n_devices))
        return _mesh_kernels[n_devices]


def _parse_coalesce_spec(spec: str) -> str:
    """'auto' | 'on' | 'off'. Same eager-validation contract as
    _parse_mesh_spec: config/env typos must fail at construction."""
    s = str(spec).strip().lower()
    if s in ("auto", ""):
        return "auto"
    if s in ("on", "1", "true", "yes"):
        return "on"
    if s in ("off", "0", "false", "no", "none"):
        return "off"
    raise ValueError(
        f"verifier coalesce must be auto|on|off, got {spec!r}")


# 'auto' | 'off' | power-of-two int, validated eagerly (shared with the
# ops.merkle mesh dispatch — one spec grammar for the whole device plane)
_parse_mesh_spec = _pmesh.parse_mesh_spec


class BatchVerifier:
    def __init__(self, backend: str = "auto", auto_threshold: int = None,
                 kernel: Callable | None = None, mesh: str = "off",
                 min_bucket: int = 8, coalesce: str | None = None,
                 coalesce_wait_ms: float | None = None,
                 coalesce_max_batch: int | None = None):
        # auto_threshold: batches at or below this verify scalar on host
        # (OpenSSL, ~130us/sig). The scalar/batch breakeven depends on
        # the dispatch round trip: ~30-50 sigs on a locally-attached
        # chip (~3-5ms), ~500+ over a tunneled link (~60-100ms). The
        # default of 128 keeps small interactive commits off the
        # dispatch latency everywhere; deployments tune it with
        # TM_TPU_AUTO_THRESHOLD. Bulk paths (fast-sync windows, lite
        # chains, 1000+-validator commits) sit far above any setting.
        if auto_threshold is None:
            auto_threshold = knobs.knob_int("TM_TPU_AUTO_THRESHOLD",
                                            default=128)
        # eager, loud validation — this is fed by config/env text, and a
        # typo must fail at startup (asserts vanish under python -O)
        if backend not in ("auto", "jax", "python"):
            raise ValueError(
                f"verifier backend must be auto|jax|python, got {backend!r}")
        self.backend = backend
        self.auto_threshold = auto_threshold
        self.kernel = kernel
        self.mesh = _parse_mesh_spec(mesh)
        self.mesh_devices = 0          # >0 once a sharded kernel is active
        # callers injecting a sharded kernel= must set min_bucket to a
        # multiple of their mesh size so padded batches stay divisible
        # (the mesh= knob derives this itself in _resolve_mesh)
        self._min_bucket = min_bucket
        self._mesh_resolved = kernel is not None or self.mesh == "off"
        self._resolve_lock = threading.Lock()
        # stats mutations are read-modify-writes reached from every
        # reactor/RPC thread concurrently — one lock, held for dict
        # arithmetic only (never across a dispatch)
        self._stats_lock = threading.Lock()
        #: guarded_by _stats_lock
        self.stats = {"calls": 0, "sigs": 0, "jax_sigs": 0,
                      "coalesced_calls": 0}
        # cross-call dispatch coalescing (models/coalescer.py): merge
        # concurrent sub-threshold verify calls into one batch. Env
        # knobs win over constructor args (same contract as telemetry:
        # an operator's TM_TPU_COALESCE=off must silence any config).
        self.coalesce = _parse_coalesce_spec(
            knobs.knob_str("TM_TPU_COALESCE", config=coalesce,
                           default="auto"))
        if coalesce_wait_ms is None:
            coalesce_wait_ms = knobs.knob_float(
                "TM_TPU_COALESCE_WAIT_MS", default=2.0)
        self._coalesce_wait_s = coalesce_wait_ms / 1e3
        if coalesce_max_batch is None:
            coalesce_max_batch = knobs.knob_int(
                "TM_TPU_COALESCE_MAX_BATCH", default=0)
        self._coalesce_max_batch = coalesce_max_batch or BATCH_CHUNK
        self._coalescer = None  #: guarded_by _resolve_lock

    def _resolve_mesh(self) -> None:
        """Build the sharded kernel on first device dispatch. mesh='auto'
        uses the largest power-of-two device count (shard_map needs the
        padded batch axis divisible by the mesh; buckets are powers of
        two); single-device hosts get the plain kernel. Thread-safe:
        concurrent verify() calls (reactor windows, evidence, RPC) must
        not dispatch with a half-initialized kernel/bucket pair."""
        with self._resolve_lock:
            if self._mesh_resolved:
                return
            import jax
            try:
                n_avail = len(jax.devices())
            except Exception:
                # no usable backend; plain kernel path will surface it
                self._mesh_resolved = True
                return
            # explicit N > available raises RuntimeError (loud, and not
            # a bad-peer-data signal) before _mesh_resolved flips
            n = _pmesh.resolve_mesh_size(self.mesh, n_avail)
            if n >= 2:
                self.kernel = _mesh_kernel(n)
                self.mesh_devices = n
                self._min_bucket = max(8, n)
            if telemetry.enabled():
                _m_mesh_devices.set(self.mesh_devices)
            self._mesh_resolved = True

    def verify(self, items: Sequence[tuple[bytes, bytes, bytes]]) -> np.ndarray:
        """items: (pubkey32, message, signature64) triples -> bool[N]."""
        return self.verify_async(items)()

    def verify_async(self, items: Sequence[tuple[bytes, bytes, bytes]]):
        """Dispatch without blocking: returns a zero-arg resolver that
        materializes bool[N]. jax dispatch is asynchronous, so the
        caller can overlap device compute with host work (the pipelined
        fast-sync loop applies window k-1 while window k verifies
        on-device); every chunk is enqueued up front so the tunnel
        round-trip is paid once.

        Sub-threshold calls route through the dispatch coalescer
        (models/coalescer.py) unless coalesce='off': concurrent
        single-vote callers merge into one batched dispatch, each
        getting back exactly its own verdicts. Calls already above the
        threshold are efficient as-is and dispatch directly."""
        n = len(items)
        if self.coalesce != "off" and 0 < n <= self.auto_threshold:
            with self._stats_lock:
                self.stats["coalesced_calls"] += 1
            # double-checked fast path: the unlocked read sees None or
            # a fully-built coalescer (assignment is atomic, publication
            # happens under the lock); the slow path re-checks locked.
            # tmlint: allow(lock-discipline): benign racy read, see above
            c = self._coalescer
            if c is None:
                with self._resolve_lock:
                    if self._coalescer is None:
                        from tendermint_tpu.models.coalescer import \
                            DispatchCoalescer
                        self._coalescer = DispatchCoalescer(
                            self._verify_async_direct,
                            max_batch=self._coalesce_max_batch,
                            max_wait_s=self._coalesce_wait_s)
                    c = self._coalescer
            return c.submit(items)
        return self._verify_async_direct(items)

    def close(self) -> None:
        """Stop the coalescer dispatcher, if one was started. Safe to
        call repeatedly; the verifier remains usable (a later coalesced
        call starts a fresh dispatcher)."""
        with self._resolve_lock:
            c, self._coalescer = self._coalescer, None
        if c is not None:
            c.close()

    def _verify_async_direct(self, items):
        """The non-coalescing dispatch path (also the coalescer's merge
        target — it must never re-enter verify_async)."""
        n = len(items)
        with self._stats_lock:
            self.stats["calls"] += 1
            self.stats["sigs"] += n
        if n == 0:
            out0 = np.zeros(0, np.bool_)
            return lambda: out0
        t_dispatch = time.perf_counter()
        # causal timeline marker (no height at this layer — the cluster
        # merge shows WHEN verify work ran relative to consensus stages)
        from tendermint_tpu.telemetry import causal
        if causal.enabled():
            causal.point("verify.dispatch", -1, n=n, backend=self.backend)
        _m_batch_size.observe(n)
        use_jax = self.backend == "jax" or (
            self.backend == "auto" and n > self.auto_threshold)
        if not use_jax:
            # scalar host path, routed by key type (ed25519 |
            # secp256k1); batches big enough to amortize per-key
            # precompute use the table oracle (keys.verify_many)
            from tendermint_tpu.types.keys import verify_many
            out1 = np.array(verify_many(items), np.bool_)
            if telemetry.enabled():
                _m_calls.labels("python").inc()
                _m_sigs.labels("python").inc(n)
                _m_dispatch.labels("python").observe(
                    time.perf_counter() - t_dispatch)
            return lambda: out1
        # fast path: the whole host prep (classification, length/s<L
        # checks, SHA-512 + mod-L) in one native call, GIL released —
        # returns None for batches that need the general path below
        # (secp256k1 keys, non-bytes members, native unavailable)
        from tendermint_tpu import native
        prep = native.prep_items(items)
        if prep is not None:
            from tendermint_tpu.ops import ed25519
            if not self._mesh_resolved:
                self._resolve_mesh()
            self._record_jax_dispatch(n)
            pk, rb, sb, hb, pre = prep
            pending = []
            occ = telemetry.enabled()
            for lo in range(0, n, BATCH_CHUNK):
                hi = min(lo + BATCH_CHUNK, n)
                res = ed25519.verify_prepared_async(
                    pk[lo:hi], rb[lo:hi], sb[lo:hi], hb[lo:hi],
                    kernel=self.kernel, min_bucket=self._min_bucket)
                pending.append((lo, hi, res, pre[lo:hi]))
                if occ:
                    b = ed25519._bucket(hi - lo, min_size=self._min_bucket)
                    _m_occupancy.observe((hi - lo) / b)
                    if self.mesh_devices >= 2:
                        _pmesh.record_dispatch("verify", hi - lo, b)
            return self._make_resolver(n, pending, t_dispatch=t_dispatch)
        # mixed-key routing: 33-byte compressed-SEC1 pubkeys are
        # secp256k1 — verified on host (off the TPU hot path by design,
        # types/keys.py); everything else goes to the ed25519 device
        # batch, where a non-ed25519 key fails its precheck anyway
        secp_idx = [i for i, it in enumerate(items)
                    if len(it[0]) == 33 and it[0][0] in (2, 3)]
        if secp_idx:
            from tendermint_tpu.types.keys import verify_any
            secp_ok = {i: verify_any(*items[i]) for i in secp_idx}
            ed_items = [it for i, it in enumerate(items)
                        if i not in secp_ok]
            if not ed_items:
                out2 = np.zeros(n, np.bool_)
                for i, ok in secp_ok.items():
                    out2[i] = ok
                return lambda: out2
            inner = self._verify_async_direct(ed_items)
            with self._stats_lock:
                self.stats["calls"] -= 1  # the outer call already counted
                self.stats["sigs"] -= len(ed_items)

            def resolve_mixed() -> np.ndarray:
                ed_ok = inner()
                out3 = np.zeros(n, np.bool_)
                k = 0
                for i in range(n):
                    if i in secp_ok:
                        out3[i] = secp_ok[i]
                    else:
                        out3[i] = ed_ok[k]
                        k += 1
                return out3

            return resolve_mixed
        from tendermint_tpu.ops import ed25519
        if not self._mesh_resolved:
            self._resolve_mesh()
        self._record_jax_dispatch(n)
        pubkeys = [it[0] for it in items]
        msgs = [it[1] for it in items]
        sigs = [it[2] for it in items]
        pending = []
        occ = telemetry.enabled()
        for lo in range(0, n, BATCH_CHUNK):
            hi = min(lo + BATCH_CHUNK, n)
            res, pre = ed25519.verify_batch_async(
                pubkeys[lo:hi], msgs[lo:hi], sigs[lo:hi], kernel=self.kernel,
                min_bucket=self._min_bucket)
            pending.append((lo, hi, res, pre))
            if occ:
                b = ed25519._bucket(hi - lo, min_size=self._min_bucket)
                _m_occupancy.observe((hi - lo) / b)
                if self.mesh_devices >= 2:
                    _pmesh.record_dispatch("verify", hi - lo, b)
        return self._make_resolver(n, pending, t_dispatch=t_dispatch)

    def _record_jax_dispatch(self, n: int) -> None:
        """Stats + calls/sigs samples for one device dispatch (chunk
        occupancy is observed inside the chunk loops, where lo/hi and
        the ed25519 module are already in hand)."""
        with self._stats_lock:
            self.stats["jax_sigs"] += n
        if not telemetry.enabled():
            return
        _m_calls.labels("jax").inc()
        _m_sigs.labels("jax").inc(n)

    @staticmethod
    def _make_resolver(n: int, pending, t_dispatch: float = 0.0):
        def resolve() -> np.ndarray:
            out = np.zeros(n, np.bool_)
            if len(pending) > 1:
                arrs = list(_fetch_pool_get().map(
                    lambda p: np.asarray(p[2]), pending))
            else:
                arrs = [np.asarray(pending[0][2])]
            for (lo, hi, _res, pre), arr in zip(pending, arrs):
                out[lo:hi] = arr[:hi - lo] & pre
            if t_dispatch and telemetry.enabled():
                _m_dispatch.labels("jax").observe(
                    time.perf_counter() - t_dispatch)
            return out

        return resolve

    def verify_one(self, pubkey: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self.verify([(pubkey, msg, sig)])[0])

    def warmup_buckets(self, max_chunk: int = BATCH_CHUNK) -> None:
        """Compile EVERY power-of-two bucket shape up to max_chunk, for
        both the full kernel and the predecompressed variant (repeated
        same-content batches engage the predecomp cache on the second
        sighting). Streaming workloads (fast-sync waves) produce
        arbitrary tail-window sizes; each lands in one of these buckets
        (ed25519._bucket), so this closes the shape set — without it, a
        first-ever tail size pays a multi-ten-second Mosaic compile
        inside the timed region."""
        if self.backend == "python":
            return
        from tendermint_tpu.ops import ed25519
        if not self._mesh_resolved:
            self._resolve_mesh()  # warm the kernel verify() will use
        b = 512
        while b <= max_chunk:
            items = [(b"\x00" * 32, b"", b"\x00" * 64)] * b
            for _ in range(2):  # 2nd pass: predecomp cache -> pre kernel
                ed25519.verify_batch([it[0] for it in items],
                                     [it[1] for it in items],
                                     [it[2] for it in items],
                                     kernel=self.kernel,
                                     min_bucket=self._min_bucket)
            b *= 2

    def warmup(self, n_sigs: int) -> None:
        """Compile every kernel shape a verify() of n_sigs total items
        will dispatch (the full BATCH_CHUNK shape and the padded tail
        bucket). Benches call this so multi-minute device compiles never
        land inside a timed region; the chunking/bucketing knowledge
        stays here, next to the code that defines it."""
        if n_sigs <= 0 or self.backend == "python":
            return  # scalar backend compiles nothing
        from tendermint_tpu import native
        from tendermint_tpu.ops import ed25519
        native.prep_items([])  # force the prep-extension g++ build now
        shapes = {min(BATCH_CHUNK, n_sigs)}
        tail = n_sigs % BATCH_CHUNK
        if n_sigs > BATCH_CHUNK and tail:
            shapes.add(tail)
        if not self._mesh_resolved:
            self._resolve_mesh()
        for s in shapes:
            # straight to the device path — self.verify would route tiny
            # tails through the scalar backend and compile nothing.
            # Zeroed items are canonical-length with s=0<L, so they run
            # the full decompress+ladder (that's what makes the compile
            # happen); the verdicts are discarded.
            items = [(b"\x00" * 32, b"", b"\x00" * 64)] * s
            ed25519.verify_batch([it[0] for it in items],
                                 [it[1] for it in items],
                                 [it[2] for it in items],
                                 kernel=self.kernel,
                                 min_bucket=self._min_bucket)


_default: BatchVerifier | None = None


def default_verifier() -> BatchVerifier:
    """Process-wide verifier; backend from TM_TPU_VERIFIER (auto|jax|python),
    mesh from TM_TPU_MESH (auto|off|N, default auto — a node on a
    multi-device host shards its signature batches over every chip with
    zero code changes)."""
    global _default
    if _default is None:
        _default = BatchVerifier(
            knobs.knob_str("TM_TPU_VERIFIER", default="auto"),
            mesh=knobs.knob_str("TM_TPU_MESH", default="auto"))
    return _default


def set_default_verifier(v: BatchVerifier) -> None:
    global _default
    _default = v
