"""BatchVerifier — the framework-wide signature verification boundary.

Reference behavior being replaced (SURVEY.md §2.9, BASELINE.md): every vote
and commit verification calls PubKey.VerifyBytes one signature at a time
(types/vote_set.go:189, types/validator_set.go:257). Here, all call sites
(VoteSet.add_vote, ValidatorSet.verify_commit, fast-sync, lite client)
funnel into one API:

    verifier.verify(items: list[(pubkey, msg, sig)]) -> bool[N]

Backends:
  "jax"    — ops/ed25519.py batch kernel; the one TPU chip XLA targets, or
             CPU XLA when no TPU is present. Chunked to BATCH_CHUNK to stay
             in VMEM (large monolithic batches fall off a perf cliff).
  "python" — pure-Python RFC 8032 loop (utils/ed25519_ref.py); the
             bit-exact oracle, also the fastest choice for N <= ~4 on hosts
             where jit dispatch overhead dominates.
  "auto"   — python below a size threshold, jax above (the dual-path split
             SURVEY.md §7 calls for: scalar for interactive single votes,
             batch for commits/fast-sync/lite).

A sharded multi-chip kernel (parallel/mesh.py) can be injected via
`kernel=` for mesh deployments.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence

import numpy as np

# Per-dispatch chunk. The fused pallas kernel tiles batches internally
# (512/VMEM tile), so big dispatches amortize launch overhead; the sweep
# on a v5e-1 peaks near 8192 (throughput still rising from 256 -> 8192,
# declining past 16384).
BATCH_CHUNK = 8192


class BatchVerifier:
    def __init__(self, backend: str = "auto", auto_threshold: int = 4,
                 kernel: Callable | None = None):
        assert backend in ("auto", "jax", "python")
        self.backend = backend
        self.auto_threshold = auto_threshold
        self.kernel = kernel
        self.stats = {"calls": 0, "sigs": 0, "jax_sigs": 0}

    def verify(self, items: Sequence[tuple[bytes, bytes, bytes]]) -> np.ndarray:
        """items: (pubkey32, message, signature64) triples -> bool[N]."""
        n = len(items)
        self.stats["calls"] += 1
        self.stats["sigs"] += n
        if n == 0:
            return np.zeros(0, np.bool_)
        use_jax = self.backend == "jax" or (
            self.backend == "auto" and n > self.auto_threshold)
        if not use_jax:
            from tendermint_tpu.utils import ed25519_ref as ref
            return np.array([ref.verify(p, m, s) for p, m, s in items], np.bool_)
        from tendermint_tpu.ops import ed25519
        self.stats["jax_sigs"] += n
        pubkeys = [it[0] for it in items]
        msgs = [it[1] for it in items]
        sigs = [it[2] for it in items]
        # enqueue every chunk before materializing any result: jax
        # dispatch is async, so chunk k's device compute overlaps chunk
        # k+1's host SHA-512 prep and transfer, and the tunnel round-trip
        # latency is paid once, not per chunk
        pending = []
        for lo in range(0, n, BATCH_CHUNK):
            hi = min(lo + BATCH_CHUNK, n)
            res, pre = ed25519.verify_batch_async(
                pubkeys[lo:hi], msgs[lo:hi], sigs[lo:hi], kernel=self.kernel)
            pending.append((lo, hi, res, pre))
        out = np.zeros(n, np.bool_)
        for lo, hi, res, pre in pending:
            out[lo:hi] = np.asarray(res)[:hi - lo] & pre
        return out

    def verify_one(self, pubkey: bytes, msg: bytes, sig: bytes) -> bool:
        return bool(self.verify([(pubkey, msg, sig)])[0])

    def warmup(self, n_sigs: int) -> None:
        """Compile every kernel shape a verify() of n_sigs total items
        will dispatch (the full BATCH_CHUNK shape and the padded tail
        bucket). Benches call this so multi-minute device compiles never
        land inside a timed region; the chunking/bucketing knowledge
        stays here, next to the code that defines it."""
        if n_sigs <= 0 or self.backend == "python":
            return  # scalar backend compiles nothing
        from tendermint_tpu.ops import ed25519
        shapes = {min(BATCH_CHUNK, n_sigs)}
        tail = n_sigs % BATCH_CHUNK
        if n_sigs > BATCH_CHUNK and tail:
            shapes.add(tail)
        for s in shapes:
            # straight to the device path — self.verify would route tiny
            # tails through the scalar backend and compile nothing.
            # Zeroed items are canonical-length with s=0<L, so they run
            # the full decompress+ladder (that's what makes the compile
            # happen); the verdicts are discarded.
            items = [(b"\x00" * 32, b"", b"\x00" * 64)] * s
            ed25519.verify_batch([it[0] for it in items],
                                 [it[1] for it in items],
                                 [it[2] for it in items],
                                 kernel=self.kernel)


_default: BatchVerifier | None = None


def default_verifier() -> BatchVerifier:
    """Process-wide verifier; backend from TM_TPU_VERIFIER (auto|jax|python)."""
    global _default
    if _default is None:
        _default = BatchVerifier(os.environ.get("TM_TPU_VERIFIER", "auto"))
    return _default


def set_default_verifier(v: BatchVerifier) -> None:
    global _default
    _default = v
