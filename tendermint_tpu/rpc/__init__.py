from tendermint_tpu.rpc.client import (
    JSONRPCClient,
    LocalClient,
    RPCClientError,
    URIClient,
    WSClient,
)
from tendermint_tpu.rpc.core import RPCCore, RPCEnv, jsonify, make_server
from tendermint_tpu.rpc.server import RPCError, RPCServer

__all__ = ["JSONRPCClient", "LocalClient", "RPCClientError", "RPCCore",
           "RPCEnv", "RPCError", "RPCServer", "URIClient", "WSClient",
           "jsonify", "make_server"]
