"""RPC clients (rpc/lib/client + rpc/client).

JSONRPCClient  — HTTP POST JSON-RPC 2.0   (http_client.go:66)
URIClient      — HTTP GET with URI params (http_client.go:109)
WSClient       — websocket JSON-RPC + event stream (ws_client.go:30)
LocalClient    — in-process dispatch against an RPCServer funcmap
                 (rpc/client/localclient.go)
"""

from __future__ import annotations

import base64
import json
import os
import queue
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional
from urllib.parse import urlencode
from urllib.request import Request, urlopen


class RPCClientError(Exception):
    def __init__(self, code, message, data=None):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.data = data


def _unwrap(resp: dict) -> Any:
    if resp.get("error"):
        e = resp["error"]
        raise RPCClientError(e.get("code"), e.get("message"),
                             e.get("data"))
    return resp.get("result")


def _encode_params(params: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (v.hex() if isinstance(v, (bytes, bytearray)) else v)
            for k, v in params.items()}


class JSONRPCClient:
    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, **params) -> Any:
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method,
                           "params": _encode_params(params)}).encode()
        req = Request(self.address, data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=self.timeout) as resp:
            return _unwrap(json.loads(resp.read()))


class URIClient:
    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def call(self, method: str, **params) -> Any:
        url = f"{self.address}/{method}"
        if params:
            url += "?" + urlencode(_encode_params(params))
        with urlopen(url, timeout=self.timeout) as resp:
            return _unwrap(json.loads(resp.read()))


class WSClient:
    """Minimal RFC 6455 client for JSON-RPC + event subscriptions."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall((
            f"GET /websocket HTTP/1.1\r\nHost: {host}:{port}\r\n"
            f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
        # consume the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self.sock.recv(1024)
            if not chunk:
                raise ConnectionError("ws handshake failed")
            buf += chunk
        if b" 101 " not in buf.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"ws upgrade refused: {buf[:120]!r}")
        self._id = 0
        self.events: "queue.Queue[dict]" = queue.Queue()
        self._replies: Dict[Any, "queue.Queue[dict]"] = {}
        self._lock = threading.Lock()
        self.open = True
        threading.Thread(target=self._read_loop, daemon=True,
                         name="ws-client-read").start()

    # ---------------------------------------------------------------- frames

    def _send_text(self, text: str) -> None:
        data = text.encode()
        mask = os.urandom(4)
        hdr = bytearray([0x81])
        n = len(data)
        if n < 126:
            hdr.append(0x80 | n)
        elif n < (1 << 16):
            hdr.append(0x80 | 126)
            hdr += struct.pack(">H", n)
        else:
            hdr.append(0x80 | 127)
            hdr += struct.pack(">Q", n)
        hdr += mask
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        with self._lock:
            self.sock.sendall(bytes(hdr) + masked)

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_message(self) -> Optional[str]:
        parts = []
        while True:
            hdr = self._read_exact(2)
            if hdr is None:
                return None
            fin, opcode = hdr[0] & 0x80, hdr[0] & 0x0F
            n = hdr[1] & 0x7F
            if n == 126:
                ext = self._read_exact(2)
                if ext is None:
                    return None  # truncated frame = connection gone
                (n,) = struct.unpack(">H", ext)
            elif n == 127:
                ext = self._read_exact(8)
                if ext is None:
                    return None
                (n,) = struct.unpack(">Q", ext)
            payload = self._read_exact(n) if n else b""
            if payload is None:
                return None
            if opcode == 0x8:
                return None
            if opcode in (0x9, 0xA):
                continue
            parts.append(payload)
            if fin:
                return b"".join(parts).decode()

    def _read_loop(self) -> None:
        while self.open:
            text = self._read_message()
            if text is None:
                self.open = False
                return
            try:
                msg = json.loads(text)
            except ValueError:
                continue
            if msg.get("id") == "#event":
                self.events.put(msg.get("result"))
            else:
                q = self._replies.pop(msg.get("id"), None)
                if q is not None:
                    q.put(msg)

    # ------------------------------------------------------------------ api

    def cast(self, method: str, **params) -> None:
        """Fire-and-forget call over the persistent connection: the
        server's reply is read and dropped by the reader thread. This
        is the tm-bench load-generation shape — thousands of
        broadcast_tx casts per second over one socket, no per-call
        round-trip wait (benchmarks/simu/counter.go's WS spammer)."""
        self._id += 1
        self._send_text(json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method,
             "params": _encode_params(params)}))

    def call(self, method: str, timeout: float = 30.0, **params) -> Any:
        self._id += 1
        id_ = self._id
        q: "queue.Queue[dict]" = queue.Queue()
        self._replies[id_] = q
        try:
            self._send_text(json.dumps(
                {"jsonrpc": "2.0", "id": id_, "method": method,
                 "params": _encode_params(params)}))
            try:
                reply = q.get(timeout=timeout)
            except queue.Empty:
                raise RPCClientError(
                    -32000, f"no reply to {method!r} within {timeout}s")
            return _unwrap(reply)
        finally:
            self._replies.pop(id_, None)

    def subscribe(self, query: str) -> None:
        self.call("subscribe", query=query)

    def next_event(self, timeout: float = 30.0) -> dict:
        return self.events.get(timeout=timeout)

    def close(self) -> None:
        self.open = False
        try:
            # wake the read loop blocked in recv (close alone wouldn't)
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class ReconnectingWSClient:
    """WSClient with automatic reconnect — the reference's ws_client.go
    (:30-140): on connection loss, redial with exponential backoff (+
    jitter), re-subscribe every recorded query, and keep delivering
    events through ONE stable queue across reconnects. Tracks per-call
    latency (the reference hangs a go-metrics timer on the same spot).

    call() during an outage raises RPCClientError immediately (the
    reference errors too); subscriptions resume without caller action.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 max_backoff_s: float = 10.0, on_reconnect=None):
        self.host, self.port, self.timeout = host, port, timeout
        self.max_backoff_s = max_backoff_s
        self.on_reconnect = on_reconnect
        self.events: "queue.Queue[dict]" = queue.Queue()
        self.open = True
        self.reconnects = 0
        self.latency = {"count": 0, "total_s": 0.0, "max_s": 0.0,
                        "min_s": None}
        self._subs: list = []
        self._lock = threading.RLock()
        self._client: Optional[WSClient] = None
        self._connect()
        threading.Thread(target=self._monitor, daemon=True,
                         name="tm-ws-reconnect").start()

    def _connect(self) -> None:
        c = WSClient(self.host, self.port, timeout=self.timeout)
        c.events = self.events  # events survive the client swap
        with self._lock:
            if not self.open:
                # close() raced the redial: don't leak the fresh conn
                c.close()
                raise OSError("client closed during reconnect")
            self._client = c

    def _monitor(self) -> None:
        import random
        import time as _t
        backoff = 0.2
        while self.open:
            c = self._client
            if c is not None and c.open:
                backoff = 0.2
                _t.sleep(0.1)
                continue
            try:
                self._connect()
            except OSError:
                if not self.open:
                    return
                _t.sleep(backoff * (1 + random.random() / 2))
                backoff = min(backoff * 2, self.max_backoff_s)
                continue
            self.reconnects += 1
            with self._lock:
                subs = list(self._subs)
            try:
                for q_ in subs:
                    self._client.call("subscribe", query=q_)
            except (OSError, RPCClientError):
                continue  # died again mid-resubscribe; monitor retries
            if self.on_reconnect is not None:
                try:
                    self.on_reconnect(self)
                except Exception as e:
                    from tendermint_tpu.utils.log import get_logger
                    get_logger("rpc.client").error(
                        "on_reconnect callback failed", err=repr(e))

    def call(self, method: str, timeout: float = 30.0, **params) -> Any:
        import time as _t
        c = self._client
        if c is None or not c.open:
            raise RPCClientError(-32000, "websocket disconnected "
                                 "(reconnecting)")
        t0 = _t.perf_counter()
        result = c.call(method, timeout=timeout, **params)
        dt = _t.perf_counter() - t0
        lat = self.latency
        lat["count"] += 1
        lat["total_s"] += dt
        lat["max_s"] = max(lat["max_s"], dt)
        lat["min_s"] = dt if lat["min_s"] is None else min(lat["min_s"], dt)
        return result

    def subscribe(self, query: str) -> None:
        with self._lock:
            if query not in self._subs:
                self._subs.append(query)
        self.call("subscribe", query=query)

    def unsubscribe(self, query: str) -> None:
        with self._lock:
            if query in self._subs:
                self._subs.remove(query)
        self.call("unsubscribe", query=query)

    def next_event(self, timeout: float = 30.0) -> dict:
        return self.events.get(timeout=timeout)

    def close(self) -> None:
        # under the lock: _connect() checks self.open and installs the
        # new client inside the same lock, so close() can never
        # interleave between that check and the install (which would
        # leak a live connection)
        with self._lock:
            self.open = False
            c = self._client
        if c is not None:
            c.close()


class LocalClient:
    """In-process client: same interface, no sockets
    (rpc/client/localclient.go)."""

    def __init__(self, server):
        self.server = server

    def call(self, method: str, **params) -> Any:
        from tendermint_tpu.rpc.core import jsonify
        return jsonify(self.server.call(method, params))


@dataclass
class Call:
    """One recorded RPC invocation (rpc/client/mock/client.go Call)."""
    method: str
    params: Dict[str, Any]
    response: Any = None
    error: Optional[Exception] = None


class MockClient:
    """Recording/canned-response client (rpc/client/mock/client.go:135).

    Two modes, combinable per method:
      * canned: `expect(method, response=... | error=...)` queues what the
        next call of `method` returns;
      * passthrough: constructed with an inner client (Local/JSONRPC),
        un-canned methods are forwarded.
    Every invocation is recorded in `.calls` for assertions.
    """

    def __init__(self, inner=None):
        self.inner = inner
        self.calls: List[Call] = []
        self._canned: Dict[str, List[Call]] = {}

    def expect(self, method: str, response: Any = None,
               error: Optional[Exception] = None) -> None:
        self._canned.setdefault(method, []).append(
            Call(method, {}, response, error))

    def call(self, method: str, **params) -> Any:
        queued = self._canned.get(method)
        if queued:
            canned = queued.pop(0)
            rec = Call(method, params, canned.response, canned.error)
            self.calls.append(rec)
            if canned.error is not None:
                raise canned.error
            return canned.response
        if self.inner is None:
            err = RPCClientError(-32601, f"no canned response and no "
                                 f"inner client for {method!r}")
            self.calls.append(Call(method, params, None, err))
            raise err
        try:
            resp = self.inner.call(method, **params)
        except Exception as e:
            self.calls.append(Call(method, params, None, e))
            raise
        self.calls.append(Call(method, params, resp, None))
        return resp

    def calls_to(self, method: str) -> List[Call]:
        return [c for c in self.calls if c.method == method]
