"""Generic JSON-RPC 2.0 server framework (rpc/lib).

Capability parity with the reference's rpc/lib/server: one function map
serves three transports —
  * HTTP POST  JSON-RPC 2.0       (handlers.go:101)
  * HTTP GET   URI params         (handlers.go:238)
  * WebSocket  JSON-RPC + events  (handlers.go:361-520)

Handlers are plain Python callables registered with their parameter names
introspected (the reference reflects on Go func signatures,
handlers.go:41-98). Values arriving as strings are coerced to the
annotated/defaulted type for URI calls. The server recovers from handler
panics and returns structured errors (http_server.go:77).

The WebSocket endpoint implements RFC 6455 server-side framing directly —
enough for JSON-RPC calls plus event subscriptions feeding from the
EventBus."""

from __future__ import annotations

import base64
import hashlib
import inspect
import json
import socket
import struct
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qsl, urlparse

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# Upper bound on any buffered client input (one WebSocket message across
# fragments, or one HTTP POST body). The server binds non-loopback
# addresses, so unbounded client-declared lengths are a remote
# memory-exhaustion vector; anything legitimate (txs, queries) fits well
# under 1 MB.
MAX_BODY_BYTES = 1 << 20

# Cap on concurrent WebSocket connections: each one holds a handler
# thread plus an event-pump thread, so an unauthenticated client must
# not be able to grow them without bound.
MAX_WS_CONNS = 100

# Global cap on concurrent HTTP connections (each is one handler
# thread in ThreadingHTTPServer): a plain connection flood must not
# starve the host (reference: one http.Serve accept loop with the OS
# backlog as the bound, http_server.go:77). Over-limit connections get
# an immediate 503 WITHOUT spawning a thread.
MAX_HTTP_CONNS = 200


class RPCError(Exception):
    def __init__(self, code: int, message: str, data=None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


class RPCFunc:
    """One registered handler: callable + introspected params
    (handlers.go RPCFunc)."""

    def __init__(self, fn: Callable, ws_only: bool = False):
        self.fn = fn
        self.ws_only = ws_only
        sig = inspect.signature(fn)
        self.params = [p for p in sig.parameters.values()
                       if p.name not in ("ws",)]
        self.takes_ws = "ws" in sig.parameters

    def call(self, args: Dict[str, Any], ws=None) -> Any:
        kwargs = {}
        for p in self.params:
            if p.name in args:
                kwargs[p.name] = _coerce(args[p.name], p)
            elif p.default is not inspect.Parameter.empty:
                kwargs[p.name] = p.default
            else:
                raise RPCError(-32602, f"missing param {p.name!r}")
        if self.takes_ws:
            kwargs["ws"] = ws
        return self.fn(**kwargs)


_TYPE_NAMES = {"int": int, "bool": bool, "bytes": bytes, "str": str,
               "float": float}


def _coerce(value: Any, param: inspect.Parameter) -> Any:
    """URI params arrive as strings; coerce by annotation/default type."""
    want = param.annotation
    if isinstance(want, str):  # `from __future__ import annotations`
        want = _TYPE_NAMES.get(want, inspect.Parameter.empty)
    if want is inspect.Parameter.empty and \
            param.default is not inspect.Parameter.empty and \
            param.default is not None:
        want = type(param.default)
    if want in (inspect.Parameter.empty, Any) or value is None:
        return value
    try:
        if want is int and not isinstance(value, int):
            return int(value)
        if want is bool and not isinstance(value, bool):
            return str(value).lower() in ("1", "true", "yes")
        if want is bytes:
            if isinstance(value, bytes):
                return value
            s = str(value)
            if s.startswith("0x"):
                s = s[2:]
            return bytes.fromhex(s)
        if want is str and not isinstance(value, str):
            return str(value)
    except (ValueError, TypeError) as e:
        raise RPCError(-32602,
                       f"bad value for {param.name!r}: {e}") from e
    return value


class WSConn:
    """One WebSocket connection: framing + a send lock; passed to ws-aware
    handlers (subscribe/unsubscribe) for pushing events."""

    def __init__(self, sock: socket.socket, remote: str):
        self.sock = sock
        self.remote = remote
        self.subscriber_id = f"ws-{remote}-{id(self)}"
        self._send_lock = threading.Lock()
        self.open = True
        self.on_close: list = []

    def send_json(self, obj: dict) -> None:
        self.send_text(json.dumps(obj))

    def send_text(self, text: str) -> None:
        data = text.encode()
        hdr = bytearray([0x81])  # FIN + text
        n = len(data)
        if n < 126:
            hdr.append(n)
        elif n < (1 << 16):
            hdr.append(126)
            hdr += struct.pack(">H", n)
        else:
            hdr.append(127)
            hdr += struct.pack(">Q", n)
        with self._send_lock:
            if not self.open:
                raise ConnectionError("websocket closed")
            self.sock.sendall(bytes(hdr) + data)

    def recv_message(self) -> Optional[str]:
        """One text message (handles fragmentation + control frames);
        None on close. Connections declaring frames/messages larger than
        MAX_BODY_BYTES are closed before buffering the payload."""
        parts = []
        total = 0
        while True:
            hdr = self._read_exact(2)
            if hdr is None:
                return None
            fin = hdr[0] & 0x80
            opcode = hdr[0] & 0x0F
            masked = hdr[1] & 0x80
            n = hdr[1] & 0x7F
            if n == 126:
                ext = self._read_exact(2)
                if ext is None:
                    return None
                (n,) = struct.unpack(">H", ext)
            elif n == 127:
                ext = self._read_exact(8)
                if ext is None:
                    return None
                (n,) = struct.unpack(">Q", ext)
            if opcode in (0x1, 0x2, 0x0):
                total += n
            if n > MAX_BODY_BYTES or total > MAX_BODY_BYTES:
                self.close()
                return None
            mask = self._read_exact(4) if masked else b"\x00" * 4
            if mask is None:
                return None
            payload = self._read_exact(n) if n else b""
            if payload is None:
                return None
            if masked:
                payload = bytes(b ^ mask[i % 4]
                                for i, b in enumerate(payload))
            if opcode == 0x8:   # close
                self.close()
                return None
            if opcode == 0x9:   # ping -> pong
                with self._send_lock:
                    if self.open:
                        self.sock.sendall(
                            bytes([0x8A, len(payload)]) + payload)
                continue
            if opcode == 0xA:   # pong
                continue
            parts.append(payload)
            if fin:
                return b"".join(parts).decode()

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def close(self) -> None:
        self.open = False
        for cb in self.on_close:
            try:
                cb(self)
            except Exception as e:
                from tendermint_tpu.utils.log import get_logger
                get_logger("rpc").error("ws on_close callback failed",
                                        err=repr(e))
        try:
            # shutdown BEFORE close: the handler thread is blocked in
            # recv on this socket, which pins the fd — a bare close()
            # would neither wake it nor send FIN to the peer
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _rpc_response(id_, result=None, error: Optional[RPCError] = None) -> dict:
    if error is not None:
        return {"jsonrpc": "2.0", "id": id_,
                "error": {"code": error.code, "message": error.message,
                          "data": error.data}}
    return {"jsonrpc": "2.0", "id": id_, "result": result}


class _BoundedHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a hard cap on live handler threads."""

    daemon_threads = True

    def __init__(self, addr, handler, max_conns: int = MAX_HTTP_CONNS):
        super().__init__(addr, handler)
        self._conn_sema = threading.BoundedSemaphore(max_conns)

    def process_request(self, request, client_address):
        if not self._conn_sema.acquire(blocking=False):
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Content-Length: 0\r\nConnection: close\r\n\r\n")
            except OSError:
                pass
            self.shutdown_request(request)
            return
        try:
            super().process_request(request, client_address)
        except Exception:
            self._conn_sema.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._conn_sema.release()


class RPCServer:
    """funcmap + HTTP server; `register` mirrors RegisterRPCFuncs
    (handlers.go:27)."""

    def __init__(self, max_http_conns: int = MAX_HTTP_CONNS):
        self.funcs: Dict[str, RPCFunc] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._ws_conns: list = []
        self.max_http_conns = max_http_conns
        # when set, GET /metrics serves this callable's text verbatim as
        # Prometheus exposition (text/plain) instead of JSON-RPC routing
        # — scrapers speak raw HTTP, not JSON-RPC envelopes
        self.metrics_provider: Optional[Callable[[], str]] = None
        # when set, GET /debug/timeline serves this callable's dict as
        # JSON — the causal span ring for trace_merge/curl consumers
        self.timeline_provider: Optional[Callable[[], dict]] = None
        # additional raw GET paths: path -> (content_type, provider).
        # A str/bytes result is served verbatim with that content type;
        # a dict result is served as JSON. /healthz and /debug/pprof
        # live here — load balancers and profile_merge speak plain
        # HTTP, not JSON-RPC envelopes.
        self.raw_routes: Dict[str, tuple] = {}

    def register(self, name: str, fn: Callable, ws_only: bool = False) -> None:
        self.funcs[name] = RPCFunc(fn, ws_only=ws_only)

    def register_all(self, routes: Dict[str, Callable]) -> None:
        for name, fn in routes.items():
            self.register(name, fn)

    # ------------------------------------------------------------ dispatch

    def call(self, method: str, params: Dict[str, Any], ws=None) -> Any:
        func = self.funcs.get(method)
        if func is None:
            raise RPCError(-32601, f"method {method!r} not found")
        if func.ws_only and ws is None:
            raise RPCError(-32601,
                           f"method {method!r} is websocket-only")
        try:
            return func.call(params or {}, ws=ws)
        except RPCError:
            raise
        except Exception as e:
            raise RPCError(-32603, f"{type(e).__name__}: {e}",
                           data=traceback.format_exc(limit=8))

    # -------------------------------------------------------------- serving

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> tuple:
        """Start the HTTP/WS server in background threads; returns the
        bound (host, port)."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # a connection that sends nothing must not hold its handler
            # thread (and its semaphore slot) forever
            timeout = 60

            def log_message(self, *a):  # silence
                pass

            def _reply(self, obj: dict, status: int = 200) -> None:
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if not (0 <= n <= MAX_BODY_BYTES):
                        self._reply(_rpc_response(None, error=RPCError(
                            -32600, "request body too large")), 413)
                        self.close_connection = True
                        return
                    req = json.loads(self.rfile.read(n) or b"{}")
                except Exception:
                    self._reply(_rpc_response(
                        None, error=RPCError(-32700, "parse error")), 400)
                    return
                id_ = req.get("id")
                try:
                    result = server.call(req.get("method", ""),
                                         req.get("params") or {})
                    self._reply(_rpc_response(id_, result))
                except RPCError as e:
                    self._reply(_rpc_response(id_, error=e))

            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    self._upgrade_websocket()
                    return
                url = urlparse(self.path)
                if url.path == "/metrics" and \
                        server.metrics_provider is not None:
                    try:
                        body = server.metrics_provider().encode()
                    except Exception as e:
                        self._reply(_rpc_response(None, error=RPCError(
                            -32603, f"metrics provider failed: {e}")), 500)
                        return
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if url.path == "/debug/timeline" and \
                        server.timeline_provider is not None:
                    try:
                        self._reply(server.timeline_provider())
                    except Exception as e:
                        self._reply(_rpc_response(None, error=RPCError(
                            -32603, f"timeline provider failed: {e}")),
                            500)
                    return
                if url.path in server.raw_routes:
                    ctype, provider = server.raw_routes[url.path]
                    try:
                        result = provider()
                    except Exception as e:
                        self._reply(_rpc_response(None, error=RPCError(
                            -32603, f"{url.path} provider failed: "
                                    f"{e}")), 500)
                        return
                    if isinstance(result, dict):
                        self._reply(result)
                        return
                    body = result.encode() if isinstance(result, str) \
                        else bytes(result)
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                method = url.path.strip("/")
                if method == "":
                    # route listing, like the reference's index page
                    self._reply({"routes": sorted(server.funcs)})
                    return
                params = dict(parse_qsl(url.query))
                try:
                    result = server.call(method, params)
                    self._reply(_rpc_response(-1, result))
                except RPCError as e:
                    self._reply(_rpc_response(-1, error=e))

            def _upgrade_websocket(self):
                if len(server._ws_conns) >= MAX_WS_CONNS:
                    self._reply(_rpc_response(None, error=RPCError(
                        -32000, "too many websocket connections")), 503)
                    self.close_connection = True
                    return
                key = self.headers.get("Sec-WebSocket-Key", "")
                accept = base64.b64encode(hashlib.sha1(
                    (key + _WS_MAGIC).encode()).digest()).decode()
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", accept)
                self.end_headers()
                # undo the handler's slow-client read timeout: a healthy
                # subscriber may legitimately send nothing for hours
                self.request.settimeout(None)
                ws = WSConn(self.request, self.client_address[0])
                server._ws_conns.append(ws)
                try:
                    server._ws_loop(ws)
                finally:
                    ws.close()
                    if ws in server._ws_conns:
                        server._ws_conns.remove(ws)
                    self.close_connection = True

        self._httpd = _BoundedHTTPServer((host, port), Handler,
                                         max_conns=self.max_http_conns)
        t = threading.Thread(target=self._httpd.serve_forever,
                             daemon=True, name="rpc-http")
        t.start()
        return self._httpd.server_address

    def _ws_loop(self, ws: WSConn) -> None:
        """Per-connection JSON-RPC loop (ws_handler.go semantics)."""
        while ws.open:
            text = ws.recv_message()
            if text is None:
                return
            try:
                req = json.loads(text)
            except ValueError:
                ws.send_json(_rpc_response(
                    None, error=RPCError(-32700, "parse error")))
                continue
            id_ = req.get("id")
            try:
                result = self.call(req.get("method", ""),
                                   req.get("params") or {}, ws=ws)
                ws.send_json(_rpc_response(id_, result))
            except RPCError as e:
                ws.send_json(_rpc_response(id_, error=e))
            except ConnectionError:
                return

    def stop(self) -> None:
        for ws in list(self._ws_conns):
            ws.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
