"""Async JSON-RPC/WebSocket server — the RPC front door on the node's
ReactorLoop (ISSUE 12).

The threaded server (rpc/server.py) spends one handler thread per HTTP
connection and TWO threads per WebSocket subscriber (handler + event
pump), hard-capped at 100 WS connections — a million-user front door
cannot be thread-per-connection. This server runs every connection on
the SAME event loop that owns the p2p sockets:

- non-blocking HTTP/1.1 (keep-alive) + RFC 6455 WebSocket framing,
  parsed incrementally from per-connection buffers;
- handlers execute on a small FIXED worker pool (never on the loop —
  broadcast_tx_commit legitimately blocks for a commit), responses
  marshal back through ``call_soon``;
- WebSocket event fan-out is loop-native: a subscription's bounded
  buffer (types/events.py, drop-oldest) is drained into the conn's
  bounded write buffer by a loop callback armed from ``Subscription.
  on_put`` — zero threads per subscriber, backpressure ends in the
  counted drop-oldest eviction, never in unbounded memory;
- admission control: a connection cap (immediate 503 over it), an
  in-flight call cap (structured overload error), and a per-client-IP
  token-bucket rate limit (TM_TPU_RPC_RATE) — all exported as
  ``tm_rpc_*`` telemetry.

The route table, parameter coercion and error envelope are shared with
the threaded server (RPCFunc/_coerce/_rpc_response) so both transports
serve byte-identical JSON-RPC."""

from __future__ import annotations

# tmlint: loop-module (async-blocking checker applies to this file)
TMLINT_LOOP_MODULE = True

import base64
import hashlib
import json
import socket
import struct
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qsl, urlparse

from tendermint_tpu import telemetry
from tendermint_tpu.telemetry import slo as _slo
from tendermint_tpu.rpc.server import (
    MAX_BODY_BYTES,
    RPCError,
    RPCFunc,
    _rpc_response,
    _WS_MAGIC,
)
from tendermint_tpu.utils import knobs

_m_conns = telemetry.gauge(
    "rpc_conns", "Open RPC connections on the async front door, by kind",
    ("kind",))
_m_requests = telemetry.counter(
    "rpc_requests_total", "JSON-RPC calls admitted, by transport",
    ("transport",))
_m_rate_limited = telemetry.counter(
    "rpc_rate_limited_total",
    "Calls refused by the per-client-IP token bucket")
_m_rejected = telemetry.counter(
    "rpc_rejected_total",
    "Connections/calls refused by admission control, by reason",
    ("reason",))
_m_subscribers = telemetry.gauge(
    "rpc_ws_subscribers", "Live WebSocket event subscriptions")
_m_events_sent = telemetry.counter(
    "rpc_events_sent_total", "Events pushed to WebSocket subscribers")
# labelled by route so the SLO plane's tail attribution can separate
# broadcast_tx_* admission cost from query traffic; unregistered
# method names collapse into one "unknown" label (clients control the
# method string — it must not mint unbounded label values). The chain
# label is SERVER-resolved (a shard front door's chain_resolver maps
# the call onto its key-space routing table; single-chain servers
# leave it ""): clients cannot mint chain values either, so the SLO
# plane reads per-shard at bounded cardinality.
_m_call_seconds = telemetry.histogram(
    "rpc_call_seconds",
    "Handler wall time per JSON-RPC call, by route and (sharded "
    "front doors) chain",
    ("route", "chain"), buckets=(1e-4, 1e-3, 5e-3, 2.5e-2, 1e-1, 1.0,
                                 10.0))

DEFAULT_MAX_CONNS = 4096
WORKERS = 6
MAX_INFLIGHT = 512          # queued+running handler calls (overload cap)
OUT_HIGH_WATER = 512 * 1024  # stop draining events into a conn past this
OUT_HARD_LIMIT = 4 << 20     # a reader this slow gets disconnected
_RECV_CHUNK = 65536


class _Bucket:
    """Token bucket: `rate` tokens/s, burst 2x. Loop-thread only."""

    __slots__ = ("tokens", "last")

    def __init__(self, burst: float):
        self.tokens = burst
        self.last = time.monotonic()

    def take(self, rate: float) -> bool:
        now = time.monotonic()
        self.tokens = min(2.0 * rate,
                          self.tokens + (now - self.last) * rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class AsyncRPCServer:
    """funcmap-compatible replacement for rpc.server.RPCServer that
    serves every connection on a ReactorLoop."""

    def __init__(self, loop, max_conns: int = 0,
                 rate_per_ip: float = 0.0, workers: int = WORKERS):
        self.loop = loop
        self.funcs: Dict[str, RPCFunc] = {}
        self.metrics_provider: Optional[Callable[[], str]] = None
        self.timeline_provider: Optional[Callable[[], dict]] = None
        self.raw_routes: Dict[str, tuple] = {}
        self.max_conns = int(max_conns) or knobs.knob_int(
            "TM_TPU_RPC_MAX_CONNS", default=0) or DEFAULT_MAX_CONNS
        self.rate_per_ip = float(rate_per_ip) or knobs.knob_float(
            "TM_TPU_RPC_RATE", default=0.0)
        self._buckets: Dict[str, _Bucket] = {}   # loop-thread only
        self._conns: set = set()                 # loop-thread only
        self._listener: Optional[socket.socket] = None
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="tm-rpc-worker")
        self._inflight = 0                       # loop-thread only
        self._stopped = False
        self._tx_batcher = None   # set by make_server; closed on stop
        # bounded chain-label provider for tm_rpc_call_seconds: a shard
        # front door (shard/router.py) installs its mapping-backed
        # resolver here; None (single-chain) labels chain=""
        self.chain_resolver: Optional[Callable] = None
        # event-render cache: one EventBus.publish fans the SAME
        # (tags, data) objects out to every matching subscriber — at
        # thousands of subscribers, re-encoding the payload per
        # subscriber would saturate the loop. Keyed by object identity
        # + query; entries hold strong refs so ids stay valid.
        self._enc_cache: Dict[tuple, tuple] = {}  # loop-thread only

    def render_event(self, item, render: Callable[[Any], dict]) -> bytes:
        key = (id(item.tags), id(item.data), item.query)
        hit = self._enc_cache.get(key)
        if hit is not None and hit[0] is item.tags and \
                hit[1] is item.data:
            return hit[2]
        data = json.dumps(render(item)).encode()
        if len(self._enc_cache) >= 128:
            self._enc_cache.pop(next(iter(self._enc_cache)))
        self._enc_cache[key] = (item.tags, item.data, data)
        return data

    # --------------------------------------------------------- routes

    def register(self, name: str, fn: Callable,
                 ws_only: bool = False) -> None:
        self.funcs[name] = RPCFunc(fn, ws_only=ws_only)

    def register_all(self, routes: Dict[str, Callable]) -> None:
        for name, fn in routes.items():
            self.register(name, fn)

    def call(self, method: str, params: Dict[str, Any], ws=None) -> Any:
        func = self.funcs.get(method)
        if func is None:
            raise RPCError(-32601, f"method {method!r} not found")
        if func.ws_only and ws is None:
            raise RPCError(-32601,
                           f"method {method!r} is websocket-only")
        try:
            return func.call(params or {}, ws=ws)
        except RPCError:
            raise
        except Exception as e:
            raise RPCError(-32603, f"{type(e).__name__}: {e}",
                           data=traceback.format_exc(limit=8))

    # -------------------------------------------------------- serving

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> tuple:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(1024)
        ls.setblocking(False)
        self._listener = ls
        addr = ls.getsockname()
        if not self.loop.running:
            self.loop.start()
        # warm the worker pool NOW: the fixed thread set exists from
        # serve() on (lazy spawn mid-request would read as a per-test
        # thread leak to harnesses that snapshot live threads)
        for _ in range(self._pool._max_workers):
            self._pool.submit(lambda: None)
        self.loop.add_reader(ls, self._on_accept, owner="rpc")
        return addr

    def stop(self) -> None:
        self._stopped = True
        ls = self._listener
        if ls is not None:
            self.loop.remove_fd(ls)
            try:
                ls.close()
            except OSError:
                pass
        done = threading.Event()

        def teardown():
            for conn in list(self._conns):
                conn.close()
            done.set()

        if self.loop.running and not self.loop.in_loop():
            self.loop.call_soon(teardown, owner="rpc")
            done.wait(2.0)  # tmlint: allow(async-blocking): only reachable from non-loop threads (in_loop() guarded one line up)
        else:
            teardown()
        if self._tx_batcher is not None:
            self._tx_batcher.close()
        self._pool.shutdown(wait=False)

    def _on_accept(self) -> None:
        for _ in range(64):
            try:
                sock, addr = self._listener.accept()  # tmlint: allow(async-blocking): O_NONBLOCK listener — raises BlockingIOError when drained
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self._stopped or len(self._conns) >= self.max_conns:
                _m_rejected.labels("conn_cap").inc()
                try:
                    sock.setblocking(False)
                    sock.send(
                        b"HTTP/1.1 503 Service Unavailable\r\n"
                        b"Content-Length: 0\r\nConnection: close\r\n\r\n")
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(self, sock, addr[0])
            self._conns.add(conn)
            _m_conns.labels("http").inc()
            self.loop.add_reader(sock, conn.on_readable, owner="rpc")

    # ------------------------------------------------------ admission

    def _admit(self, ip: str) -> Optional[RPCError]:
        """Loop-thread: per-IP rate limit + in-flight overload cap."""
        if self.rate_per_ip > 0:
            b = self._buckets.get(ip)
            if b is None:
                if len(self._buckets) > 65536:
                    self._buckets.clear()  # bound state under IP churn
                b = self._buckets[ip] = _Bucket(2.0 * self.rate_per_ip)
            if not b.take(self.rate_per_ip):
                _m_rate_limited.inc()
                return RPCError(-32005,
                                "rate limit exceeded for this client")
        if self._inflight >= MAX_INFLIGHT:
            _m_rejected.labels("overload").inc()
            return RPCError(-32000, "server overloaded; retry")
        return None

    def _dispatch(self, conn: "_Conn", transport: str, method: str,
                  params: dict, id_, ws=None,
                  reply: Optional[Callable[[dict], None]] = None) -> None:
        """Loop-thread: admission, then run the handler on the worker
        pool; the reply callback runs back on the loop."""
        err = self._admit(conn.ip)
        send = reply or conn.send_json_response
        if err is not None:
            send(_rpc_response(id_, error=err))
            return
        _m_requests.labels(transport).inc()
        self._inflight += 1
        tele = telemetry.enabled()
        route = method if isinstance(method, str) and \
            method in self.funcs else "unknown"
        chain = ""
        if tele and self.chain_resolver is not None:
            try:
                chain = self.chain_resolver(method, params) or ""
            except Exception:
                chain = ""   # label resolution must never fail a call

        def work():
            t0 = time.perf_counter() if tele else 0.0
            try:
                result = self.call(method, params, ws=ws)
                resp = _rpc_response(id_, result)
            except RPCError as e:
                resp = _rpc_response(id_, error=e)
            if tele:
                _m_call_seconds.labels(route, chain).observe(
                    time.perf_counter() - t0)
            self.loop.call_soon(lambda: self._complete(send, resp),
                                owner="rpc")

        try:
            self._pool.submit(work)
        except RuntimeError:   # pool shut down under us
            self._inflight -= 1

    def _complete(self, send: Callable[[dict], None], resp: dict) -> None:
        self._inflight -= 1
        send(resp)

    def _conn_closed(self, conn: "_Conn") -> None:
        if conn in self._conns:
            self._conns.discard(conn)
            _m_conns.labels("ws" if conn.is_ws else "http").dec()


class _AsyncWS:
    """The `ws` facade handed to ws-aware handlers (subscribe /
    unsubscribe): same surface as rpc.server.WSConn — subscriber_id,
    send_json, on_close, open — plus attach_subscription, which
    RPCCore.subscribe uses to go loop-native instead of spawning a
    pump thread."""

    def __init__(self, conn: "_Conn"):
        self._conn = conn
        self.subscriber_id = f"ws-{conn.ip}-{id(conn)}"
        self.open = True
        self.on_close: list = []
        self._subs: list = []

    def send_json(self, obj: dict) -> None:
        """Thread-safe: marshals onto the loop."""
        conn = self._conn
        if not self.open:
            raise ConnectionError("websocket closed")
        data = json.dumps(obj).encode()
        if conn.server.loop.in_loop():
            conn.send_ws_text(data)
        else:
            conn.server.loop.call_soon(
                lambda: conn.send_ws_text(data), owner="rpc")

    def attach_subscription(self, sub, render: Callable[[Any], dict]) \
            -> None:
        """Loop-native fan-out: sub.on_put schedules a drain on the
        loop; the drain moves events from the subscription's bounded
        buffer into the conn's bounded write buffer. A slow reader
        stalls the drain at OUT_HIGH_WATER and backlogs into the
        subscription's drop-oldest eviction — bounded memory
        end-to-end."""
        conn = self._conn
        loop = conn.server.loop
        self._subs.append(sub)
        _m_subscribers.inc()
        pending = [False]

        def drain():
            pending[0] = False
            if not self.open or sub.cancelled:
                return
            while len(conn.outbuf) < OUT_HIGH_WATER:
                item = sub.get_nowait()
                if item is None:
                    return
                conn.send_ws_text(
                    conn.server.render_event(item, render))
                _m_events_sent.inc()
                _slo.deliver_item(item)
            # outbuf high: resume when the socket drains
            conn.on_drain = schedule

        def schedule():
            if pending[0] or not self.open:
                return
            pending[0] = True
            loop.call_soon(drain, owner="rpc")

        sub.on_put = schedule
        schedule()

    def close(self) -> None:
        if not self.open:
            return
        self.open = False
        _m_subscribers.dec(len(self._subs))
        for cb in self.on_close:
            try:
                cb(self)
            except Exception as e:
                from tendermint_tpu.utils.log import get_logger
                get_logger("rpc").error("ws on_close callback failed",
                                        err=repr(e))
        self._subs = []


class _Conn:
    """One client connection on the loop: HTTP state machine that may
    upgrade to WebSocket. All methods run on the loop thread except
    where noted."""

    def __init__(self, server: AsyncRPCServer, sock: socket.socket,
                 ip: str):
        self.server = server
        self.sock = sock
        self.ip = ip
        self.rbuf = bytearray()
        self.outbuf = bytearray()
        self.is_ws = False
        self.ws: Optional[_AsyncWS] = None
        self._ws_parts: list = []
        self._ws_total = 0
        self.closed = False
        self.keep_alive = True
        self.in_flight = False     # one HTTP request at a time per conn
        self.on_drain: Optional[Callable[[], None]] = None
        self._write_armed = False

    # ------------------------------------------------------------ I/O

    def on_readable(self) -> None:
        if self.closed:
            return
        try:
            data = self.sock.recv(_RECV_CHUNK)  # tmlint: allow(async-blocking): O_NONBLOCK socket — raises BlockingIOError instead of parking
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self.close()
            return
        if not data:
            self.close()
            return
        self.rbuf += data
        if len(self.rbuf) > MAX_BODY_BYTES + 65536:
            self.close()   # header/body flood
            return
        if self.is_ws:
            self._parse_ws()
        else:
            self._parse_http()

    def _send_bytes(self, data: bytes) -> None:
        if self.closed:
            return
        self.outbuf += data
        if len(self.outbuf) > OUT_HARD_LIMIT:
            self.close()   # reader irreparably slow
            return
        self._write_some()

    def _write_some(self) -> None:
        while self.outbuf:
            try:
                n = self.sock.send(self.outbuf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self.close()
                return
            if n <= 0:
                break
            del self.outbuf[:n]
        if self.outbuf:
            if not self._write_armed:
                self._write_armed = True
                self.server.loop.add_reader(
                    self.sock, self.on_readable, owner="rpc",
                    writer=self._on_writable)
        else:
            if self._write_armed:
                self._write_armed = False
                self.server.loop.add_reader(
                    self.sock, self.on_readable, owner="rpc",
                    writer=None)
            cb, self.on_drain = self.on_drain, None
            if cb is not None:
                cb()
            if not self.keep_alive and not self.in_flight and \
                    not self.is_ws:
                self.close()

    def _on_writable(self) -> None:
        self._write_some()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.ws is not None:
            self.ws.close()
        self.server.loop.remove_fd(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._conn_closed(self)

    # ----------------------------------------------------------- HTTP

    def _parse_http(self) -> None:
        while not self.closed and not self.is_ws and not self.in_flight:
            head_end = self.rbuf.find(b"\r\n\r\n")
            if head_end < 0:
                return
            head = bytes(self.rbuf[:head_end]).decode(
                "latin-1", "replace")
            lines = head.split("\r\n")
            try:
                method, target, version = lines[0].split(" ", 2)
            except ValueError:
                self._plain_response(400, b"")
                self.close()
                return
            headers = {}
            for line in lines[1:]:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
            try:
                clen = int(headers.get("content-length", "0") or "0")
            except ValueError:
                clen = -1
            if not 0 <= clen <= MAX_BODY_BYTES:
                self.send_json_response(_rpc_response(
                    None, error=RPCError(-32600,
                                         "request body too large")),
                    status=413)
                self.keep_alive = False
                return
            if len(self.rbuf) < head_end + 4 + clen:
                return   # body incomplete
            body = bytes(self.rbuf[head_end + 4:head_end + 4 + clen])
            del self.rbuf[:head_end + 4 + clen]
            self.keep_alive = (
                headers.get("connection", "").lower() != "close"
                and version != "HTTP/1.0")
            if headers.get("upgrade", "").lower() == "websocket":
                self._upgrade_ws(headers)
                return
            if method == "POST":
                self._http_post(body)
            elif method == "GET":
                self._http_get(target)
            else:
                self._plain_response(405, b"")

    def _http_post(self, body: bytes) -> None:
        try:
            req = json.loads(body or b"{}")
        except ValueError:
            self.send_json_response(_rpc_response(
                None, error=RPCError(-32700, "parse error")), status=400)
            return
        self.in_flight = True
        self.server._dispatch(self, "http", req.get("method", ""),
                              req.get("params") or {}, req.get("id"))

    def _http_get(self, target: str) -> None:
        url = urlparse(target)
        srv = self.server
        provider = None
        ctype = "application/json"
        if url.path == "/metrics" and srv.metrics_provider is not None:
            provider = srv.metrics_provider
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif url.path == "/debug/timeline" and \
                srv.timeline_provider is not None:
            provider = srv.timeline_provider
        elif url.path in srv.raw_routes:
            ctype, provider = srv.raw_routes[url.path]
        if provider is not None:
            self.in_flight = True
            self._dispatch_raw(provider, ctype)
            return
        method = url.path.strip("/")
        if method == "":
            self.send_json_response({"routes": sorted(srv.funcs)})
            return
        params = dict(parse_qsl(url.query))
        self.in_flight = True
        srv._dispatch(self, "uri", method, params, -1)

    def _dispatch_raw(self, provider, ctype: str) -> None:
        """Raw GET routes (healthz, pprof, metrics) run on the worker
        pool too — exposition can be ms-scale on a big registry."""
        srv = self.server
        err = srv._admit(self.ip)
        if err is not None:
            self.send_json_response(_rpc_response(None, error=err),
                                    status=429)
            return
        srv._inflight += 1

        def work():
            try:
                result = provider()
            except Exception as e:
                resp = (_rpc_response(None, error=RPCError(
                    -32603, f"provider failed: {e}")), 500, None)
            else:
                if isinstance(result, dict):
                    resp = (result, 200, None)
                else:
                    body = result.encode() if isinstance(result, str) \
                        else bytes(result)
                    resp = (None, 200, (ctype, body))
            srv.loop.call_soon(lambda: self._raw_done(resp), owner="rpc")

        try:
            srv._pool.submit(work)
        except RuntimeError:
            srv._inflight -= 1

    def _raw_done(self, resp) -> None:
        self.server._inflight -= 1
        obj, status, raw = resp
        if raw is not None:
            ctype, body = raw
            self._plain_response(status, body, ctype)
            self.in_flight = False
            self._parse_http()
        else:
            self.send_json_response(obj, status=status)

    def send_json_response(self, obj: dict, status: int = 200) -> None:
        body = json.dumps(obj).encode()
        self._plain_response(status, body, "application/json")
        self.in_flight = False
        if not self.is_ws:
            self._parse_http()   # next pipelined request, if buffered

    def _plain_response(self, status: int, body: bytes,
                        ctype: str = "application/json") -> None:
        reason = {200: "OK", 400: "Bad Request", 405: "Bad Method",
                  413: "Payload Too Large", 429: "Too Many Requests",
                  500: "Internal Server Error",
                  503: "Service Unavailable"}.get(status, "OK")
        conn = "keep-alive" if self.keep_alive else "close"
        head = (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: {conn}\r\n\r\n").encode()
        self._send_bytes(head + body)

    # ------------------------------------------------------ WebSocket

    def _upgrade_ws(self, headers: dict) -> None:
        key = headers.get("sec-websocket-key", "")
        accept = base64.b64encode(hashlib.sha1(
            (key + _WS_MAGIC).encode()).digest()).decode()
        self._send_bytes(
            ("HTTP/1.1 101 Switching Protocols\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
        self.is_ws = True
        _m_conns.labels("http").dec()
        _m_conns.labels("ws").inc()
        self.ws = _AsyncWS(self)
        if self.rbuf:
            self._parse_ws()

    def send_ws_text(self, data: bytes) -> None:
        hdr = bytearray([0x81])
        n = len(data)
        if n < 126:
            hdr.append(n)
        elif n < (1 << 16):
            hdr.append(126)
            hdr += struct.pack(">H", n)
        else:
            hdr.append(127)
            hdr += struct.pack(">Q", n)
        self._send_bytes(bytes(hdr) + data)

    def _parse_ws(self) -> None:
        while not self.closed:
            frame = self._next_ws_frame()
            if frame is None:
                return
            opcode, payload, fin = frame
            if opcode == 0x8:     # close
                self.close()
                return
            if opcode == 0x9:     # ping -> pong
                self._send_bytes(
                    bytes([0x8A, len(payload)]) + payload)
                continue
            if opcode == 0xA:     # pong
                continue
            self._ws_parts.append(payload)
            self._ws_total += len(payload)
            if self._ws_total > MAX_BODY_BYTES:
                self.close()
                return
            if fin:
                text = b"".join(self._ws_parts)
                self._ws_parts = []
                self._ws_total = 0
                self._ws_message(text)

    def _next_ws_frame(self):
        buf = self.rbuf
        if len(buf) < 2:
            return None
        fin = buf[0] & 0x80
        opcode = buf[0] & 0x0F
        masked = buf[1] & 0x80
        n = buf[1] & 0x7F
        pos = 2
        if n == 126:
            if len(buf) < 4:
                return None
            (n,) = struct.unpack(">H", bytes(buf[2:4]))
            pos = 4
        elif n == 127:
            if len(buf) < 10:
                return None
            (n,) = struct.unpack(">Q", bytes(buf[2:10]))
            pos = 10
        if n > MAX_BODY_BYTES:
            self.close()
            return None
        mask = b"\x00" * 4
        if masked:
            if len(buf) < pos + 4:
                return None
            mask = bytes(buf[pos:pos + 4])
            pos += 4
        if len(buf) < pos + n:
            return None
        payload = bytes(buf[pos:pos + n])
        del buf[:pos + n]
        if masked and any(mask):
            payload = bytes(b ^ mask[i % 4]
                            for i, b in enumerate(payload))
        return opcode, payload, fin

    def _ws_message(self, data: bytes) -> None:
        try:
            req = json.loads(data)
        except ValueError:
            self.send_ws_text(json.dumps(_rpc_response(
                None, error=RPCError(-32700, "parse error"))).encode())
            return
        id_ = req.get("id")
        ws = self.ws

        def reply(resp: dict) -> None:
            if not self.closed:
                self.send_ws_text(json.dumps(resp).encode())

        self.server._dispatch(self, "ws", req.get("method", ""),
                              req.get("params") or {}, id_, ws=ws,
                              reply=reply)
