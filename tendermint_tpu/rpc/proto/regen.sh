#!/bin/sh
# Regenerate tmtpu_pb2.py from tmtpu.proto (no grpc_tools in the image;
# service stubs are hand-wired in grpc_service.py / abci/grpc_app.py).
cd "$(dirname "$0")" && protoc --python_out=. tmtpu.proto
