"""RPC core — the route table + handlers over node internals
(rpc/core/routes.go:8-50 + handlers; env injection mirrors
rpc/core/pipe.go:42-119).

Every handler returns plain JSON-able objects (bytes as hex). The route
set matches the reference: status, net_info, blockchain, genesis, block,
commit, validators, dump_consensus_state, unconfirmed txs, the three
broadcast_tx variants, abci_query/info, tx, tx_search, subscribe /
unsubscribe / unsubscribe_all (websocket), plus the unsafe routes gated
on config (dial_peers, flush_mempool)."""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional

from tendermint_tpu.rpc.server import RPCError
from tendermint_tpu.telemetry import slo as slo_obs
from tendermint_tpu.types.events import EventTx, Query, TagTxHash


def jsonify(x: Any) -> Any:
    """Deep-convert framework objects to JSON-able plain data."""
    if isinstance(x, (bytes, bytearray)):
        return x.hex()
    if hasattr(x, "to_obj"):
        return jsonify(x.to_obj())
    if isinstance(x, dict):
        return {str(k): jsonify(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [jsonify(v) for v in x]
    return x


class RPCEnv:
    """References handlers need (rpc/core/pipe.go setters)."""

    def __init__(self, consensus=None, block_store=None, state_store=None,
                 mempool=None, evidence_pool=None, switch=None,
                 event_bus=None, tx_indexer=None, gen_doc=None,
                 app_conns=None, pubkey: bytes = b"", unsafe: bool = False,
                 blockchain_reactor=None, statesync_reactor=None,
                 snapshot_store=None, stall_detector=None):
        self.consensus = consensus
        self.block_store = block_store
        self.state_store = state_store
        self.mempool = mempool
        self.evidence_pool = evidence_pool
        self.switch = switch
        self.event_bus = event_bus
        self.tx_indexer = tx_indexer
        self.gen_doc = gen_doc
        self.app_conns = app_conns
        self.pubkey = pubkey
        self.unsafe = unsafe
        self.blockchain_reactor = blockchain_reactor
        self.statesync_reactor = statesync_reactor
        self.snapshot_store = snapshot_store
        self.stall_detector = stall_detector

    @classmethod
    def from_node(cls, node) -> "RPCEnv":
        return cls(
            consensus=node.consensus, block_store=node.block_store,
            state_store=node.state_store, mempool=node.mempool,
            evidence_pool=node.evidence_pool, switch=node.switch,
            event_bus=node.event_bus,
            tx_indexer=getattr(node, "tx_indexer", None),
            gen_doc=node.gen_doc, app_conns=node.app_conns,
            pubkey=(node.consensus.priv_validator.pubkey.ed25519
                    if node.consensus.priv_validator else b""),
            unsafe=node.config.rpc.unsafe,
            blockchain_reactor=getattr(node, "blockchain_reactor", None),
            statesync_reactor=getattr(node, "statesync_reactor", None),
            snapshot_store=getattr(node, "snapshot_store", None),
            stall_detector=getattr(node, "_stall_detector", None))


_m_tx_batched = None   # registered lazily by TxBatcher (keeps this
#                        module import-light for the lint's route scan)


class TxBatcher:
    """Front-door admission coalescing (the PR 2 coalescer's pattern at
    the RPC boundary): concurrent broadcast_tx_sync/async calls arriving
    within a short linger merge into ONE Mempool.check_tx_batch — one
    proxy_mtx acquisition and one tx-WAL append for the whole batch.
    Per-call verdicts demux back to each waiter."""

    def __init__(self, mempool, wait_s: float = 0.002,
                 max_batch: int = 256):
        global _m_tx_batched
        from tendermint_tpu import telemetry
        if _m_tx_batched is None:
            _m_tx_batched = (
                telemetry.counter(
                    "rpc_tx_batched_total",
                    "broadcast_tx admissions served through the "
                    "front-door batcher"),
                telemetry.counter(
                    "rpc_tx_batch_flushes_total",
                    "check_tx_batch flushes issued by the front-door "
                    "batcher"))
        self.mempool = mempool
        self.wait_s = wait_s
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._queue: list = []        #: guarded_by _cond
        self._closed = False          #: guarded_by _cond
        # eager worker: part of the node's fixed thread set from
        # construction (lazy spawn reads as a thread leak to harnesses
        # snapshotting live threads around a request)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tm-rpc-txbatch")
        self._thread.start()

    def submit(self, tx: bytes, wait: bool = True):
        """Queue one tx; wait=True blocks for its ResultCheckTx."""
        import queue as _qmod
        slot: Optional[_qmod.SimpleQueue] = \
            _qmod.SimpleQueue() if wait else None
        with self._cond:
            if self._closed:
                raise RPCError(-32000, "tx batcher closed")
            self._queue.append((bytes(tx), slot))
            self._cond.notify()
        if slot is None:
            return None
        res = slot.get()
        if isinstance(res, BaseException):
            raise res
        return res

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
            time.sleep(self.wait_s)   # linger: let a burst accumulate
            with self._cond:
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
            if not batch:
                continue
            txs = [tx for tx, _ in batch]
            try:
                results = self.mempool.check_tx_batch(txs)
            except Exception as e:
                results = [e] * len(batch)
            _m_tx_batched[0].inc(len(batch))
            _m_tx_batched[1].inc()
            for (_, slot), res in zip(batch, results):
                if slot is not None:
                    slot.put(res)


class RPCCore:
    def __init__(self, env: RPCEnv):
        self.env = env
        self.tx_batcher: Optional[TxBatcher] = None
        self._profiler = None
        self._profiler_lock = threading.Lock()
        # shard attribution for the SLO plane: the chain this core
        # serves, stamped on every admit. Bounded by construction —
        # it is OUR genesis chain id (one value per core; a shard
        # front door runs one core per chain), never a client string.
        self._chain = env.gen_doc.chain_id if env.gen_doc else ""

    def enable_tx_batching(self) -> None:
        """Async front door: coalesce concurrent broadcast_tx
        admissions (no-op when the mempool lacks check_tx_batch)."""
        if self.tx_batcher is None and \
                hasattr(self.env.mempool, "check_tx_batch"):
            self.tx_batcher = TxBatcher(self.env.mempool)

    def routes(self) -> Dict[str, Any]:
        """rpc/core/routes.go:8-37 (+ unsafe :39-50)."""
        r = {
            "status": self.status,
            "net_info": self.net_info,
            "blockchain": self.blockchain,
            "genesis": self.genesis,
            "block": self.block,
            "block_results": self.block_results,
            "commit": self.commit,
            "validators": self.validators,
            "dump_consensus_state": self.dump_consensus_state,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_batch": self.broadcast_tx_batch,
            "broadcast_tx_async": self.broadcast_tx_async,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "metrics": self.metrics,
            "dump_height_timeline": self.dump_height_timeline,
            "debug_profile": self.debug_profile,
            "healthz": self.healthz,
            "slo": self.slo,
        }
        if self.env.unsafe:
            r.update({
                "dial_seeds": self.dial_seeds,
                "dial_peers": self.dial_peers,
                "unsafe_flush_mempool": self.unsafe_flush_mempool,
                "unsafe_start_cpu_profiler": self.unsafe_start_cpu_profiler,
                "unsafe_stop_cpu_profiler": self.unsafe_stop_cpu_profiler,
                "unsafe_write_heap_profile": self.unsafe_write_heap_profile,
                "unsafe_dump_trace": self.unsafe_dump_trace,
            })
        return r

    def ws_routes(self) -> Dict[str, Any]:
        return {"subscribe": self.subscribe,
                "unsubscribe": self.unsubscribe,
                "unsubscribe_all": self.unsubscribe_all}

    # ------------------------------------------------------------------ info

    def status(self) -> dict:
        """rpc/core status."""
        cs = self.env.consensus
        store = self.env.block_store
        h = store.height() if store else 0
        meta = store.load_block_meta(h) if store and h > 0 else None
        listen = ""
        if self.env.switch is not None and \
                self.env.switch.listen_address is not None:
            listen = str(self.env.switch.listen_address)
        return jsonify({
            "node_info": (self.env.switch.node_info.to_obj()
                          if self.env.switch else {}),
            "listen_addr": listen,
            "pub_key": self.env.pubkey,
            "latest_block_height": h,
            "latest_block_hash": meta.block_id.hash if meta else b"",
            "latest_app_hash": cs.state.app_hash if cs else b"",
            "latest_block_time_ns":
                meta.header.time_ns if meta else 0,
            "syncing": (self.env.blockchain_reactor is not None and
                        not self.env.blockchain_reactor.synced),
        })

    def net_info(self) -> dict:
        sw = self.env.switch
        if sw is None:
            return {"listening": False, "peers": []}
        return jsonify({
            "listening": sw.listen_address is not None,
            "listen_addr": str(sw.listen_address or ""),
            "n_peers": sw.peers.size(),
            "peers": [{
                "node_info": p.node_info.to_obj(),
                "is_outbound": p.outbound,
                "rtt_s": round(p.rtt_s, 6),
            } for p in sw.peers.list()],
        })

    def genesis(self) -> dict:
        return jsonify({"genesis": self.env.gen_doc.to_obj()
                        if self.env.gen_doc else None})

    def dump_consensus_state(self) -> dict:
        """rpc/core/consensus.go DumpConsensusState: our round state +
        what we know of every peer's round state."""
        cs = self.env.consensus
        rs = cs.rs
        peer_states = {}
        if self.env.switch is not None:
            reactor = self.env.switch.reactors.get("consensus")
            # snapshot under the reactor's lock: add_peer/remove_peer
            # mutate the dict from peer threads while this RPC iterates
            lock = getattr(reactor, "_lock", None)
            if lock is not None:
                with lock:
                    items = list(reactor.peer_states.items())
            else:
                items = list(getattr(reactor, "peer_states", {}).items())
            for pid, ps in items:
                (h, r, step, has_prop, parts,
                 last_commit_round) = ps.snapshot()
                peer_states[pid] = {
                    "height": h, "round": r, "step": step,
                    "has_proposal": has_prop,
                    "proposal_parts": sorted(parts),
                    "last_commit_round": last_commit_round,
                }
        bus = self.env.event_bus
        return jsonify({
            "round_state": {
                "height": rs.height, "round": rs.round,
                "step": int(rs.step),
                "proposal": rs.proposal.to_obj() if rs.proposal else None,
                "locked_round": rs.locked_round,
                "locked_block_hash":
                    rs.locked_block.hash() if rs.locked_block else b"",
                "validators":
                    rs.validators.to_obj() if rs.validators else None,
            },
            "peer_round_states": peer_states,
            # slow-subscriber visibility (VERDICT r5 item 8): bounded
            # event buffers evict oldest-first and count here
            "event_bus": None if bus is None else {
                "subscriptions": bus.n_subscriptions(),
                "dropped_total": bus.dropped_total,
            },
        })

    # ------------------------------------------------------------ blockchain

    def blockchain(self, min_height: int = 0, max_height: int = 0) -> dict:
        """rpc/core/blocks.go:66 BlockchainInfo: metas for a range,
        newest first, capped at 20."""
        store = self.env.block_store
        h = store.height()
        if max_height <= 0 or max_height > h:
            max_height = h
        if min_height <= 0:
            min_height = max(1, max_height - 19)
        min_height = max(min_height, max_height - 19)
        metas = []
        for hh in range(max_height, min_height - 1, -1):
            meta = store.load_block_meta(hh)
            if meta is not None:
                metas.append(meta.to_obj())
        return jsonify({"last_height": h, "block_metas": metas})

    def block(self, height: int = 0) -> dict:
        store = self.env.block_store
        if height <= 0:
            height = store.height()
        meta = store.load_block_meta(height)
        blk = store.load_block(height)
        if blk is None:
            raise RPCError(-32000, f"no block at height {height}")
        return jsonify({"block_meta": meta.to_obj() if meta else None,
                        "block": blk.to_obj()})

    def block_results(self, height: int = 0) -> dict:
        """rpc/core/blocks.go:332 BlockResults: the ABCI responses
        (DeliverTx results + EndBlock) persisted for `height` by the
        state store (state/store.go:127)."""
        store = self.env.block_store
        h = store.height()
        if height <= 0:
            height = h
        if height > h or height < 1:
            raise RPCError(-32000,
                           f"height {height} must be in [1, {h}]")
        results = self.env.state_store.load_abci_responses(height)
        if results is None:
            raise RPCError(-32000, f"no results for height {height}")
        if "deliver_txs_uniform" in results:
            # the compact persisted form is internal: external clients
            # always see the per-tx deliver_txs shape
            from tendermint_tpu.state.execution import ABCIResponses
            resp = ABCIResponses.from_obj(results)
            results = {"deliver_txs": [r.to_obj()
                                       for r in resp.deliver_txs],
                       "end_block": resp.end_block_obj}
        return jsonify({"height": height, "results": results})

    def commit(self, height: int = 0) -> dict:
        """rpc/core/blocks.go:278: height's commit; the canonical commit
        for the latest height is the SeenCommit."""
        store = self.env.block_store
        h = store.height()
        if height <= 0:
            height = h
        meta = store.load_block_meta(height)
        if meta is None:
            raise RPCError(-32000, f"no block at height {height}")
        if height == h:
            commit = store.load_seen_commit(height)
            canonical = False
        else:
            commit = store.load_block_commit(height)
            canonical = True
        return jsonify({"header": meta.header.to_obj(),
                        "commit": commit.to_obj() if commit else None,
                        "canonical": canonical})

    def validators(self, height: int = 0) -> dict:
        """rpc/core/consensus.go:47."""
        if height <= 0:
            cs = self.env.consensus
            height = cs.state.last_block_height + 1
            valset = cs.state.validators
        else:
            valset = self.env.state_store.load_validators(height)
        return jsonify({"block_height": height,
                        "validators": valset.to_obj()})

    # --------------------------------------------------------------- mempool

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.env.mempool.reap(limit)
        return jsonify({"n_txs": len(txs), "txs": txs})

    def num_unconfirmed_txs(self) -> dict:
        return {"n_txs": self.env.mempool.size()}

    def _check_tx(self, tx: bytes):
        from tendermint_tpu.mempool import MempoolFull, TxAlreadyInCache
        if self.tx_batcher is not None:
            # front-door coalescing: one mempool lock + WAL append per
            # merged batch; admission errors come back as result codes
            # and map onto the same RPCError surface as the direct path
            res = self.tx_batcher.submit(tx)
            if isinstance(res, Exception):
                raise RPCError(-32000, str(res))
            if res.code != 0 and (
                    res.log == "tx already in cache" or
                    res.log.startswith("mempool is full")):
                raise RPCError(-32000, res.log)
            return res
        try:
            return self.env.mempool.check_tx(tx)
        except TxAlreadyInCache:
            raise RPCError(-32000, "tx already in cache")
        except MempoolFull as e:
            raise RPCError(-32000, str(e))

    def broadcast_tx_async(self, tx: bytes) -> dict:
        """Fire-and-forget (rpc/core/mempool.go:51). With the front-door
        batcher (async server) the tx rides the next merged
        check_tx_batch; the threaded path keeps its one-off thread."""
        import hashlib
        slo_obs.admit(tx, chain=self._chain)
        if self.tx_batcher is not None:
            self.tx_batcher.submit(tx, wait=False)
        else:
            threading.Thread(target=lambda: self._try_check(tx),
                             daemon=True).start()
        return jsonify({"hash": hashlib.sha256(tx).digest()})

    def _try_check(self, tx: bytes) -> None:
        try:
            self._check_tx(tx)
        except RPCError:
            pass

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        """Wait for CheckTx result (rpc/core/mempool.go:91)."""
        import hashlib
        slo_obs.admit(tx, chain=self._chain)
        res = self._check_tx(tx)
        return jsonify({"code": res.code, "data": res.data,
                        "log": res.log,
                        "hash": hashlib.sha256(tx).digest()})

    def broadcast_tx_batch(self, txs: list) -> dict:
        """Batched CheckTx admission: one RPC round trip and one
        mempool lock for the whole list (no reference equivalent — the
        tm-bench-style per-tx casts cost a server round trip per tx,
        capping injection far below the commit rate the pipelined block
        path sustains). `txs` are hex strings; returns per-tx
        {code, log} aligned with the input."""
        if not isinstance(txs, list):
            raise RPCError(-32602, "txs must be a list of hex strings")
        try:
            raw = [bytes.fromhex(t[2:] if t.startswith("0x") else t)
                   for t in txs]
        except (ValueError, AttributeError) as e:
            raise RPCError(-32602, f"bad tx hex: {e}") from e
        slo_obs.admit_many(raw, chain=self._chain)
        mp = self.env.mempool
        if hasattr(mp, "check_tx_batch"):
            results = mp.check_tx_batch(raw)
        else:  # mock/minimal mempools: per-tx path, errors as codes
            from tendermint_tpu.abci.types import ResultCheckTx
            from tendermint_tpu.mempool import (MempoolFull,
                                                TxAlreadyInCache)
            results = []
            for tx in raw:
                try:
                    results.append(mp.check_tx(tx))
                except TxAlreadyInCache:
                    results.append(ResultCheckTx(
                        code=1, log="tx already in cache"))
                except MempoolFull as e:
                    results.append(ResultCheckTx(code=1, log=str(e)))
        return jsonify({"results": [{"code": r.code, "log": r.log}
                                    for r in results]})

    def broadcast_tx_commit(self, tx: bytes, timeout: float = 60.0) -> dict:
        """CheckTx then wait for the tx to land in a block
        (rpc/core/mempool.go:109): subscribe to EventTx for this hash
        BEFORE submitting, then block on delivery."""
        import hashlib
        slo_obs.admit(tx, chain=self._chain)
        bus = self.env.event_bus
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        subscriber = f"bcast-{tx_hash[:16]}-{time.monotonic_ns()}"
        query = f"tm.event = 'Tx' AND {TagTxHash} = '{tx_hash}'"
        sub = bus.subscribe(subscriber, query)
        try:
            try:
                check = self._check_tx(tx)
            except RPCError as e:
                # a re-broadcast of a tx that is STILL PENDING should
                # wait for its commit, not error — O(1) to distinguish
                # from a genuine duplicate via the mempool hash index
                mp = self.env.mempool
                if "already in cache" in str(e.args) and \
                        hasattr(mp, "get_by_hash") and mp.get_by_hash(
                            hashlib.sha256(tx).digest()) is not None:
                    from tendermint_tpu.abci.types import ResultCheckTx
                    check = ResultCheckTx(code=0, log="tx already pending")
                else:
                    raise
            if not check.ok:
                return jsonify({"check_tx": check.to_obj(),
                                "deliver_tx": None, "hash": tx_hash,
                                "height": 0})
            try:
                item = sub.get(timeout=timeout)
            except Exception:
                raise RPCError(-32000,
                               "timed out waiting for tx to commit")
            data = item.data
            return jsonify({"check_tx": check.to_obj(),
                            "deliver_tx": data["result"].to_obj(),
                            "hash": tx_hash,
                            "height": data["height"]})
        finally:
            bus.unsubscribe_all(subscriber)

    def unsafe_flush_mempool(self) -> dict:
        self.env.mempool.flush()
        return {}

    # profiling (rpc/core/dev.go:23-43; cProfile/tracemalloc instead of
    # Go's pprof). Per-instance state + lock: multiple in-process nodes
    # each own their profiler, and concurrent starts cannot double-enable.

    def unsafe_start_cpu_profiler(self, filename: str = "") -> dict:
        import cProfile
        with self._profiler_lock:
            if self._profiler is not None:
                raise RPCError(-32000, "profiler already running")
            self._profiler = (cProfile.Profile(), filename or "cpu.prof")
            self._profiler[0].enable()
        return {}

    def unsafe_stop_cpu_profiler(self) -> dict:
        with self._profiler_lock:
            if self._profiler is None:
                raise RPCError(-32000, "profiler not running")
            prof, filename = self._profiler
            self._profiler = None
        prof.disable()
        prof.dump_stats(filename)
        return {"written": filename}

    def metrics(self) -> dict:
        """JSON-RPC view of the telemetry state. Prometheus scrapers use
        the raw GET /metrics path on the same listener instead (served
        as text/plain by the server, not this handler)."""
        from tendermint_tpu import telemetry
        return {"enabled": telemetry.enabled(),
                "namespace": telemetry.namespace(),
                "exposition": telemetry.expose()}

    def dump_height_timeline(self, min_height: int = 0,
                             max_height: int = 0) -> dict:
        """The node's causal span ring (telemetry/causal.py) + merge
        metadata: wall-clock anchor, keepalive RTT per peer, drop
        accounting. scripts/trace_merge.py fetches this route from
        every node and aligns the buffers into one cluster timeline.
        Empty (enabled=false) unless TM_TPU_TRACE is on."""
        from tendermint_tpu.telemetry import causal
        d = causal.dump(min_height, max_height)
        cs = self.env.consensus
        if cs is not None:
            d["height"] = cs.state.last_block_height
        return jsonify(d)

    def debug_profile(self, action: str = "status",
                      hz: float = 0.0) -> dict:
        """The sampling profiler (telemetry/profile.py) over RPC:
        status | start [hz] | stop | dump. `dump` returns the
        collapsed-stack text plus per-subsystem busy/lock-wait sample
        counts — the payload scripts/profile_merge.py merges across
        nodes. Raw consumers use GET /debug/pprof instead (collapsed
        text, no JSON envelope)."""
        from tendermint_tpu.telemetry import causal, profile
        action = (action or "status").strip().lower()
        if action == "start":
            p = profile.start(hz=hz or None)
            return {"running": True, "hz": p.hz}
        if action == "stop":
            p = profile.stop()
            return {"running": False,
                    "samples": 0 if p is None else p.snapshot()["samples"]}
        if action == "dump":
            doc = profile.snapshot()
            doc["node"] = causal.node()
            return doc
        if action != "status":
            raise RPCError(-32602, f"unknown action {action!r} "
                           f"(status|start|stop|dump)")
        doc = profile.snapshot()
        doc.pop("collapsed", None)  # status is the cheap probe
        doc["node"] = causal.node()
        return doc

    def slo(self, sketches: bool = False) -> dict:
        """The tx-lifecycle SLO table (telemetry/slo.py): per-stage
        p50/p95/p99/p999 over the cumulative sketches and the
        1s/10s/60s rolling windows, in-flight and drop/timeout
        accounting, tail attribution, and the health verdict.
        `sketches=true` adds the mergeable weighted samples
        scripts/slo_report.py concatenates across nodes. Also served
        raw at GET /slo."""
        return jsonify(slo_obs.snapshot(sketches=bool(sketches)))

    def healthz(self) -> dict:
        """One JSON verdict for load balancers and operators: height
        progress, queue saturation (telemetry/queues.py catalog), the
        stall detector's episode state, the profiler's top-5 busy
        subsystems, and sync/snapshot status. `ok` is false while any
        queue sits saturated or the chain is stalled — the conditions
        the triage playbook (docs/observability.md) starts from."""
        from tendermint_tpu.telemetry import profile, queues
        cs = self.env.consensus
        sd = self.env.stall_detector
        saturated = queues.saturated()
        stalled = bool(sd is not None and sd.stalled)
        prof = profile.get()
        syncing = (self.env.blockchain_reactor is not None and
                   not self.env.blockchain_reactor.synced)
        # SLO verdict fold-in: sampled txs failing to complete (drops
        # beyond 5% of the 60s window's completions, or a saturated
        # tracker) flip the health bit — always {"ok": True} while
        # the plane is off
        slo_verdict = slo_obs.verdict()
        doc = {
            "ok": (not saturated and not stalled and
                   slo_verdict["ok"]),
            "slo": {"enabled": slo_obs.enabled(), **slo_verdict},
            "height": cs.state.last_block_height
            if cs is not None else 0,
            "syncing": syncing,
            "queues": {"saturated": saturated,
                       "table": queues.table()},
            "stall": {"stalled": stalled,
                      "episodes": 0 if sd is None else sd.fired,
                      "window_s": None if sd is None else sd.window_s},
            "profile": {
                "enabled": profile.enabled(),
                "running": bool(prof is not None and prof.running),
                "top": prof.top(5) if prof is not None else [],
            },
        }
        ss = self.env.snapshot_store
        if ss is not None:
            try:
                heights = ss.list_heights()
                doc["snapshot"] = {
                    "latest_height": max(heights) if heights else 0,
                    "count": len(heights)}
            except Exception as e:
                doc["snapshot"] = {"error": repr(e)}
        sr = self.env.statesync_reactor
        if sr is not None and hasattr(sr, "status"):
            try:
                doc["statesync"] = sr.status()
            except Exception as e:
                doc["statesync"] = {"error": repr(e)}
        return jsonify(doc)

    def unsafe_dump_trace(self, filename: str = "") -> dict:
        """Write the in-memory consensus/verifier timeline as
        Chrome-trace JSON (chrome://tracing, ui.perfetto.dev)."""
        from tendermint_tpu import telemetry
        filename = filename or "consensus_trace.json"
        n = len(telemetry.TRACER.events())
        return {"written": telemetry.dump_trace(filename), "events": n}

    def unsafe_write_heap_profile(self, filename: str = "") -> dict:
        """First call arms tracemalloc and returns started=true (there is
        nothing to snapshot yet); later calls write the snapshot."""
        import tracemalloc
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return {"started": True,
                    "note": "tracemalloc armed; call again to snapshot"}
        filename = filename or "heap.prof"
        snap = tracemalloc.take_snapshot()
        with open(filename, "w") as f:
            for stat in snap.statistics("lineno")[:200]:
                f.write(f"{stat}\n")
        return {"written": filename}

    # ------------------------------------------------------------------ abci

    def abci_query(self, path: str = "", data: bytes = b"",
                   height: int = 0, prove: bool = False) -> dict:
        res = self.env.app_conns.query.query(path, data, height=height,
                                             prove=prove)
        return jsonify({"response": res.to_obj()})

    def abci_info(self) -> dict:
        return jsonify({"response": self.env.app_conns.query.info().to_obj()})

    # ------------------------------------------------------------------- txs

    def tx(self, hash: bytes = b"", prove: bool = False) -> dict:
        """rpc/core/tx.go:70 — requires the tx indexer."""
        indexer = self.env.tx_indexer
        if indexer is None:
            raise RPCError(-32000, "transaction indexing is disabled")
        result = indexer.get(hash)
        if result is None:
            # O(1) mempool probe (Mempool.get_by_hash): a pending tx
            # gets a distinguishable error instead of plain not-found
            mp = self.env.mempool
            if hasattr(mp, "get_by_hash") and \
                    mp.get_by_hash(hash) is not None:
                raise RPCError(
                    -32000, f"tx {hash.hex()} is pending in the "
                    f"mempool (not yet committed)")
            raise RPCError(-32000, f"tx {hash.hex()} not found")
        out = dict(result)
        if prove:
            block = self.env.block_store.load_block(result["height"])
            if block is not None:
                from tendermint_tpu.ops import merkle
                root, aunts = merkle.proof_host(block.data.txs,
                                                result["index"])
                out["proof"] = {
                    "root_hash": root,
                    "proof": aunts,
                    "index": result["index"],
                    "total": len(block.data.txs),
                }
        return jsonify(out)

    def tx_search(self, query: str = "", prove: bool = False,
                  page: int = 1, per_page: int = 30) -> dict:
        indexer = self.env.tx_indexer
        if indexer is None:
            raise RPCError(-32000, "transaction indexing is disabled")
        results = indexer.search(query)
        total = len(results)
        start = max(0, (page - 1) * per_page)
        return jsonify({"txs": results[start:start + per_page],
                        "total_count": total})

    # ------------------------------------------------------------------- p2p

    def dial_peers(self, peers: str = "", persistent: bool = False) -> dict:
        from tendermint_tpu.p2p import NetAddress
        addrs = [NetAddress.from_string(p)
                 for p in peers.split(",") if p]
        self.env.switch.dial_peers_async(addrs, persistent=persistent)
        return {"dialed": [str(a) for a in addrs]}

    def dial_seeds(self, seeds: str = "") -> dict:
        """rpc/core/routes.go:41 unsafe_dial_seeds: one-shot dials into
        the topology, non-persistent."""
        return {"dialed": self.dial_peers(seeds)["dialed"],
                "log": "dialing seeds in rounds"}

    # ---------------------------------------------------------------- events

    def subscribe(self, query: str = "", ws=None) -> dict:
        """WS-only (rpc/core/events.go:87): push matching events as
        jsonrpc notifications with id '#event'."""
        bus = self.env.event_bus
        try:
            Query(query)
        except ValueError as e:
            raise RPCError(-32602, f"bad query: {e}")
        sub = bus.subscribe(ws.subscriber_id, query)

        attach = getattr(ws, "attach_subscription", None)
        if attach is not None:
            # async front door: loop-native fan-out, zero threads per
            # subscriber — the drain renders each event exactly like
            # the pump below
            def render(item):
                return {"jsonrpc": "2.0", "id": "#event",
                        "result": {"query": item.query,
                                   "data": jsonify(item.data),
                                   "tags": jsonify(item.tags)}}

            attach(sub, render)
            ws.on_close.append(
                lambda w: bus.unsubscribe_all(w.subscriber_id))
            return {}

        def pump():
            while ws.open and not sub.cancelled:
                try:
                    item = sub.get(timeout=0.5)
                except queue.Empty:
                    continue
                try:
                    ws.send_json({"jsonrpc": "2.0", "id": "#event",
                                  "result": {"query": item.query,
                                             "data": jsonify(item.data),
                                             "tags": jsonify(item.tags)}})
                    slo_obs.deliver_item(item)
                except ConnectionError:
                    return

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        ws.on_close.append(
            lambda w: bus.unsubscribe_all(w.subscriber_id))
        return {}

    def unsubscribe(self, query: str = "", ws=None) -> dict:
        self.env.event_bus.unsubscribe(ws.subscriber_id, query)
        return {}

    def unsubscribe_all(self, ws=None) -> dict:
        self.env.event_bus.unsubscribe_all(ws.subscriber_id)
        return {}


def make_server(env: RPCEnv, loop=None):
    """Assemble a server with the full route table: the threaded
    RPCServer by default, or — when handed the node's ReactorLoop —
    the async front door (rpc/aserver.py) serving every connection on
    that loop, with broadcast_tx admission batching enabled."""
    from tendermint_tpu import telemetry
    core = RPCCore(env)
    if loop is not None:
        from tendermint_tpu.rpc.aserver import AsyncRPCServer
        server = AsyncRPCServer(loop)
        core.enable_tx_batching()
        server._tx_batcher = core.tx_batcher
    else:
        from tendermint_tpu.rpc.server import RPCServer
        server = RPCServer()
    server.register_all(core.routes())
    for name, fn in core.ws_routes().items():
        server.register(name, fn, ws_only=True)
    # raw Prometheus scrape path; serves the (possibly empty) registry
    # even when telemetry is disabled so scrapers never see a 404 flap
    server.metrics_provider = telemetry.expose
    # raw GET /debug/timeline: the causal span ring as JSON (curl-able
    # without a JSON-RPC envelope; same payload as dump_height_timeline)
    server.timeline_provider = core.dump_height_timeline
    # raw GET /healthz (JSON verdict for load balancers) and GET
    # /debug/pprof (flamegraph collapsed-stack text, the Go-pprof
    # convention path) — plain-HTTP consumers, no JSON-RPC envelope
    from tendermint_tpu.telemetry import profile

    def _pprof_text() -> str:
        p = profile.get()
        return "" if p is None else p.collapsed()

    server.raw_routes["/healthz"] = ("application/json", core.healthz)
    # raw GET /slo: the tx-lifecycle SLO table (per-stage quantiles,
    # windows, tail attribution) — same payload as the `slo` route
    server.raw_routes["/slo"] = ("application/json", core.slo)
    server.raw_routes["/debug/pprof"] = (
        "text/plain; charset=utf-8", _pprof_text)
    return server, core
