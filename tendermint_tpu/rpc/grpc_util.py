"""Shared gRPC scaffolding for the two services (BroadcastAPI,
ABCIApplication): server construction and unary-stub maps.

One place for transport policy:
- SO_REUSEPORT is DISABLED. grpcio enables it by default, under which a
  second node binding the same laddr silently succeeds and the kernel
  round-robins connections between the two processes. The reference's
  net.Listen fails loudly on a busy port (rpc/grpc/client_server.go:15);
  so do we — add_insecure_port returns 0 and start() raises.
"""

from __future__ import annotations

from concurrent import futures
from typing import Callable, Dict, Mapping

import grpc

_SERVER_OPTIONS = (("grpc.so_reuseport", 0),)


def strip_tcp(addr: str) -> str:
    return addr.replace("tcp://", "")


class GrpcServerBase:
    """Owns a grpc.server bound to laddr serving one generic handler.

    Subclasses implement handlers() -> {method: (fn, Req, Resp)} where fn
    is fn(request, context) -> response.
    """

    SERVICE = ""  # full service name, e.g. "tendermint_tpu.BroadcastAPI"

    def __init__(self, laddr: str, max_workers: int = 8):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_SERVER_OPTIONS)
        method_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString)
            for name, (fn, req, resp) in self.handlers().items()}
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                self.SERVICE, method_handlers),))
        self.port = self._server.add_insecure_port(strip_tcp(laddr))
        if self.port == 0:
            raise OSError(f"gRPC bind failed on {laddr!r} (port in use?)")

    def handlers(self) -> Dict[str, tuple]:
        raise NotImplementedError

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def make_stubs(channel: grpc.Channel, service: str,
               req_map: Mapping[str, type],
               resp_map: Mapping[str, type]) -> Dict[str, Callable]:
    """Unary-unary stubs for every method in req_map."""
    return {
        m: channel.unary_unary(
            f"/{service}/{m}",
            request_serializer=req_map[m].SerializeToString,
            response_deserializer=resp_map[m].FromString)
        for m in req_map}
