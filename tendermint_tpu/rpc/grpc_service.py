"""gRPC BroadcastAPI — server + client.

Capability parity with the reference's minimal gRPC surface
(/root/reference/rpc/grpc/types.proto:33-36, client_server.go:15,34):
`Ping` and `BroadcastTx`, where BroadcastTx submits through the full
commit path (the reference implements it via core.BroadcastTxCommit) and
returns both the CheckTx and DeliverTx results.

grpc_tools is not in the image, so the service is wired with
`grpc.method_handlers_generic_handler` over the protoc-generated
messages instead of generated *_pb2_grpc stubs.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from tendermint_tpu.rpc.proto import tmtpu_pb2 as pb

_SERVICE = "tendermint_tpu.BroadcastAPI"


def _tx_result(obj: Optional[dict]) -> pb.TxResult:
    if not obj:
        return pb.TxResult()
    return pb.TxResult(
        code=obj.get("code", 0), data=bytes.fromhex(obj.get("data") or ""),
        log=obj.get("log", ""),
        tags={str(k): str(v) for k, v in (obj.get("tags") or {}).items()},
        gas_wanted=obj.get("gas_wanted", 0))


class BroadcastAPIServer:
    """Serves Ping + BroadcastTx over the RPCCore handlers."""

    def __init__(self, core, laddr: str, max_workers: int = 8):
        """core: rpc.core.RPCCore; laddr: 'host:port' or
        'tcp://host:port' (port 0 picks a free port)."""
        self.core = core
        addr = laddr.replace("tcp://", "")
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((self._handler(),))
        self.port = self._server.add_insecure_port(addr)

    def _handler(self):
        def ping(request, context):
            return pb.PingResponse()

        def broadcast_tx(request, context):
            from tendermint_tpu.rpc.server import RPCError
            try:
                res = self.core.broadcast_tx_commit(request.tx)
            except RPCError as e:
                context.abort(grpc.StatusCode.INTERNAL, e.message)
                return
            return pb.BroadcastTxResponse(
                check_tx=_tx_result(res.get("check_tx")),
                deliver_tx=_tx_result(res.get("deliver_tx")),
                hash=bytes.fromhex(res.get("hash") or ""),
                height=res.get("height", 0))

        handlers = {
            "Ping": grpc.unary_unary_rpc_method_handler(
                ping, request_deserializer=pb.PingRequest.FromString,
                response_serializer=pb.PingResponse.SerializeToString),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                broadcast_tx,
                request_deserializer=pb.BroadcastTxRequest.FromString,
                response_serializer=pb.BroadcastTxResponse.SerializeToString),
        }
        return grpc.method_handlers_generic_handler(_SERVICE, handlers)

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class BroadcastAPIClient:
    """Client for BroadcastAPIServer (rpc/grpc/client_server.go:15)."""

    def __init__(self, address: str, timeout: float = 60.0):
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            address.replace("tcp://", ""))
        self._ping = self._channel.unary_unary(
            f"/{_SERVICE}/Ping",
            request_serializer=pb.PingRequest.SerializeToString,
            response_deserializer=pb.PingResponse.FromString)
        self._broadcast = self._channel.unary_unary(
            f"/{_SERVICE}/BroadcastTx",
            request_serializer=pb.BroadcastTxRequest.SerializeToString,
            response_deserializer=pb.BroadcastTxResponse.FromString)

    def ping(self) -> None:
        self._ping(pb.PingRequest(), timeout=self.timeout)

    def broadcast_tx(self, tx: bytes) -> pb.BroadcastTxResponse:
        return self._broadcast(pb.BroadcastTxRequest(tx=tx),
                               timeout=self.timeout)

    def close(self) -> None:
        self._channel.close()
